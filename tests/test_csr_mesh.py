"""Row-sharded CSR (sparse data parallelism) parity.

The reference's distributed pass accepts sparse MLlib vectors
(``Gradient.compute`` takes any ``Vector`` inside the treeAggregate seqOp,
reference ``AcceleratedGradientDescent.scala:196-204``) — so sparse data
must run the framework's primary parallelism mode too.  These tests pin
the mesh CSR path (``parallel.mesh.shard_csr_batch`` +
``parallel.dist_smooth._make_shard_map_csr``) against the single-device
CSR path at 1/2/8-way shardings for all three GLM losses (VERDICT r1
item 3's done-condition).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_agd_tpu import api
from spark_agd_tpu.core import smooth as smooth_lib
from spark_agd_tpu.ops import sparse
from spark_agd_tpu.ops.losses import (
    HingeGradient,
    LeastSquaresGradient,
    LogisticGradient,
    SoftmaxGradient,
)
from spark_agd_tpu.ops.prox import L1Prox, L2Prox
from spark_agd_tpu.parallel import dist_smooth, mesh as mesh_lib


@pytest.fixture(scope="module")
def csr_problem():
    """Sparse rows with varying nnz, N deliberately not divisible by 8."""
    rng = np.random.default_rng(17)
    n, d = 301, 157
    counts = rng.integers(1, 12, n)
    indptr = np.concatenate([[0], np.cumsum(counts)])
    nnz = int(indptr[-1])
    indices = rng.integers(0, d, nnz).astype(np.int32)
    values = rng.standard_normal(nnz).astype(np.float32)
    w_true = rng.standard_normal(d).astype(np.float32) / np.sqrt(8)
    margins = np.zeros(n, np.float32)
    np.add.at(margins, np.repeat(np.arange(n), counts),
              values * w_true[indices])
    y = (rng.random(n) < 1 / (1 + np.exp(-margins))).astype(np.float32)
    w = rng.standard_normal(d).astype(np.float32) / np.sqrt(d)
    X = sparse.CSRMatrix.from_csr_arrays(indptr, indices, values, d)
    return X, y, w, d


def data_mesh(k):
    return mesh_lib.make_mesh({mesh_lib.DATA_AXIS: k},
                              devices=jax.devices()[:k])


class TestShardCsrBatch:
    @pytest.mark.parametrize("k", [1, 2, 8])
    @pytest.mark.parametrize("balance", [True, False])
    def test_layout_roundtrip(self, csr_problem, cpu_devices, k, balance):
        """Every (row, col, value) entry and every (y, mask) slot must
        survive the layout exactly once."""
        X, y, w, d = csr_problem
        m = data_mesh(k)
        batch = mesh_lib.shard_csr_batch(m, X, y, balance=balance)
        Xs = batch.X
        assert isinstance(Xs, sparse.RowShardedCSR)
        assert Xs.shape == X.shape
        assert Xs.n_shards == k
        # mask marks exactly n real rows
        assert int(np.asarray(batch.mask).sum()) == X.shape[0]
        # value multiset is preserved (padding adds only zeros)
        vals = np.asarray(Xs.values)
        np.testing.assert_allclose(
            np.sort(vals[vals != 0.0]),
            np.sort(np.asarray(X.values)[np.asarray(X.values) != 0.0]))

    def test_balance_bounds_padding(self, cpu_devices):
        """Power-law row nnz (a few huge rows) must not blow up the padded
        footprint the way contiguous blocks can."""
        rng = np.random.default_rng(3)
        n, d = 2000, 300
        counts = np.minimum((1.0 / rng.random(n)).astype(int), 200)
        indptr = np.concatenate([[0], np.cumsum(counts)])
        nnz = int(indptr[-1])
        indices = rng.integers(0, d, nnz).astype(np.int32)
        values = np.ones(nnz, np.float32)
        y = rng.integers(0, 2, n).astype(np.float32)
        X = sparse.CSRMatrix.from_csr_arrays(indptr, indices, values, d)
        m = data_mesh(8)
        bal = mesh_lib.shard_csr_batch(m, X, y, balance=True)
        blowup = bal.X.values.shape[0] / nnz
        assert blowup < 1.5, f"balanced padding blowup {blowup:.2f}x"


class TestMeshCsrSmooth:
    @pytest.mark.parametrize("grad_cls", [LogisticGradient,
                                          LeastSquaresGradient,
                                          HingeGradient])
    @pytest.mark.parametrize("k", [1, 2, 8])
    def test_matches_single_device(self, csr_problem, cpu_devices,
                                   grad_cls, k):
        X, y, w, d = csr_problem
        g = grad_cls()
        ref_loss, ref_grad = smooth_lib.make_smooth(
            g, X, jnp.asarray(y))(jnp.asarray(w))

        m = data_mesh(k)
        batch = mesh_lib.shard_csr_batch(m, X, y)
        smooth, smooth_loss = dist_smooth.make_dist_smooth(
            g, batch, mesh=m)
        loss, grad = smooth(mesh_lib.replicate(jnp.asarray(w), m))
        assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad),
                                   rtol=1e-4, atol=1e-6)
        assert float(smooth_loss(mesh_lib.replicate(jnp.asarray(w), m))) \
            == pytest.approx(float(loss), rel=1e-6)

    def test_mask_composes_with_padding(self, csr_problem, cpu_devices):
        """A caller's minibatch mask must compose with the layout's row
        padding mask."""
        X, y, w, d = csr_problem
        rng = np.random.default_rng(5)
        mask = (rng.random(X.shape[0]) < 0.55).astype(np.float32)
        g = LogisticGradient()
        ref = g.mean_loss_and_grad(jnp.asarray(w), X, jnp.asarray(y),
                                   jnp.asarray(mask))
        m = data_mesh(8)
        batch = mesh_lib.shard_csr_batch(m, X, y, mask=mask)
        smooth, _ = dist_smooth.make_dist_smooth(g, batch, mesh=m)
        loss, grad = smooth(mesh_lib.replicate(jnp.asarray(w), m))
        assert float(loss) == pytest.approx(float(ref[0]), rel=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref[1]),
                                   rtol=1e-4, atol=1e-6)

    def test_softmax_csr_on_mesh(self, cpu_devices):
        """Multinomial softmax over sparse rows on the data mesh (the
        MNIST-8M config shape with CSR features)."""
        rng = np.random.default_rng(11)
        n, d, k_classes = 120, 40, 5
        counts = rng.integers(1, 6, n)
        indptr = np.concatenate([[0], np.cumsum(counts)])
        nnz = int(indptr[-1])
        X = sparse.CSRMatrix.from_csr_arrays(
            indptr, rng.integers(0, d, nnz).astype(np.int32),
            rng.standard_normal(nnz).astype(np.float32), d)
        y = rng.integers(0, k_classes, n).astype(np.int32)
        W = (rng.standard_normal((d, k_classes)) / np.sqrt(d)).astype(
            np.float32)
        g = SoftmaxGradient(k_classes)
        ref = smooth_lib.make_smooth(g, X, jnp.asarray(y))(jnp.asarray(W))
        m = data_mesh(8)
        batch = mesh_lib.shard_csr_batch(m, X, y)
        smooth, _ = dist_smooth.make_dist_smooth(g, batch, mesh=m)
        loss, grad = smooth(mesh_lib.replicate(jnp.asarray(W), m))
        assert float(loss) == pytest.approx(float(ref[0]), rel=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref[1]),
                                   rtol=1e-4, atol=1e-6)

    def test_auto_mode_rejected(self, csr_problem, cpu_devices):
        X, y, w, d = csr_problem
        m = data_mesh(2)
        batch = mesh_lib.shard_csr_batch(m, X, y)
        with pytest.raises(ValueError, match="shard_map"):
            dist_smooth.make_dist_smooth(LogisticGradient(), batch,
                                         mesh=m, mode="auto")


class TestMeshCsrAGD:
    @pytest.mark.parametrize("k", [2, 8])
    def test_full_agd_trajectory_parity(self, csr_problem, cpu_devices, k):
        """api.run on mesh-sharded CSR must walk the single-device CSR
        trajectory (VERDICT r1 item 3 done-condition)."""
        X, y, w, d = csr_problem
        w0 = np.zeros(d, np.float32)
        ref_w, ref_hist = api.run(
            (X, y), LogisticGradient(), L2Prox(), num_iterations=8,
            reg_param=0.1, initial_weights=w0, mesh=False,
            convergence_tol=0.0)
        mesh_w, mesh_hist = api.run(
            (X, y), LogisticGradient(), L2Prox(), num_iterations=8,
            reg_param=0.1, initial_weights=w0, mesh=data_mesh(k),
            convergence_tol=0.0)
        np.testing.assert_allclose(mesh_hist, ref_hist, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(mesh_w), np.asarray(ref_w),
                                   rtol=1e-4, atol=1e-6)

    def test_l1_sparse_config_shape(self, csr_problem, cpu_devices):
        """BASELINE config 3's shape (hinge + L1) on the sparse mesh
        path — runs end to end and matches single-device."""
        X, y, w, d = csr_problem
        w0 = np.zeros(d, np.float32)
        ref_w, ref_hist = api.run(
            (X, y), HingeGradient(), L1Prox(), num_iterations=6,
            reg_param=0.01, initial_weights=w0, mesh=False,
            convergence_tol=0.0)
        mesh_w, mesh_hist = api.run(
            (X, y), HingeGradient(), L1Prox(), num_iterations=6,
            reg_param=0.01, initial_weights=w0, mesh=data_mesh(8),
            convergence_tol=0.0)
        np.testing.assert_allclose(mesh_hist, ref_hist, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(mesh_w), np.asarray(ref_w),
                                   rtol=1e-4, atol=1e-6)

    def test_default_mesh_routes_csr(self, csr_problem, cpu_devices):
        """mesh=None (the default) must now shard CSR over all devices
        instead of raising NotImplementedError (VERDICT r1 item 3)."""
        X, y, w, d = csr_problem
        w0 = np.zeros(d, np.float32)
        mesh_w, hist = api.run(
            (X, y), LogisticGradient(), L2Prox(), num_iterations=4,
            reg_param=0.1, initial_weights=w0, convergence_tol=0.0)
        assert len(hist) == 4
        assert np.all(np.isfinite(hist))
