"""Child program for the real 2-process ``jax.distributed`` smoke test
(tests/test_multihost.py::TestTwoProcess).  Each process contributes its
local CPU devices to one global mesh, builds a process-local shard of a
global array, and runs ONE psum over the data axis — the reference's
executor-process isolation (``LocalClusterSparkContext``, reference
Suite:242-260) re-created with real separate interpreters, real
coordinator handshake, real cross-process collective.

Usage: python multihost_child.py <coordinator_addr> <n_proc> <proc_id>
"""

import os
import sys

import jax

# Order matters: platform config BEFORE distributed init BEFORE any
# backend use (see parallel/multihost.initialize's ordering guard).
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    # older jaxlib (< 0.4.38): the XLA flag it replaced, still read at
    # backend instantiation (same fallback as tests/conftest.py)
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=2")
try:
    # cross-process CPU collectives need an explicit transport on older
    # jaxlib (newer ones default it); without this the psum below dies
    # with "Multiprocess computations aren't implemented on the CPU
    # backend"
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:  # noqa: BLE001 — newer jax: flag gone, default works
    pass

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402


def main():
    addr, nproc, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])

    from spark_agd_tpu.parallel import mesh as mesh_lib, multihost as mh

    mh.initialize(addr, nproc, pid)
    mh.initialize(addr, nproc, pid)  # idempotent second call
    assert jax.process_count() == nproc, jax.process_count()
    devs = jax.devices()
    assert len(devs) == 2 * nproc, devs

    mesh = mesh_lib.make_mesh({"data": len(devs)})

    n_global = 8
    rows = mh.process_local_rows(n_global)
    local = np.arange(n_global, dtype=np.float32)[rows]
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), local, (n_global,))

    from jax import lax

    from spark_agd_tpu.parallel.shmap import shard_map

    total = shard_map(
        lambda x: lax.psum(jnp.sum(x), "data"),
        mesh=mesh, in_specs=P("data"), out_specs=P(),
        check_vma=False)(arr)
    expect = float(np.arange(n_global).sum())
    assert float(total) == expect, (float(total), expect)

    if len(sys.argv) > 4:
        _ingest_check(sys.argv[4], mesh)
        _sparse_ingest_check(sys.argv[4], mesh)
        _grid_check(mesh)
        _lbfgs_check(mesh)
        _dist_ckpt_check(sys.argv[4])
    print(f"CHILD_OK pid={pid} psum={float(total)}", flush=True)


def _ingest_check(part_dir, mesh):
    """Multi-host ingest: each process reads its round-robin partition
    subset; the assembled global batch's mean loss/grad must equal the
    full-dataset answer every child can compute locally (the files are
    tiny and shared)."""
    import glob

    from spark_agd_tpu.data import ingest, libsvm
    from spark_agd_tpu.ops.losses import LogisticGradient
    from spark_agd_tpu.parallel import dist_smooth, mesh as mesh_lib

    paths = sorted(glob.glob(part_dir + "/part-*.libsvm"))
    assert len(paths) >= 2, paths
    batch = ingest.from_partitioned_files(paths, mesh)
    sm, _ = dist_smooth.make_dist_smooth(LogisticGradient(), batch,
                                         mesh=mesh)
    d = batch.X.shape[1]
    w = jnp.asarray(np.linspace(-0.4, 0.4, d), jnp.float32)
    loss, grad = sm(mesh_lib.replicate(w, mesh))

    # every child recomputes the reference from ALL partitions
    parts = [libsvm.load_libsvm(p, n_features=d) for p in paths]
    X = np.concatenate([p.to_dense(d) for p in parts])
    y = np.concatenate([p.binarized_labels() for p in parts]).astype(
        np.float32)
    ref_loss, ref_grad = LogisticGradient().mean_loss_and_grad(
        jnp.asarray(w), jnp.asarray(X), jnp.asarray(y))
    assert abs(float(loss) - float(ref_loss)) < 1e-5 * abs(
        float(ref_loss)), (float(loss), float(ref_loss))
    np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad),
                               rtol=1e-4, atol=1e-6)
    print(f"INGEST_OK pid={jax.process_index()} rows={batch.X.shape[0]}",
          flush=True)


def _sparse_ingest_check(part_dir, mesh):
    """Sparse multi-host ingest (r2 VERDICT item 3): each process
    assembles its partitions into LOCAL RowShardedCSR shards with
    allgather-agreed dimensions; the global sparse batch must stream the
    SAME mesh-CSR AGD every host can verify against the dense answer —
    no densification anywhere in the assembly."""
    import glob

    from spark_agd_tpu import api
    from spark_agd_tpu.data import ingest, libsvm
    from spark_agd_tpu.ops.losses import LogisticGradient
    from spark_agd_tpu.ops.prox import L2Prox
    from spark_agd_tpu.ops.sparse import RowShardedCSR

    paths = sorted(glob.glob(part_dir + "/part-*.libsvm"))
    d = 9
    batch = ingest.from_partitioned_files_csr(paths, mesh, n_features=d)
    assert isinstance(batch.X, RowShardedCSR), type(batch.X)

    # The fused jit path closes over the data arrays — fine in one
    # process, disallowed for cross-process global arrays — so drive
    # the HOST-loop AGD twin over the eager shard_map smooth (the same
    # pairing the streaming path uses): every collective still runs
    # cross-process, and replicated outputs are fetchable everywhere.
    from spark_agd_tpu.core import agd, host_agd, smooth as smooth_lib
    from spark_agd_tpu.parallel import dist_smooth

    sm, sl = dist_smooth.make_dist_smooth(LogisticGradient(), batch,
                                          mesh=mesh)
    px, rv = smooth_lib.make_prox(L2Prox(), 0.1)
    cfg = agd.AGDConfig(num_iterations=3, convergence_tol=0.0)
    w0 = np.zeros(d, np.float32)
    res = host_agd.run_agd_host(sm, px, rv, w0, cfg, smooth_loss=sl)

    # dense single-device reference from ALL partitions (files are tiny)
    parts = [libsvm.load_libsvm(p, n_features=d) for p in paths]
    X = np.concatenate([p.to_dense(d) for p in parts])
    y = np.concatenate([p.binarized_labels() for p in parts]).astype(
        np.float32)
    w_ref, hist_ref = api.run((X, y), LogisticGradient(), L2Prox(),
                              num_iterations=3, reg_param=0.1,
                              initial_weights=w0, convergence_tol=0.0,
                              mesh=False)
    np.testing.assert_allclose(
        np.asarray(res.loss_history)[:res.num_iters],
        np.asarray(hist_ref), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(res.weights),
                               np.asarray(w_ref), rtol=1e-4, atol=1e-6)
    print(f"SPARSE_INGEST_OK pid={jax.process_index()} "
          f"rows={batch.X.shape[0]}", flush=True)


def _grid_check(mesh):
    """Mesh-composed grid fits across PROCESS boundaries: the vmapped
    lanes + psum inside the shard_map must produce the single-device
    answer when the data axis spans two interpreters.  Data is
    deterministic and identical on every host, so ``shard_batch``'s
    ``device_put`` places one consistent global batch (each process
    commits its addressable shards)."""
    from spark_agd_tpu import api
    from spark_agd_tpu.ops.losses import LogisticGradient
    from spark_agd_tpu.ops.prox import SquaredL2Updater

    rng = np.random.default_rng(11)
    n, d = 96, 6
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    w0 = np.zeros(d, np.float32)
    regs = [0.05, 0.5]
    kw = dict(num_iterations=3, convergence_tol=0.0,
              initial_weights=w0)

    res = api.sweep((X, y), LogisticGradient(), SquaredL2Updater(),
                    regs, mesh=mesh, **kw)
    # single-device reference: every child computes it locally
    ref = api.sweep((X, y), LogisticGradient(), SquaredL2Updater(),
                    regs, mesh=False, **kw)
    np.testing.assert_array_equal(np.asarray(res.num_iters),
                                  np.asarray(ref.num_iters))
    np.testing.assert_allclose(np.asarray(res.loss_history),
                               np.asarray(ref.loss_history),
                               rtol=1e-5, atol=1e-7)
    np.testing.assert_allclose(np.asarray(res.weights),
                               np.asarray(ref.weights),
                               rtol=1e-4, atol=1e-6)

    cv = api.cross_validate((X, y), LogisticGradient(),
                            SquaredL2Updater(), regs, n_folds=2,
                            mesh=mesh, seed=4, **kw)
    cv1 = api.cross_validate((X, y), LogisticGradient(),
                             SquaredL2Updater(), regs, n_folds=2,
                             mesh=False, seed=4, **kw)
    np.testing.assert_allclose(np.asarray(cv.val_loss),
                               np.asarray(cv1.val_loss),
                               rtol=1e-5, atol=1e-7)
    assert int(cv.best_index) == int(cv1.best_index)

    wg, hg = api.run_minibatch_sgd(
        (X, y), LogisticGradient(), SquaredL2Updater(), mesh=mesh,
        step_size=0.5, num_iterations=4, minibatch_fraction=0.5,
        seed=2, initial_weights=w0)
    wg1, hg1 = api.run_minibatch_sgd(
        (X, y), LogisticGradient(), SquaredL2Updater(), mesh=False,
        step_size=0.5, num_iterations=4, minibatch_fraction=0.5,
        seed=2, initial_weights=w0)
    np.testing.assert_allclose(np.asarray(hg), np.asarray(hg1),
                               rtol=1e-5, atol=1e-7)
    # weights too: loss_history[t] reflects PRE-step weights, so only
    # the weight compare pins the final distributed update
    np.testing.assert_allclose(np.asarray(wg), np.asarray(wg1),
                               rtol=1e-4, atol=1e-6)
    print(f"GRID_OK pid={jax.process_index()}", flush=True)


def _lbfgs_check(mesh):
    """The quasi-Newton Optimizer-family member across PROCESS
    boundaries: host-loop L-BFGS (``core.host_lbfgs`` — the fused jit
    would close over cross-process global arrays) over the eager
    shard_map smooth, vs the single-device fused answer every child
    computes locally."""
    from spark_agd_tpu import api
    from spark_agd_tpu.core import host_lbfgs, lbfgs as lbfgs_lib
    from spark_agd_tpu.ops.losses import LogisticGradient
    from spark_agd_tpu.ops.prox import L2Prox
    from spark_agd_tpu.parallel import dist_smooth, mesh as mesh_lib

    rng = np.random.default_rng(23)
    n, d, reg = 80, 5, 0.1
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    batch = mesh_lib.shard_batch(mesh, X, y)
    sm, _ = dist_smooth.make_dist_smooth(LogisticGradient(), batch,
                                         mesh=mesh)
    # Cap at 3 iterations: this problem's improvements stay >=2.6e-5
    # relative through step 3 — far above f32 rounding — so every
    # sharding does exactly 3 clean Wolfe steps.  (At 4+ steps the run
    # sits ON the f32 noise floor, where stop mode, count, and final
    # micro-position all legitimately differ between reduction orders —
    # observed: 6-vs-4 counts, mixed ls_failed, ~1e-4 weight wiggle.)
    obj = lbfgs_lib.make_objective(sm, L2Prox(), reg)
    cfg = lbfgs_lib.LBFGSConfig(convergence_tol=0.0, num_iterations=3)
    res = host_lbfgs.run_lbfgs_host(obj, np.zeros(d, np.float32), cfg)

    ref = api.run_lbfgs((X, y), LogisticGradient(), L2Prox(),
                        reg_param=reg, convergence_tol=0.0,
                        num_iterations=3,
                        initial_weights=np.zeros(d, np.float32),
                        mesh=False)
    assert not res.aborted_non_finite and not res.ls_failed
    assert res.num_iters == int(ref.num_iters) == 3, (
        res.num_iters, int(ref.num_iters))
    np.testing.assert_allclose(res.loss_history,
                               np.asarray(ref.loss_history)[:4],
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(res.weights),
                               np.asarray(ref.weights),
                               rtol=1e-3, atol=1e-5)
    print(f"LBFGS_OK pid={jax.process_index()} iters={res.num_iters}",
          flush=True)


def _dist_ckpt_check(tmp_dir):
    """Barrier-committed distributed checkpointing across the two REAL
    processes (resilience.distributed): each host writes its shard, the
    allgather barrier exchanges CRCs, the primary commits the manifest;
    then a same-topology reload must be exact and an elastic 1-process
    view must re-assemble both hosts' partition/row assignments."""
    import os
    import time

    from spark_agd_tpu.core.agd import AGDConfig, AGDWarmState
    from spark_agd_tpu.resilience import (DistributedCheckpointer,
                                          load_for_topology, manifest)

    pid = jax.process_index()
    d = os.path.join(tmp_dir, "distckpt")
    w0 = np.linspace(0.0, 1.0, 5).astype(np.float32)
    cfg = AGDConfig(num_iterations=4)
    warm = AGDWarmState.initial(w0, cfg)._replace(prior_iters=2)
    ck = DistributedCheckpointer(
        d, every_iters=1, keep=2,
        partitions=[f"part-{pid}"],
        row_state={"rows": np.arange(pid * 3, pid * 3 + 3)})
    assert ck.update(warm, [0.3, 0.2])  # collective: gen 0 commits

    m = None
    for _ in range(200):  # rank 1 may peek before rank 0's commit lands
        m = manifest.load_manifest(d)
        if m is not None:
            break
        time.sleep(0.05)
    assert m is not None and m.process_count == jax.process_count(), m
    assert manifest.verify_manifest(m, d) == [], \
        manifest.verify_manifest(m, d)

    loaded = ck.load(w0)
    assert loaded is not None and not loaded.elastic
    assert int(loaded.warm.prior_iters) == 2
    np.testing.assert_array_equal(np.asarray(loaded.warm.x), w0)
    assert loaded.partitions == (f"part-{pid}",), loaded.partitions

    el = load_for_topology(d, w0, process_index=0, process_count=1)
    assert el is not None and el.elastic and el.saved_process_count == 2
    assert el.partitions == ("part-0", "part-1"), el.partitions
    np.testing.assert_array_equal(el.row_state["rows"], np.arange(6))
    print(f"DISTCKPT_OK pid={pid} generation={loaded.generation}",
          flush=True)


if __name__ == "__main__":
    main()
