"""The CSC-twin sparse layout (sorted-reduction gradient path).

``X.T @ mult`` over unsorted column ids is a scatter-add — the one sparse
primitive TPUs lower badly.  ``CSRMatrix.with_csc()`` carries a
column-sorted copy of the entries so ``rmatvec``/``rmatmat`` become the
same sorted ``segment_sum`` shape as the forward product (ops/sparse.py
module docstring).  These tests pin:

- product parity: the CSC path equals the scatter path and the dense
  products (up to f32 reassociation),
- layout invariants: per-shard ids really are nondecreasing after
  ``shard_csr_batch`` (the precondition for ``indices_are_sorted`` —
  claiming it falsely produces silently wrong sums),
- end-to-end: mesh AGD trajectories with and without the twin agree with
  the single-device run.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_agd_tpu import api
from spark_agd_tpu.models import glm
from spark_agd_tpu.ops import sparse
from spark_agd_tpu.ops.losses import (
    HingeGradient,
    LeastSquaresGradient,
    LogisticGradient,
    SoftmaxGradient,
)
from spark_agd_tpu.ops.prox import L2Prox
from spark_agd_tpu.parallel import mesh as mesh_lib


@pytest.fixture(scope="module")
def csr_problem():
    """Duplicate (row, col) pairs included — scatter-add and segment-sum
    must both accumulate them."""
    rng = np.random.default_rng(23)
    n, d = 211, 97
    counts = rng.integers(1, 9, n)
    indptr = np.concatenate([[0], np.cumsum(counts)])
    nnz = int(indptr[-1])
    indices = rng.integers(0, d, nnz).astype(np.int32)
    values = rng.standard_normal(nnz).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    X = sparse.CSRMatrix.from_csr_arrays(indptr, indices, values, d,
                                         with_csc=True)
    return X, y, n, d


def dense_of(X: sparse.CSRMatrix) -> np.ndarray:
    D = np.zeros(X.shape, np.float64)
    np.add.at(D, (np.asarray(X.row_ids), np.asarray(X.col_ids)),
              np.asarray(X.values, np.float64))
    return D


class TestCscProducts:
    def test_construction(self, csr_problem):
        X, _, _, _ = csr_problem
        assert X.has_csc and X.rows_sorted
        cid = np.asarray(X.csc_col_ids)
        assert np.all(np.diff(cid) >= 0), "csc cols must be nondecreasing"
        # same multiset of entries in both copies
        ents = sorted(zip(np.asarray(X.row_ids).tolist(),
                          np.asarray(X.col_ids).tolist(),
                          np.asarray(X.values).tolist()))
        csc_ents = sorted(zip(np.asarray(X.csc_row_ids).tolist(),
                              np.asarray(X.csc_col_ids).tolist(),
                              np.asarray(X.csc_values).tolist()))
        assert ents == csc_ents

    def test_with_csc_idempotent(self, csr_problem):
        X, _, _, _ = csr_problem
        assert X.with_csc() is X

    def test_lazy_marker(self, csr_problem, cpu_devices):
        """with_csc(lazy=True) defers the build: prepare() materializes
        it for single-device runs; shard_csr_batch reads the flag and
        builds per-shard twins without a global one ever existing."""
        X, y, n, d = csr_problem
        lazy = sparse.CSRMatrix(X.row_ids, X.col_ids, X.values, X.shape,
                                rows_sorted=True).with_csc(lazy=True)
        assert lazy.want_csc and not lazy.has_csc
        assert lazy.with_csc(lazy=True) is lazy
        Xp, _, _ = LogisticGradient().prepare(lazy, y)
        assert Xp.has_csc
        mesh = mesh_lib.make_mesh({mesh_lib.DATA_AXIS: 4},
                                  devices=jax.devices()[:4])
        batch = mesh_lib.shard_csr_batch(mesh, lazy, y)
        assert batch.X.has_csc

    def test_rmatvec_matches_scatter_and_dense(self, csr_problem):
        X, _, n, d = csr_problem
        rng = np.random.default_rng(5)
        v = rng.standard_normal(n).astype(np.float32)
        no_csc = sparse.CSRMatrix(X.row_ids, X.col_ids, X.values, X.shape,
                                  rows_sorted=True)
        got = np.asarray(X.rmatvec(jnp.asarray(v)))
        scatter = np.asarray(no_csc.rmatvec(jnp.asarray(v)))
        ref = dense_of(X).T @ v.astype(np.float64)
        np.testing.assert_allclose(got, scatter, rtol=2e-5, atol=1e-5)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-4)

    def test_rmatmat_matches(self, csr_problem):
        X, _, n, d = csr_problem
        rng = np.random.default_rng(6)
        V = rng.standard_normal((n, 4)).astype(np.float32)
        got = np.asarray(X.rmatmat(jnp.asarray(V)))
        ref = dense_of(X).T @ V.astype(np.float64)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-4)

    def test_matvec_sorted_claim(self, csr_problem):
        """from_csr_arrays row ids are sorted; the forward product with
        the claim must equal the dense product."""
        X, _, n, d = csr_problem
        rng = np.random.default_rng(7)
        w = rng.standard_normal(d).astype(np.float32)
        got = np.asarray(X.matvec(jnp.asarray(w)))
        ref = dense_of(X) @ w.astype(np.float64)
        np.testing.assert_allclose(got, ref, rtol=2e-4, atol=1e-4)

    def test_losses_pick_up_csc(self, csr_problem):
        """The Gradient kernels call rmatvec through the same object, so
        loss/grad sums must agree between layouts for every GLM loss."""
        X, y, n, d = csr_problem
        no_csc = sparse.CSRMatrix(X.row_ids, X.col_ids, X.values, X.shape,
                                  rows_sorted=True)
        rng = np.random.default_rng(8)
        w = rng.standard_normal(d).astype(np.float32) / np.sqrt(d)
        for g in (LogisticGradient(), LeastSquaresGradient(),
                  HingeGradient()):
            l1, g1, n1 = g.batch_loss_and_grad(jnp.asarray(w), X, y)
            l2, g2, n2 = g.batch_loss_and_grad(jnp.asarray(w), no_csc, y)
            np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
            np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                       rtol=2e-5, atol=1e-5)
            assert int(n1) == int(n2) == n


class TestInterceptPreservesCsc:
    def test_add_intercept(self, csr_problem):
        X, _, n, d = csr_problem
        Xi = glm._add_intercept(X)
        assert Xi.has_csc
        cid = np.asarray(Xi.csc_col_ids)
        assert np.all(np.diff(cid) >= 0), (
            "intercept prepend must keep csc column order")
        rng = np.random.default_rng(9)
        v = rng.standard_normal(n).astype(np.float32)
        ref = np.concatenate([[v.sum()], dense_of(X).T @ v])
        np.testing.assert_allclose(np.asarray(Xi.rmatvec(jnp.asarray(v))),
                                   ref, rtol=2e-4, atol=1e-4)


    def test_add_intercept_keeps_lazy_marker(self, csr_problem):
        """The default train(add_intercept=True) path must not silently
        drop a lazily-requested twin."""
        X, _, _, _ = csr_problem
        lazy = sparse.CSRMatrix(X.row_ids, X.col_ids, X.values, X.shape,
                                rows_sorted=True).with_csc(lazy=True)
        Xi = glm._add_intercept(lazy)
        assert Xi.want_csc and not Xi.has_csc


class TestShardedCsc:
    @pytest.mark.parametrize("k", [2, 8])
    @pytest.mark.parametrize("balance", [True, False])
    def test_shard_layout_sorted(self, csr_problem, cpu_devices, k,
                                 balance):
        """Per-shard row ids and csc col ids must be nondecreasing — the
        precondition for the sorted segment-sums inside shard_map."""
        X, y, n, d = csr_problem
        mesh = mesh_lib.make_mesh({mesh_lib.DATA_AXIS: k},
                                  devices=jax.devices()[:k])
        batch = mesh_lib.shard_csr_batch(mesh, X, y)
        Xs = batch.X
        assert Xs.has_csc and Xs.rows_sorted
        nnz_s = Xs.nnz_per_shard
        R = np.asarray(Xs.row_ids).reshape(k, nnz_s)
        Cc = np.asarray(Xs.csc_col_ids).reshape(k, nnz_s)
        for s in range(k):
            assert np.all(np.diff(R[s]) >= 0), f"shard {s} rows unsorted"
            assert np.all(np.diff(Cc[s]) >= 0), f"shard {s} csc unsorted"

    @pytest.mark.parametrize("k", [1, 2, 8])
    def test_mesh_agd_parity(self, csr_problem, cpu_devices, rel_assert,
                             k):
        """Full fused AGD on the mesh: the csc layout must reproduce the
        single-device (no-csc) trajectory."""
        X, y, n, d = csr_problem
        w0 = np.zeros(d, np.float32)
        no_csc = sparse.CSRMatrix(X.row_ids, X.col_ids, X.values, X.shape,
                                  rows_sorted=True)
        w_ref, hist_ref = api.run(
            (no_csc, y), LogisticGradient(), L2Prox(),
            num_iterations=6, reg_param=0.05, initial_weights=w0)
        mesh = mesh_lib.make_mesh({mesh_lib.DATA_AXIS: k},
                                  devices=jax.devices()[:k])
        w_mesh, hist_mesh = api.run(
            (X, y), LogisticGradient(), L2Prox(),
            num_iterations=6, reg_param=0.05, initial_weights=w0,
            mesh=mesh)
        assert len(hist_ref) == len(hist_mesh)
        for a, b in zip(hist_ref, hist_mesh):
            rel_assert(a, b, 1e-5, "csc mesh trajectory")
        np.testing.assert_allclose(np.asarray(w_mesh), np.asarray(w_ref),
                                   rtol=1e-4, atol=1e-6)

    def test_feature_sharded_csc(self, csr_problem, cpu_devices,
                                 rel_assert):
        """D-axis layout: the column-sorted twin must reproduce the
        scatter layout's smooth evaluation, and per-shard ids must
        actually be sorted."""
        from spark_agd_tpu.parallel import feature_sharded as fs

        X, y, n, d = csr_problem
        rid = np.asarray(X.row_ids)
        indptr = np.concatenate(
            [[0], np.cumsum(np.bincount(rid, minlength=n))])
        mesh = mesh_lib.make_mesh({mesh_lib.MODEL_AXIS: 4},
                                  devices=jax.devices()[:4])
        k_shards = 4
        b_csc = fs.shard_csr_by_columns(
            indptr, np.asarray(X.col_ids), np.asarray(X.values), d, y,
            mesh)
        b_sct = fs.shard_csr_by_columns(
            indptr, np.asarray(X.col_ids), np.asarray(X.values), d, y,
            mesh, with_csc=False)
        assert b_csc.has_csc and not b_sct.has_csc
        nnz_s = len(np.asarray(b_csc.values)) // k_shards
        R = np.asarray(b_csc.row_ids).reshape(k_shards, nnz_s)
        Cc = np.asarray(b_csc.csc_col_local).reshape(k_shards, nnz_s)
        for s in range(k_shards):
            assert np.all(np.diff(R[s]) >= 0)
            assert np.all(np.diff(Cc[s]) >= 0)
        rng = np.random.default_rng(13)
        w = rng.standard_normal(d).astype(np.float32) / np.sqrt(d)
        g = LogisticGradient()
        sm1, _ = fs.make_feature_sharded_smooth(g, b_csc, mesh=mesh)
        sm2, _ = fs.make_feature_sharded_smooth(g, b_sct, mesh=mesh)
        l1, g1 = sm1(fs.shard_weights(w, b_csc, mesh))
        l2, g2 = sm2(fs.shard_weights(w, b_sct, mesh))
        rel_assert(l1, l2, 1e-6, "feature-sharded csc loss")
        np.testing.assert_allclose(
            fs.unshard_weights(g1, b_csc), fs.unshard_weights(g2, b_sct),
            rtol=2e-5, atol=1e-6)

    def test_softmax_rmatmat_mesh(self, csr_problem, cpu_devices):
        """The (D, K) gradient path through the sharded csc layout."""
        X, _, n, d = csr_problem
        rng = np.random.default_rng(11)
        k_cls = 5
        y_cls = rng.integers(0, k_cls, n).astype(np.int32)
        W0 = np.zeros((d, k_cls), np.float32)
        g = SoftmaxGradient(k_cls)
        l_ref, g_ref, n_ref = g.batch_loss_and_grad(jnp.asarray(W0), X,
                                                    y_cls)
        mesh = mesh_lib.make_mesh({mesh_lib.DATA_AXIS: 4},
                                  devices=jax.devices()[:4])
        batch = mesh_lib.shard_csr_batch(mesh, X, y_cls)
        from spark_agd_tpu.parallel import dist_smooth

        sm, _ = dist_smooth.make_dist_smooth(g, batch, mesh=mesh)
        loss, grad = sm(jnp.asarray(W0))
        np.testing.assert_allclose(float(loss), float(l_ref) / n,
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(grad),
                                   np.asarray(g_ref) / n,
                                   rtol=2e-5, atol=1e-6)
