"""`api.cross_validate` — F folds x R strengths in ONE compiled program.

Contract: every (fold, strength) lane must equal an individual masked
`api.run` at that configuration, validation losses must equal manual
held-out evaluation, and the selected strength must be sane on planted
data where the answer is known.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_agd_tpu import api
from spark_agd_tpu.ops import losses, prox, sparse


@pytest.fixture
def problem(rng):
    X = rng.standard_normal((400, 10)).astype(np.float32)
    w_true = rng.standard_normal(10).astype(np.float32)
    p = 1 / (1 + np.exp(-(X @ w_true)))
    y = (rng.random(400) < p).astype(np.float32)
    return X, y, np.zeros(10, np.float32)


class TestCrossValidate:
    def test_lane_matches_individual_masked_run(self, problem):
        X, y, w0 = problem
        regs = [0.01, 0.2]
        cv = api.cross_validate(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            regs, n_folds=3, num_iterations=5, convergence_tol=0.0,
            initial_weights=w0, seed=3)
        assert cv.val_loss.shape == (3, 2)
        assert cv.train_result.weights.shape == (3, 2, 10)
        fold_ids = np.asarray(cv.fold_ids)
        for f in range(3):
            for r, reg in enumerate(regs):
                train_mask = (fold_ids != f).astype(np.float32)
                w_ref, hist_ref = api.run(
                    (X, y, train_mask), losses.LogisticGradient(),
                    prox.SquaredL2Updater(), reg_param=reg,
                    num_iterations=5, convergence_tol=0.0,
                    initial_weights=w0, mesh=False)
                np.testing.assert_allclose(
                    np.asarray(cv.train_result.weights)[f, r],
                    np.asarray(w_ref), rtol=5e-2, atol=5e-3)
                # validation loss == manual held-out evaluation
                val_mask = (fold_ids == f).astype(np.float32)
                g = losses.LogisticGradient()
                ls, _, cnt = g.batch_loss_and_grad(
                    jnp.asarray(np.asarray(
                        cv.train_result.weights)[f, r]),
                    jnp.asarray(X), jnp.asarray(y),
                    jnp.asarray(val_mask))
                want = float(ls) / float(cnt)
                assert float(cv.val_loss[f, r]) == pytest.approx(
                    want, rel=1e-5)

    def test_fold_partition(self, problem):
        X, y, w0 = problem
        cv = api.cross_validate(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            [0.1], n_folds=4, num_iterations=2, convergence_tol=0.0,
            initial_weights=w0)
        ids = np.asarray(cv.fold_ids)
        assert ids.shape == (400,)
        assert set(np.unique(ids)) == set(range(4))

    def test_selects_sane_strength(self, rng):
        """Planted high-dimensional noise problem: heavy regularization
        must beat (over)fitting with none — best_index must not pick the
        unregularized extreme."""
        n, d = 80, 120  # d > n: unregularized logistic overfits badly
        X = rng.standard_normal((n, d)).astype(np.float32)
        y = (rng.random(n) < 0.5).astype(np.float32)  # pure noise labels
        regs = [1e-6, 0.1, 1.0]
        cv = api.cross_validate(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            regs, n_folds=4, num_iterations=25, convergence_tol=0.0,
            initial_weights=np.zeros(d, np.float32), seed=1)
        assert int(cv.best_index) != 0, np.asarray(cv.mean_val_loss)
        assert np.all(np.isfinite(np.asarray(cv.mean_val_loss)))

    def test_base_mask_excluded_everywhere(self, problem):
        """Rows masked out in the input must influence neither training
        nor validation: results equal running on the subset."""
        X, y, w0 = problem
        keep = np.ones(400, np.float32)
        keep[350:] = 0.0
        cv_masked = api.cross_validate(
            (X, y, keep), losses.LogisticGradient(),
            prox.SquaredL2Updater(), [0.1], n_folds=3,
            num_iterations=3, convergence_tol=0.0,
            initial_weights=w0, seed=5)
        # subset run with the same fold assignment restricted
        ids = np.asarray(cv_masked.fold_ids)
        f = 0
        train_mask = keep * (ids != f)
        w_ref, _ = api.run(
            (X, y, train_mask), losses.LogisticGradient(),
            prox.SquaredL2Updater(), reg_param=0.1, num_iterations=3,
            convergence_tol=0.0, initial_weights=w0, mesh=False)
        np.testing.assert_allclose(
            np.asarray(cv_masked.train_result.weights)[f, 0],
            np.asarray(w_ref), rtol=5e-2, atol=5e-3)

    def test_sparse_input(self, rng):
        n, d, npr = 240, 20, 4
        indptr = np.arange(n + 1) * npr
        X = sparse.CSRMatrix.from_csr_arrays(
            indptr, rng.integers(0, d, n * npr).astype(np.int32),
            rng.normal(size=n * npr).astype(np.float32), d,
            with_csc=True)
        y = (rng.random(n) < 0.5).astype(np.float32)
        cv = api.cross_validate(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            [0.05, 0.5], n_folds=2, num_iterations=3,
            convergence_tol=0.0,
            initial_weights=np.zeros(d, np.float32))
        assert cv.val_loss.shape == (2, 2)
        assert np.all(np.isfinite(np.asarray(cv.val_loss)))

    def test_rejects_bad_inputs(self, problem):
        X, y, w0 = problem
        with pytest.raises(ValueError, match="initial_weights"):
            api.cross_validate((X, y), losses.LogisticGradient(),
                               prox.SquaredL2Updater(), [0.1])
        with pytest.raises(ValueError, match="n_folds"):
            api.cross_validate((X, y), losses.LogisticGradient(),
                               prox.SquaredL2Updater(), [0.1],
                               n_folds=1, initial_weights=w0)
        from spark_agd_tpu.ops.pallas_kernels import PallasMarginGradient
        with pytest.raises(ValueError, match="prepare"):
            api.cross_validate(
                (X, y), PallasMarginGradient(losses.LogisticGradient(),
                                             interpret=True),
                prox.SquaredL2Updater(), [0.1], initial_weights=w0)

    def test_optimizer_method_forwards_config(self, problem):
        """AcceleratedGradientDescent.cross_validate must equal the
        module function under the same configuration and seed."""
        X, y, w0 = problem
        opt = api.AcceleratedGradientDescent(
            losses.LogisticGradient(), prox.SquaredL2Updater())
        opt.set_num_iterations(3).set_convergence_tol(0.0)
        got = opt.cross_validate((X, y), [0.1, 0.5], w0, n_folds=2,
                                 seed=9)
        want = api.cross_validate(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            [0.1, 0.5], n_folds=2, num_iterations=3,
            convergence_tol=0.0, initial_weights=w0, seed=9)
        np.testing.assert_allclose(np.asarray(got.val_loss),
                                   np.asarray(want.val_loss), rtol=1e-6)
        assert int(got.best_index) == int(want.best_index)

    def test_no_empty_folds_small_n(self, rng):
        """Balanced assignment: n barely above n_folds must still give
        every fold at least one row (an empty fold would silently score
        a perfect 0.0 validation loss)."""
        n, d = 11, 3
        X = rng.standard_normal((n, d)).astype(np.float32)
        y = (rng.random(n) < 0.5).astype(np.float32)
        cv = api.cross_validate(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            [0.1], n_folds=10, num_iterations=2, convergence_tol=0.0,
            initial_weights=np.zeros(d, np.float32))
        counts = np.bincount(np.asarray(cv.fold_ids), minlength=10)
        assert counts.min() >= 1, counts
        assert np.all(np.isfinite(np.asarray(cv.val_loss)))

    def test_masked_out_fold_reads_nan(self, problem):
        """A base mask that empties a fold's validation rows must read
        NaN, never 0.0."""
        X, y, w0 = problem
        cv0 = api.cross_validate(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            [0.1], n_folds=4, num_iterations=2, convergence_tol=0.0,
            initial_weights=w0, seed=2)
        ids = np.asarray(cv0.fold_ids)
        keep = (ids != 1).astype(np.float32)  # base mask empties fold 1
        cv = api.cross_validate(
            (X, y, keep), losses.LogisticGradient(),
            prox.SquaredL2Updater(), [0.1], n_folds=4,
            num_iterations=2, convergence_tol=0.0,
            initial_weights=w0, seed=2)
        v = np.asarray(cv.val_loss)
        assert np.isnan(v[1, 0])
        assert np.isfinite(v[[0, 2, 3], 0]).all()


class TestTrainerCV:
    def test_refit_on_best(self, problem):
        from spark_agd_tpu.models import LogisticRegressionWithAGD

        X, y, _ = problem
        t = LogisticRegressionWithAGD()
        t.optimizer.set_num_iterations(4).set_convergence_tol(0.0)
        t.optimizer.set_mesh(False)
        regs = [0.01, 0.5]
        model, cv = t.cross_validate(X, y, regs, n_folds=3, seed=4)
        assert cv.val_loss.shape == (3, 2)
        best = regs[int(cv.best_index)]
        # the refit model equals a direct train at the winning strength
        t2 = LogisticRegressionWithAGD(reg_param=best)
        t2.optimizer.set_num_iterations(4).set_convergence_tol(0.0)
        t2.optimizer.set_mesh(False)
        m_ref = t2.train(X, y)
        np.testing.assert_allclose(np.asarray(model.weights),
                                   np.asarray(m_ref.weights), rtol=1e-5)
        # the trainer's own reg_param is restored
        assert t.optimizer._reg_param == 0.0
        m2, cv2 = t.cross_validate(X, y, regs, n_folds=3, seed=4,
                                   refit=False)
        assert m2 is None
        np.testing.assert_allclose(np.asarray(cv2.val_loss),
                                   np.asarray(cv.val_loss), rtol=1e-6)


class TestMakeCVRunner:
    def test_compile_once_across_grids(self, problem):
        """Same grid SHAPE -> one trace; results equal the one-shot
        cross_validate under the same seed."""
        X, y, w0 = problem
        traces = {"n": 0}

        class Counting(losses.LogisticGradient):
            def batch_loss_and_grad(self, wv, Xv, yv, mask=None):
                traces["n"] += 1
                return super().batch_loss_and_grad(wv, Xv, yv, mask)

        fit = api.make_cv_runner(
            (X, y), Counting(), prox.SquaredL2Updater(), n_folds=2,
            num_iterations=3, convergence_tol=0.0, seed=7, mesh=False)
        cv1 = fit(w0, [0.1, 0.5])
        after_first = traces["n"]
        cv2 = fit(w0, [0.2, 0.9])  # same shape: no new traces
        assert traces["n"] == after_first
        want = api.cross_validate(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            [0.1, 0.5], n_folds=2, num_iterations=3,
            convergence_tol=0.0, initial_weights=w0, seed=7, mesh=False)
        np.testing.assert_allclose(np.asarray(cv1.val_loss),
                                   np.asarray(want.val_loss), rtol=1e-6)
        assert cv2.val_loss.shape == (2, 2)
        assert np.all(np.isfinite(np.asarray(cv2.val_loss)))

    def test_runner_on_mesh(self, problem, cpu_devices):
        from spark_agd_tpu.parallel import mesh as mesh_lib

        X, y, w0 = problem
        mesh = mesh_lib.make_mesh({"data": 4}, devices=cpu_devices[:4])
        fit = api.make_cv_runner(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            n_folds=2, num_iterations=3, convergence_tol=0.0, seed=7,
            mesh=mesh)
        cv = fit(w0, [0.1, 0.5])
        want = api.cross_validate(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            [0.1, 0.5], n_folds=2, num_iterations=3,
            convergence_tol=0.0, initial_weights=w0, seed=7, mesh=False)
        np.testing.assert_allclose(np.asarray(cv.val_loss),
                                   np.asarray(want.val_loss),
                                   rtol=1e-5, atol=1e-7)

    def test_missing_weights_rejected(self, problem):
        X, y, _ = problem
        fit = api.make_cv_runner((X, y), losses.LogisticGradient(),
                                 prox.SquaredL2Updater(), n_folds=2,
                                 mesh=False)
        with pytest.raises(ValueError, match="initial_weights"):
            fit(None, [0.1])
