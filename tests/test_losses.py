"""Unit tests for the batched loss kernels (SURVEY §7 step 1).

The reference has no kernel-level unit tests (its math is only tested
end-to-end vs a GD oracle — SURVEY §4); these add the missing pyramid layer:
each kernel vs (a) a direct NumPy closed form, (b) ``jax.grad`` of its own
loss, (c) finite differences.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_agd_tpu.ops import losses


def _fd_grad(f, w, eps=1e-6):
    """Central finite-difference gradient of scalar f at flat vector w."""
    w = np.asarray(w, dtype=np.float64)
    g = np.zeros_like(w)
    for i in range(w.size):
        up = w.copy()
        dn = w.copy()
        up[i] += eps
        dn[i] -= eps
        g[i] = (f(up) - f(dn)) / (2 * eps)
    return g


@pytest.fixture
def batch(rng):
    N, D = 64, 5
    X = rng.normal(size=(N, D))
    w = rng.normal(size=(D,))
    y01 = (rng.random(N) > 0.5).astype(np.float64)
    yreal = rng.normal(size=(N,))
    return X, w, y01, yreal


class TestLogistic:
    def test_closed_form_vs_numpy(self, batch):
        X, w, y, _ = batch
        loss, grad, n = losses.LogisticGradient().batch_loss_and_grad(
            jnp.asarray(w), jnp.asarray(X), jnp.asarray(y))
        # NumPy reference: sum_i log(1+exp(-x.w)) - (1-y)(-x.w)
        m = -X @ w
        expect = np.sum(np.log1p(np.exp(m)) - (1 - y) * m)
        np.testing.assert_allclose(float(loss), expect, rtol=1e-12)
        p = 1 / (1 + np.exp(-(X @ w)))
        np.testing.assert_allclose(np.asarray(grad), X.T @ (p - y), rtol=1e-10)
        assert int(n) == X.shape[0]

    def test_grad_vs_autodiff_and_fd(self, batch):
        X, w, y, _ = batch
        g = losses.LogisticGradient()
        Xj, yj = jnp.asarray(X), jnp.asarray(y)
        _, grad, _ = g.batch_loss_and_grad(jnp.asarray(w), Xj, yj)
        auto = jax.grad(lambda wv: g.batch_loss_and_grad(wv, Xj, yj)[0])(
            jnp.asarray(w))
        np.testing.assert_allclose(np.asarray(grad), np.asarray(auto),
                                   rtol=1e-10)
        fd = _fd_grad(
            lambda wv: float(g.batch_loss_and_grad(jnp.asarray(wv), Xj, yj)[0]),
            w)
        np.testing.assert_allclose(np.asarray(grad), fd, rtol=1e-5, atol=1e-7)

    def test_stability_large_margin(self):
        # softplus formulation must not overflow where naive log1p(exp) would.
        X = jnp.array([[1000.0], [-1000.0]])
        y = jnp.array([0.0, 1.0])
        w = jnp.array([1.0])
        loss, grad, _ = losses.LogisticGradient().batch_loss_and_grad(X=X, y=y,
                                                                     weights=w)
        assert np.isfinite(float(loss))
        assert np.all(np.isfinite(np.asarray(grad)))
        # both examples are maximally wrong: loss ~ 1000 each
        np.testing.assert_allclose(float(loss), 2000.0, rtol=1e-6)


class TestLeastSquares:
    def test_closed_form(self, batch):
        X, w, _, y = batch
        loss, grad, n = losses.LeastSquaresGradient().batch_loss_and_grad(
            jnp.asarray(w), jnp.asarray(X), jnp.asarray(y))
        diff = X @ w - y
        # 1.3 convention: diff^2 (not halved), grad 2*diff*x
        np.testing.assert_allclose(float(loss), np.sum(diff**2), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(grad), 2 * X.T @ diff,
                                   rtol=1e-10)

    def test_grad_vs_autodiff(self, batch):
        X, w, _, y = batch
        g = losses.LeastSquaresGradient()
        Xj, yj = jnp.asarray(X), jnp.asarray(y)
        _, grad, _ = g.batch_loss_and_grad(jnp.asarray(w), Xj, yj)
        auto = jax.grad(lambda wv: g.batch_loss_and_grad(wv, Xj, yj)[0])(
            jnp.asarray(w))
        np.testing.assert_allclose(np.asarray(grad), np.asarray(auto),
                                   rtol=1e-10)


class TestHinge:
    def test_closed_form(self, batch):
        X, w, y, _ = batch
        loss, grad, _ = losses.HingeGradient().batch_loss_and_grad(
            jnp.asarray(w), jnp.asarray(X), jnp.asarray(y))
        s = 2 * y - 1
        margin = 1 - s * (X @ w)
        active = margin > 0
        np.testing.assert_allclose(float(loss), np.sum(margin[active]),
                                   rtol=1e-12)
        np.testing.assert_allclose(
            np.asarray(grad), X[active].T @ (-s[active]), rtol=1e-10)

    def test_inactive_everywhere_gives_zero(self):
        # perfectly separated data with big margins: loss 0, grad 0
        X = jnp.array([[10.0], [-10.0]])
        y = jnp.array([1.0, 0.0])
        w = jnp.array([1.0])
        loss, grad, _ = losses.HingeGradient().batch_loss_and_grad(w, X, y)
        assert float(loss) == 0.0
        np.testing.assert_array_equal(np.asarray(grad), [0.0])


class TestSoftmax:
    def test_matches_binary_logistic(self, rng):
        """2-class softmax with class-0 column frozen at 0 == binary logistic."""
        N, D = 32, 4
        X = rng.normal(size=(N, D))
        w = rng.normal(size=(D,))
        y = (rng.random(N) > 0.5).astype(np.int32)
        W2 = np.stack([np.zeros(D), w], axis=1)  # (D, 2)
        sm = losses.SoftmaxGradient(2)
        lo = losses.LogisticGradient()
        l_sm, g_sm, _ = sm.batch_loss_and_grad(jnp.asarray(W2), jnp.asarray(X),
                                               jnp.asarray(y))
        l_lo, g_lo, _ = lo.batch_loss_and_grad(jnp.asarray(w), jnp.asarray(X),
                                               jnp.asarray(y.astype(np.float64)))
        np.testing.assert_allclose(float(l_sm), float(l_lo), rtol=1e-10)
        np.testing.assert_allclose(np.asarray(g_sm)[:, 1], np.asarray(g_lo),
                                   rtol=1e-9)

    def test_grad_vs_autodiff(self, rng):
        N, D, K = 16, 3, 5
        X = jnp.asarray(rng.normal(size=(N, D)))
        y = jnp.asarray(rng.integers(0, K, size=N))
        W = jnp.asarray(rng.normal(size=(D, K)))
        sm = losses.SoftmaxGradient(K)
        _, grad, _ = sm.batch_loss_and_grad(W, X, y)
        auto = jax.grad(lambda Wv: sm.batch_loss_and_grad(Wv, X, y)[0])(W)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(auto),
                                   rtol=1e-9)


class TestMasking:
    """Padding rows (mask 0) must be invisible to all three sums — the
    contract the sharding layer's pad-to-even-shards relies on."""

    @pytest.mark.parametrize("kind", ["logistic", "least_squares", "hinge"])
    def test_padded_equals_unpadded(self, rng, kind):
        N, D, pad = 40, 4, 7
        X = rng.normal(size=(N, D))
        w = jnp.asarray(rng.normal(size=D))
        if kind == "least_squares":
            y = rng.normal(size=N)
            g = losses.LeastSquaresGradient()
        else:
            y = (rng.random(N) > 0.5).astype(np.float64)
            g = (losses.LogisticGradient() if kind == "logistic"
                 else losses.HingeGradient())
        Xp = np.concatenate([X, np.zeros((pad, D))])
        yp = np.concatenate([y, np.zeros(pad)])
        mask = np.concatenate([np.ones(N), np.zeros(pad)])
        l0, g0, n0 = g.batch_loss_and_grad(w, jnp.asarray(X), jnp.asarray(y))
        l1, g1, n1 = g.batch_loss_and_grad(
            w, jnp.asarray(Xp), jnp.asarray(yp), jnp.asarray(mask))
        np.testing.assert_allclose(float(l1), float(l0), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                                   rtol=1e-12)
        assert int(n1) == int(n0) == N

    def test_softmax_masked(self, rng):
        N, D, K, pad = 24, 3, 4, 5
        X = rng.normal(size=(N, D))
        y = rng.integers(0, K, size=N)
        W = jnp.asarray(rng.normal(size=(D, K)))
        g = losses.SoftmaxGradient(K)
        Xp = np.concatenate([X, np.zeros((pad, D))])
        yp = np.concatenate([y, np.zeros(pad, dtype=y.dtype)])
        mask = np.concatenate([np.ones(N), np.zeros(pad)])
        l0, g0, n0 = g.batch_loss_and_grad(W, jnp.asarray(X), jnp.asarray(y))
        l1, g1, n1 = g.batch_loss_and_grad(
            W, jnp.asarray(Xp), jnp.asarray(yp), jnp.asarray(mask))
        np.testing.assert_allclose(float(l1), float(l0), rtol=1e-12)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                                   rtol=1e-12)
        assert int(n1) == N

    def test_custom_rejects_mask_unless_declared(self, rng):
        g = losses.CustomGradient(lambda w, X, y: jnp.sum((X @ w - y) ** 2))
        X = jnp.asarray(rng.normal(size=(4, 2)))
        y = jnp.asarray(rng.normal(size=4))
        with pytest.raises(ValueError, match="supports_mask"):
            g.batch_loss_and_grad(jnp.zeros(2), X, y, jnp.ones(4))


class TestCustom:
    def test_pytree_weights(self, rng):
        """CustomGradient over an MLP-style pytree (config-5 seam)."""
        N, D, H = 16, 4, 3
        X = jnp.asarray(rng.normal(size=(N, D)))
        y = jnp.asarray((rng.random(N) > 0.5).astype(np.float64))
        params = {
            "W1": jnp.asarray(rng.normal(size=(D, H))),
            "b1": jnp.zeros(H),
            "w2": jnp.asarray(rng.normal(size=(H,))),
        }

        def mlp_loss(p, X, y):
            h = jnp.tanh(X @ p["W1"] + p["b1"])
            logits = h @ p["w2"]
            return jnp.sum(jax.nn.softplus(-logits) + (1 - y) * logits)

        g = losses.CustomGradient(mlp_loss)
        loss, grad, n = g.batch_loss_and_grad(params, X, y)
        assert int(n) == N
        assert set(grad.keys()) == {"W1", "b1", "w2"}
        auto = jax.grad(mlp_loss)(params, X, y)
        for k in params:
            np.testing.assert_allclose(np.asarray(grad[k]),
                                       np.asarray(auto[k]), rtol=1e-10)
