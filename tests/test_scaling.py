"""Scaling-observatory tests: weak-scaling curve math, the host-
contention sentinel, curve-shape perf gating, provenance-keyed history
refusals, and the 1->4 virtual-device CPU ladder.

The acceptance triad lives here in tier-1: (a) a clean measured curve
passes the gate, (b) a synthetically degraded curve FAILS on shape
(efficiency floor / monotonicity / serial-fraction ceiling), and (c) a
cross-environment or contention-flagged comparison is REFUSED with a
typed exit-2 record — never silently compared.  The full-device ladder
rides behind ``-m slow``.

NOTE on the real-ladder legs: tier-1 runs on virtual CPU devices that
often share ONE physical core (the container quota), so weak-scaling
efficiency legitimately decays ~1/k there — the real-curve gate legs
therefore use a mechanics-lenient policy (tiny efficiency floor, no
serial ceiling) and the strict-policy semantics are pinned on synthetic
curves where the numbers are exact.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from benchmarks import run as bench_run
from spark_agd_tpu.obs import (
    InMemorySink,
    Telemetry,
    introspect,
    perfgate,
    scaling,
    schema,
)

pytestmark = pytest.mark.bench

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# synthetic curve builders (exact numbers, no hardware noise)
# ---------------------------------------------------------------------------


def make_point(devices, sec_per_iter, *, flagged=False, rows=None,
               contention=True, **extra):
    p = {"devices": devices, "rows": rows or 100 * devices, "iters": 8,
         "wall_s": sec_per_iter * 8, "sec_per_iter": sec_per_iter,
         "iters_per_sec": round(1.0 / sec_per_iter, 2),
         "collectives": {"all-reduce": 3}, **extra}
    if contention:
        p["contention"] = {
            "flagged": bool(flagged), "spin_score": 0.9 if flagged
            else 0.01, "steal_ticks": 0, "loadavg_before": 0.2,
            "loadavg_during_max": 0.3,
            "reasons": (["spin-probe interference score 0.90 > 0.75"]
                        if flagged else []),
        }
    return p


def make_curve(name="ladder", spis=(0.05, 0.052, 0.055), *,
               flag_at=None, env=None, contention=True, **extra):
    points = [make_point(2 ** i, spi, flagged=(flag_at == 2 ** i),
                         contention=contention)
              for i, spi in enumerate(spis)]
    fields = scaling.curve_fields(points)
    rec = schema.scaling_curve_record(
        schema.new_run_id(), name, fields.pop("points"),
        algorithm="agd", **fields, platform="cpu", device_kind="cpu",
        jax_version="0.4.37", jaxlib_version="0.4.37", n_processes=1,
        cpu_count=8, env_key="env-aaaaaaaaaaaa", **extra)
    rec.update(env or {})
    return schema.stamp(rec, tool="benchmarks.run",
                        kind="scaling_curve")


# ---------------------------------------------------------------------------
# host facts + sentinel
# ---------------------------------------------------------------------------


class TestHostFingerprint:
    def test_fields_and_types(self):
        fp = scaling.host_fingerprint()
        assert isinstance(fp["cpu_count"], int) and fp["cpu_count"] >= 1
        # loadavg exists on every POSIX CI box this suite runs on
        assert isinstance(fp["loadavg_1m"], float)
        for key in ("cpu_governor", "cpu_turbo"):
            if key in fp:
                assert isinstance(fp[key], str)
        if "cgroup_cpu_quota" in fp:
            assert isinstance(fp["cgroup_cpu_quota"], (int, float, str))

    def test_environment_fingerprint_carries_host_half(self):
        fp = introspect.environment_fingerprint()
        assert fp["cpu_count"] == os.cpu_count()
        assert "loadavg_1m" in fp
        # the extended fingerprint must remain a valid run record —
        # bench.py and benchmarks/run.py stamp it onto every row
        rec = schema.run_record(tool="test", **fp)
        assert schema.validate_record(json.loads(json.dumps(rec))) == []

    def test_fingerprint_without_backend_fields_keeps_host_half(self):
        # the bench watchdog path: only_if_initialized with a live
        # backend still returns everything; the host half never needs
        # a backend (asserted via host_fingerprint being a subset)
        fp = introspect.environment_fingerprint(only_if_initialized=True)
        assert "cpu_count" in fp and "jax_version" in fp

    def test_environment_key_stability_and_sensitivity(self):
        base = {"platform": "cpu", "jax_version": "0.4.37",
                "cpu_count": 8, "loadavg_1m": 0.5}
        k1 = scaling.environment_key(base)
        # loadavg is measurement-time state, NOT identity
        k2 = scaling.environment_key({**base, "loadavg_1m": 7.5})
        assert k1 == k2 and k1.startswith("env-")
        # identity fields flip the key
        assert scaling.environment_key({**base, "cpu_count": 64}) != k1
        assert scaling.environment_key(
            {**base, "platform": "tpu"}) != k1


class TestSpinProbeAndSentinel:
    def test_probe_calibrates_and_scores(self):
        probe = scaling.SpinProbe(work=20_000)
        base = probe.calibrate(repeats=3)
        assert base > 0
        score = probe.score(repeats=2)
        assert score >= 0.0

    def test_watch_report_shape(self):
        sentinel = scaling.ContentionSentinel(
            probe=scaling.SpinProbe(work=20_000),
            sample_interval_s=0.01)
        with sentinel.watch() as w:
            sum(range(10_000))
        rep = w.report
        assert rep is not None
        for key in ("seconds", "loadavg_before", "spin_score_before",
                    "spin_score_after", "spin_score", "flagged"):
            assert key in rep
        assert rep["seconds"] > 0
        assert isinstance(rep["flagged"], bool)

    def test_flagging_thresholds(self):
        policy = scaling.ContentionPolicy(max_spin_score=0.5,
                                          max_steal_ticks=10,
                                          max_loadavg_jump=2.0)
        clean = {"spin_score": 0.1, "steal_ticks": 0,
                 "loadavg_before": 1.0, "loadavg_during_max": 1.5}
        flagged, reasons = scaling.flag_contention(clean, policy)
        assert not flagged and reasons == []
        for dirty, needle in (
                ({"spin_score": 0.9}, "spin-probe"),
                ({"steal_ticks": 50}, "steal"),
                ({"loadavg_before": 1.0, "loadavg_during_max": 9.0},
                 "loadavg")):
            flagged, reasons = scaling.flag_contention(
                {**clean, **dirty}, policy)
            assert flagged and any(needle in r for r in reasons), dirty

    def test_unreadable_fields_never_flag(self):
        flagged, reasons = scaling.flag_contention(
            {"spin_score": None, "steal_ticks": None,
             "loadavg_before": None, "loadavg_during_max": None})
        assert not flagged and reasons == []


# ---------------------------------------------------------------------------
# curve math
# ---------------------------------------------------------------------------


class TestCurveMath:
    def test_weak_scaling_efficiency(self):
        pts = [make_point(1, 0.05), make_point(2, 0.0625),
               make_point(4, 0.1)]
        assert scaling.weak_scaling_efficiency(pts) == [1.0, 0.8, 0.5]

    def test_efficiency_sorts_points_by_devices(self):
        pts = [make_point(4, 0.1), make_point(1, 0.05)]
        assert scaling.weak_scaling_efficiency(pts) == [1.0, 0.5]

    def test_point_time_fallback_to_wall(self):
        p = {"devices": 2, "wall_s": 0.8, "iters": 8}
        assert scaling.point_time(p) == 0.1
        assert scaling.point_time({"devices": 2}) is None

    def test_serial_fraction_exact_recovery(self):
        # generate t_k = t1 * ((1-s) + s*k) for known s and recover it
        s, t1 = 0.2, 0.04
        pts = [make_point(k, t1 * ((1 - s) + s * k))
               for k in (1, 2, 4, 8)]
        assert scaling.fit_serial_fraction(pts) == pytest.approx(
            s, abs=1e-6)

    def test_serial_fraction_clamps_and_degenerates(self):
        # superlinear (faster at more devices) clamps at 0
        pts = [make_point(1, 0.05), make_point(2, 0.03)]
        assert scaling.fit_serial_fraction(pts) == 0.0
        # worse than fully-serial clamps at 1
        pts = [make_point(1, 0.05), make_point(2, 1.0)]
        assert scaling.fit_serial_fraction(pts) == 1.0
        # one point: no fit
        assert scaling.fit_serial_fraction([make_point(1, 0.05)]) is None

    def test_curve_fields_rollup(self):
        pts = [make_point(2, 0.052, flagged=True), make_point(1, 0.05)]
        fields = scaling.curve_fields(pts)
        assert fields["n_points"] == 2
        assert fields["max_devices"] == 2
        assert [p["devices"] for p in fields["points"]] == [1, 2]
        assert fields["contention_flagged"] == 1
        assert fields["efficiency"][0] == 1.0
        assert "serial_fraction" in fields


class TestCurveShape:
    def test_clean_curve_passes(self):
        v = scaling.check_curve(make_curve(), scaling.CurvePolicy())
        assert v.ok and v.failures == [] and v.contended == []

    def test_efficiency_floor(self):
        v = scaling.check_curve(make_curve(spis=(0.05, 0.09, 0.2)))
        assert any("below the 0.5 floor" in f for f in v.failures)

    def test_non_monotone_curve_fails_shape(self):
        # efficiency dips then recovers: the smaller rung was contended
        v = scaling.check_curve(
            make_curve(spis=(0.05, 0.09, 0.05)),
            scaling.CurvePolicy(min_efficiency=0.0))
        assert any("non-monotone" in f for f in v.failures)

    def test_serial_fraction_ceiling(self):
        v = scaling.check_curve(
            make_curve(spis=(0.05, 0.075, 0.125)),  # s = 0.5 exactly
            scaling.CurvePolicy(min_efficiency=0.0, monotone_slack=1.0,
                                max_serial_fraction=0.3))
        assert any("serial fraction" in f for f in v.failures)
        assert v.serial_fraction == pytest.approx(0.5, abs=1e-3)

    def test_contaminated_points_reported(self):
        v = scaling.check_curve(make_curve(flag_at=2))
        assert v.contended and "devices=2" in v.contended[0]
        assert not v.ok

    def test_single_point_is_not_a_curve(self):
        v = scaling.check_curve(make_curve(spis=(0.05,)))
        assert any("at least 2 mesh shapes" in f for f in v.failures)


class TestProvenanceQuarantine:
    def test_legacy_wrapper_row_quarantined(self):
        gaps = scaling.provenance_gaps(
            {"n": 1, "cmd": "python bench.py", "rc": 1, "tail": "..."})
        assert gaps and "legacy bench driver log" in gaps[0]

    def test_missing_provenance_fields_reported(self):
        gaps = scaling.provenance_gaps({"kind": "run", "name": "x"})
        assert any("jax_version" in g for g in gaps)

    def test_curve_without_contention_or_env_key_quarantined(self):
        rec = make_curve(contention=False)
        del rec["env_key"]
        gaps = scaling.provenance_gaps(rec)
        assert any("contention report" in g for g in gaps)
        assert any("env_key" in g for g in gaps)

    def test_full_curve_is_trusted(self):
        assert scaling.provenance_gaps(make_curve()) == []


# ---------------------------------------------------------------------------
# schema + telemetry
# ---------------------------------------------------------------------------


class TestScalingSchema:
    def test_kind_registered_and_selfcheck(self):
        assert "scaling_curve" in schema.KINDS
        ok, msgs = schema.selfcheck()
        assert ok, "\n".join(msgs)

    def test_synthetic_curve_record_validates(self):
        rec = make_curve()
        assert schema.validate_record(json.loads(json.dumps(rec))) == []

    def test_telemetry_helper_emits_and_gauges(self):
        tel = Telemetry(sinks=[InMemorySink()])
        fields = scaling.curve_fields(
            [make_point(1, 0.05), make_point(2, 0.1, flagged=True)])
        pts = fields.pop("points")
        rec = tel.scaling_curve(name="lad", points=pts, **fields)
        assert schema.validate_record(json.loads(json.dumps(rec))) == []
        assert rec in tel.records
        snap = tel.registry.snapshot()
        assert snap["scaling.lad.efficiency_floor"] == 0.5
        assert snap["scaling.lad.serial_fraction"] == 1.0
        assert snap["scaling.contended_points"] == 1


# ---------------------------------------------------------------------------
# the curve-shape gate
# ---------------------------------------------------------------------------


class TestScalingGate:
    def test_clean_candidate_passes(self):
        res = perfgate.gate_scaling([make_curve()])
        assert res.exit_code() == 0 and res.status() == "pass"

    def test_degraded_curve_fails_on_shape(self):
        res = perfgate.gate_scaling([make_curve(spis=(0.05, 0.09, 0.2))])
        assert res.exit_code() == 1 and res.status() == "fail"
        assert res.shape_failures

    def test_no_curves_is_a_refusal(self):
        res = perfgate.gate_scaling([{"kind": "run"}])
        assert res.exit_code() == 2

    def test_contention_flagged_comparison_refused_typed(self):
        res = perfgate.gate_scaling([make_curve(flag_at=2)])
        assert res.exit_code() == 2 and res.status() == "refused"
        rec = res.record()
        assert schema.validate_record(json.loads(json.dumps(rec))) == []
        assert rec["gate_status"] == "refused"
        assert any("contention-contaminated" in r
                   for r in rec["refusals"])

    def test_contention_refusal_waivable_by_policy(self):
        policy = scaling.CurvePolicy(
            contention=scaling.ContentionPolicy(refuse_contended=False))
        res = perfgate.gate_scaling([make_curve(flag_at=2)],
                                    policy=policy)
        assert res.exit_code() == 0

    def test_cross_environment_comparison_refused_typed(self):
        cand = make_curve()
        base = make_curve(env={"jax_version": "0.9.99"})
        res = perfgate.gate_scaling([cand], [base])
        assert res.exit_code() == 2
        assert any("cross-environment" in r for r in res.refusals)
        rec = res.record()
        assert rec["gate_status"] == "refused"
        # allow-cross-env downgrades the refusal, mirroring perf_gate
        res = perfgate.gate_scaling([cand], [base],
                                    allow_cross_env=True)
        assert res.exit_code() == 0

    def test_contaminated_baseline_also_refused(self):
        res = perfgate.gate_scaling([make_curve()],
                                    [make_curve(flag_at=4)])
        assert res.exit_code() == 2
        assert any(r.startswith("[baseline]") for r in res.refusals)

    def test_quarantined_candidate_refused(self):
        rec = make_curve(contention=False)
        res = perfgate.gate_scaling([rec])
        assert res.exit_code() == 2
        assert any("quarantined" in r for r in res.refusals)

    def test_per_point_regression_vs_baseline(self):
        lenient = scaling.CurvePolicy(min_efficiency=0.0,
                                      monotone_slack=10.0,
                                      max_serial_fraction=1.0)
        base = make_curve()
        cand = make_curve(spis=(0.05, 0.07, 0.1))
        res = perfgate.gate_scaling([cand], [base], policy=lenient)
        assert res.exit_code() == 1
        metrics = {d.metric for d in res.regressions}
        assert {"sec_per_iter", "efficiency",
                "serial_fraction"} <= metrics
        # identical curves pass
        res = perfgate.gate_scaling([base], [base], policy=lenient)
        assert res.exit_code() == 0

    def test_report_renders(self):
        res = perfgate.gate_scaling([make_curve(spis=(0.05, 0.09, 0.2))])
        text = perfgate.format_scaling_report(res)
        assert "efficiency" in text and "FAIL" in text

    def test_env_fields_extended_for_runs(self):
        # the hardened host-identity fields now refuse run comparisons
        base = {"kind": "run", "tool": "t", "name": "x",
                "wall_s": 1.0, "cpu_count": 8}
        cand = dict(base, cpu_count=64, wall_s=2.0)
        res = perfgate.compare_records([base], [cand])
        assert res.refused
        assert any("cpu_count" in m for m in res.env_mismatches)


# ---------------------------------------------------------------------------
# the real ladder (1->4 virtual CPU devices; tier-1)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def ladder_record(cpu_devices):
    """One shared real ladder run: config 2 (dense linreg), weak-scaled
    2k/4k/8k rows over 1/2/4 virtual devices, tiny iteration budget."""
    sentinel = scaling.ContentionSentinel(
        probe=scaling.SpinProbe(work=50_000), sample_interval_s=0.05)
    return bench_run.run_ladder(
        bench_run.CONFIGS[1], scale_per_device=0.0002, iters=5,
        max_devices=4, sentinel=sentinel)


# the real-curve legs gate MECHANICS, not hardware parallelism: tier-1
# virtual devices may share one physical core (see module docstring)
LENIENT = scaling.CurvePolicy(
    min_efficiency=0.01, monotone_slack=10.0, max_serial_fraction=1.0,
    contention=scaling.ContentionPolicy(refuse_contended=False))


class TestRealLadder:
    def test_record_validates_and_is_weak_scaled(self, ladder_record):
        rec = ladder_record
        assert schema.validate_record(json.loads(json.dumps(rec))) == []
        assert rec["kind"] == "scaling_curve"
        assert [p["devices"] for p in rec["points"]] == [1, 2, 4]
        rows = [p["rows"] for p in rec["points"]]
        assert rows == [2000, 4000, 8000], \
            f"rows must scale with devices (weak scaling), got {rows}"

    def test_points_carry_program_cost_and_contention(self,
                                                      ladder_record):
        for p in ladder_record["points"]:
            assert p["flops"] is not None and p["flops"] > 0
            assert isinstance(p["collectives"], dict)
            assert "all-reduce" in p["collectives"]
            cont = p["contention"]
            assert isinstance(cont["flagged"], bool)
            assert cont["spin_score"] >= 0
            assert p["sec_per_iter"] > 0 and p["iters"] == 5

    def test_mesh_shapes_recorded_per_point(self, ladder_record):
        shapes = [p["mesh_shape"] for p in ladder_record["points"]]
        assert shapes == [{"data": 1}, {"data": 2}, {"data": 4}]

    def test_provenance_stamped_and_trusted(self, ladder_record):
        rec = ladder_record
        assert rec["env_key"] == scaling.environment_key(rec)
        assert rec["platform"] == "cpu"
        assert rec["jax_version"] and rec["cpu_count"] >= 1
        assert rec["spin_baseline_s"] > 0
        assert scaling.provenance_gaps(rec) == []

    def test_curve_fields_consistent(self, ladder_record):
        rec = ladder_record
        assert rec["n_points"] == 3 and rec["max_devices"] == 4
        assert rec["efficiency"][0] == 1.0
        assert rec["efficiency"] == \
            scaling.weak_scaling_efficiency(rec["points"])

    def test_acceptance_triad(self, ladder_record):
        """(a) the clean measured curve passes the gate; (b) a
        synthetically degraded twin FAILS on shape; (c) a contention-
        flagged / cross-env comparison is refused exit-2 typed."""
        clean = ladder_record
        res = perfgate.gate_scaling([clean], policy=LENIENT)
        assert res.exit_code() == 0, \
            perfgate.format_scaling_report(res)

        degraded = json.loads(json.dumps(clean))
        for p in degraded["points"][1:]:
            p["sec_per_iter"] = p["sec_per_iter"] * 40 * p["devices"]
            p["wall_s"] = p["sec_per_iter"] * p["iters"]
        degraded["efficiency"] = scaling.weak_scaling_efficiency(
            degraded["points"])
        degraded["serial_fraction"] = scaling.fit_serial_fraction(
            degraded["points"])
        res = perfgate.gate_scaling([degraded])
        assert res.exit_code() == 1 and res.shape_failures

        flagged = json.loads(json.dumps(clean))
        flagged["points"][-1]["contention"]["flagged"] = True
        flagged["contention_flagged"] = 1
        res = perfgate.gate_scaling([flagged])
        assert res.exit_code() == 2
        assert res.record()["gate_status"] == "refused"

        xenv = json.loads(json.dumps(clean))
        xenv["jax_version"] = "9.9.9"
        res = perfgate.gate_scaling([clean], [xenv], policy=LENIENT)
        assert res.exit_code() == 2
        assert any("cross-environment" in r for r in res.refusals)

    def test_history_roundtrip_and_same_env_gate(self, ladder_record,
                                                 tmp_path):
        hist = tmp_path / "hist.jsonl"
        base = json.loads(json.dumps(ladder_record))
        base["run_id"] = "r-baseline-0"
        with open(hist, "a") as f:
            f.write(json.dumps(base) + "\n")
            f.write(json.dumps(ladder_record) + "\n")
        records = schema.read_jsonl(str(hist))
        curves = perfgate.split_curves(records)
        assert len(curves) == 1  # same identity key: last wins
        res = perfgate.gate_scaling([ladder_record], [base],
                                    policy=LENIENT)
        assert res.exit_code() == 0

    @pytest.mark.slow
    def test_full_device_ladder(self, cpu_devices):
        """The full 1->8 ladder over every virtual device (slow)."""
        rec = bench_run.run_ladder(
            bench_run.CONFIGS[1], scale_per_device=0.0002, iters=8)
        ks = [p["devices"] for p in rec["points"]]
        assert ks == [1, 2, 4, 8]
        assert schema.validate_record(json.loads(json.dumps(rec))) == []
        res = perfgate.gate_scaling([rec], policy=LENIENT)
        assert res.exit_code() == 0


class TestLadderRungs:
    def test_powers_of_two_and_remainder(self):
        assert bench_run.ladder_rungs(8) == [1, 2, 4, 8]
        assert bench_run.ladder_rungs(6) == [1, 2, 4, 6]
        assert bench_run.ladder_rungs(1) == [1]
        assert bench_run.ladder_rungs(8, max_devices=4) == [1, 2, 4]
        assert bench_run.ladder_rungs(8, max_devices=3) == [1, 2, 3]


# ---------------------------------------------------------------------------
# CLI legs
# ---------------------------------------------------------------------------


def _bench_cmd(*args):
    tool = os.path.join(REPO, "tools", "agd_bench.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [REPO] + env.get("PYTHONPATH", "").split(os.pathsep))
    return [sys.executable, tool, *args], env


class TestAgdBenchCLI:
    def test_validate_quarantines_legacy_bench_files(self):
        """The repo's own poisoned BENCH_r0*.json trajectory is parsed,
        reported, and quarantined — not crashed on."""
        legacy = [p for p in (os.path.join(REPO, f"BENCH_r0{i}.json")
                              for i in (1, 5)) if os.path.exists(p)]
        if not legacy:
            pytest.skip("legacy BENCH files not present")
        cmd, env = _bench_cmd("validate", *legacy)
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120, env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert proc.stdout.count("QUARANTINED") == len(legacy)
        assert "legacy bench driver log" in proc.stdout
        assert "excluded from history comparisons" in proc.stdout

    def test_validate_trusts_full_curves(self, tmp_path):
        path = tmp_path / "curves.jsonl"
        path.write_text(json.dumps(make_curve()) + "\n")
        cmd, env = _bench_cmd("validate", str(path))
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120, env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "trusted [scaling_curve]" in proc.stdout

    def test_gate_cli_pass_fail_refuse(self, tmp_path):
        """gate exit codes 0/1/2 + the typed outcome record on stdout."""
        clean, degraded, flagged = (
            make_curve(),
            make_curve(spis=(0.05, 0.09, 0.2)),
            make_curve(flag_at=2))
        for rec, want, status in ((clean, 0, "pass"),
                                  (degraded, 1, "fail"),
                                  (flagged, 2, "refused")):
            path = tmp_path / f"c{want}.jsonl"
            path.write_text(json.dumps(rec) + "\n")
            cmd, env = _bench_cmd("gate", str(path))
            proc = subprocess.run(cmd, capture_output=True, text=True,
                                  timeout=120, env=env)
            assert proc.returncode == want, \
                f"{status}: {proc.stdout[-2000:]}{proc.stderr[-1000:]}"
            typed = json.loads(proc.stdout.strip().splitlines()[-1])
            assert typed["kind"] == "run"
            assert typed["name"] == "scaling_gate"
            assert typed["gate_status"] == status
            assert schema.validate_record(typed) == []

    def test_gate_cli_cross_env_refused_and_waived(self, tmp_path):
        cand, base = tmp_path / "cand.jsonl", tmp_path / "base.jsonl"
        cand.write_text(json.dumps(make_curve()) + "\n")
        base.write_text(json.dumps(
            make_curve(env={"jax_version": "9.9.9"})) + "\n")
        cmd, env = _bench_cmd("gate", str(cand), "--baseline",
                              str(base))
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120, env=env)
        assert proc.returncode == 2, proc.stdout[-2000:]
        assert "cross-environment" in proc.stdout
        cmd, env = _bench_cmd("gate", str(cand), "--baseline",
                              str(base), "--allow-cross-env")
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120, env=env)
        assert proc.returncode == 0, proc.stdout[-2000:]

    def test_gate_cli_history_quarantine(self, tmp_path):
        """History rows from another environment (different env_key)
        are quarantined from the comparison, not compared."""
        cand_rec = make_curve()
        other = make_curve(env={"env_key": "env-bbbbbbbbbbbb",
                                "jax_version": "9.9.9"})
        other["run_id"] = "r-other-env"
        hist = tmp_path / "hist.jsonl"
        hist.write_text(json.dumps(other) + "\n"
                        + json.dumps(cand_rec) + "\n")
        cand = tmp_path / "cand.jsonl"
        cand.write_text(json.dumps(cand_rec) + "\n")
        cmd, env = _bench_cmd("gate", str(cand), "--history",
                              str(hist))
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120, env=env)
        assert proc.returncode == 0, proc.stdout[-2000:]
        assert "quarantined from history comparison" in proc.stderr
        assert "different environment" in proc.stderr

    def test_compare_cli_reports_without_failing(self, tmp_path):
        base, cand = tmp_path / "b.jsonl", tmp_path / "c.jsonl"
        base.write_text(json.dumps(make_curve()) + "\n")
        cand.write_text(json.dumps(
            make_curve(spis=(0.05, 0.09, 0.2))) + "\n")
        cmd, env = _bench_cmd("compare", str(base), str(cand))
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120, env=env)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "scaling compare" in proc.stdout
        assert "sec_per_iter" in proc.stdout

    def test_run_cli_end_to_end(self, tmp_path):
        """The acceptance leg: tools/agd_bench.py on CPU runs a 1->4
        virtual-device weak-scaling ladder end to end and appends a
        provenance-stamped scaling_curve record to the history."""
        hist = tmp_path / "hist.jsonl"
        cmd, env = _bench_cmd(
            "run", "--config", "2", "--devices", "4",
            "--scale-per-device", "0.0002", "--iters", "4",
            "--history", str(hist))
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=420, env=env)
        assert proc.returncode == 0, \
            f"run failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-3000:]}"
        recs = [json.loads(ln) for ln in
                hist.read_text().splitlines() if ln.strip()]
        assert len(recs) == 1
        rec = recs[0]
        assert rec["kind"] == "scaling_curve"
        assert [p["devices"] for p in rec["points"]] == [1, 2, 4]
        assert schema.validate_record(rec) == []
        assert scaling.provenance_gaps(rec) == []
        assert rec["env_key"].startswith("env-")
        # the gate accepts its own fresh artifact (shape mechanics)
        cmd, env = _bench_cmd(
            "gate", str(hist), "--min-efficiency", "0.01",
            "--monotone-slack", "10", "--max-serial-fraction", "1.0",
            "--no-refuse-contended")
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120, env=env)
        assert proc.returncode == 0, proc.stdout[-2000:]


class TestReportScalingSection:
    def test_scaling_rollup_and_filter(self, tmp_path, capsys):
        sys.path.insert(0, os.path.join(REPO, "tools"))
        try:
            import agd_report
        finally:
            sys.path.pop(0)
        path = tmp_path / "curves.jsonl"
        rec = make_curve(flag_at=2)
        path.write_text(json.dumps(rec) + "\n"
                        + json.dumps(schema.run_record(
                            tool="t", name="x", final_loss=0.5)) + "\n")
        assert agd_report.main([str(path)]) == 0
        out = capsys.readouterr().out
        assert "== scaling (1 ladder(s)) ==" in out
        assert "CONTENDED" in out and "efficiency" in out
        assert "== runs" in out
        # --scaling prints ONLY the rollup
        assert agd_report.main([str(path), "--scaling"]) == 0
        out = capsys.readouterr().out
        assert "== scaling" in out and "== runs" not in out
        assert "1 CONTENTION-FLAGGED" in out
