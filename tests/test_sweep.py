"""`api.sweep` — K regularization strengths in ONE compiled program.

The contract: a sweep lane must be indistinguishable from an individual
`api.run` at that reg_param (same trajectory, same weights, same
iteration count under a convergence tolerance), because vmap batches the
loop without changing its math and the while_loop batching rule masks
finished lanes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_agd_tpu import api
from spark_agd_tpu.ops import losses, prox, sparse


@pytest.fixture
def problem(rng):
    X = rng.standard_normal((300, 12)).astype(np.float32)
    y = (rng.random(300) < 0.5).astype(np.float32)
    w0 = np.zeros(12, np.float32)
    return X, y, w0


REGS = [0.0, 0.05, 0.5]


class TestSweep:
    def test_lanes_match_individual_runs(self, problem):
        X, y, w0 = problem
        res = api.sweep((X, y), losses.LogisticGradient(),
                        prox.SquaredL2Updater(), REGS,
                        num_iterations=6, convergence_tol=0.0,
                        initial_weights=w0)
        assert res.weights.shape == (3, 12)
        for k, reg in enumerate(REGS):
            w_ref, hist_ref = api.run(
                (X, y), losses.LogisticGradient(),
                prox.SquaredL2Updater(), reg_param=reg,
                num_iterations=6, convergence_tol=0.0,
                initial_weights=w0, mesh=False)
            # atol 2e-5: near-zero weight components pick up absolute
            # f32 drift from the vmapped (N,D)@(D,K) contraction's
            # different reduction order vs the solo matvec (observed
            # 1.0e-5 abs on the 0.4.x CPU toolchain)
            np.testing.assert_allclose(np.asarray(res.weights)[k],
                                       np.asarray(w_ref), rtol=2e-4,
                                       atol=2e-5)
            np.testing.assert_allclose(
                np.asarray(res.loss_history)[k][:len(hist_ref)],
                hist_ref, rtol=2e-4)

    def test_l1_lanes_match(self, problem):
        X, y, w0 = problem
        res = api.sweep((X, y), losses.LogisticGradient(),
                        prox.L1Updater(), [0.01, 0.2],
                        num_iterations=5, convergence_tol=0.0,
                        initial_weights=w0)
        for k, reg in enumerate([0.01, 0.2]):
            w_ref, _ = api.run((X, y), losses.LogisticGradient(),
                               prox.L1Updater(), reg_param=reg,
                               num_iterations=5, convergence_tol=0.0,
                               initial_weights=w0, mesh=False)
            np.testing.assert_allclose(np.asarray(res.weights)[k],
                                       np.asarray(w_ref), rtol=2e-4,
                                       atol=2e-6)
        # stronger L1 ⇒ sparser/smaller weights (the path is real)
        n1 = float(jnp.abs(res.weights[0]).sum())
        n2 = float(jnp.abs(res.weights[1]).sum())
        assert n2 < n1

    def test_per_lane_convergence(self, problem):
        """Lanes stop independently under a tolerance: each lane's
        num_iters must equal its individual run's (the while_loop
        batching rule masks finished lanes)."""
        X, y, w0 = problem
        regs = [0.0, 2.0]  # strong reg converges in fewer iterations
        res = api.sweep((X, y), losses.LogisticGradient(),
                        prox.SquaredL2Updater(), regs,
                        num_iterations=40, convergence_tol=1e-3,
                        initial_weights=w0)
        iters = np.asarray(res.num_iters)
        for k, reg in enumerate(regs):
            _, hist_ref = api.run(
                (X, y), losses.LogisticGradient(),
                prox.SquaredL2Updater(), reg_param=reg,
                num_iterations=40, convergence_tol=1e-3,
                initial_weights=w0, mesh=False)
            assert iters[k] == len(hist_ref), (k, iters, len(hist_ref))
        assert iters[0] != iters[1], "tolerance did not differentiate"

    def test_sparse_sweep(self, rng):
        n, d, npr = 200, 30, 5
        indptr = np.arange(n + 1) * npr
        indices = rng.integers(0, d, n * npr).astype(np.int32)
        values = rng.normal(size=n * npr).astype(np.float32)
        y = (rng.random(n) < 0.5).astype(np.float32)
        X = sparse.CSRMatrix.from_csr_arrays(indptr, indices, values, d,
                                             with_csc=True)
        w0 = np.zeros(d, np.float32)
        res = api.sweep((X, y), losses.LogisticGradient(),
                        prox.SquaredL2Updater(), [0.0, 0.1],
                        num_iterations=4, convergence_tol=0.0,
                        initial_weights=w0)
        for k, reg in enumerate([0.0, 0.1]):
            w_ref, _ = api.run((X, y), losses.LogisticGradient(),
                               prox.SquaredL2Updater(), reg_param=reg,
                               num_iterations=4, convergence_tol=0.0,
                               initial_weights=w0, mesh=False)
            np.testing.assert_allclose(np.asarray(res.weights)[k],
                                       np.asarray(w_ref), rtol=2e-4,
                                       atol=2e-6)

    def test_one_compile_for_all_lanes(self, problem):
        X, y, w0 = problem
        traces = {"n": 0}

        class Counting(losses.LogisticGradient):
            def batch_loss_and_grad(self, wv, Xv, yv, mask=None):
                traces["n"] += 1
                return super().batch_loss_and_grad(wv, Xv, yv, mask)

        api.sweep((X, y), Counting(), prox.SquaredL2Updater(),
                  np.linspace(0.0, 1.0, 7), num_iterations=3,
                  convergence_tol=0.0, initial_weights=w0)
        assert traces["n"] <= 4, (
            f"expected one trace of the smooth per call site, got "
            f"{traces['n']} — the sweep must not compile per lane")

    def test_rejects_bad_inputs(self, problem):
        X, y, w0 = problem
        with pytest.raises(ValueError, match="initial_weights"):
            api.sweep((X, y), losses.LogisticGradient(),
                      prox.SquaredL2Updater(), REGS)
        with pytest.raises(ValueError, match="1-D"):
            api.sweep((X, y), losses.LogisticGradient(),
                      prox.SquaredL2Updater(), [[0.1]],
                      initial_weights=w0)


class TestTrainPath:
    def test_models_match_individual_training(self, problem):
        from spark_agd_tpu.models import LogisticRegressionWithAGD

        X, y, _ = problem
        regs = [0.01, 0.3]

        def make_trainer():
            t = LogisticRegressionWithAGD()
            t.optimizer.set_num_iterations(5).set_convergence_tol(0.0)
            t.optimizer.set_mesh(False)
            return t

        models, res = make_trainer().train_path(X, y, regs)
        assert len(models) == 2
        assert np.asarray(res.num_iters).shape == (2,)
        for k, reg in enumerate(regs):
            t = make_trainer()
            t.optimizer.set_reg_param(reg)
            m_ref = t.train(X, y)
            # data-dependent branches (backtrack accepts / restarts) can
            # flip at 1-ulp boundaries under the batched matmul's
            # reassociation, legitimately moving the iterate path — so
            # gate loosely on weights; exact lane parity on the stable
            # problem is TestSweep.test_lanes_match_individual_runs
            np.testing.assert_allclose(np.asarray(models[k].weights),
                                       np.asarray(m_ref.weights),
                                       rtol=5e-2, atol=5e-3)
            assert abs(models[k].intercept - m_ref.intercept) < 5e-2
        # predictions are usable straight off the path
        preds = models[0].predict(X)
        assert set(np.unique(np.asarray(preds))) <= {0.0, 1.0}

    def test_softmax_path_shapes(self, rng):
        from spark_agd_tpu.models import SoftmaxRegressionWithAGD

        X = rng.standard_normal((120, 9)).astype(np.float32)
        y = rng.integers(0, 4, 120).astype(np.int32)
        t = SoftmaxRegressionWithAGD(4)
        t.optimizer.set_num_iterations(3).set_convergence_tol(0.0)
        t.optimizer.set_mesh(False)
        models, res = t.train_path(X, y, [0.0, 0.1, 1.0])
        assert len(models) == 3
        assert models[0].weights.shape == (9, 4)
        assert models[0].intercept.shape == (4,)
        assert res.weights.shape[0] == 3

    def test_mesh_trainer_path_matches_single_device(self, problem,
                                                     cpu_devices):
        """r2 VERDICT item 2: the trainer's regularization path now
        COMPOSES with a mesh (rows sharded, lanes vmapped inside the
        shard_map) instead of rejecting it."""
        from spark_agd_tpu.models import LogisticRegressionWithAGD
        from spark_agd_tpu.parallel import mesh as mesh_lib

        X, y, _ = problem
        t = LogisticRegressionWithAGD(
            mesh=mesh_lib.make_mesh({"data": 2},
                                    devices=cpu_devices[:2]))
        t.optimizer.set_num_iterations(4).set_convergence_tol(0.0)
        models, res = t.train_path(X, y, [0.0, 0.1])
        t1 = LogisticRegressionWithAGD(mesh=False)
        t1.optimizer.set_num_iterations(4).set_convergence_tol(0.0)
        models1, _ = t1.train_path(X, y, [0.0, 0.1])
        for m, m1 in zip(models, models1):
            np.testing.assert_allclose(np.asarray(m.weights),
                                       np.asarray(m1.weights),
                                       rtol=1e-5, atol=1e-7)

    def test_identity_prox_grid_rejected(self, problem):
        from spark_agd_tpu.models import LinearRegressionWithAGD

        X, y, _ = problem
        t = LinearRegressionWithAGD()  # ctor froze IdentityProx (reg=0)
        t.optimizer.set_mesh(False)
        with pytest.raises(ValueError, match="IdentityProx"):
            t.train_path(X, y.astype(np.float32), [0.0, 0.1])
        # an all-zero grid through the identity prox is legitimate
        models, _ = t.train_path(X, y.astype(np.float32), [0.0])
        assert len(models) == 1


class TestSweepContinuation:
    def test_two_segments_equal_one_run(self, problem):
        """4+4 iterations via sweep_warm_state must equal 8 straight,
        per lane — the checkpoint-segment contract, batched."""
        X, y, w0 = problem
        regs = [0.01, 0.3]
        fit8 = api.make_sweep_runner(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            num_iterations=8, convergence_tol=0.0)
        ref = fit8(w0, regs)

        fit4 = api.make_sweep_runner(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            num_iterations=4, convergence_tol=0.0)
        seg1 = fit4(w0, regs)
        seg2 = fit4(w0, regs, warm=api.sweep_warm_state(seg1))
        np.testing.assert_allclose(np.asarray(seg2.weights),
                                   np.asarray(ref.weights),
                                   rtol=1e-6, atol=1e-8)
        hist = np.concatenate([np.asarray(seg1.loss_history),
                               np.asarray(seg2.loss_history)], axis=1)
        np.testing.assert_allclose(hist, np.asarray(ref.loss_history),
                                   rtol=1e-6)

    def test_three_segments_accumulate_prior_iters(self, problem):
        """Chaining further segments must ACCUMULATE prior iterations
        (the checkpoint driver's cumulative contract): 4+4+4 == 12
        straight, and the third warm carries prior_iters=8."""
        X, y, w0 = problem
        regs = [0.01, 0.3]
        ref = api.make_sweep_runner(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            num_iterations=12, convergence_tol=0.0)(w0, regs)
        fit4 = api.make_sweep_runner(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            num_iterations=4, convergence_tol=0.0)
        seg1 = fit4(w0, regs)
        warm1 = api.sweep_warm_state(seg1)
        seg2 = fit4(w0, regs, warm=warm1)
        warm2 = api.sweep_warm_state(seg2,
                                     prior_iters=warm1.prior_iters)
        np.testing.assert_array_equal(np.asarray(warm2.prior_iters),
                                      [8, 8])
        seg3 = fit4(w0, regs, warm=warm2)
        np.testing.assert_allclose(np.asarray(seg3.weights),
                                   np.asarray(ref.weights),
                                   rtol=1e-6, atol=1e-8)

    def test_warm_preserves_per_lane_state(self, problem):
        """Lanes carry DIFFERENT (theta, L, bts) into the next segment —
        the batched warm must not collapse them."""
        X, y, w0 = problem
        fit = api.make_sweep_runner(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            num_iterations=5, convergence_tol=0.0, l0=1e-3)
        seg1 = fit(w0, [0.0, 1.0])
        warm = api.sweep_warm_state(seg1)
        assert np.asarray(warm.big_l).shape == (2,)  # per-lane scalars
        # the lanes' iterates genuinely diverged (L itself tracks the
        # smooth part and may legitimately agree across strengths)
        assert not np.allclose(np.asarray(warm.x)[0],
                               np.asarray(warm.x)[1])
        seg2 = fit(w0, [0.0, 1.0], warm=warm)
        assert np.all(np.isfinite(np.asarray(seg2.weights)))
