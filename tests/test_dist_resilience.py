"""Distributed resilience (resilience/manifest.py + distributed.py):
barrier-committed multi-host checkpoints, checksummed manifests,
host-loss detection, and elastic resume onto a changed topology — plus
this PR's satellites (supervisor wall-clock deadline, ingest
validation, per-entry checkpoint CRCs).

Single-process tests simulate the SPMD hosts with explicit
``process_index``/``process_count`` and a thread-barrier ``exchange``
(the real allgather path runs in ``tests/test_multihost.py``'s
2-process child and the ``dist_fault``-marked drill test below).
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from spark_agd_tpu.core import agd
from spark_agd_tpu.core.agd import AGDConfig, AGDWarmState
from spark_agd_tpu.data import ingest, libsvm
from spark_agd_tpu.obs import Telemetry, schema
from spark_agd_tpu.parallel import multihost as mh
from spark_agd_tpu.resilience import (
    DistributedCheckpointer,
    HeartbeatWriter,
    HostLost,
    HostMonitor,
    ResiliencePolicy,
    SupervisorGivingUp,
    classify_failure,
    errors,
    faults,
    load_for_topology,
    manifest,
    run_agd_supervised,
)
from spark_agd_tpu.utils import checkpoint as ckpt

pytestmark = pytest.mark.fault


def _warm(prior_iters=3, d=4, seed=0):
    rng = np.random.default_rng(seed)
    cfg = AGDConfig(num_iterations=10)
    w = rng.standard_normal(d).astype(np.float32)
    return AGDWarmState.initial(w, cfg)._replace(
        prior_iters=prior_iters), w


class ThreadExchange:
    """A real (threading.Barrier) stand-in for the allgather barrier:
    N simulated hosts block until all have contributed their row."""

    def __init__(self, n):
        self.n = n
        self._barrier = threading.Barrier(n, timeout=30)
        self._rows = {}

    def for_process(self, p):
        def exchange(row):
            self._rows[p] = np.asarray(row)
            self._barrier.wait()
            out = np.stack([self._rows[i] for i in range(self.n)])
            self._barrier.wait()  # hold rows until everyone copied
            return out

        return exchange


def _two_host_save(tmp_path, warm, hist=(0.5, 0.4), *, keep=3,
                   generations=1, fingerprint=None, telemetry=None,
                   row_len=4):
    """Run a REAL concurrent 2-host barrier commit (threads) for
    ``generations`` saves; returns the checkpointers."""
    ex = ThreadExchange(2)
    cks = [DistributedCheckpointer(
        str(tmp_path), every_iters=1, keep=keep,
        fingerprint=fingerprint, telemetry=telemetry,
        mesh_shape={"data": 2},
        partitions=[f"part-{p}", f"part-{p + 2}"],
        row_state={"rows": np.arange(p * row_len, (p + 1) * row_len)},
        process_index=p, process_count=2,
        exchange=ex.for_process(p)) for p in (0, 1)]

    errs = []

    def run(p):
        try:
            w = warm
            for g in range(generations):
                cks[p]._save(w._replace(prior_iters=int(w.prior_iters)
                                        + g), list(hist), False, False)
        except Exception as e:  # noqa: BLE001 — surfaced to the test
            errs.append(e)

    threads = [threading.Thread(target=run, args=(p,)) for p in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs
    return cks


class TestManifest:
    def test_roundtrip(self, tmp_path):
        m = manifest.Manifest(
            generation=7, process_count=2,
            shards=[manifest.ShardEntry(manifest.shard_name(7, p), p,
                                        123 + p, 456) for p in (0, 1)],
            mesh_shape={"data": 4}, fingerprint="fp",
            prior_iters=12)
        manifest.write_manifest(str(tmp_path), m)
        # HEAD and the per-generation manifest both parse to the same
        head = manifest.load_manifest(str(tmp_path))
        by_gen = manifest.load_manifest(str(tmp_path), 7)
        assert head.generation == by_gen.generation == 7
        assert head.shards == m.shards
        assert head.mesh_shape == {"data": 4}
        assert manifest.committed_generations(str(tmp_path)) == [7]

    def test_verify_catches_missing_torn_and_corrupt(self, tmp_path):
        warm, w0 = _warm()
        _two_host_save(tmp_path, warm)
        m = manifest.load_manifest(str(tmp_path))
        assert manifest.verify_manifest(m, str(tmp_path)) == []
        shard1 = m.shard_path(str(tmp_path), 1)
        faults.truncate_file(shard1, keep_fraction=0.5)
        assert any("torn" in p
                   for p in manifest.verify_manifest(m, str(tmp_path)))
        faults.scramble_file(shard1, seed=3)  # same length, bad bytes
        os.truncate(shard1, m.shards[1].size)
        problems = manifest.verify_manifest(m, str(tmp_path))
        assert any("CRC32" in p for p in problems), problems
        os.unlink(shard1)
        assert any("missing" in p
                   for p in manifest.verify_manifest(m, str(tmp_path)))

    def test_head_fallback_when_head_torn(self, tmp_path):
        warm, w0 = _warm()
        _two_host_save(tmp_path, warm)
        head = os.path.join(str(tmp_path), manifest.HEAD_NAME)
        with open(head, "w") as f:
            f.write("{not json")
        m = manifest.load_manifest(str(tmp_path))
        assert m is not None and m.generation == 0

    def test_gc_keeps_newest_and_spares_inflight(self, tmp_path):
        warm, w0 = _warm()
        _two_host_save(tmp_path, warm, generations=4, keep=2)
        gens = manifest.committed_generations(str(tmp_path))
        assert gens == [3, 2]
        # an orphan shard NEWER than the newest commit (a commit in
        # flight) must survive gc; a dead old orphan must not
        inflight = os.path.join(str(tmp_path), manifest.shard_name(9, 0))
        ckpt.atomic_savez(inflight, {"generation": np.asarray(9)})
        manifest.gc_generations(str(tmp_path), keep=2)
        assert os.path.exists(inflight)


class TestDistributedCheckpointer:
    def test_unchanged_topology_roundtrip_bit_identical(self, tmp_path):
        warm, w0 = _warm(prior_iters=5)
        tel = Telemetry()
        _two_host_save(tmp_path, warm, fingerprint="fp", telemetry=tel)
        for p in (0, 1):
            loaded = load_for_topology(str(tmp_path), w0,
                                       process_index=p, process_count=2,
                                       fingerprint="fp")
            assert loaded is not None and not loaded.elastic
            assert loaded.generation == 0
            assert loaded.saved_process_count == 2
            # bit-identical: the host reads back its own shard's bytes
            for a, b in ((loaded.warm.x, warm.x), (loaded.warm.z, warm.z)):
                np.testing.assert_array_equal(np.asarray(a),
                                              np.asarray(b))
                assert np.asarray(a).dtype == np.asarray(b).dtype
            assert float(loaded.warm.big_l) == float(warm.big_l)
            assert int(loaded.warm.prior_iters) == 5
            assert loaded.partitions == (f"part-{p}", f"part-{p + 2}")
            np.testing.assert_array_equal(loaded.row_state["rows"],
                                          np.arange(p * 4, (p + 1) * 4))

    def test_elastic_2_to_1_gathers_everything(self, tmp_path):
        warm, w0 = _warm()
        tel = Telemetry()
        _two_host_save(tmp_path, warm, fingerprint="fp")
        loaded = load_for_topology(str(tmp_path), w0, process_index=0,
                                   process_count=1, fingerprint="fp",
                                   telemetry=tel)
        assert loaded.elastic and loaded.saved_process_count == 2
        # partitions: sorted union, round-robin for ONE process = all
        assert loaded.partitions == ("part-0", "part-1", "part-2",
                                     "part-3")
        np.testing.assert_array_equal(loaded.row_state["rows"],
                                      np.arange(8))
        np.testing.assert_array_equal(np.asarray(loaded.warm.x),
                                      np.asarray(warm.x))
        recs = [r for r in tel.records
                if r.get("action") == "elastic_resume"]
        assert len(recs) == 1 and recs[0]["saved_process_count"] == 2

    def test_elastic_1_to_2_resplits(self, tmp_path):
        """Growth works too: a 1-process save resumes on 2 processes
        with the partition list and rows re-split per host."""
        warm, w0 = _warm()
        ck = DistributedCheckpointer(
            str(tmp_path), every_iters=1,
            partitions=["part-0", "part-1", "part-2"],
            row_state={"rows": np.arange(6)},
            process_index=0, process_count=1)
        ck._save(warm, [0.5], False, False)
        for p, (parts, rows) in enumerate(
                [(("part-0", "part-2"), np.arange(3)),
                 (("part-1",), np.arange(3, 6))]):
            loaded = load_for_topology(str(tmp_path), w0,
                                       process_index=p, process_count=2)
            assert loaded.elastic
            assert loaded.partitions == parts
            np.testing.assert_array_equal(loaded.row_state["rows"], rows)

    def test_torn_newest_generation_falls_back(self, tmp_path):
        warm, w0 = _warm(prior_iters=2)
        tel = Telemetry()
        _two_host_save(tmp_path, warm, generations=3, telemetry=tel)
        m = manifest.load_manifest(str(tmp_path))
        assert m.generation == 2
        faults.truncate_file(m.shard_path(str(tmp_path), 0),
                             keep_fraction=0.4)
        loaded = load_for_topology(str(tmp_path), w0, process_index=0,
                                   process_count=2, telemetry=tel)
        assert loaded is not None and loaded.generation == 1
        assert int(loaded.warm.prior_iters) == 3  # gen1 = prior + 1
        fb = [r for r in tel.records
              if r.get("action") == "checkpoint_fallback"]
        assert fb and fb[0]["generation"] == 2

    def test_uncommitted_shard_is_invisible(self, tmp_path):
        """The commit-barrier contract: a shard WITHOUT its manifest —
        a host died between shard write and barrier — must not be
        loadable, while the previous committed generation is."""
        warm, w0 = _warm(prior_iters=4)
        _two_host_save(tmp_path, warm)
        orphan = os.path.join(str(tmp_path), manifest.shard_name(1, 0))
        ckpt.atomic_savez(orphan, ckpt.warm_payload(
            warm._replace(prior_iters=99)) | {
                "generation": np.asarray(1),
                "process_index": np.asarray(0),
                "process_count": np.asarray(2)})
        loaded = load_for_topology(str(tmp_path), w0, process_index=0,
                                   process_count=2)
        assert loaded.generation == 0
        assert int(loaded.warm.prior_iters) == 4  # not the orphan's 99

    def test_mixed_generation_commit_refused(self, tmp_path):
        warm, w0 = _warm()
        ex = ThreadExchange(2)
        cks = [DistributedCheckpointer(
            str(tmp_path), every_iters=1, process_index=p,
            process_count=2, exchange=ex.for_process(p))
            for p in (0, 1)]
        cks[1]._next_generation = 5  # host 1 lost lockstep
        errs = {}

        def run(p):
            try:
                cks[p]._save(warm, [0.5], False, False)
            except Exception as e:  # noqa: BLE001
                errs[p] = e

        ts = [threading.Thread(target=run, args=(p,)) for p in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(errs) == 2
        assert all("mixed-generation" in str(e) for e in errs.values())
        # and nothing was committed
        assert manifest.committed_generations(str(tmp_path)) == []

    def test_replica_divergence_refused(self, tmp_path):
        warm, w0 = _warm()
        ex = ThreadExchange(2)
        cks = [DistributedCheckpointer(
            str(tmp_path), every_iters=1, process_index=p,
            process_count=2, exchange=ex.for_process(p))
            for p in (0, 1)]
        warms = [warm, warm._replace(big_l=999.0)]  # host 1 diverged
        errs = {}

        def run(p):
            try:
                cks[p]._save(warms[p], [0.5], False, False)
            except Exception as e:  # noqa: BLE001
                errs[p] = e

        ts = [threading.Thread(target=run, args=(p,)) for p in (0, 1)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(errs) == 2
        assert all("divergence" in str(e) for e in errs.values())

    def test_fingerprint_mismatch_raises_not_falls_back(self, tmp_path):
        warm, w0 = _warm()
        _two_host_save(tmp_path, warm, fingerprint="problem-A")
        with pytest.raises(ValueError, match="different problem"):
            load_for_topology(str(tmp_path), w0, process_index=0,
                              process_count=2,
                              fingerprint="problem-B")

    def test_all_generations_corrupt_returns_none(self, tmp_path):
        warm, w0 = _warm()
        _two_host_save(tmp_path, warm, generations=2)
        for gen in (0, 1):
            m = manifest.load_manifest(str(tmp_path), gen)
            faults.truncate_file(m.shard_path(str(tmp_path), 1),
                                 keep_fraction=0.3)
        assert load_for_topology(str(tmp_path), w0, process_index=0,
                                 process_count=2) is None

    def test_single_process_supervised_resume_matches_plain(
            self, tmp_path):
        """The DistributedCheckpointer drops into the supervisor's
        ``checkpointer=`` seat: on ONE process a kill-free save/resume
        cycle must reproduce the plain supervised run exactly."""
        from spark_agd_tpu.core import smooth as smooth_lib
        from spark_agd_tpu.data import synthetic
        from spark_agd_tpu.ops.losses import LogisticGradient
        from spark_agd_tpu.ops.prox import L2Prox
        import jax.numpy as jnp

        X, y = synthetic.generate_gd_input(2.0, -1.5, 200, 11)
        X = synthetic.with_intercept_column(X).astype(np.float32)
        build, dargs = smooth_lib.make_smooth_staged(
            LogisticGradient(), jnp.asarray(X), jnp.asarray(y))
        px, rv = smooth_lib.make_prox(L2Prox(), 0.1)
        w0 = jnp.zeros(2, jnp.float32)
        cfg = agd.AGDConfig(convergence_tol=0.0, num_iterations=12)
        pol = ResiliencePolicy(max_attempts=2, backoff_base=0.0,
                               jitter=0.0, seed=0, segment_iters=4)
        plain = run_agd_supervised(prox=px, reg_value=rv, w0=w0,
                                   config=cfg, policy=pol,
                                   staged=(build, dargs))
        fp = ckpt.problem_fingerprint(w0, cfg)

        # first launch: run only 8 of 12 iterations, then "die"
        import dataclasses

        ck = DistributedCheckpointer(str(tmp_path), every_iters=4,
                                     fingerprint=fp, process_index=0,
                                     process_count=1)
        run_agd_supervised(
            prox=px, reg_value=rv, w0=w0,
            config=dataclasses.replace(cfg, num_iterations=8),
            policy=pol, staged=(build, dargs), checkpointer=ck)
        # relaunch with the full budget: resumes at 8, finishes at 12
        ck2 = DistributedCheckpointer(str(tmp_path), every_iters=4,
                                      fingerprint=fp, process_index=0,
                                      process_count=1)
        res = run_agd_supervised(prox=px, reg_value=rv, w0=w0,
                                 config=cfg, policy=pol,
                                 staged=(build, dargs),
                                 checkpointer=ck2)
        assert res.resumed_from == 8
        assert res.num_iters == plain.num_iters
        np.testing.assert_array_equal(np.asarray(res.weights),
                                      np.asarray(plain.weights))
        np.testing.assert_allclose(res.loss_history,
                                   plain.loss_history, rtol=0, atol=0)


class TestHeartbeats:
    def test_writer_emits_file_and_record(self, tmp_path):
        tel = Telemetry()
        hb = HeartbeatWriter(str(tmp_path), process_index=1,
                             process_count=2, telemetry=tel)
        hb.beat(iter=7, phase="segment")
        with open(hb.path) as f:
            rec = json.load(f)
        assert rec["process"] == 1 and rec["iter"] == 7
        hbs = [r for r in tel.records if r["kind"] == "heartbeat"]
        assert len(hbs) == 1 and hbs[0]["process"] == 1
        assert hbs[0]["phase"] == "segment"
        assert not schema.validate_record(
            json.loads(json.dumps(hbs[0])))

    def test_monitor_detects_stale_host(self, tmp_path):
        t = [100.0]
        tel = Telemetry()
        hb = HeartbeatWriter(str(tmp_path), process_index=1,
                             process_count=2, clock=lambda: t[0])
        hb.beat(iter=3)
        mon = HostMonitor(str(tmp_path), stale_after_s=5.0,
                          telemetry=tel, clock=lambda: t[0])
        mon.check()  # fresh: no raise
        t[0] += 10.0
        with pytest.raises(HostLost) as ei:
            mon.check()
        assert ei.value.process_index == 1
        assert classify_failure(ei.value) == errors.TRANSIENT
        lost = [r for r in tel.records if r.get("action") == "host_lost"]
        assert len(lost) == 1 and lost[0]["process"] == 1
        # repeated checks raise again but do not re-emit the record
        with pytest.raises(HostLost):
            mon.check()
        assert len([r for r in tel.records
                    if r.get("action") == "host_lost"]) == 1

    def test_unseen_host_is_not_lost(self, tmp_path):
        mon = HostMonitor(str(tmp_path), stale_after_s=0.01,
                          expected=[0, 1])
        assert mon.lost_hosts() == []
        mon.check()

    def test_supervisor_beats_and_monitor_retry(self, tmp_path):
        """Wiring: the supervisor beats at every segment boundary, and a
        HostLost from the monitor is retried as TRANSIENT (the peer came
        back / was replaced) rather than treated FATAL."""
        from spark_agd_tpu.core import smooth as smooth_lib
        from spark_agd_tpu.data import synthetic
        from spark_agd_tpu.ops.losses import LogisticGradient
        from spark_agd_tpu.ops.prox import L2Prox
        import jax.numpy as jnp

        X, y = synthetic.generate_gd_input(2.0, -1.5, 200, 5)
        X = synthetic.with_intercept_column(X).astype(np.float32)
        build, dargs = smooth_lib.make_smooth_staged(
            LogisticGradient(), jnp.asarray(X), jnp.asarray(y))
        px, rv = smooth_lib.make_prox(L2Prox(), 0.1)
        w0 = jnp.zeros(2, jnp.float32)
        cfg = agd.AGDConfig(convergence_tol=0.0, num_iterations=8)
        tel = Telemetry()
        hb = HeartbeatWriter(str(tmp_path), process_index=0,
                             process_count=1, telemetry=tel)

        class OneShotLostMonitor:
            calls = 0

            def check(self):
                self.calls += 1
                if self.calls == 2:  # lost once, at the second segment
                    raise HostLost(1, "peer gone")

        res = run_agd_supervised(
            prox=px, reg_value=rv, w0=w0, config=cfg,
            policy=ResiliencePolicy(max_attempts=3, backoff_base=0.0,
                                    jitter=0.0, seed=0,
                                    segment_iters=4),
            staged=(build, dargs), telemetry=tel, heartbeat=hb,
            monitor=OneShotLostMonitor())
        assert res.num_iters == 8 and res.retries == 1
        beats = [r for r in tel.records if r["kind"] == "heartbeat"]
        assert len(beats) >= 3  # two segments + retry + exit
        assert beats[-1]["phase"] == "exit"
        lost_attempts = [r for r in tel.records
                         if r.get("kind") == "attempt"
                         and r.get("outcome") == "failed"]
        assert lost_attempts and \
            lost_attempts[0]["failure_kind"] == "transient"
        assert "HostLost" in lost_attempts[0]["error"]


class TestSupervisorDeadline:
    """Satellite: ``max_wall_seconds`` turns an endless retry spiral
    into a DEADLINE-tagged SupervisorGivingUp."""

    def _problem(self):
        from spark_agd_tpu.core import smooth as smooth_lib
        from spark_agd_tpu.data import synthetic
        from spark_agd_tpu.ops.losses import LogisticGradient
        from spark_agd_tpu.ops.prox import L2Prox
        import jax.numpy as jnp

        X, y = synthetic.generate_gd_input(2.0, -1.5, 200, 3)
        X = synthetic.with_intercept_column(X).astype(np.float32)
        build, dargs = smooth_lib.make_smooth_staged(
            LogisticGradient(), jnp.asarray(X), jnp.asarray(y))
        px, rv = smooth_lib.make_prox(L2Prox(), 0.1)
        return build, dargs, px, rv, jnp.zeros(2, jnp.float32)

    def test_deadline_raises_with_tagged_ledger(self):
        build, dargs, px, rv, w0 = self._problem()
        cfg = agd.AGDConfig(convergence_tol=0.0, num_iterations=40)
        t = [0.0]

        def clock():
            t[0] += 2.0  # every boundary costs 2 "seconds"
            return t[0]

        tel = Telemetry()
        with pytest.raises(SupervisorGivingUp, match="DEADLINE") as ei:
            run_agd_supervised(
                prox=px, reg_value=rv, w0=w0, config=cfg,
                policy=ResiliencePolicy(
                    max_attempts=3, backoff_base=0.0, jitter=0.0,
                    seed=0, segment_iters=5, max_wall_seconds=5.0),
                staged=(build, dargs), telemetry=tel, clock=clock)
        ledger = ei.value.ledger
        assert ledger and ledger[-1]["outcome"] == "deadline"
        assert ledger[-1]["failure_kind"] == "deadline"
        # the deadline attempt landed on the telemetry stream too,
        # schema-valid
        dl = [r for r in tel.records if r.get("kind") == "attempt"
              and r.get("outcome") == "deadline"]
        assert len(dl) == 1
        assert not schema.validate_record(json.loads(json.dumps(dl[0])))

    def test_no_deadline_when_budget_sufficient(self):
        build, dargs, px, rv, w0 = self._problem()
        cfg = agd.AGDConfig(convergence_tol=0.0, num_iterations=8)
        res = run_agd_supervised(
            prox=px, reg_value=rv, w0=w0, config=cfg,
            policy=ResiliencePolicy(
                max_attempts=3, backoff_base=0.0, jitter=0.0, seed=0,
                segment_iters=4, max_wall_seconds=3600.0),
            staged=(build, dargs))
        assert res.num_iters == 8

    def test_policy_validates_budget(self):
        with pytest.raises(ValueError, match="max_wall_seconds"):
            ResiliencePolicy(max_wall_seconds=0.0)


class TestIngestValidation:
    """Satellite: typed rejection of non-finite/out-of-range data."""

    def _parts(self, tmp_path):
        rng = np.random.default_rng(0)
        X = rng.standard_normal((6, 4)).astype(np.float32)
        y = np.where(rng.random(6) < 0.5, 1.0, -1.0)
        good = str(tmp_path / "good.libsvm")
        libsvm.save_libsvm(good, X, y)
        bad = str(tmp_path / "bad.libsvm")
        with open(good) as f:
            lines = f.read().splitlines()
        lines[1] = "1 2:nan 3:0.5"        # non-finite feature
        lines[3] = "nan 1:0.25"           # non-finite label
        lines[4] = "-1 9:1.5"             # index 9 > n_features=4
        with open(bad, "w") as f:
            f.write("\n".join(lines) + "\n")
        return good, bad

    def test_load_libsvm_validate_raises(self, tmp_path):
        good, bad = self._parts(tmp_path)
        libsvm.load_libsvm(good, n_features=4, validate=True)  # clean
        with pytest.raises(libsvm.DataValidationError) as ei:
            libsvm.load_libsvm(bad, n_features=4, validate=True)
        msg = str(ei.value)
        assert "non-finite" in msg
        assert classify_failure(ei.value) == errors.FATAL

    def test_default_is_permissive(self, tmp_path):
        _, bad = self._parts(tmp_path)
        data = libsvm.load_libsvm(bad, n_features=9)
        assert data.n_rows == 6  # historical behavior: reads garbage

    def test_ingest_raise_mode(self, tmp_path, cpu_devices):
        good, bad = self._parts(tmp_path)
        with pytest.raises(libsvm.DataValidationError):
            ingest.from_partitioned_files([good, bad], n_features=4,
                                          validate="raise")

    def test_ingest_drop_mode_counts(self, tmp_path, cpu_devices):
        good, bad = self._parts(tmp_path)
        tel = Telemetry()
        batch = ingest.from_partitioned_files(
            [good, bad], n_features=4, validate="drop", telemetry=tel)
        # 12 rows total, 3 invalid dropped
        assert int(np.asarray(batch.mask).sum()) == 9
        assert tel.registry.counter("data.invalid_records").value == 3
        assert np.isfinite(np.asarray(batch.X)).all()
        assert np.isfinite(np.asarray(batch.y)).all()

    def test_ingest_csr_drop_mode(self, tmp_path, cpu_devices):
        good, bad = self._parts(tmp_path)
        tel = Telemetry()
        batch = ingest.from_partitioned_files_csr(
            [good, bad], n_features=4, validate="drop", telemetry=tel)
        assert int(np.asarray(batch.mask).sum()) == 9
        assert tel.registry.counter("data.invalid_records").value == 3

    def test_ingest_rejects_unknown_mode(self, tmp_path, cpu_devices):
        good, _ = self._parts(tmp_path)
        with pytest.raises(ValueError, match="validate"):
            ingest.from_partitioned_files([good], n_features=4,
                                          validate="maybe")

    def test_drop_rows_repacks_csr(self):
        data = libsvm.CSRData(
            labels=np.array([1.0, np.nan, 0.0]),
            indptr=np.array([0, 2, 3, 5]),
            indices=np.array([0, 2, 1, 0, 3], np.int32),
            values=np.array([1.0, 2.0, 3.0, 4.0, 5.0], np.float32),
            n_features=4)
        mask = libsvm.invalid_row_mask(data)
        np.testing.assert_array_equal(mask, [False, True, False])
        out = libsvm.drop_rows(data, mask)
        assert out.n_rows == 2
        np.testing.assert_array_equal(out.indptr, [0, 2, 4])
        np.testing.assert_array_equal(out.values, [1, 2, 4, 5])


class TestEntryChecksums:
    """Satellite: per-entry CRC32 inside every npz — silent bit-flips
    raise CheckpointCorruptError, not just unparseable zips."""

    def test_roundtrip_carries_and_verifies_crcs(self, tmp_path):
        warm, w0 = _warm(prior_iters=2)
        path = str(tmp_path / "c.npz")
        ckpt.save_checkpoint(path, warm, [0.5, 0.4], fingerprint="fp")
        with np.load(path) as data:
            assert ckpt.CRC_ENTRY in data.files
        entries = ckpt.read_npz_entries(path)
        assert ckpt.CRC_ENTRY not in entries  # stripped after verify
        loaded = ckpt.load_checkpoint(path, w0)
        assert int(loaded.warm.prior_iters) == 2

    def test_silent_bit_flip_detected(self, tmp_path):
        """Rewrite the npz with one entry's VALUES changed but the OLD
        crc map kept — a zip-consistent archive whose payload lies
        (what a bad sector or a buggy rewriting tool produces)."""
        warm, w0 = _warm(prior_iters=2)
        path = str(tmp_path / "c.npz")
        ckpt.save_checkpoint(path, warm, [0.5, 0.4])
        with np.load(path) as data:
            entries = {k: np.asarray(data[k]) for k in data.files}
        entries["big_l"] = np.asarray(12345.0)  # flipped payload
        with open(path, "wb") as f:
            np.savez(f, **entries)  # old __crc32__ map rides along
        with pytest.raises(ckpt.CheckpointCorruptError,
                           match="CRC32"):
            ckpt.read_npz_entries(path)
        with pytest.raises(ckpt.CheckpointCorruptError):
            ckpt.load_checkpoint(path, w0, fallback_to_bak=False)

    def test_bit_flip_falls_back_to_bak(self, tmp_path):
        warm, w0 = _warm(prior_iters=2)
        path = str(tmp_path / "c.npz")
        ckpt.save_checkpoint(path + ".bak", warm, [0.5])
        ckpt.save_checkpoint(path, warm._replace(prior_iters=7),
                             [0.5, 0.4])
        with np.load(path) as data:
            entries = {k: np.asarray(data[k]) for k in data.files}
        entries["theta"] = np.asarray(-1.0)
        with open(path, "wb") as f:
            np.savez(f, **entries)
        loaded = ckpt.load_checkpoint(path, w0)  # falls back
        assert int(loaded.warm.prior_iters) == 2

    def test_legacy_file_without_crcs_loads(self, tmp_path):
        warm, w0 = _warm(prior_iters=3)
        path = str(tmp_path / "legacy.npz")
        payload = ckpt.warm_payload(warm, [0.5])
        with open(path, "wb") as f:
            np.savez(f, **payload)  # no __crc32__ entry
        loaded = ckpt.load_checkpoint(path, w0)
        assert int(loaded.warm.prior_iters) == 3


@pytest.mark.dist_fault
class TestDistFaultDrill:
    """The 2-process SIGKILL + elastic-resume drill as a gate: real
    separate interpreters, real gloo collectives, real host death."""

    def test_drill_passes(self, tmp_path):
        tool = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "dist_fault_drill.py")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PYTHONPATH"] = os.pathsep.join(
            [os.path.dirname(os.path.dirname(tool))] +
            env.get("PYTHONPATH", "").split(os.pathsep))
        proc = subprocess.run(
            [sys.executable, tool, "--out", str(tmp_path / "drill")],
            capture_output=True, text=True, timeout=420, env=env)
        assert proc.returncode == 0, \
            f"drill failed:\n{proc.stdout[-3000:]}\n{proc.stderr[-2000:]}"
        assert "DIST FAULT DRILL PASSED" in proc.stdout
