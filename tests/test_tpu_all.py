"""The single-claim artifact driver (tpu_all.py).

Runs the configs stage end-to-end on the CPU mesh (TPU_ALL_ALLOW_CPU)
and pins the artifact contract the judge-facing files depend on: one
truncated JSON-lines file, records for every (dtype, pallas) variant,
ride-along passes skipping the redundant GD oracle, and a non-zero exit
on garbage input BEFORE any stage runs.
"""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import tpu_all  # noqa: E402


@pytest.fixture()
def cpu_ok(monkeypatch):
    monkeypatch.setenv("TPU_ALL_ALLOW_CPU", "1")


def test_configs_stage_artifact_contract(cpu_ok, tmp_path, monkeypatch,
                                         cpu_devices):
    monkeypatch.chdir(tmp_path)
    rc = tpu_all.main(["--tag", "t", "--skip-bench", "--skip-checks",
                       "--configs", "5,", "--config-iters", "2",
                       "--config-dtypes", "f32"])
    assert rc == 0
    recs = [json.loads(l)
            for l in open(tmp_path / "BENCH_CONFIGS_t.json")]
    assert [r["dtype"] for r in recs] == ["f32"]
    assert all(r["config"] == 5 for r in recs)
    assert all("error" not in r for r in recs)
    # rerun truncates rather than accumulating stale records
    rc = tpu_all.main(["--tag", "t", "--skip-bench", "--skip-checks",
                       "--configs", "5", "--config-iters", "2",
                       "--config-dtypes", "f32"])
    assert rc == 0
    recs2 = [json.loads(l)
             for l in open(tmp_path / "BENCH_CONFIGS_t.json")]
    assert len(recs2) == len(recs)


def test_pallas_ride_along_skips_oracle(cpu_ok, tmp_path, monkeypatch,
                                        cpu_devices):
    monkeypatch.chdir(tmp_path)
    rc = tpu_all.main(["--tag", "t2", "--skip-bench", "--skip-checks",
                       "--configs", "2", "--config-iters", "2",
                       "--gd-cap", "4", "--config-dtypes", "f32"])
    assert rc == 0
    recs = [json.loads(l)
            for l in open(tmp_path / "BENCH_CONFIGS_t2.json")]
    assert [(r["dtype"], r["pallas"]) for r in recs] == [
        ("f32", False), ("f32", True)]
    assert recs[0]["agd_vs_gd_iters"] is not None
    assert recs[1]["agd_vs_gd_iters"] is None  # oracle skipped


@pytest.fixture()
def small_ladder(tmp_path, monkeypatch):
    """Shrink the bench ladder to test shapes: tiny rows/iters via the
    BENCH_* env knobs, a fresh bench module so they take effect, and a
    small probe RNG shape."""
    monkeypatch.chdir(tmp_path)
    for k, v in {"BENCH_ROWS": "1024", "BENCH_FEATURES": "16",
                 "BENCH_ITERS_TPU": "2", "BENCH_ITERS_CPU": "2",
                 "BENCH_ITERS_HOST": "2",
                 "BENCH_PARITY_ITERS": "2"}.items():
        monkeypatch.setenv(k, v)
    # drop the module-cached bench so the env shapes take effect
    monkeypatch.delitem(sys.modules, "bench", raising=False)
    monkeypatch.setattr(tpu_all, "PROBE_RNG_SHAPE", (256, 64))


def test_bench_stage_runs_shared_ladder(cpu_ok, small_ladder,
                                        cpu_devices):
    """The bench stage delegates to bench.run_ladder with this driver's
    probe hooks and banks the best record into the cycle artifact."""
    rc = tpu_all.main(["--tag", "lb", "--skip-checks", "--skip-configs"])
    assert rc == 0
    rec = json.loads(open("BENCH_MANUAL_lb.json").read())
    assert rec["unit"] == "iters/sec"
    assert rec["value"] > 0
    assert rec["bench_driver"] in ("fused", "host")
    assert "ladder" in rec
    # rehearsal backend is the CPU mesh; a real claim writes tpu here
    assert rec["platform"] == "cpu"
    tpu_all._WD["deadline"] = None


def test_wedge_capable_probes_run_after_bench_banks(cpu_ok,
                                                    small_ladder,
                                                    capsys,
                                                    cpu_devices):
    """r3 lesson at the probe level: the fused-small and H2D probes can
    themselves wedge a healthy claim, so they must run only AFTER the
    bench ladder has banked real records."""
    rc = tpu_all.main(["--tag", "lo", "--skip-checks", "--skip-configs"])
    assert rc == 0
    stages = [json.loads(ln)["stage"] for ln in
              capsys.readouterr().out.splitlines()
              if ln.strip().startswith("{") and "stage" in ln]
    assert stages.index("bench done") < stages.index("fused-small-trace")
    assert stages.index("fused-small-trace") < stages.index("h2d-1mib")
    tpu_all._WD["deadline"] = None


def test_garbage_configs_fail_before_stages(cpu_ok):
    with pytest.raises(SystemExit) as exc:
        tpu_all.main(["--configs", "1,oops"])
    assert exc.value.code == 2


class TestArtifactReuse:
    """--reuse-artifacts: partial claim windows accumulate across
    cycles instead of re-running finished on-chip work."""

    def test_artifact_ok_accepts_healthy_tpu_record(self, tmp_path,
                                                    monkeypatch):
        monkeypatch.chdir(tmp_path)
        with open("a.json", "w") as f:
            f.write(json.dumps({"value": 1.0, "platform": "tpu",
                                "error": None}) + "\n")
        assert tpu_all.artifact_ok("a.json")

    def test_artifact_ok_rejects_cpu_error_and_failed(self, tmp_path,
                                                      monkeypatch):
        monkeypatch.chdir(tmp_path)
        cases = [
            {"platform": "cpu", "value": 1.0},          # wrong backend
            {"platform": "tpu", "error": "degraded"},   # errored
            {"check": "x", "ok": False},                # failed check
        ]
        for i, rec in enumerate(cases):
            with open(f"c{i}.json", "w") as f:
                f.write(json.dumps(rec) + "\n")
            assert not tpu_all.artifact_ok(f"c{i}.json"), rec
        assert not tpu_all.artifact_ok("missing.json")
        with open("short.json", "w") as f:
            f.write(json.dumps({"check": "env", "ok": True,
                                "platform": "tpu"}) + "\n")
        assert not tpu_all.artifact_ok("short.json", min_rows=2)

    def test_configs_done_requires_all_dtypes(self, tmp_path,
                                              monkeypatch):
        monkeypatch.chdir(tmp_path)
        rows = [
            {"config": 1, "dtype": "f32", "platform": "tpu"},
            {"config": 1, "dtype": "bf16", "platform": "tpu"},
            {"config": 2, "dtype": "f32", "platform": "tpu"},
            {"config": 3, "dtype": "f32", "platform": "tpu",
             "error": "boom"},
            {"config": 3, "dtype": "bf16", "platform": "tpu"},
            {"config": 4, "dtype": "f32", "platform": "cpu"},
            {"config": 4, "dtype": "bf16", "platform": "tpu"},
        ]
        with open("cfg.json", "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        done = tpu_all.configs_done("cfg.json", ["f32", "bf16"])
        # 1: both dtypes healthy; 2: missing bf16; 3: errored f32;
        # 4: f32 measured on the wrong backend
        assert done == {1}
        assert tpu_all.configs_done("missing.json", ["f32"]) == set()


class TestNegativeControls:
    """Mutation-style controls for the driver-facing parity harnesses
    (VERDICT r4 item 6): prove the asserts can FIRE — a harness that
    only ever sees correct code proves nothing."""

    def test_graft_assert_parity_fires(self, cpu_ok):
        """__graft_entry__._assert_parity must trip on each divergence
        kind the dryrun guards: trajectory skew, weight skew, and a
        sharded-control-flow length mismatch."""
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        import __graft_entry__ as graft

        hist = [0.9, 0.5, 0.3]
        w = [1.0, 2.0]
        graft._assert_parity("ok", hist, list(hist), w, list(w))
        with pytest.raises(AssertionError):
            graft._assert_parity("traj", [0.9, 0.5, 0.31], hist)
        with pytest.raises(AssertionError):
            graft._assert_parity("wts", hist, list(hist), [1.0, 2.01], w)
        with pytest.raises(AssertionError):
            graft._assert_parity("len", hist[:2], hist)

    def test_bench_parity_gate_fires_on_divergence(self, cpu_ok):
        """bench.check_parity (the fused rung's banked-record gate) must
        reject a skewed oracle trajectory — and accept the true one."""
        import importlib.util

        import jax.numpy as jnp
        import numpy as np

        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        spec = importlib.util.spec_from_file_location(
            "bench_negctl", os.path.join(repo, "bench.py"))
        bench = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(bench)

        rng = np.random.default_rng(5)
        Xd = jnp.asarray(rng.standard_normal((256, 16)), jnp.float32)
        yd = jnp.asarray((rng.random(256) < 0.5), jnp.float32)
        w0 = jnp.zeros(16, jnp.float32)
        k = 3
        bench.PARITY_ITERS = k
        step = bench._make_step(
            __import__("spark_agd_tpu.ops.losses",
                       fromlist=["LogisticGradient"]).LogisticGradient(),
            Xd, yd, k)
        true_hist = np.asarray(step(w0).loss_history)[:k]
        bench.check_parity(Xd, yd, w0, true_hist)  # must accept
        with pytest.raises(AssertionError):
            bench.check_parity(Xd, yd, w0, true_hist * 1.05)
