"""Parity tests for the fused Pallas logistic kernel (interpreter mode on
CPU — same kernel code the TPU compiles; ops/pallas_kernels.py)."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_agd_tpu.ops.losses import LogisticGradient
from spark_agd_tpu.ops.pallas_kernels import (
    PallasLogisticGradient,
    fused_logistic_loss_grad,
)


@pytest.fixture(scope="module")
def data(  ):
    rng = np.random.default_rng(11)
    n, d = 700, 130  # deliberately unaligned: pads to 1024 x 256
    X = rng.standard_normal((n, d)).astype(np.float32)
    w = (rng.standard_normal(d) / np.sqrt(d)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    return jnp.asarray(X), jnp.asarray(w), jnp.asarray(y)


class TestFusedLogistic:
    def test_matches_jnp_kernel(self, data):
        X, w, y = data
        ref_loss, ref_grad, ref_n = LogisticGradient().batch_loss_and_grad(
            w, X, y)
        loss, grad = fused_logistic_loss_grad(w, X, y, interpret=True)
        assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad),
                                   rtol=1e-4, atol=1e-4)

    def test_mask_parity(self, data):
        X, w, y = data
        rng = np.random.default_rng(3)
        mask = jnp.asarray((rng.random(X.shape[0]) < 0.7).astype(np.float32))
        ref_loss, ref_grad, ref_n = LogisticGradient().batch_loss_and_grad(
            w, X, y, mask)
        g = PallasLogisticGradient(interpret=True)
        loss, grad, n = g.batch_loss_and_grad(w, X, y, mask)
        assert int(n) == int(ref_n)
        assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad),
                                   rtol=1e-4, atol=1e-4)

    def test_bf16_input(self, data):
        X, w, y = data
        loss, grad = fused_logistic_loss_grad(
            w, X.astype(jnp.bfloat16), y, interpret=True)
        ref_loss, ref_grad, _ = LogisticGradient().batch_loss_and_grad(
            w, X, y)
        # bf16 mantissa: coarse but structurally right
        assert float(loss) == pytest.approx(float(ref_loss), rel=0.05)
        cos = float(np.dot(np.asarray(grad), np.asarray(ref_grad)) /
                    (np.linalg.norm(grad) * np.linalg.norm(ref_grad)))
        assert cos > 0.99

    def test_aligned_shapes_no_padding(self):
        rng = np.random.default_rng(5)
        X = jnp.asarray(rng.standard_normal((1024, 256)), jnp.float32)
        w = jnp.asarray(rng.standard_normal(256) / 16, jnp.float32)
        y = jnp.asarray((rng.random(1024) < 0.5), jnp.float32)
        ref = LogisticGradient().batch_loss_and_grad(w, X, y)
        loss, grad = fused_logistic_loss_grad(w, X, y, interpret=True)
        assert float(loss) == pytest.approx(float(ref[0]), rel=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref[1]),
                                   rtol=1e-4, atol=1e-4)

    def test_full_agd_run_with_pallas_gradient(self, data):
        from spark_agd_tpu import api
        from spark_agd_tpu.ops.prox import L2Prox

        X, w, y = data
        w0 = np.zeros(X.shape[1], np.float32)
        ref_w, ref_hist = api.run(
            (X, y), LogisticGradient(), L2Prox(), num_iterations=5,
            reg_param=0.1, initial_weights=w0, mesh=False)
        pal_w, pal_hist = api.run(
            (X, y), PallasLogisticGradient(interpret=True), L2Prox(),
            num_iterations=5, reg_param=0.1, initial_weights=w0, mesh=False)
        np.testing.assert_allclose(pal_hist, ref_hist, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(pal_w), np.asarray(ref_w),
                                   rtol=1e-3, atol=1e-5)

    def test_csr_falls_back(self, data):
        from spark_agd_tpu.ops import sparse

        X, w, y = data
        n = X.shape[0]
        indptr = np.arange(n + 1)
        Xs = sparse.CSRMatrix.from_csr_arrays(
            indptr, np.zeros(n, np.int32),
            np.asarray(X[:, 0]), X.shape[1])
        g = PallasLogisticGradient(interpret=True)
        loss, grad, cnt = g.batch_loss_and_grad(w, Xs, y)
        ref = LogisticGradient().batch_loss_and_grad(w, Xs, y)
        assert float(loss) == pytest.approx(float(ref[0]), rel=1e-6)
