"""Parity tests for the fused Pallas margin kernel (interpreter mode on
CPU — same kernel code the TPU compiles; ops/pallas_kernels.py).
Compiled-mode checks at rcv1 width need the real chip: tpu_checks.py."""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_agd_tpu.ops.losses import (
    HingeGradient,
    LeastSquaresGradient,
    LogisticGradient,
    SoftmaxGradient,
)
from spark_agd_tpu.ops.pallas_kernels import (
    PaddedDense,
    PallasLogisticGradient,
    PallasMarginGradient,
    choose_block_rows,
    fused_logistic_loss_grad,
    fused_margin_loss_grad,
    pad_dense,
)


@pytest.fixture(scope="module")
def data(  ):
    rng = np.random.default_rng(11)
    n, d = 700, 130  # deliberately unaligned: pads to 1024 x 256
    X = rng.standard_normal((n, d)).astype(np.float32)
    w = (rng.standard_normal(d) / np.sqrt(d)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    return jnp.asarray(X), jnp.asarray(w), jnp.asarray(y)


class TestFusedLogistic:
    def test_matches_jnp_kernel(self, data):
        X, w, y = data
        ref_loss, ref_grad, ref_n = LogisticGradient().batch_loss_and_grad(
            w, X, y)
        loss, grad = fused_logistic_loss_grad(w, X, y, interpret=True)
        assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad),
                                   rtol=1e-4, atol=1e-4)

    def test_mask_parity(self, data):
        X, w, y = data
        rng = np.random.default_rng(3)
        mask = jnp.asarray((rng.random(X.shape[0]) < 0.7).astype(np.float32))
        ref_loss, ref_grad, ref_n = LogisticGradient().batch_loss_and_grad(
            w, X, y, mask)
        g = PallasLogisticGradient(interpret=True)
        loss, grad, n = g.batch_loss_and_grad(w, X, y, mask)
        assert int(n) == int(ref_n)
        assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad),
                                   rtol=1e-4, atol=1e-4)

    def test_bf16_input(self, data):
        X, w, y = data
        loss, grad = fused_logistic_loss_grad(
            w, X.astype(jnp.bfloat16), y, interpret=True)
        ref_loss, ref_grad, _ = LogisticGradient().batch_loss_and_grad(
            w, X, y)
        # bf16 mantissa: coarse but structurally right
        assert float(loss) == pytest.approx(float(ref_loss), rel=0.05)
        cos = float(np.dot(np.asarray(grad), np.asarray(ref_grad)) /
                    (np.linalg.norm(grad) * np.linalg.norm(ref_grad)))
        assert cos > 0.99

    def test_aligned_shapes_no_padding(self):
        rng = np.random.default_rng(5)
        X = jnp.asarray(rng.standard_normal((1024, 256)), jnp.float32)
        w = jnp.asarray(rng.standard_normal(256) / 16, jnp.float32)
        y = jnp.asarray((rng.random(1024) < 0.5), jnp.float32)
        ref = LogisticGradient().batch_loss_and_grad(w, X, y)
        loss, grad = fused_logistic_loss_grad(w, X, y, interpret=True)
        assert float(loss) == pytest.approx(float(ref[0]), rel=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref[1]),
                                   rtol=1e-4, atol=1e-4)

    def test_full_agd_run_with_pallas_gradient(self, data):
        from spark_agd_tpu import api
        from spark_agd_tpu.ops.prox import L2Prox

        X, w, y = data
        w0 = np.zeros(X.shape[1], np.float32)
        ref_w, ref_hist = api.run(
            (X, y), LogisticGradient(), L2Prox(), num_iterations=5,
            reg_param=0.1, initial_weights=w0, mesh=False)
        pal_w, pal_hist = api.run(
            (X, y), PallasLogisticGradient(interpret=True), L2Prox(),
            num_iterations=5, reg_param=0.1, initial_weights=w0, mesh=False)
        np.testing.assert_allclose(pal_hist, ref_hist, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(pal_w), np.asarray(ref_w),
                                   rtol=1e-3, atol=1e-5)

    def test_csr_falls_back(self, data):
        from spark_agd_tpu.ops import sparse

        X, w, y = data
        n = X.shape[0]
        indptr = np.arange(n + 1)
        Xs = sparse.CSRMatrix.from_csr_arrays(
            indptr, np.zeros(n, np.int32),
            np.asarray(X[:, 0]), X.shape[1])
        g = PallasLogisticGradient(interpret=True)
        loss, grad, cnt = g.batch_loss_and_grad(w, Xs, y)
        ref = LogisticGradient().batch_loss_and_grad(w, Xs, y)
        assert float(loss) == pytest.approx(float(ref[0]), rel=1e-6)


class TestMarginGeneralKernel:
    """The margin-form seam: one kernel, every GLM loss (VERDICT r1 #4)."""

    @pytest.mark.parametrize("grad_cls", [LogisticGradient,
                                          LeastSquaresGradient,
                                          HingeGradient])
    def test_matches_jnp_kernel(self, data, grad_cls):
        X, w, y = data
        inner = grad_cls()
        ref_loss, ref_grad, ref_n = inner.batch_loss_and_grad(w, X, y)
        padded = pad_dense(X, y)
        loss, grad = fused_margin_loss_grad(inner, w, padded,
                                            interpret=True)
        assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_grad),
                                   rtol=1e-4, atol=1e-4)

    @pytest.mark.parametrize("grad_cls", [LeastSquaresGradient,
                                          HingeGradient])
    def test_full_agd_parity(self, data, grad_cls):
        from spark_agd_tpu import api
        from spark_agd_tpu.ops.prox import L2Prox

        X, w, y = data
        w0 = np.zeros(X.shape[1], np.float32)
        ref_w, ref_hist = api.run(
            (X, y), grad_cls(), L2Prox(), num_iterations=5,
            reg_param=0.1, initial_weights=w0, mesh=False,
            convergence_tol=0.0)
        pal_w, pal_hist = api.run(
            (X, y), PallasMarginGradient(grad_cls(), interpret=True),
            L2Prox(), num_iterations=5, reg_param=0.1,
            initial_weights=w0, mesh=False, convergence_tol=0.0)
        np.testing.assert_allclose(pal_hist, ref_hist, rtol=1e-4)
        np.testing.assert_allclose(np.asarray(pal_w), np.asarray(ref_w),
                                   rtol=1e-3, atol=1e-5)

    def test_rejects_non_margin_loss(self):
        with pytest.raises(TypeError, match="MarginGradient"):
            PallasMarginGradient(SoftmaxGradient(10))

    def test_is_a_margin_gradient(self, data):
        """Margin-seam consumers (feature_sharded's isinstance gate) must
        accept the wrapper, like the round-1 subclass did."""
        from spark_agd_tpu.ops.losses import MarginGradient

        g = PallasLogisticGradient(interpret=True)
        assert isinstance(g, MarginGradient)
        X, w, y = data
        dots = X @ w
        ref = LogisticGradient().dots_loss_and_mult(dots, y)
        out = g.dots_loss_and_mult(dots, y)
        np.testing.assert_allclose(np.asarray(out[0]), np.asarray(ref[0]))
        np.testing.assert_allclose(np.asarray(out[1]), np.asarray(ref[1]))


class TestAdaptiveBlocks:
    """VMEM-budgeted row blocks: the width ceiling is now adaptive, not a
    hard ~4k-feature crash (VERDICT r1 weak #2)."""

    def test_block_rows_shrink_with_width(self):
        assert choose_block_rows(512, 4) == 512  # narrow: capped
        br_47k = choose_block_rows(47104, 4)  # rcv1 width, f32
        assert br_47k >= 8 and br_47k % 8 == 0
        assert choose_block_rows(47104, 2) >= 2 * br_47k - 8  # bf16
        assert choose_block_rows(4 * 10**6, 4) == 0  # beyond ceiling

    def test_wide_parity_small_budget(self):
        """Force tiny blocks via explicit block_rows to exercise the
        multi-block accumulation path the 47k width uses on hardware."""
        rng = np.random.default_rng(7)
        n, d = 96, 640
        X = jnp.asarray(rng.standard_normal((n, d)), jnp.float32)
        w = jnp.asarray(rng.standard_normal(d) / 25, jnp.float32)
        y = jnp.asarray((rng.random(n) < 0.5), jnp.float32)
        ref = LogisticGradient().batch_loss_and_grad(w, X, y)
        padded = pad_dense(X, y, block_rows=8)
        loss, grad = fused_margin_loss_grad(
            LogisticGradient(), w, padded, interpret=True, block_rows=8)
        assert float(loss) == pytest.approx(float(ref[0]), rel=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref[1]),
                                   rtol=1e-4, atol=1e-4)

    def test_overwide_falls_back_to_inner(self, data, monkeypatch):
        """Past the VMEM ceiling the wrapper must route to the XLA path
        rather than crash."""
        X, w, y = data
        g = PallasMarginGradient(LogisticGradient(), interpret=True)
        monkeypatch.setattr(
            "spark_agd_tpu.ops.pallas_kernels.choose_block_rows",
            lambda *a, **k: 0)
        Xp, yp, mp = g.prepare(X, y)
        assert not isinstance(Xp, PaddedDense)  # fell back
        loss, grad, n = g.batch_loss_and_grad(w, X, y)
        ref = LogisticGradient().batch_loss_and_grad(w, X, y)
        assert float(loss) == pytest.approx(float(ref[0]), rel=1e-6)


class TestPrepare:
    """One-time staging (ADVICE r1: no per-call re-padding)."""

    def test_smooth_factory_uses_padded_layout(self, data):
        from spark_agd_tpu.core import smooth as smooth_lib

        X, w, y = data
        g = PallasLogisticGradient(interpret=True)
        Xp, yp, mp = g.prepare(X, y)
        assert isinstance(Xp, PaddedDense) and yp is None and mp is None
        assert Xp.X.shape[0] % 8 == 0 and Xp.X.shape[1] % 128 == 0
        assert int(Xp.n_valid) == X.shape[0]
        sm = smooth_lib.make_smooth(g, X, y)
        loss, grad = sm(w)
        ref = LogisticGradient().mean_loss_and_grad(w, X, y)
        assert float(loss) == pytest.approx(float(ref[0]), rel=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref[1]),
                                   rtol=1e-4, atol=1e-4)

    def test_prepared_mask_composes(self, data):
        X, w, y = data
        rng = np.random.default_rng(9)
        mask = jnp.asarray((rng.random(X.shape[0]) < 0.6), jnp.float32)
        g = PallasLogisticGradient(interpret=True)
        Xp, _, _ = g.prepare(X, y, mask)
        loss, grad, n = g.batch_loss_and_grad(w, Xp, None, None)
        ref = LogisticGradient().batch_loss_and_grad(w, X, y, mask)
        assert int(n) == int(ref[2])
        assert float(loss) == pytest.approx(float(ref[0]), rel=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref[1]),
                                   rtol=1e-4, atol=1e-4)

    def test_prepare_is_identity_for_csr_and_tracers(self, data):
        import jax

        from spark_agd_tpu.ops import sparse

        X, w, y = data
        g = PallasLogisticGradient(interpret=True)
        n = X.shape[0]
        Xs = sparse.CSRMatrix.from_csr_arrays(
            np.arange(n + 1), np.zeros(n, np.int32),
            np.asarray(X[:, 0]), X.shape[1])
        assert g.prepare(Xs, y)[0] is Xs

        def traced(Xt):
            Xp, _, _ = g.prepare(Xt, y)
            assert isinstance(Xp, jax.core.Tracer)  # no eager staging
            return jnp.sum(Xt)

        jax.jit(traced)(X)


class TestFusedSoftmax:
    """The fused softmax kernel (BASELINE config 4's dense path)."""

    @pytest.fixture(scope="class")
    def sm_data(self):
        rng = np.random.default_rng(21)
        n, d, k = 700, 130, 10  # unaligned: pads to (rows, 256) x Kp=128
        X = rng.standard_normal((n, d)).astype(np.float32)
        W = (rng.standard_normal((d, k)) / np.sqrt(d)).astype(np.float32)
        y = rng.integers(0, k, n).astype(np.float32)
        return jnp.asarray(X), jnp.asarray(W), jnp.asarray(y), k

    def test_matches_jnp_kernel(self, sm_data):
        from spark_agd_tpu.ops.pallas_kernels import (
            PallasSoftmaxGradient, choose_block_rows_softmax, pad_dense)

        X, W, y, k = sm_data
        ref_l, ref_g, ref_n = SoftmaxGradient(k).batch_loss_and_grad(
            W, X, y)
        g = PallasSoftmaxGradient(SoftmaxGradient(k), interpret=True)
        Xp, yp, mp = g.prepare(X, y)
        loss, grad, n = g.batch_loss_and_grad(W, Xp, yp, mp)
        assert int(n) == int(ref_n)
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_g),
                                   rtol=1e-4, atol=1e-5)

    def test_masked_rows_excluded(self, sm_data):
        from spark_agd_tpu.ops.pallas_kernels import PallasSoftmaxGradient

        X, W, y, k = sm_data
        rng = np.random.default_rng(5)
        mask = (rng.random(X.shape[0]) < 0.7).astype(np.float32)
        ref_l, ref_g, ref_n = SoftmaxGradient(k).batch_loss_and_grad(
            W, X, y, mask)
        g = PallasSoftmaxGradient(SoftmaxGradient(k), interpret=True)
        args = g.prepare(X, y, mask)
        loss, grad, n = g.batch_loss_and_grad(W, *args)
        assert int(n) == int(ref_n)
        np.testing.assert_allclose(float(loss), float(ref_l), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(grad), np.asarray(ref_g),
                                   rtol=1e-4, atol=1e-5)

    def test_fused_loop_parity(self, sm_data):
        """Full AGD through the fused softmax smooth vs the jnp path."""
        import jax

        from spark_agd_tpu.core import agd, smooth as smooth_lib
        from spark_agd_tpu.ops.pallas_kernels import PallasSoftmaxGradient
        from spark_agd_tpu.ops.prox import L2Prox

        X, W, y, k = sm_data
        W0 = jnp.zeros_like(W)
        cfg = agd.AGDConfig(num_iterations=4, convergence_tol=0.0)
        px, rv = smooth_lib.make_prox(L2Prox(), 0.01)

        def fit(gradient):
            Xp, yp, mp = gradient.prepare(X, y)
            sm = smooth_lib.make_smooth(gradient, Xp, yp, mp)
            sl = smooth_lib.make_smooth_loss(gradient, Xp, yp, mp)
            r = jax.jit(lambda w: agd.run_agd(sm, px, rv, w, cfg,
                                              smooth_loss=sl))(W0)
            return np.asarray(r.loss_history)[:int(r.num_iters)]

        h_ref = fit(SoftmaxGradient(k))
        h_fused = fit(PallasSoftmaxGradient(SoftmaxGradient(k),
                                            interpret=True))
        np.testing.assert_allclose(h_fused, h_ref, rtol=1e-5)

    def test_rejects_non_softmax(self):
        from spark_agd_tpu.ops.pallas_kernels import PallasSoftmaxGradient

        with pytest.raises(TypeError, match="SoftmaxGradient"):
            PallasSoftmaxGradient(LogisticGradient())


class TestPallasOnMesh:
    """The fused kernel under data parallelism: dist_smooth's per-shard
    tile-aligned relayout must reproduce the generic XLA mesh path."""

    @pytest.fixture(scope="class")
    def mesh_problem(self):
        import jax

        from spark_agd_tpu.parallel import mesh as mesh_lib

        rng = np.random.default_rng(29)
        n, d = 401, 70  # ragged: row-pads per shard, lane-pads width
        X = rng.standard_normal((n, d)).astype(np.float32)
        y = (rng.random(n) < 0.5).astype(np.float32)
        w = (rng.standard_normal(d) / np.sqrt(d)).astype(np.float32)
        mesh = mesh_lib.make_mesh({"data": 4},
                                  devices=jax.devices()[:4])
        return X, y, w, mesh

    @pytest.mark.parametrize("inner_cls", [
        LogisticGradient, LeastSquaresGradient, HingeGradient])
    def test_smooth_parity(self, mesh_problem, inner_cls):
        from spark_agd_tpu.parallel import dist_smooth, mesh as mesh_lib

        X, y, w, mesh = mesh_problem
        batch = mesh_lib.shard_batch(mesh, X, y)
        sm_ref, _ = dist_smooth.make_dist_smooth(
            inner_cls(), batch, mesh=mesh)
        g = PallasMarginGradient(inner_cls(), interpret=True)
        sm_fused, sl_fused = dist_smooth.make_dist_smooth(
            g, batch, mesh=mesh)
        f_ref, g_ref = sm_ref(jnp.asarray(w))
        f_fused, g_fused = sm_fused(jnp.asarray(w))
        np.testing.assert_allclose(float(f_fused), float(f_ref),
                                   rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g_fused),
                                   np.asarray(g_ref), rtol=1e-4,
                                   atol=1e-6)
        np.testing.assert_allclose(float(sl_fused(jnp.asarray(w))),
                                   float(f_ref), rtol=1e-5)

    def test_full_loop_through_api(self, mesh_problem, rel_assert):
        from spark_agd_tpu import api
        from spark_agd_tpu.ops.prox import L2Prox

        X, y, w, mesh = mesh_problem
        d = X.shape[1]
        kw = dict(num_iterations=5, reg_param=0.02,
                  initial_weights=np.zeros(d, np.float32), mesh=mesh)
        _, h_ref = api.run((X, y), LogisticGradient(), L2Prox(), **kw)
        _, h_fused = api.run(
            (X, y), PallasMarginGradient(LogisticGradient(),
                                         interpret=True),
            L2Prox(), **kw)
        assert len(h_ref) == len(h_fused)
        for a, b in zip(h_fused, h_ref):
            rel_assert(a, b, 1e-5, "fused mesh trajectory")

    def test_masked_rows(self, mesh_problem):
        from spark_agd_tpu.parallel import dist_smooth, mesh as mesh_lib

        X, y, w, mesh = mesh_problem
        rng = np.random.default_rng(31)
        mask = (rng.random(X.shape[0]) < 0.8).astype(np.float32)
        batch = mesh_lib.shard_batch(mesh, X, y, mask)
        g = PallasMarginGradient(LogisticGradient(), interpret=True)
        sm_fused, _ = dist_smooth.make_dist_smooth(g, batch, mesh=mesh)
        sm_ref, _ = dist_smooth.make_dist_smooth(
            LogisticGradient(), batch, mesh=mesh)
        f1, g1 = sm_fused(jnp.asarray(w))
        f0, g0 = sm_ref(jnp.asarray(w))
        np.testing.assert_allclose(float(f1), float(f0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                                   rtol=1e-4, atol=1e-6)
