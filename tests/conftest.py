"""Test fixtures: the TPU framework's answer to MLlibTestSparkContext.

The reference unit-tests "distributed" code with no cluster by running a
``local[2]`` threaded SparkContext (reference Suite:27,30 via
``MLlibTestSparkContext``), and exercises real process isolation with
``local-cluster`` mode (Suite:242).  The TPU-native analogue: force the host
platform to expose 8 virtual CPU devices and build real ``jax.sharding.Mesh``
meshes over them — real shardings, real collectives (XLA CPU emulates them
faithfully), no hardware.

x64 is enabled so oracle-parity tests can match the reference's
Double-precision driver math bit-for-bit.

NOTE: env vars (JAX_PLATFORMS / XLA_FLAGS) are too late by the time conftest
runs — the container's sitecustomize.py (/root/.axon_site) imports jax at
interpreter startup with JAX_PLATFORMS=axon (the tunneled real TPU chip).
``jax.config.update`` still works because no backend has been instantiated
yet, and ``jax_num_cpu_devices`` is the modern replacement for
``--xla_force_host_platform_device_count``.
"""

import os

# The suite is CPU-only by design; child processes it spawns (the
# 2-process multihost smoke, bench-worker tests) must not re-run the
# tunneled-TPU registration in THEIR sitecustomize — when the tunnel is
# wedged that registration hangs at interpreter startup (AVAILABILITY.md)
# and the child never reaches its own platform config.  The parent
# process already survived registration by the time conftest runs;
# dropping the trigger var here makes every child start clean.
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # older jaxlib (< 0.4.38) predates the config option; the XLA flag
    # it replaced still works and is read at backend instantiation, which
    # has not happened yet at conftest time
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cpu_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected >=8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture(scope="session")
def mesh8(cpu_devices):
    """The all-device pure-DP mesh most distributed tests run on."""
    from spark_agd_tpu.parallel import mesh as mesh_lib

    return mesh_lib.make_mesh({"data": 8}, devices=cpu_devices)


def assert_rel(actual, expected, rel_tol, msg=""):
    """Relative-tolerance assert, the ``TestingUtils.~=`` analogue
    (reference Suite:28)."""
    actual = float(actual)
    expected = float(expected)
    denom = max(abs(actual), abs(expected))
    if denom == 0.0:
        return
    assert abs(actual - expected) / denom <= rel_tol, (
        f"{msg}: {actual} !~= {expected} (relTol {rel_tol}, "
        f"got {abs(actual - expected) / denom:.3e})"
    )


@pytest.fixture
def rel_assert():
    return assert_rel


@pytest.fixture
def rng():
    return np.random.default_rng(42)
