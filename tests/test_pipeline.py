"""Continuous-learning pipeline tests (``spark_agd_tpu/pipeline/``).

The contracts pinned here close the train→serve loop: the registry's
rollback primitives move ONLY the HEAD pointer (the committed chain —
and forward generation counting — survive a backward repoint), torn
targets are refused with the training-side loader semantics, the
promotion gate refuses rather than guesses on thin/mismatched/noisy
evidence, the trainer's warm-start chain stays clean even when the
published candidate is fault-injected, a failed post-promotion check
rolls HEAD back automatically (emitted as the ``rollback_generation``
recovery action), and every record the loop emits is schema-valid.
The reduced drill smoke rides at the bottom, serve-drill style.
"""

import json

import numpy as np
import pytest

from spark_agd_tpu.core import agd
from spark_agd_tpu.core import smooth as smooth_lib
from spark_agd_tpu.models.evaluation import log_loss
from spark_agd_tpu.models.glm import (LinearRegressionModel,
                                      LogisticRegressionModel)
from spark_agd_tpu.obs import (InMemorySink, Telemetry, perfgate,
                               schema)
from spark_agd_tpu.ops.losses import LogisticGradient
from spark_agd_tpu.ops.prox import L2Prox
from spark_agd_tpu.pipeline import (CanaryController, ContinuousTrainer,
                                    Promoter)
from spark_agd_tpu.resilience.faults import scramble_file
from spark_agd_tpu.serve import (MicroBatchQueue, ModelRegistry,
                                 ServeEngine)
from spark_agd_tpu.utils.checkpoint import CheckpointCorruptError

pytestmark = pytest.mark.pipeline

D = 6


def _rng(seed=0):
    return np.random.default_rng(seed)


def _model(seed=1, scale=1.0):
    r = _rng(seed)
    return LogisticRegressionModel(
        (r.normal(size=D) * scale).astype(np.float32), 0.0)


def _data(seed=0, n=64):
    r = _rng(seed)
    X = r.normal(size=(n, D)).astype(np.float32)
    w = _rng(99).normal(size=D).astype(np.float32)
    y = (r.random(n) < 1.0 / (1.0 + np.exp(-(X @ w)))).astype(
        np.float32)
    return X, y


def _telemetry():
    sink = InMemorySink()
    return Telemetry([sink]), sink


# ---------------------------------------------------------------------------
# satellite: registry rollback primitives


class TestRegistryRollback:
    def _publish_n(self, tmp_path, n=3):
        reg = ModelRegistry(str(tmp_path))
        for i in range(n):
            reg.publish(_model(seed=i + 1))
        return reg

    def test_previous_walks_back_from_head(self, tmp_path):
        reg = self._publish_n(tmp_path)
        assert reg.previous() == 2
        assert reg.previous(2) == 1
        assert reg.previous(1) is None

    def test_repoint_moves_head_only(self, tmp_path):
        from spark_agd_tpu.resilience import manifest as mf

        reg = self._publish_n(tmp_path)
        loaded = reg.repoint(1)
        assert loaded.generation == 1
        assert reg.current.generation == 1
        # HEAD on disk moved; the committed chain did not
        assert mf.load_manifest(str(tmp_path)).generation == 1
        assert mf.committed_generations(str(tmp_path)) == [3, 2, 1]
        # a fresh registry restarting from disk serves the repointed gen
        assert ModelRegistry(str(tmp_path)).load().generation == 1

    def test_publish_after_rollback_counts_forward(self, tmp_path):
        reg = self._publish_n(tmp_path)
        reg.repoint(1)
        # forward counting: a rollback must never cause a generation
        # collision with the still-committed later generations
        assert reg.publish(_model(seed=9)) == 4

    def test_repoint_missing_generation_raises(self, tmp_path):
        reg = self._publish_n(tmp_path)
        with pytest.raises(LookupError):
            reg.repoint(17)

    def test_repoint_binds_engine(self, tmp_path):
        reg = self._publish_n(tmp_path)
        engine = ServeEngine(reg.load(3).model, generation=3,
                             max_batch=8, min_bucket=4)
        reg.repoint(2, engine=engine)
        assert engine.generation == 2
        assert engine.hot_swaps == 1

    def _scramble_gen(self, tmp_path, generation):
        from spark_agd_tpu.resilience import manifest as mf

        man = mf.load_manifest(str(tmp_path), generation)
        scramble_file(str(tmp_path / man.shards[0].path), seed=3)

    def test_previous_skips_torn_generation(self, tmp_path):
        reg = self._publish_n(tmp_path)
        self._scramble_gen(tmp_path, 2)
        assert reg.previous(3) == 1
        with pytest.raises(CheckpointCorruptError):
            reg.repoint(2)

    def test_repoint_refusal_leaves_head(self, tmp_path):
        reg = self._publish_n(tmp_path)
        self._scramble_gen(tmp_path, 2)
        with pytest.raises(CheckpointCorruptError):
            reg.repoint(2)
        assert reg.load().generation == 3  # HEAD never moved


# ---------------------------------------------------------------------------
# satellite: schema kinds, Telemetry helpers, recovery action


class TestPipelineSchema:
    def test_examples_validate(self):
        for kind in ("canary", "promotion"):
            assert kind in schema.KINDS
            assert not schema.validate_record(schema.EXAMPLES[kind])

    def test_selfcheck_green(self):
        ok, problems = schema.selfcheck()
        assert ok, problems

    def test_canary_constructor_and_helper(self):
        rec = schema.canary_record("r1", 5, "pass",
                                   baseline_generation=4,
                                   shadow_requests=32)
        assert not schema.validate_record(rec)
        tel, sink = _telemetry()
        out = tel.canary(generation=5, verdict="fail",
                         quality_delta=0.2)
        assert not schema.validate_record(out)
        assert sink.records[-1]["kind"] == "canary"
        assert tel.registry.snapshot()["pipeline.canary.fail"] == 1

    def test_promotion_constructor_and_helper(self):
        rec = schema.promotion_record("r1", "promoted",
                                      from_generation=4,
                                      to_generation=5)
        assert not schema.validate_record(rec)
        tel, sink = _telemetry()
        out = tel.promotion(decision="rolled_back", from_generation=5,
                            to_generation=4)
        assert not schema.validate_record(out)
        assert sink.records[-1]["decision"] == "rolled_back"

    def test_rollback_generation_recovery_action(self):
        assert "rollback_generation" in schema.RECOVERY_ACTIONS
        tel, sink = _telemetry()
        rec = tel.recovery(action="rollback_generation",
                           from_generation=5, generation=4,
                           reason="post-check failed")
        assert not schema.validate_record(rec)

    def test_bad_required_types_rejected(self):
        rec = schema.canary_record("r1", 5, "pass")
        rec["generation"] = "five"
        assert schema.validate_record(rec)
        rec2 = schema.promotion_record("r1", "promoted")
        del rec2["decision"]
        assert schema.validate_record(rec2)


# ---------------------------------------------------------------------------
# satellite: the promotion gate (obs.perfgate.gate_promotion)


def _canary(gen=5, **over):
    rec = {"schema_version": schema.SCHEMA_VERSION, "kind": "canary",
           "run_id": "r1", "generation": gen, "verdict": "pass",
           "baseline_generation": gen - 1, "shadow_requests": 64,
           "quality_baseline": 0.50, "quality_candidate": 0.49,
           "p50_ms": 1.0, "p99_ms": 2.0,
           "baseline_p50_ms": 1.0, "baseline_p99_ms": 2.0}
    rec.update(over)
    return rec


class TestGatePromotion:
    def test_pass(self):
        g = perfgate.gate_promotion([_canary()])
        assert g.ok and not g.refused and g.exit_code() == 0

    def test_quality_regression_fails(self):
        g = perfgate.gate_promotion(
            [_canary(quality_candidate=0.60)])
        assert not g.ok and g.exit_code() == 1
        assert any("holdout_loss" in f for f in g.failures)

    def test_latency_regression_fails(self):
        g = perfgate.gate_promotion([_canary(p99_ms=4.0)])
        assert g.exit_code() == 1

    def test_thin_shadow_traffic_refuses(self):
        g = perfgate.gate_promotion([_canary(shadow_requests=3)])
        assert g.refused and g.exit_code() == 2

    def test_spec_mismatch_refuses(self):
        g = perfgate.gate_promotion([_canary(
            baseline_spec={"kind": "logistic"},
            candidate_spec={"kind": "linear"})])
        assert g.refused and g.exit_code() == 2

    def test_contention_flag_refuses(self):
        g = perfgate.gate_promotion([_canary(contention_flagged=True)])
        assert g.refused and g.exit_code() == 2

    def test_missing_quality_refuses(self):
        rec = _canary()
        del rec["quality_candidate"]
        g = perfgate.gate_promotion([rec])
        assert g.refused

    def test_vacuous_and_require_canary(self):
        assert perfgate.gate_promotion([]).exit_code() == 0
        g = perfgate.gate_promotion([], require_canary=True)
        assert g.refused and g.exit_code() == 2

    def test_quality_threshold_knob(self):
        rec = _canary(quality_candidate=0.52)  # +4% relative
        assert perfgate.gate_promotion([rec]).ok
        assert not perfgate.gate_promotion(
            [rec], quality_threshold=0.01).ok

    def test_record_is_schema_valid(self):
        g = perfgate.gate_promotion([_canary()])
        assert not schema.validate_record(g.record(run_id="r1"))

    def test_report_renders(self):
        g = perfgate.gate_promotion([_canary(shadow_requests=1)])
        text = perfgate.format_promotion_report(g)
        assert "REFUSED" in text

    def test_cli_promotion_exit_codes(self, tmp_path):
        from tools import perf_gate as cli

        def run(recs, *extra):
            path = tmp_path / "c.jsonl"
            with open(path, "w") as f:
                for r in recs:
                    f.write(json.dumps(r) + "\n")
            return cli.main([str(path), "--promotion", *extra])

        assert run([_canary()]) == 0
        assert run([_canary(quality_candidate=0.9)]) == 1
        assert run([_canary(shadow_requests=1)]) == 2
        assert run([]) == 2  # --promotion requires canary evidence
        assert run([_canary(quality_candidate=0.52)],
                   "--quality-threshold", "0.01") == 1


# ---------------------------------------------------------------------------
# tentpole: the continuous trainer


def _trainer(tmp_path, tel=None, **over):
    prox, reg_value = smooth_lib.make_prox(L2Prox(), 0.01)
    kwargs = dict(
        prox=prox, reg_value=reg_value,
        w0=np.zeros(D, np.float32),
        config=agd.AGDConfig(num_iterations=8, convergence_tol=0.0),
        make_model=lambda w: LogisticRegressionModel(
            np.asarray(w, np.float32), 0.0),
        telemetry=tel)
    kwargs.update(over)
    reg = ModelRegistry(str(tmp_path), telemetry=tel)
    return ContinuousTrainer(reg, LogisticGradient(), **kwargs), reg


class TestContinuousTrainer:
    def test_epochs_warm_start_and_publish(self, tmp_path):
        trainer, reg = _trainer(tmp_path)
        X, y = _data(seed=1)
        r1 = trainer.run_epoch(X, y)
        X2, y2 = _data(seed=2)
        r2 = trainer.run_epoch(X2, y2)
        assert (r1.generation, r2.generation) == (1, 2)
        assert r2.epoch == 2
        # warm start: epoch 2 began from epoch 1's weights, moved on
        assert not np.allclose(np.asarray(r1.weights),
                               np.asarray(r2.weights))
        assert trainer.total_iters == 16
        # published candidates round-trip through the registry
        assert np.allclose(
            np.asarray(reg.load(2).model.weights),
            np.asarray(r2.weights))

    def test_compile_once_epochs_share_build_and_cache(self, tmp_path):
        trainer, _ = _trainer(tmp_path)
        X, y = _data(seed=1)
        trainer.run_epoch(X, y)
        build = trainer._build
        cache_keys = set(trainer._seg_cache)
        X2, y2 = _data(seed=2)
        trainer.run_epoch(X2, y2)
        assert trainer._build is build
        assert set(trainer._seg_cache) == cache_keys  # same program

    def test_weight_fault_corrupts_publish_not_chain(self, tmp_path):
        fault = lambda epoch, w: np.asarray(w) + 100.0  # noqa: E731
        trainer, reg = _trainer(tmp_path, weight_fault=fault)
        X, y = _data(seed=1)
        r = trainer.run_epoch(X, y)
        published = np.asarray(reg.load(r.generation).model.weights)
        assert np.allclose(published, np.asarray(r.weights) + 100.0)
        # the warm-start chain kept the CLEAN weights
        assert np.allclose(np.asarray(trainer.weights),
                           np.asarray(r.weights))

    def test_epoch_emits_trace_span(self, tmp_path):
        tel, sink = _telemetry()
        trainer, _ = _trainer(tmp_path, tel=tel)
        X, y = _data(seed=1)
        trainer.run_epoch(X, y)
        spans = [r for r in sink.records
                 if r.get("kind") == "span"
                 and r.get("name") == "pipeline_epoch"
                 and "generation" in r]  # the completed span
        assert len(spans) == 1 and spans[0].get("trace_id")


# ---------------------------------------------------------------------------
# tentpole: canary window + typed promotion decisions


def _serving_stack(tmp_path, tel, **canary_over):
    """A registry with one good serving generation, its engine+queue,
    and a canary controller graded on a real held-out set."""
    Xv, yv = _data(seed=5, n=96)
    reg = ModelRegistry(str(tmp_path), telemetry=tel)
    g1 = _model(seed=99)  # weights ~ the data's true w (seed 99)
    reg.publish(g1)
    engine = ServeEngine(g1, generation=1, max_batch=8, min_bucket=4,
                         telemetry=tel)
    reg.refresh(engine)
    queue = MicroBatchQueue(engine, telemetry=tel).start()
    kwargs = dict(telemetry=tel, holdout=(Xv, yv), slice_fraction=1.0,
                  min_shadow_requests=2,
                  thresholds={"p50_ms": 50.0, "p99_ms": 50.0})
    kwargs.update(canary_over)
    return reg, engine, queue, CanaryController(reg, engine, queue,
                                                **kwargs)


class TestCanaryAndPromotion:
    def test_pass_window_promotes(self, tmp_path):
        tel, sink = _telemetry()
        reg, engine, queue, ctl = _serving_stack(tmp_path, tel)
        try:
            gen = reg.publish(_model(seed=99))  # identical quality
            assert ctl.start_canary(gen, epoch=1)
            for i in range(6):
                ctl.submit(_rng(i).normal(size=(3, D)).astype(
                    np.float32)).result(timeout=30)
            assert ctl.shadow_count >= 2
            report = ctl.finish_canary()
            assert report.verdict == "pass"
            assert not ctl.active
            decision = Promoter(reg, engine,
                                telemetry=tel).decide(report)
            assert decision.decision == "promoted"
            assert decision.to_generation == gen
            assert reg.current.generation == gen
            assert engine.generation == gen
        finally:
            queue.stop()
        kinds = [r["kind"] for r in sink.records]
        assert "canary" in kinds and "promotion" in kinds
        assert all(not schema.validate_record(r) for r in sink.records)

    def test_quality_regression_rejected_head_stays(self, tmp_path):
        tel, sink = _telemetry()
        reg, engine, queue, ctl = _serving_stack(tmp_path, tel)
        try:
            gen = reg.publish(_model(seed=3, scale=40.0))  # terrible
            assert ctl.start_canary(gen, epoch=1)
            for i in range(6):
                ctl.submit(_rng(i).normal(size=(3, D)).astype(
                    np.float32)).result(timeout=30)
            report = ctl.finish_canary()
            assert report.verdict == "fail"
            decision = Promoter(reg, engine,
                                telemetry=tel).decide(report)
            assert decision.decision == "rejected"
            assert reg.current.generation == 1  # HEAD never moved
        finally:
            queue.stop()

    def test_missing_candidate_refused_preflight(self, tmp_path):
        tel, sink = _telemetry()
        reg, engine, queue, ctl = _serving_stack(tmp_path, tel)
        try:
            assert not ctl.start_canary(42, epoch=1)
            report = ctl.finish_canary()
            assert report.verdict == "refused"
            assert report.refusals
            decision = Promoter(reg, engine,
                                telemetry=tel).decide(report)
            assert decision.decision == "rejected"
            assert decision.gate_status == "refused"
        finally:
            queue.stop()

    def test_spec_mismatch_refused_preflight(self, tmp_path):
        tel, _ = _telemetry()
        reg, engine, queue, ctl = _serving_stack(tmp_path, tel)
        try:
            r = _rng(4)
            gen = reg.publish(LinearRegressionModel(
                r.normal(size=D).astype(np.float32), 0.0))
            assert not ctl.start_canary(gen)
            report = ctl.finish_canary()
            assert report.verdict == "refused"
            assert any("spec mismatch" in s for s in report.refusals)
            assert report.record.get("candidate_spec")
        finally:
            queue.stop()

    def test_fault_injected_pass_rolls_back(self, tmp_path):
        """The drill's story in miniature: the canary is lied to
        (quality_override), the repoint happens, the post-promotion
        check catches the live regression, and HEAD rolls back —
        recovery action, flight path and all."""
        tel, sink = _telemetry()
        reg, engine, queue, ctl = _serving_stack(tmp_path, tel)
        Xv, yv = _data(seed=5, n=96)
        try:
            good_loss = float(log_loss(
                reg.current.model.predict_proba(Xv), yv))
            gen = reg.publish(_model(seed=3, scale=40.0))  # corrupted
            assert ctl.start_canary(gen, epoch=2,
                                    quality_override=good_loss)
            for i in range(6):
                ctl.submit(_rng(i).normal(size=(3, D)).astype(
                    np.float32)).result(timeout=30)
            report = ctl.finish_canary()
            assert report.verdict == "pass"  # the lie worked
            assert report.record["quality_fault_injected"] is True

            def post_check(loaded):
                live = float(log_loss(
                    loaded.model.predict_proba(Xv), yv))
                ok = live <= good_loss * 1.5
                return ok, "" if ok else f"live loss {live:.3f}"

            decision = Promoter(reg, engine, telemetry=tel,
                                post_check=post_check).decide(report)
            assert decision.decision == "rolled_back"
            assert decision.to_generation == 1
            assert reg.current.generation == 1
            assert engine.generation == 1
        finally:
            queue.stop()
        rollbacks = [r for r in sink.records
                     if r.get("kind") == "recovery"
                     and r.get("action") == "rollback_generation"]
        assert len(rollbacks) == 1
        assert rollbacks[0]["from_generation"] == 2
        promo = [r for r in sink.records
                 if r.get("kind") == "promotion"][-1]
        assert promo["decision"] == "rolled_back"
        assert promo["evidence"]["post_check"]

    def test_double_start_raises(self, tmp_path):
        tel, _ = _telemetry()
        reg, engine, queue, ctl = _serving_stack(tmp_path, tel)
        try:
            gen = reg.publish(_model(seed=99))
            assert ctl.start_canary(gen)
            with pytest.raises(RuntimeError):
                ctl.start_canary(gen)
            ctl.finish_canary()
        finally:
            queue.stop()


# ---------------------------------------------------------------------------
# satellite: the report rollup


class TestPipelineReport:
    def test_pipeline_section_and_filter(self, tmp_path, capsys):
        from tools import agd_report

        path = tmp_path / "p.jsonl"
        recs = [
            schema.canary_record("rX", 5, "pass", epoch=1,
                                 baseline_generation=4,
                                 shadow_requests=30,
                                 quality_delta=-0.01, p99_ms=2.0),
            schema.promotion_record("rX", "promoted", epoch=1,
                                    candidate_generation=5,
                                    from_generation=4,
                                    to_generation=5),
        ]
        with open(path, "w") as f:
            for r in recs:
                f.write(json.dumps(r) + "\n")
        assert agd_report.main([str(path), "--pipeline"]) == 0
        out = capsys.readouterr().out
        assert "== pipeline" in out and "promoted" in out \
            and "g5" in out
        assert agd_report.main([str(path)]) == 0
        assert "== pipeline" in capsys.readouterr().out

    def test_pipeline_filter_empty_errors(self, tmp_path, capsys):
        from tools import agd_report

        path = tmp_path / "empty.jsonl"
        with open(path, "w") as f:
            f.write(json.dumps(schema.stamp(
                {"name": "x"}, tool="t")) + "\n")
        assert agd_report.main([str(path), "--pipeline"]) == 1


# ---------------------------------------------------------------------------
# the drill tool (reduced smoke; the full drill is the CI acceptance)


class TestPipelineDrillTool:
    def test_reduced_smoke(self, tmp_path):
        from tools import pipeline_drill

        rc = pipeline_drill.main([
            "--out", str(tmp_path), "--epochs", "2",
            "--fail-epoch", "2", "--clients", "2", "--iters", "6",
            "--rows", "64", "--min-shadow", "4", "--slice", "1.0"])
        assert rc == 0
        records = schema.read_jsonl(
            str(tmp_path / "pipeline_drill.jsonl"))
        decisions = [r["decision"] for r in records
                     if r.get("kind") == "promotion"]
        assert decisions.count("promoted") == 1
        assert decisions.count("rolled_back") == 1
