"""Chaos campaigns, the crash-safe recovery journal, and quorum-based
graceful degradation (``resilience/chaos.py`` / ``journal.py`` /
``degrade.py``).

Per-campaign drills here are tier-1-fast (shared seg_cache, tiny
problem); the full randomized soak and the subprocess drill gate ride
behind ``-m chaos`` (the soak additionally behind ``slow``).  Journal
torn-tail coverage uses the existing ``faults.truncate_file`` /
``faults.scramble_file`` helpers — the satellite contract: replay drops
ONLY the torn tail and recovers every committed record.
"""

import json
import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from spark_agd_tpu.core.agd import AGDConfig, AGDWarmState
from spark_agd_tpu.obs import JSONLSink, Telemetry, schema
from spark_agd_tpu.parallel import multihost as mh
from spark_agd_tpu.resilience import (
    ChaosCampaign,
    ChaosSchedule,
    DegradePolicy,
    DegradedCheckpointer,
    DistributedCheckpointer,
    Journal,
    JournalSink,
    QuorumLost,
    ResiliencePolicy,
    ScheduledFault,
    classify_failure,
    errors,
    faults,
    journal as journal_lib,
    load_degraded,
    run_campaign,
)
from spark_agd_tpu.resilience.chaos import InjectedFatalError
from spark_agd_tpu.resilience.errors import SimulatedDeviceLoss

pytestmark = [pytest.mark.fault, pytest.mark.chaos]


# ---------------------------------------------------------------------------
# the recovery journal


def _decision(i, kind="attempt", **kw):
    base = {"schema_version": schema.SCHEMA_VERSION, "kind": kind,
            "run_id": "jtest", "outcome": "ok", "start_iter": i * 4,
            "iters": 4}
    base.update(kw)
    return base


class TestJournal:
    def test_roundtrip_bit_identical(self, tmp_path):
        path = str(tmp_path / "run.journal")
        with Journal(path) as j:
            stamped = [j.append(_decision(i)) for i in range(5)]
        rep = journal_lib.replay(path)
        assert rep.reason is None and rep.torn_bytes == 0
        assert rep.records == stamped
        # seq stamped monotonically from 0
        assert [r["seq"] for r in rep.records] == list(range(5))
        assert rep.last_seq == 4

    def test_written_mirrors_disk(self, tmp_path):
        path = str(tmp_path / "run.journal")
        j = Journal(path)
        for i in range(3):
            j.append(_decision(i))
        j.close()
        rep = journal_lib.replay(path)
        assert [bytes(p) for p in rep.payloads] == j.written

    def test_missing_file_replays_clean_empty(self, tmp_path):
        rep = journal_lib.replay(str(tmp_path / "absent.journal"))
        assert rep.records == [] and rep.reason is None
        assert rep.last_seq == -1

    def test_reopen_continues_seq(self, tmp_path):
        path = str(tmp_path / "run.journal")
        with Journal(path) as j:
            j.append(_decision(0))
            j.append(_decision(1))
        with Journal(path) as j2:
            assert j2.next_seq == 2
            assert [r["seq"] for r in j2.recovered] == [0, 1]
            j2.append(_decision(2))
        rep = journal_lib.replay(path)
        assert [r["seq"] for r in rep.records] == [0, 1, 2]

    @pytest.mark.parametrize("keep_fraction", [0.15, 0.4, 0.65, 0.9])
    def test_torn_tail_truncation_recovers_committed_prefix(
            self, tmp_path, keep_fraction):
        """Satellite: truncate mid-record at several cut points — every
        record whose frame fits in the kept bytes is recovered, nothing
        past the cut is, and nothing recovered is altered."""
        path = str(tmp_path / "run.journal")
        j = Journal(path)
        stamped = [j.append(_decision(i, note="x" * (20 + 13 * i)))
                   for i in range(8)]
        j.close()
        kept = faults.truncate_file(path, keep_fraction=keep_fraction)
        # expected survivors: frames wholly inside the kept bytes
        off = len(journal_lib.MAGIC)
        expect = []
        for rec, payload in zip(stamped, j.written):
            end = off + journal_lib._FRAME.size + len(payload)
            if end <= kept:
                expect.append(rec)
                off = end
            else:
                break
        rep = journal_lib.replay(path)
        assert rep.records == expect
        assert len(rep.records) < len(stamped)  # something WAS torn
        assert rep.reason is not None
        assert rep.valid_bytes == off
        assert rep.torn_bytes == kept - off

    def test_bit_flip_mid_record_drops_only_the_tail(self, tmp_path):
        """Satellite: scramble bytes INSIDE record k — replay recovers
        records 0..k-1 intact, stops with a CRC reason, never returns
        garbage."""
        path = str(tmp_path / "run.journal")
        j = Journal(path)
        stamped = [j.append(_decision(i, note="y" * 40))
                   for i in range(6)]
        j.close()
        # byte offset of record 3's payload
        off = len(journal_lib.MAGIC)
        for payload in j.written[:3]:
            off += journal_lib._FRAME.size + len(payload)
        faults.scramble_file(path, seed=7, n_bytes=4,
                             offset=off + journal_lib._FRAME.size + 5)
        rep = journal_lib.replay(path)
        assert rep.records == stamped[:3]
        assert rep.reason is not None and "CRC" in rep.reason
        assert rep.torn_bytes > 0

    def test_scrambled_header_replays_empty_with_reason(self, tmp_path):
        path = str(tmp_path / "run.journal")
        with Journal(path) as j:
            j.append(_decision(0))
        faults.scramble_file(path, seed=3, n_bytes=8, offset=0)
        rep = journal_lib.replay(path)
        assert rep.records == [] and "magic" in rep.reason

    def test_reopen_repairs_torn_tail_and_appends_cleanly(
            self, tmp_path):
        """The resume story: a SIGKILL mid-append leaves a torn tail;
        the next open truncates it, reports the repair, continues seq
        from the last COMMITTED record, and new appends replay clean."""
        path = str(tmp_path / "run.journal")
        j = Journal(path)
        for i in range(4):
            j.append(_decision(i))
        j.close()
        size = os.path.getsize(path)
        faults.truncate_file(path, keep_bytes=size - 3)  # torn tail
        tel = Telemetry()
        j2 = Journal(path, telemetry=tel)
        assert j2.replay_summary["repaired"] is True
        assert j2.replay_summary["records"] == 3
        assert j2.replay_summary["torn_bytes"] > 0
        assert j2.next_seq == 3  # record 3 was torn -> re-issued
        j2.append(_decision(3))
        j2.close()
        rep = journal_lib.replay(path)
        assert rep.reason is None and rep.torn_bytes == 0
        assert [r["seq"] for r in rep.records] == [0, 1, 2, 3]
        # the repair decision itself landed on telemetry, schema-valid
        jr = [r for r in tel.records if r.get("kind") == "journal_replay"]
        assert len(jr) == 1 and jr[0]["repaired"] is True
        assert not schema.validate_record(json.loads(json.dumps(jr[0])))

    def test_repair_false_inspects_without_touching(self, tmp_path):
        path = str(tmp_path / "run.journal")
        with Journal(path) as j:
            for i in range(3):
                j.append(_decision(i))
        size = os.path.getsize(path)
        faults.truncate_file(path, keep_bytes=size - 2)
        ro = Journal(path, repair=False)
        ro.close()
        assert os.path.getsize(path) == size - 2  # bytes untouched
        assert ro.replay_summary["repaired"] is False

    def test_sink_filters_to_decision_kinds(self, tmp_path):
        path = str(tmp_path / "run.journal")
        j = Journal(path)
        tel = Telemetry([JournalSink(j)], run_id="jt")
        tel.attempt(attempt=1, outcome="ok", start_iter=0, iters=4)
        tel.heartbeat(process=0)  # high-rate kind: filtered out
        tel.chaos(fault="nan", at_iter=3)
        tel.flush()
        j.close()
        rep = journal_lib.replay(path)
        assert [r["kind"] for r in rep.records] == ["attempt", "chaos"]

    def test_segment_accounting_last_wins(self):
        recs = [_decision(0), _decision(1),
                _decision(1, iters=2),          # re-run supersedes
                _decision(2, outcome="failed"),  # failures don't count
                {"kind": "recovery", "action": "rollback"}]
        acct = journal_lib.segment_accounting(recs)
        assert acct == {0: 4, 4: 2}
        assert sum(acct.values()) == 6

    def test_decision_sequence_shape(self):
        recs = [_decision(0),
                {"kind": "recovery", "action": "rollback", "from_iter": 8,
                 "to_iter": 4, "generation": None},
                {"kind": "chaos", "fault": "nan", "at_iter": 3,
                 "process": None},
                {"kind": "degraded", "surviving": 1,
                 "saved_process_count": 2, "to_iter": 12},
                {"kind": "iteration", "iter": 5}]  # skipped
        seq = journal_lib.decision_sequence(recs)
        assert [t[0] for t in seq] == ["attempt", "recovery", "chaos",
                                       "degraded"]


# ---------------------------------------------------------------------------
# chaos schedules + campaigns


class TestChaosSchedule:
    def test_fault_validation(self):
        with pytest.raises(ValueError, match="unknown fault kind"):
            ScheduledFault("meteor", 3)
        with pytest.raises(ValueError, match="at_iter"):
            ScheduledFault("nan", -1)

    def test_file_faults_rejected_in_run(self):
        with pytest.raises(ValueError, match="FILE fault"):
            ChaosSchedule([ScheduledFault("truncate_ckpt", 4)])

    def test_sequence_fires_in_order_one_per_boundary(self):
        tel = Telemetry(run_id="sched")
        sched = ChaosSchedule(
            [ScheduledFault("device_loss", 8),
             ScheduledFault("fatal", 4)], telemetry=tel, seed=11)
        assert not sched.exhausted
        sched.before_segment(0)  # nothing due
        assert sched.fired == []
        with pytest.raises(InjectedFatalError):
            sched.before_segment(5)  # fatal armed at 4 fires first
        with pytest.raises(SimulatedDeviceLoss):
            sched.before_segment(9)
        assert sched.exhausted
        assert [f[0] for f in sched.fired] == ["fatal", "device_loss"]
        recs = [r for r in tel.records if r.get("kind") == "chaos"]
        assert [r["fault"] for r in recs] == ["fatal", "device_loss"]
        assert all(r["seed"] == 11 for r in recs)
        assert recs[0]["at_iter"] == 4 and recs[0]["fired_iter"] == 5

    def test_slow_host_sleeps_without_interrupting(self):
        naps = []
        sched = ChaosSchedule(
            [ScheduledFault("slow_host", 2, payload=0.03)],
            sleep=naps.append)
        sched.before_segment(3)  # no exception
        assert naps == [0.03]
        assert sched.exhausted

    def test_take_poison_one_shot(self):
        sched = ChaosSchedule([ScheduledFault("nan", 4)])
        assert not sched.take_poison(3)
        assert sched.take_poison(4)
        assert not sched.take_poison(4)  # one-shot
        assert sched.exhausted


class TestChaosCampaign:
    def test_generate_deterministic_in_seed(self):
        a = ChaosCampaign.generate(123, iters=40)
        b = ChaosCampaign.generate(123, iters=40)
        assert a == b
        assert ChaosCampaign.generate(124, iters=40) != a

    def test_generated_campaigns_are_normalized(self):
        """The fairness invariants over a wide seed sweep: bounded NaN
        count, file faults always preceded by a sigterm, arming inside
        the first 70% of the budget."""
        for seed in range(120):
            c = ChaosCampaign.generate(seed, iters=48)
            kinds = [f.kind for f in c.faults]
            assert 1 <= len(kinds) <= 4
            assert kinds.count("nan") <= 2
            for f in c.faults:
                assert 2 <= f.at_iter < 48 * 0.7 + 1
            first_file = next((i for i, k in enumerate(kinds)
                               if k in ("truncate_ckpt",
                                        "scramble_ckpt")), None)
            if first_file is not None:
                assert "sigterm" in kinds[:first_file]
            if "fatal" in kinds:
                assert kinds[-1] == "fatal"
            assert c.expects_giveup == ("fatal" in kinds)

    def test_schedule_for_targets_processes(self):
        c = ChaosCampaign(
            seed=1, iters=20, process_count=2,
            faults=(ScheduledFault("nan", 4),            # everyone
                    ScheduledFault("sigkill", 8, process=1),
                    ScheduledFault("truncate_ckpt", 10, payload=0.4)))
        s0 = c.schedule_for(0)
        s1 = c.schedule_for(1)
        # the nan targets every process; only process 1 sees the kill
        assert s0.take_poison(4) and s1.take_poison(4)
        assert [f.kind for f in s1._pending] == ["sigkill"]
        assert s0._pending == []
        assert s0.exhausted and not s1.exhausted
        assert [f.kind for f in c.file_faults()] == ["truncate_ckpt"]


# ---------------------------------------------------------------------------
# per-campaign drills (tier-1 fast: shared seg_cache, tiny problem)


@pytest.fixture(scope="module")
def campaign_problem():
    import jax.numpy as jnp

    from spark_agd_tpu.core import smooth as smooth_lib
    from spark_agd_tpu.data import synthetic
    from spark_agd_tpu.ops.losses import LogisticGradient
    from spark_agd_tpu.ops.prox import L2Prox

    X, y = synthetic.generate_gd_input(2.0, -1.5, 240, 5)
    X = synthetic.with_intercept_column(X).astype(np.float64)
    build, dargs = smooth_lib.make_smooth_staged(
        LogisticGradient(), jnp.asarray(X), jnp.asarray(y))
    px, rv = smooth_lib.make_prox(L2Prox(), 0.1)
    w0 = jnp.zeros(2, jnp.float64)
    cfg = AGDConfig(convergence_tol=0.0, num_iterations=32)
    policy = ResiliencePolicy(max_attempts=3, backoff_base=0.0,
                              jitter=0.0, seed=0, segment_iters=4)
    seg_cache: dict = {}
    from spark_agd_tpu.resilience import run_agd_supervised

    base = run_agd_supervised(prox=px, reg_value=rv, w0=w0, config=cfg,
                              policy=policy, staged=(build, dargs),
                              seg_cache=seg_cache,
                              stream_iterations=False)
    return dict(staged=(build, dargs), prox=px, reg_value=rv, w0=w0,
                config=cfg, policy=policy, seg_cache=seg_cache,
                baseline_loss=float(base.loss_history[-1]))


def _campaign_run(campaign_problem, campaign, tmp_path, tag="c"):
    wd = str(tmp_path / tag)
    os.makedirs(wd, exist_ok=True)
    journal = Journal(os.path.join(wd, "run.journal"))
    tel = Telemetry([JSONLSink(os.path.join(wd, "run.jsonl")),
                     JournalSink(journal)], run_id=f"chaos-{tag}")
    tel.journal_replay(**journal.replay_summary)
    res = run_campaign(campaign, workdir=wd, telemetry=tel,
                       **campaign_problem)
    tel.flush()
    journal.close()
    return res, journal, wd


class TestRunCampaign:
    def test_preemption_and_torn_checkpoint_converges(
            self, campaign_problem, tmp_path):
        """sigterm → relaunch applies a checkpoint truncation → the
        `.bak` chain resumes — final loss matches baseline to 1e-6
        (f64) and the journal carries the whole story bit-identically."""
        campaign = ChaosCampaign(
            seed=901, iters=32,
            faults=(ScheduledFault("sigterm", 10),
                    ScheduledFault("truncate_ckpt", 12, payload=0.4)))
        res, journal, wd = _campaign_run(campaign_problem, campaign,
                                         tmp_path, "torn")
        assert res.outcome == "converged", res
        assert res.diff <= 1e-6
        assert res.relaunches == 1
        assert [f[0] for f in res.fired] == ["sigterm"]
        assert res.file_applied and "truncate_ckpt" in res.file_applied[0]
        rep = journal_lib.replay(journal.path)
        assert rep.reason is None
        assert [bytes(p) for p in rep.payloads] == journal.written
        # exactly-once census across BOTH attempts equals what counted
        acct = journal_lib.segment_accounting(rep.records)
        assert sum(acct.values()) == res.num_iters
        # decision sequence reconstructs: preemption flush, resume
        seq = journal_lib.decision_sequence(rep.records)
        actions = [t[1] for t in seq if t[0] == "recovery"]
        assert "preemption_flush" in actions and "resume" in actions

    def test_nan_then_device_loss_converges(self, campaign_problem,
                                            tmp_path):
        campaign = ChaosCampaign(
            seed=902, iters=32,
            faults=(ScheduledFault("nan", 6),
                    ScheduledFault("device_loss", 14)))
        res, journal, _ = _campaign_run(campaign_problem, campaign,
                                        tmp_path, "nanloss")
        assert res.outcome == "converged", res
        assert res.diff <= 1e-6
        seq = journal_lib.decision_sequence(
            journal_lib.replay(journal.path).records)
        actions = [t[1] for t in seq if t[0] == "recovery"]
        assert "rollback" in actions and "retry" in actions
        chaos_fired = [t[1] for t in seq if t[0] == "chaos"]
        assert chaos_fired == ["nan", "device_loss"]

    def test_fatal_gives_up_typed(self, campaign_problem, tmp_path):
        campaign = ChaosCampaign(
            seed=903, iters=32,
            faults=(ScheduledFault("fatal", 8),))
        res, journal, _ = _campaign_run(campaign_problem, campaign,
                                        tmp_path, "fatal")
        assert res.outcome == "gave_up"
        assert "InjectedFatalError" in res.giveup_message
        # the failed attempt is journaled before the give-up
        rep = journal_lib.replay(journal.path)
        fails = [r for r in rep.records if r.get("kind") == "attempt"
                 and r.get("outcome") == "failed"]
        assert fails and fails[0]["failure_kind"] == "fatal"

    def test_campaign_replay_is_deterministic(self, campaign_problem,
                                              tmp_path):
        """One seeded campaign, run twice in fresh workdirs: identical
        terminal state and identical journaled decision sequences —
        the acceptance criterion's bit-identical reconstruction."""
        campaign = ChaosCampaign.generate(9, iters=32)
        r1, j1, _ = _campaign_run(campaign_problem, campaign, tmp_path,
                                  "det1")
        r2, j2, _ = _campaign_run(campaign_problem, campaign, tmp_path,
                                  "det2")
        assert r1.outcome == r2.outcome
        assert r1.final_loss == r2.final_loss
        assert r1.fired == r2.fired
        s1 = journal_lib.decision_sequence(
            journal_lib.replay(j1.path).records)
        s2 = journal_lib.decision_sequence(
            journal_lib.replay(j2.path).records)
        assert s1 == s2

    def test_all_records_schema_valid(self, campaign_problem, tmp_path):
        campaign = ChaosCampaign(
            seed=904, iters=32,
            faults=(ScheduledFault("nan", 5),
                    ScheduledFault("sigterm", 12),
                    ScheduledFault("scramble_ckpt", 14, payload=32)))
        res, journal, wd = _campaign_run(campaign_problem, campaign,
                                         tmp_path, "valid")
        assert res.outcome == "converged", res
        records = schema.read_jsonl(os.path.join(wd, "run.jsonl"))
        records += journal_lib.replay(journal.path).records
        bad = [schema.validate_record(json.loads(json.dumps(r)))
               for r in records]
        assert not [b for b in bad if b]


# ---------------------------------------------------------------------------
# quorum-based graceful degradation


class _ThreadExchange:
    """threading.Barrier stand-in for the allgather commit barrier
    (same shape as tests/test_dist_resilience.py)."""

    def __init__(self, n):
        self.n = n
        self._barrier = threading.Barrier(n, timeout=30)
        self._rows = {}

    def for_process(self, p):
        def exchange(row):
            self._rows[p] = np.asarray(row)
            self._barrier.wait()
            out = np.stack([self._rows[i] for i in range(self.n)])
            self._barrier.wait()
            return out

        return exchange


def _warm(prior_iters=3, d=4, seed=0):
    rng = np.random.default_rng(seed)
    cfg = AGDConfig(num_iterations=10)
    w = rng.standard_normal(d).astype(np.float32)
    return AGDWarmState.initial(w, cfg)._replace(
        prior_iters=prior_iters), w


def _two_host_save(tmp_path, warm, *, generations=1, telemetry=None,
                   fingerprint=None, keep=3, row_len=4):
    ex = _ThreadExchange(2)
    cks = [DistributedCheckpointer(
        str(tmp_path), every_iters=1, keep=keep,
        fingerprint=fingerprint, telemetry=telemetry,
        mesh_shape={"data": 2},
        partitions=[f"part-{p}", f"part-{p + 2}"],
        row_state={"rows": np.arange(p * row_len, (p + 1) * row_len)},
        process_index=p, process_count=2,
        exchange=ex.for_process(p)) for p in (0, 1)]
    errs_ = []

    def run(p):
        try:
            for g in range(generations):
                cks[p]._save(warm._replace(
                    prior_iters=int(warm.prior_iters) + g),
                    [0.5, 0.4], False, False)
        except Exception as e:  # noqa: BLE001 — surfaced to the test
            errs_.append(e)

    threads = [threading.Thread(target=run, args=(p,)) for p in (0, 1)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs_, errs_
    return cks


class TestDegradePolicy:
    @pytest.mark.parametrize("saved,alive,allowed", [
        (2, 1, True), (2, 2, True), (4, 2, True),
        (4, 1, False), (8, 3, False),
    ])
    def test_default_quorum_matrix(self, saved, alive, allowed):
        d = DegradePolicy().decide(saved, alive)
        assert d.allowed is allowed
        assert d.surviving == alive and d.saved == saved
        assert d.quorum == pytest.approx(alive / saved)
        assert str(d.required) in d.reason or "quorum" in d.reason

    def test_min_processes_floor(self):
        p = DegradePolicy(min_quorum=0.25, min_processes=2)
        assert not p.decide(4, 1).allowed  # quorum ok, floor unmet
        assert p.decide(4, 2).allowed

    def test_validation(self):
        with pytest.raises(ValueError):
            DegradePolicy(min_quorum=0.0)
        with pytest.raises(ValueError):
            DegradePolicy(min_quorum=1.5)
        with pytest.raises(ValueError):
            DegradePolicy(min_processes=0)
        with pytest.raises(ValueError):
            DegradePolicy().decide(2, 3)

    def test_quorum_lost_is_fatal(self):
        assert classify_failure(QuorumLost("1/4 survive")) == errors.FATAL

    def test_rank_among(self):
        assert mh.rank_among([0, 2, 3], 2) == 1
        assert mh.rank_among([0, 2, 3], 0) == 0
        with pytest.raises(ValueError, match="not among"):
            mh.rank_among([0, 2], 1)


class TestLoadDegraded:
    def test_survivor_resumes_with_dead_partitions_dropped(
            self, tmp_path):
        warm, w0 = _warm(prior_iters=5)
        _two_host_save(tmp_path, warm, fingerprint="fp")
        tel = Telemetry()
        resumed = load_degraded(str(tmp_path), w0, surviving=[0],
                                fingerprint="fp", telemetry=tel)
        assert resumed is not None
        loaded, decision, dropped = resumed
        assert decision.allowed and decision.quorum == 0.5
        assert loaded.elastic and loaded.saved_process_count == 2
        # only the survivor's own partitions remain; the dead host's
        # are reported dropped
        assert loaded.partitions == ("part-0", "part-2")
        assert dropped == ("part-1", "part-3")
        # warm carry is the replicated state — any surviving copy
        np.testing.assert_array_equal(np.asarray(loaded.warm.x),
                                      np.asarray(warm.x))
        assert int(loaded.warm.prior_iters) == 5
        # row-sharded extras: only the surviving rows, re-split to 1
        np.testing.assert_array_equal(loaded.row_state["rows"],
                                      np.arange(4))
        deg = [r for r in tel.records if r.get("kind") == "degraded"]
        assert len(deg) == 1 and deg[0]["surviving"] == 1
        assert deg[0]["lost"] == [1]
        assert not schema.validate_record(json.loads(json.dumps(
            deg[0], default=str)))
        acts = [r for r in tel.records if r.get("kind") == "recovery"]
        assert any(r["action"] == "degraded_continue" for r in acts)

    def test_below_quorum_raises_typed(self, tmp_path):
        warm, w0 = _warm()
        _two_host_save(tmp_path, warm)
        with pytest.raises(QuorumLost, match="quorum lost"):
            load_degraded(str(tmp_path), w0, surviving=[1],
                          policy=DegradePolicy(min_quorum=1.0))

    def test_dead_shard_corruption_is_tolerated(self, tmp_path):
        """The dead host may have died mid-write: its torn shard must
        not block the survivors' resume."""
        warm, w0 = _warm()
        _two_host_save(tmp_path, warm)
        from spark_agd_tpu.resilience import manifest
        m = manifest.load_manifest(str(tmp_path))
        faults.truncate_file(m.shard_path(str(tmp_path), 1),
                             keep_fraction=0.3)
        resumed = load_degraded(str(tmp_path), w0, surviving=[0])
        assert resumed is not None
        assert resumed.loaded.partitions == ("part-0", "part-2")
        # the dead shard was unreadable -> its partitions still count
        # as dropped (known only from the manifest topology, not named)
        assert resumed.dropped_partitions == ()

    def test_surviving_shard_corruption_falls_back_a_generation(
            self, tmp_path):
        warm, w0 = _warm(prior_iters=3)
        _two_host_save(tmp_path, warm, generations=2)
        from spark_agd_tpu.resilience import manifest
        newest = manifest.load_manifest(str(tmp_path))
        assert newest.generation == 1
        faults.scramble_file(newest.shard_path(str(tmp_path), 0),
                             seed=5, n_bytes=64)
        tel = Telemetry()
        resumed = load_degraded(str(tmp_path), w0, surviving=[0],
                                telemetry=tel)
        assert resumed is not None
        assert resumed.loaded.generation == 0
        fb = [r for r in tel.records
              if r.get("action") == "checkpoint_fallback"]
        assert fb and fb[0]["generation"] == 1

    def test_nothing_survives_returns_none(self, tmp_path):
        warm, w0 = _warm()
        _two_host_save(tmp_path, warm)
        from spark_agd_tpu.resilience import manifest
        m = manifest.load_manifest(str(tmp_path))
        faults.truncate_file(m.shard_path(str(tmp_path), 0),
                             keep_fraction=0.3)
        assert load_degraded(str(tmp_path), w0, surviving=[0]) is None

    def test_process_index_must_be_surviving(self, tmp_path):
        warm, w0 = _warm()
        with pytest.raises(ValueError, match="not in"):
            load_degraded(str(tmp_path), w0, surviving=[0],
                          process_index=1)


class TestDegradedCheckpointer:
    def test_load_memoized_and_saves_chain_on(self, tmp_path):
        warm, w0 = _warm(prior_iters=5)
        _two_host_save(tmp_path, warm, fingerprint="fp")
        tel = Telemetry()
        ck = DegradedCheckpointer(
            str(tmp_path), surviving=[1], original_process_index=1,
            every_iters=1, fingerprint="fp", telemetry=tel,
            mesh_shape={"data": 1})
        assert ck.process_index == 0 and ck.process_count == 1
        loaded = ck.load(w0)
        assert loaded is not None
        assert loaded.partitions == ("part-1", "part-3")
        assert ck.dropped_partitions == ("part-0", "part-2")
        assert ck.last_decision is not None and ck.last_decision.allowed
        # second load: memoized — no new degraded record emitted
        n_deg = sum(1 for r in tel.records
                    if r.get("kind") == "degraded")
        assert ck.load(w0) is loaded
        assert sum(1 for r in tel.records
                   if r.get("kind") == "degraded") == n_deg
        # the degraded run's own save is a first-class generation of
        # the SURVIVING topology, resumable by a normal elastic load
        ck._save(warm._replace(prior_iters=9), [0.3], False, False)
        from spark_agd_tpu.resilience import load_for_topology, manifest
        newest = manifest.load_manifest(str(tmp_path))
        assert newest.process_count == 1
        re = load_for_topology(str(tmp_path), w0, process_index=0,
                               process_count=1, fingerprint="fp")
        assert re is not None and int(re.warm.prior_iters) == 9


# ---------------------------------------------------------------------------
# the drill tool gate


def _drill_cmd(tmp_path, *extra):
    tool = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "chaos_drill.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.pathsep.join(
        [os.path.dirname(os.path.dirname(tool))]
        + env.get("PYTHONPATH", "").split(os.pathsep))
    return ([sys.executable, tool, "--out", str(tmp_path / "drill")]
            + list(extra)), env


class TestChaosDrillTool:
    def test_smoke_soak_exits_zero(self, tmp_path):
        """exit-0/1 contract (same as the other fault drills): a small
        randomized soak, single-process, tier-1-budget-friendly."""
        cmd, env = _drill_cmd(tmp_path, "--campaigns", "3",
                              "--skip-two-process")
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=420, env=env)
        assert proc.returncode == 0, \
            f"drill failed:\n{proc.stdout[-3000:]}\n{proc.stderr[-2000:]}"
        assert "CHAOS DRILL PASSED" in proc.stdout

    @pytest.mark.slow
    def test_full_soak_with_two_process_legs(self, tmp_path):
        """The acceptance-criteria configuration: >= 20 randomized
        campaigns plus the SIGKILL+torn-write and quorum-degrade
        two-process legs (behind ``-m chaos``, excluded from tier-1 by
        the slow marker)."""
        cmd, env = _drill_cmd(tmp_path, "-v")
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=560, env=env)
        assert proc.returncode == 0, \
            f"drill failed:\n{proc.stdout[-4000:]}\n{proc.stderr[-2000:]}"
        assert "CHAOS DRILL PASSED: 22 campaigns" in proc.stdout
