"""Mesh-sharded CSR streaming — the north-star composition: more sparse
rows than the pod's HBM, streamed as macro-batches, each batch row-
sharded over the data axis and evaluated by the shard_map+psum kernel.
Previously an explicit NotImplementedError (streaming.py): sparse data
could stream OR ride the mesh, not both.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_agd_tpu.core import agd, host_agd, smooth as smooth_lib
from spark_agd_tpu.data import streaming
from spark_agd_tpu.ops import losses, prox, sparse
from spark_agd_tpu.parallel import mesh as mesh_lib


def _make_problem(rng, n=700, d=41, npr=6):
    indptr = np.arange(n + 1) * npr
    indices = rng.integers(0, d, n * npr).astype(np.int32)
    values = rng.normal(size=n * npr).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    w = (rng.normal(size=d) / 8).astype(np.float32)
    return indptr, indices, values, y, w, d


class TestStreamedCsrMeshSmooth:
    @pytest.mark.parametrize("n_shards", [1, 2, 8])
    def test_matches_single_device(self, rng, cpu_devices, n_shards):
        """Streamed + mesh-sharded CSR smooth == in-memory single-device
        CSR smooth, for every mesh width (the sharding-parity contract of
        tests/test_csr_mesh.py extended to the streamed layout)."""
        indptr, indices, values, y, w, d = _make_problem(rng)
        g = losses.LogisticGradient()
        X = sparse.CSRMatrix.from_csr_arrays(indptr, indices, values, d,
                                             with_csc=True)
        sm_ref = smooth_lib.make_smooth(g, X, jnp.asarray(y))
        f_ref, g_ref = jax.jit(sm_ref)(jnp.asarray(w))

        mesh = mesh_lib.make_mesh({"data": n_shards},
                                  devices=cpu_devices[:n_shards])
        ds = streaming.StreamingDataset.from_csr(
            indptr, indices, values, d, y, batch_rows=256)
        sm, sl = streaming.make_streaming_smooth(g, ds, mesh=mesh)
        f, gr = sm(jnp.asarray(w))
        np.testing.assert_allclose(float(f), float(f_ref), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(gr), np.asarray(g_ref),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(float(sl(jnp.asarray(w))),
                                   float(f_ref), rtol=1e-6)

    def test_host_agd_trajectory_matches_fused(self, rng, cpu_devices):
        """Full host-driver AGD over mesh-streamed CSR equals the fused
        in-memory single-device sparse run — the complete north-star
        stack (stream + shard + accelerate) against the spec."""
        indptr, indices, values, y, w, d = _make_problem(rng)
        g = losses.LogisticGradient()
        w0 = jnp.zeros(d, jnp.float32)
        px, rv = smooth_lib.make_prox(prox.MLlibSquaredL2Updater(), 0.05)
        cfg = agd.AGDConfig(num_iterations=6, convergence_tol=0.0)

        X = sparse.CSRMatrix.from_csr_arrays(indptr, indices, values, d,
                                             with_csc=True)
        sm_ref = smooth_lib.make_smooth(g, X, jnp.asarray(y))
        r_fused = jax.jit(
            lambda wv: agd.run_agd(sm_ref, px, rv, wv, cfg))(w0)

        mesh = mesh_lib.make_mesh({"data": 4}, devices=cpu_devices[:4])
        ds = streaming.StreamingDataset.from_csr(
            indptr, indices, values, d, y, batch_rows=256)
        sm, sl = streaming.make_streaming_smooth(g, ds, mesh=mesh)
        r_host = host_agd.run_agd_host(sm, px, rv, w0, cfg,
                                       smooth_loss=sl)
        assert r_host.num_iters == int(r_fused.num_iters)
        np.testing.assert_allclose(
            r_host.loss_history,
            np.asarray(r_fused.loss_history)[:r_host.num_iters],
            rtol=1e-5)

    def test_lazy_twin_mode(self, rng, cpu_devices):
        """with_csc='lazy' (the recommended mesh-streaming mode): no
        eager global twin is built per batch — only the marker — yet the
        sharder materializes per-shard twins and the gradient matches."""
        indptr, indices, values, y, w, d = _make_problem(rng)
        g = losses.LogisticGradient()
        X = sparse.CSRMatrix.from_csr_arrays(indptr, indices, values, d,
                                             with_csc=True)
        f_ref, g_ref = jax.jit(
            smooth_lib.make_smooth(g, X, jnp.asarray(y)))(jnp.asarray(w))

        ds = streaming.StreamingDataset.from_csr(
            indptr, indices, values, d, y, batch_rows=256,
            with_csc="lazy")
        for Xb, _, _ in ds:
            assert Xb.want_csc and not Xb.has_csc  # marker only
        mesh = mesh_lib.make_mesh({"data": 4}, devices=cpu_devices[:4])
        sm, _ = streaming.make_streaming_smooth(g, ds, mesh=mesh)
        f, gr = sm(jnp.asarray(w))
        np.testing.assert_allclose(float(f), float(f_ref), rtol=1e-6)
        np.testing.assert_allclose(np.asarray(gr), np.asarray(g_ref),
                                   rtol=1e-5, atol=1e-6)

    def test_budget_too_small_raises_with_knob(self, rng, cpu_devices):
        indptr, indices, values, y, _, d = _make_problem(rng)
        mesh = mesh_lib.make_mesh({"data": 2}, devices=cpu_devices[:2])
        ds = streaming.StreamingDataset.from_csr(
            indptr, indices, values, d, y, batch_rows=256)
        sm, _ = streaming.make_streaming_smooth(
            losses.LogisticGradient(), ds, mesh=mesh,
            csr_nnz_per_shard=8)
        with pytest.raises(ValueError, match="csr_nnz_per_shard"):
            sm(jnp.zeros(d, jnp.float32))

    def test_one_compiled_shape_across_batches(self, rng, cpu_devices):
        """Every macro-batch (tail included) must reuse ONE kernel shape:
        count traces through a counting gradient."""
        indptr, indices, values, y, w, d = _make_problem(rng, n=700)
        traces = {"n": 0}

        class Counting(losses.LogisticGradient):
            def batch_loss_and_grad(self, wv, X, yv, mask=None):
                traces["n"] += 1  # Python-level: counts TRACES
                return super().batch_loss_and_grad(wv, X, yv, mask)

        mesh = mesh_lib.make_mesh({"data": 4}, devices=cpu_devices[:4])
        ds = streaming.StreamingDataset.from_csr(
            indptr, indices, values, d, y, batch_rows=256)  # 3 batches
        sm, _ = streaming.make_streaming_smooth(Counting(), ds, mesh=mesh)
        sm(jnp.asarray(w))
        after_first = traces["n"]
        assert after_first >= 1
        sm(jnp.asarray(w))  # second full pass: zero new traces
        assert traces["n"] == after_first


class TestStreamingEvalMulti:
    """K-lane streamed evaluation: score a whole regularization path /
    CV candidate set over larger-than-HBM data in ONE stream pass."""

    def test_dense_single_device_matches_per_lane(self, rng):
        n, d, k = 500, 12, 3
        X = rng.standard_normal((n, d)).astype(np.float32)
        y = (rng.random(n) < 0.5).astype(np.float32)
        W = rng.standard_normal((k, d)).astype(np.float32) / 4
        g = losses.LogisticGradient()
        ds = streaming.StreamingDataset.from_arrays(X, y, batch_rows=128)
        ev = streaming.make_streaming_eval_multi(g, ds, pad_to=128)
        ls, gs = ev(W)
        assert ls.shape == (k,) and gs.shape == (k, d)
        sm, _ = streaming.make_streaming_smooth(g, ds, pad_to=128)
        for i in range(k):
            f_i, g_i = sm(jnp.asarray(W[i]))
            np.testing.assert_allclose(float(ls[i]), float(f_i),
                                       rtol=1e-6)
            np.testing.assert_allclose(np.asarray(gs[i]),
                                       np.asarray(g_i),
                                       rtol=1e-5, atol=1e-7)

    def test_loss_only_mode(self, rng):
        n, d, k = 300, 10, 4
        X = rng.standard_normal((n, d)).astype(np.float32)
        y = (rng.random(n) < 0.5).astype(np.float32)
        W = rng.standard_normal((k, d)).astype(np.float32) / 4
        g = losses.LogisticGradient()
        ds = streaming.StreamingDataset.from_arrays(X, y, batch_rows=128)
        ls = streaming.make_streaming_eval_multi(
            g, ds, pad_to=128, with_grad=False)(W)
        ls_full, _ = streaming.make_streaming_eval_multi(
            g, ds, pad_to=128)(W)
        np.testing.assert_allclose(np.asarray(ls), np.asarray(ls_full),
                                   rtol=1e-6)

    def test_csr_single_device_matches_per_lane(self, rng):
        """The no-mesh CSR lane path (vmapped kernel over a device
        CSRMatrix, lazy CSC twin materialized at placement)."""
        indptr, indices, values, y, w, d = _make_problem(rng, n=400)
        k = 3
        W = np.stack([w * (i + 1) for i in range(k)])
        g = losses.LogisticGradient()
        ds = streaming.StreamingDataset.from_csr(
            indptr, indices, values, d, y, batch_rows=128)  # lazy csc
        ls, gs = streaming.make_streaming_eval_multi(g, ds)(W)
        sm, _ = streaming.make_streaming_smooth(g, ds)
        for i in range(k):
            f_i, g_i = sm(jnp.asarray(W[i]))
            np.testing.assert_allclose(float(ls[i]), float(f_i),
                                       rtol=1e-6)
            np.testing.assert_allclose(np.asarray(gs[i]),
                                       np.asarray(g_i),
                                       rtol=1e-5, atol=1e-7)

    def test_csr_mesh_matches_per_lane(self, rng, cpu_devices):
        indptr, indices, values, y, w, d = _make_problem(rng, n=500)
        k = 3
        W = np.stack([w * (i + 1) for i in range(k)])
        g = losses.LogisticGradient()
        mesh = mesh_lib.make_mesh({"data": 4}, devices=cpu_devices[:4])
        ds = streaming.StreamingDataset.from_csr(
            indptr, indices, values, d, y, batch_rows=256)
        ev = streaming.make_streaming_eval_multi(g, ds, mesh=mesh)
        ls, gs = ev(W)
        sm, _ = streaming.make_streaming_smooth(g, ds, mesh=mesh)
        for i in range(k):
            f_i, g_i = sm(jnp.asarray(W[i]))
            np.testing.assert_allclose(float(ls[i]), float(f_i),
                                       rtol=1e-6)
            np.testing.assert_allclose(np.asarray(gs[i]),
                                       np.asarray(g_i),
                                       rtol=1e-5, atol=1e-7)

    def test_scores_a_sweep_result_over_the_stream(self, rng,
                                                   cpu_devices):
        """The intended composition: train a path on in-HBM data with
        the mesh sweep, then score every lane on a (notionally larger)
        streamed validation set in one pass."""
        from spark_agd_tpu import api

        n, d = 400, 10
        X = rng.standard_normal((n, d)).astype(np.float32)
        y = (X[:, 0] > 0).astype(np.float32)
        mesh = mesh_lib.make_mesh({"data": 4}, devices=cpu_devices[:4])
        res = api.sweep((X, y), losses.LogisticGradient(),
                        prox.SquaredL2Updater(), [0.01, 0.1, 1.0],
                        num_iterations=6, convergence_tol=0.0,
                        initial_weights=np.zeros(d, np.float32),
                        mesh=mesh)
        Xv = rng.standard_normal((600, d)).astype(np.float32)
        yv = (Xv[:, 0] > 0).astype(np.float32)
        ds = streaming.StreamingDataset.from_arrays(Xv, yv,
                                                    batch_rows=256)
        val = streaming.make_streaming_eval_multi(
            losses.LogisticGradient(), ds, pad_to=256,
            with_grad=False)(res.weights)
        assert val.shape == (3,)
        # small reg should generalize best on this separable problem
        assert int(np.argmin(np.asarray(val))) in (0, 1)
