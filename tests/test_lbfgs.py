"""The L-BFGS member of the Optimizer family (``core/lbfgs.py``).

The reference implements spark-mllib 1.3.0's ``Optimizer`` trait so it
swaps with MLlib's ``GradientDescent`` / ``LBFGS`` inside
``GeneralizedLinearAlgorithm`` callers (reference
``AcceleratedGradientDescent.scala:41-42``; SURVEY §1 L5).  These tests
pin the L-BFGS member the same way the reference pins AGD: against an
independent oracle (scipy's L-BFGS-B in f64) instead of against its own
implementation, plus the family's iteration-efficiency headline vs the
GD oracle (the reference's 10-vs-50 test shape, Suite:60, :77).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.optimize as sopt

from spark_agd_tpu import api
from spark_agd_tpu.ops import losses, prox, sparse


def logistic_problem(rng, n=400, d=10):
    X = rng.standard_normal((n, d))
    w_true = rng.standard_normal(d)
    p = 1.0 / (1.0 + np.exp(-(X @ w_true)))
    y = (rng.random(n) < p).astype(np.float64)
    return X, y


def logistic_l2_np(X, y, reg):
    n = X.shape[0]

    def f(w):
        z = X @ w
        return float(np.mean(np.logaddexp(0, z) - y * z)
                     + 0.5 * reg * w @ w)

    def g(w):
        p = 1.0 / (1.0 + np.exp(-(X @ w)))
        return X.T @ (p - y) / n + reg * w

    return f, g


class TestAgainstScipy:
    def test_logistic_l2_matches_lbfgsb(self, rng):
        X, y = logistic_problem(rng)
        reg = 0.05
        res = api.run_lbfgs((X, y), losses.LogisticGradient(),
                            prox.SquaredL2Updater(), reg_param=reg,
                            convergence_tol=1e-10, num_iterations=200,
                            initial_weights=np.zeros(10), mesh=False)
        assert bool(res.converged) and not bool(res.ls_failed)
        f, g = logistic_l2_np(X, y, reg)
        ref = sopt.minimize(f, np.zeros(10), jac=g, method="L-BFGS-B",
                            options=dict(maxiter=500, ftol=1e-16,
                                         gtol=1e-12))
        ours = f(np.asarray(res.weights))
        # same optimum as an independent L-BFGS implementation
        assert ours <= ref.fun + 1e-8
        np.testing.assert_allclose(np.asarray(res.weights), ref.x,
                                   atol=1e-4)

    def test_least_squares_unregularized(self, rng):
        X = rng.standard_normal((300, 8))
        w_true = rng.standard_normal(8)
        y = X @ w_true + 0.01 * rng.standard_normal(300)
        res = api.run_lbfgs((X, y), losses.LeastSquaresGradient(),
                            prox.SimpleUpdater(),
                            convergence_tol=1e-12, num_iterations=200,
                            initial_weights=np.zeros(8), mesh=False)
        # quadratic objective: L-BFGS must land on the normal-equations
        # solution (the 1.3 convention is mean of diff^2, same argmin)
        w_ls = np.linalg.lstsq(X, y, rcond=None)[0]
        np.testing.assert_allclose(np.asarray(res.weights), w_ls,
                                   atol=1e-6)

    def test_loss_history_semantics(self, rng):
        X, y = logistic_problem(rng, n=200, d=6)
        res = api.run_lbfgs((X, y), losses.LogisticGradient(),
                            prox.SquaredL2Updater(), reg_param=0.1,
                            convergence_tol=1e-10, num_iterations=50,
                            initial_weights=np.zeros(6), mesh=False)
        hist = np.asarray(res.loss_history)
        k = int(res.num_iters)
        # [0] is the objective at w0: log(2) + 0 penalty for zeros
        np.testing.assert_allclose(hist[0], np.log(2.0), rtol=1e-12)
        assert np.all(np.isfinite(hist[:k + 1]))
        assert np.all(np.isnan(hist[k + 1:]))
        # monotone decrease (Wolfe-accepted steps only)
        assert np.all(np.diff(hist[:k + 1]) <= 0)

    def test_num_corrections_one_still_converges(self, rng):
        X, y = logistic_problem(rng, n=200, d=6)
        res = api.run_lbfgs((X, y), losses.LogisticGradient(),
                            prox.SquaredL2Updater(), reg_param=0.1,
                            num_corrections=1, convergence_tol=1e-10,
                            num_iterations=300,
                            initial_weights=np.zeros(6), mesh=False)
        f, _ = logistic_l2_np(X, y, 0.1)
        assert bool(res.converged)
        assert f(np.asarray(res.weights)) <= f(np.zeros(6))


class TestBehavior:
    def test_tighter_tol_runs_more_iterations(self, rng):
        X, y = logistic_problem(rng)
        kw = dict(reg_param=0.01, num_iterations=200,
                  initial_weights=np.zeros(10), mesh=False)
        loose = api.run_lbfgs((X, y), losses.LogisticGradient(),
                              prox.SquaredL2Updater(),
                              convergence_tol=1e-3, **kw)
        tight = api.run_lbfgs((X, y), losses.LogisticGradient(),
                              prox.SquaredL2Updater(),
                              convergence_tol=1e-12, **kw)
        assert int(tight.num_iters) > int(loose.num_iters)
        assert bool(loose.converged) and bool(tight.converged)

    def test_beats_gd_oracle_iteration_efficiency(self, rng):
        """The family headline, reference Suite:60/:77 shape: the
        second-order member reaches GD@50's loss in far fewer
        iterations."""
        X, y = logistic_problem(rng)
        gd_w, gd_hist = api.run_minibatch_sgd(
            (X, y), losses.LogisticGradient(), prox.SimpleUpdater(),
            step_size=1.0, num_iterations=50,
            initial_weights=np.zeros(10), mesh=False)
        res = api.run_lbfgs((X, y), losses.LogisticGradient(),
                            prox.SimpleUpdater(),
                            convergence_tol=0.0, num_iterations=10,
                            initial_weights=np.zeros(10), mesh=False)
        hist = np.asarray(res.loss_history)
        k = int(res.num_iters)
        assert hist[min(k, 10)] <= float(np.asarray(gd_hist)[-1]) + 1e-12

    def test_l1_updater_routes_to_owlqn(self, rng):
        """An L1 updater is no longer rejected: it dispatches to the
        OWL-QN driver (the post-1.3 Spark lift) and produces a sparse
        solution of the same L1 objective."""
        X, y = logistic_problem(rng, n=120, d=6)
        res = api.run_lbfgs((X, y), losses.LogisticGradient(),
                            prox.L1Updater(), reg_param=0.1,
                            convergence_tol=1e-10, num_iterations=100,
                            initial_weights=np.zeros(6), mesh=False)
        assert bool(res.converged)
        assert np.all(np.isfinite(np.asarray(res.weights)))

    def test_non_finite_objective_aborts(self, rng):
        X = rng.standard_normal((20, 3))
        X[0, 0] = np.inf
        y = np.zeros(20)
        res = api.run_lbfgs((X, y), losses.LeastSquaresGradient(),
                            prox.SimpleUpdater(),
                            initial_weights=np.ones(3), mesh=False)
        assert bool(res.aborted_non_finite)
        assert int(res.num_iters) == 0

    def test_optimizer_class_drop_in(self, rng):
        """The Optimizer-trait shape: LBFGS(g, u).optimize(...) swaps
        with AcceleratedGradientDescent(g, u).optimize(...), camelCase
        setters included."""
        X, y = logistic_problem(rng, n=200, d=6)
        opt = (api.LBFGS(losses.LogisticGradient(),
                         prox.SquaredL2Updater())
               .setRegParam(0.1).setConvergenceTol(1e-10)
               .setNumIterations(100).setNumCorrections(7))
        opt.set_mesh(False)
        w = opt.optimize((X, y), np.zeros(6))
        ref = api.run_lbfgs((X, y), losses.LogisticGradient(),
                            prox.SquaredL2Updater(), reg_param=0.1,
                            num_corrections=7, convergence_tol=1e-10,
                            num_iterations=100,
                            initial_weights=np.zeros(6), mesh=False)
        np.testing.assert_array_equal(np.asarray(w),
                                      np.asarray(ref.weights))


class TestHostTwin:
    """core/host_lbfgs: the streaming / cross-process driver must make
    the SAME decisions as the fused loop (the host_agd parity model)."""

    def _objective(self, X, y, reg):
        from spark_agd_tpu.core import lbfgs as lbfgs_lib, smooth
        sm = smooth.make_smooth(losses.LogisticGradient(),
                                jnp.asarray(X), jnp.asarray(y))
        return lbfgs_lib.make_objective(sm, prox.SquaredL2Updater(), reg)

    def test_host_matches_fused_trajectory(self, rng):
        from spark_agd_tpu.core import host_lbfgs, lbfgs as lbfgs_lib

        X, y = logistic_problem(rng, n=300, d=9)
        reg = 0.07
        cfg = lbfgs_lib.LBFGSConfig(convergence_tol=1e-11,
                                    num_iterations=80)
        obj = self._objective(X, y, reg)
        fused = jax.jit(lambda w: lbfgs_lib.run_lbfgs(obj, w, cfg))(
            jnp.zeros(9))
        host = host_lbfgs.run_lbfgs_host(obj, jnp.zeros(9), cfg)
        kf = int(fused.num_iters)
        assert host.num_iters == kf
        assert bool(fused.converged) == host.converged
        np.testing.assert_allclose(
            host.loss_history,
            np.asarray(fused.loss_history)[:kf + 1], rtol=1e-12)
        np.testing.assert_allclose(np.asarray(host.weights),
                                   np.asarray(fused.weights),
                                   rtol=1e-10, atol=1e-12)
        assert host.num_fn_evals == int(fused.num_fn_evals)

    def test_streamed_matches_in_memory(self, rng):
        """L-BFGS over macro-batched streamed data == the fused
        in-memory fit — the > HBM composition for the quasi-Newton
        member."""
        from spark_agd_tpu.core import host_lbfgs, lbfgs as lbfgs_lib
        from spark_agd_tpu.data import streaming

        X, y = logistic_problem(rng, n=350, d=8)
        reg = 0.05
        cfg = lbfgs_lib.LBFGSConfig(convergence_tol=1e-10,
                                    num_iterations=60)
        ds = streaming.StreamingDataset.from_arrays(X, y, batch_rows=64)
        sm, _ = streaming.make_streaming_smooth(
            losses.LogisticGradient(), ds, pad_to=64)
        obj_s = lbfgs_lib.make_objective(sm, prox.SquaredL2Updater(),
                                         reg)
        res_s = host_lbfgs.run_lbfgs_host(obj_s, jnp.zeros(8), cfg)
        res_f = api.run_lbfgs((X, y), losses.LogisticGradient(),
                              prox.SquaredL2Updater(), reg_param=reg,
                              convergence_tol=1e-10, num_iterations=60,
                              initial_weights=np.zeros(8), mesh=False)
        assert res_s.num_iters == int(res_f.num_iters)
        np.testing.assert_allclose(np.asarray(res_s.weights),
                                   np.asarray(res_f.weights),
                                   rtol=1e-7, atol=1e-9)

    def test_warm_resume_is_exact(self, rng):
        """A segmented run (stop after k, resume from the carry) makes
        decisions IDENTICAL to the uninterrupted run — curvature pairs
        and gradient carry over, nothing is re-evaluated."""
        from spark_agd_tpu.core import host_lbfgs, lbfgs as lbfgs_lib

        X, y = logistic_problem(rng, n=250, d=7)
        obj = self._objective(X, y, 0.03)
        cfg = lbfgs_lib.LBFGSConfig(convergence_tol=1e-11,
                                    num_iterations=40)
        full = host_lbfgs.run_lbfgs_host(obj, jnp.zeros(7), cfg)
        assert full.num_iters >= 6  # enough room to split

        cfg_k = lbfgs_lib.LBFGSConfig(convergence_tol=1e-11,
                                      num_iterations=3)
        seg1 = host_lbfgs.run_lbfgs_host(obj, jnp.zeros(7), cfg_k)
        assert seg1.num_iters == 3 and not seg1.converged
        warm = host_lbfgs.HostLBFGSWarm.from_result(seg1)
        seg2 = host_lbfgs.run_lbfgs_host(obj, jnp.zeros(7), cfg,
                                         warm=warm)
        assert 3 + seg2.num_iters == full.num_iters
        assert seg2.converged == full.converged
        joined = np.concatenate([seg1.loss_history,
                                 seg2.loss_history[1:]])
        np.testing.assert_array_equal(joined, full.loss_history)
        np.testing.assert_array_equal(np.asarray(seg2.weights),
                                      np.asarray(full.weights))
        # the objective was NOT re-evaluated at the resume point
        assert seg1.num_fn_evals + seg2.num_fn_evals == \
            int(full.num_fn_evals)

    def test_on_iteration_carry_round_trips(self, rng):
        """Checkpointing from the hook payload resumes exactly."""
        from spark_agd_tpu.core import host_lbfgs, lbfgs as lbfgs_lib

        X, y = logistic_problem(rng, n=200, d=6)
        obj = self._objective(X, y, 0.05)
        cfg = lbfgs_lib.LBFGSConfig(convergence_tol=1e-11,
                                    num_iterations=30)
        full = host_lbfgs.run_lbfgs_host(obj, jnp.zeros(6), cfg)
        snaps = []
        host_lbfgs.run_lbfgs_host(
            obj, jnp.zeros(6), cfg,
            on_iteration=lambda s: snaps.append(s) if s["it"] == 2
            else None)
        s = snaps[0]
        warm = host_lbfgs.HostLBFGSWarm(
            w=s["w"], f=s["f"], g=s["g"], pairs=s["pairs"],
            prior_iters=s["it"])
        seg2 = host_lbfgs.run_lbfgs_host(obj, jnp.zeros(6), cfg,
                                         warm=warm)
        np.testing.assert_array_equal(np.asarray(seg2.weights),
                                      np.asarray(full.weights))
        assert 2 + seg2.num_iters == full.num_iters

    def test_prox_only_rejected_by_objective_builder(self):
        from spark_agd_tpu.core import lbfgs as lbfgs_lib

        with pytest.raises(ValueError, match="smooth penalty"):
            lbfgs_lib.make_objective(lambda w: (0.0, w),
                                     prox.L1Updater(), 0.1)


class TestOWLQN:
    """run_owlqn vs prox-AGD: both minimize the identical convex
    F(w) = f(w) + l1·‖w‖₁, so the proximal member IS the independent
    oracle for the orthant-wise one (and vice versa)."""

    def _objective_F(self, X, y, l1):
        def F(w):
            z = X @ w
            return float(np.mean(np.logaddexp(0, z) - y * z)
                         + l1 * np.abs(w).sum())

        return F

    def test_matches_prox_agd_on_l1_logistic(self, rng):
        X, y = logistic_problem(rng, n=400, d=12)
        l1 = 0.05
        res = api.run_lbfgs((X, y), losses.LogisticGradient(),
                            prox.L1Updater(), reg_param=l1,
                            convergence_tol=1e-12, num_iterations=300,
                            initial_weights=np.zeros(12), mesh=False)
        w_agd, hist = api.run((X, y), losses.LogisticGradient(),
                              prox.L1Prox(), reg_param=l1,
                              convergence_tol=1e-12,
                              num_iterations=2000,
                              initial_weights=np.zeros(12), mesh=False)
        F = self._objective_F(X, y, l1)
        f_owl, f_agd = F(np.asarray(res.weights)), F(np.asarray(w_agd))
        # same optimum from two unrelated algorithms
        assert abs(f_owl - f_agd) <= 1e-6 * max(abs(f_agd), 1.0), \
            (f_owl, f_agd)
        # the history tracks the FULL objective and matches F at exit
        k = int(res.num_iters)
        np.testing.assert_allclose(float(res.loss_history[k]), f_owl,
                                   rtol=1e-9)

    def test_produces_exact_zeros(self, rng):
        X, y = logistic_problem(rng, n=300, d=20)
        res = api.run_lbfgs((X, y), losses.LogisticGradient(),
                            prox.L1Updater(), reg_param=0.15,
                            convergence_tol=1e-11, num_iterations=200,
                            initial_weights=np.zeros(20), mesh=False)
        w = np.asarray(res.weights)
        # the orthant projection writes EXACT zeros, not small values
        assert np.sum(w == 0.0) > 0, w
        agd_w, _ = api.run((X, y), losses.LogisticGradient(),
                           prox.L1Prox(), reg_param=0.15,
                           convergence_tol=1e-12, num_iterations=2000,
                           initial_weights=np.zeros(20), mesh=False)
        # same support as the soft-thresholding prox finds
        assert set(np.nonzero(w)[0]) == set(
            np.nonzero(np.asarray(agd_w))[0])

    def test_elastic_net_dispatch(self, rng):
        X, y = logistic_problem(rng, n=250, d=8)
        en = prox.ElasticNetProx(l1_ratio=0.5)
        res = api.run_lbfgs((X, y), losses.LogisticGradient(), en,
                            reg_param=0.1, convergence_tol=1e-12,
                            num_iterations=300,
                            initial_weights=np.zeros(8), mesh=False)
        w_agd, _ = api.run((X, y), losses.LogisticGradient(), en,
                           reg_param=0.1, convergence_tol=1e-12,
                           num_iterations=2000,
                           initial_weights=np.zeros(8), mesh=False)

        def F(w):
            z = X @ w
            return float(np.mean(np.logaddexp(0, z) - y * z)
                         + 0.05 * np.abs(w).sum()
                         + 0.025 * (w @ w))

        assert abs(F(np.asarray(res.weights))
                   - F(np.asarray(w_agd))) <= 1e-6

    def test_mesh_matches_single_device(self, rng, mesh8):
        X, y = logistic_problem(rng, n=300, d=10)
        kw = dict(reg_param=0.08, convergence_tol=0.0,
                  num_iterations=8, initial_weights=np.zeros(10))
        res_1 = api.run_lbfgs((X, y), losses.LogisticGradient(),
                              prox.L1Updater(), mesh=False, **kw)
        res_m = api.run_lbfgs((X, y), losses.LogisticGradient(),
                              prox.L1Updater(), mesh=mesh8, **kw)
        assert int(res_m.num_iters) == int(res_1.num_iters)
        np.testing.assert_allclose(np.asarray(res_m.loss_history),
                                   np.asarray(res_1.loss_history),
                                   rtol=1e-8, atol=1e-11)
        np.testing.assert_allclose(np.asarray(res_m.weights),
                                   np.asarray(res_1.weights),
                                   rtol=1e-7, atol=1e-10)

    def test_host_twin_matches_fused(self, rng):
        """run_owlqn_host mirrors the fused driver's decisions (x64:
        branch-identical, like the smooth host twin)."""
        from spark_agd_tpu.core import (host_lbfgs,
                                        lbfgs as lbfgs_lib, smooth)

        X, y = logistic_problem(rng, n=250, d=9)
        sm = smooth.make_smooth(losses.LogisticGradient(),
                                jnp.asarray(X), jnp.asarray(y))
        cfg = lbfgs_lib.LBFGSConfig(convergence_tol=1e-11,
                                    num_iterations=80)
        fused = jax.jit(lambda w: lbfgs_lib.run_owlqn(sm, w, 0.06,
                                                      cfg))(
            jnp.zeros(9))
        host = host_lbfgs.run_owlqn_host(sm, jnp.zeros(9), 0.06, cfg)
        kf = int(fused.num_iters)
        assert host.num_iters == kf
        np.testing.assert_allclose(
            host.loss_history,
            np.asarray(fused.loss_history)[:kf + 1], rtol=1e-12)
        np.testing.assert_allclose(np.asarray(host.weights),
                                   np.asarray(fused.weights),
                                   rtol=1e-10, atol=1e-12)
        assert host.num_fn_evals == int(fused.num_fn_evals)

    def test_streamed_l1_matches_in_memory(self, rng):
        """Streamed macro-batch OWL-QN == the fused in-memory L1 fit —
        larger-than-HBM L1 paths for the quasi-Newton member."""
        from spark_agd_tpu.core import host_lbfgs, lbfgs as lbfgs_lib
        from spark_agd_tpu.data import streaming

        X, y = logistic_problem(rng, n=330, d=8)
        ds = streaming.StreamingDataset.from_arrays(X, y, batch_rows=64)
        sm, _ = streaming.make_streaming_smooth(
            losses.LogisticGradient(), ds, pad_to=64)
        cfg = lbfgs_lib.LBFGSConfig(convergence_tol=1e-10,
                                    num_iterations=60)
        res_s = host_lbfgs.run_owlqn_host(sm, jnp.zeros(8), 0.07, cfg)
        res_f = api.run_lbfgs((X, y), losses.LogisticGradient(),
                              prox.L1Updater(), reg_param=0.07,
                              convergence_tol=1e-10, num_iterations=60,
                              initial_weights=np.zeros(8), mesh=False)
        assert res_s.num_iters == int(res_f.num_iters)
        np.testing.assert_allclose(np.asarray(res_s.weights),
                                   np.asarray(res_f.weights),
                                   rtol=1e-7, atol=1e-9)

    def test_host_warm_resume_is_exact(self, rng):
        from spark_agd_tpu.core import (host_lbfgs,
                                        lbfgs as lbfgs_lib, smooth)

        X, y = logistic_problem(rng, n=200, d=7)
        sm = smooth.make_smooth(losses.LogisticGradient(),
                                jnp.asarray(X), jnp.asarray(y))
        cfg = lbfgs_lib.LBFGSConfig(convergence_tol=1e-11,
                                    num_iterations=50)
        full = host_lbfgs.run_owlqn_host(sm, jnp.zeros(7), 0.05, cfg)
        assert full.num_iters >= 4
        cfg3 = lbfgs_lib.LBFGSConfig(convergence_tol=1e-11,
                                     num_iterations=3)
        s1 = host_lbfgs.run_owlqn_host(sm, jnp.zeros(7), 0.05, cfg3)
        # from_result picks the SMOOTH part via final_f_smooth (the
        # history holds F = f + L1), so the carry round-trips directly
        warm = host_lbfgs.HostLBFGSWarm.from_result(s1)
        s2 = host_lbfgs.run_owlqn_host(sm, jnp.zeros(7), 0.05, cfg,
                                       warm=warm)
        assert 3 + s2.num_iters == full.num_iters
        np.testing.assert_array_equal(np.asarray(s2.weights),
                                      np.asarray(full.weights))

    def test_l1_zero_is_plain_lbfgs(self, rng):
        """ElasticNet with l1_ratio=0 dispatches to the smooth driver
        and matches an explicit L2 run exactly."""
        X, y = logistic_problem(rng, n=200, d=6)
        kw = dict(reg_param=0.1, convergence_tol=1e-10,
                  num_iterations=100, initial_weights=np.zeros(6),
                  mesh=False)
        r_en = api.run_lbfgs((X, y), losses.LogisticGradient(),
                             prox.ElasticNetProx(l1_ratio=0.0), **kw)
        r_l2 = api.run_lbfgs((X, y), losses.LogisticGradient(),
                             prox.L2Prox(), **kw)
        np.testing.assert_array_equal(np.asarray(r_en.weights),
                                      np.asarray(r_l2.weights))


class TestSweep:
    """make_lbfgs_sweep_runner: K regularization lanes of the fused
    quasi-Newton loop in one compiled program."""

    def test_lanes_match_individual_fits(self, rng):
        X, y = logistic_problem(rng, n=250, d=8)
        regs = [0.01, 0.1, 1.0]
        fit = api.make_lbfgs_sweep_runner(
            (X, y), losses.LogisticGradient(), prox.SquaredL2Updater(),
            convergence_tol=1e-10, num_iterations=80, mesh=False)
        res = fit(np.zeros(8), regs)
        assert np.asarray(res.weights).shape == (3, 8)
        for k, reg in enumerate(regs):
            solo = api.run_lbfgs(
                (X, y), losses.LogisticGradient(),
                prox.SquaredL2Updater(), reg_param=reg,
                convergence_tol=1e-10, num_iterations=80,
                initial_weights=np.zeros(8), mesh=False)
            assert int(res.num_iters[k]) == int(solo.num_iters)
            np.testing.assert_allclose(np.asarray(res.weights)[k],
                                       np.asarray(solo.weights),
                                       rtol=1e-9, atol=1e-11)

    def test_mesh_matches_single_device(self, rng, mesh8):
        X, y = logistic_problem(rng, n=300, d=10)
        regs = [0.05, 0.5]
        kw = dict(convergence_tol=0.0, num_iterations=6)
        fit_m = api.make_lbfgs_sweep_runner(
            (X, y), losses.LogisticGradient(), prox.L2Prox(),
            mesh=mesh8, **kw)
        fit_1 = api.make_lbfgs_sweep_runner(
            (X, y), losses.LogisticGradient(), prox.L2Prox(),
            mesh=False, **kw)
        res_m = fit_m(np.zeros(10), regs)
        res_1 = fit_1(np.zeros(10), regs)
        np.testing.assert_array_equal(np.asarray(res_m.num_iters),
                                      np.asarray(res_1.num_iters))
        np.testing.assert_allclose(np.asarray(res_m.loss_history),
                                   np.asarray(res_1.loss_history),
                                   rtol=1e-8, atol=1e-11)
        np.testing.assert_allclose(np.asarray(res_m.weights),
                                   np.asarray(res_1.weights),
                                   rtol=1e-7, atol=1e-10)

    def test_l1_rejected_with_guidance(self, rng):
        X, y = logistic_problem(rng, n=60, d=4)
        with pytest.raises(ValueError, match="smooth penalty"):
            api.make_lbfgs_sweep_runner(
                (X, y), losses.LogisticGradient(), prox.L1Updater(),
                mesh=False)

    def test_trainer_train_path_with_lbfgs_seat(self, rng):
        """GLM train_path now works from the LBFGS seat (the class
        gained sweep), returning one typed model per strength."""
        from spark_agd_tpu import models

        X, y = logistic_problem(rng, n=200, d=3)
        lr = models.LogisticRegressionWithLBFGS()
        lr.optimizer.set_num_iterations(40).set_convergence_tol(1e-9)
        lr.optimizer.set_mesh(False)
        ms, res = lr.train_path(X, y, [0.01, 0.5])
        assert len(ms) == 2
        preds = np.asarray(ms[0].predict(X))
        assert preds.shape == (200,)


class TestStreamedMultiLane:
    """run_lbfgs_host_multi / api.streaming_lbfgs_sweep: K lock-step
    lanes over one multi-evaluation per round.  The per-lane contract
    is the run_agd_host_multi standard: EXACT equality with solo host
    runs of the same objective."""

    def test_lanes_exactly_match_solo_runs(self, rng):
        from spark_agd_tpu.core import host_lbfgs, lbfgs as lbfgs_lib
        from spark_agd_tpu.core import smooth
        from spark_agd_tpu.core import tvec

        X, y = logistic_problem(rng, n=280, d=9)
        regs = [0.01, 0.1, 1.0]
        sm = smooth.make_smooth(losses.LogisticGradient(),
                                jnp.asarray(X), jnp.asarray(y))
        cfg = lbfgs_lib.LBFGSConfig(convergence_tol=1e-10,
                                    num_iterations=60)

        def obj_k(reg):
            def obj(w):
                f, g = sm(w)
                pv, pg = prox.SquaredL2Updater().smooth_penalty(w, reg)
                return f + pv, tvec.add(g, pg)
            return obj

        def objective_multi(W):
            fs, gs = jax.vmap(
                lambda wk, rk: obj_k(rk)(wk))(W, jnp.asarray(regs))
            return fs, gs

        multi = host_lbfgs.run_lbfgs_host_multi(
            objective_multi, jnp.zeros((3, 9)), cfg)
        total_evals = 0
        for k, reg in enumerate(regs):
            solo = host_lbfgs.run_lbfgs_host(obj_k(reg), jnp.zeros(9),
                                             cfg)
            assert int(multi.num_iters[k]) == solo.num_iters, k
            assert bool(multi.converged[k]) == solo.converged
            assert int(multi.num_fn_evals[k]) == solo.num_fn_evals
            # decisions are identical by construction (same generator);
            # VALUES agree to the vmapped kernel's own rounding (~1
            # ulp: vmap can fuse the reduction differently than the
            # solo kernel)
            np.testing.assert_allclose(
                multi.loss_history[k][:solo.num_iters + 1],
                solo.loss_history, rtol=1e-13, atol=1e-15)
            np.testing.assert_allclose(
                np.asarray(multi.weights)[k], np.asarray(solo.weights),
                rtol=1e-12, atol=1e-14)
            total_evals += solo.num_fn_evals
        # the lock-step claim: rounds = max lane evals, not the sum
        assert multi.eval_rounds == int(np.max(multi.num_fn_evals))
        assert multi.eval_rounds < total_evals

    def test_streaming_sweep_api(self, rng):
        from spark_agd_tpu.data import streaming

        X, y = logistic_problem(rng, n=300, d=8)
        regs = [0.02, 0.2]
        ds = streaming.StreamingDataset.from_arrays(X, y, batch_rows=64)
        res = api.streaming_lbfgs_sweep(
            ds, losses.LogisticGradient(), prox.SquaredL2Updater(),
            regs, convergence_tol=1e-10, num_iterations=60,
            initial_weights=np.zeros(8))
        # each lane == the fused in-memory fit at its strength
        for k, reg in enumerate(regs):
            fused = api.run_lbfgs(
                (X, y), losses.LogisticGradient(),
                prox.SquaredL2Updater(), reg_param=reg,
                convergence_tol=1e-10, num_iterations=60,
                initial_weights=np.zeros(8), mesh=False)
            assert int(res.num_iters[k]) == int(fused.num_iters)
            np.testing.assert_allclose(
                np.asarray(res.weights)[k], np.asarray(fused.weights),
                rtol=1e-9, atol=1e-12)

    def test_l1_rejected(self, rng):
        from spark_agd_tpu.data import streaming

        X, y = logistic_problem(rng, n=60, d=4)
        ds = streaming.StreamingDataset.from_arrays(X, y, batch_rows=32)
        with pytest.raises(ValueError, match="smooth penalty"):
            api.streaming_lbfgs_sweep(
                ds, losses.LogisticGradient(), prox.L1Updater(),
                [0.1], initial_weights=np.zeros(4))


class TestQuasiNewtonFuzz:
    """Randomized knob-space parity for the quasi-Newton drivers:
    single-device vs 8-way mesh on the SAME problem (the
    test_grid_mesh::TestMeshFuzz pattern).  f64: reduction noise is
    ~1e-16, so near-strict trajectory equality is the invariant —
    guarding knob interactions (m, tol, penalty type, dispatch) the
    enumerated tests don't cover."""

    @pytest.mark.parametrize("case", range(8))
    def test_random_config_parity(self, case, mesh8):
        r = np.random.default_rng(9100 + case)
        n, d = int(r.integers(150, 450)), int(r.integers(4, 16))
        X = r.standard_normal((n, d))
        yb = (r.random(n) < 0.5).astype(np.float64)
        grad = [losses.LogisticGradient(),
                losses.LeastSquaresGradient()][case % 2]
        # half the cases dispatch to OWL-QN (L1 / elastic net), half to
        # strong-Wolfe L-BFGS (L2 / identity)
        p, reg = [
            (prox.SquaredL2Updater(), float(r.uniform(0.01, 0.5))),
            (prox.L1Updater(), float(r.uniform(0.005, 0.1))),
            (prox.IdentityProx(), 0.0),
            (prox.ElasticNetProx(float(r.uniform(0.1, 0.9))),
             float(r.uniform(0.01, 0.3))),
        ][(case // 2) % 4]
        kw = dict(reg_param=reg,
                  num_corrections=int(r.integers(1, 12)),
                  convergence_tol=float(10.0 ** -r.integers(6, 11)),
                  num_iterations=int(r.integers(10, 60)),
                  initial_weights=r.standard_normal(d) * 0.1)
        res_1 = api.run_lbfgs((X, yb), grad, p, mesh=False, **kw)
        res_m = api.run_lbfgs((X, yb), grad, p, mesh=mesh8, **kw)
        assert int(res_m.num_iters) == int(res_1.num_iters), case
        assert bool(res_m.converged) == bool(res_1.converged)
        assert bool(res_m.ls_failed) == bool(res_1.ls_failed)
        k = int(res_1.num_iters)
        np.testing.assert_allclose(
            np.asarray(res_m.loss_history)[:k + 1],
            np.asarray(res_1.loss_history)[:k + 1],
            rtol=1e-10, atol=1e-13, err_msg=f"case {case}")
        np.testing.assert_allclose(
            np.asarray(res_m.weights), np.asarray(res_1.weights),
            rtol=1e-8, atol=1e-11, err_msg=f"case {case}")


class TestLsStopReason:
    """``ls_failed`` split into diagnosable stop reasons (VERDICT r3
    weak #3 / item 4): each code manufactured deliberately, the host
    twin classifying identically, clean runs reporting none, and the
    bench artifact carrying the name.  Pin: Breeze folds every such
    outcome into one ``LineSearchFailed`` throw — the split is the
    diagnostic the round-3 artifacts lacked."""

    @staticmethod
    def _noise_floor_objective(np_mod):
        """Quadratic whose LOSS is quantized coarser than its gradient
        — near the optimum every trial's f is bit-identical while the
        gradient still points downhill, so no Wolfe point exists: the
        benign noise-floor stall (what a f32 sum-reduction does to a
        converged logistic loss)."""
        def obj(w):
            r = (w - 1.0).astype(np_mod.float32)
            f = (r * r).sum()
            return np_mod.round(f * 1e4) / 1e4, 2.0 * r

        return obj

    @staticmethod
    def _linear_objective(np_mod):
        """Constant-slope |w|: Armijo always holds, the curvature
        condition never can, so the bracket phase grows until its
        budget dies mid-descent."""
        def obj(w):
            return np_mod.abs(w).sum(), np_mod.sign(w)

        return obj

    @staticmethod
    def _steep_objective(np_mod):
        """1e8·‖w‖²: the unit first trial overshoots so far that 12
        bisections cannot reach the Wolfe point — zoom exhausts
        mid-descent."""
        def obj(w):
            return 1e8 * (w * w).sum(), 2e8 * w

        return obj

    def test_noise_floor_f32(self):
        from spark_agd_tpu.core import lbfgs as lb

        cfg = lb.LBFGSConfig(convergence_tol=-1.0, num_iterations=200)
        w0 = jnp.full((4,), 1.0 + 1e-4, jnp.float32)
        res = jax.jit(lambda w: lb.run_lbfgs(
            self._noise_floor_objective(jnp), w, cfg))(w0)
        assert bool(res.ls_failed)
        assert int(res.ls_stop_reason) == lb.LS_STOP_NOISE_FLOOR
        assert lb.ls_stop_reason_name(res.ls_stop_reason) == \
            "no_progress_at_noise_floor"

    def test_bracket_exhausted_mid_descent(self):
        from spark_agd_tpu.core import lbfgs as lb

        cfg = lb.LBFGSConfig(num_iterations=3)
        w0 = jnp.full((4,), 1e7, jnp.float32)
        res = jax.jit(lambda w: lb.run_lbfgs(
            self._linear_objective(jnp), w, cfg))(w0)
        assert bool(res.ls_failed)
        assert int(res.ls_stop_reason) == lb.LS_STOP_BRACKET

    def test_zoom_exhausted_mid_descent(self):
        from spark_agd_tpu.core import lbfgs as lb

        cfg = lb.LBFGSConfig(num_iterations=3)
        w0 = jnp.ones((4,), jnp.float32)
        res = jax.jit(lambda w: lb.run_lbfgs(
            self._steep_objective(jnp), w, cfg))(w0)
        assert bool(res.ls_failed)
        assert int(res.ls_stop_reason) == lb.LS_STOP_ZOOM

    def test_host_twin_classifies_identically(self):
        from spark_agd_tpu.core import host_lbfgs, lbfgs as lb

        cases = [
            (self._noise_floor_objective, jnp.full((4,), 1.0 + 1e-4,
                                                   jnp.float32),
             lb.LBFGSConfig(convergence_tol=-1.0, num_iterations=200)),
            (self._linear_objective, jnp.full((4,), 1e7, jnp.float32),
             lb.LBFGSConfig(num_iterations=3)),
            (self._steep_objective, jnp.ones((4,), jnp.float32),
             lb.LBFGSConfig(num_iterations=3)),
        ]
        for mk, w0, cfg in cases:
            fused = jax.jit(lambda w, o=mk(jnp), c=cfg:
                            lb.run_lbfgs(o, w, c))(w0)
            host = host_lbfgs.run_lbfgs_host(mk(np), np.asarray(w0),
                                             cfg)
            assert bool(host.ls_failed) and bool(fused.ls_failed)
            assert int(host.ls_stop_reason) == \
                int(fused.ls_stop_reason), mk.__name__

    def test_owlqn_armijo_exhausted(self):
        from spark_agd_tpu.core import lbfgs as lb

        # optimum at 0.5, NOT on the orthant boundary: every steep
        # overshoot clips to w=0 where F is no better, so no trial can
        # satisfy Armijo within the budget
        def smooth(w):
            r = w - 0.5
            return 1e8 * (r * r).sum(), 2e8 * r

        cfg = lb.LBFGSConfig(num_iterations=3, max_ls_steps=4)
        res = jax.jit(lambda w: lb.run_owlqn(smooth, w, 0.1, cfg))(
            jnp.ones((4,), jnp.float32))
        assert bool(res.ls_failed)
        assert int(res.ls_stop_reason) == lb.LS_STOP_ARMIJO

    def test_owlqn_noise_floor(self):
        from spark_agd_tpu.core import host_lbfgs, lbfgs as lb

        cfg = lb.LBFGSConfig(convergence_tol=-1.0, num_iterations=200)
        w0 = jnp.full((4,), 1.0 + 1e-4, jnp.float32)
        res = jax.jit(lambda w: lb.run_owlqn(
            self._noise_floor_objective(jnp), w, 0.0, cfg))(w0)
        assert bool(res.ls_failed)
        assert int(res.ls_stop_reason) == lb.LS_STOP_NOISE_FLOOR
        hres = host_lbfgs.run_owlqn_host(
            self._noise_floor_objective(np), np.asarray(w0), 0.0, cfg)
        assert bool(hres.ls_failed)
        assert int(hres.ls_stop_reason) == lb.LS_STOP_NOISE_FLOOR

    def test_clean_runs_report_none(self, rng):
        from spark_agd_tpu.core import lbfgs as lb

        X, y = logistic_problem(rng)
        res = api.run_lbfgs((X, y), losses.LogisticGradient(),
                            prox.SquaredL2Updater(), reg_param=0.05,
                            initial_weights=np.zeros(X.shape[1]))
        assert bool(res.converged) and not bool(res.ls_failed)
        assert int(res.ls_stop_reason) == lb.LS_STOP_NONE

    def test_bench_artifact_carries_reason_name(self, rng):
        from benchmarks import run as brun

        cfg = brun.CONFIGS[4]  # dense softmax-free small config
        data = cfg.make_data(0.0)  # scale floor: minimum rows
        w0 = cfg.make_w0(data[0])
        row = brun.lbfgs_comparison(cfg, data, w0, iters=3,
                                    agd_final_loss=0.0)
        assert row["lbfgs_ls_stop_reason"] in lb_reason_names()


def lb_reason_names():
    from spark_agd_tpu.core import lbfgs as lb

    return lb.LS_STOP_REASONS


class TestMesh:
    def test_mesh_matches_single_device(self, rng, mesh8):
        X, y = logistic_problem(rng, n=300, d=12)  # 300: padding live
        kw = dict(reg_param=0.05, convergence_tol=1e-10,
                  num_iterations=100, initial_weights=np.zeros(12))
        res_1 = api.run_lbfgs((X, y), losses.LogisticGradient(),
                              prox.SquaredL2Updater(), mesh=False, **kw)
        res_m = api.run_lbfgs((X, y), losses.LogisticGradient(),
                              prox.SquaredL2Updater(), mesh=mesh8, **kw)
        assert int(res_m.num_iters) == int(res_1.num_iters)
        np.testing.assert_allclose(np.asarray(res_m.loss_history),
                                   np.asarray(res_1.loss_history),
                                   rtol=1e-9, atol=1e-12)
        np.testing.assert_allclose(np.asarray(res_m.weights),
                                   np.asarray(res_1.weights),
                                   rtol=1e-8, atol=1e-10)

    def test_csr_mesh(self, rng, mesh8):
        n, d, npr = 120, 9, 3
        indptr = np.arange(n + 1) * npr
        X = sparse.CSRMatrix.from_csr_arrays(
            indptr, rng.integers(0, d, n * npr).astype(np.int32),
            rng.normal(size=n * npr), d)
        y = (rng.random(n) < 0.5).astype(np.float64)
        kw = dict(reg_param=0.1, convergence_tol=1e-10,
                  num_iterations=60, initial_weights=np.zeros(d))
        res_1 = api.run_lbfgs((X, y), losses.LogisticGradient(),
                              prox.SquaredL2Updater(), mesh=False, **kw)
        res_m = api.run_lbfgs((X, y), losses.LogisticGradient(),
                              prox.SquaredL2Updater(), mesh=mesh8, **kw)
        np.testing.assert_allclose(np.asarray(res_m.weights),
                                   np.asarray(res_1.weights),
                                   rtol=1e-7, atol=1e-9)

    def test_runner_reuse_compiles_once(self, rng, mesh8):
        X, y = logistic_problem(rng, n=160, d=8)
        fit = api.make_lbfgs_runner(
            (X, y), losses.LogisticGradient(),
            prox.SquaredL2Updater(), reg_param=0.1,
            convergence_tol=1e-10, num_iterations=50, mesh=mesh8)
        r1 = fit(np.zeros(8))
        r2 = fit(np.ones(8) * 0.1)
        assert np.all(np.isfinite(np.asarray(r1.weights)))
        assert np.all(np.isfinite(np.asarray(r2.weights)))
        # different starts, same optimum (strongly convex objective)
        np.testing.assert_allclose(np.asarray(r1.weights),
                                   np.asarray(r2.weights), atol=1e-5)
