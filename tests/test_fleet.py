"""Serve-fleet tests: the latency-EWMA router, the replica wire
protocol, fleet chaos faults, and the traffic-shift gate (``serve/
fleet.py`` + ``serve/router.py``).

The contracts pinned here are the drill's story told at unit scale:
statistically-equal replicas share traffic (the spread band), a stuck
request hedges and the first answer wins, a dead replica is evicted
once and its in-flight requests retry transparently on a survivor
(predict is pure), a flooding tenant sheds TYPED while other tenants
keep flowing, verdict changes emit exactly once, an evicted index is
sticky until a fresh "ok" heartbeat proves life, and a torn published
generation never splits the fleet — every replica process falls back
to the same verifiable generation.  The drill tool gate
(``tools/fleet_drill.py``) rides at the bottom, chaos-drill style: the
reduced smoke in tier-1, the full soak behind ``-m 'fleet and slow'``.
"""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from spark_agd_tpu.models.glm import LogisticRegressionModel
from spark_agd_tpu.obs import InMemorySink, Telemetry, schema
from spark_agd_tpu.obs.perfgate import (FleetGateResult,
                                        format_fleet_report, gate_fleet)
from spark_agd_tpu.obs.sinks import JSONLSink
from spark_agd_tpu.resilience import chaos as chaos_mod
from spark_agd_tpu.resilience import manifest as mf
from spark_agd_tpu.resilience.chaos import (ChaosCampaign, ChaosSchedule,
                                            ScheduledFault)
from spark_agd_tpu.resilience.errors import ServeOverloaded
from spark_agd_tpu.serve import (FleetRouter, MicroBatchQueue,
                                 ModelRegistry, NoReplicasLeft,
                                 ReplicaHandle, ReplicaLatencyTracker,
                                 ReplicaServer, ServeEngine,
                                 discover_replicas)
from spark_agd_tpu.serve.fleet import replica_file_name

pytestmark = pytest.mark.fleet

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
DRILL = os.path.join(REPO_ROOT, "tools", "fleet_drill.py")

D = 8  # feature count every fleet fixture model shares


def _rng(seed=0):
    return np.random.default_rng(seed)


def _logistic(seed=3):
    r = _rng(seed)
    return LogisticRegressionModel(
        (r.normal(size=D) * 0.8).astype(np.float32), 0.25)


def _proba_ref(X, model):
    """f64 reference for op="predict_proba" through the f32 wire."""
    Xd = np.asarray(X, dtype=np.float32).astype(np.float64)
    w = np.asarray(model.weights, dtype=np.float64)
    z = Xd @ w + float(model.intercept)
    return 1.0 / (1.0 + np.exp(-z))


def _tel():
    return Telemetry([InMemorySink()])


def _records(tel):
    return tel.bus.sinks[0].records


def _by_kind(tel, kind, **match):
    return [r for r in _records(tel) if r.get("kind") == kind
            and all(r.get(k) == v for k, v in match.items())]


class FakeBackend:
    """In-process router backend: the ``predict`` contract of
    ``ReplicaHandle`` without a socket.  ``latency_s`` sleeps,
    ``gate`` blocks until set (deterministic concurrency tests),
    ``fail`` raises ConnectionError — a dead replica."""

    def __init__(self, replica, *, latency_s=0.0, generation=1,
                 fail=False, gate=None):
        self.replica = int(replica)
        self.latency_s = float(latency_s)
        self.generation = int(generation)
        self.fail = fail
        self.gate = gate
        self.calls = 0
        self.in_flight = 0
        self._lock = threading.Lock()

    def predict(self, rows, op="predict", tenant=None, timeout=30.0):
        with self._lock:
            self.calls += 1
            self.in_flight += 1
        try:
            if self.fail:
                raise ConnectionError(
                    f"fake replica {self.replica} is dead")
            if self.gate is not None:
                self.gate.wait(timeout)
            if self.latency_s:
                time.sleep(self.latency_s)
            n = int(getattr(rows, "shape", [len(rows)])[0])
            return {"values": [0.5] * n,
                    "generation": self.generation,
                    "replica": self.replica,
                    "latency_ms": self.latency_s * 1e3}
        finally:
            with self._lock:
                self.in_flight -= 1


class FakeMonitor:
    """A ``HostMonitor.verdicts()`` stand-in the tests script."""

    def __init__(self, verdicts=None):
        self._verdicts = dict(verdicts or {})

    def set(self, replica, verdict):
        self._verdicts[int(replica)] = verdict

    def verdicts(self):
        return dict(self._verdicts)


# ---------------------------------------------------------------------------
class TestReplicaLatencyTracker:
    def test_ewma_math(self):
        t = ReplicaLatencyTracker(alpha=0.5, floor_ms=0.01)
        t.observe(0, 10.0)
        assert t.cost(0) == pytest.approx(10.0)
        t.observe(0, 20.0)
        assert t.cost(0) == pytest.approx(15.0)
        assert t.samples(0) == 2

    def test_unobserved_replica_costs_the_floor(self):
        t = ReplicaLatencyTracker(floor_ms=0.5)
        assert t.cost(7) == 0.5
        assert t.samples(7) == 0

    def test_forget_resets_to_optimistic_floor(self):
        t = ReplicaLatencyTracker(floor_ms=0.1)
        t.observe(2, 50.0)
        t.forget(2)
        assert t.cost(2) == 0.1
        assert t.samples(2) == 0

    def test_median_interpolates_and_starts_none(self):
        t = ReplicaLatencyTracker()
        assert t.median_ms() is None
        t.observe(0, 2.0)
        t.observe(1, 4.0)
        assert t.median_ms() == pytest.approx(3.0)
        t.observe(2, 10.0)
        assert t.median_ms() == pytest.approx(4.0)

    def test_floor_clamps_costs(self):
        t = ReplicaLatencyTracker(floor_ms=1.0)
        t.observe(0, 0.001)
        assert t.cost(0) == 1.0
        assert t.costs() == {0: 1.0}

    def test_alpha_validation(self):
        with pytest.raises(ValueError):
            ReplicaLatencyTracker(alpha=0.0)
        with pytest.raises(ValueError):
            ReplicaLatencyTracker(alpha=1.5)


# ---------------------------------------------------------------------------
class TestRouterRouting:
    def test_constructor_validation(self):
        b = {0: FakeBackend(0)}
        with pytest.raises(ValueError):
            FleetRouter(b, hedge_multiple=1.0)
        with pytest.raises(ValueError):
            FleetRouter(b, warm_every=1)
        with pytest.raises(ValueError):
            FleetRouter(b, spread_tolerance=0.5)
        with pytest.raises(ValueError):
            FleetRouter(b, tenant_max_outstanding=0)

    def test_spread_band_shares_traffic_across_equals(self):
        backends = {r: FakeBackend(r) for r in range(3)}
        with FleetRouter(backends) as router:
            for _ in range(30):
                res = router.request(np.ones((2, D)))
                assert res.values == [0.5, 0.5]
            served = router.stats.per_replica
        assert sorted(served) == [0, 1, 2]
        assert all(served[r] >= 3 for r in range(3)), served
        assert router.stats.requests == 30

    def test_warm_turn_probes_the_most_expensive_member(self):
        router = FleetRouter({r: FakeBackend(r) for r in range(3)},
                             warm_every=2)
        router.tracker.observe(0, 50.0)
        first = router._candidates(set())   # tick 1: normal ranking
        second = router._candidates(set())  # tick 2: warm probe
        assert first[0] != 0
        assert second[0] == 0
        assert sorted(second) == [0, 1, 2]
        router.close()

    def test_route_records_are_schema_valid(self):
        tel = _tel()
        with FleetRouter({0: FakeBackend(0)}, telemetry=tel) as router:
            router.request(np.ones((3, D)), tenant="acme")
        routes = _by_kind(tel, "fleet_route", decision="route")
        assert len(routes) == 1
        rec = routes[0]
        assert rec["winner"] == 0 and rec["rows"] == 3
        assert rec["tenant"] == "acme"
        for r in _records(tel):
            assert schema.validate_record(r) == [], r


class TestRouterHedge:
    def test_stuck_primary_hedges_and_first_answer_wins(self):
        tel = _tel()
        backends = {0: FakeBackend(0, latency_s=0.25),
                    1: FakeBackend(1)}
        with FleetRouter(backends, telemetry=tel,
                         hedge_multiple=2.0, hedge_floor_ms=1.0,
                         min_hedge_samples=2,
                         spread_tolerance=1.5) as router:
            # seed the tracker so 0 is the cheap primary and the
            # fleet median is trusted (2 samples >= min_hedge_samples)
            router.tracker.observe(0, 1.0)
            router.tracker.observe(1, 5.0)
            res = router.request(np.ones((2, D)))
        assert res.hedged is True
        assert res.replica == 1          # the hedge answered first
        assert res.values == [0.5, 0.5]  # nothing dropped on the way
        assert router.stats.hedges == 1
        assert router.stats.hedges_won == 1
        hedges = _by_kind(tel, "recovery", action="request_hedge")
        assert len(hedges) == 1 and hedges[0]["process"] == 1
        routed = _by_kind(tel, "fleet_route", decision="hedge")
        assert routed and routed[0]["winner"] == 1
        assert routed[0]["replica"] == 0

    def test_no_hedge_below_the_sample_floor(self):
        router = FleetRouter({0: FakeBackend(0), 1: FakeBackend(1)},
                             min_hedge_samples=8)
        router.tracker.observe(0, 1.0)
        assert router._hedge_wait_s() is None
        router.tracker.observe(1, 1.0)
        assert router._hedge_wait_s() is None  # 2 samples < 8
        router.close()


class TestRouterRetryEvict:
    def test_dead_primary_evicts_once_and_retries_transparently(self):
        tel = _tel()
        backends = {0: FakeBackend(0, fail=True), 1: FakeBackend(1)}
        with FleetRouter(backends, telemetry=tel) as router:
            router.tracker.observe(0, 1.0)   # 0 looks cheapest
            router.tracker.observe(1, 5.0)
            res = router.request(np.ones((1, D)))
        assert res.replica == 1 and res.retried and res.attempt == 2
        assert router.stats.retries == 1
        assert router.stats.evictions == 1
        assert router.members == [1]
        evicts = _by_kind(tel, "recovery", action="replica_evict")
        assert len(evicts) == 1 and evicts[0]["process"] == 0
        retries = _by_kind(tel, "recovery", action="request_retry")
        assert len(retries) == 1
        assert _by_kind(tel, "fleet_route", decision="retry")

    def test_everything_dead_raises_typed_transient(self):
        backends = {r: FakeBackend(r, fail=True) for r in range(2)}
        with FleetRouter(backends) as router:
            with pytest.raises(NoReplicasLeft) as ei:
                router.request(np.ones((1, D)))
        assert isinstance(ei.value, ConnectionError)  # TRANSIENT taxon
        assert router.stats.evictions == 2


class TestRouterTenantAdmission:
    def test_flooding_tenant_sheds_typed_while_others_flow(self):
        tel = _tel()
        gate = threading.Event()
        slow = FakeBackend(0, gate=gate)
        with FleetRouter({0: slow}, telemetry=tel,
                         tenant_max_outstanding=1) as router:
            results = {}

            def hold():
                results["alice"] = router.request(
                    np.ones((1, D)), tenant="alice")

            t = threading.Thread(target=hold)
            t.start()
            for _ in range(200):   # wait until alice is in flight
                if slow.in_flight >= 1:
                    break
                time.sleep(0.005)
            assert slow.in_flight >= 1
            with pytest.raises(ServeOverloaded) as ei:
                router.request(np.ones((1, D)), tenant="alice")
            gate.set()
            t.join(timeout=5)
            # the well-behaved tenant was never capped
            bob = router.request(np.ones((1, D)), tenant="bob")
        assert "admission cap" in str(ei.value)
        assert ei.value.limit_rows == 1
        assert results["alice"].values == [0.5]
        assert bob.values == [0.5]
        assert router.stats.shed == {"alice": 1}
        sheds = _by_kind(tel, "fleet_route", decision="shed_tenant")
        assert len(sheds) == 1 and sheds[0]["tenant"] == "alice"
        reg = tel.registry
        assert reg.counter("serve.tenant_rejected").value == 1
        assert reg.counter("serve.tenant_rejected.alice").value == 1


class TestRouterVerdicts:
    def test_verdict_sync_emits_changes_only_and_evicts_lost(self):
        tel = _tel()
        monitor = FakeMonitor({0: "ok", 1: "slow"})
        backends = {0: FakeBackend(0), 1: FakeBackend(1)}
        router = FleetRouter(backends, monitor=monitor, telemetry=tel)
        assert router.verdict_sync() == {0: "ok", 1: "slow"}
        assert len(_by_kind(tel, "replica_verdict")) == 2
        router.verdict_sync()   # no change -> no new records
        assert len(_by_kind(tel, "replica_verdict")) == 2
        monitor.set(1, "lost")
        router.verdict_sync()
        verdicts = _by_kind(tel, "replica_verdict", replica=1)
        assert [v["verdict"] for v in verdicts] == ["slow", "lost"]
        assert verdicts[-1]["previous"] == "slow"
        assert router.members == [0]
        assert _by_kind(tel, "recovery", action="replica_evict")
        for r in _records(tel):
            assert schema.validate_record(r) == [], r
        router.close()

    def test_slow_is_deprioritized_but_kept_warm(self):
        monitor = FakeMonitor({0: "slow", 1: "ok"})
        router = FleetRouter({0: FakeBackend(0), 1: FakeBackend(1)},
                             monitor=monitor)
        router.verdict_sync()
        ranked = router._candidates(set())
        assert ranked == [1, 0]  # slow trails but is still a member
        router.close()

    def test_refresh_membership_join_and_leave(self):
        b = {r: FakeBackend(r) for r in range(2)}
        router = FleetRouter({0: b[0]})
        delta = router.refresh_membership({0: b[0], 1: b[1]})
        assert delta == {"joined": [1], "left": []}
        assert router.members == [0, 1]
        delta = router.refresh_membership({0: b[0]})
        assert delta == {"joined": [], "left": [1]}
        assert router.members == [0]
        router.close()

    def test_evicted_index_is_sticky_until_a_fresh_ok(self):
        monitor = FakeMonitor({0: "ok", 1: "lost"})
        b = {r: FakeBackend(r) for r in range(2)}
        router = FleetRouter(dict(b), monitor=monitor)
        router.verdict_sync()   # evicts 1
        assert router.members == [0]
        # the crashed replica's leftover files age through "slow" —
        # a membership refresh must NOT resurrect it on that verdict
        monitor.set(1, "slow")
        delta = router.refresh_membership(dict(b))
        assert delta["joined"] == [] and router.members == [0]
        # a fresh heartbeat ("ok") is proof of life: now it rejoins
        monitor.set(1, "ok")
        delta = router.refresh_membership(dict(b))
        assert delta["joined"] == [1] and router.members == [0, 1]
        router.close()


# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def fleet_engine():
    return ServeEngine(_logistic(), generation=1, max_batch=8,
                       min_bucket=4)


class TestReplicaWireProtocol:
    def test_roundtrip_values_match_the_engine(self, tmp_path,
                                               fleet_engine):
        fleet_dir = str(tmp_path / "fleet")
        model = _logistic()
        with ReplicaServer(fleet_dir, 0, fleet_engine) as server:
            handles = discover_replicas(fleet_dir)
            assert list(handles) == [0]
            h = handles[0]
            assert h.port == server.port
            X = _rng(5).normal(size=(5, D)).astype(np.float32)
            resp = h.predict(X, op="predict_proba")
            assert resp["status"] == "ok"
            assert resp["generation"] == 1 and resp["replica"] == 0
            np.testing.assert_allclose(
                np.asarray(resp["values"]), _proba_ref(X, model),
                atol=1e-4)
            assert server.requests_seen == 1

    def test_trace_context_rides_the_wire(self, tmp_path):
        server_tel = _tel()
        engine = ServeEngine(_logistic(), generation=1, max_batch=8,
                             min_bucket=4)
        fleet_dir = str(tmp_path / "fleet")
        client_tel = _tel()
        with ReplicaServer(fleet_dir, 0, engine,
                           telemetry=server_tel):
            h = discover_replicas(fleet_dir)[0]
            with client_tel.trace_span("client_request") as ctx:
                h.predict(np.ones((2, D), dtype=np.float32))
        # the replica's serve_request span joined the CLIENT's tree
        spans = [r for r in _records(server_tel)
                 if r.get("name") == "serve_request"]
        assert spans
        assert all(r.get("trace_id") == ctx.trace_id for r in spans)

    def test_replica_side_shed_is_typed_across_the_wire(self, tmp_path,
                                                        fleet_engine):
        fleet_dir = str(tmp_path / "fleet")
        with ReplicaServer(fleet_dir, 1, fleet_engine,
                           max_queue_rows=2):
            h = discover_replicas(fleet_dir)[1]
            with pytest.raises(ServeOverloaded) as ei:
                h.predict(np.ones((4, D), dtype=np.float32))
        assert ei.value.queued_rows == 4
        assert ei.value.limit_rows == 2
        assert "replica 1 shed" in str(ei.value)

    def test_bad_request_is_a_typed_error_reply(self, tmp_path,
                                                fleet_engine):
        fleet_dir = str(tmp_path / "fleet")
        with ReplicaServer(fleet_dir, 0, fleet_engine):
            h = discover_replicas(fleet_dir)[0]
            with pytest.raises(RuntimeError, match="replica 0 error"):
                h.predict(np.ones((2, D), dtype=np.float32),
                          op="no_such_op")

    def test_discovery_skips_torn_membership_files(self, tmp_path):
        fleet_dir = tmp_path / "fleet"
        fleet_dir.mkdir()
        (fleet_dir / replica_file_name(7)).write_text("{torn mid-wri")
        (fleet_dir / replica_file_name(3)).write_text(
            json.dumps({"replica": 3, "port": 12345}))
        (fleet_dir / "unrelated.txt").write_text("x")
        handles = discover_replicas(str(fleet_dir))
        assert list(handles) == [3]
        assert isinstance(handles[3], ReplicaHandle)
        assert handles[3].port == 12345

    def test_clean_stop_is_a_leave_not_a_crash(self, tmp_path,
                                               fleet_engine):
        fleet_dir = str(tmp_path / "fleet")
        server = ReplicaServer(fleet_dir, 2, fleet_engine).start()
        membership = server.membership_path
        beat_path = server.heartbeat.path
        assert os.path.exists(membership) and os.path.exists(beat_path)
        server.request_stop()   # the SIGTERM-handler half
        server.stop()
        # both announcements removed: discovery and the monitor agree
        # this replica LEFT (a crash would leave them to go stale)
        assert not os.path.exists(membership)
        assert not os.path.exists(beat_path)
        assert discover_replicas(fleet_dir) == {}


# ---------------------------------------------------------------------------
_RACE_WORKER = r"""
import json, sys, time
from spark_agd_tpu.serve.registry import ModelRegistry

reg = ModelRegistry(sys.argv[1])
print("READY", flush=True)
seen = []
for _ in range(int(sys.argv[2])):
    loaded = reg.load_newest()
    if loaded is not None:
        seen.append(int(loaded.generation))
    time.sleep(0.01)
print(json.dumps(sorted(set(seen))))
"""

_REFRESH_WORKER = r"""
import sys
from spark_agd_tpu.serve.registry import ModelRegistry

reg = ModelRegistry(sys.argv[1])
reg.refresh(None)
cur = reg.current
print(-1 if cur is None else int(cur.generation))
"""


def _spawn_worker(script, *args):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO_ROOT)
    return subprocess.Popen(
        [sys.executable, "-c", script, *[str(a) for a in args]],
        cwd=REPO_ROOT, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)


class TestRegistryFleetRaces:
    def test_concurrent_load_newest_never_sees_a_half_publish(
            self, tmp_path):
        reg_dir = str(tmp_path / "registry")
        registry = ModelRegistry(reg_dir)
        registry.publish(_logistic(1))
        workers = [_spawn_worker(_RACE_WORKER, reg_dir, 80)
                   for _ in range(3)]
        try:
            for p in workers:   # wait out the interpreter warmup
                assert p.stdout.readline().strip() == "READY"
            published = {1}
            for seed in (2, 3, 4, 5):
                published.add(registry.publish(_logistic(seed)))
                time.sleep(0.15)
            outs = [p.communicate(timeout=60) for p in workers]
        finally:
            for p in workers:
                p.kill()
        for p, (out, err) in zip(workers, outs):
            assert p.returncode == 0, err
            seen = set(json.loads(out.strip().splitlines()[-1]))
            # every generation a replica loaded mid-publish is a REAL
            # committed one — a torn half-publish is invisible
            assert seen, "worker never loaded a generation"
            assert seen <= published, (seen, published)

    def test_torn_generation_never_splits_the_fleet(self, tmp_path):
        reg_dir = str(tmp_path / "registry")
        tel = _tel()
        registry = ModelRegistry(reg_dir, telemetry=tel)
        registry.publish(_logistic(1))
        g2 = registry.publish(_logistic(2))
        shard = os.path.join(reg_dir, mf.shard_name(g2, 0))
        size = os.path.getsize(shard)
        with open(shard, "r+b") as f:   # tear the newest shard
            f.truncate(size // 2)

        def fleet_view():
            procs = [_spawn_worker(_REFRESH_WORKER, reg_dir)
                     for _ in range(2)]
            outs = [p.communicate(timeout=60) for p in procs]
            assert all(p.returncode == 0 for p in procs), outs
            return [int(out.strip().splitlines()[-1])
                    for out, _ in outs]

        # every replica process walks back to the SAME verifiable
        # generation: degraded in lockstep, never split
        assert fleet_view() == [1, 1]
        loaded = registry.load_newest()
        assert loaded is not None and loaded.generation == 1
        fallbacks = _by_kind(tel, "recovery",
                             action="checkpoint_fallback")
        assert fallbacks and fallbacks[0]["generation"] == g2
        # the next good publish re-converges the whole fleet forward
        g3 = registry.publish(_logistic(3))
        assert fleet_view() == [g3, g3]


# ---------------------------------------------------------------------------
class TestFleetChaos:
    def test_generate_fleet_is_deterministic_and_normalized(self):
        for seed in range(12):
            a = ChaosCampaign.generate_fleet(seed, requests=64,
                                             replica_count=3)
            b = ChaosCampaign.generate_fleet(seed, requests=64,
                                             replica_count=3)
            assert a.faults == b.faults
            victims = [f.process for f in a.faults]
            assert len(set(victims)) == len(victims)   # no double-hit
            assert 1 <= len(a.faults) <= 2             # >= 1 survivor
            for f in a.faults:
                assert f.kind in ("slow_replica", "kill_replica")
                assert 1 <= f.at_iter <= int(64 * 0.7)
                if f.kind == "slow_replica":
                    assert f.persist and 0.85 <= f.decay <= 1.0
                    assert 0.05 <= f.payload <= 0.2
                else:
                    assert not f.persist

    def test_generate_fleet_needs_a_survivor(self):
        with pytest.raises(ValueError):
            ChaosCampaign.generate_fleet(0, replica_count=1)

    def test_schedule_for_replica_filters_by_victim(self):
        camp = ChaosCampaign(
            seed=1, iters=10, process_count=3,
            faults=(ScheduledFault("slow_replica", at_iter=2,
                                   process=1, payload=0.01,
                                   persist=True),
                    ScheduledFault("kill_replica", at_iter=5,
                                   process=0)))
        sleeps = []
        sched1 = camp.schedule_for_replica(1, sleep=sleeps.append)
        for i in range(1, 7):
            sched1.before_request(i)
        assert [k for k, _ in sched1.fired] == ["slow_replica"] * 5
        bystander = camp.schedule_for_replica(2)
        assert bystander.exhausted
        for i in range(1, 7):
            bystander.before_request(i)
        assert bystander.fired == []

    def test_persistent_slow_replica_decays_per_firing(self):
        sleeps = []
        sched = ChaosSchedule(
            [ScheduledFault("slow_replica", at_iter=3, payload=0.1,
                            persist=True, decay=0.5)],
            sleep=sleeps.append)
        sched.before_request(1)
        assert sleeps == []
        sched.before_request(3)
        sched.before_request(4)
        assert sleeps == pytest.approx([0.1, 0.05])
        # persistent faults never pend: with no one-shots the schedule
        # reads exhausted yet keeps firing at every later request
        assert sched.exhausted
        sched.before_request(5)
        assert sleeps == pytest.approx([0.1, 0.05, 0.025])

    def test_one_shot_slow_replica_fires_once(self):
        sleeps = []
        sched = ChaosSchedule(
            [ScheduledFault("slow_replica", at_iter=2, payload=0.05)],
            sleep=sleeps.append)
        sched.before_request(2)
        sched.before_request(3)
        assert sleeps == [0.05]
        assert sched.exhausted

    def test_kill_replica_flushes_the_record_before_the_kill(
            self, monkeypatch):
        kills = []
        monkeypatch.setattr(chaos_mod.os, "kill",
                            lambda pid, sig: kills.append((pid, sig)))
        tel = _tel()
        sched = ChaosSchedule(
            [ScheduledFault("kill_replica", at_iter=2, process=1)],
            telemetry=tel)
        sched.before_request(1)
        assert kills == []
        sched.before_request(2)
        assert kills == [(os.getpid(), chaos_mod.signal_lib.SIGKILL)]
        recs = _by_kind(tel, "chaos", fault="kill_replica")
        assert len(recs) == 1 and recs[0]["process"] == 1

    def test_persist_is_a_slow_fault_modifier_only(self):
        with pytest.raises(ValueError, match="persist"):
            ScheduledFault("kill_replica", at_iter=1, persist=True)


# ---------------------------------------------------------------------------
class TestQueueFleetAttribution:
    def test_records_carry_replica_and_tenant(self, fleet_engine):
        tel = _tel()
        with MicroBatchQueue(fleet_engine, telemetry=tel, replica=5,
                             max_wait_us=0) as q:
            res = q.submit(np.ones((3, D), dtype=np.float32),
                           tenant="acme").result(timeout=10)
            assert res.rows == 3
            summary = q.latency_summary()
            recent = q.recent_latencies()
        oks = _by_kind(tel, "serve_request", status="ok")
        assert len(oks) == 1
        assert oks[0]["replica"] == 5 and oks[0]["tenant"] == "acme"
        assert summary["replica"] == 5
        assert recent == [pytest.approx(res.latency_ms)]
        for r in _records(tel):
            assert schema.validate_record(r) == [], r

    def test_depth_gauge_tracks_per_op_and_drains_to_zero(
            self, fleet_engine):
        tel = _tel()
        with MicroBatchQueue(fleet_engine, telemetry=tel,
                             max_wait_us=0) as q:
            q.submit(np.ones((2, D), dtype=np.float32),
                     op="predict_proba").result(timeout=10)
        gauge = tel.registry.gauge("serve.queue_depth.predict_proba")
        assert gauge.value == 0

    def test_tenant_attributed_rejects_count(self, fleet_engine):
        tel = _tel()
        with MicroBatchQueue(fleet_engine, telemetry=tel,
                             max_queue_rows=2) as q:
            with pytest.raises(ServeOverloaded):
                q.submit(np.ones((4, D), dtype=np.float32),
                         tenant="mallory")
        rejected = _by_kind(tel, "serve_request", status="rejected")
        assert len(rejected) == 1
        assert rejected[0]["tenant"] == "mallory"
        reg = tel.registry
        assert reg.counter("serve.tenant_rejected").value == 1
        assert reg.counter("serve.tenant_rejected.mallory").value == 1


# ---------------------------------------------------------------------------
def _route_rec(ts, who, decision="route", **extra):
    rec = {"kind": "fleet_route", "decision": decision, "replica": who,
           "winner": who, "timestamp_unix": float(ts)}
    rec.update(extra)
    return rec


def _slow_chaos(ts, process):
    return {"kind": "chaos", "fault": "slow_replica",
            "process": process, "timestamp_unix": float(ts)}


def _synthetic_shift(pre_slow=5, pre_other=5, post_slow=1,
                     post_other=11):
    """pre/post routed counts for slow replica 1 around boundary 100."""
    recs = []
    t = 90.0
    for i in range(pre_slow):
        recs.append(_route_rec(t + i * 0.1, 1))
    for i in range(pre_other):
        recs.append(_route_rec(t + 5 + i * 0.1, 0))
    recs.append(_slow_chaos(100.0, 1))
    for i in range(post_slow):
        recs.append(_route_rec(101.0 + i * 0.1, 1))
    for i in range(post_other):
        recs.append(_route_rec(102.0 + i * 0.1, 0))
    return recs


class TestFleetGate:
    def test_traffic_shift_passes(self):
        result = gate_fleet(_synthetic_shift())
        assert isinstance(result, FleetGateResult)
        assert result.slow_replica == 1
        assert result.pre_share == pytest.approx(0.5)
        assert result.post_share == pytest.approx(1 / 12)
        assert result.ok and result.exit_code() == 0
        assert "FLEET GATE: pass" in format_fleet_report(result)
        rec = result.record(run_id="r1")
        assert schema.validate_record(rec) == [], rec

    def test_no_shift_fails(self):
        result = gate_fleet(_synthetic_shift(post_slow=6,
                                             post_other=6))
        assert not result.ok and not result.refused
        assert result.exit_code() == 1
        assert "FAIL" in format_fleet_report(result)

    def test_empty_stream_refuses_typed(self):
        result = gate_fleet([])
        assert result.refused and result.exit_code() == 2
        assert len(result.refusals) == 2
        assert "REFUSED" in format_fleet_report(result)

    def test_missing_chaos_boundary_refuses(self):
        result = gate_fleet([_route_rec(1.0 + i, 0)
                             for i in range(20)])
        assert result.exit_code() == 2
        assert any("slow_replica chaos" in r for r in result.refusals)

    def test_too_few_requests_on_a_side_refuses(self):
        result = gate_fleet(_synthetic_shift(post_slow=1,
                                             post_other=2))
        assert result.exit_code() == 2
        assert any("post-chaos" in r for r in result.refusals)

    def test_zero_pre_traffic_refuses(self):
        result = gate_fleet(_synthetic_shift(pre_slow=0,
                                             pre_other=10))
        assert result.exit_code() == 2
        assert any("cannot drop" in r for r in result.refusals)

    def test_eviction_contamination_refuses(self):
        recs = _synthetic_shift()
        recs.append({"kind": "recovery", "action": "replica_evict",
                     "process": 1, "timestamp_unix": 101.5})
        result = gate_fleet(recs)
        assert result.exit_code() == 2
        assert any("EVICTED" in r for r in result.refusals)

    def test_kill_contamination_refuses(self):
        recs = _synthetic_shift()
        recs.append({"kind": "chaos", "fault": "kill_replica",
                     "process": 1, "timestamp_unix": 101.5})
        result = gate_fleet(recs)
        assert result.exit_code() == 2
        assert any("KILLED" in r for r in result.refusals)

    def test_window_bounds_the_post_side(self):
        # inside the window the slow replica is drained; far past it
        # the traffic returns — an unbounded gate would read that as
        # "no shift", a windowed one must pass
        recs = _synthetic_shift()
        recs.extend(_route_rec(500.0 + i * 0.1, 1) for i in range(30))
        assert gate_fleet(recs).exit_code() == 1
        assert gate_fleet(recs, window_s=10.0).exit_code() == 0


class TestFleetReport:
    def test_fleet_rollup_cli(self, tmp_path, capsys):
        path = str(tmp_path / "fleet.jsonl")
        tel = Telemetry([JSONLSink(path)])
        tel.fleet_route(decision="route", replica=0, winner=0,
                        latency_ms=1.2, tool="serve.router")
        tel.fleet_route(decision="hedge", replica=0, winner=1,
                        latency_ms=9.0, tool="serve.router")
        tel.fleet_route(decision="shed_tenant", tenant="mallory",
                        tool="serve.router")
        tel.replica_verdict(replica=0, verdict="slow",
                            tool="serve.router")
        tel.recovery(action="replica_evict", process=2,
                     source="serve.router")
        tel.flush()
        from tools import agd_report

        assert agd_report.main(["--fleet", path]) == 0
        out = capsys.readouterr().out
        assert "== fleet" in out
        assert "mallory" in out


# ---------------------------------------------------------------------------
class TestFleetDrillTool:
    def test_smoke_soak_exits_zero(self, tmp_path):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, DRILL, "--smoke",
             "--out", str(tmp_path / "drill")],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True,
            timeout=600)
        assert proc.returncode == 0, (proc.stdout[-4000:]
                                      + proc.stderr[-4000:])
        assert "FLEET DRILL PASSED" in proc.stdout

    @pytest.mark.slow
    def test_full_soak_exits_zero(self, tmp_path):
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, DRILL,
             "--out", str(tmp_path / "drill")],
            cwd=REPO_ROOT, env=env, capture_output=True, text=True,
            timeout=600)
        assert proc.returncode == 0, (proc.stdout[-4000:]
                                      + proc.stderr[-4000:])
        assert "FLEET DRILL PASSED" in proc.stdout
