"""Compiled-program introspection + perf-regression gate.

CPU-deterministic coverage of ``obs.introspect`` (the ``ProgramCost``
census over AGD, L-BFGS, and the sharded paths — its collective counts
must agree with the raw HLO guards in ``test_hlo_cost_shape.py``) and
``obs.perfgate`` / ``tools/perf_gate.py`` (identical baseline/candidate
run records pass; a synthetically regressed candidate fails with a
diff table; cross-environment comparisons are refused).
"""

import importlib.util
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from spark_agd_tpu import api
from spark_agd_tpu.obs import (Telemetry, introspect, perfgate, schema)
from spark_agd_tpu.ops.losses import LogisticGradient
from spark_agd_tpu.ops.prox import L2Prox, SquaredL2Updater
from spark_agd_tpu.parallel import dist_smooth, mesh as mesh_lib


def _tiny_problem(n=64, d=8, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.standard_normal((n, d)).astype(np.float32)
    y = (rng.random(n) < 0.5).astype(np.float32)
    return X, y


# ---------------------------------------------------------------- census

class TestProgramCost:
    def test_agd_runner_census_cpu(self):
        X, y = _tiny_problem()
        fit = api.make_runner((X, y), LogisticGradient(), L2Prox(),
                              reg_param=0.1, num_iterations=5,
                              mesh=False)
        cost = introspect.analyze_runner(fit, np.zeros(X.shape[1],
                                                       np.float32))
        assert cost.label == "agd" and cost.backend == "cpu"
        # XLA CPU reports the cost model: a real fit does real FLOPs
        assert cost.flops and cost.flops > 0
        assert cost.bytes_accessed and cost.bytes_accessed > 0
        # memory analysis: the data rides as arguments (staged split)
        assert cost.argument_bytes >= X.nbytes
        assert cost.peak_hbm_bytes >= cost.argument_bytes
        # single-device program: no collectives at all
        assert cost.n_collectives == 0
        assert set(cost.collectives) == set(introspect.COLLECTIVE_OPS)
        assert cost.hlo_bytes > 0

    def test_lbfgs_runner_census_cpu(self):
        X, y = _tiny_problem()
        fit = api.make_lbfgs_runner((X, y), LogisticGradient(),
                                    SquaredL2Updater(), reg_param=0.1,
                                    num_iterations=5, mesh=False)
        cost = introspect.analyze_runner(
            fit, np.zeros(X.shape[1], np.float32))
        assert cost.label == "lbfgs"
        assert cost.flops and cost.flops > 0
        assert cost.n_collectives == 0

    def test_sharded_smooth_census_agrees_with_hlo_guard(self,
                                                         cpu_devices):
        """The census API and the raw HLO text count the same ops — the
        one-source-of-truth contract behind refactoring
        test_hlo_cost_shape.py onto introspect.count_ops."""
        X, y = _tiny_problem(n=256, d=16)
        mesh = mesh_lib.make_mesh({"data": 8})
        batch = mesh_lib.shard_batch(mesh, X, y)
        sm, _ = dist_smooth.make_dist_smooth(LogisticGradient(), batch,
                                             mesh=mesh)
        w0 = mesh_lib.replicate(jnp.zeros(X.shape[1], jnp.float32),
                                mesh)
        cost = introspect.analyze(sm, w0, label="dist_smooth")
        hlo = introspect.hlo_text(sm, w0)
        assert cost.collectives == introspect.collective_census(hlo)
        # the same envelope test_hlo_cost_shape pins: one psum phase
        assert 1 <= cost.collectives["all-reduce"] <= 3
        for op in ("all-gather", "collective-permute", "all-to-all"):
            assert cost.collectives[op] == 0

    def test_sharded_runner_census(self, cpu_devices):
        """The PUBLIC runner on a mesh reports the mesh program's
        collectives (nonzero all-reduce count)."""
        X, y = _tiny_problem(n=256, d=16)
        mesh = mesh_lib.make_mesh({"data": 8})
        fit = api.make_runner((X, y), LogisticGradient(), L2Prox(),
                              reg_param=0.1, num_iterations=5,
                              convergence_tol=0.0, mesh=mesh)
        cost = introspect.analyze_runner(
            fit, np.zeros(X.shape[1], np.float32))
        assert cost.collectives["all-reduce"] >= 1
        assert cost.collectives["all-gather"] == 0

    def test_mesh_sweep_lower_hook(self, cpu_devices):
        """parallel.grid's fit.lower hook censuses the sharded-grid
        program: the lane-vmapped loop keeps the same per-collective
        shape as a solo mesh fit (all-reduces only)."""
        from spark_agd_tpu.core import agd
        from spark_agd_tpu.parallel import grid

        X, y = _tiny_problem(n=256, d=16)
        mesh = mesh_lib.make_mesh({"data": 8})
        batch = mesh_lib.shard_batch(mesh, X, y)
        cfg = agd.AGDConfig(num_iterations=5, convergence_tol=0.0)
        fit = grid.make_mesh_sweep_fit(LogisticGradient(), L2Prox(),
                                       batch, mesh, cfg)
        cost = introspect.analyze_lowered(
            fit.lower([0.1, 0.2], np.zeros(16, np.float32)),
            label="mesh_sweep")
        assert cost.collectives["all-reduce"] >= 1
        for op in ("all-gather", "collective-permute", "all-to-all",
                   "reduce-scatter"):
            assert cost.collectives[op] == 0

    def test_record_emission_validates(self):
        X, y = _tiny_problem()
        fit = api.make_runner((X, y), LogisticGradient(), L2Prox(),
                              reg_param=0.1, num_iterations=3,
                              mesh=False)
        cost = introspect.analyze_runner(
            fit, np.zeros(X.shape[1], np.float32))
        tel = Telemetry()
        rec = tel.program_cost(cost, algorithm="agd")
        assert schema.validate_record(
            json.loads(json.dumps(rec))) == []
        assert rec["kind"] == "program_cost" and rec["label"] == "agd"
        assert rec in tel.records
        snap = tel.registry.snapshot()
        assert snap["program.agd.flops"] == cost.flops
        assert snap["program.agd.collectives"] == 0

    def test_environment_fingerprint(self):
        fp = introspect.environment_fingerprint()
        assert fp["platform"] == "cpu" and fp["n_devices"] >= 8
        assert fp["jax_version"] == jax.__version__
        mesh = mesh_lib.make_mesh({"data": 8})
        fp2 = introspect.environment_fingerprint(mesh)
        assert fp2["mesh_shape"] == {"data": 8}
        # provenance fields are valid optional run-record fields
        rec = schema.run_record(tool="test", **fp2)
        assert schema.validate_record(json.loads(json.dumps(rec))) == []


class TestProfilerCapture:
    def test_one_shot_trace_and_annotated_spans(self, tmp_path):
        """telemetry=profile_dir captures the first execute phase as a
        profiler trace; span records still stream for every phase."""
        X, y = _tiny_problem()
        tel = Telemetry(profile_dir=str(tmp_path / "trace"))
        fit = api.make_runner((X, y), LogisticGradient(), L2Prox(),
                              reg_param=0.1, num_iterations=3,
                              mesh=False, telemetry=tel)
        w0 = np.zeros(X.shape[1], np.float32)
        fit(w0)
        fit(w0)  # second fit: capture must NOT re-arm (no nested trace)
        assert [s["name"] for s in tel.spans()].count("execute") == 2
        # the profiler wrote a trace under the requested dir
        captured = []
        for root, _, files in os.walk(tmp_path / "trace"):
            captured += files
        assert captured, "no profiler trace files written"


class TestNumericsFailureEvents:
    def test_checked_smooth_emits_event(self):
        from spark_agd_tpu.utils import debug

        tel = Telemetry()

        def smooth(w):
            return jnp.sum(w), {"w": w * jnp.nan}

        sm = debug.checked_smooth(smooth, telemetry=tel)
        with pytest.raises(Exception):
            sm(jnp.ones(3))
        recs = [r for r in tel.records
                if r.get("kind") == "numerics_failure"]
        assert len(recs) == 1
        rec = recs[0]
        assert schema.validate_record(json.loads(json.dumps(rec))) == []
        assert "non-finite" in rec["message"]
        assert rec["leaf"] is not None and "w" in rec["leaf"]
        assert rec["evaluation"] == 1
        assert tel.registry.snapshot()["numerics.failures"] == 1

    def test_checked_smooth_clean_run_emits_nothing(self):
        from spark_agd_tpu.utils import debug

        tel = Telemetry()
        sm = debug.checked_smooth(lambda w: (jnp.sum(w), w),
                                  telemetry=tel)
        sm(jnp.ones(3))
        assert not [r for r in tel.records
                    if r.get("kind") == "numerics_failure"]

    def test_live_stream_flags_nonfinite_loss(self):
        """The in-loop iteration stream lands a numerics_failure record
        when the streamed loss goes non-finite (once per run)."""
        tel = Telemetry()
        cb = tel.iteration_callback("agd")
        cb(it=1, loss=0.5)
        cb(it=2, loss=float("nan"))
        cb(it=3, loss=float("nan"))
        recs = [r for r in tel.records
                if r.get("kind") == "numerics_failure"]
        assert len(recs) == 1 and recs[0]["iter"] == 2


# ------------------------------------------------------------- perf gate

def _run_rec(**over):
    rec = dict(schema.EXAMPLE_RUN_RECORD)
    rec.update(name="cfg1", algorithm="agd", wall_to_eps_s=2.0,
               iters_per_sec=400.0, converged=True, iters=20,
               device_kind="cpu", jax_version="0.4.37")
    rec.update(over)
    return rec


def _cost_rec(**over):
    rec = dict(schema.EXAMPLE_PROGRAM_COST_RECORD)
    rec.update(over)
    return rec


def _write_jsonl(path, records):
    with open(path, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")
    return str(path)


@pytest.mark.perfgate
class TestPerfGate:
    def test_identical_records_pass(self):
        base = [_run_rec(), _cost_rec()]
        result = perfgate.compare_records(base, [dict(r) for r in base])
        assert result.ok and result.exit_code() == 0
        assert not result.regressions
        compared = [d for d in result.deltas if d.status != "skipped"]
        assert compared, "identical records must actually be compared"

    def test_wall_time_regression_fails(self):
        base = [_run_rec()]
        cand = [_run_rec(wall_to_eps_s=4.0)]  # 2x slower
        result = perfgate.compare_records(base, cand)
        assert result.exit_code() == 1
        assert any(d.metric == "wall_to_eps_s"
                   for d in result.regressions)
        table = perfgate.format_report(result)
        assert "wall_to_eps_s" in table and "regression" in table

    def test_improvement_is_not_a_regression(self):
        result = perfgate.compare_records(
            [_run_rec()], [_run_rec(wall_to_eps_s=1.0,
                                    iters_per_sec=800.0)])
        assert result.exit_code() == 0
        assert any(d.status == "improved" for d in result.deltas)

    def test_within_threshold_noise_passes(self):
        result = perfgate.compare_records(
            [_run_rec()], [_run_rec(wall_to_eps_s=2.1)])  # +5% < 15%
        assert result.exit_code() == 0

    def test_collective_count_regression_fails(self):
        base = [_cost_rec()]
        cand = [_cost_rec(collectives={"all-reduce": 3,
                                       "all-gather": 1})]
        result = perfgate.compare_records(base, cand)
        assert result.exit_code() == 1
        assert any(d.metric == "collectives.all-gather"
                   for d in result.regressions)

    def test_flops_and_hbm_regression(self):
        base = [_cost_rec()]
        cand = [_cost_rec(flops=base[0]["flops"] * 1.5,
                          peak_hbm_bytes=base[0]["peak_hbm_bytes"] * 2)]
        result = perfgate.compare_records(base, cand)
        names = {d.metric for d in result.regressions}
        assert {"flops", "peak_hbm_bytes"} <= names

    def test_iters_to_tol_requires_convergence(self):
        """A capped (converged=False) iteration count is the cap, not a
        tolerance claim — it must not gate."""
        result = perfgate.compare_records(
            [_run_rec(converged=False)],
            [_run_rec(converged=False, iters=100)])
        d = [x for x in result.deltas if x.metric == "iters_to_tol"]
        assert d and d[0].status == "skipped"

    def test_cross_environment_refused(self):
        base = [_run_rec(platform="tpu", device_kind="TPU v5e")]
        cand = [_run_rec()]
        result = perfgate.compare_records(base, cand)
        assert result.refused and result.exit_code() == 2
        allowed = perfgate.compare_records(base, cand,
                                           allow_cross_env=True)
        assert not allowed.refused and allowed.exit_code() != 2

    def test_threshold_override(self):
        result = perfgate.compare_records(
            [_run_rec()], [_run_rec(wall_to_eps_s=2.1)],
            thresholds={"wall_to_eps_s": 0.01})
        assert result.exit_code() == 1


def _load_tool(name):
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.perfgate
class TestPerfGateCLI:
    def test_identical_files_exit_zero(self, tmp_path, capsys):
        recs = [_run_rec(), _cost_rec()]
        b = _write_jsonl(tmp_path / "base.jsonl", recs)
        c = _write_jsonl(tmp_path / "cand.jsonl", recs)
        assert _load_tool("perf_gate").main([b, c]) == 0
        assert "pass" in capsys.readouterr().out

    def test_regressed_candidate_exits_nonzero_with_table(
            self, tmp_path, capsys):
        b = _write_jsonl(tmp_path / "base.jsonl",
                         [_run_rec(), _cost_rec()])
        c = _write_jsonl(
            tmp_path / "cand.jsonl",
            [_run_rec(wall_to_eps_s=40.0),
             _cost_rec(collectives={"all-reduce": 9})])
        code = _load_tool("perf_gate").main([b, c])
        assert code == 1
        out = capsys.readouterr().out
        assert "wall_to_eps_s" in out
        assert "collectives.all-reduce" in out
        assert "regression" in out

    def test_cross_env_refused_then_allowed(self, tmp_path):
        b = _write_jsonl(tmp_path / "base.jsonl",
                         [_run_rec(platform="tpu")])
        c = _write_jsonl(tmp_path / "cand.jsonl", [_run_rec()])
        tool = _load_tool("perf_gate")
        assert tool.main([b, c]) == 2
        assert tool.main([b, c, "--allow-cross-env"]) == 0

    def test_threshold_flag(self, tmp_path):
        b = _write_jsonl(tmp_path / "base.jsonl", [_run_rec()])
        c = _write_jsonl(tmp_path / "cand.jsonl",
                         [_run_rec(wall_to_eps_s=2.1)])
        tool = _load_tool("perf_gate")
        assert tool.main([b, c]) == 0
        assert tool.main(
            [b, c, "--threshold", "wall_to_eps_s=0.01"]) == 1

    def test_require_match_guards_empty_gate(self, tmp_path):
        b = _write_jsonl(tmp_path / "base.jsonl",
                         [_run_rec(name="only-in-base")])
        c = _write_jsonl(tmp_path / "cand.jsonl",
                         [_run_rec(name="only-in-cand")])
        tool = _load_tool("perf_gate")
        assert tool.main([b, c]) == 0  # nothing compared, nothing broke
        assert tool.main([b, c, "--require-match"]) == 1

    def test_gate_on_real_runner_census(self, tmp_path):
        """End-to-end on a real compiled program: census the AGD
        runner, write baseline/candidate JSONLs, gate them — identical
        passes, an inflated collective count fails."""
        X, y = _tiny_problem()
        fit = api.make_runner((X, y), LogisticGradient(), L2Prox(),
                              reg_param=0.1, num_iterations=3,
                              mesh=False)
        cost = introspect.analyze_runner(
            fit, np.zeros(X.shape[1], np.float32))
        rec = cost.record(schema.new_run_id(), algorithm="agd")
        b = _write_jsonl(tmp_path / "base.jsonl", [rec])
        c_same = _write_jsonl(tmp_path / "cand.jsonl", [rec])
        tool = _load_tool("perf_gate")
        assert tool.main([b, c_same, "--require-match"]) == 0
        worse = dict(rec)
        worse["collectives"] = dict(rec["collectives"],
                                    **{"all-reduce": 5})
        c_bad = _write_jsonl(tmp_path / "worse.jsonl", [worse])
        assert tool.main([b, c_bad]) == 1


@pytest.mark.perfgate
class TestAgdReportCompare:
    def test_side_by_side_diff(self, tmp_path, capsys):
        b = _write_jsonl(tmp_path / "base.jsonl", [
            _run_rec(),
            schema.iteration_record("ra", "agd", 1, loss=1.0),
            schema.iteration_record("ra", "agd", 2, loss=0.5),
        ])
        c = _write_jsonl(tmp_path / "cand.jsonl", [
            _run_rec(wall_to_eps_s=3.0),
            schema.iteration_record("rb", "agd", 1, loss=1.0),
            schema.iteration_record("rb", "agd", 2, loss=0.4),
        ])
        report = _load_tool("agd_report")
        assert report.main(["--compare", b, c]) == 0
        out = capsys.readouterr().out
        assert "wall_to_eps_s" in out and "+50" in out
        assert "iteration streams" in out and "final_loss" in out

    def test_plain_report_still_works(self, tmp_path, capsys):
        b = _write_jsonl(tmp_path / "one.jsonl", [_run_rec()])
        report = _load_tool("agd_report")
        assert report.main([b]) == 0
        assert "runs (1)" in capsys.readouterr().out
