"""Unit tests for the proximal operators (SURVEY §7 step 1).

Pins the MLlib-1.3 conventions and the two API subtleties the reference
relies on: no hidden step rescaling (reference passes iter=1, ``:218-219``)
and the ``prox(w, g, 0) == (w, reg_value(w))`` identity (reference ``:305``).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from spark_agd_tpu.ops import prox
from spark_agd_tpu.core import tvec


@pytest.fixture
def vecs(rng):
    w = jnp.asarray(rng.normal(size=(7,)))
    g = jnp.asarray(rng.normal(size=(7,)))
    return w, g


ALL_PROXES = [
    prox.IdentityProx(),
    prox.L2Prox(),
    prox.MLlibSquaredL2Updater(),
    prox.L1Prox(),
    prox.ElasticNetProx(0.3),
]


class TestStepZeroIdentity:
    """reference :305 — reg-value read via step=0 must not move weights."""

    @pytest.mark.parametrize("p", ALL_PROXES, ids=lambda p: type(p).__name__)
    def test_identity(self, p, vecs):
        w, g = vecs
        w_new, rv = p.prox(w, g, 0.0, 0.7)
        np.testing.assert_array_equal(np.asarray(w_new), np.asarray(w))
        np.testing.assert_allclose(float(rv), float(p.reg_value(w, 0.7)),
                                   rtol=1e-12)


class TestIdentityProx:
    def test_plain_step(self, vecs):
        w, g = vecs
        w_new, rv = prox.IdentityProx().prox(w, g, 0.25, 0.0)
        np.testing.assert_allclose(np.asarray(w_new),
                                   np.asarray(w) - 0.25 * np.asarray(g),
                                   rtol=1e-12)
        assert float(rv) == 0.0


class TestL2Prox:
    def test_shrink_formula(self, vecs):
        w, g = vecs
        step, reg = 0.5, 0.2
        w_new, rv = prox.L2Prox().prox(w, g, step, reg)
        expect = (np.asarray(w) - step * np.asarray(g)) / (1 + step * reg)
        np.testing.assert_allclose(np.asarray(w_new), expect, rtol=1e-12)
        # MLlib convention: penalty evaluated at the NEW weights
        np.testing.assert_allclose(float(rv),
                                   0.5 * reg * np.sum(expect**2), rtol=1e-12)

    def test_is_exact_prox(self, vecs):
        """w' minimizes step*reg/2 ||u||^2 + 1/2 ||u - (w - step g)||^2 —
        check first-order optimality."""
        w, g = vecs
        step, reg = 0.3, 0.4
        w_new, _ = prox.L2Prox().prox(w, g, step, reg)
        v = np.asarray(w) - step * np.asarray(g)
        resid = step * reg * np.asarray(w_new) + (np.asarray(w_new) - v)
        np.testing.assert_allclose(resid, 0.0, atol=1e-12)


class TestMLlibSquaredL2Updater:
    def test_linearized_formula(self, vecs):
        """MLlib 1.3.0 is a linearized step, NOT the exact prox:
        w' = (1 - step*reg)*w - step*g (see 1.3.0 SquaredL2Updater source
        comment); this is what reference :215-220 actually executed."""
        w, g = vecs
        step, reg = 0.5, 0.2
        w_new, rv = prox.MLlibSquaredL2Updater().prox(w, g, step, reg)
        expect = (1 - step * reg) * np.asarray(w) - step * np.asarray(g)
        np.testing.assert_allclose(np.asarray(w_new), expect, rtol=1e-12)
        np.testing.assert_allclose(float(rv), 0.5 * reg * np.sum(expect**2),
                                   rtol=1e-12)

    def test_parity_alias_points_here(self):
        assert prox.SquaredL2Updater is prox.MLlibSquaredL2Updater

    def test_agrees_with_exact_prox_to_first_order(self, vecs):
        w, g = vecs
        reg = 0.3
        for step in [1e-3, 1e-4]:
            a, _ = prox.MLlibSquaredL2Updater().prox(w, g, step, reg)
            b, _ = prox.L2Prox().prox(w, g, step, reg)
            diff = np.linalg.norm(np.asarray(a) - np.asarray(b))
            # exact decomposition, e = step*reg:
            #   linearized - exact = -e^2/(1+e)·w - step·e/(1+e)·g
            e = step * reg
            bound = (e**2 * np.linalg.norm(np.asarray(w))
                     + step * e * np.linalg.norm(np.asarray(g))) / (1 + e)
            assert diff <= 1.01 * bound


class TestL1Prox:
    def test_soft_threshold(self):
        w = jnp.asarray([3.0, -3.0, 0.05, -0.05, 0.0])
        g = jnp.zeros(5)
        step, reg = 1.0, 0.1
        w_new, rv = prox.L1Prox().prox(w, g, step, reg)
        np.testing.assert_allclose(np.asarray(w_new),
                                   [2.9, -2.9, 0.0, 0.0, 0.0], atol=1e-12)
        np.testing.assert_allclose(float(rv), 0.1 * 5.8, rtol=1e-12)

    def test_sparsity_inducing(self, rng):
        w = jnp.asarray(rng.normal(size=(100,)) * 0.01)
        g = jnp.zeros(100)
        w_new, _ = prox.L1Prox().prox(w, g, 1.0, 1.0)
        assert np.all(np.asarray(w_new) == 0.0)


class TestElasticNet:
    def test_reduces_to_l1_and_l2(self, vecs):
        w, g = vecs
        step, reg = 0.5, 0.3
        en1 = prox.ElasticNetProx(1.0).prox(w, g, step, reg)
        l1 = prox.L1Prox().prox(w, g, step, reg)
        np.testing.assert_allclose(np.asarray(en1[0]), np.asarray(l1[0]),
                                   rtol=1e-12)
        en0 = prox.ElasticNetProx(0.0).prox(w, g, step, reg)
        l2 = prox.L2Prox().prox(w, g, step, reg)
        np.testing.assert_allclose(np.asarray(en0[0]), np.asarray(l2[0]),
                                   rtol=1e-12)


class TestPytreeSupport:
    def test_prox_over_pytree(self, rng):
        p = {"W": jnp.asarray(rng.normal(size=(3, 4))),
             "b": jnp.asarray(rng.normal(size=(4,)))}
        gr = {"W": jnp.asarray(rng.normal(size=(3, 4))),
              "b": jnp.asarray(rng.normal(size=(4,)))}
        w_new, rv = prox.L2Prox().prox(p, gr, 0.1, 0.5)
        assert set(w_new.keys()) == {"W", "b"}
        flat_w = np.concatenate([np.asarray(p["W"]).ravel(),
                                 np.asarray(p["b"])])
        flat_g = np.concatenate([np.asarray(gr["W"]).ravel(),
                                 np.asarray(gr["b"])])
        flat_new = (flat_w - 0.1 * flat_g) / (1 + 0.1 * 0.5)
        got = np.concatenate([np.asarray(w_new["W"]).ravel(),
                              np.asarray(w_new["b"])])
        np.testing.assert_allclose(got, flat_new, rtol=1e-12)
        np.testing.assert_allclose(float(rv), 0.25 * np.sum(flat_new**2),
                                   rtol=1e-12)


class TestTvec:
    def test_dot_norm_axpby(self, rng):
        a = {"x": jnp.asarray(rng.normal(size=(5,))),
             "y": jnp.asarray(rng.normal(size=(2, 3)))}
        b = {"x": jnp.asarray(rng.normal(size=(5,))),
             "y": jnp.asarray(rng.normal(size=(2, 3)))}
        fa = np.concatenate([np.asarray(a["x"]), np.asarray(a["y"]).ravel()])
        fb = np.concatenate([np.asarray(b["x"]), np.asarray(b["y"]).ravel()])
        np.testing.assert_allclose(float(tvec.dot(a, b)), fa @ fb, rtol=1e-12)
        np.testing.assert_allclose(float(tvec.norm(a)), np.linalg.norm(fa),
                                   rtol=1e-12)
        c = tvec.axpby(2.0, a, -0.5, b)
        fc = np.concatenate([np.asarray(c["x"]), np.asarray(c["y"]).ravel()])
        np.testing.assert_allclose(fc, 2 * fa - 0.5 * fb, rtol=1e-12)
        assert tvec.size(a) == 11
