"""Straggler-aware scheduling (``resilience.scheduler``): the weighted
re-split math, the skew tracker's hysteresis, the supervisor feedback
loop, persistent chaos faults, the slow-vs-lost monitor split, the
perfgate rebalance gate, speculation bit-safety, and the drill.

Everything here is CPU-deterministic tier-1 except the reduced
2-process drill smoke (marked ``dist_fault`` like its siblings).
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from spark_agd_tpu.obs import Telemetry, perfgate, schema
from spark_agd_tpu.resilience import scheduler as sched_lib
from spark_agd_tpu.resilience.scheduler import (
    RebalanceDecision,
    ReschedulePolicy,
    SkewTracker,
    StragglerScheduler,
    assign_weighted,
    modeled_makespan,
    resolve_speculation,
    run_speculative_segment,
    speculation_due,
    uniform_counts,
    weighted_counts,
)

pytestmark = pytest.mark.sched


# ---------------------------------------------------------------------------
# the weighted re-split math (property-style)


class TestWeightedCounts:
    def _speed_cases(self):
        rng = np.random.default_rng(7)
        cases = [
            [1.0, 1.0], [1.0, 0.2], [0.2, 1.0], [1.0, 1000.0],
            [1e-6, 1.0, 1.0], [1.0] * 8 + [0.001],
            [5.0, 1.0, 0.5, 0.1],
        ]
        for _ in range(20):
            n = int(rng.integers(2, 7))
            cases.append(list(np.exp(rng.normal(0.0, 1.5, n))))
        return cases

    def test_covers_exactly_and_respects_floor(self):
        rng = np.random.default_rng(3)
        for speeds in self._speed_cases():
            for parts in (0, 1, 3, 7, 12, 40,
                          int(rng.integers(1, 64))):
                for floor in (0, 1, 2):
                    counts = weighted_counts(parts, speeds,
                                             min_shard=floor)
                    assert sum(counts) == parts
                    eff = min(floor, parts // len(speeds))
                    assert all(c >= eff for c in counts)

    def test_never_worse_than_uniform(self):
        for speeds in self._speed_cases():
            for parts in (1, 5, 12, 37):
                counts = weighted_counts(parts, speeds, min_shard=1)
                assert (modeled_makespan(counts, speeds)
                        <= modeled_makespan(
                            uniform_counts(parts, len(speeds)),
                            speeds) + 1e-12)

    def test_strictly_better_for_skewed_fleet(self):
        speeds = [1.0, 0.2]
        counts = weighted_counts(12, speeds, min_shard=1)
        assert counts == [10, 2]
        assert (modeled_makespan(counts, speeds)
                < modeled_makespan(uniform_counts(12, 2), speeds))

    def test_min_shard_zero_starves_dead_weight(self):
        assert weighted_counts(12, [1.0, 0.001],
                               min_shard=0) == [12, 0]

    def test_deterministic(self):
        speeds = [1.3, 0.7, 0.7]
        assert (weighted_counts(11, speeds, min_shard=1)
                == weighted_counts(11, speeds, min_shard=1))

    def test_zero_speed_clamped_not_crash(self):
        counts = weighted_counts(6, [1.0, 0.0], min_shard=1)
        assert sum(counts) == 6 and counts[1] >= 1

    def test_fewer_parts_than_hosts(self):
        counts = weighted_counts(2, [1.0, 1.0, 1.0], min_shard=1)
        assert sum(counts) == 2 and all(c >= 0 for c in counts)

    def test_validation(self):
        with pytest.raises(ValueError):
            weighted_counts(3, [])
        with pytest.raises(ValueError):
            weighted_counts(-1, [1.0])


class TestAssignWeighted:
    def test_partition_coverage_exactly_once(self):
        union = [f"part-{i:02d}" for i in range(13)]
        table = assign_weighted(union, [1.0, 0.25, 1.0], min_shard=1)
        flat = [p for row in table for p in row]
        assert sorted(flat) == sorted(union)
        assert len(flat) == len(set(flat)) == 13

    def test_matches_round_robin_rule_counts_when_balanced(self):
        union = [f"p{i}" for i in range(10)]
        table = assign_weighted(union, [1.0, 1.0, 1.0], min_shard=1)
        assert sorted(len(r) for r in table) == sorted(
            uniform_counts(10, 3))

    def test_deterministic_across_hosts(self):
        union = [f"p{i}" for i in range(9)]
        speeds = [0.9, 2.0]
        assert assign_weighted(union, speeds) == assign_weighted(
            union, speeds)


# ---------------------------------------------------------------------------
# skew tracker + hysteresis


class TestSkewTracker:
    def test_ewma_math(self):
        t = SkewTracker(alpha=0.5, floor_s=1e-9)
        t.observe(0, 1.0)
        t.observe(0, 0.0)
        assert t.costs()[0] == pytest.approx(0.5)

    def test_skew_and_speeds(self):
        t = SkewTracker(floor_s=1e-3)
        t.fold({0: 0.001, 1: 0.4, 2: 0.001})
        assert t.straggler() == 1
        assert t.skew() == pytest.approx(400.0)
        sp = t.speeds()
        assert sp[0] == pytest.approx(1.0) and sp[1] < 0.01

    def test_floor_makes_idle_fleet_balanced(self):
        t = SkewTracker(floor_s=1e-3)
        t.fold({0: 0.0001, 1: 0.0004})
        assert t.skew() == pytest.approx(1.0)
        assert t.straggler() is None

    def test_blip_does_not_persist(self):
        t = SkewTracker(alpha=1.0, skew_threshold=1.5,
                        trigger_segments=2)
        assert not t.fold({0: 0.001, 1: 0.5}).persistent
        snap = t.fold({0: 0.001, 1: 0.001})  # the blip cleared
        assert snap.consecutive == 0 and not snap.persistent

    def test_consecutive_same_straggler_triggers(self):
        t = SkewTracker(alpha=1.0, skew_threshold=1.5,
                        trigger_segments=2)
        t.fold({0: 0.001, 1: 0.5})
        snap = t.fold({0: 0.001, 1: 0.5})
        assert snap.consecutive == 2 and snap.persistent
        assert snap.straggler == 1

    def test_straggler_change_resets_counter(self):
        t = SkewTracker(alpha=1.0, skew_threshold=1.5,
                        trigger_segments=3)
        t.fold({0: 0.001, 1: 0.5})
        snap = t.fold({0: 0.5, 1: 0.001})
        assert snap.straggler == 0 and snap.consecutive == 1

    def test_observe_heartbeats(self, tmp_path):
        from spark_agd_tpu.resilience.distributed import heartbeat_name

        d = str(tmp_path)
        for p, phase in ((0, "segment"), (1, "slow")):
            with open(os.path.join(d, heartbeat_name(p)), "w") as f:
                json.dump({"process": p, "time": 0.0,
                           "phase": phase}, f)
        t = SkewTracker()
        seen = t.observe_heartbeats(d)
        assert set(seen) == {0, 1}
        assert t.hb_slow == [1]
        assert all(a >= 0 for a in t.hb_ages.values())


# ---------------------------------------------------------------------------
# the scheduler object (fake exchange — no collectives needed)


def _two_host_exchange(slow_us=400000):
    """An exchange stub: this host's row plus a fabricated slow peer."""
    def exchange(row):
        other = row.copy()
        other[1] = slow_us
        return np.stack([row, other])
    return exchange


def _drive(scheduler, segments, boundary_s=0.0002, start=0, k=4):
    decision = None
    for i in range(segments):
        decision = scheduler.after_segment(
            start_iter=start + i * k, iters=k, boundary_s=boundary_s)
        if decision is not None:
            break
    return decision


class TestStragglerScheduler:
    def _mk(self, tel=None, **pol):
        policy = ReschedulePolicy(**{"trigger_segments": 2,
                                     "min_shard": 0, **pol})
        return StragglerScheduler(
            [f"p{i:02d}" for i in range(12)], policy=policy,
            telemetry=tel, process_index=0, process_count=2,
            exchange=_two_host_exchange())

    def test_initial_assignment_is_round_robin(self):
        s = self._mk()
        union = sorted(f"p{i:02d}" for i in range(12))
        assert list(s.assignment) == union[0::2]
        assert list(s.assignments[1]) == union[1::2]

    def test_decides_after_trigger_syncs(self):
        tel = Telemetry()
        s = self._mk(tel)
        d = _drive(s, 4)
        assert isinstance(d, RebalanceDecision)
        assert d.at_iter == 8 and d.before == (6, 6)
        assert d.after[0] > d.after[1] and sum(d.after) == 12
        assert d.straggler == 1 and d.moved >= 1
        kinds = [r["kind"] for r in tel.records]
        assert kinds.count("skew_estimate") == 2

    def test_apply_updates_state_and_emits(self):
        tel = Telemetry()
        s = self._mk(tel)
        d = _drive(s, 4)
        rebuilt = []
        s.rebuild = lambda dec: rebuilt.append(dec.mine) or "staged!"
        assert s.apply(d) is None or True  # rebuild return forwarded
        assert s.assignments == d.assignments
        assert s.rebalances == 1
        assert rebuilt == [d.mine]
        recs = {r["kind"] for r in tel.records}
        assert "rebalance" in recs
        actions = [r["action"] for r in tel.records
                   if r["kind"] == "recovery"]
        assert actions == ["rebalance"]
        assert not any(schema.validate_record(r)
                       for r in tel.records)

    def test_same_assignment_suppressed(self):
        s = self._mk()
        d = _drive(s, 4)
        s.apply(d)
        # skew persists, but the weighted table is already in place:
        # no repeated decision, hysteresis re-arms instead
        assert _drive(s, 6, start=d.at_iter) is None or \
            s.policy.max_rebalances > 1

    def test_max_rebalances_cap(self):
        s = self._mk(max_rebalances=0)
        assert _drive(s, 6) is None

    def test_observe_only_policy(self):
        tel = Telemetry()
        s = self._mk(tel, rebalance=False)
        assert _drive(s, 6) is None
        assert any(r["kind"] == "skew_estimate" for r in tel.records)

    def test_lockstep_mismatch_refused(self):
        def bad_exchange(row):
            other = row.copy()
            other[0] = row[0] + 4  # a host at a different iteration
            return np.stack([row, other])
        s = StragglerScheduler(
            ["a", "b"], policy=ReschedulePolicy(),
            process_index=0, process_count=2, exchange=bad_exchange)
        with pytest.raises(RuntimeError, match="lockstep"):
            s.after_segment(start_iter=0, iters=4, boundary_s=0.001)

    def test_single_process_identity_never_triggers(self):
        s = StragglerScheduler(
            ["a", "b", "c"],
            policy=ReschedulePolicy(trigger_segments=1),
            process_index=0, process_count=1)
        for i in range(4):
            assert s.after_segment(start_iter=i * 4, iters=4,
                                   boundary_s=0.5) is None

    def test_policy_validation(self):
        for bad in (dict(skew_threshold=0.5),
                    dict(trigger_segments=0), dict(sync_every=0),
                    dict(min_shard=-1), dict(speculative_multiple=1.0),
                    dict(ewma_alpha=0.0), dict(floor_s=0.0)):
            with pytest.raises(ValueError):
                ReschedulePolicy(**bad)


# ---------------------------------------------------------------------------
# supervisor integration (single process, real compiled segments)


@pytest.fixture(scope="module")
def staged_problem(cpu_devices):
    from spark_agd_tpu.core import agd, smooth as smooth_lib
    from spark_agd_tpu.ops.losses import LogisticGradient
    from spark_agd_tpu.ops.prox import L2Prox

    rng = np.random.default_rng(5)
    X = rng.standard_normal((96, 5)).astype(np.float64)
    w_true = np.linspace(-1.0, 1.0, 5)
    y = (X @ w_true > 0).astype(np.float64)
    build, dargs = smooth_lib.make_smooth_staged(
        LogisticGradient(), X, y)
    px, rv = smooth_lib.make_prox(L2Prox(), 0.1)
    w0 = np.zeros(5, np.float64)
    cfg = agd.AGDConfig(convergence_tol=0.0, num_iterations=24)
    return dict(build=build, dargs=dargs, px=px, rv=rv, w0=w0,
                cfg=cfg, seg_cache={})


def _supervised(sp, **kw):
    from spark_agd_tpu.resilience import (ResiliencePolicy,
                                          run_agd_supervised)

    return run_agd_supervised(
        prox=sp["px"], reg_value=sp["rv"], w0=sp["w0"],
        config=sp["cfg"],
        policy=ResiliencePolicy(segment_iters=4, max_attempts=2,
                                backoff_base=0.01, jitter=0.0, seed=0),
        staged=(sp["build"], sp["dargs"]),
        seg_cache=sp["seg_cache"], stream_iterations=False, **kw)


class TestSupervisorIntegration:
    def test_scheduling_off_is_bit_identical(self, staged_problem):
        plain = _supervised(staged_problem)
        again = _supervised(staged_problem, scheduler=None)
        assert np.array_equal(np.asarray(plain.weights),
                              np.asarray(again.weights))

    def test_observe_only_scheduler_bit_identical_no_retrace(
            self, staged_problem):
        plain = _supervised(staged_problem)
        keys = set(staged_problem["seg_cache"])
        tel = Telemetry()
        s = StragglerScheduler(
            [f"p{i}" for i in range(8)],
            policy=ReschedulePolicy(rebalance=False),
            telemetry=tel, process_index=0, process_count=2,
            exchange=_two_host_exchange())
        res = _supervised(staged_problem, telemetry=tel, scheduler=s)
        # the compiled program is untouched: the shared segment cache
        # gained no keys, and the trajectory is bit-identical
        assert set(staged_problem["seg_cache"]) == keys
        assert np.array_equal(np.asarray(plain.weights),
                              np.asarray(res.weights))
        assert any(r["kind"] == "skew_estimate" for r in tel.records)
        assert not any(r["kind"] == "rebalance" for r in tel.records)

    def test_rebalance_applied_at_generation_boundary(
            self, staged_problem, tmp_path):
        from spark_agd_tpu.resilience import DistributedCheckpointer
        from spark_agd_tpu.resilience.manifest import (
            committed_generations, load_manifest)

        plain = _supervised(staged_problem)
        tel = Telemetry()
        rebuilt = []

        def rebuild(decision):
            rebuilt.append(decision.mine)
            # same data: the rebalance machinery must not perturb math
            return (staged_problem["build"], staged_problem["dargs"])

        s = StragglerScheduler(
            [f"p{i}" for i in range(8)],
            policy=ReschedulePolicy(trigger_segments=2, min_shard=1),
            telemetry=tel, process_index=0, process_count=2,
            exchange=_two_host_exchange(), rebuild=rebuild)
        ck = DistributedCheckpointer(
            str(tmp_path / "ck"), every_iters=4, keep=32,
            telemetry=tel, process_index=0, process_count=1,
            partitions=[f"p{i}" for i in range(0, 8, 2)])
        res = _supervised(staged_problem, telemetry=tel, scheduler=s,
                          checkpointer=ck)
        assert np.array_equal(np.asarray(plain.weights),
                              np.asarray(res.weights))
        assert s.rebalances == 1 and len(rebuilt) == 1
        # the checkpointer's NEXT generation carries the new list
        assert ck.partitions == list(s.assignment)
        # the forced commit landed: one generation records the
        # rebalanced assignment (shards carry "partitions")
        gens = committed_generations(str(tmp_path / "ck"))
        assert len(gens) >= 2
        newest = load_manifest(str(tmp_path / "ck"), gens[0])
        assert newest is not None
        recs = [r for r in tel.records if r.get("kind") == "rebalance"]
        assert len(recs) == 1 and recs[0]["at_iter"] == 8
        assert not any(schema.validate_record(r) for r in tel.records)

    def test_rebuild_requires_staged(self, staged_problem):
        from spark_agd_tpu.core import smooth as smooth_lib
        from spark_agd_tpu.ops.losses import LogisticGradient

        rng = np.random.default_rng(0)
        X = rng.standard_normal((8, 5))
        y = (X.sum(axis=1) > 0).astype(np.float64)
        sm = smooth_lib.make_smooth(LogisticGradient(), X, y)
        from spark_agd_tpu.resilience import (ResiliencePolicy,
                                              run_agd_supervised)

        s = StragglerScheduler(["a"], rebuild=lambda d: None,
                               process_index=0, process_count=1)
        with pytest.raises(ValueError, match="staged"):
            run_agd_supervised(
                smooth=sm, prox=staged_problem["px"],
                reg_value=staged_problem["rv"],
                w0=staged_problem["w0"], config=staged_problem["cfg"],
                policy=ResiliencePolicy(segment_iters=4),
                scheduler=s)


# ---------------------------------------------------------------------------
# persistent chaos faults + heartbeat sub-beats


class TestPersistentSlowHost:
    def test_fires_every_boundary_with_decay(self):
        from spark_agd_tpu.resilience.chaos import (ChaosSchedule,
                                                    ScheduledFault)

        naps = []
        s = ChaosSchedule(
            [ScheduledFault("slow_host", 4, payload=1.0,
                            persist=True, decay=0.5)],
            sleep=naps.append)
        s.before_segment(0)   # not armed yet
        s.before_segment(4)
        s.before_segment(8)
        s.before_segment(12)
        assert naps == [1.0, 0.5, 0.25]
        assert [f[0] for f in s.fired] == ["slow_host"] * 3
        assert s.exhausted  # persistent faults never count against it

    def test_slow_scale_hook_and_quiet_when_zero(self):
        from spark_agd_tpu.resilience.chaos import (ChaosSchedule,
                                                    ScheduledFault)

        naps = []
        scale = [1.0]
        tel = Telemetry()
        s = ChaosSchedule(
            [ScheduledFault("slow_host", 0, payload=0.5,
                            persist=True)],
            sleep=naps.append, slow_scale=lambda: scale[0],
            telemetry=tel)
        s.before_segment(0)
        scale[0] = 0.0  # the rebalance stripped this host's data
        s.before_segment(4)
        assert naps == [0.5]
        chaos = [r for r in tel.records if r["kind"] == "chaos"]
        assert len(chaos) == 1 and chaos[0]["payload"] == 0.5

    def test_one_shot_slow_host_unchanged(self):
        from spark_agd_tpu.resilience.chaos import (ChaosSchedule,
                                                    ScheduledFault)

        naps = []
        s = ChaosSchedule(
            [ScheduledFault("slow_host", 2, payload=0.03)],
            sleep=naps.append)
        s.before_segment(3)
        s.before_segment(7)
        assert naps == [0.03] and s.exhausted

    def test_sub_interval_beats_during_sleep(self):
        from spark_agd_tpu.resilience.chaos import (ChaosSchedule,
                                                    ScheduledFault)

        naps, beats = [], []

        class FakeHB:
            def beat(self, **kw):
                beats.append(kw)

        s = ChaosSchedule(
            [ScheduledFault("slow_host", 0, payload=1.0,
                            persist=True)],
            sleep=naps.append, beat_interval_s=0.25)
        s.bind_heartbeat(FakeHB())
        s.before_segment(0)
        assert naps == [0.25] * 4
        assert len(beats) == 4
        assert all(b["phase"] == "slow" for b in beats)

    def test_persist_validation(self):
        from spark_agd_tpu.resilience.chaos import ScheduledFault

        with pytest.raises(ValueError, match="slow_host"):
            ScheduledFault("sigterm", 4, persist=True)
        with pytest.raises(ValueError, match="decay"):
            ScheduledFault("slow_host", 4, decay=0.0)

    def test_generate_draws_persistent_and_stays_normalized(self):
        from spark_agd_tpu.resilience.chaos import (FILE_KINDS,
                                                    ChaosCampaign)

        n_persist = 0
        for seed in range(150):
            c = ChaosCampaign.generate(seed, iters=48)
            assert c == ChaosCampaign.generate(seed, iters=48)
            kinds = [f.kind for f in c.faults]
            assert kinds.count("nan") <= 2
            for f in c.faults:
                assert 2 <= f.at_iter < 48 * 0.7 + 1
                if f.persist:
                    assert f.kind == "slow_host"
                    assert 0 < f.decay < 1.0
                    n_persist += 1
            first_file = next((i for i, k in enumerate(kinds)
                               if k in FILE_KINDS), None)
            if first_file is not None:
                assert "sigterm" in kinds[:first_file]
        assert n_persist >= 5  # the degraded-host leg actually draws


class TestMonitorVerdicts:
    def _pair(self, tmp_path, stale=2.0, slow_after=None):
        from spark_agd_tpu.resilience.distributed import (
            HeartbeatWriter, HostMonitor)

        now = [0.0]
        hb = HeartbeatWriter(str(tmp_path), process_index=1,
                             process_count=2, clock=lambda: now[0])
        mon = HostMonitor(str(tmp_path), stale_after_s=stale,
                          slow_after_s=slow_after,
                          clock=lambda: now[0])
        return now, hb, mon

    def test_fresh_segment_beat_is_ok(self, tmp_path):
        now, hb, mon = self._pair(tmp_path)
        hb.beat(iter=4, phase="segment")
        assert mon.verdicts() == {1: "ok"}
        mon.check()  # no raise

    def test_slow_phase_beat_is_slow_not_lost(self, tmp_path):
        now, hb, mon = self._pair(tmp_path)
        hb.beat(iter=4, phase="slow")
        now[0] = 1.5  # inside staleness
        assert mon.verdicts() == {1: "slow"}
        assert mon.slow_hosts() == [1]
        mon.check()  # SLOW never raises

    def test_age_based_slow_verdict(self, tmp_path):
        now, hb, mon = self._pair(tmp_path, stale=4.0, slow_after=1.0)
        hb.beat(iter=0, phase="segment")
        now[0] = 2.0
        assert mon.verdicts() == {1: "slow"}

    def test_stale_is_lost_and_raises(self, tmp_path):
        from spark_agd_tpu.resilience import HostLost

        now, hb, mon = self._pair(tmp_path)
        hb.beat(iter=4, phase="segment")
        now[0] = 10.0
        assert mon.verdicts() == {1: "lost"}
        with pytest.raises(HostLost):
            mon.check()

    def test_long_injected_sleep_with_sub_beats_never_lost(
            self, tmp_path):
        """The misdiagnosis this PR fixes: a slow_host sleep LONGER
        than the staleness window used to read as HostLost; with the
        chaos sub-interval beats it reads SLOW throughout."""
        from spark_agd_tpu.resilience.chaos import (ChaosSchedule,
                                                    ScheduledFault)

        now, hb, mon = self._pair(tmp_path, stale=2.0)
        hb.beat(iter=0, phase="segment")

        verdicts = []

        def fake_sleep(dt):  # the injected sleep advances fake time
            now[0] += dt
            verdicts.append(mon.verdicts().get(1))
            mon.check()  # must never raise mid-sleep

        s = ChaosSchedule(
            [ScheduledFault("slow_host", 0, payload=6.0,
                            persist=True)],
            sleep=fake_sleep, beat_interval_s=0.5)
        s.bind_heartbeat(hb)
        s.before_segment(0)  # a 6 s sleep against a 2 s staleness
        assert verdicts and all(v == "slow" for v in verdicts)

        # the counterfactual: the SAME sleep without sub-beats IS lost
        from spark_agd_tpu.resilience import HostLost

        now[0] += 6.0
        with pytest.raises(HostLost):
            mon.check()

    def test_slow_after_validation(self, tmp_path):
        from spark_agd_tpu.resilience.distributed import HostMonitor

        with pytest.raises(ValueError):
            HostMonitor(str(tmp_path), stale_after_s=2.0,
                        slow_after_s=3.0)


# ---------------------------------------------------------------------------
# perfgate: the rebalance-effectiveness gate


def _boundary_span(it, proc, secs):
    return {"schema_version": 1, "kind": "span", "run_id": "r",
            "name": "boundary", "seconds": secs, "trace_id": "t1",
            "span_id": f"s{it}-{proc}", "parent_id": None,
            "process": proc, "status": "ok", "start_iter": it}


def _gate_records(post_slow=0.0004):
    recs = []
    for it in (0, 4):
        recs += [_boundary_span(it, 0, 0.0002),
                 _boundary_span(it, 1, 0.4)]
    for it in range(8, 40, 4):
        recs += [_boundary_span(it, 0, 0.0002),
                 _boundary_span(it, 1, post_slow)]
    recs.append({"schema_version": 1, "kind": "recovery",
                 "run_id": "r", "action": "rebalance", "from_iter": 8})
    return recs


class TestRebalanceGate:
    def test_pass_when_post_score_drops(self):
        g = perfgate.gate_rebalance(_gate_records(),
                                    require_rebalance=True)
        assert g.exit_code() == 0 and g.improved
        assert g.pre_score > g.post_score
        assert g.rebalance_iter == 8
        assert "pass" in perfgate.format_rebalance_report(g)

    def test_fail_when_rebalance_did_not_help(self):
        g = perfgate.gate_rebalance(_gate_records(post_slow=0.5),
                                    require_rebalance=True)
        assert g.exit_code() == 1 and not g.improved

    def test_refusal_without_spans_is_typed_exit_2(self):
        recs = [{"schema_version": 1, "kind": "recovery",
                 "run_id": "r", "action": "rebalance",
                 "from_iter": 8}]
        g = perfgate.gate_rebalance(recs, require_rebalance=True)
        assert g.exit_code() == 2 and g.refusals
        assert "REFUSED" in perfgate.format_rebalance_report(g)

    def test_refusal_one_sided_samples(self):
        recs = _gate_records()
        recs = [r for r in recs
                if not (r.get("kind") == "span"
                        and r.get("start_iter", 99) < 8)]
        g = perfgate.gate_rebalance(recs, require_rebalance=True)
        assert g.exit_code() == 2

    def test_no_rebalance_vacuous_pass_unless_required(self):
        spans = [r for r in _gate_records() if r["kind"] == "span"]
        assert perfgate.gate_rebalance(spans).exit_code() == 0
        assert perfgate.gate_rebalance(
            spans, require_rebalance=True).exit_code() == 2

    def test_floor_silences_sub_ms_noise(self):
        # post-side host 1 is 2x host 0 in MICROSECONDS — noise, not
        # skew: the floor must keep post below pre
        g = perfgate.gate_rebalance(_gate_records(post_slow=0.0008),
                                    require_rebalance=True)
        assert g.post_score == pytest.approx(1.0)
        assert g.exit_code() == 0

    def test_kind_rebalance_record_places_boundary_too(self):
        recs = [r for r in _gate_records() if r["kind"] == "span"]
        recs.append({"schema_version": 1, "kind": "rebalance",
                     "run_id": "r", "at_iter": 8})
        g = perfgate.gate_rebalance(recs, require_rebalance=True)
        assert g.rebalance_iter == 8 and g.exit_code() == 0

    def test_cli_single_file_mode(self, tmp_path):
        path = tmp_path / "recs.jsonl"
        with open(path, "w") as f:
            for r in _gate_records():
                f.write(json.dumps(r) + "\n")
        from tools import perf_gate as cli

        assert cli.main([str(path), "--rebalance"]) == 0


# ---------------------------------------------------------------------------
# speculation


class TestSpeculation:
    def test_due_rule(self):
        assert not speculation_due(1.0, 0.0, 3.0)  # no median yet
        assert not speculation_due(0.2, 0.1, 3.0)
        assert speculation_due(0.5, 0.1, 3.0)

    def test_bit_identical_first_result_wins(self, staged_problem):
        """The safety argument itself: the SAME compiled segment from
        the SAME committed warm state is bit-identical, so taking
        whichever of (primary, backup) lands first changes nothing."""
        import dataclasses

        import jax

        from spark_agd_tpu.core import agd

        sp = staged_problem
        cfg4 = dataclasses.replace(sp["cfg"], num_iterations=4)
        fn = sp["seg_cache"].get((4, False))
        if fn is None:
            def _seg(ws, da, c=cfg4):
                sm, sl = sp["build"](*da)
                return agd.run_agd(sm, sp["px"], sp["rv"], ws.x, c,
                                   smooth_loss=sl, warm=ws)

            # graftlint: disable=donation -- ws is the committed
            # speculation anchor; a lost backup must leave it intact
            fn = jax.jit(_seg)

        def run_seg(ws, k):
            res = fn(ws, sp["dargs"])
            jax.block_until_ready(res.num_iters)
            return res

        warm = agd.AGDWarmState.initial(sp["w0"], sp["cfg"])
        tel = Telemetry()
        a = run_speculative_segment(run_seg, warm, 4)
        b = run_speculative_segment(run_seg, warm, 4)
        out = resolve_speculation(a, b.warm, fleet_seconds=999.0,
                                  telemetry=tel, straggler=1)
        assert out["matched"] and out["max_diff"] == 0.0
        assert out["outcome"] == "won"
        recs = [r for r in tel.records if r.get("kind") == "recovery"]
        assert recs and recs[0]["action"] == "speculative_exec"
        assert recs[0]["outcome"] == "won" and recs[0]["matched"]
        assert not any(schema.validate_record(r) for r in tel.records)

    def test_lost_outcome_and_mismatch_detected(self, staged_problem):
        import jax

        from spark_agd_tpu.core import agd

        warm = agd.AGDWarmState.initial(staged_problem["w0"],
                                        staged_problem["cfg"])
        spec = run_speculative_segment(
            lambda ws, k: _real_segment(staged_problem, ws), warm, 4)
        other = spec.warm._replace(
            x=jax.tree_util.tree_map(lambda a: a + 1e-3, spec.warm.x))
        out = resolve_speculation(spec, other, fleet_seconds=0.0,
                                  tol=1e-9)
        assert not out["matched"] and out["outcome"] == "lost"


def _real_segment(sp, ws):
    import dataclasses

    import jax

    from spark_agd_tpu.core import agd

    cfg4 = dataclasses.replace(sp["cfg"], num_iterations=4)

    def _seg(w, da):
        sm, sl = sp["build"](*da)
        return agd.run_agd(sm, sp["px"], sp["rv"], w.x, cfg4,
                           smooth_loss=sl, warm=w)

    res = _seg(ws, sp["dargs"])
    jax.block_until_ready(res.num_iters)
    return res


# ---------------------------------------------------------------------------
# schema + telemetry + report


class TestSchemaAndReport:
    def test_new_kinds_in_selfcheck(self):
        ok, msgs = schema.selfcheck()
        assert ok, msgs

    def test_examples_validate(self):
        assert not schema.validate_record(
            schema.EXAMPLE_SKEW_ESTIMATE_RECORD)
        assert not schema.validate_record(
            schema.EXAMPLE_REBALANCE_RECORD)

    def test_telemetry_helpers(self):
        tel = Telemetry()
        tel.skew_estimate(skew=2.5, speeds={"0": 1.0, "1": 0.4},
                          straggler=1)
        tel.rebalance(at_iter=12, before={"0": 6, "1": 6},
                      after={"0": 11, "1": 1}, moved=5)
        assert tel.registry.snapshot()["sched.skew"] == 2.5
        assert tel.registry.snapshot()["sched.rebalances"] == 1
        assert not any(schema.validate_record(r) for r in tel.records)

    def test_recovery_actions_registered(self):
        assert "rebalance" in schema.RECOVERY_ACTIONS
        assert "speculative_exec" in schema.RECOVERY_ACTIONS

    def test_report_scheduling_section(self, tmp_path, capsys):
        tel = Telemetry()
        tel.skew_estimate(skew=4.8, speeds={"0": 1.0, "1": 0.2},
                          straggler=1, consecutive=2)
        tel.rebalance(at_iter=12, before={"0": 6, "1": 6},
                      after={"0": 11, "1": 1}, moved=5)
        tel.recovery(action="speculative_exec", outcome="won",
                     matched=True, from_iter=4, iters=4)
        path = tmp_path / "sched.jsonl"
        with open(path, "w") as f:
            for r in tel.records:
                f.write(json.dumps(r) + "\n")
        from tools import agd_report

        assert agd_report.main(["--scheduling", str(path)]) == 0
        out = capsys.readouterr().out
        assert "scheduling" in out and "1w/0l" in out
        assert "h1=0.2" in out

        assert agd_report.main([str(path)]) == 0
        out = capsys.readouterr().out
        assert "== scheduling" in out


# ---------------------------------------------------------------------------
# ingest: explicit assignment + pinned padding


class TestIngestAssignment:
    @pytest.fixture()
    def parts(self, tmp_path):
        from spark_agd_tpu.data import libsvm

        rng = np.random.default_rng(2)
        paths = []
        for k in range(4):
            X = rng.standard_normal((5, 3)).astype(np.float32)
            y = np.where(X.sum(axis=1) > 0, 1.0, -1.0)
            p = str(tmp_path / f"part-{k}.libsvm")
            libsvm.save_libsvm(p, X, y)
            paths.append(p)
        return paths

    def test_explicit_assignment_reads_subset(self, parts,
                                              cpu_devices):
        from spark_agd_tpu.data import ingest
        from spark_agd_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.make_mesh({"data": 1})
        batch = ingest.from_partitioned_files(
            parts, mesh, n_features=3, assignment=parts[:2])
        # 2 partitions x 5 rows each (single-process: no padding
        # needed on a 1-device axis, so the mask may be None)
        assert np.asarray(batch.X).shape[0] == 10
        if batch.mask is not None:
            assert int(np.asarray(batch.mask).sum()) == 10

    def test_assignment_subset_changes_the_objective_data(
            self, parts, cpu_devices):
        from spark_agd_tpu.data import ingest
        from spark_agd_tpu.parallel import mesh as mesh_lib

        mesh = mesh_lib.make_mesh({"data": 1})
        full = ingest.from_partitioned_files(parts, mesh,
                                             n_features=3)
        sub = ingest.from_partitioned_files(
            parts, mesh, n_features=3, assignment=parts[:1])
        assert np.asarray(full.X).shape[0] == 20
        assert np.asarray(sub.X).shape[0] == 5
        assert np.allclose(np.asarray(sub.X),
                           np.asarray(full.X)[:5])


# ---------------------------------------------------------------------------
# the drill (reduced smoke — real 2-process gloo)


@pytest.mark.dist_fault
class TestStragglerDrill:
    def test_reduced_drill_passes(self, tmp_path):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        drill = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "tools", "straggler_drill.py")
        proc = subprocess.run(
            [sys.executable, drill, "--parts", "8", "--rows", "8",
             "--iters", "48", "--segment", "4", "--max-ratio", "2.5",
             "--out", str(tmp_path)],
            capture_output=True, text=True, timeout=360, env=env)
        assert proc.returncode == 0, \
            f"stdout:\n{proc.stdout[-4000:]}\nstderr:\n{proc.stderr[-2000:]}"
        assert "STRAGGLER DRILL PASSED" in proc.stdout
