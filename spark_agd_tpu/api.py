"""Public API — drop-in surface parity with the reference, TPU underneath.

Mirrors the reference's L5 (SURVEY §1): the ``AcceleratedGradientDescent``
class with its nine fluent setters and defaults (reference
``AcceleratedGradientDescent.scala:44-51, :57-120``), ``optimize(data,
initial_weights)`` (``:128``), the functional ``run(...) -> (weights,
loss_history)`` (``:177-189``), and the ``run_minibatch_agd`` alias the
north-star config names.  CamelCase aliases (``setConvergenceTol`` …) are
provided so reference-style call sites port verbatim.

What "data" is here: instead of an ``RDD[(Double, Vector)]`` the API takes
``(X, y)`` arrays, an ``(X, y, mask)`` triple, or a ``parallel.mesh.
ShardedBatch`` already placed on a mesh.  By default the optimizer runs
distributed over every visible device (a ``data``-axis mesh) — the
reference's executor parallelism with the driver round-trips deleted; pass
``mesh=False`` to force single-device, or an explicit ``jax.sharding.Mesh``
(e.g. with a ``model`` axis for wide softmax/MLP weights).
"""

from __future__ import annotations

import math
import os
from typing import Any, NamedTuple, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .core import agd, gd, smooth as smooth_lib
from .ops.losses import Gradient
from .ops.prox import Prox
from .ops.sparse import CSRMatrix
from .parallel import dist_smooth, mesh as mesh_lib, \
    sharded_update as sharded_lib

Data = Union[Tuple, "mesh_lib.ShardedBatch"]


def _normalize_data(data: Data):
    """Accept (X, y), (X, y, mask), or ShardedBatch."""
    if isinstance(data, mesh_lib.ShardedBatch):
        return data
    if isinstance(data, (tuple, list)):
        if len(data) == 2:
            return data[0], data[1], None
        if len(data) == 3:
            return data[0], data[1], data[2]
    raise TypeError(
        "data must be (X, y), (X, y, mask), or a ShardedBatch; got "
        f"{type(data).__name__}")


def _resolve_mesh(mesh):
    """None → all-device data mesh (single-device short-circuits to local);
    False → force local; a Mesh → as given."""
    if mesh is False:
        return None
    if mesh is None:
        devs = jax.devices()
        if len(devs) == 1:
            return None
        return mesh_lib.make_mesh({mesh_lib.DATA_AXIS: len(devs)})
    return mesh


def _batch_mesh(batch: "mesh_lib.ShardedBatch"):
    """The mesh a pre-placed batch lives on.  ``RowShardedCSR`` exposes
    ``.sharding`` exactly like a dense array (``ops/sparse.py``), so one
    expression serves both layouts."""
    return batch.X.sharding.mesh


def _resolve_fit_mesh(data: Data, mesh):
    """The ONE mesh-dispatch decision for every mesh-capable entry point
    (sweep, cross-validate, the GD oracle) — hand-rolled per-site
    variants drifted into real bugs (r3 review).

    Returns ``(m, batch, csr_raw)``:

    - ``batch`` is the ``ShardedBatch`` when ``data`` is pre-placed
      (``m`` is then its mesh; an explicit ``mesh`` argument must match
      or this raises), else ``None``;
    - ``m`` is the resolved mesh — ``None`` means single-device
      (``mesh=False``, or ``mesh=None`` on a single-device host);
    - ``csr_raw``: ``data`` is a raw ``(CSRMatrix, ...)`` tuple.
      Callers that cannot mesh CSR apply their policy: RAISE when the
      mesh was requested explicitly (a silently-undistributed run is
      worse than an error), fall back to single-device under the auto
      default (``mesh=None``).
    """
    if isinstance(data, mesh_lib.ShardedBatch):
        m = _batch_mesh(data)
        if mesh not in (None, False) and mesh != m:
            raise ValueError(
                "explicit mesh differs from the ShardedBatch's mesh; "
                "re-shard the batch or drop the mesh argument")
        return m, data, False
    csr_raw = (isinstance(data, (tuple, list))
               and isinstance(data[0], CSRMatrix))
    return _resolve_mesh(mesh), None, csr_raw


def _reconcile_runner_mesh(data: Data, mesh, dist_mode: str):
    """Shared ``make_*_runner`` preamble, built ON
    :func:`_resolve_fit_mesh` so the batch-mesh conflict policy has one
    copy (per-site variants drifted into real bugs, r3 review).  Two
    runner-specific extras: ``mesh=False`` forces single-device even on
    a pre-placed batch (the grid fits have no such override), and raw
    CSR forces the explicit shard_map mode (GSPMD cannot partition the
    segment-sum's row-id indirection).  Returns
    ``(data, resolved_mesh, dist_mode)``."""
    data = _normalize_data(data)
    m, batch, csr_raw = _resolve_fit_mesh(data, mesh)
    if batch is not None and mesh is False:
        m = None
    if csr_raw:
        dist_mode = "shard_map"
    return data, m, dist_mode


def _check_grid_fit(updater, reg_params, op_name: str):
    """Shared guard for every batched grid fit (AGD sweep/CV, LBFGS
    sweep): a grid through the identity prox would be silently
    ignored."""
    from .ops.prox import IdentityProx

    reg_params = list(reg_params)
    if isinstance(updater, IdentityProx) and any(
            float(r) != 0.0 for r in reg_params):
        raise ValueError(
            f"the updater is IdentityProx (no penalty), so "
            f"reg_params would be ignored; use an explicit updater "
            f"(e.g. L2Prox()) for {op_name}")
    return reg_params


def _build_smooth(gradient, data, mesh, dist_mode, sharded_update=False):
    """``(build, data_args)``: prepared/placed data as a pytree to pass
    THROUGH ``jax.jit``, plus ``build(*traced) -> (smooth, smooth_loss)``
    to call inside the trace.  Closing the jitted step over the concrete
    arrays instead would embed them as program constants and make XLA
    compile time scale with the dataset (the r4 ``compile_s: 1842.74``
    full-scale row) — see ``core.smooth.make_smooth_staged``.

    ``sharded_update=True`` returns the sharded-mode pair instead: the
    build slot is a ``parallel.sharded_update.ShardedUpdateBuild`` whose
    ``make_agd_run`` hook compiles the whole AGD loop (reduce-scatter
    gradient, 1/N-shard update, exit allgather) — consumers dispatch on
    the hook, never call the build."""
    if sharded_update:
        if mesh is None:
            raise ValueError(
                "sharded_update=True requires a mesh (the 1/N weight "
                "shard is per-replica); pass mesh= or a ShardedBatch, "
                "or drop sharded_update on a single-device run")
        if dist_mode != "shard_map":
            raise ValueError(
                "sharded_update=True requires dist_mode='shard_map' "
                "(the sharded carry is an explicit-SPMD construction "
                "the GSPMD partitioner cannot express)")
        batch = (data if isinstance(data, mesh_lib.ShardedBatch)
                 else mesh_lib.shard_batch(mesh, data[0], data[1],
                                           data[2]))
        return sharded_lib.make_sharded_staged(gradient, batch, mesh=mesh)
    if mesh is None:
        if isinstance(data, mesh_lib.ShardedBatch):
            X, y, mask = data
        else:
            X, y, mask = data
            if not isinstance(X, CSRMatrix):
                X = jnp.asarray(X)
            y = jnp.asarray(y)
            mask = None if mask is None else jnp.asarray(mask)
        # One prepare() inside the staged factory — a second prepare
        # would stage two full-size copies of a prepared layout (e.g.
        # the Pallas tile padding) in HBM.
        return smooth_lib.make_smooth_staged(gradient, X, y, mask)
    batch = (data if isinstance(data, mesh_lib.ShardedBatch)
             else mesh_lib.shard_batch(mesh, data[0], data[1], data[2]))
    return dist_smooth.make_dist_smooth_staged(gradient, batch, mesh=mesh,
                                               mode=dist_mode)


def _owned_array(x):
    """A fresh device buffer the donated step may CONSUME.  The runner
    steps donate their carry (``donate_argnums=0``), which invalidates
    the input buffer after the call — ``jnp.asarray`` would alias an
    already-placed caller array, letting donation delete the caller's
    weights out from under a second ``fit``."""
    return jnp.array(x, copy=True)


def _make_instrumented_fit(step, place_w, dargs, telemetry):
    """The telemetry twin of the plain ``fit`` closure: the same ONE
    jitted program, but each phase runs under a span timer that streams
    a ``span`` record as it closes — ``h2d_transfer`` (host→device
    weight placement), then an AOT phase split (``trace`` / ``compile``)
    on the first call per weight shape, then ``execute`` (which blocks
    until ready, so the span measures device time, not dispatch).  The
    AOT split exists so "how long did compile take" is a first-class
    metric instead of being smeared into the first execute (the r3/r4
    compile wedges were exactly this opacity); if this backend cannot
    AOT-compile the program the fit falls back to the plain jit call and
    ``execute`` absorbs the compile.

    Every phase additionally runs under a matching profiler
    ``TraceAnnotation``, and when the telemetry carries a
    ``profile_dir`` the first ``execute`` is captured as a device-
    timeline trace (``utils.profiling.OneShotTrace``) — so the span
    timers and the profiler timeline line up by name."""
    from .utils import profiling

    _AOT_FAILED = object()
    cache = {}
    capture = profiling.OneShotTrace(
        getattr(telemetry, "profile_dir", None))

    def fit(initial_weights):
        with telemetry.span("h2d_transfer"), \
                profiling.annotate("h2d_transfer"):
            w = place_w(initial_weights)
        leaves = jax.tree_util.tree_leaves(w)
        key = (jax.tree_util.tree_structure(w),
               tuple((tuple(l.shape), str(l.dtype)) for l in leaves))
        exe = cache.get(key)
        if exe is None:
            try:
                with telemetry.span("trace"), \
                        profiling.annotate("trace"):
                    lowered = step.lower(w, dargs)
                with telemetry.span("compile"), \
                        profiling.annotate("compile"):
                    exe = lowered.compile()
            except Exception:  # noqa: BLE001 — AOT unsupported here;
                # the jit path below still runs (and compiles) fine
                exe = _AOT_FAILED
            cache[key] = exe
        with capture(), telemetry.span("execute"), \
                profiling.annotate("execute"):
            if exe is _AOT_FAILED:
                res = step(w, dargs)
            else:
                res = exe(w, dargs)
            jax.block_until_ready(res)
        return res

    return fit


def make_runner(
    data: Data,
    gradient: Gradient,
    updater: Prox,
    convergence_tol: float = 1e-4,
    num_iterations: int = 100,
    reg_param: float = 0.0,
    l0: float = 1.0,
    l_exact: float = math.inf,
    beta: float = 0.5,
    alpha: float = 0.9,
    may_restart: bool = True,
    *,
    mesh=None,
    dist_mode: str = "shard_map",
    loss_mode: str = "x",
    telemetry=None,
    sharded_update: bool = False,
):
    """Build ``fit(initial_weights) -> AGDResult``, compiled ONCE.

    ``run()`` builds fresh closures per call, so jit's executable cache
    misses and a second ``run()`` on the same problem re-traces and
    re-compiles — fatal for repeated fits (hyper-parameter sweeps,
    steady-state benchmarking).  The runner returned here carries one
    ``jax.jit`` program; every ``fit`` after the first reuses it.

    ``telemetry`` (an ``obs.Telemetry``, default off): live in-loop
    streaming — the compiled loop emits one record per iteration (iter,
    loss, L, theta, step, restarted) via ``jax.debug.callback`` WHILE it
    runs, and each ``fit`` phase (h2d transfer, trace, compile, execute)
    is span-timed.  Costs a host round-trip per iteration, so the
    default ``None`` compiles the identical program as before (no
    callback in the HLO) — see ``docs/OBSERVABILITY.md``.

    ``sharded_update`` (off by default; requires a mesh and the
    ``shard_map`` dist mode): run the cross-replica sharded weight
    update (``parallel.sharded_update``, docs/PERFORMANCE.md §"sharded
    weight update") — reduce-scatter the gradient, prox/momentum on the
    1/N shard, allgather full weights only for the smooth kernel.  Same
    ``fit`` contract, same ``AGDResult``, parity within reduction
    reordering; ``False`` traces programs bit-identical to before the
    flag existed.
    """
    data, m, dist_mode = _reconcile_runner_mesh(data, mesh, dist_mode)
    build, dargs = _build_smooth(gradient, data, m, dist_mode,
                                 sharded_update=sharded_update)
    px, rv = smooth_lib.make_prox(updater, reg_param)
    cfg = agd.AGDConfig(
        convergence_tol=convergence_tol, num_iterations=num_iterations,
        l0=l0, l_exact=l_exact, beta=beta, alpha=alpha,
        may_restart=may_restart, loss_mode=loss_mode)

    tel_cb = (None if telemetry is None
              else telemetry.iteration_callback("agd"))

    if sharded_update:
        _step = build.make_agd_run(px, rv, cfg, telemetry_cb=tel_cb)
    else:
        def _step(w, da):
            sm, sl = build(*da)
            return agd.run_agd(sm, px, rv, w, cfg, smooth_loss=sl,
                               telemetry_cb=tel_cb)

    # the carry is donated: XLA aliases the weights buffer in place
    # instead of copying it (graftlint donation contract; the aliasing
    # is pinned against the compiled program by analysis.contracts) —
    # _place_w hands the program a fresh buffer it may consume.  The
    # sharded run donates the same way: its entry/exit speak full
    # replicated trees, so the result aliases the donated carry.
    step = jax.jit(_step, donate_argnums=0)

    def _place_w(initial_weights):
        w0 = jax.tree_util.tree_map(_owned_array, initial_weights)
        return w0 if m is None else mesh_lib.replicate(w0, m)

    if telemetry is None:
        def fit(initial_weights):
            return step(_place_w(initial_weights), dargs)
    else:
        fit = _make_instrumented_fit(step, _place_w, dargs, telemetry)

    # AOT hook: trace/inspect the ONE program fit() runs without
    # executing it (phase-split compiles, HLO-level guards — e.g. the
    # program-size-vs-nnz regression test; data rides as arguments, so
    # the lowered text must NOT scale with the dataset)
    fit.lower_step = lambda w0: step.lower(_place_w(w0), dargs)
    # the raw (jitted step, staged data) pair — the bench ladder binds
    # these so its AOT phase-split timing measures EXACTLY the public
    # runner's program, not a parallel reimplementation
    fit.jitted_step = step
    fit.data_args = dargs
    return fit


def run(
    data: Data,
    gradient: Gradient,
    updater: Prox,
    convergence_tol: float = 1e-4,
    num_iterations: int = 100,
    reg_param: float = 0.0,
    initial_weights: Any = None,
    l0: float = 1.0,
    l_exact: float = math.inf,
    beta: float = 0.5,
    alpha: float = 0.9,
    may_restart: bool = True,
    *,
    mesh=None,
    dist_mode: str = "shard_map",
    loss_mode: str = "x",
    return_result: bool = False,
    telemetry=None,
    verbose: bool = False,
    resilience=None,
    checkpointer=None,
    journal=None,
    sharded_update: bool = False,
):
    """Functional entry point, signature-parity with reference ``run``
    (``:177-189``).  Returns ``(weights, loss_history)`` where
    ``loss_history`` is a NumPy array with exactly one entry per executed
    iteration (the reference's ``len(lossHistory) == iterations`` contract,
    Suite:181-182).  ``return_result=True`` additionally returns the full
    ``AGDResult`` diagnostics.  For repeated fits of the same problem use
    ``make_runner`` (compiles once).

    ``telemetry`` (``obs.Telemetry``, default off): live per-iteration
    streaming + span-timed phases — see :func:`make_runner`; a ``run``
    summary record is emitted at completion.  ``verbose=True`` logs the
    post-hoc per-iteration diagnostics through ``utils.logging.
    log_result`` (the structured lines + the reference's completion/
    abort lines) on the ``spark_agd_tpu`` logger — no callback, no
    overhead inside the compiled program.

    ``resilience`` (a ``resilience.ResiliencePolicy``, or ``True`` for
    the defaults; off by default — zero new machinery in the plain
    path): run under the fault-aware supervisor instead of one bare
    fused call — segmented execution, bounded retries with backoff on
    transient failures, rollback to the last-good warm state with a
    step cut on non-finite numerics, and ``attempt``/``recovery``
    records on the telemetry stream.  ``checkpointer`` (supervised path
    only) adds preemption-safe auto-checkpointing and
    corruption-tolerant resume: a ``resilience.AutoCheckpointer``
    (single process, ``.bak`` retention chain) or a
    ``resilience.DistributedCheckpointer`` (multi-host SPMD:
    barrier-committed generations with checksummed manifests, host
    shards exchanged through one allgather, elastic resume onto a
    changed process count — see ``docs/ROBUSTNESS.md`` §distributed).
    ``return_result=True`` then returns the ``SupervisedResult`` as the
    third element.  See ``docs/ROBUSTNESS.md``.

    ``journal`` (supervised path only; a path or an open
    ``resilience.Journal``): every recovery DECISION of the run
    (``attempt``/``recovery``/``chaos``/``degraded`` records) is also
    appended to the crash-safe recovery journal — an append-only,
    CRC-per-record WAL that tolerates a torn tail and replays
    bit-identically for post-mortems and exactly-once segment
    accounting (``resilience.journal``, docs/ROBUSTNESS.md
    §recovery-journal).  A path is opened (replaying + repairing any
    torn tail from a previous crash of the same run) and closed by this
    call; an open ``Journal`` is shared and left open."""
    if initial_weights is None:
        raise ValueError("initial_weights is required")
    if resilience is not None:
        return _run_supervised(
            data, gradient, updater, convergence_tol, num_iterations,
            reg_param, initial_weights, l0, l_exact, beta, alpha,
            may_restart, mesh, dist_mode, loss_mode, return_result,
            telemetry, verbose, resilience, checkpointer, journal,
            sharded_update=sharded_update)
    if checkpointer is not None or journal is not None:
        raise ValueError(
            "checkpointer=/journal= require the supervised path; pass "
            "resilience=True (or a ResiliencePolicy) as well")
    fit = make_runner(
        data, gradient, updater, convergence_tol=convergence_tol,
        num_iterations=num_iterations, reg_param=reg_param, l0=l0,
        l_exact=l_exact, beta=beta, alpha=alpha, may_restart=may_restart,
        mesh=mesh, dist_mode=dist_mode, loss_mode=loss_mode,
        telemetry=telemetry, sharded_update=sharded_update)
    result = fit(initial_weights)
    n = int(result.num_iters)
    loss_history = np.asarray(result.loss_history)[:n]
    if telemetry is not None:
        telemetry.run_summary(
            tool="api.run", algorithm="agd", iters=n,
            final_loss=float(loss_history[-1]) if n else None,
            converged=bool(result.converged),
            restarts=int(result.num_restarts),
            backtracks=int(result.num_backtracks),
            error=("aborted: non-finite loss"
                   if bool(result.aborted_non_finite) else None))
    if verbose:
        from .utils import logging as logging_utils

        logging_utils.log_result(result)
    if return_result:
        return result.weights, loss_history, result
    return result.weights, loss_history


def _run_supervised(data, gradient, updater, convergence_tol,
                    num_iterations, reg_param, initial_weights, l0,
                    l_exact, beta, alpha, may_restart, mesh, dist_mode,
                    loss_mode, return_result, telemetry, verbose,
                    resilience, checkpointer, journal=None, *,
                    sharded_update=False):
    """The ``resilience=`` branch of :func:`run`: the same data staging
    and mesh resolution as :func:`make_runner`, driven by
    ``resilience.supervisor.run_agd_supervised`` (segmented fused
    programs — data rides as jit ARGUMENTS, so supervision costs no
    extra compiles beyond one per segment length)."""
    from .resilience import supervisor as supervisor_lib

    policy = None if resilience is True else resilience
    data, m, dist_mode = _reconcile_runner_mesh(data, mesh, dist_mode)
    build, dargs = _build_smooth(gradient, data, m, dist_mode,
                                 sharded_update=sharded_update)
    px, rv = smooth_lib.make_prox(updater, reg_param)
    cfg = agd.AGDConfig(
        convergence_tol=convergence_tol, num_iterations=num_iterations,
        l0=l0, l_exact=l_exact, beta=beta, alpha=alpha,
        may_restart=may_restart, loss_mode=loss_mode)

    def _place_w(w):
        w0 = jax.tree_util.tree_map(jnp.asarray, w)
        return w0 if m is None else mesh_lib.replicate(w0, m)

    # journal= wiring: a JournalSink rides the run's event bus for the
    # duration of this call.  A bare journal (telemetry=None) gets a
    # decision-records-only Telemetry with the in-loop iteration stream
    # OFF — the compiled program stays identical to the plain path.
    jrnl = sink = None
    own_journal = False
    stream_iterations = telemetry is not None
    if journal is not None:
        from .obs import Telemetry
        from .resilience import journal as journal_lib

        if isinstance(journal, journal_lib.Journal):
            jrnl = journal
        else:
            jrnl = journal_lib.Journal(os.fspath(journal))
            own_journal = True
        sink = journal_lib.JournalSink(jrnl)
        if telemetry is None:
            telemetry = Telemetry([sink])
        else:
            telemetry.bus.sinks.append(sink)
        telemetry.journal_replay(**jrnl.replay_summary)

    try:
        sres = supervisor_lib.run_agd_supervised(
            prox=px, reg_value=rv, w0=initial_weights, config=cfg,
            policy=policy, telemetry=telemetry,
            checkpointer=checkpointer, staged=(build, dargs),
            place_w=_place_w, stream_iterations=stream_iterations)
    finally:
        if sink is not None:
            if sink in telemetry.bus.sinks:
                telemetry.bus.sinks.remove(sink)
            if own_journal:
                jrnl.close()
            else:
                jrnl.flush()
    loss_history = np.asarray(sres.loss_history)
    if telemetry is not None:
        telemetry.run_summary(
            tool="api.run", algorithm="agd", iters=int(sres.num_iters),
            final_loss=(float(loss_history[-1]) if len(loss_history)
                        else None),
            converged=bool(sres.converged),
            error=("aborted: non-finite loss"
                   if sres.aborted_non_finite else None))
    if verbose:
        from .utils import logging as logging_utils

        logging_utils.logger.info(
            "supervised run: %d iterations, %d retries, %d rollbacks, "
            "resumed from %d", sres.num_iters, sres.retries,
            sres.rollbacks, sres.resumed_from)
    if return_result:
        return sres.weights, loss_history, sres
    return sres.weights, loss_history


def run_minibatch_agd(
    data: Data,
    gradient: Gradient,
    updater: Prox,
    minibatch_fraction: float = 1.0,
    seed: int = 42,
    **kwargs,
):
    """``runMiniBatchAGD`` entry point (named by the north-star config).

    AGD's backtracking line search requires a *consistent* smooth function
    across the evaluations of one run — per-iteration resampling (MLlib
    SGD style) would make the Lipschitz estimates incoherent.  So the
    mini-batch here is one fixed Bernoulli subsample of the dataset drawn
    up front (deterministic in ``seed``), then full AGD on it.
    """
    if not 0.0 < minibatch_fraction <= 1.0:
        raise ValueError("minibatch_fraction must be in (0, 1]")
    if minibatch_fraction < 1.0:
        X, y, mask = _normalize_data(data)
        n = X.shape[0]
        rng = np.random.default_rng(seed)
        sample = (rng.random(n) < minibatch_fraction).astype(np.float32)
        mask = sample if mask is None else np.asarray(mask) * sample
        data = (X, y, mask)
    return run(data, gradient, updater, **kwargs)


def make_sweep_runner(
    data: Data,
    gradient: Gradient,
    updater: Prox,
    convergence_tol: float = 1e-4,
    num_iterations: int = 100,
    l0: float = 1.0,
    l_exact: float = math.inf,
    beta: float = 0.5,
    alpha: float = 0.9,
    may_restart: bool = True,
    *,
    mesh=False,
    loss_mode: str = "x",
):
    """Build ``fit(initial_weights, reg_params) -> batched AGDResult``,
    compiled ONCE — the ``make_runner`` twin of :func:`sweep` for
    repeated paths (cross-validation folds, warm-started grids).

    ``mesh``: ``False`` (default) runs single-device — the sweep axis is
    the parallel axis.  Pass a ``jax.sharding.Mesh`` (or ``None`` for
    the all-device data mesh) to ALSO shard rows over the mesh's
    ``data`` axis: lanes are vmapped inside one shard_map, so the grid
    runs on the full mesh the way the reference runs its sequential
    grid on the full cluster (``AcceleratedGradientDescent.scala:128``
    per job) — mandatory at scales where one device cannot hold the
    rows.  A ``ShardedBatch`` (dense or nnz-balanced ``RowShardedCSR``)
    is accepted and implies its own mesh.
    """
    cfg = agd.AGDConfig(
        convergence_tol=convergence_tol, num_iterations=num_iterations,
        l0=l0, l_exact=l_exact, beta=beta, alpha=alpha,
        may_restart=may_restart, loss_mode=loss_mode)

    mesh, batch, _ = _resolve_fit_mesh(data, mesh)  # CSR meshes fine
    if mesh is not None:
        from .parallel import grid

        if batch is None:
            batch = mesh_lib.shard_batch(mesh, *_normalize_data(data))
        mesh_fit = grid.make_mesh_sweep_fit(gradient, updater, batch,
                                            mesh, cfg)

        def fit(initial_weights, reg_params, warm=None):
            return mesh_fit(reg_params, initial_weights, warm=warm)

        return fit

    X, y, mask = _normalize_data(data)
    # the single-device branch of the shared builder: one prepare(), one
    # staged copy (see _build_smooth's prepare-once invariant); the data
    # rides as a lane-invariant vmap/jit argument, not a program constant
    build, dargs = _build_smooth(gradient, (X, y, mask), None, "shard_map")

    def fit_one(reg, w0, da, warm=None):
        sm, sl = build(*da)
        px, rv = smooth_lib.make_prox(updater, reg)
        return agd.run_agd(sm, px, rv, w0, cfg, smooth_loss=sl,
                           warm=warm)

    step = jax.jit(jax.vmap(fit_one, in_axes=(0, None, None)))
    step_warm = jax.jit(jax.vmap(fit_one, in_axes=(0, None, None, 0)))

    def fit(initial_weights, reg_params, warm=None):
        """``warm`` (optional): a BATCHED ``AGDWarmState`` — one carry
        per lane, e.g. ``sweep_warm_state(previous_result)`` — to
        continue every lane exactly where a prior segment stopped
        (checkpoint-style segmented paths)."""
        regs = jnp.asarray(reg_params, jnp.float32)
        if regs.ndim != 1:
            raise ValueError("reg_params must be 1-D")
        w0 = jax.tree_util.tree_map(jnp.asarray, initial_weights)
        if warm is None:
            return step(regs, w0, dargs)
        return step_warm(regs, w0, dargs, warm)

    return fit


def sweep_warm_state(res, prior_iters=0) -> "agd.AGDWarmState":
    """The batched continuation carry out of a sweep's ``AGDResult`` —
    the per-lane twin of ``utils.checkpoint.warm_from_result``.  Feed to
    ``make_sweep_runner``'s ``fit(..., warm=...)`` to run the next
    segment of every lane.

    ``prior_iters``: iterations already executed BEFORE the segment
    ``res`` came from (0 for the first continuation; pass the previous
    warm's ``prior_iters`` when chaining further segments) — the total
    must accumulate so the ``nIter > 1`` exact-zero-step gate makes the
    same stop decisions as an uninterrupted run."""
    return agd.AGDWarmState(
        x=res.weights, z=res.final_z, theta=res.final_theta,
        big_l=res.final_l, bts=res.final_bts,
        prior_iters=jnp.asarray(prior_iters, jnp.int32) + res.num_iters)


def sweep(
    data: Data,
    gradient: Gradient,
    updater: Prox,
    reg_params,
    convergence_tol: float = 1e-4,
    num_iterations: int = 100,
    initial_weights: Any = None,
    l0: float = 1.0,
    l_exact: float = math.inf,
    beta: float = 0.5,
    alpha: float = 0.9,
    may_restart: bool = True,
    *,
    mesh=False,
    loss_mode: str = "x",
):
    """Fit ONE problem at K regularization strengths in ONE compiled
    program — the regularization path, batched over the sweep axis.

    This is a capability the reference's architecture cannot express: a
    Spark regularization path is K sequential jobs, each re-broadcasting
    weights and re-reducing gradients.  Here ``jax.vmap`` batches the
    entire fused AGD loop over ``reg_params``: the dataset stays
    resident in HBM ONCE (shared by every lane), the K margin matvecs
    fuse into one ``(N, D) @ (D, K)`` MXU matmul — *better* MXU
    utilization than a single fit — and each lane converges
    independently (the ``lax.while_loop`` batching rule masks finished
    lanes, so per-lane ``convergence_tol`` semantics are preserved;
    wall-clock runs until the slowest lane finishes).

    Returns a batched ``AGDResult``: every field gains a leading K axis
    (``weights[k]``, ``loss_history[k]``, ``num_iters[k]``, …).

    ``mesh=False`` (default) evaluates single-device — the sweep axis is
    the parallel axis.  Pass a ``Mesh`` / ``None`` / a ``ShardedBatch``
    to also shard rows over the mesh's ``data`` axis (lanes replicated,
    rows sharded; see ``parallel.grid``).  Re-traces per call like
    :func:`run`; use :func:`make_sweep_runner` for repeated fits.
    """
    if initial_weights is None:
        raise ValueError("initial_weights is required")
    fit = make_sweep_runner(
        data, gradient, updater, convergence_tol=convergence_tol,
        num_iterations=num_iterations, l0=l0, l_exact=l_exact, beta=beta,
        alpha=alpha, may_restart=may_restart, mesh=mesh,
        loss_mode=loss_mode)
    return fit(initial_weights, reg_params)


class CVResult(NamedTuple):
    """``cross_validate`` output: everything is device-resident and
    indexed ``[fold, strength]``."""

    val_loss: jax.Array       # (F, R) mean smooth loss on the held-out fold
    train_result: Any         # batched AGDResult, leading axes (F, R)
    mean_val_loss: jax.Array  # (R,) averaged over folds
    best_index: jax.Array     # () argmin of mean_val_loss
    fold_ids: jax.Array       # (N,) the fold assignment used
    base_mask: jax.Array      # (N,) validity mask the CV ran under (all
    #                           ones when the data carried none) — post-hoc
    #                           scorers (models.evaluation.
    #                           cv_validation_scores) default to it


def make_cv_runner(
    data: Data,
    gradient: Gradient,
    updater: Prox,
    n_folds: int = 5,
    convergence_tol: float = 1e-4,
    num_iterations: int = 100,
    l0: float = 1.0,
    l_exact: float = math.inf,
    beta: float = 0.5,
    alpha: float = 0.9,
    may_restart: bool = True,
    *,
    mesh=False,
    loss_mode: str = "x",
    seed: int = 0,
):
    """Build ``fit(initial_weights, reg_params) -> CVResult``, compiled
    once per grid SHAPE — the ``make_sweep_runner`` twin of
    :func:`cross_validate` for repeated CV (refined grids, warm-start
    studies).  Repeated calls with the same number of strengths reuse
    one executable; the fold assignment (``seed``) and data staging
    happen once, at build time."""
    return _build_cv(data, gradient, updater, n_folds, convergence_tol,
                     num_iterations, l0, l_exact, beta, alpha,
                     may_restart, mesh, loss_mode, seed)


def cross_validate(
    data: Data,
    gradient: Gradient,
    updater: Prox,
    reg_params,
    n_folds: int = 5,
    convergence_tol: float = 1e-4,
    num_iterations: int = 100,
    initial_weights: Any = None,
    l0: float = 1.0,
    l_exact: float = math.inf,
    beta: float = 0.5,
    alpha: float = 0.9,
    may_restart: bool = True,
    *,
    mesh=False,
    loss_mode: str = "x",
    seed: int = 0,
) -> CVResult:
    """K-fold cross-validation over a regularization grid — ALL
    ``n_folds x len(reg_params)`` fits AND their held-out evaluations in
    ONE compiled program.

    The lane axis is the flattened (fold, strength) grid: every lane
    trains on its fold's complement through a traced mask (the kernels'
    ``mask`` argument — the same mechanism that excludes padding), then
    evaluates the smooth loss on the held-out fold inside the same
    program.  The dataset lives in HBM once, shared by every lane; the
    margin matvecs batch onto the MXU exactly as in :func:`sweep`.  A
    Spark grid search is F·R sequential jobs with F·R·iterations
    broadcast/reduce round-trips; this is one launch.

    **Cost shape — quietly quadratic in coverage:** every (fold,
    strength) lane evaluates the FULL N×D matvec with a mask, so one CV
    launch costs ~``n_folds * len(reg_params)`` times the FLOPs of one
    fit, with ``(n_folds-1)/n_folds`` of each lane's rows contributing
    zeros.  That trade is deliberate at moderate scale (one launch, no
    gathers, perfect MXU batching) but real at config scale: when
    ``n_folds * len(reg_params)`` is large relative to available FLOPs,
    prefer :func:`sweep` over manually compacted per-fold subsets (F
    separate sweeps over N·(F-1)/F gathered rows — F times less masked
    waste at the cost of F launches).

    Folds are a deterministic (``seed``) uniform assignment.  Rows
    masked out by an input ``(X, y, mask)`` triple stay excluded from
    BOTH training and validation everywhere.

    ``mesh=False`` (default) runs single-device.  Pass a ``Mesh`` /
    ``None`` / a ``ShardedBatch`` (dense or nnz-balanced
    ``RowShardedCSR``) to shard rows over the mesh's ``data`` axis —
    lanes vmapped inside one shard_map (``parallel.grid``), the
    cluster-wide grid the reference runs as sequential jobs.  Sparse
    (CSR) fold ids follow the nnz-balanced row permutation through the
    sharding's extras channel (``mesh.shard_csr_batch``), so raw-CSR
    mesh CV matches the single-device fold assignment in input-row
    order; a PRE-placed CSR batch assigns folds in its padded layout
    order instead (see ``parallel.grid.make_mesh_cv_fit``).
    """
    fit = make_cv_runner(
        data, gradient, updater, n_folds=n_folds,
        convergence_tol=convergence_tol,
        num_iterations=num_iterations, l0=l0, l_exact=l_exact,
        beta=beta, alpha=alpha, may_restart=may_restart, mesh=mesh,
        loss_mode=loss_mode, seed=seed)
    return fit(initial_weights, reg_params)


def _build_cv(data, gradient, updater, n_folds, convergence_tol,
              num_iterations, l0, l_exact, beta, alpha, may_restart,
              mesh, loss_mode, seed):
    """Shared CV builder: stage data, assign folds, and compile the
    lane grid ONCE; the returned ``fit(initial_weights, reg_params)``
    reuses one executable per grid shape."""
    if n_folds < 2:
        raise ValueError("n_folds must be >= 2")
    cfg = agd.AGDConfig(
        convergence_tol=convergence_tol, num_iterations=num_iterations,
        l0=l0, l_exact=l_exact, beta=beta, alpha=alpha,
        may_restart=may_restart, loss_mode=loss_mode)

    def _fold_assignment(n):
        # balanced assignment (round-robin over a random permutation):
        # fold sizes differ by at most 1, so no fold is empty for
        # n >= n_folds — an empty fold would silently score 0.0
        # validation loss
        perm = jax.random.permutation(jax.random.PRNGKey(seed), n)
        return jnp.zeros(n, jnp.int32).at[perm].set(
            jnp.arange(n, dtype=jnp.int32) % n_folds)

    m, batch, _ = _resolve_fit_mesh(data, mesh)
    if m is not None:
        from .parallel import grid

        if batch is not None:
            # Pre-placed batch: assign folds in its (padded) row layout —
            # for a RowShardedCSR that is the nnz-balanced permutation
            # (not recoverable here), which is equivalent for the random
            # uniform assignment; callers needing input-row-order folds
            # shard with shard_csr_batch(extras={"fold_ids": ...}) and
            # drive parallel.grid.make_mesh_cv_fit directly.
            n = batch.y.shape[0]  # padded layout; mask covers padding
            fold_ids = _fold_assignment(n)
            base_mask = (batch.mask if batch.mask is not None
                         else jnp.ones(n, jnp.float32))
            fids_sharded = grid.shard_row_array(m, np.asarray(fold_ids),
                                                n, fill=-1)
        else:
            X, y, base_mask = _normalize_data(data)
            n = X.shape[0]
            fold_ids = _fold_assignment(n)
            base_mask = (jnp.ones(n, jnp.float32) if base_mask is None
                         else jnp.asarray(base_mask, jnp.float32))
            if isinstance(X, CSRMatrix):
                # fold ids ride the extras channel through the
                # nnz-balanced row permutation, so they stay aligned to
                # the permuted layout while matching the single-device
                # assignment in input-row order
                batch, placed = mesh_lib.shard_csr_batch(
                    m, X, y, np.asarray(base_mask),
                    extras={"fold_ids": np.asarray(fold_ids)})
                fids_sharded = placed["fold_ids"]
            else:
                batch = mesh_lib.shard_batch(m, X, y,
                                             np.asarray(base_mask))
                fids_sharded = grid.shard_row_array(
                    m, np.asarray(fold_ids), batch.y.shape[0], fill=-1)
        mesh_fit = grid.make_mesh_cv_fit(gradient, updater, batch,
                                         fids_sharded, m, cfg)
        run = mesh_fit
    else:
        X, y, base_mask = _normalize_data(data)
        n = X.shape[0]
        if not isinstance(X, CSRMatrix):
            X = jnp.asarray(X)
        y = jnp.asarray(y)
        base_mask = (jnp.ones(n, jnp.float32) if base_mask is None
                     else jnp.asarray(base_mask, jnp.float32))
        X, y, _ = gradient.prepare(X, y, None)
        if getattr(X, "shape", (None,))[0] != n:
            raise ValueError(
                "cross_validate drives masks through the kernels, so a "
                "gradient whose prepare() re-pads rows (e.g. the fused "
                "Pallas layouts) is not supported here; use the plain "
                "XLA gradients")
        fold_ids = _fold_assignment(n)

        dargs = (X, y, base_mask, fold_ids)

        def fit_one(fold_k, reg, w0, da):
            Xa, ya, bm, fids = da
            train_mask = bm * (fids != fold_k)
            val_mask = bm * (fids == fold_k)
            sm = lambda w: gradient.mean_loss_and_grad(w, Xa, ya,
                                                       train_mask)
            sl = lambda w: _mean_loss(gradient, w, Xa, ya, train_mask)
            px, rv = smooth_lib.make_prox(updater, reg)
            res = agd.run_agd(sm, px, rv, w0, cfg, smooth_loss=sl)
            val = _mean_loss(gradient, res.weights, Xa, ya, val_mask)
            return val, res

        step = jax.jit(jax.vmap(fit_one, in_axes=(0, 0, None, None)))

        def run(fold_lane, reg_lane, initial_weights):
            w0 = jax.tree_util.tree_map(jnp.asarray, initial_weights)
            return step(fold_lane, reg_lane, w0, dargs)

    def fit(initial_weights, reg_params):
        if initial_weights is None:
            raise ValueError("initial_weights is required")
        regs = jnp.asarray(reg_params, jnp.float32)
        if regs.ndim != 1:
            raise ValueError("reg_params must be 1-D")
        n_regs = regs.shape[0]
        fold_lane = jnp.repeat(jnp.arange(n_folds, dtype=jnp.int32),
                               n_regs)
        reg_lane = jnp.tile(regs, n_folds)
        val_flat, res_flat = run(fold_lane, reg_lane, initial_weights)
        val_loss = val_flat.reshape(n_folds, n_regs)
        train_result = jax.tree_util.tree_map(
            lambda a: a.reshape((n_folds, n_regs) + a.shape[1:]),
            res_flat)
        # nanmean: a fold emptied by the base mask reports NaN (see
        # _mean_loss) and must not poison every strength's average; a
        # strength with NO valid fold stays NaN and argmin will not
        # pick it (NaN comparisons are false) unless ALL are NaN —
        # callers refitting on best_index must check finiteness (the
        # model layer does).
        mean_val = jnp.nanmean(val_loss, axis=0)
        return CVResult(val_loss=val_loss, train_result=train_result,
                        mean_val_loss=mean_val,
                        best_index=jnp.argmin(mean_val),
                        fold_ids=fold_ids, base_mask=base_mask)

    return fit


def _mean_loss(gradient, w, X, y, mask):
    ls, _, cnt = gradient.batch_loss_and_grad(w, X, y, mask)
    cnt = jnp.asarray(cnt, ls.dtype)
    # an empty selection (e.g. a base mask emptying a fold) must read as
    # NaN, never as a perfect 0.0 loss
    return jnp.where(cnt > 0, ls / jnp.maximum(cnt, 1), jnp.nan)


class AcceleratedGradientDescent:
    """Config-holder class, reference ``:41-144``: nine fluent setters with
    the reference's defaults, one ``optimize``."""

    def __init__(self, gradient: Gradient, updater: Prox):
        self._gradient = gradient
        self._updater = updater
        self._convergence_tol = 1e-4
        self._num_iterations = 100
        self._reg_param = 0.0
        self._l0 = 1.0
        self._l_exact = math.inf
        self._beta = 0.5
        self._alpha = 0.9
        self._may_restart = True
        self._mesh = None
        self._dist_mode = "shard_map"
        self._loss_mode = "x"

    # -- the nine reference setters (snake_case + camelCase parity) -------
    def set_convergence_tol(self, tol: float):
        self._convergence_tol = float(tol)
        return self

    def set_num_iterations(self, iters: int):
        self._num_iterations = int(iters)
        return self

    def set_reg_param(self, reg_param: float):
        self._reg_param = float(reg_param)
        return self

    def set_l0(self, l0: float):
        self._l0 = float(l0)
        return self

    def set_lexact(self, l_exact: float):
        self._l_exact = float(l_exact)
        return self

    def set_beta(self, beta: float):
        self._beta = float(beta)
        return self

    def set_alpha(self, alpha: float):
        self._alpha = float(alpha)
        return self

    def set_may_restart(self, may_restart: bool):
        self._may_restart = bool(may_restart)
        return self

    def set_gradient(self, gradient: Gradient):
        self._gradient = gradient
        return self

    def set_updater(self, updater: Prox):
        self._updater = updater
        return self

    # TPU-specific knobs (beyond the reference surface)
    def set_mesh(self, mesh):
        self._mesh = mesh
        return self

    def set_loss_mode(self, loss_mode: str):
        self._loss_mode = loss_mode
        return self

    def set_dist_mode(self, dist_mode: str):
        """'shard_map' (explicit psum) or 'auto' (GSPMD; required for
        model-axis tensor parallelism through this class)."""
        self._dist_mode = dist_mode
        return self

    # camelCase aliases for verbatim ports of reference call sites
    setConvergenceTol = set_convergence_tol
    setNumIterations = set_num_iterations
    setRegParam = set_reg_param
    setL0 = set_l0
    setLexact = set_lexact
    setBeta = set_beta
    setAlpha = set_alpha
    setMayRestart = set_may_restart
    setGradient = set_gradient
    setUpdater = set_updater

    def optimize(self, data: Data, initial_weights: Any):
        """reference ``:128-144``: run and return the solution weights."""
        weights, _ = run(
            data, self._gradient, self._updater,
            convergence_tol=self._convergence_tol,
            num_iterations=self._num_iterations,
            reg_param=self._reg_param,
            initial_weights=initial_weights,
            l0=self._l0, l_exact=self._l_exact, beta=self._beta,
            alpha=self._alpha, may_restart=self._may_restart,
            mesh=self._mesh, dist_mode=self._dist_mode,
            loss_mode=self._loss_mode)
        return weights

    def _check_grid_fit(self, reg_params, op_name: str):
        return _check_grid_fit(self._updater, reg_params, op_name)

    def sweep(self, data: Data, reg_params, initial_weights: Any):
        """Regularization path with this object's configuration: K
        strengths in one compiled program (module-level :func:`sweep`).
        ``set_reg_param`` is ignored — the grid supplies the strengths.
        The optimizer's mesh composes: like ``optimize``, the default
        (``None``) shards rows over every visible device; ``set_mesh
        (False)`` forces single-device.  The config forwarding lives
        HERE so every optimizer knob reaches the sweep the way
        ``optimize`` forwards it."""
        reg_params = self._check_grid_fit(reg_params, "sweep")
        return sweep(
            data, self._gradient, self._updater, reg_params,
            convergence_tol=self._convergence_tol,
            num_iterations=self._num_iterations,
            initial_weights=initial_weights,
            l0=self._l0, l_exact=self._l_exact, beta=self._beta,
            alpha=self._alpha, may_restart=self._may_restart,
            mesh=self._mesh, loss_mode=self._loss_mode)

    def cross_validate(self, data: Data, reg_params,
                       initial_weights: Any, n_folds: int = 5,
                       seed: int = 0) -> CVResult:
        """K-fold CV over a grid with this object's configuration —
        every (fold, strength) fit and its held-out evaluation in one
        compiled program (module-level :func:`cross_validate`).  The
        optimizer's mesh composes exactly as in :meth:`sweep`."""
        reg_params = self._check_grid_fit(reg_params, "cross_validate")
        return cross_validate(
            data, self._gradient, self._updater, reg_params,
            n_folds=n_folds, convergence_tol=self._convergence_tol,
            num_iterations=self._num_iterations,
            initial_weights=initial_weights,
            l0=self._l0, l_exact=self._l_exact, beta=self._beta,
            alpha=self._alpha, may_restart=self._may_restart,
            mesh=self._mesh, loss_mode=self._loss_mode, seed=seed)


def _stack_lanes(initial_weights, k: int):
    """Broadcast one starting point onto a leading K lane axis — the
    streaming grid-fit family's convention (one copy)."""
    return jax.tree_util.tree_map(
        lambda a: jnp.stack([jnp.asarray(a)] * k), initial_weights)


def streaming_sweep(
    dataset,
    gradient: Gradient,
    updater: Prox,
    reg_params,
    convergence_tol: float = 1e-4,
    num_iterations: int = 100,
    initial_weights: Any = None,
    l0: float = 1.0,
    l_exact: float = math.inf,
    beta: float = 0.5,
    alpha: float = 0.9,
    may_restart: bool = True,
    *,
    mesh=None,
    pad_to=None,
    csr_nnz_per_shard=None,
    loss_mode: str = "x",
):
    """Train a K-strength regularization path over a STREAMED dataset —
    one stream read per trial for ALL lanes.

    The in-memory :func:`sweep` requires the data in HBM; this is its
    larger-than-HBM member: the K lanes run the host AGD driver in
    lock-step (``core.host_agd.run_agd_host_multi``, per-lane semantics
    pinned exactly against solo runs) over a multi-lane streamed smooth
    (``data.streaming.make_streaming_eval_multi`` — per macro-batch the
    K margin products fuse into one ``(rows, D) @ (D, K)``
    contraction).  A solo sweep over a stream costs K full stream reads
    per evaluation; this costs ONE.

    ``dataset`` is a ``data.streaming.StreamingDataset``; ``mesh``
    follows the streaming modules' convention (``None`` = single
    device, pass a ``Mesh`` to shard each macro-batch).  Returns a
    ``core.host_agd.HostAGDMultiResult`` (leading K axis per field;
    ``loss_history[:, k][:num_iters[k]]`` is lane k's history).
    """
    if initial_weights is None:
        raise ValueError("initial_weights is required")
    from .core import host_agd
    from .data import streaming as streaming_lib

    regs = list(reg_params)
    sm = streaming_lib.make_streaming_eval_multi(
        gradient, dataset, mesh=mesh, pad_to=pad_to,
        csr_nnz_per_shard=csr_nnz_per_shard)
    sl = streaming_lib.make_streaming_eval_multi(
        gradient, dataset, mesh=mesh, pad_to=pad_to,
        csr_nnz_per_shard=csr_nnz_per_shard, with_grad=False)
    pxm, rvm = host_agd.make_prox_multi(updater, regs)
    W0 = _stack_lanes(initial_weights, len(regs))
    cfg = agd.AGDConfig(
        convergence_tol=convergence_tol, num_iterations=num_iterations,
        l0=l0, l_exact=l_exact, beta=beta, alpha=alpha,
        may_restart=may_restart, loss_mode=loss_mode)
    return host_agd.run_agd_host_multi(sm, pxm, rvm, W0, cfg,
                                       smooth_loss_multi=sl)


def run_minibatch_sgd(
    data: Data,
    gradient: Gradient,
    updater: Prox,
    step_size: float = 1.0,
    num_iterations: int = 100,
    reg_param: float = 0.0,
    minibatch_fraction: float = 1.0,
    initial_weights: Any = None,
    seed: int = 42,
    *,
    mesh=False,
):
    """MLlib ``GradientDescent.runMiniBatchSGD`` equivalent — the oracle
    the reference tests against (SURVEY §2.2).
    Returns ``(weights, loss_history)``.

    ``mesh=False`` (default) evaluates single-device.  Pass a ``Mesh`` /
    ``None`` / a dense ``ShardedBatch`` to shard rows over the mesh's
    ``data`` axis — the reference's GD *is* distributed (MLlib's
    ``runMiniBatchSGD`` runs the same treeAggregate as AGD), and the
    Bernoulli sample sequence is bit-identical to a single-device run
    on the identically-padded arrays (``core.gd.run_minibatch_sgd``'s
    global-sample contract).  Dense only: the nnz-balanced CSR shard
    layout permutes rows, which would break the contiguous
    global-sample slicing.
    """
    if initial_weights is None:
        raise ValueError("initial_weights is required")
    w0 = jax.tree_util.tree_map(jnp.asarray, initial_weights)
    kw = dict(step_size=step_size, num_iterations=num_iterations,
              reg_param=reg_param,
              minibatch_fraction=minibatch_fraction, seed=seed)

    m, batch, csr_raw = _resolve_fit_mesh(data, mesh)
    if csr_raw:
        if mesh not in (None, False):
            # an explicitly requested mesh must not silently degrade to
            # an undistributed run (r3 review)
            raise ValueError(
                "mesh run_minibatch_sgd supports dense data only (the "
                "nnz-balanced CSR layout permutes rows, breaking the "
                "global Bernoulli sample slicing); drop the mesh "
                "argument for a single-device oracle run")
        m = None  # auto default: single-device handles CSR fine
    if m is not None:
        import functools

        from jax import lax
        from jax.sharding import PartitionSpec as P

        from .parallel.shmap import shard_map

        if batch is not None:
            if isinstance(batch.X, mesh_lib.RowShardedCSR):
                raise ValueError(
                    "mesh run_minibatch_sgd supports dense batches "
                    "only (the nnz-balanced CSR layout permutes rows, "
                    "breaking the global Bernoulli sample slicing)")
        else:
            batch = mesh_lib.shard_batch(m, *_normalize_data(data))
        X, y, mask = batch
        axis = mesh_lib.DATA_AXIS
        n_global = X.shape[0]
        rows_per_shard = n_global // m.shape[axis]
        row = P(axis)
        xspec = P(axis, *([None] * (X.ndim - 1)))
        has_mask = mask is not None
        in_specs = (P(), xspec, row) + ((row,) if has_mask else ())

        def _body(w, Xs, ys, *ms):
            off = lax.axis_index(axis) * rows_per_shard
            return gd.run_minibatch_sgd(
                gradient, updater, Xs, ys, w,
                mask=ms[0] if has_mask else None, data_axis=axis,
                global_rows=n_global, row_offset=off, **kw)

        step = jax.jit(functools.partial(
            shard_map, mesh=m, in_specs=in_specs, out_specs=P(),
            check_vma=False)(_body))
        args = (X, y, mask) if has_mask else (X, y)
        res = step(mesh_lib.replicate(w0, m), *args)
        return res.weights, np.asarray(res.loss_history)

    X, y, mask = _normalize_data(data)
    if not isinstance(X, CSRMatrix):
        X = jnp.asarray(X)
    y = jnp.asarray(y)
    mask = None if mask is None else jnp.asarray(mask)
    # graftlint: disable=donation -- one-shot program on the CALLER'S
    # w0; donating would invalidate their buffer for a single execution
    res = jax.jit(
        lambda w, Xa, ya, ma: gd.run_minibatch_sgd(
            gradient, updater, Xa, ya, w, mask=ma, **kw))(w0, X, y, mask)
    return res.weights, np.asarray(res.loss_history)


def make_lbfgs_runner(
    data: Data,
    gradient: Gradient,
    updater: Prox,
    num_corrections: int = 10,
    convergence_tol: float = 1e-4,
    num_iterations: int = 100,
    reg_param: float = 0.0,
    *,
    grad_tol: float = 0.0,
    mesh=None,
    dist_mode: str = "shard_map",
    telemetry=None,
):
    """Build ``fit(initial_weights) -> LBFGSResult``, compiled ONCE — the
    quasi-Newton member of the reference's ``Optimizer`` family (MLlib
    1.3's ``LBFGS``, the other optimizer the reference is drop-in
    interchangeable with; SURVEY §1 L5).

    The objective is the mean data loss plus the updater's penalty.
    Smooth (L2) penalties fold straight into the objective — exactly
    MLlib LBFGS's ``CostFun`` treatment of ``SquaredL2Updater`` — and
    run the strong-Wolfe L-BFGS; an L1 / elastic-net updater routes to
    **OWL-QN** (``core.lbfgs.run_owlqn``) via
    ``Prox.owlqn_decomposition``, the same lift Spark applied after 1.3
    (Breeze OWLQN under ``ml``).  An updater offering neither split is
    rejected before any data staging.

    ``mesh`` composes exactly as in :func:`make_runner`: the psum lives
    inside the objective, so the identical fused minimizer (two-loop
    recursion + Wolfe search as one ``lax.while_loop`` program,
    ``core/lbfgs.py``) runs single-device or row-sharded.

    ``telemetry`` (``obs.Telemetry``, default off): live per-iteration
    streaming from inside the fused quasi-Newton loop plus span-timed
    fit phases — the same contract and overhead caveat as
    :func:`make_runner` (records carry ``algorithm`` = the real
    dispatch, ``lbfgs`` or ``owlqn``).
    """
    from .core import lbfgs as lbfgs_lib, tvec

    decomp = updater.owlqn_decomposition(float(reg_param))  # before
    # any data staging: an unsupported updater must fail free
    if decomp is None:
        raise ValueError(
            f"{type(updater).__name__} offers neither a smooth penalty "
            "nor an L1+smooth split (Prox.owlqn_decomposition); the "
            "quasi-Newton drivers cannot represent it — use "
            "AcceleratedGradientDescent")
    l1_coeff, extra = decomp
    data, m, dist_mode = _reconcile_runner_mesh(data, mesh, dist_mode)
    build, dargs = _build_smooth(gradient, data, m, dist_mode)
    cfg = lbfgs_lib.LBFGSConfig(
        num_corrections=num_corrections,
        convergence_tol=convergence_tol,
        num_iterations=num_iterations, grad_tol=grad_tol)

    def _objective(sm):
        def objective(w):
            f, g = sm(w)
            pv, pg = extra(w)
            return f + pv, tvec.add(g, pg)

        return objective

    algorithm = "owlqn" if l1_coeff > 0 else "lbfgs"
    tel_cb = (None if telemetry is None
              else telemetry.iteration_callback(algorithm))
    # carry donated exactly as in make_runner: the quasi-Newton loop's
    # weight buffer aliases in place (pinned by analysis.contracts)
    if l1_coeff > 0:
        step = jax.jit(lambda w, da: lbfgs_lib.run_owlqn(
            _objective(build(*da)[0]), w, l1_coeff, cfg,
            telemetry_cb=tel_cb), donate_argnums=0)
    else:
        step = jax.jit(lambda w, da: lbfgs_lib.run_lbfgs(
            _objective(build(*da)[0]), w, cfg, telemetry_cb=tel_cb),
            donate_argnums=0)

    def _place_w(initial_weights):
        w0 = jax.tree_util.tree_map(_owned_array, initial_weights)
        return w0 if m is None else mesh_lib.replicate(w0, m)

    if telemetry is None:
        def fit(initial_weights):
            return step(_place_w(initial_weights), dargs)
    else:
        fit = _make_instrumented_fit(step, _place_w, dargs, telemetry)

    # which driver the dispatch chose — reporting callers (benchmarks)
    # must label numbers with the REAL dispatch, not re-derive it
    fit.algorithm = algorithm
    # the same AOT introspection surface as make_runner, so
    # obs.introspect.analyze_runner censuses the quasi-Newton member's
    # ONE program too (FLOPs / HBM / collectives of the fused loop)
    fit.lower_step = lambda w0: step.lower(_place_w(w0), dargs)
    fit.jitted_step = step
    fit.data_args = dargs
    return fit


def run_lbfgs(
    data: Data,
    gradient: Gradient,
    updater: Prox,
    num_corrections: int = 10,
    convergence_tol: float = 1e-4,
    num_iterations: int = 100,
    reg_param: float = 0.0,
    initial_weights: Any = None,
    *,
    grad_tol: float = 0.0,
    mesh=None,
    dist_mode: str = "shard_map",
    telemetry=None,
):
    """Functional L-BFGS entry point — MLlib's ``LBFGS.runLBFGS``
    equivalent, returning the full ``LBFGSResult`` (its ``(weights,
    loss_history)`` pair plus the diagnostics MLlib discards).
    ``telemetry``: live streaming + spans, see
    :func:`make_lbfgs_runner`."""
    if initial_weights is None:
        raise ValueError("initial_weights is required")
    fit = make_lbfgs_runner(
        data, gradient, updater, num_corrections=num_corrections,
        convergence_tol=convergence_tol, num_iterations=num_iterations,
        reg_param=reg_param, grad_tol=grad_tol, mesh=mesh,
        dist_mode=dist_mode, telemetry=telemetry)
    result = fit(initial_weights)
    if telemetry is not None:
        k = int(result.num_iters)
        telemetry.run_summary(
            tool="api.run_lbfgs", algorithm=fit.algorithm, iters=k,
            final_loss=float(np.asarray(result.loss_history)[k]),
            converged=bool(result.converged),
            error=("aborted: non-finite objective"
                   if bool(result.aborted_non_finite) else None))
    return result


class LBFGS:
    """Config-holder twin of MLlib 1.3's ``LBFGS(gradient, updater)`` —
    the reference's ``Optimizer`` trait shape (``optimize(data,
    initial_weights) -> weights``), so it swaps with
    :class:`AcceleratedGradientDescent` the way the reference swaps with
    MLlib's optimizers inside ``GeneralizedLinearAlgorithm`` callers."""

    def __init__(self, gradient: Gradient, updater: Prox):
        self._gradient = gradient
        self._updater = updater
        self._num_corrections = 10
        self._convergence_tol = 1e-4
        self._num_iterations = 100
        self._reg_param = 0.0
        self._grad_tol = 0.0
        self._mesh = None
        self._dist_mode = "shard_map"

    def set_num_corrections(self, m: int):
        self._num_corrections = int(m)
        return self

    def set_convergence_tol(self, tol: float):
        self._convergence_tol = float(tol)
        return self

    def set_num_iterations(self, iters: int):
        self._num_iterations = int(iters)
        return self

    def set_reg_param(self, reg_param: float):
        self._reg_param = float(reg_param)
        return self

    def set_gradient(self, gradient: Gradient):
        self._gradient = gradient
        return self

    def set_updater(self, updater: Prox):
        self._updater = updater
        return self

    # TPU-specific knobs (beyond the MLlib surface)
    def set_grad_tol(self, tol: float):
        self._grad_tol = float(tol)
        return self

    def set_mesh(self, mesh):
        self._mesh = mesh
        return self

    def set_dist_mode(self, dist_mode: str):
        self._dist_mode = dist_mode
        return self

    # camelCase aliases for verbatim ports of MLlib call sites
    setNumCorrections = set_num_corrections
    setConvergenceTol = set_convergence_tol
    setNumIterations = set_num_iterations
    setRegParam = set_reg_param
    setGradient = set_gradient
    setUpdater = set_updater

    def optimize(self, data: Data, initial_weights: Any):
        res = run_lbfgs(
            data, self._gradient, self._updater,
            num_corrections=self._num_corrections,
            convergence_tol=self._convergence_tol,
            num_iterations=self._num_iterations,
            reg_param=self._reg_param,
            initial_weights=initial_weights,
            grad_tol=self._grad_tol, mesh=self._mesh,
            dist_mode=self._dist_mode)
        return res.weights

    def sweep(self, data: Data, reg_params, initial_weights: Any):
        """Regularization path with this object's configuration: K
        strengths in one compiled program (module-level
        :func:`make_lbfgs_sweep_runner`; smooth penalties only —
        ``set_reg_param`` is ignored, the grid supplies the strengths).
        Makes the LBFGS-seated trainers' ``train_path`` work like the
        AGD-seated ones'."""
        reg_params = _check_grid_fit(self._updater, reg_params, "sweep")
        fit = make_lbfgs_sweep_runner(
            data, self._gradient, self._updater,
            num_corrections=self._num_corrections,
            convergence_tol=self._convergence_tol,
            num_iterations=self._num_iterations,
            grad_tol=self._grad_tol, mesh=self._mesh)
        return fit(initial_weights, reg_params)


def make_lbfgs_sweep_runner(
    data: Data,
    gradient: Gradient,
    updater: Prox,
    num_corrections: int = 10,
    convergence_tol: float = 1e-4,
    num_iterations: int = 100,
    *,
    grad_tol: float = 0.0,
    mesh=False,
):
    """Build ``fit(initial_weights, reg_params) -> batched LBFGSResult``
    — the regularization path for the quasi-Newton member, K lanes in
    ONE compiled program (the :func:`make_sweep_runner` twin).  Each
    lane runs the full fused L-BFGS; under ``vmap`` the ``while_loop``
    freezes finished lanes, so early-converging strengths cost nothing
    extra.

    SMOOTH penalties only: the lanes trace one objective with a traced
    ``reg``, which the OWL-QN dispatch (a static decision) cannot join;
    for an L1 grid run per-strength :func:`run_lbfgs` fits (each one
    compiled once) or an AGD :func:`sweep`.

    ``mesh``: as in :func:`make_sweep_runner` — ``False`` single-device,
    a ``Mesh``/``None``/``ShardedBatch`` shards rows with lanes vmapped
    inside the shard_map (``parallel.grid.make_mesh_lbfgs_sweep_fit``).
    """
    from .core import lbfgs as lbfgs_lib, tvec

    lbfgs_lib.check_smooth_penalty(updater, 1.0)
    cfg = lbfgs_lib.LBFGSConfig(
        num_corrections=num_corrections,
        convergence_tol=convergence_tol,
        num_iterations=num_iterations, grad_tol=grad_tol)

    m, batch, _ = _resolve_fit_mesh(data, mesh)
    if m is not None:
        from .parallel import grid

        if batch is None:
            batch = mesh_lib.shard_batch(m, *_normalize_data(data))
        mesh_fit = grid.make_mesh_lbfgs_sweep_fit(gradient, updater,
                                                  batch, m, cfg)

        def fit(initial_weights, reg_params):
            # same IdentityProx-vs-nonzero-grid guard LBFGS.sweep
            # applies: a no-penalty updater ignores reg, so K lanes
            # would silently be identical (r3 advisor)
            reg_params = _check_grid_fit(updater, reg_params,
                                         "make_lbfgs_sweep_runner")
            return mesh_fit(reg_params, initial_weights)

        return fit

    X, y, mask = _normalize_data(data)
    build, dargs = _build_smooth(gradient, (X, y, mask), None, "shard_map")

    def fit_one(reg, w0, da):
        sm, _ = build(*da)

        def objective(w):
            f, g = sm(w)
            pv, pg = updater.smooth_penalty(w, reg)
            return f + pv, tvec.add(g, pg)

        return lbfgs_lib.run_lbfgs(objective, w0, cfg)

    step = jax.jit(jax.vmap(fit_one, in_axes=(0, None, None)))

    def fit(initial_weights, reg_params):
        reg_params = _check_grid_fit(updater, reg_params,
                                     "make_lbfgs_sweep_runner")
        # default float dtype (f64 under x64): lane regs must match the
        # precision a solo fit's python-float reg_param would carry
        regs = jnp.asarray(reg_params, jnp.result_type(float))
        if regs.ndim != 1:
            raise ValueError("reg_params must be 1-D")
        w0 = jax.tree_util.tree_map(jnp.asarray, initial_weights)
        return step(regs, w0, dargs)

    return fit


def streaming_lbfgs_sweep(
    dataset,
    gradient: Gradient,
    updater: Prox,
    reg_params,
    num_corrections: int = 10,
    convergence_tol: float = 1e-4,
    num_iterations: int = 100,
    initial_weights: Any = None,
    *,
    grad_tol: float = 0.0,
    mesh=None,
    pad_to=None,
    csr_nnz_per_shard=None,
):
    """A K-strength L-BFGS regularization path over a STREAMED dataset
    — one stream read per evaluation round for ALL lanes (the
    :func:`streaming_sweep` twin for the quasi-Newton member).

    Each lane executes the EXACT solo host algorithm
    (``core.host_lbfgs._lbfgs_gen`` — the same generator
    ``run_lbfgs_host`` drives), with the lanes' pending objective
    evaluations batched into one
    ``data.streaming.make_streaming_eval_multi`` pass (the K margin
    products fuse into one ``(rows, D) @ (D, K)`` contraction per
    macro-batch).  Smooth penalties only, like
    :func:`make_lbfgs_sweep_runner`.

    Returns a ``core.host_lbfgs.HostLBFGSMultiResult`` (leading K axis;
    ``eval_rounds`` counts the stream passes consumed — sequential solo
    fits would pay ``sum(num_fn_evals)`` passes).
    """
    if initial_weights is None:
        raise ValueError("initial_weights is required")
    from .core import host_lbfgs, lbfgs as lbfgs_lib, tvec
    from .data import streaming as streaming_lib

    # same IdentityProx-vs-nonzero-grid guard LBFGS.sweep applies
    # (r3 advisor: a no-penalty updater would silently return K
    # identical lanes)
    reg_params = _check_grid_fit(updater, reg_params,
                                 "streaming_lbfgs_sweep")
    lbfgs_lib.check_smooth_penalty(updater, 1.0)
    regs = jnp.asarray(list(reg_params), jnp.result_type(float))
    if regs.ndim != 1:
        raise ValueError("reg_params must be 1-D")
    sm_multi = streaming_lib.make_streaming_eval_multi(
        gradient, dataset, mesh=mesh, pad_to=pad_to,
        csr_nnz_per_shard=csr_nnz_per_shard)

    pen_multi = jax.jit(jax.vmap(
        lambda wk, rk: updater.smooth_penalty(wk, rk)))

    def objective_multi(W):
        fs, Gs = sm_multi(W)
        pv, pg = pen_multi(W, regs)
        return fs + pv, tvec.add(Gs, pg)

    W0 = _stack_lanes(initial_weights, int(regs.shape[0]))
    cfg = lbfgs_lib.LBFGSConfig(
        num_corrections=num_corrections,
        convergence_tol=convergence_tol,
        num_iterations=num_iterations, grad_tol=grad_tol)
    return host_lbfgs.run_lbfgs_host_multi(objective_multi, W0, cfg)
