"""Native (C++) host-runtime components, loaded over ctypes.

The reference's native layer is third-party (JNI BLAS under Breeze, Netty
transport — SURVEY §2.4); its compute equivalent here is XLA-generated TPU
code.  What remains genuinely host-side in the TPU runtime — bulk text
ingest — is implemented in C++ (``libsvm_parser.cpp``) and loaded lazily
here, compiled on first use with the in-tree Makefile.  Everything degrades
gracefully: if no toolchain is available the callers fall back to the pure-
Python paths.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_LIB: Optional[ctypes.CDLL] = None
_LOAD_FAILED = False


class _ParseResult(ctypes.Structure):
    _fields_ = [
        ("n_rows", ctypes.c_int64),
        ("nnz", ctypes.c_int64),
        ("max_index", ctypes.c_int32),
        ("labels", ctypes.POINTER(ctypes.c_double)),
        ("indptr", ctypes.POINTER(ctypes.c_int64)),
        ("indices", ctypes.POINTER(ctypes.c_int32)),
        ("values", ctypes.POINTER(ctypes.c_float)),
    ]


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-s", "libsvm_parser.so"], cwd=_DIR, check=True,
            capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def load_parser() -> Optional[ctypes.CDLL]:
    """Return the native parser library, building it if needed; None if the
    native path is unavailable (callers must fall back)."""
    global _LIB, _LOAD_FAILED
    with _LOCK:
        if _LIB is not None:
            return _LIB
        if _LOAD_FAILED:
            return None
        so = os.path.join(_DIR, "libsvm_parser.so")
        # Always invoke make: its .cpp dependency makes this a no-op when
        # the binary is fresh, and it rebuilds stale binaries after source
        # edits.  A pre-existing .so still serves if the toolchain is gone.
        if not _build() and not os.path.exists(so):
            _LOAD_FAILED = True
            return None
        try:
            lib = ctypes.CDLL(so)
            lib.parse_libsvm.argtypes = [ctypes.c_char_p,
                                         ctypes.POINTER(_ParseResult)]
            lib.parse_libsvm.restype = ctypes.c_int
            lib.free_parse_result.argtypes = [ctypes.POINTER(_ParseResult)]
            lib.free_parse_result.restype = None
            _LIB = lib
            return lib
        except OSError:
            _LOAD_FAILED = True
            return None


def parse_libsvm_native(path: str):
    """Parse with the C++ core.  Returns ``(labels, indptr, indices,
    values, n_features)`` as NumPy arrays (copies — the C buffers are freed
    before returning), or None when the native library is unavailable.
    Raises ValueError on malformed input."""
    import numpy as np

    lib = load_parser()
    if lib is None:
        return None
    res = _ParseResult()
    rc = lib.parse_libsvm(os.fsencode(path), ctypes.byref(res))
    if rc == -1:  # fopen failed
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        raise OSError(f"cannot open {path!r}")
    if rc == -5:
        raise MemoryError(f"native LIBSVM parser out of memory on {path!r}")
    if rc == -6:
        raise OSError(f"I/O error reading {path!r}")
    if rc < 0:
        raise ValueError(
            f"malformed LIBSVM file {path!r} (native parser code {rc})")
    try:
        n, nnz = res.n_rows, res.nnz
        n_features = int(res.max_index) + 1  # read before the free clears it
        labels = np.ctypeslib.as_array(res.labels, (n,)).copy() if n else \
            np.zeros(0)
        indptr = np.ctypeslib.as_array(res.indptr, (n + 1,)).copy()
        indices = (np.ctypeslib.as_array(res.indices, (nnz,)).copy()
                   if nnz else np.zeros(0, np.int32))
        values = (np.ctypeslib.as_array(res.values, (nnz,)).copy()
                  if nnz else np.zeros(0, np.float32))
    finally:
        lib.free_parse_result(ctypes.byref(res))
    return labels, indptr, indices, values, n_features
