"""Native (C++) host-runtime components, loaded over ctypes.

The reference's native layer is third-party (JNI BLAS under Breeze, Netty
transport — SURVEY §2.4); its compute equivalent here is XLA-generated TPU
code.  What remains genuinely host-side in the TPU runtime — bulk text
ingest (``libsvm_parser.cpp``) and the sharding layout solver
(``shard_balance.cpp``, the greedy nnz balancer behind the row- and
column-sharded CSR layouts) — is implemented in C++ and loaded lazily
here, compiled on first use with the in-tree Makefile.  Everything
degrades gracefully: if no toolchain is available the callers fall back
to the pure-Python paths (same algorithm, bit-identical output).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
# shared .so load protocol state: so_name -> CDLL | None (None = failed,
# latched so a missing toolchain is probed once per process)
_LIBS: dict = {}
# so_name -> typed reason string, recorded the ONE time a library load
# fell back to the Python path; consumed (once) by pop_fallback_event so
# the data layer can emit a single telemetry record instead of spamming
# one per shard read
_FALLBACK: dict = {}


def pop_fallback_event(so_name: str) -> Optional[str]:
    """One-shot fallback report: the typed reason the named library is
    unavailable, returned exactly once per process (None afterwards, and
    None when the library loaded fine)."""
    with _LOCK:
        return _FALLBACK.pop(so_name, None)


class _ParseResult(ctypes.Structure):
    _fields_ = [
        ("n_rows", ctypes.c_int64),
        ("nnz", ctypes.c_int64),
        ("max_index", ctypes.c_int32),
        ("labels", ctypes.POINTER(ctypes.c_double)),
        ("indptr", ctypes.POINTER(ctypes.c_int64)),
        ("indices", ctypes.POINTER(ctypes.c_int32)),
        ("values", ctypes.POINTER(ctypes.c_float)),
    ]


def _build(target: str) -> bool:
    try:
        subprocess.run(
            ["make", "-s", target], cwd=_DIR, check=True,
            capture_output=True, timeout=120)
        return True
    except Exception:
        return False


def _load_lib(so_name: str, configure) -> Optional[ctypes.CDLL]:
    """Shared .so load protocol: build (or accept a pre-built binary when
    the toolchain is gone), dlopen, run ``configure(lib)`` to set the
    prototypes.  Failure — including a stale binary missing a symbol
    (AttributeError from configure) — is latched and returns None so
    callers fall back to their Python paths."""
    with _LOCK:
        if so_name in _LIBS:
            return _LIBS[so_name]
        so = os.path.join(_DIR, so_name)
        # Always invoke make: its .cpp dependency makes this a no-op when
        # the binary is fresh, and it rebuilds stale binaries after source
        # edits.  A pre-existing .so still serves if the toolchain is gone.
        if not _build(so_name) and not os.path.exists(so):
            _LIBS[so_name] = None
            _FALLBACK[so_name] = (
                f"{so_name}: build failed and no pre-built binary; "
                f"using the Python fallback")
            return None
        try:
            lib = ctypes.CDLL(so)
            configure(lib)
        except OSError as e:
            lib = None
            _FALLBACK[so_name] = (
                f"{so_name}: dlopen failed ({e}); using the Python "
                f"fallback")
        except AttributeError as e:
            lib = None
            _FALLBACK[so_name] = (
                f"{so_name}: ABI mismatch — stale binary missing a "
                f"symbol ({e}); rebuild with `make -C "
                f"spark_agd_tpu/native`; using the Python fallback")
        _LIBS[so_name] = lib
        return lib


def _configure_parser(lib):
    lib.parse_libsvm.argtypes = [ctypes.c_char_p,
                                 ctypes.POINTER(_ParseResult)]
    lib.parse_libsvm.restype = ctypes.c_int
    lib.free_parse_result.argtypes = [ctypes.POINTER(_ParseResult)]
    lib.free_parse_result.restype = None


def load_parser() -> Optional[ctypes.CDLL]:
    """Return the native parser library, building it if needed; None if the
    native path is unavailable (callers must fall back)."""
    return _load_lib("libsvm_parser.so", _configure_parser)


def _configure_balancer(lib):
    lib.greedy_balance.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
        ctypes.c_int32, ctypes.c_int64,
        ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32)]
    lib.greedy_balance.restype = ctypes.c_int


def load_balancer() -> Optional[ctypes.CDLL]:
    """Return the native shard balancer, building it if needed; None if
    unavailable (``greedy_balance`` then runs its Python fallback)."""
    return _load_lib("shard_balance.so", _configure_balancer)


def greedy_balance(counts, n_shards: int, capacity: int):
    """Greedy heaviest-first balanced shard assignment.

    Each item goes onto the currently lightest shard with remaining
    capacity (load ties -> lowest shard id), local slots in placement
    order.  Returns ``(shard_of, local_of)`` int64 arrays.  Raises
    ValueError when ``n_shards * capacity`` cannot hold the items —
    before dispatch, so the error is identical with or without the
    toolchain.  C++ core (``shard_balance.cpp``); the Python loop below
    is the bit-identical executable spec it is tested against
    (``tests/test_native_balance.py``).
    """
    import numpy as np

    counts = np.ascontiguousarray(counts, np.int64)
    n = len(counts)
    if n_shards * capacity < n:
        raise ValueError(
            f"{n} items exceed {n_shards} shards x capacity {capacity}")
    lib = load_balancer()
    if lib is not None:
        shard_of = np.empty(n, np.int32)
        local_of = np.empty(n, np.int32)
        rc = lib.greedy_balance(
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ctypes.c_int64(n), ctypes.c_int32(int(n_shards)),
            ctypes.c_int64(int(capacity)),
            shard_of.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            local_of.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc != 0:
            raise ValueError(f"greedy_balance failed (code {rc})")
        return shard_of.astype(np.int64), local_of.astype(np.int64)

    import heapq

    order = np.argsort(-counts, kind="stable")
    shard_of = np.empty(n, np.int64)
    local_of = np.empty(n, np.int64)
    heap = [(0, s) for s in range(n_shards)]
    cap = [capacity] * n_shards
    next_local = [0] * n_shards
    nnz_list = counts[order].tolist()
    for rank, r in enumerate(order.tolist()):
        while True:
            load, s = heapq.heappop(heap)
            if cap[s]:
                break
        shard_of[r] = s
        local_of[r] = next_local[s]
        next_local[s] += 1
        cap[s] -= 1
        heapq.heappush(heap, (load + nnz_list[rank], s))
    return shard_of, local_of


def parse_libsvm_native(path: str):
    """Parse with the C++ core.  Returns ``(labels, indptr, indices,
    values, n_features)`` as NumPy arrays (copies — the C buffers are freed
    before returning), or None when the native library is unavailable.
    Raises ValueError on malformed input."""
    import numpy as np

    lib = load_parser()
    if lib is None:
        return None
    res = _ParseResult()
    rc = lib.parse_libsvm(os.fsencode(path), ctypes.byref(res))
    if rc == -1:  # fopen failed
        if not os.path.exists(path):
            raise FileNotFoundError(path)
        raise OSError(f"cannot open {path!r}")
    if rc == -5:
        raise MemoryError(f"native LIBSVM parser out of memory on {path!r}")
    if rc == -6:
        raise OSError(f"I/O error reading {path!r}")
    if rc < 0:
        raise ValueError(
            f"malformed LIBSVM file {path!r} (native parser code {rc})")
    try:
        n, nnz = res.n_rows, res.nnz
        n_features = int(res.max_index) + 1  # read before the free clears it
        labels = np.ctypeslib.as_array(res.labels, (n,)).copy() if n else \
            np.zeros(0)
        indptr = np.ctypeslib.as_array(res.indptr, (n + 1,)).copy()
        indices = (np.ctypeslib.as_array(res.indices, (nnz,)).copy()
                   if nnz else np.zeros(0, np.int32))
        values = (np.ctypeslib.as_array(res.values, (nnz,)).copy()
                  if nnz else np.zeros(0, np.float32))
    finally:
        lib.free_parse_result(ctypes.byref(res))
    return labels, indptr, indices, values, n_features
