"""AOT-compiled batched inference engine over a bucketed shape ladder.

The training side learned twice (PR 5's constant-capture bug, the
staged-compile wedge) that the program and the data must be split: pay
tracing/compilation once, thread everything that changes per call as an
argument.  Serving doubles down on both points:

- **weights are arguments**, so a registry hot-swap binds a new
  generation into the *same* compiled programs — no recompile, no
  dropped requests (the swap is an atomic reference flip under the call
  lock);
- **batch shapes come from a fixed ladder** (powers of two up to
  ``max_batch``), all compiled at construction — a request of any
  admissible size pads to the nearest bucket and runs an existing
  executable.  The request path NEVER compiles; an unknown shape is a
  typed error, not a 20-second XLA stall;
- **the output buffer is donated**: each program takes a same-shaped
  scratch array, overwrites it in place (``dynamic_update_slice`` over
  the full extent, value-identical to returning the result), and the
  engine rebinds the aliased output as the next call's scratch — steady
  state allocates nothing per batch.  The aliasing is pinned by the
  ``serve_*`` entries in ``analysis/pins.json`` (donation honored, zero
  collectives, constant-byte budget) against the real compiled HLO.

The forward math reuses the model classes' own kernels (``ops.sparse.
matvec``, ``models.mlp.mlp_forward``) so a served prediction is the same
computation the in-memory model runs, just batched and padded — padding
rows are sliced off host-side before the caller sees them.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.sparse import matvec

# model class name -> the short kind the program labels use
_KIND_SHORT = {
    "LogisticRegressionModel": "logistic",
    "SVMModel": "svm",
    "LinearRegressionModel": "linear",
    "SoftmaxRegressionModel": "softmax",
    "MLPModel": "mlp",
}

# ops each kind serves; SVM/linear have no probability (mirrors the
# model classes' own method surface)
_KIND_OPS = {
    "logistic": ("predict", "predict_proba"),
    "svm": ("predict",),
    "linear": ("predict",),
    "softmax": ("predict", "predict_proba"),
    "mlp": ("predict", "predict_proba"),
}

DEFAULT_MAX_BATCH = 64
DEFAULT_MIN_BUCKET = 8


class ServeSpecMismatch(ValueError):
    """A hot-swap candidate's shape signature differs from the programs
    the engine compiled (different feature count, class count, threshold
    mode, activation, or dtype) — binding it would need a recompile on
    the request path, which the engine refuses by design.  Classified
    FATAL by the resilience taxonomy (``ValueError``): the fix is a new
    engine, not a retry."""


@dataclasses.dataclass(frozen=True)
class ModelSpec:
    """The shape signature of a servable model — everything that is
    baked into the compiled programs (weights are NOT part of it; they
    stay arguments so generations sharing a spec share programs)."""

    kind: str                 # _KIND_SHORT value
    n_features: int
    num_classes: int          # 1 for the binary/regression family
    dtype: str                # weights dtype string, e.g. "float32"
    has_threshold: bool = False
    activation: Optional[str] = None  # MLP only
    hidden_units: int = 0             # MLP only

    @property
    def ops(self) -> Tuple[str, ...]:
        return _KIND_OPS[self.kind]


def spec_of(model) -> ModelSpec:
    """Derive the shape signature of any registered model class."""
    name = type(model).__name__
    kind = _KIND_SHORT.get(name)
    if kind is None:
        raise TypeError(
            f"{name} is not a servable model class; known: "
            f"{sorted(_KIND_SHORT)}")
    if kind == "mlp":
        from ..models.mlp import _ACTIVATIONS

        act = next((n for n, f in _ACTIVATIONS.items()
                    if f is model.activation), None)
        if act is None:
            raise ValueError(
                "cannot serve an MLP with an unregistered activation "
                f"callable; known: {sorted(_ACTIVATIONS)}")
        d, h = model.params["W1"].shape
        k = model.params["W2"].shape[1]
        return ModelSpec(kind, int(d), int(k),
                         str(model.params["W1"].dtype),
                         activation=act, hidden_units=int(h))
    w = model.weights
    if kind == "softmax":
        return ModelSpec(kind, int(w.shape[0]), int(w.shape[1]),
                         str(w.dtype))
    return ModelSpec(kind, int(w.shape[0]), 1, str(w.dtype),
                     has_threshold=getattr(model, "threshold",
                                           None) is not None)


def params_of(model, spec: Optional[ModelSpec] = None) -> Dict[str, Any]:
    """The model's weights as the argument pytree the compiled programs
    take.  Scalars (intercept, threshold) are cast to the weights dtype
    so the served math promotes exactly like the in-memory model's."""
    spec = spec or spec_of(model)
    if spec.kind == "mlp":
        return {k: jnp.asarray(v) for k, v in model.params.items()}
    w = jnp.asarray(model.weights)
    params: Dict[str, Any] = {
        "w": w, "b": jnp.asarray(model.intercept, dtype=w.dtype)}
    if spec.has_threshold:
        params["thr"] = jnp.asarray(model.threshold, dtype=w.dtype)
    return params


def _make_forward(spec: ModelSpec, op: str):
    """The pure ``(params, X) -> values`` function for one (kind, op) —
    the model classes' own math, verbatim."""
    if op not in spec.ops:
        raise ValueError(
            f"op {op!r} is not served for kind {spec.kind!r} "
            f"(supported: {spec.ops})")
    kind = spec.kind

    def forward(params, X):
        if kind == "mlp":
            from ..models.mlp import _ACTIVATIONS, mlp_forward

            logits = mlp_forward(params, X, _ACTIVATIONS[spec.activation])
            if op == "predict_proba":
                return jax.nn.softmax(logits, axis=-1)
            return jnp.argmax(logits, axis=-1)
        if kind == "softmax":
            logits = matvec(X, params["w"]) + params["b"]
            if op == "predict_proba":
                return jax.nn.softmax(logits, axis=-1)
            return jnp.argmax(logits, axis=-1)
        margin = matvec(X, params["w"]) + params["b"]
        if kind == "logistic":
            p = jax.nn.sigmoid(margin)
            if op == "predict_proba":
                return p
            if spec.has_threshold:
                return (p > params["thr"]).astype(jnp.float32)
            return p
        # svm / linear predict
        if kind == "svm" and spec.has_threshold:
            return (margin > params["thr"]).astype(jnp.float32)
        return margin

    return forward


def _make_program(forward):
    """Wrap a forward into the donated-scratch program shape: ``out`` is
    a same-shaped buffer overwritten in place (full-extent
    ``dynamic_update_slice`` — value-identical to ``forward``'s result,
    but keeps the donated input live so XLA honors the aliasing)."""

    def program(params, X, out):
        vals = forward(params, X)
        return jax.lax.dynamic_update_slice(out, vals,
                                            (0,) * vals.ndim)

    return jax.jit(program, donate_argnums=2)


class BucketLadder:
    """The fixed padding-shape ladder: powers of two from ``min_bucket``
    up to ``max_batch`` (``max_batch`` itself is always a rung, even
    when it is not a power of two).  ``bucket_for(n)`` maps any
    admissible request size to the smallest rung that holds it."""

    def __init__(self, max_batch: int = DEFAULT_MAX_BATCH,
                 min_bucket: int = DEFAULT_MIN_BUCKET,
                 buckets: Optional[Sequence[int]] = None):
        max_batch = int(max_batch)
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if buckets is None:
            b = min(int(min_bucket), max_batch)
            ladder = []
            while b < max_batch:
                ladder.append(b)
                b *= 2
            ladder.append(max_batch)
        else:
            ladder = sorted({int(b) for b in buckets})
            if not ladder or ladder[0] < 1:
                raise ValueError(f"invalid bucket ladder {buckets!r}")
            if ladder[-1] != max_batch:
                raise ValueError(
                    f"the top bucket must equal max_batch={max_batch}, "
                    f"got {ladder!r}")
        self.buckets: Tuple[int, ...] = tuple(ladder)
        self.max_batch = max_batch

    def bucket_for(self, n: int) -> int:
        if n < 1 or n > self.max_batch:
            raise ValueError(
                f"batch of {n} rows is not admissible (1 <= n <= "
                f"max_batch={self.max_batch})")
        for b in self.buckets:
            if n <= b:
                return b
        raise AssertionError("unreachable: ladder tops at max_batch")

    def __repr__(self):
        return f"BucketLadder{self.buckets}"


@dataclasses.dataclass
class _Program:
    """One compiled (op, bucket) executable plus its donated scratch."""

    compiled: Any
    scratch: Any          # device array; rebound to the output per call
    out_shape: Tuple[int, ...]
    out_dtype: Any
    compiles: int = 1


class ServeEngine:
    """See module docstring.  Construction compiles every (op, bucket)
    program up front (the warmup IS ``__init__`` — an engine that
    exists can serve); ``bind`` hot-swaps a new same-spec generation's
    weights into the running programs.

    Thread-safety: ``serve_batch``/``predict``/``bind`` serialize on one
    internal lock (the donated scratch makes concurrent calls into the
    same program unsound by construction); the micro-batching queue is
    the intended concurrency layer above.
    """

    def __init__(self, model, *, generation: int = 0,
                 max_batch: int = DEFAULT_MAX_BATCH,
                 min_bucket: int = DEFAULT_MIN_BUCKET,
                 buckets: Optional[Sequence[int]] = None,
                 ops: Optional[Sequence[str]] = None,
                 telemetry=None):
        self.spec = spec_of(model)
        self.ladder = BucketLadder(max_batch, min_bucket, buckets)
        self.ops: Tuple[str, ...] = tuple(ops or self.spec.ops)
        for op in self.ops:
            if op not in self.spec.ops:
                raise ValueError(
                    f"op {op!r} not served for kind {self.spec.kind!r} "
                    f"(supported: {self.spec.ops})")
        self.telemetry = telemetry
        self._lock = threading.Lock()
        self._params = params_of(model, self.spec)
        self._generation = int(generation)
        self._np_dtype = np.dtype(self.spec.dtype)
        self.hot_swaps = 0
        self._programs: Dict[Tuple[str, int], _Program] = {}
        self._compile_programs()

    # -- warmup (compile the whole ladder, count every compile) -----------
    def _compile_programs(self) -> None:
        p_struct = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
            self._params)
        span = (self.telemetry.span("serve_warmup")
                if self.telemetry is not None else None)
        if span is not None:
            span.__enter__()
        try:
            for op in self.ops:
                forward = _make_forward(self.spec, op)
                jfn = _make_program(forward)
                for bucket in self.ladder.buckets:
                    x_struct = jax.ShapeDtypeStruct(
                        (bucket, self.spec.n_features), self._np_dtype)
                    out_struct = jax.eval_shape(forward, p_struct,
                                                x_struct)
                    compiled = jfn.lower(
                        p_struct, x_struct,
                        jax.ShapeDtypeStruct(out_struct.shape,
                                             out_struct.dtype)).compile()
                    scratch = jnp.zeros(out_struct.shape,
                                        out_struct.dtype)
                    self._programs[(op, bucket)] = _Program(
                        compiled, scratch, tuple(out_struct.shape),
                        out_struct.dtype)
        finally:
            if span is not None:
                span.__exit__(None, None, None)
        if self.telemetry is not None:
            self._emit_program_costs()

    def _emit_program_costs(self) -> None:
        from ..obs import introspect

        for (op, bucket), prog in self._programs.items():
            cost = introspect.analyze_compiled(
                prog.compiled, label=self.program_label(op))
            self.telemetry.program_cost(cost, algorithm="serve",
                                        bucket=bucket)

    def program_label(self, op: str) -> str:
        """The pin/telemetry label of one op's programs (shared across
        buckets — the pin is about program *structure*, which the
        bucket does not change)."""
        return f"serve_{self.spec.kind}_{op}"

    # -- introspection -----------------------------------------------------
    @property
    def generation(self) -> int:
        return self._generation

    @property
    def max_batch(self) -> int:
        return self.ladder.max_batch

    def compiled_programs(self) -> Dict[Tuple[str, int], Any]:
        """(op, bucket) -> the real ``jax.stages.Compiled`` — what the
        contract pins (``analysis.contracts.check_serve_engine``) and
        tests introspect."""
        return {k: p.compiled for k, p in self._programs.items()}

    def compile_census(self) -> Dict[str, int]:
        """Per-(op, bucket) compile counts — the drill pins this frozen
        after warmup: serving must never add an entry or a count."""
        return {f"{op}/b{bucket}": p.compiles
                for (op, bucket), p in self._programs.items()}

    # -- hot swap ----------------------------------------------------------
    def bind(self, model, generation: int) -> None:
        """Atomically swap in a new generation's weights.  The spec must
        match the compiled programs (else :class:`ServeSpecMismatch`);
        an in-flight batch finishes on the old weights — the swap waits
        for the call lock, never interrupts."""
        new_spec = spec_of(model)
        if new_spec != self.spec:
            raise ServeSpecMismatch(
                f"generation {generation} has spec {new_spec}, engine "
                f"compiled for {self.spec}; refusing a hot swap that "
                "would recompile on the request path")
        new_params = params_of(model, new_spec)
        with self._lock:
            self._params = new_params
            self._generation = int(generation)
            self.hot_swaps += 1

    # -- the serving path --------------------------------------------------
    def serve_batch(self, X: np.ndarray,
                    op: str = "predict") -> Tuple[np.ndarray, int, int]:
        """Serve one coalesced batch: pad to the nearest bucket, run the
        pre-compiled program, slice the padding back off.  Returns
        ``(values, generation, bucket)`` with ``values`` already on
        host.  Raises ``ValueError`` for inadmissible sizes/ops — the
        request path never compiles."""
        if op not in self.ops:
            raise ValueError(
                f"op {op!r} not served for kind {self.spec.kind!r} "
                f"(supported: {self.ops})")
        X = np.ascontiguousarray(X, dtype=self._np_dtype)
        if X.ndim != 2 or X.shape[1] != self.spec.n_features:
            raise ValueError(
                f"expected a (n, {self.spec.n_features}) batch, got "
                f"shape {X.shape}")
        n = X.shape[0]
        bucket = self.ladder.bucket_for(n)
        prog = self._programs.get((op, bucket))
        if prog is None:
            raise ValueError(
                f"no compiled program for op={op!r} bucket={bucket} "
                f"(ops: {self.ops}, ladder: {self.ladder.buckets}) — "
                "the request path never compiles")
        if n == bucket:
            padded = X
        else:
            padded = np.zeros((bucket, X.shape[1]), self._np_dtype)
            padded[:n] = X
        with self._lock:
            generation = self._generation
            # one causal ``engine_call`` span per batch (obs.trace):
            # inherits the queue's serve_batch context through the
            # context variable (same worker thread), so request →
            # batch → engine reads as one chain in the timeline.
            # Host-side only — the compiled program is untouched.
            span = (self.telemetry.trace_span(
                "engine_call", op=op, bucket=bucket,
                generation=generation, tool="serve.engine")
                if self.telemetry is not None else None)
            with span if span is not None \
                    else contextlib.nullcontext():
                out = prog.compiled(self._params, padded, prog.scratch)
                # the donated scratch's buffer now IS the output; copy
                # the result to host, then recycle the device buffer
                # as the next call's scratch.  The host copy must OWN
                # its memory: on CPU ``device_get`` may return a
                # zero-copy VIEW of the device buffer (persistent-
                # cache-deserialized executables do), and the next
                # call's donation would mutate results already handed
                # to callers.
                host = np.asarray(jax.device_get(out))
                if host.base is not None or not host.flags.owndata:
                    host = host.copy()
            prog.scratch = out
        return host[:n], generation, bucket

    def predict(self, X, op: str = "predict") -> np.ndarray:
        """Direct (queue-less) convenience: serve ``X`` of any size,
        chunking batches larger than ``max_batch`` through the top
        bucket.  One device sync per chunk, results concatenated."""
        X = np.asarray(X, dtype=self._np_dtype)
        squeeze = X.ndim == 1
        if squeeze:
            X = X[None, :]
        chunks: List[np.ndarray] = []
        top = self.ladder.max_batch
        for start in range(0, X.shape[0], top):
            chunks.append(self.serve_batch(X[start:start + top], op)[0])
        vals = chunks[0] if len(chunks) == 1 else np.concatenate(chunks)
        return vals[0] if squeeze else vals

    def __repr__(self):
        return (f"ServeEngine(kind={self.spec.kind}, "
                f"d={self.spec.n_features}, ops={self.ops}, "
                f"ladder={self.ladder.buckets}, "
                f"generation={self._generation})")
