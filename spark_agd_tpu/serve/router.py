"""Fleet router: latency-aware spreading, verdict-driven failover,
tail hedging, and per-tenant admission over a replica fleet.

One request in, one answer out — and every robustness decision the
router takes on the way is a typed schema record:

- **spread** — each replica carries an EWMA of its observed request
  latency (:class:`ReplicaLatencyTracker`, the request-routing
  generalization of ``resilience.scheduler.SkewTracker``'s per-host
  segment EWMA); a request goes to the candidate minimizing
  ``ewma_ms * (1 + outstanding)`` — cheapest queue-adjusted cost, the
  same "move work toward fast hosts" math as partition rebalancing
  (arXiv 1612.01437 §straggler), applied per request instead of per
  generation.
- **verdicts** — ``HostMonitor.verdicts()`` (the PR-10 heartbeat
  machinery; replicas beat with a serve phase) classifies replicas
  ok/slow/lost.  SLOW is deprioritized but kept *warm*: every
  ``warm_every``-th request trickles to a slow replica so its EWMA
  stays current and recovery is observed, but the bulk of traffic
  shifts away.  Only LOST is evicted (``replica_evict`` recovery,
  once per replica); its in-flight requests are transparently
  retried on a survivor (``request_retry``) — safe by construction,
  predict is pure.
- **hedge** — a request stuck past ``hedge_multiple ×`` the fleet
  median is re-issued to the next-best replica; first answer wins,
  the loser is ignored (``request_hedge`` recovery; the
  ``fleet_route`` record's ``winner`` says who won the race).
- **shed** — per-tenant outstanding caps on top of the queue's typed
  ``ServeOverloaded``: one flooding tenant degrades to *typed
  shedding* (``fleet_route`` decision ``shed_tenant``) while other
  tenants keep their latency budget.  Degrade by shedding — never by
  dropping: an admitted request either returns a value or raises a
  typed error; it is never silently lost.

The router is transport-agnostic: a replica backend is anything with
``predict(rows, op=..., tenant=..., timeout=...) -> dict`` that raises
``ConnectionError`` when the replica is gone and ``ServeOverloaded``
when it sheds (``serve.fleet.ReplicaHandle`` is the TCP one; tests use
in-process fakes).  Elastic membership: :meth:`FleetRouter.refresh_membership`
adopts joins/leaves discovered from the fleet directory.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..obs import trace as trace_lib
from ..resilience.errors import ServeOverloaded

DEFAULT_ALPHA = 0.3
DEFAULT_FLOOR_MS = 0.05
DEFAULT_HEDGE_MULTIPLE = 4.0
DEFAULT_HEDGE_FLOOR_MS = 5.0
DEFAULT_MIN_HEDGE_SAMPLES = 8
DEFAULT_WARM_EVERY = 16
DEFAULT_TENANT_OUTSTANDING = 8
DEFAULT_SPREAD_TOLERANCE = 2.0


class NoReplicasLeft(ConnectionError):
    """Every replica is lost or evicted.  A ``ConnectionError`` so the
    resilience taxonomy classifies it TRANSIENT — the caller backs off
    and retries once membership recovers; nothing is silently dropped.
    """

    def __init__(self, detail: str = ""):
        super().__init__(
            "no live replicas" + (f" ({detail})" if detail else ""))


def _median(sorted_vals: List[float]) -> float:
    """Interpolating median of an already-sorted non-empty list (the
    same convention as ``resilience.scheduler``'s)."""
    n = len(sorted_vals)
    mid = n // 2
    if n % 2:
        return sorted_vals[mid]
    return 0.5 * (sorted_vals[mid - 1] + sorted_vals[mid])


class ReplicaLatencyTracker:
    """Per-replica EWMA of observed request latency (ms) — the
    request-scale twin of ``SkewTracker``'s per-host segment EWMA.
    ``alpha`` weighs the newest sample; ``floor_ms`` keeps costs
    positive so ratios stay meaningful; an unobserved replica costs
    the floor (optimistic: new joiners get traffic until measured)."""

    def __init__(self, *, alpha: float = DEFAULT_ALPHA,
                 floor_ms: float = DEFAULT_FLOOR_MS):
        if not 0 < alpha <= 1:
            raise ValueError("alpha must sit in (0, 1]")
        self.alpha = float(alpha)
        self.floor_ms = float(floor_ms)
        self._ewma: Dict[int, float] = {}
        self._samples: Dict[int, int] = {}

    def observe(self, replica: int, latency_ms: float) -> None:
        r = int(replica)
        s = max(float(latency_ms), 0.0)
        prev = self._ewma.get(r)
        self._ewma[r] = s if prev is None else (
            self.alpha * s + (1.0 - self.alpha) * prev)
        self._samples[r] = self._samples.get(r, 0) + 1

    def forget(self, replica: int) -> None:
        self._ewma.pop(int(replica), None)
        self._samples.pop(int(replica), None)

    def cost(self, replica: int) -> float:
        return max(self._ewma.get(int(replica), self.floor_ms),
                   self.floor_ms)

    def costs(self) -> Dict[int, float]:
        return {r: max(v, self.floor_ms)
                for r, v in sorted(self._ewma.items())}

    def samples(self, replica: int) -> int:
        return self._samples.get(int(replica), 0)

    def median_ms(self) -> Optional[float]:
        """Fleet-median EWMA latency — the hedging yardstick.  None
        until at least one replica has been observed."""
        if not self._ewma:
            return None
        return _median(sorted(max(v, self.floor_ms)
                              for v in self._ewma.values()))


@dataclass
class RouteResult:
    """What :meth:`FleetRouter.request` returns."""

    values: list
    generation: int
    replica: int          # the replica whose answer won
    latency_ms: float     # client-observed, admission -> answer
    attempt: int = 1      # 1 = first try; >1 means retried after evict
    hedged: bool = False  # a hedge was launched for this request
    retried: bool = False


@dataclass
class FleetStats:
    """Router-side counters — the drill's quick verdict numbers; the
    authoritative story is the ``fleet_route`` record stream."""

    requests: int = 0
    retries: int = 0
    hedges: int = 0
    hedges_won: int = 0   # the hedge replica answered first
    evictions: int = 0
    shed: Dict[str, int] = field(default_factory=dict)
    per_replica: Dict[int, int] = field(default_factory=dict)


class FleetRouter:
    """See module docstring.  ``replicas`` maps replica index ->
    backend; ``monitor`` is a ``HostMonitor`` over the fleet heartbeat
    directory (optional: without one every member is assumed ok)."""

    def __init__(self, replicas: Dict[int, object], *,
                 monitor=None, telemetry=None,
                 alpha: float = DEFAULT_ALPHA,
                 floor_ms: float = DEFAULT_FLOOR_MS,
                 hedge_multiple: float = DEFAULT_HEDGE_MULTIPLE,
                 hedge_floor_ms: float = DEFAULT_HEDGE_FLOOR_MS,
                 min_hedge_samples: int = DEFAULT_MIN_HEDGE_SAMPLES,
                 warm_every: int = DEFAULT_WARM_EVERY,
                 spread_tolerance: float = DEFAULT_SPREAD_TOLERANCE,
                 tenant_max_outstanding: int = DEFAULT_TENANT_OUTSTANDING,
                 request_timeout_s: float = 30.0,
                 max_workers: Optional[int] = None):
        if hedge_multiple <= 1:
            raise ValueError("hedge_multiple must be > 1")
        if warm_every < 2:
            raise ValueError("warm_every must be >= 2")
        if spread_tolerance < 1:
            raise ValueError("spread_tolerance must be >= 1")
        if tenant_max_outstanding < 1:
            raise ValueError("tenant_max_outstanding must be >= 1")
        self.monitor = monitor
        self.telemetry = telemetry
        self.tracker = ReplicaLatencyTracker(alpha=alpha,
                                             floor_ms=floor_ms)
        self.hedge_multiple = float(hedge_multiple)
        self.hedge_floor_ms = float(hedge_floor_ms)
        self.min_hedge_samples = int(min_hedge_samples)
        self.warm_every = int(warm_every)
        self.spread_tolerance = float(spread_tolerance)
        self.tenant_max_outstanding = int(tenant_max_outstanding)
        self.request_timeout_s = float(request_timeout_s)
        self.stats = FleetStats()
        self._lock = threading.Lock()
        self._replicas: Dict[int, object] = {
            int(r): b for r, b in replicas.items()}
        self._evicted: set = set()
        self._outstanding: Dict[int, int] = {}
        self._tenant_outstanding: Dict[str, int] = {}
        self._verdicts: Dict[int, str] = {}
        self._warm_tick = 0
        self._pool = ThreadPoolExecutor(
            max_workers=(max_workers if max_workers is not None
                         else 2 * max(len(self._replicas), 1) + 4),
            thread_name_prefix="fleet-router")

    # -- membership --------------------------------------------------------
    @property
    def members(self) -> List[int]:
        with self._lock:
            return sorted(self._replicas)

    def refresh_membership(self, replicas: Dict[int, object]) -> dict:
        """Adopt a freshly-discovered membership map: new replicas
        join (optimistic floor cost — they get traffic immediately),
        absent ones leave.  An EVICTED index is sticky: it only
        rejoins on proof of life — a monitor verdict of ``"ok"`` from
        a fresh heartbeat (a crashed replica's leftover membership
        file reads "slow" while its last beat ages toward stale, and
        must never resurrect it).  The elastic-resume analogue:
        membership changes ride generation boundaries, the caller
        decides when."""
        joined, left = [], []
        verdicts = (self.monitor.verdicts()
                    if self.monitor is not None else {})
        with self._lock:
            incoming = {int(r): b for r, b in replicas.items()}
            for r, backend in incoming.items():
                if r not in self._replicas:
                    if r in self._evicted and verdicts.get(r) != "ok":
                        continue
                    self._replicas[r] = backend
                    self._evicted.discard(r)
                    self.tracker.forget(r)
                    joined.append(r)
            for r in [r for r in self._replicas if r not in incoming]:
                del self._replicas[r]
                self.tracker.forget(r)
                left.append(r)
        if self.telemetry is not None:
            for r in joined:
                self.telemetry.fleet_route(
                    decision="route", replica=r, reason="join",
                    source="serve.router", tool="serve.router")
        return {"joined": joined, "left": left}

    # -- verdicts ----------------------------------------------------------
    def verdict_sync(self) -> Dict[int, str]:
        """Read the monitor's ok/slow/lost verdicts, emit one
        ``replica_verdict`` record per *change*, and evict newly-lost
        replicas (``replica_evict`` recovery, once each).  Called at
        the top of every request; cheap — a directory stat."""
        if self.monitor is None:
            return {r: "ok" for r in self.members}
        raw = self.monitor.verdicts()
        with self._lock:
            verdicts = {r: raw.get(r, "ok") for r in self._replicas}
            changed = [(r, v, self._verdicts.get(r))
                       for r, v in verdicts.items()
                       if self._verdicts.get(r) != v]
            self._verdicts = dict(verdicts)
        for r, v, prev in changed:
            if self.telemetry is not None:
                self.telemetry.replica_verdict(
                    replica=r, verdict=v, previous=prev,
                    source="serve.router", tool="serve.router")
            if v == "lost":
                self._evict(r, reason="heartbeat stale")
        return verdicts

    def _evict(self, replica: int, *, reason: str) -> None:
        with self._lock:
            if replica in self._evicted:
                return
            self._evicted.add(replica)
            self._replicas.pop(replica, None)
            self.tracker.forget(replica)
            self.stats.evictions += 1
        if self.telemetry is not None:
            self.telemetry.recovery(
                action="replica_evict", process=int(replica),
                reason=reason, source="serve.router")

    # -- candidate selection ----------------------------------------------
    def _candidates(self, exclude: set) -> List[int]:
        """Live replicas ranked by queue-adjusted EWMA cost.  OK
        replicas first; SLOW ones appended (kept warm, deprioritized)
        — and every ``warm_every``-th pick deliberately leads with the
        most *expensive* member (by EWMA, not verdict: verdicts can
        flap while the cost stays high) so its estimate keeps
        breathing and a recovered replica can rejoin the spread band.

        Within ``spread_tolerance`` × the cheapest cost, OK replicas
        are ranked least-served-first instead of strictly by cost:
        pure min-cost routing self-reinforces (only the replica that
        gets traffic gets fresh samples) and collapses onto one host;
        the band spreads statistically-equal replicas evenly while a
        genuinely slow one — whose EWMA leaves the band — still loses
        its traffic, which is exactly the shift ``gate_fleet``
        measures."""
        with self._lock:
            verdicts = dict(self._verdicts)
            live = [r for r in self._replicas if r not in exclude]
            outstanding = dict(self._outstanding)
            served = dict(self.stats.per_replica)
            self._warm_tick += 1
            warm_turn = (self._warm_tick % self.warm_every == 0)

        def cost(r: int) -> float:
            return self.tracker.cost(r) * (1 + outstanding.get(r, 0))

        ok = sorted((r for r in live
                     if verdicts.get(r, "ok") == "ok"), key=cost)
        if len(ok) > 1:
            band = cost(ok[0]) * self.spread_tolerance
            near = sorted((r for r in ok if cost(r) <= band),
                          key=lambda r: (served.get(r, 0), cost(r)))
            ok = near + [r for r in ok if r not in near]
        slow = sorted((r for r in live
                       if verdicts.get(r, "ok") == "slow"), key=cost)
        ranked = ok + slow
        if warm_turn and len(ranked) > 1:
            probe = max(ranked, key=self.tracker.cost)
            if probe != ranked[0]:
                ranked = [probe] + [r for r in ranked if r != probe]
        return ranked

    # -- the request path --------------------------------------------------
    def request(self, rows, op: str = "predict",
                tenant: Optional[str] = None,
                timeout: Optional[float] = None) -> RouteResult:
        """Route one request; returns a :class:`RouteResult` or raises
        typed: ``ServeOverloaded`` (tenant cap / fleet-wide shed),
        ``NoReplicasLeft`` (every replica gone — TRANSIENT)."""
        timeout = self.request_timeout_s if timeout is None else timeout
        tenant_key = None if tenant is None else str(tenant)
        self._admit_tenant(tenant_key, rows, op)
        try:
            return self._routed(rows, op, tenant_key, timeout)
        finally:
            self._release_tenant(tenant_key)

    def _admit_tenant(self, tenant: Optional[str], rows, op: str):
        if tenant is None:
            return
        with self._lock:
            n = self._tenant_outstanding.get(tenant, 0)
            if n >= self.tenant_max_outstanding:
                self.stats.shed[tenant] = (
                    self.stats.shed.get(tenant, 0) + 1)
                shed_count = self.stats.shed[tenant]
            else:
                self._tenant_outstanding[tenant] = n + 1
                return
        if self.telemetry is not None:
            self.telemetry.fleet_route(
                decision="shed_tenant", tenant=tenant, op=op,
                rows=int(getattr(rows, "shape", [len(rows)])[0]),
                outstanding=n, reason="tenant admission cap",
                source="serve.router", tool="serve.router")
            self.telemetry.registry.counter(
                "serve.tenant_rejected").inc()
            self.telemetry.registry.counter(
                f"serve.tenant_rejected.{tenant}").inc()
        raise ServeOverloaded(
            n, self.tenant_max_outstanding,
            detail=f"tenant {tenant!r} at admission cap "
                   f"(shed #{shed_count})")

    def _release_tenant(self, tenant: Optional[str]) -> None:
        if tenant is None:
            return
        with self._lock:
            n = self._tenant_outstanding.get(tenant, 0)
            if n > 0:
                self._tenant_outstanding[tenant] = n - 1

    def _issue(self, replica: int, rows, op: str,
               tenant: Optional[str], timeout: float, ctx=None):
        backend = self._replicas.get(replica)
        if backend is None:
            raise ConnectionError(f"replica {replica} left the fleet")
        with self._lock:
            self._outstanding[replica] = (
                self._outstanding.get(replica, 0) + 1)
        try:
            # re-activate the caller's trace context: _issue runs on a
            # pool thread, where the thread-local context is empty
            t0 = time.monotonic()
            with trace_lib.activate(ctx):
                payload = backend.predict(rows, op=op, tenant=tenant,
                                          timeout=timeout)
            # observe the CLIENT-measured wall (includes any injected
            # stall the replica's own queue clock never sees), and do
            # it here — not on the winner in _routed — so a hedged
            # race's LOSER still teaches the tracker its true cost
            self.tracker.observe(
                replica, (time.monotonic() - t0) * 1e3)
            return payload
        finally:
            with self._lock:
                self._outstanding[replica] = max(
                    0, self._outstanding.get(replica, 1) - 1)

    def _hedge_wait_s(self) -> Optional[float]:
        """How long to let the primary run before hedging; None
        disables hedging (not enough samples to trust a median)."""
        med = self.tracker.median_ms()
        if med is None:
            return None
        total = sum(self.tracker.samples(r) for r in self.members)
        if total < self.min_hedge_samples:
            return None
        return max(self.hedge_multiple * med,
                   self.hedge_floor_ms) / 1e3

    def _routed(self, rows, op: str, tenant: Optional[str],
                timeout: float) -> RouteResult:
        t0 = time.monotonic()
        deadline = t0 + timeout
        tried: set = set()
        attempt = 0
        hedged = False
        while True:
            self.verdict_sync()
            candidates = self._candidates(tried)
            if not candidates:
                raise NoReplicasLeft(
                    f"tried {sorted(tried)}" if tried else "empty fleet")
            primary = candidates[0]
            attempt += 1
            tried.add(primary)
            try:
                result = self._race(primary, candidates[1:], rows, op,
                                    tenant, deadline)
            except ConnectionError as e:
                # the replica died under us: evict once, retry the
                # request on a survivor — transparently, because
                # predict is pure (idempotent by construction)
                self._evict(primary, reason=f"{type(e).__name__}: {e}")
                with self._lock:
                    self.stats.retries += 1
                if self.telemetry is not None:
                    self.telemetry.recovery(
                        action="request_retry", process=int(primary),
                        reason=f"replica {primary} unreachable; "
                               f"re-routing (attempt {attempt + 1})",
                        source="serve.router")
                    self.telemetry.fleet_route(
                        decision="retry", replica=primary, op=op,
                        rows=int(getattr(rows, "shape",
                                         [len(rows)])[0]),
                        attempt=attempt,
                        error=f"{type(e).__name__}: {e}",
                        source="serve.router", tool="serve.router",
                        **({} if tenant is None else
                           {"tenant": tenant}))
                continue
            winner, payload, was_hedged = result
            hedged = hedged or was_hedged
            latency_ms = (time.monotonic() - t0) * 1e3
            with self._lock:
                self.stats.requests += 1
                self.stats.per_replica[winner] = (
                    self.stats.per_replica.get(winner, 0) + 1)
                verdict = self._verdicts.get(winner, "ok")
            if self.telemetry is not None:
                self.telemetry.fleet_route(
                    decision="hedge" if was_hedged else "route",
                    replica=primary, winner=winner, op=op,
                    rows=int(getattr(rows, "shape", [len(rows)])[0]),
                    attempt=attempt,
                    latency_ms=round(latency_ms, 3),
                    ewma_ms=round(self.tracker.cost(winner), 3),
                    median_ms=self.tracker.median_ms(),
                    verdict=verdict,
                    generation=int(payload.get("generation", -1)),
                    source="serve.router", tool="serve.router",
                    **({} if tenant is None else {"tenant": tenant}))
            return RouteResult(
                values=payload["values"],
                generation=int(payload.get("generation", -1)),
                replica=winner,
                latency_ms=latency_ms,
                attempt=attempt,
                hedged=hedged,
                retried=attempt > 1)

    def _race(self, primary: int, alternates: List[int], rows, op,
              tenant, deadline):
        """Issue to the primary; if it outlives the hedge window and an
        alternate exists, race a hedge — first answer wins, the loser
        is ignored (predict is pure, an extra answer is just heat).
        Returns ``(winner, payload, hedged)``; raises the primary's
        ``ConnectionError`` only when no hedge answer saved the
        request."""
        remaining = max(deadline - time.monotonic(), 1e-3)
        ctx = trace_lib.current_context()
        fut = self._pool.submit(self._issue, primary, rows, op,
                                tenant, remaining, ctx)
        hedge_wait = self._hedge_wait_s()
        if hedge_wait is not None and alternates:
            done, _ = wait([fut], timeout=min(hedge_wait, remaining))
            if not done:
                hedge_to = alternates[0]
                with self._lock:
                    self.stats.hedges += 1
                if self.telemetry is not None:
                    self.telemetry.recovery(
                        action="request_hedge", process=int(hedge_to),
                        reason=f"primary {primary} exceeded "
                               f"{self.hedge_multiple:g}x fleet "
                               "median; racing a second copy",
                        source="serve.router")
                remaining = max(deadline - time.monotonic(), 1e-3)
                hfut = self._pool.submit(self._issue, hedge_to, rows,
                                         op, tenant, remaining, ctx)
                return self._first_of(primary, fut, hedge_to, hfut,
                                      deadline)
        return primary, fut.result(
            timeout=max(deadline - time.monotonic(), 1e-3)), False

    def _first_of(self, primary, fut, hedge_to, hfut, deadline):
        pending = {fut: primary, hfut: hedge_to}
        first_err = None
        while pending:
            done, _ = wait(list(pending), timeout=max(
                deadline - time.monotonic(), 1e-3),
                return_when=FIRST_COMPLETED)
            if not done:
                raise TimeoutError("request deadline during hedge race")
            for f in done:
                who = pending.pop(f)
                try:
                    payload = f.result()
                except (ConnectionError, ServeOverloaded, OSError) as e:
                    first_err = first_err or e
                    continue
                if who == hedge_to:
                    with self._lock:
                        self.stats.hedges_won += 1
                return who, payload, True
        # both sides failed: surface as ConnectionError so the retry
        # path evicts and re-routes
        if isinstance(first_err, ServeOverloaded):
            raise first_err
        raise ConnectionError(
            f"both primary {primary} and hedge {hedge_to} failed: "
            f"{first_err}")

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        self._pool.shutdown(wait=False, cancel_futures=True)

    def __enter__(self) -> "FleetRouter":
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False
