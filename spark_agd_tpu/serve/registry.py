"""Versioned model registry over the CRC-verified manifest machinery.

A served model is a checkpoint with users attached, so the registry
speaks the exact commit protocol the training side's distributed
checkpoints do (``resilience.manifest``): a published generation is one
shard npz (the model snapshot, written atomically with per-entry CRCs
by ``models.glm.save_model``) plus one ``manifest-gNNNNNNNN.json``
carrying the file-level CRC32/size, committed by atomically repointing
``manifest.json``.  That buys serving the same guarantees training
already trusts:

- a generation is visible only once fully landed (manifest-after-shard
  ordering);
- a torn, truncated, or bit-flipped shard FAILS ``verify_manifest`` and
  the loader walks back one generation instead of serving garbage —
  the refusal is identical to ``DistributedCheckpointer``'s, down to
  the ``checkpoint_fallback`` recovery record;
- old generations are the rollback chain (``keep`` newest retained,
  GC'd with the same in-flight-orphan sparing).

Hot swap: ``refresh(engine=...)`` loads the newest verifiable
generation and binds its weights into the running engine's compiled
programs — weights are program arguments, so in-flight batches finish
on the old generation and the next batch serves the new one; nothing
drops and nothing recompiles.  Each swap emits a ``recovery`` record
with the new ``hot_swap`` action.

A training loop publishes with ``registry.publish(model)``; a serving
process polls ``registry.refresh(engine)`` — the two never need to
share more than the directory.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Any, List, Optional

from ..resilience import manifest as mf
from ..utils.checkpoint import CheckpointCorruptError

DEFAULT_KEEP = 4

# serving is single-process on the loading side: the one shard of a
# published generation is written as process 0
_SHARD_PROCESS = 0


@dataclasses.dataclass
class LoadedModel:
    """One verified, loaded generation."""

    generation: int
    model: Any
    path: str                 # the shard file the model came from
    manifest: mf.Manifest


class ModelRegistry:
    """See module docstring."""

    def __init__(self, directory: str, *, telemetry=None,
                 fingerprint: Optional[str] = None,
                 keep: int = DEFAULT_KEEP):
        self.directory = str(directory)
        self.telemetry = telemetry
        self.fingerprint = fingerprint
        self.keep = max(1, int(keep))
        self._current: Optional[LoadedModel] = None

    # -- publishing --------------------------------------------------------
    def newest_generation(self) -> int:
        """Newest committed generation id (0 when none)."""
        gens = mf.committed_generations(self.directory)
        return gens[0] if gens else 0

    def publish(self, model, *, converged: bool = False,
                prior_iters: int = 0) -> int:
        """Commit one model snapshot as the next generation: shard
        first (atomic npz), manifest after — the same write ordering
        the distributed checkpointer uses, so a crash mid-publish
        leaves an invisible orphan, never a half-visible generation.
        Returns the new generation id."""
        from ..models.glm import save_model

        os.makedirs(self.directory, exist_ok=True)
        generation = self.newest_generation() + 1
        shard = mf.shard_name(generation, _SHARD_PROCESS)
        path = os.path.join(self.directory, shard)
        save_model(model, path)
        entry = mf.ShardEntry(path=shard, process=_SHARD_PROCESS,
                              crc32=mf.crc32_file(path),
                              size=os.path.getsize(path))
        man = mf.Manifest(generation=generation, process_count=1,
                          shards=[entry], fingerprint=self.fingerprint,
                          converged=bool(converged),
                          prior_iters=int(prior_iters))
        mf.write_manifest(self.directory, man)
        mf.gc_generations(self.directory, self.keep)
        if self.telemetry is not None:
            self.telemetry.recovery(
                action="checkpoint", generation=generation, path=shard,
                source="serve.registry", tool="serve.registry")
        return generation

    # -- loading -----------------------------------------------------------
    def load(self, generation: Optional[int] = None) -> LoadedModel:
        """Load one specific generation (default: the HEAD manifest's),
        REFUSING anything unverifiable: a missing manifest raises
        ``LookupError``, a failed file-level CRC/size check or an
        unparseable shard raises ``CheckpointCorruptError`` — exactly
        the training-side loader contract, with no fallback."""
        man = mf.load_manifest(self.directory, generation)
        if man is None:
            raise LookupError(
                f"no committed generation"
                + (f" g{generation}" if generation is not None else "")
                + f" in {self.directory!r}")
        return self._load_manifest(man)

    def _load_manifest(self, man: mf.Manifest) -> LoadedModel:
        from ..models.glm import load_model

        problems = mf.verify_manifest(man, self.directory)
        if problems:
            raise CheckpointCorruptError(
                self.directory,
                ValueError(f"generation g{man.generation}: "
                           + "; ".join(problems)))
        path = man.shard_path(self.directory, _SHARD_PROCESS)
        try:
            model = load_model(path)
        except (ValueError, KeyError, OSError) as e:
            raise CheckpointCorruptError(path, e) from e
        return LoadedModel(man.generation, model, path, man)

    def load_newest(self) -> Optional[LoadedModel]:
        """Walk committed generations newest → oldest, returning the
        first that verifies and loads; unverifiable generations are
        skipped with a ``checkpoint_fallback`` recovery record (the
        multi-generation ``.bak`` chain, serving edition).  None when
        nothing loadable exists."""
        for generation in mf.committed_generations(self.directory):
            man = mf.load_manifest(self.directory, generation)
            if man is None:
                continue
            try:
                return self._load_manifest(man)
            except CheckpointCorruptError as e:
                if self.telemetry is not None:
                    self.telemetry.recovery(
                        action="checkpoint_fallback",
                        generation=generation, reason=str(e)[:200],
                        source="serve.registry", tool="serve.registry")
        return None

    # -- hot swap ----------------------------------------------------------
    @property
    def current(self) -> Optional[LoadedModel]:
        return self._current

    def refresh(self, engine=None) -> Optional[int]:
        """Poll for a newer loadable generation; when found, bind it
        into ``engine`` (when given) and emit a ``hot_swap`` recovery
        record.  Returns the new generation id, or None when already
        current (or nothing loadable).  A spec-incompatible generation
        propagates ``ServeSpecMismatch`` from the engine — the registry
        never half-swaps."""
        newest = self.newest_generation()
        have = self._current.generation if self._current else 0
        if newest <= have and self._current is not None:
            return None
        loaded = self.load_newest()
        if loaded is None or (self._current is not None
                              and loaded.generation <= have):
            return None
        if engine is not None:
            engine.bind(loaded.model, loaded.generation)
        previous = have
        self._current = loaded
        if self.telemetry is not None:
            self.telemetry.recovery(
                action="hot_swap", generation=loaded.generation,
                from_generation=previous, source="serve.registry",
                tool="serve.registry")
        return loaded.generation

    # -- rollback ----------------------------------------------------------
    def previous(self, generation: Optional[int] = None) -> Optional[int]:
        """The newest VERIFIABLE committed generation strictly older
        than ``generation`` (default: the currently-bound generation,
        falling back to HEAD's).  Unverifiable generations along the
        walk are skipped with a ``checkpoint_fallback`` recovery record,
        exactly like ``load_newest``; None when nothing older is
        loadable — the rollback chain is exhausted."""
        if generation is None:
            if self._current is not None:
                generation = self._current.generation
            else:
                head = mf.load_manifest(self.directory)
                if head is None:
                    return None
                generation = head.generation
        for g in mf.committed_generations(self.directory):
            if g >= generation:
                continue
            man = mf.load_manifest(self.directory, g)
            if man is None:
                continue
            if not mf.verify_manifest(man, self.directory):
                return g
            if self.telemetry is not None:
                self.telemetry.recovery(
                    action="checkpoint_fallback", generation=g,
                    reason="skipped while walking back: failed "
                           "file-level verification",
                    source="serve.registry", tool="serve.registry")
        return None

    def repoint(self, generation: int, engine=None) -> LoadedModel:
        """Deliberately move serving HEAD to ``generation`` — forward
        (promotion) or backward (rollback).  The target must be a
        committed, verifiable generation: a missing manifest raises
        ``LookupError``, a torn shard raises ``CheckpointCorruptError``
        (``checkpoint_fallback``-recorded) — the registry never repoints
        at garbage.  On success the manifest HEAD pointer is atomically
        rewritten (so a restart serves this generation), the model is
        bound into ``engine`` when given, and a ``hot_swap`` recovery
        record ties the movement into the trace."""
        man = mf.load_manifest(self.directory, generation)
        if man is None:
            raise LookupError(
                f"no committed generation g{generation} in "
                f"{self.directory!r}")
        try:
            loaded = self._load_manifest(man)
        except CheckpointCorruptError:
            if self.telemetry is not None:
                self.telemetry.recovery(
                    action="checkpoint_fallback", generation=generation,
                    reason="repoint refused: target failed "
                           "verification",
                    source="serve.registry", tool="serve.registry")
            raise
        mf.repoint_head(self.directory, man)
        if engine is not None:
            engine.bind(loaded.model, loaded.generation)
        previous = (self._current.generation
                    if self._current is not None else 0)
        self._current = loaded
        if self.telemetry is not None:
            self.telemetry.recovery(
                action="hot_swap", generation=loaded.generation,
                from_generation=previous, source="serve.registry",
                tool="serve.registry")
        return loaded

    def gc(self) -> List[str]:
        """Housekeeping: drop all but the ``keep`` newest generations
        (same in-flight-orphan sparing as the training GC)."""
        return mf.gc_generations(self.directory, self.keep)
