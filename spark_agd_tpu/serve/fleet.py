"""Replica fleet plumbing: the process-level serving replica and the
client handle the router talks through.

One replica = one real OS process running :class:`ReplicaServer`:

- a ``ServeEngine`` + ``MicroBatchQueue`` (constructed with
  ``replica=`` so every ``serve_request`` record is attributed),
- a ``HeartbeatWriter`` beating ``phase="serve"`` into the shared
  fleet directory — the SAME heartbeat machinery the training drills
  use, so ``HostMonitor.verdicts()`` works unchanged over replicas,
- a ``ModelRegistry`` poller so a ``publish()``/``repoint()`` from the
  continuous-learning pipeline fans out fleet-wide: every replica's
  next poll sees the new HEAD and hot-swaps (weights are program
  arguments — zero dropped requests),
- a localhost TCP JSON-line endpoint (one line in, one line out),
  announced through an atomically-written ``replica.hNNN.json``
  membership file next to the heartbeats.

Membership is file-based on purpose: joins/leaves are a file
appearing/vanishing, discovery (:func:`discover_replicas`) is a
directory listing, and the gloo process group is only needed ONCE —
at fleet start, to barrier replicas before clients arrive (the drill
does that with ``parallel.multihost``); the request path never runs a
collective.

Transport protocol (versioned by field presence, all JSON):

    -> {"op": "predict", "rows": [[...]], "tenant": "acme",
        "trace": {...SpanContext.to_wire()...}}
    <- {"status": "ok", "values": [...], "generation": 5,
        "replica": 2, "latency_ms": 1.8}
    <- {"status": "rejected", "error": "ServeOverloaded",
        "queued_rows": 64, "limit_rows": 64}
    <- {"status": "error", "error": "ValueError: ..."}

The trace context rides the wire so a request span in the replica
parents under the CLIENT's span — the whole fleet story reconstructs
as one tree.  Chaos hooks: a ``ChaosSchedule`` bound via ``chaos=``
fires ``before_request`` per admitted request (``slow_replica`` sleeps
inline while the bound heartbeat beats ``phase="slow"``;
``kill_replica`` SIGKILLs the process mid-soak — the drill's
zero-dropped-requests proof).
"""

from __future__ import annotations

import json
import os
import re
import socket
import threading
import time
from typing import Dict, Optional

import numpy as np

from ..obs import trace as trace_lib
from ..resilience import manifest as manifest_lib
from ..resilience.distributed import HeartbeatWriter
from ..resilience.errors import ServeOverloaded
from .queue import MicroBatchQueue

_REPLICA_RE = re.compile(r"^replica\.h(\d{3})\.json$")
DEFAULT_BEAT_EVERY_S = 0.25
DEFAULT_POLL_EVERY_S = 0.25
_RECV_CHUNK = 65536


def replica_file_name(replica: int) -> str:
    return f"replica.h{int(replica):03d}.json"


# -- client side ------------------------------------------------------------
class ReplicaHandle:
    """The router's backend for one replica: connection-per-request
    over localhost TCP (simple, and a dead replica fails fast as
    ``ConnectionError`` instead of poisoning a pooled socket).  Typed
    surfaces: ``ServeOverloaded`` for a replica-side shed,
    ``ConnectionError``/``TimeoutError`` for death/stall — exactly
    what ``FleetRouter`` retries, hedges, and evicts on."""

    def __init__(self, replica: int, port: int, *,
                 host: str = "127.0.0.1", pid: Optional[int] = None):
        self.replica = int(replica)
        self.port = int(port)
        self.host = host
        self.pid = pid

    def __repr__(self) -> str:
        return (f"ReplicaHandle(replica={self.replica}, "
                f"port={self.port})")

    def predict(self, rows, op: str = "predict",
                tenant: Optional[str] = None,
                timeout: float = 30.0) -> dict:
        payload: dict = {"op": op,
                         "rows": np.asarray(rows, dtype=np.float32)
                         .tolist()}
        if tenant is not None:
            payload["tenant"] = str(tenant)
        ctx = trace_lib.current_context()
        if ctx is not None:
            payload["trace"] = ctx.to_wire()
        line = (json.dumps(payload) + "\n").encode()
        with socket.create_connection((self.host, self.port),
                                      timeout=timeout) as sock:
            sock.settimeout(timeout)
            sock.sendall(line)
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = sock.recv(_RECV_CHUNK)
                if not chunk:
                    raise ConnectionError(
                        f"replica {self.replica} closed the "
                        "connection mid-request")
                buf += chunk
        resp = json.loads(buf.decode())
        status = resp.get("status")
        if status == "ok":
            return resp
        if status == "rejected":
            raise ServeOverloaded(
                int(resp.get("queued_rows", 0)),
                int(resp.get("limit_rows", 0)),
                detail=f"replica {self.replica} shed: "
                       f"{resp.get('error', 'overloaded')}")
        raise RuntimeError(
            f"replica {self.replica} error: "
            f"{resp.get('error', 'unknown')}")


def discover_replicas(fleet_dir: str) -> Dict[int, ReplicaHandle]:
    """Parse every ``replica.hNNN.json`` membership file into a
    handle map — the router's ``refresh_membership`` input.  Torn or
    garbled files (a join mid-write) are skipped, not fatal; the next
    discovery sees them whole."""
    out: Dict[int, ReplicaHandle] = {}
    if not os.path.isdir(fleet_dir):
        return out
    for name in sorted(os.listdir(fleet_dir)):
        m = _REPLICA_RE.match(name)
        if not m:
            continue
        try:
            with open(os.path.join(fleet_dir, name)) as f:
                rec = json.load(f)
            out[int(m.group(1))] = ReplicaHandle(
                int(m.group(1)), int(rec["port"]),
                pid=rec.get("pid"))
        except (ValueError, KeyError, OSError):
            continue
    return out


# -- server side ------------------------------------------------------------
class ReplicaServer:
    """See module docstring.  ``start()`` binds the socket, announces
    membership, and spawns the accept/heartbeat/registry-poll threads;
    ``stop()`` leaves cleanly (membership + heartbeat files removed —
    a *leave*, distinct from a crash the monitor flags LOST).  Context
    manager form does both."""

    def __init__(self, fleet_dir: str, replica: int, engine, *,
                 registry=None, telemetry=None, chaos=None,
                 process_count: Optional[int] = None,
                 max_wait_us: int = 2000,
                 max_queue_rows: Optional[int] = None,
                 beat_every_s: float = DEFAULT_BEAT_EVERY_S,
                 poll_every_s: float = DEFAULT_POLL_EVERY_S):
        self.fleet_dir = fleet_dir
        self.replica = int(replica)
        self.engine = engine
        self.registry = registry
        self.telemetry = telemetry
        self.chaos = chaos
        self.beat_every_s = float(beat_every_s)
        self.poll_every_s = float(poll_every_s)
        self.queue = MicroBatchQueue(
            engine, telemetry=telemetry, replica=self.replica,
            max_wait_us=max_wait_us, max_queue_rows=max_queue_rows)
        self.heartbeat = HeartbeatWriter(
            fleet_dir, process_index=self.replica,
            # membership is elastic: without an explicit count, claim
            # just enough room for our own index (a late joiner must
            # not be rejected by a single-process inference)
            process_count=(process_count if process_count is not None
                           else self.replica + 1),
            telemetry=telemetry)
        if chaos is not None:
            # chaos slow-sleeps beat phase="slow" through the injected
            # stall -> HostMonitor verdicts the replica SLOW, and the
            # router measurably shifts traffic (the gate_fleet proof)
            chaos.bind_heartbeat(self.heartbeat)
        self.port: Optional[int] = None
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads: list = []
        self._requests_seen = 0
        self._req_lock = threading.Lock()

    @property
    def membership_path(self) -> str:
        return os.path.join(self.fleet_dir,
                            replica_file_name(self.replica))

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "ReplicaServer":
        os.makedirs(self.fleet_dir, exist_ok=True)
        self._sock = socket.create_server(("127.0.0.1", 0))
        self._sock.settimeout(0.2)
        self.port = self._sock.getsockname()[1]
        self.queue.start()
        # beat BEFORE announcing: a discovered replica always has a
        # heartbeat on disk, so it can never be born "lost"
        self.heartbeat.beat(phase="serve")
        manifest_lib._atomic_write_text(
            self.membership_path,
            json.dumps({"replica": self.replica, "port": self.port,
                        "pid": os.getpid(),
                        "time": round(time.time(), 3)}))
        for name, fn in (("accept", self._accept_loop),
                         ("beat", self._beat_loop),
                         ("poll", self._poll_loop)):
            t = threading.Thread(
                target=fn, name=f"replica{self.replica}-{name}",
                daemon=True)
            t.start()
            self._threads.append(t)
        return self

    def request_stop(self) -> None:
        """Async-signal-safe stop flag (a SIGTERM handler calls this;
        the owning thread then runs :meth:`stop` for the real
        teardown — joining threads from a handler would deadlock)."""
        self._stop.set()

    @property
    def requests_seen(self) -> int:
        """Requests accepted off the wire so far (chaos boundary
        counter — the drill's summaries report it)."""
        with self._req_lock:
            return self._requests_seen

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        self._threads = []
        if self._sock is not None:
            self._sock.close()
            self._sock = None
        self.queue.stop()
        # a clean LEAVE removes both announcements; a crash leaves
        # them and the monitor says "lost" — that asymmetry is the
        # whole verdict story
        for path in (self.membership_path, self.heartbeat.path):
            try:
                os.remove(path)
            except OSError:
                pass

    def __enter__(self) -> "ReplicaServer":
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False

    def wait(self, timeout: Optional[float] = None) -> None:
        """Block the caller (a drill child's main thread) until
        ``stop()`` — or forever, which for a kill_replica leg means
        until SIGKILL."""
        self._stop.wait(timeout)

    # -- background loops --------------------------------------------------
    def _beat_loop(self) -> None:
        while not self._stop.wait(self.beat_every_s):
            self.heartbeat.beat(phase="serve")

    def _poll_loop(self) -> None:
        if self.registry is None:
            return
        while not self._stop.wait(self.poll_every_s):
            try:
                self.registry.refresh(self.engine)
            except Exception:  # noqa: BLE001 — a torn publish mid-
                # write must not kill the replica; next poll retries
                # (the registry's own fallback walk records the skip)
                continue

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 daemon=True)
            t.start()

    # -- the request path --------------------------------------------------
    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            conn.settimeout(60.0)
            buf = b""
            while not self._stop.is_set():
                while b"\n" not in buf:
                    try:
                        chunk = conn.recv(_RECV_CHUNK)
                    except (socket.timeout, OSError):
                        return
                    if not chunk:
                        return
                    buf += chunk
                line, buf = buf.split(b"\n", 1)
                try:
                    reply = self._handle(json.loads(line.decode()))
                except Exception as e:  # noqa: BLE001 — typed reply,
                    # the connection must outlive one bad request
                    reply = {"status": "error",
                             "error": f"{type(e).__name__}: {e}"}
                try:
                    conn.sendall((json.dumps(reply) + "\n").encode())
                except OSError:
                    return

    def _handle(self, req: dict) -> dict:
        with self._req_lock:
            self._requests_seen += 1
            index = self._requests_seen
        if self.chaos is not None:
            # slow_replica sleeps here (heartbeat says "slow");
            # kill_replica SIGKILLs — the client sees a reset and the
            # router retries on a survivor
            self.chaos.before_request(index)
        ctx = None
        if isinstance(req.get("trace"), dict):
            try:
                ctx = trace_lib.SpanContext.from_wire(req["trace"])
            except (KeyError, ValueError, TypeError):
                ctx = None  # garbled caller trace: serve untraced
        rows = np.asarray(req["rows"], dtype=np.float32)
        op = str(req.get("op", "predict"))
        tenant = req.get("tenant")
        try:
            with trace_lib.activate(ctx):
                fut = self.queue.submit(rows, op, tenant=tenant)
            res = fut.result(timeout=30.0)
        except ServeOverloaded as e:
            return {"status": "rejected", "error": "ServeOverloaded",
                    "queued_rows": e.queued_rows,
                    "limit_rows": e.limit_rows}
        return {"status": "ok",
                "values": np.asarray(res.value).tolist(),
                "generation": int(res.generation),
                "replica": self.replica,
                "latency_ms": round(float(res.latency_ms), 3)}
