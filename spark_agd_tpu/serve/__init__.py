"""serve/ — batched low-latency inference for the trained model zoo.

The north star demands "heavy traffic from millions of users"; after six
PRs the repo could train and survive anything while serving nothing.
This package is the serving plane, built on the same two disciplines the
training side already enforces:

- **compile once, never on the request path** (the Flare staged-query
  lesson, arXiv 1703.08219): :class:`~spark_agd_tpu.serve.engine.
  ServeEngine` AOT-compiles one program per (op, bucket) pair up front —
  a small ladder of padded batch shapes — so every request size maps to
  an existing executable and request-size jitter can never trigger an
  XLA recompile (the MLPerf TPU-pod fixed-shape playbook,
  arXiv 1909.09756);
- **verified state, typed refusals**: :class:`~spark_agd_tpu.serve.
  registry.ModelRegistry` publishes and loads model generations through
  ``resilience.manifest``'s CRC-verified manifests, refusing torn or
  corrupt generations exactly like the training-side loaders, and
  hot-swaps weights without dropping in-flight requests (weights are
  program *arguments*, so a swap is a pointer flip, not a recompile).

:class:`~spark_agd_tpu.serve.queue.MicroBatchQueue` sits in front:
dynamic micro-batching (max-batch + max-wait admission), padding to the
nearest bucket, per-request slicing, and backpressure with a typed
``ServeOverloaded`` rejection classified TRANSIENT by the resilience
taxonomy.  Telemetry rides the canonical ``obs.schema`` record family
(``serve_request`` / ``serve_latency``); ``tools/serve_drill.py`` is the
load-generator gate.  See ``docs/SERVING.md``.
"""

from ..resilience.errors import ServeOverloaded  # noqa: F401
from .engine import (BucketLadder, ModelSpec, ServeEngine,  # noqa: F401
                     params_of, spec_of)
from .fleet import (ReplicaHandle, ReplicaServer,  # noqa: F401
                    discover_replicas)
from .queue import MicroBatchQueue, ServeResult  # noqa: F401
from .registry import LoadedModel, ModelRegistry  # noqa: F401
from .router import (FleetRouter, NoReplicasLeft,  # noqa: F401
                     ReplicaLatencyTracker, RouteResult)

__all__ = [
    "BucketLadder", "FleetRouter", "LoadedModel", "MicroBatchQueue",
    "ModelRegistry", "ModelSpec", "NoReplicasLeft", "ReplicaHandle",
    "ReplicaLatencyTracker", "ReplicaServer", "RouteResult",
    "ServeEngine", "ServeOverloaded", "ServeResult",
    "discover_replicas", "params_of", "spec_of",
]
