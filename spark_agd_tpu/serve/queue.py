"""Dynamic micro-batching queue: coalesce, pad, serve, slice.

The throughput/latency bargain of batched serving: a lone request
should not wait for a full batch (latency), and a burst should not run
row-at-a-time (throughput).  The admission rule here is the standard
two-knob one — a batch closes when it holds ``max_batch`` rows OR the
oldest queued request has waited ``max_wait_us`` — so a quiet queue
serves singles at wire speed and a busy queue converges to full
buckets.

Backpressure is a hard row bound: when admitting a request would push
the queued row count past ``max_queue_rows``, ``submit`` raises the
typed :class:`~spark_agd_tpu.resilience.errors.ServeOverloaded`
(classified TRANSIENT — the client backs off and retries; the server
sheds instead of queueing unboundedly).

Device discipline (the ``host-sync`` lint rule patrols this file): the
worker loop coalesces host-side numpy only; exactly ONE device
round-trip happens per *batch* (inside ``ServeEngine.serve_batch``),
never per request — per-request work is pure numpy slicing of the
already-fetched batch output.

Telemetry: one ``serve_request`` record per request (ok / rejected /
error), and ``serve_latency`` rollups (QPS, p50/p99, queue depth) on
demand and at shutdown — the record kinds ``tools/agd_report.py``'s
serving section and the drill's perf gate consume.  Per-op queue depth
rides the ``serve.queue_depth.<op>`` gauges; tenant-attributed rejects
count under ``serve.tenant_rejected`` (and per tenant) — the fleet
router's admission-control evidence.  A queue constructed with
``replica=`` stamps that replica index onto every request/latency
record, so the router's per-replica EWMA and ``latency_summary()``
attribute the same numbers to the same replica.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Deque, Dict, List, Optional

import numpy as np

from ..obs import flight as flight_lib, trace as trace_lib
from ..resilience.errors import ServeOverloaded
from .engine import ServeEngine

DEFAULT_MAX_WAIT_US = 2000


@dataclasses.dataclass
class ServeResult:
    """What a request's future resolves to."""

    value: np.ndarray
    generation: int      # the model generation that served it
    op: str
    rows: int
    bucket: int          # padded batch shape the rows rode in
    batch_rows: int      # total live rows coalesced into that batch
    queue_ms: float      # admission -> dispatch
    latency_ms: float    # admission -> result ready


@dataclasses.dataclass
class _Request:
    rows: np.ndarray
    op: str
    future: Future
    t_submit: float
    squeeze: bool
    # the submitting thread's trace context (obs.trace), captured at
    # admission: the worker thread does NOT inherit the context
    # variable, so the causal request→batch→engine chain is carried
    # across the queue by hand — the explicit cross-thread propagation
    # rule of docs/OBSERVABILITY.md §distributed-tracing
    ctx: Optional[trace_lib.SpanContext] = None
    t_submit_unix: float = 0.0
    tenant: Optional[str] = None


class MicroBatchQueue:
    """See module docstring.  ``start()`` spawns the single worker
    thread (one engine call at a time — the engine's donated scratch
    wants exactly that); ``stop()`` drains admitted requests, then
    emits the final ``serve_latency`` rollup.  Context-manager form
    does both."""

    def __init__(self, engine: ServeEngine, *,
                 max_wait_us: int = DEFAULT_MAX_WAIT_US,
                 max_queue_rows: Optional[int] = None,
                 telemetry=None, replica: Optional[int] = None):
        self.engine = engine
        self.max_batch = engine.max_batch
        self.max_wait_s = max(0, int(max_wait_us)) / 1e6
        self.max_queue_rows = (4 * self.max_batch
                               if max_queue_rows is None
                               else int(max_queue_rows))
        self.telemetry = telemetry
        self.replica = None if replica is None else int(replica)
        self._pending: Deque[_Request] = deque()
        self._pending_rows = 0
        self._pending_rows_by_op: Dict[str, int] = {}
        self._cond = threading.Condition()
        self._stopping = False
        self._started = False
        self._worker: Optional[threading.Thread] = None
        self._t_start = time.monotonic()
        # rolled-up serving stats (guarded by _cond); the latency ring
        # is bounded so week-long soaks don't grow host memory —
        # percentiles are over the most recent window
        self._latencies_ms: Deque[float] = deque(maxlen=8192)
        self._requests_done = 0
        self._rows_done = 0
        self._rejected = 0
        self._errors = 0
        self._batches = 0

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "MicroBatchQueue":
        with self._cond:
            if self._started:
                return self
            self._started = True
            self._stopping = False
            self._t_start = time.monotonic()
        self._worker = threading.Thread(target=self._run,
                                        name="serve-queue", daemon=True)
        self._worker.start()
        return self

    def stop(self) -> None:
        """Drain everything already admitted, then stop the worker and
        emit the final latency rollup.  New submits are rejected from
        the moment stop is called."""
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join()
            self._worker = None
        with self._cond:
            self._started = False
        if self.telemetry is not None:
            self.emit_latency()

    def __enter__(self) -> "MicroBatchQueue":
        return self.start()

    def __exit__(self, exc_type, exc, tb):
        self.stop()
        return False

    # -- admission ---------------------------------------------------------
    def _attrib(self, tenant: Optional[str] = None) -> dict:
        """Optional record fields shared by every emit path: the
        replica this queue serves for, and the submitting tenant."""
        extra: dict = {}
        if self.replica is not None:
            extra["replica"] = self.replica
        if tenant is not None:
            extra["tenant"] = tenant
        return extra

    def _note_depth(self, op: str) -> None:
        """Refresh the per-op queue-depth gauge (caller holds _cond)."""
        if self.telemetry is not None:
            self.telemetry.registry.gauge(
                f"serve.queue_depth.{op}").set(
                    self._pending_rows_by_op.get(op, 0))

    def submit(self, x, op: str = "predict",
               tenant: Optional[str] = None) -> Future:
        """Admit one request (a feature row or a row batch); returns a
        future resolving to a :class:`ServeResult`.  Raises
        ``ServeOverloaded`` (TRANSIENT) at capacity, ``ValueError``
        (FATAL) for inadmissible shapes, ``RuntimeError`` once
        stopped."""
        rows = np.asarray(x, dtype=self.engine.spec.dtype)
        squeeze = rows.ndim == 1
        if squeeze:
            rows = rows[None, :]
        if rows.ndim != 2 or rows.shape[1] != self.engine.spec.n_features:
            raise ValueError(
                f"expected ({self.engine.spec.n_features},) or "
                f"(n, {self.engine.spec.n_features}) features, got "
                f"shape {rows.shape}")
        n = rows.shape[0]
        if n < 1 or n > self.max_batch:
            raise ValueError(
                f"request of {n} rows is not admissible (1 <= n <= "
                f"max_batch={self.max_batch}); chunk client-side or "
                "use ServeEngine.predict")
        if op not in self.engine.ops:
            raise ValueError(f"op {op!r} not served (ops: "
                             f"{self.engine.ops})")
        req = _Request(rows, op, Future(), time.monotonic(), squeeze,
                       ctx=trace_lib.current_context(),
                       t_submit_unix=time.time(),
                       tenant=None if tenant is None else str(tenant))
        with self._cond:
            if self._stopping or not self._started:
                raise RuntimeError(
                    "queue is not running (start() it, or submit "
                    "before stop())")
            if self._pending_rows + n > self.max_queue_rows:
                self._rejected += 1
                queued = self._pending_rows
                if self.telemetry is not None:
                    self.telemetry.serve_request(
                        rows=n, op=op, status="rejected",
                        tool="serve.queue", **self._attrib(req.tenant))
                    if req.tenant is not None:
                        self.telemetry.registry.counter(
                            "serve.tenant_rejected").inc()
                        self.telemetry.registry.counter(
                            f"serve.tenant_rejected.{req.tenant}").inc()
                # the overload ships with its last-seconds timeline;
                # rate-limited inside the recorder (one dump per
                # reason per window, not one per rejected request)
                flight_lib.dump_on_failure(self.telemetry,
                                           "serve_overloaded")
                raise ServeOverloaded(queued + n, self.max_queue_rows)
            self._pending.append(req)
            self._pending_rows += n
            self._pending_rows_by_op[op] = (
                self._pending_rows_by_op.get(op, 0) + n)
            self._note_depth(op)
            self._cond.notify_all()
        return req.future

    def predict(self, x, op: str = "predict", timeout: float = 30.0):
        """Blocking convenience: ``submit`` + wait, returning just the
        values array."""
        return self.submit(x, op).result(timeout=timeout).value

    @property
    def depth_rows(self) -> int:
        with self._cond:
            return self._pending_rows

    # -- the worker --------------------------------------------------------
    def _run(self) -> None:
        while True:
            group = self._next_group()
            if group is None:
                return
            self._dispatch(group)

    def _next_group(self) -> Optional[List[_Request]]:
        """Block until a batch is ready under the two-knob admission
        rule, then pop a same-op FIFO prefix of at most ``max_batch``
        rows.  Returns None when stopped and drained."""
        with self._cond:
            while True:
                if self._pending:
                    break
                if self._stopping:
                    return None
                self._cond.wait()
            # wait out the coalescing window (unless already full or
            # draining)
            deadline = self._pending[0].t_submit + self.max_wait_s
            while (not self._stopping
                   and self._pending_rows < self.max_batch):
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
                if not self._pending:
                    return self._none_or_retry()
            group: List[_Request] = []
            rows = 0
            op = self._pending[0].op
            while self._pending and self._pending[0].op == op:
                n = self._pending[0].rows.shape[0]
                if rows + n > self.max_batch:
                    break
                req = self._pending.popleft()
                self._pending_rows -= n
                self._pending_rows_by_op[op] = (
                    self._pending_rows_by_op.get(op, 0) - n)
                rows += n
                group.append(req)
            self._note_depth(op)
            return group

    def _none_or_retry(self) -> Optional[List[_Request]]:
        # the queue emptied while we coalesced (only possible on stop
        # paths); loop or exit via _run's next _next_group call
        return None if self._stopping else []

    def _request_contexts(self, group: List[_Request]):
        """One pre-allocated request-span context per request, each a
        child of its SUBMITTER's captured context (or a fresh root for
        an untraced client) — minted before dispatch so the batch span
        can parent under the first request."""
        if self.telemetry is None:
            return None
        return [trace_lib.child_of(req.ctx) for req in group]

    def _dispatch(self, group: List[_Request]) -> None:
        if not group:
            return
        op = group[0].op
        X = (group[0].rows if len(group) == 1
             else np.concatenate([r.rows for r in group], axis=0))
        batch_rows = X.shape[0]
        req_ctxs = self._request_contexts(group)
        # causal chain: request spans hang off their submitters; the
        # coalesced batch span is a SIBLING of the first request's
        # span, parented on its submitter (whose open record is
        # already durable in the caller's stream — a worker killed
        # mid-batch must truncate the tree, never orphan it); the
        # siblings link back via batch_span_id, and the engine call
        # inside inherits the batch context through the context
        # variable (same worker thread)
        batch_span = (self.telemetry.trace_span(
            "serve_batch", parent=group[0].ctx, op=op,
            batch_rows=batch_rows, requests=len(group),
            tool="serve.queue")
            if req_ctxs is not None else None)
        batch_span_id = None
        t_dispatch = time.monotonic()
        try:
            with batch_span if batch_span is not None \
                    else contextlib.nullcontext() as bctx:
                if bctx is not None:
                    batch_span_id = bctx.span_id
                values, generation, bucket = self.engine.serve_batch(
                    X, op)
                if batch_span is not None:
                    batch_span.note(generation=generation,
                                    bucket=bucket)
        except BaseException as e:  # noqa: BLE001 — forwarded to callers
            self._fail_group(group, op, e, req_ctxs, batch_span_id)
            return
        t_done = time.monotonic()
        offset = 0
        results = []
        for req in group:
            n = req.rows.shape[0]
            out = values[offset:offset + n]
            offset += n
            res = ServeResult(
                value=out[0] if req.squeeze else out,
                generation=generation, op=op, rows=n, bucket=bucket,
                batch_rows=batch_rows,
                queue_ms=(t_dispatch - req.t_submit) * 1e3,
                latency_ms=(t_done - req.t_submit) * 1e3)
            results.append((req, res))
        with self._cond:
            self._batches += 1
            for _, res in results:
                self._requests_done += 1
                self._rows_done += res.rows
                self._latencies_ms.append(res.latency_ms)
        for i, (req, res) in enumerate(results):
            if self.telemetry is not None:
                self.telemetry.serve_request(
                    rows=res.rows, op=op, status="ok",
                    bucket=res.bucket, batch_rows=res.batch_rows,
                    generation=res.generation,
                    queue_ms=round(res.queue_ms, 3),
                    latency_ms=round(res.latency_ms, 3),
                    tool="serve.queue", **self._attrib(req.tenant))
                self.telemetry.trace_point(
                    "serve_request", seconds=res.latency_ms / 1e3,
                    ctx=req_ctxs[i], t_start_unix=req.t_submit_unix,
                    rows=res.rows, op=op, bucket=res.bucket,
                    generation=res.generation,
                    batch_span_id=batch_span_id, tool="serve.queue")
            req.future.set_result(res)

    def _fail_group(self, group: List[_Request], op: str,
                    exc: BaseException, req_ctxs=None,
                    batch_span_id=None) -> None:
        with self._cond:
            self._errors += len(group)
        for i, req in enumerate(group):
            if self.telemetry is not None:
                self.telemetry.serve_request(
                    rows=req.rows.shape[0], op=op, status="error",
                    error=f"{type(exc).__name__}: {exc}",
                    tool="serve.queue", **self._attrib(req.tenant))
                if req_ctxs is not None:
                    self.telemetry.trace_point(
                        "serve_request",
                        seconds=time.monotonic() - req.t_submit,
                        ctx=req_ctxs[i],
                        t_start_unix=req.t_submit_unix, status="error",
                        rows=req.rows.shape[0], op=op,
                        error=f"{type(exc).__name__}: {exc}",
                        batch_span_id=batch_span_id,
                        tool="serve.queue")
            req.future.set_exception(exc)

    # -- stats / telemetry -------------------------------------------------
    def latency_summary(self) -> dict:
        """The serving rollup over everything completed so far — the
        ``serve_latency`` record's field set."""
        with self._cond:
            lat = sorted(self._latencies_ms)
            done = self._requests_done
            rows = self._rows_done
            rejected = self._rejected
            errors = self._errors
            depth = self._pending_rows
        window_s = max(time.monotonic() - self._t_start, 1e-9)
        summary = {
            "requests": done, "rows": rows, "rejected": rejected,
            "errors": errors, "queue_depth": depth,
            "qps": round(done / window_s, 3),
            "window_s": round(window_s, 3),
            "hot_swaps": self.engine.hot_swaps,
            "generation": self.engine.generation,
        }
        if self.replica is not None:
            summary["replica"] = self.replica
        if lat:
            summary.update(
                p50_ms=round(_percentile(lat, 0.50), 3),
                p99_ms=round(_percentile(lat, 0.99), 3),
                mean_ms=round(sum(lat) / len(lat), 3),
                max_ms=round(lat[-1], 3))
        return summary

    def recent_latencies(self) -> List[float]:
        """The most recent per-request latencies (ms), oldest first —
        the SAME bounded ring ``latency_summary()`` takes percentiles
        over, exposed so the fleet router's per-replica EWMA and the
        rollup agree on the same numbers."""
        with self._cond:
            return list(self._latencies_ms)

    def emit_latency(self) -> Optional[dict]:
        """Emit (and return) one ``serve_latency`` record with the
        current rollup; no-op without telemetry."""
        if self.telemetry is None:
            return None
        summary = self.latency_summary()
        return self.telemetry.serve_latency(tool="serve.queue",
                                            **summary)


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile of an already-sorted non-empty list."""
    idx = max(0, min(len(sorted_vals) - 1,
                     int(round(q * len(sorted_vals) + 0.5)) - 1))
    return sorted_vals[idx]
