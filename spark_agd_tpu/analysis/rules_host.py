"""Host-sync rule: device round-trips inside host iteration loops.

The fused design's whole premise (SURVEY §7) is that the optimizer loop
runs on device and the host reads scalars ONCE at the end.  A
``float()`` / ``.item()`` / ``bool()`` / ``np.asarray()`` on a device
value inside a host iteration loop silently reintroduces the per-step
round-trip the reference paid in network hops — invisible in the code,
dominant in the profile (arXiv 1612.01437's silent per-iteration
overheads).

Scope: the hot-path subsystems — ``core/``, ``parallel/``, the
resilience supervisor (its segment loop brushes against device values
every boundary), and ``serve/`` (the request path: one device sync per
batch inside the engine, never a ``float()``/``.item()`` per request in
the queue worker loop).  Host DRIVER files whose loops are host-side by
design (``core/host_agd.py``, ``core/host_lbfgs.py``) opt out with a
``disable-file`` waiver naming the reason.

Loops inside traced functions are exempt: a Python loop under a trace
unrolls at trace time — there is no per-iteration host hop to flag.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional, Sequence, Tuple

from .framework import Finding, Module, Rule, call_name, dotted_name

DEFAULT_SCOPE: Tuple[str, ...] = (
    "spark_agd_tpu/core/",
    "spark_agd_tpu/parallel/",
    "spark_agd_tpu/resilience/supervisor.py",
    # the serving request path: the micro-batch worker loop must sync
    # once per BATCH (inside serve_batch), never per request
    "spark_agd_tpu/serve/",
)

# dotted-call forms that force a device->host transfer of their argument
_TRANSFER_CALLS = frozenset({"np.asarray", "np.array", "numpy.asarray",
                             "numpy.array", "jax.device_get",
                             "device_get"})


def _contains_device_shape(expr: ast.AST) -> bool:
    """Heuristic for 'this expression plausibly reads a device value':
    it contains a call or a subscript (``loss_hist[i]``,
    ``smooth(w)[0]``).  Bare names/attributes (``warm.big_l``) are
    usually already-host scalars — flagging them drowns the signal."""
    for node in ast.walk(expr):
        if isinstance(node, (ast.Call, ast.Subscript)):
            return True
    return False


class HostSyncRule(Rule):
    name = "host-sync"
    description = ("float()/.item()/bool()/np.asarray() on device values "
                   "inside a host iteration loop reintroduces a per-step "
                   "device round-trip")

    def __init__(self, scope: Optional[Sequence[str]] = None):
        self.scope = tuple(DEFAULT_SCOPE if scope is None else scope)

    def _in_scope(self, path: str) -> bool:
        return any(path.startswith(p) or path.endswith(p)
                   for p in self.scope)

    def check(self, mod: Module) -> Iterable[Finding]:
        if not self._in_scope(mod.path):
            return
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            if mod.in_host_loop(node) is None or mod.in_traced(node):
                continue
            hit = self._classify(node)
            if hit is not None:
                yield mod.finding(
                    self.name, node,
                    f"{hit} inside a host iteration loop forces a "
                    "device->host sync every pass; hoist it out of the "
                    "loop, batch it per segment, or waive with a "
                    "justification")

    @staticmethod
    def _classify(node: ast.Call) -> Optional[str]:
        # x.item()
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "item" and not node.args:
            return ".item()"
        name = dotted_name(node.func)
        if name in _TRANSFER_CALLS:
            return f"{name}()"
        if isinstance(node.func, ast.Name) \
                and node.func.id in ("float", "bool") \
                and len(node.args) == 1 \
                and _contains_device_shape(node.args[0]):
            return f"{node.func.id}() on a computed value"
        return None
