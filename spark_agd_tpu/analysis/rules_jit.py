"""Compiled-program rules: constant capture, donation, recompile hazards.

The three hazard classes that cost real debugging time in this repo:

- **constant-capture** — an ndarray closed over by a jit-compiled
  function is baked into the program as a CONSTANT: XLA compile time
  scales with the dataset (the r4 ``compile_s: 1842.74`` full-scale
  wedge) and HBM holds a frozen copy.  The PR 5
  ``cv_validation_scores`` bug, generalized: data must ride as jit
  ARGUMENTS (``core.smooth.make_smooth_staged``'s whole reason to
  exist).  The dynamic twin — a byte budget on the constants actually
  embedded in the compiled HLO — is ``analysis.contracts``.

- **donation** — a jitted step whose first argument is the optimizer
  carry (``w``/``state``/``warm``...) without ``donate_argnums``
  makes XLA copy the carry instead of aliasing it in place; and the
  inverse bug, *using* a donated buffer after the call, is a runtime
  error on backends that honor donation.  The dynamic twin asserts the
  input-output aliasing in the real ``Compiled``.

- **recompile-hazard** — a per-iteration-varying Python scalar reaching
  a ``static_argnums`` position retraces every loop step; a ``jax.jit``
  CALL inside a host loop builds a fresh callable (fresh cache) every
  iteration.  Both turn a compile-once design into a compile-per-step
  design, silently.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .framework import (Finding, Module, Rule, call_name, dotted_name)

# last dotted segment of calls that manufacture a concrete array on the
# host — the bindings whose closure-capture by a jit entry embeds a
# program constant
ARRAY_MAKERS = frozenset({
    "asarray", "array", "zeros", "ones", "full", "arange", "linspace",
    "eye", "zeros_like", "ones_like", "full_like", "device_put",
    "replicate", "stack", "concatenate", "copy", "empty",
})

# first-parameter names that mark a jitted function as taking the
# optimizer carry / mutable state
CARRY_NAMES = frozenset({"w", "ws", "w0", "state", "warm", "carry",
                         "opt_state"})

_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


def _param_names(fn: ast.AST) -> List[str]:
    a = fn.args
    return [p.arg for p in (*a.posonlyargs, *a.args)]


def _local_bindings(fn: ast.AST) -> Set[str]:
    """Names bound anywhere inside ``fn``'s own subtree (params, assigns,
    for-targets, withitems, comprehensions, nested defs)."""
    bound: Set[str] = set(_param_names(fn))
    a = fn.args
    for p in (a.vararg, a.kwarg, *a.kwonlyargs):
        if p is not None:
            bound.add(p.arg)
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(
                    node.ctx, (ast.Store, ast.Del)):
                bound.add(node.id)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(node.name)
                bound.update(_param_names(node))
            elif isinstance(node, ast.Lambda):
                bound.update(_param_names(node))
    return bound


def _loads(fn: ast.AST) -> List[ast.Name]:
    body = fn.body if isinstance(fn.body, list) else [fn.body]
    out = []
    for stmt in body:
        for node in ast.walk(stmt):
            if isinstance(node, ast.Name) and isinstance(node.ctx,
                                                         ast.Load):
                out.append(node)
    return out


def _is_array_maker(expr: ast.AST) -> bool:
    """Does this RHS manufacture a concrete host/device array?"""
    if isinstance(expr, (ast.Tuple, ast.List)):
        return any(_is_array_maker(e) for e in expr.elts)
    if not isinstance(expr, ast.Call):
        return False
    name = call_name(expr)
    if name in ARRAY_MAKERS:
        return True
    if name == "tree_map" and expr.args:
        # jax.tree_util.tree_map(jnp.asarray, pytree)
        first = expr.args[0]
        return call_name(first) in ARRAY_MAKERS \
            or (isinstance(first, ast.Lambda)
                and _is_array_maker(first.body))
    return False


class ConstantCaptureRule(Rule):
    name = "constant-capture"
    description = ("an ndarray/jnp value closed over by a jit-compiled "
                   "function becomes an embedded program constant; pass "
                   "it as an argument instead")

    def check(self, mod: Module) -> Iterable[Finding]:
        seen: Set[Tuple[int, str, int]] = set()
        for fn in mod.jit_entry:
            local = _local_bindings(fn)
            enclosing = list(mod.enclosing_functions(fn))
            for load in _loads(fn):
                var = load.id
                if var in local:
                    continue
                binding = self._array_binding(mod, enclosing, var)
                if binding is None:
                    continue
                key = (id(fn), var, binding.lineno)
                if key in seen:
                    continue
                seen.add(key)
                fname = getattr(fn, "name", "<lambda>")
                yield mod.finding(
                    self.name, load,
                    f"jit-compiled function '{fname}' closes over "
                    f"array '{var}' (built at line {binding.lineno}) — "
                    "it will be embedded as a compiled-program "
                    "constant; thread it through as an argument")

    @staticmethod
    def _array_binding(mod: Module, enclosing: List[ast.AST],
                       var: str) -> Optional[ast.AST]:
        """The assignment that binds ``var`` to a fresh array in one of
        the ENCLOSING function scopes (module-level constants are left
        to judgement — they are usually small, deliberate tables)."""
        for scope in enclosing:
            body = scope.body if isinstance(scope.body, list) else []
            for stmt in body:
                for node in ast.walk(stmt):
                    if isinstance(node, ast.Assign) \
                            and mod.scope_of(node) is scope:
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name) \
                                    and tgt.id == var \
                                    and _is_array_maker(node.value):
                                return node
        return None


def _donate_kwargs(call: ast.Call) -> Optional[ast.keyword]:
    for kw in call.keywords:
        if kw.arg in ("donate_argnums", "donate_argnames"):
            return kw
    return None


def _unwrap_to_function(mod: Module, call: ast.Call) -> Optional[ast.AST]:
    """The underlying function node of ``jit(f)`` / ``jit(vmap(f))`` /
    ``jit(lambda ...)``, resolved in-module; None when not resolvable."""
    if not call.args:
        return None
    arg = call.args[0]
    from .framework import TRACE_WRAPPERS

    while isinstance(arg, ast.Call) and call_name(arg) in TRACE_WRAPPERS:
        if not arg.args:
            return None
        arg = arg.args[0]
    if isinstance(arg, ast.Lambda):
        return arg
    if isinstance(arg, ast.Name):
        return mod._functions_named(mod.scope_of(call), arg.id)
    return None


class DonationRule(Rule):
    name = "donation"
    description = ("jit call sites taking carry-shaped state should "
                   "donate the carry buffer; a donated buffer must not "
                   "be used after the call")

    def check(self, mod: Module) -> Iterable[Finding]:
        # name -> [(scope of the assignment, donated indices)]; the
        # reuse pass only honors a binding whose scope lexically
        # ENCLOSES the call site — `step = jit(f, donate_argnums=0)` in
        # one factory must not taint an unrelated `step` in another
        donated_fns: Dict[str, List[Tuple[ast.AST, Set[int]]]] = {}
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and call_name(node) in ("jit", "pjit")):
                continue
            kw = _donate_kwargs(node)
            if kw is None:
                fn = _unwrap_to_function(mod, node)
                if fn is None:
                    continue
                params = _param_names(fn)
                if params and params[0] in CARRY_NAMES:
                    fname = getattr(fn, "name", "<lambda>")
                    yield mod.finding(
                        self.name, node,
                        f"jit of '{fname}' takes carry-shaped first "
                        f"argument '{params[0]}' without donate_argnums"
                        " — the carry buffer is copied instead of "
                        "aliased in place; add donate_argnums=0 (and "
                        "never reuse the input after the call) or "
                        "waive with a justification")
            else:
                idxs = self._donated_indices(kw)
                tgt = self._assigned_name(mod, node)
                if tgt is not None and idxs:
                    donated_fns.setdefault(tgt, []).append(
                        (mod.scope_of(node), idxs))
        yield from self._check_reuse(mod, donated_fns)

    @staticmethod
    def _donated_indices(kw: ast.keyword) -> Set[int]:
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List)):
            return {e.value for e in v.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, int)}
        return set()

    @staticmethod
    def _assigned_name(mod: Module, call: ast.Call) -> Optional[str]:
        parent = mod.parent.get(call)
        if isinstance(parent, ast.Assign) and len(parent.targets) == 1 \
                and isinstance(parent.targets[0], ast.Name):
            return parent.targets[0].id
        return None

    def _check_reuse(self, mod: Module,
                     donated: Dict[str, List[Tuple[ast.AST, Set[int]]]]
                     ) -> Iterable[Finding]:
        if not donated:
            return
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in donated):
                continue
            scope = mod.scope_of(node)
            visible = [scope, *mod.enclosing_functions(node), mod.tree]
            idxs: Set[int] = set()
            for bind_scope, bind_idxs in donated[node.func.id]:
                if any(s is bind_scope for s in visible):
                    idxs |= bind_idxs
            if not idxs:
                continue
            # `w = g(w)` rebinds the name to the OUTPUT — later loads of
            # it are the fresh buffer, not the donated one
            parent = mod.parent.get(node)
            rebound: Set[str] = set()
            if isinstance(parent, ast.Assign):
                for tgt in parent.targets:
                    rebound |= {n.id for n in ast.walk(tgt)
                                if isinstance(n, ast.Name)}
            for i in idxs:
                if i >= len(node.args):
                    continue
                arg = node.args[i]
                if not isinstance(arg, ast.Name) or arg.id in rebound:
                    continue
                for later in ast.walk(scope):
                    if isinstance(later, ast.Name) \
                            and isinstance(later.ctx, ast.Load) \
                            and later.id == arg.id \
                            and later.lineno > node.lineno:
                        yield mod.finding(
                            self.name, later,
                            f"'{arg.id}' was donated to "
                            f"'{node.func.id}' at line {node.lineno} "
                            "and is used again afterwards — the buffer "
                            "is invalidated on backends that honor "
                            "donation")
                        break


class RecompileHazardRule(Rule):
    name = "recompile-hazard"
    description = ("a loop-varying Python value reaching static_argnums "
                   "(or a jax.jit call inside a host loop) retraces the "
                   "program every iteration")

    def check(self, mod: Module) -> Iterable[Finding]:
        # (a) jax.jit(...) constructed INSIDE a host loop — a fresh
        # callable (fresh compile cache) per iteration
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and call_name(node) in ("jit", "pjit") \
                    and mod.in_host_loop(node) is not None:
                yield mod.finding(
                    self.name, node,
                    "jax.jit called inside a host loop builds a fresh "
                    "callable (and compiles) every iteration; hoist "
                    "the jit out of the loop")
        # (b) call sites passing the loop variable into a static
        # position of a jit-with-static-argnums function
        static_fns: Dict[str, Set[int]] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call) \
                    and call_name(node) in ("jit", "pjit"):
                for kw in node.keywords:
                    if kw.arg == "static_argnums":
                        idxs = DonationRule._donated_indices(kw)
                        tgt = DonationRule._assigned_name(mod, node)
                        if tgt and idxs:
                            static_fns.setdefault(tgt, set()).update(idxs)
        if not static_fns:
            return
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Name)
                    and node.func.id in static_fns):
                continue
            loop = mod.in_host_loop(node)
            if loop is None or not isinstance(loop, ast.For):
                continue
            loop_vars = {n.id for n in ast.walk(loop.target)
                         if isinstance(n, ast.Name)}
            for i in static_fns[node.func.id]:
                if i < len(node.args) \
                        and isinstance(node.args[i], ast.Name) \
                        and node.args[i].id in loop_vars:
                    yield mod.finding(
                        self.name, node,
                        f"loop variable '{node.args[i].id}' reaches "
                        f"static_argnums position {i} of "
                        f"'{node.func.id}' — every iteration is a "
                        "fresh trace+compile; make the argument traced "
                        "or hoist distinct values out of the loop")
