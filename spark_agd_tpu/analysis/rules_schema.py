"""Schema-drift rule: telemetry emit sites vs the canonical record schema.

``obs/schema.py`` is the one contract every telemetry producer and
consumer meet at.  Drift between an emit site and the schema (a typo'd
kind, a missing required field) is invisible until a consumer chokes on
the JSONL — long after the run that wrote it is gone.  This rule checks
the static half at lint time:

- ``schema.<kind>_record(...)`` calls (and names imported from the
  schema module) must name a registered kind;
- hand-built record dict literals carrying ``schema_version`` must use
  a registered ``kind``;
- ``Telemetry`` helper call sites must pass the helper's required
  fields (skipped when the site forwards ``**kwargs`` — the supervisor
  ledger pattern);
- project-wide: every kind in ``KINDS`` must have a selfcheck example
  (``EXAMPLE_<KIND>_RECORD``) and a matching ``Telemetry`` helper, and
  every mapped helper must exist — the drift class PR 5's chaos kinds
  were added against by hand.

The schema itself is imported (stdlib-only module) straight from its
file, so the rule validates against the REAL registered kinds, not a
parallel list that could itself drift.
"""

from __future__ import annotations

import ast
import importlib.util
import os
from typing import Dict, Iterable, Optional, Sequence, Set, Tuple

from .framework import Finding, Module, Rule, dotted_name

# kind -> Telemetry helper name, where they differ from the kind itself
KIND_TO_HELPER: Dict[str, str] = {
    "run": "run_summary",
    "iteration": "iteration_callback",
    "span": "span",
    "metrics": "metrics_snapshot",
}

# Telemetry helper -> (names of leading positional params, required
# keyword fields the SITE must supply — auto-filled fields like run_id
# and heartbeat's process are absent)
HELPER_SIGNATURES: Dict[str, Tuple[Tuple[str, ...], frozenset]] = {
    "run_summary": ((), frozenset({"tool"})),
    "iteration_callback": ((), frozenset()),
    "span": (("name",), frozenset()),
    "metrics_snapshot": ((), frozenset()),
    "program_cost": (("cost",), frozenset()),
    "numerics_failure": (("message",), frozenset()),
    "attempt": ((), frozenset({"attempt", "outcome"})),
    "recovery": ((), frozenset({"action"})),
    "heartbeat": ((), frozenset()),
    "chaos": ((), frozenset({"fault"})),
    "journal_replay": ((), frozenset({"records"})),
    "degraded": ((), frozenset({"surviving"})),
    "contract_pin": ((), frozenset({"contract", "ok"})),
    "serve_request": ((), frozenset({"rows"})),
    "serve_latency": ((), frozenset({"requests"})),
    # the causal-tracing helpers (obs.trace / obs.timeline): a span
    # context manager, a pre-measured closed span, and the per-trace
    # analysis rollup
    "trace_span": (("name",), frozenset()),
    "trace_point": (("name",), frozenset({"seconds"})),
    "trace_summary": ((), frozenset({"trace_id", "spans"})),
    # one weak-scaling ladder (obs.scaling / benchmarks.run.run_ladder)
    "scaling_curve": ((), frozenset({"name", "points"})),
    # the straggler scheduler (resilience.scheduler): one skew sync and
    # one applied generation-boundary rebalance
    "skew_estimate": ((), frozenset({"skew"})),
    "rebalance": ((), frozenset({"at_iter"})),
    # the continuous-learning pipeline (pipeline.canary /
    # pipeline.promote): one shadow-served canary evaluation and one
    # typed promotion decision
    "canary": ((), frozenset({"generation", "verdict"})),
    "promotion": ((), frozenset({"decision"})),
    # the serve fleet router (serve.router): one routing decision and
    # one replica-health classification change
    "fleet_route": ((), frozenset({"decision"})),
    "replica_verdict": ((), frozenset({"replica", "verdict"})),
    # the streaming data plane (data.streaming): one poisoned-shard
    # quarantine decision and one completed streamed pass
    "shard_quarantine": ((), frozenset({"shard"})),
    "stream_epoch": ((), frozenset({"epoch", "batches"})),
}


def _load_schema_module(path: Optional[str] = None):
    """Import ``obs/schema.py`` standalone from its file (it is stdlib-
    only by contract, so this never drags in jax)."""
    if path is None:
        path = os.path.join(os.path.dirname(__file__), os.pardir,
                            "obs", "schema.py")
    path = os.path.abspath(path)
    spec = importlib.util.spec_from_file_location("_graftlint_schema",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class SchemaDriftRule(Rule):
    name = "schema-drift"
    description = ("telemetry emit sites must agree with obs/schema.py: "
                   "registered kinds, required fields, and full "
                   "example/helper coverage")

    def __init__(self, schema_file: Optional[str] = None,
                 kinds: Optional[Sequence[str]] = None):
        self._schema_file = schema_file
        self._kinds: Optional[Tuple[str, ...]] = \
            tuple(kinds) if kinds is not None else None
        self._schema_mod = None

    @property
    def kinds(self) -> Tuple[str, ...]:
        if self._kinds is None:
            self._kinds = tuple(self.schema_module.KINDS)
        return self._kinds

    @property
    def schema_module(self):
        if self._schema_mod is None:
            self._schema_mod = _load_schema_module(self._schema_file)
        return self._schema_mod

    # -- per-file ---------------------------------------------------------
    def check(self, mod: Module) -> Iterable[Finding]:
        schema_names = self._schema_imports(mod)
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                yield from self._check_record_call(mod, node,
                                                  schema_names)
                yield from self._check_helper_call(mod, node)
            elif isinstance(node, ast.Dict):
                yield from self._check_record_literal(mod, node)

    @staticmethod
    def _schema_imports(mod: Module) -> Set[str]:
        """Names imported FROM a schema module (``from ..obs.schema
        import chaos_record``) — the bare-name emit sites to check."""
        names: Set[str] = set()
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom) and node.module \
                    and node.module.split(".")[-1] == "schema":
                names.update(a.asname or a.name for a in node.names)
        return names

    def _check_record_call(self, mod: Module, node: ast.Call,
                           schema_names: Set[str]) -> Iterable[Finding]:
        name = dotted_name(node.func)
        if name is None or not name.endswith("_record"):
            return
        parts = name.split(".")
        bare = parts[-1]
        if len(parts) > 1 and parts[-2] != "schema":
            return
        if len(parts) == 1 and bare not in schema_names:
            return
        kind = bare[:-len("_record")]
        if kind in self.kinds:
            return
        if hasattr(self.schema_module, bare):
            # a real non-constructor helper (validate_record, ...)
            return
        yield mod.finding(
                self.name, node,
                f"'{bare}' is not a constructor in obs.schema and "
                f"'{kind}' is not a registered kind "
                f"{tuple(self.kinds)} — typo'd kind or unregistered "
                "record family")

    def _check_helper_call(self, mod: Module, node: ast.Call
                           ) -> Iterable[Finding]:
        if not isinstance(node.func, ast.Attribute):
            return
        helper = node.func.attr
        sig = HELPER_SIGNATURES.get(helper)
        if sig is None:
            return
        recv = dotted_name(node.func.value)
        last = (recv or "").split(".")[-1].lower()
        if "tel" not in last:
            return
        if any(kw.arg is None for kw in node.keywords):
            return  # **kwargs forwarding — not statically checkable
        pos_names, required = sig
        given = {kw.arg for kw in node.keywords}
        given.update(pos_names[:len(node.args)])
        missing = sorted((required | set(pos_names)) - given)
        if missing:
            yield mod.finding(
                self.name, node,
                f"Telemetry.{helper}() call is missing required "
                f"field(s) {missing} — the emitted record would fail "
                "schema validation")

    def _check_record_literal(self, mod: Module, node: ast.Dict
                              ) -> Iterable[Finding]:
        keys = {k.value: v for k, v in zip(node.keys, node.values)
                if isinstance(k, ast.Constant)}
        if "schema_version" not in keys or "kind" not in keys:
            return
        kv = keys["kind"]
        if isinstance(kv, ast.Constant) and isinstance(kv.value, str) \
                and kv.value not in self.kinds:
            yield mod.finding(
                self.name, kv,
                f"hand-built record uses kind '{kv.value}', which is "
                "not registered in obs.schema.KINDS")

    # -- project-wide coverage -------------------------------------------
    def check_project(self, mods: Sequence[Module]) -> Iterable[Finding]:
        schema_mod = next((m for m in mods
                           if m.path.endswith("obs/schema.py")), None)
        tel_mod = next((m for m in mods
                        if m.path.endswith("obs/telemetry.py")), None)
        if schema_mod is not None:
            real = self.schema_module
            for kind in self.kinds:
                attr = f"EXAMPLE_{kind.upper()}_RECORD"
                if not hasattr(real, attr):
                    yield Finding(
                        self.name, schema_mod.path, 1, 0,
                        f"kind '{kind}' has no selfcheck example "
                        f"({attr}) — every kind must round-trip "
                        "through selfcheck")
            examples = getattr(real, "EXAMPLES", None)
            if isinstance(examples, dict):
                for kind in self.kinds:
                    if kind not in examples:
                        yield Finding(
                            self.name, schema_mod.path, 1, 0,
                            f"kind '{kind}' missing from the EXAMPLES "
                            "table selfcheck iterates")
        if tel_mod is not None:
            methods = self._telemetry_methods(tel_mod)
            if methods:
                for kind in self.kinds:
                    helper = KIND_TO_HELPER.get(kind, kind)
                    if helper not in methods:
                        yield Finding(
                            self.name, tel_mod.path, 1, 0,
                            f"kind '{kind}' has no Telemetry helper "
                            f"(expected a '{helper}' method)")

    @staticmethod
    def _telemetry_methods(mod: Module) -> Set[str]:
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ClassDef) and node.name == "Telemetry":
                return {n.name for n in node.body
                        if isinstance(n, (ast.FunctionDef,
                                          ast.AsyncFunctionDef))}
        return set()
