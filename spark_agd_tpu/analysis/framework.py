"""graftlint core: the AST lint framework the JAX-aware rules plug into.

PR 5 fixed three compiled-program hazards by hand-review (an ndarray
embedded as a program constant in ``models/evaluation.py``, misattributed
bench phases); the ROADMAP serving/inner-loop items will multiply the
number of jitted programs in the tree.  This package turns those hazard
classes into *analysis*: stdlib-``ast`` rules that understand the repo's
JAX idioms — which functions are traced, what a carry looks like, what
the telemetry schema requires — plus dynamic contract pins
(``analysis.contracts``) that verify the riskiest static claims against
the real XLA program.

This module is the rule-agnostic core:

- :class:`Finding` — one diagnostic, stable across runs;
- :class:`Module` — one parsed file with the shared semantic facts every
  rule needs (parent links, scope map, the **traced-function set**: the
  functions whose bodies execute under ``jax.jit``/``vmap``/
  ``lax.while_loop``/... tracing);
- waivers — ``# graftlint: disable=<rule>[,<rule>...] -- reason`` on the
  flagged line (or a standalone comment on the line above), and
  ``# graftlint: disable-file=<rule>`` anywhere in the first 40 lines
  for whole-file opt-outs (host-driver files);
- baseline — a JSON file grandfathering *intended* findings by
  ``(rule, path, source line)`` so a newly added rule can land before
  the tree is fully clean.  The shipped tree keeps it empty.

Deliberately dependency-free (stdlib only): the lint gate must run in
CI without touching a JAX backend.  Only ``analysis.contracts`` (the
dynamic half) imports jax, and only when invoked.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple

# ---------------------------------------------------------------------------
# findings

@dataclasses.dataclass(frozen=True)
class Finding:
    """One diagnostic: ``rule`` names the check, ``path`` is repo-relative
    (posix separators), ``snippet`` is the stripped source line — the
    stable identity baselines match on (line numbers drift, code lines
    rarely do)."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    snippet: str = ""

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: " \
               f"{self.message}"

    def baseline_key(self) -> Tuple[str, str, str]:
        return (self.rule, self.path, self.snippet)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


# ---------------------------------------------------------------------------
# trace-awareness: which functions run under a JAX trace?

# call/decorator names that trace their function argument(s).  Matched on
# the LAST attribute segment, so ``jax.jit``, ``jax.lax.cond``, and bare
# ``jit`` all resolve.
TRACE_WRAPPERS = frozenset({
    "jit", "pjit", "vmap", "pmap", "grad", "value_and_grad",
    "jacfwd", "jacrev", "hessian", "checkpoint", "remat",
    "while_loop", "fori_loop", "cond", "scan", "switch",
    "associative_scan", "shard_map", "pallas_call", "custom_jvp",
    "custom_vjp", "linearize", "vjp", "jvp",
})

# the COMPILATION entry points among the wrappers: a concrete array
# closed over by one of these becomes an embedded program constant.
# (while_loop/cond/scan bodies, by contrast, are only callable during an
# enclosing trace — their closures are tracers, which is idiomatic.)
JIT_ENTRY_WRAPPERS = frozenset({"jit", "pjit", "pmap", "pallas_call"})


def call_name(node: ast.AST) -> Optional[str]:
    """The last dotted segment of a call target / decorator expression
    (``jax.jit`` -> ``jit``); ``None`` when it isn't a name shape."""
    if isinstance(node, ast.Call):
        node = node.func
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def dotted_name(node: ast.AST) -> Optional[str]:
    """Full dotted form of a Name/Attribute chain (``np.asarray``), or
    ``None`` for anything more dynamic."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_SCOPE_NODES = _FUNC_NODES + (ast.Module,)

_WAIVER_RE = re.compile(r"#\s*graftlint:\s*disable=([\w, -]+?)(?:--|$)")
_FILE_WAIVER_RE = re.compile(
    r"#\s*graftlint:\s*disable-file=([\w, -]+?)(?:--|$)")


class Module:
    """One parsed source file plus the semantic facts rules share.

    ``traced``: the set of function nodes (def or lambda) whose BODIES
    execute under a JAX trace — decorated with / passed to a
    :data:`TRACE_WRAPPERS` call (through nested wrapper calls like
    ``jit(vmap(f))``), resolved by name within the enclosing lexical
    scopes, plus everything lexically nested inside such a function.
    """

    def __init__(self, path: str, source: str,
                 tree: Optional[ast.Module] = None):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree if tree is not None else ast.parse(source)
        self.parent: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(self.tree):
            for child in ast.iter_child_nodes(node):
                self.parent[child] = node
        self.traced: Set[ast.AST] = set()
        # functions that are jit/pjit/pmap COMPILATION roots (directly
        # wrapped, possibly through vmap/grad chains) — the scope whose
        # closed-over concrete arrays become embedded program constants
        self.jit_entry: Set[ast.AST] = set()
        self._compute_traced()
        self._line_waivers: Dict[int, Set[str]] = {}
        self.file_waivers: Set[str] = set()
        self._collect_waivers()

    # -- scopes -----------------------------------------------------------
    def scope_of(self, node: ast.AST) -> ast.AST:
        """Nearest enclosing function (or the module) that OWNS ``node``
        — for a function node, the scope it is defined in, not itself."""
        cur = self.parent.get(node)
        while cur is not None and not isinstance(cur, _SCOPE_NODES):
            cur = self.parent.get(cur)
        return cur if cur is not None else self.tree

    def enclosing_functions(self, node: ast.AST) -> Iterator[ast.AST]:
        """Function nodes containing ``node``, innermost first."""
        cur = self.parent.get(node)
        while cur is not None:
            if isinstance(cur, _FUNC_NODES):
                yield cur
            cur = self.parent.get(cur)

    def in_traced(self, node: ast.AST) -> bool:
        """Whether ``node`` executes under a JAX trace (it sits inside a
        traced function's body)."""
        if isinstance(node, _FUNC_NODES) and node in self.traced:
            return True
        return any(f in self.traced
                   for f in self.enclosing_functions(node))

    def in_host_loop(self, node: ast.AST) -> Optional[ast.AST]:
        """The innermost HOST ``for``/``while`` loop containing ``node``
        (``None`` when there is none, or when the loop itself is traced
        code — a Python loop inside a jitted function unrolls at trace
        time; the host-sync rules target host iteration loops)."""
        cur = self.parent.get(node)
        while cur is not None:
            if isinstance(cur, _FUNC_NODES):
                return None  # left the loop's statement nesting
            if isinstance(cur, (ast.For, ast.While)):
                if self.in_traced(cur):
                    return None
                return cur
            cur = self.parent.get(cur)
        return None

    # -- traced-function discovery ---------------------------------------
    def _functions_named(self, scope_start: ast.AST, name: str
                         ) -> Optional[ast.AST]:
        """Resolve ``name`` to a FunctionDef visible from ``scope_start``
        by walking outward through enclosing scopes."""
        scopes = [scope_start, *self.enclosing_functions(scope_start),
                  self.tree]
        for scope in scopes:
            body = getattr(scope, "body", None)
            if not isinstance(body, list):
                continue
            for stmt in ast.walk(scope):
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) \
                        and stmt.name == name \
                        and self.scope_of(stmt) is scope:
                    return stmt
        return None

    def _mark_traced_arg(self, arg: ast.AST, at: ast.AST,
                         entry: bool = False) -> None:
        if isinstance(arg, ast.Lambda):
            self.traced.add(arg)
            if entry:
                self.jit_entry.add(arg)
        elif isinstance(arg, ast.Call) and call_name(arg) in TRACE_WRAPPERS:
            for inner in arg.args:
                self._mark_traced_arg(inner, at, entry=entry)
        elif isinstance(arg, ast.Name):
            fn = self._functions_named(self.scope_of(at), arg.id)
            if fn is not None:
                self.traced.add(fn)
                if entry:
                    self.jit_entry.add(fn)

    def _compute_traced(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call) \
                    and call_name(node) in TRACE_WRAPPERS:
                entry = call_name(node) in JIT_ENTRY_WRAPPERS
                for arg in node.args:
                    self._mark_traced_arg(arg, node, entry=entry)
                for kw in node.keywords:
                    # lax.while_loop(cond_fun=..., body_fun=...) style
                    if kw.arg in ("cond_fun", "body_fun", "f", "fun",
                                  "body", "kernel"):
                        self._mark_traced_arg(kw.value, node, entry=entry)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    name = call_name(dec)
                    wrapped = None
                    if name in TRACE_WRAPPERS:
                        wrapped = name
                    elif name == "partial" and isinstance(dec, ast.Call) \
                            and dec.args:
                        # @functools.partial(jax.jit, static_argnums=...)
                        inner = call_name(dec.args[0])
                        if inner in TRACE_WRAPPERS:
                            wrapped = inner
                    if wrapped is not None:
                        self.traced.add(node)
                        if wrapped in JIT_ENTRY_WRAPPERS:
                            self.jit_entry.add(node)
        # transitive closure: functions defined lexically inside a traced
        # function execute at trace time too
        changed = True
        while changed:
            changed = False
            for node in ast.walk(self.tree):
                if isinstance(node, _FUNC_NODES) \
                        and node not in self.traced \
                        and any(f in self.traced
                                for f in self.enclosing_functions(node)):
                    self.traced.add(node)
                    changed = True

    # -- waivers ----------------------------------------------------------
    @staticmethod
    def _parse_rules(spec: str) -> Set[str]:
        return {r.strip() for r in spec.split(",") if r.strip()}

    def _collect_waivers(self) -> None:
        for i, text in enumerate(self.lines, 1):
            m = _FILE_WAIVER_RE.search(text)
            if m and i <= 40:
                self.file_waivers |= self._parse_rules(m.group(1))
                continue
            m = _WAIVER_RE.search(text)
            if m:
                rules = self._parse_rules(m.group(1))
                self._line_waivers.setdefault(i, set()).update(rules)
                if text.lstrip().startswith("#"):
                    # standalone waiver comment applies to the first
                    # CODE line below it (a justification may span
                    # several comment lines)
                    j = i + 1
                    while j <= len(self.lines) \
                            and self.lines[j - 1].lstrip().startswith("#"):
                        j += 1
                    self._line_waivers.setdefault(j, set()) \
                        .update(rules)

    def waived(self, rule: str, line: int) -> bool:
        for rules in (self.file_waivers,
                      self._line_waivers.get(line, ()),
                      self._line_waivers.get(line - 1, ())):
            if rule in rules or "all" in rules:
                return True
        return False

    # -- finding construction ---------------------------------------------
    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        return Finding(rule=rule, path=self.path, line=line, col=col,
                       message=message, snippet=self.snippet(line))


# ---------------------------------------------------------------------------
# rules

class Rule:
    """One lint check.  ``check`` runs per file; ``check_project`` runs
    once over the whole parsed set (cross-file consistency — the
    schema-drift rule)."""

    name: str = "rule"
    description: str = ""

    def check(self, mod: Module) -> Iterable[Finding]:
        return ()

    def check_project(self, mods: Sequence[Module]) -> Iterable[Finding]:
        return ()


# ---------------------------------------------------------------------------
# running

_SKIP_DIRS = {"__pycache__", ".git", ".claude", "node_modules",
              ".pytest_cache", "build", "dist"}


def iter_py_files(paths: Sequence[str]) -> Iterator[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for root, dirs, names in os.walk(p):
                dirs[:] = sorted(d for d in dirs if d not in _SKIP_DIRS)
                for n in sorted(names):
                    if n.endswith(".py"):
                        yield os.path.join(root, n)


def rel_path(path: str, root: Optional[str]) -> str:
    if root:
        try:
            path = os.path.relpath(path, root)
        except ValueError:  # different drive (windows) — keep absolute
            pass
    return path.replace(os.sep, "/")


def parse_file(path: str, root: Optional[str] = None
               ) -> Tuple[Optional[Module], Optional[Finding]]:
    """(module, None) on success; (None, syntax-error finding) on a file
    that does not parse — a non-parsing file is itself a finding, never
    a crash of the gate."""
    rel = rel_path(path, root)
    try:
        with open(path, encoding="utf-8") as f:
            source = f.read()
    except OSError as e:
        return None, Finding("parse-error", rel, 1, 0,
                             f"cannot read file: {e}")
    try:
        return Module(rel, source), None
    except SyntaxError as e:
        return None, Finding("parse-error", rel, e.lineno or 1, 0,
                             f"syntax error: {e.msg}")


def lint_modules(mods: Sequence[Module], rules: Sequence[Rule]
                 ) -> List[Finding]:
    """Run every rule over every parsed module (plus the project-level
    passes), apply waivers, and return findings sorted by location."""
    by_path = {m.path: m for m in mods}
    findings: List[Finding] = []
    for rule in rules:
        for mod in mods:
            findings.extend(rule.check(mod))
        findings.extend(rule.check_project(mods))
    kept = []
    for f in findings:
        mod = by_path.get(f.path)
        if mod is not None and mod.waived(f.rule, f.line):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return kept


def lint_paths(paths: Sequence[str], rules: Sequence[Rule],
               root: Optional[str] = None
               ) -> Tuple[List[Finding], int]:
    """Lint every ``.py`` under ``paths``; returns ``(findings,
    n_files)``.  ``root`` relativizes reported paths (default: CWD)."""
    root = root if root is not None else os.getcwd()
    mods: List[Module] = []
    findings: List[Finding] = []
    n = 0
    for path in iter_py_files(paths):
        n += 1
        mod, err = parse_file(path, root)
        if err is not None:
            findings.append(err)
        if mod is not None:
            mods.append(mod)
    findings.extend(lint_modules(mods, rules))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings, n


def lint_source(source: str, rules: Sequence[Rule],
                path: str = "<string>") -> List[Finding]:
    """Lint one in-memory source string (the test-fixture entry point)."""
    return lint_modules([Module(path, source)], rules)


# ---------------------------------------------------------------------------
# baseline

BASELINE_VERSION = 1


def load_baseline(path: str) -> List[dict]:
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    if not isinstance(data, dict) or "findings" not in data:
        raise ValueError(
            f"{path}: not a graftlint baseline (expected an object with "
            "a 'findings' list)")
    return list(data["findings"])


def save_baseline(path: str, findings: Sequence[Finding]) -> None:
    entries = [{"rule": f.rule, "path": f.path, "snippet": f.snippet,
                "message": f.message} for f in findings]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": BASELINE_VERSION, "findings": entries},
                  f, indent=2, sort_keys=True)
        f.write("\n")


def apply_baseline(findings: Sequence[Finding], baseline: Sequence[dict]
                   ) -> Tuple[List[Finding], int]:
    """Drop findings grandfathered by the baseline — multiset match on
    ``(rule, path, snippet)``, so a moved line stays waived but a NEW
    occurrence of the same pattern is reported.  Returns ``(kept,
    n_matched)``."""
    budget: Dict[Tuple[str, str, str], int] = {}
    for e in baseline:
        key = (e.get("rule", ""), e.get("path", ""), e.get("snippet", ""))
        budget[key] = budget.get(key, 0) + 1
    kept: List[Finding] = []
    matched = 0
    for f in findings:
        key = f.baseline_key()
        if budget.get(key, 0) > 0:
            budget[key] -= 1
            matched += 1
            continue
        kept.append(f)
    return kept, matched
