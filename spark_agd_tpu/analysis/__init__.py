"""graftlint: JAX-aware static analysis + compiled-program contract pins.

The static half (stdlib-only, no backend) is an AST lint framework with
repo-specific rules for the hazard classes PR 5 fixed by hand-review:

- ``constant-capture`` — arrays closed over by jit-compiled functions
  (embedded program constants);
- ``host-sync`` — ``float()``/``.item()``/``bool()``/``np.asarray()``
  on device values inside host iteration loops in the hot-path
  subsystems;
- ``donation`` — carry-shaped jit arguments without ``donate_argnums``,
  and reuse of a donated buffer after the call;
- ``recompile-hazard`` — loop-varying values reaching static argnums, or
  ``jax.jit`` called inside a host loop;
- ``np-jnp-mix`` / ``f64-literal`` — numpy ops and f64 dtypes in traced
  code;
- ``schema-drift`` — telemetry emit sites vs ``obs/schema.py``.

The dynamic half (``analysis.contracts``) verifies the riskiest static
claims against the real XLA program: an embedded-constant byte budget,
donation honored in the input-output aliasing, and a collective census
matching the checked-in ``pins.json``.

CLI: ``python tools/graft_lint.py [paths...]`` — exit 0/1, text+JSON,
``# graftlint: disable=<rule>`` inline waivers, baseline grandfathering.
See ``docs/STATIC_ANALYSIS.md``.
"""

from . import contracts
from .framework import (Finding, Module, Rule, apply_baseline,
                        lint_modules, lint_paths, lint_source,
                        load_baseline, save_baseline)
from .rules_host import HostSyncRule
from .rules_jit import (ConstantCaptureRule, DonationRule,
                        RecompileHazardRule)
from .rules_numeric import F64LiteralRule, NpJnpMixRule
from .rules_schema import SchemaDriftRule


def default_rules():
    """One fresh instance of every shipped rule (fresh because rules may
    carry per-run caches, e.g. the schema module)."""
    return [
        ConstantCaptureRule(),
        HostSyncRule(),
        DonationRule(),
        RecompileHazardRule(),
        NpJnpMixRule(),
        F64LiteralRule(),
        SchemaDriftRule(),
    ]


RULE_NAMES = tuple(r.name for r in default_rules())

__all__ = [
    "Finding", "Module", "Rule", "apply_baseline", "contracts",
    "default_rules", "lint_modules", "lint_paths", "lint_source",
    "load_baseline", "save_baseline", "RULE_NAMES",
    "ConstantCaptureRule", "HostSyncRule", "DonationRule",
    "RecompileHazardRule", "NpJnpMixRule", "F64LiteralRule",
    "SchemaDriftRule",
]
