"""Dynamic contract pins: verify the lint's riskiest claims against the
REAL compiled program.

Static analysis argues about source text; XLA argues back.  The two
claims graftlint makes that are worth real money — "no dataset rides the
program as an embedded constant" and "the carry is donated" — are
verified here against the ``jax.stages.Compiled`` the public runners
actually execute (via their ``lower_step`` AOT hooks and
``obs.introspect``), plus a third pin the ROADMAP inner-loop work
depends on: the per-program **collective census** must match a
checked-in pin file, so a PR that silently adds an all-reduce to the
hot loop fails the gate before any TPU time is spent.

Checked-in pins live in ``analysis/pins.json``: per program label, the
expected collective census, a byte budget for embedded constants, and
whether donation must be honored in the input-output aliasing.

Violations serialize as the ``contract_pin`` record kind of
``obs.schema`` so run-record JSONLs carry them next to the metrics and
``tools/agd_report.py`` can surface them.

Unlike the rest of ``analysis`` this module imports jax (lazily, inside
the entry points) — it is the opt-in dynamic half
(``tools/graft_lint.py --contracts``); the static gate stays
backend-free.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_PINS_PATH = os.path.join(os.path.dirname(__file__), "pins.json")

# bytes per element for the HLO shape prefixes XLA emits
_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8,
    "c128": 16,
}

# `  %x = f32[128,64]{1,0} constant({...})` — the embedded-literal form;
# scalars print as f32[] (empty dims -> product 1)
_CONST_RE = re.compile(
    r"=\s*([a-z][a-z0-9]*)\[([\d,]*)\][^ ]*\s+constant\(")

# a 1 MiB ceiling: orders of magnitude above the scalar/iota constants a
# staged program legitimately embeds, orders below any real dataset
DEFAULT_CONSTANT_BUDGET = 1 << 20


@dataclasses.dataclass(frozen=True)
class ContractViolation:
    """One failed pin; ``contract`` is ``constant-bytes`` / ``donation``
    / ``collective-census`` / ``collective-bytes``."""

    contract: str
    label: str
    message: str
    observed: Any = None
    expected: Any = None

    def format(self) -> str:
        return f"[{self.contract}] {self.label}: {self.message}"


def embedded_constant_bytes(hlo_text: str) -> int:
    """Total bytes of array literals embedded in optimized-HLO text —
    the quantity the constant-capture rule bounds.  Unknown dtype
    prefixes count 4 bytes/element (conservative, never zero)."""
    total = 0
    for dtype, dims in _CONST_RE.findall(hlo_text):
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * _DTYPE_BYTES.get(dtype, 4)
    return total


def donation_honored(hlo_text: str) -> bool:
    """Whether the compiled program aliases any input to an output —
    what ``donate_argnums`` becomes when XLA honors it."""
    return "input_output_alias" in hlo_text


# ---------------------------------------------------------------------------
# pins

def load_pins(path: Optional[str] = None) -> Dict[str, dict]:
    """The checked-in pin table: ``label -> {"collectives": {...},
    "max_constant_bytes": int, "donation": bool}``."""
    path = path or DEFAULT_PINS_PATH
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    pins = data.get("pins")
    if not isinstance(pins, dict):
        raise ValueError(f"{path}: expected an object with a 'pins' map")
    return pins


def check_constant_budget(hlo_text: str, label: str,
                          budget_bytes: int = DEFAULT_CONSTANT_BUDGET
                          ) -> List[ContractViolation]:
    observed = embedded_constant_bytes(hlo_text)
    if observed > budget_bytes:
        return [ContractViolation(
            "constant-bytes", label,
            f"{observed} bytes of array literals embedded in the "
            f"compiled program (budget {budget_bytes}) — some array "
            "is riding as a closure constant instead of an argument",
            observed=observed, expected=budget_bytes)]
    return []


def check_donation(hlo_text: str, label: str, expect: bool = True
                   ) -> List[ContractViolation]:
    honored = donation_honored(hlo_text)
    if expect and not honored:
        return [ContractViolation(
            "donation", label,
            "no input-output aliasing in the compiled program — the "
            "carry donation is missing or was not honored",
            observed=False, expected=True)]
    return []


def check_collective_census(census: Dict[str, int], label: str,
                            pin: Dict[str, int]
                            ) -> List[ContractViolation]:
    """The compiled program's per-collective counts must EQUAL the pin —
    a new collective in the hot loop is a review event, not a drift."""
    out: List[ContractViolation] = []
    for op in sorted(set(pin) | set(census)):
        want, got = int(pin.get(op, 0)), int(census.get(op, 0))
        if want != got:
            out.append(ContractViolation(
                "collective-census", label,
                f"{op}: compiled program has {got}, pin says {want}",
                observed={op: got}, expected={op: want}))
    return out


def check_allreduce_bytes(collective_bytes: Optional[Dict[str, int]],
                          label: str, max_bytes: int
                          ) -> List[ContractViolation]:
    """The sharded-update hot loop's all-reduce traffic must stay
    scalar-control-only (``obs.introspect.collective_bytes``): a stray
    full-D psum re-entering the loop would pass the *census* pin only if
    it also replaced an existing one, but it can never hide from the
    byte ceiling — a D-sized gradient is orders of magnitude above the
    handful of f32/s32 scalars the control plane psums per iteration."""
    if collective_bytes is None:
        return [ContractViolation(
            "collective-bytes", label,
            "max_all_reduce_bytes pinned but the analyzer reported no "
            "collective byte census for this program",
            observed=None, expected=max_bytes)]
    got = int(collective_bytes.get("all-reduce", 0))
    if got > int(max_bytes):
        return [ContractViolation(
            "collective-bytes", label,
            f"all-reduce result bytes {got} exceed the pin "
            f"{int(max_bytes)} — a full-size reduction is riding the "
            "hot loop where only scalar control psums belong",
            observed=got, expected=int(max_bytes))]
    return []


def check_runner(fit, w0, *, label: str,
                 pins: Optional[Dict[str, dict]] = None,
                 budget_bytes: Optional[int] = None,
                 expect_donation: Optional[bool] = None,
                 ) -> Tuple[List[ContractViolation], Any]:
    """Run every pin against the ONE program ``fit`` executes (via its
    ``lower_step`` AOT hook — see ``obs.introspect.analyze_runner``).

    ``pins`` (default: the checked-in ``pins.json``) supplies the
    per-label expectations; explicit ``budget_bytes`` /
    ``expect_donation`` override it.  Returns ``(violations,
    ProgramCost)``.
    """
    from ..obs import introspect

    lower = getattr(fit, "lower_step", None)
    if lower is None:
        raise TypeError(
            "fit has no lower_step AOT hook; pass an api.make_runner / "
            "api.make_lbfgs_runner fit")
    compiled = lower(w0).compile()
    hlo = compiled.as_text()
    cost = introspect.analyze_compiled(compiled, label=label)

    pin = {} if pins is None else dict(pins.get(label, {}))
    if pins is None and os.path.exists(DEFAULT_PINS_PATH):
        pin = dict(load_pins().get(label, {}))
    budget = budget_bytes if budget_bytes is not None else int(
        pin.get("max_constant_bytes", DEFAULT_CONSTANT_BUDGET))
    donate = expect_donation if expect_donation is not None else bool(
        pin.get("donation", True))

    violations = []
    violations += check_constant_budget(hlo, label, budget)
    if donate:
        violations += check_donation(hlo, label, expect=True)
    if "collectives" in pin:
        violations += check_collective_census(cost.collectives, label,
                                              pin["collectives"])
    if "max_all_reduce_bytes" in pin:
        violations += check_allreduce_bytes(
            cost.collective_bytes, label,
            int(pin["max_all_reduce_bytes"]))
    return violations, cost


_DEFAULT_CONTRACTS = ("constant-bytes", "donation", "collective-census")


def pin_records(run_id: str, label: str,
                violations: List[ContractViolation],
                cost=None,
                checked: Tuple[str, ...] = _DEFAULT_CONTRACTS,
                ) -> List[dict]:
    """The ``contract_pin`` records for one checked runner: one OK
    record per passed contract, one failing record per violation — a
    JSONL consumer sees pins were RUN, not merely not-violated.
    ``checked`` names the contracts that actually ran (labels whose pin
    carries ``max_all_reduce_bytes`` add ``collective-bytes``)."""
    from ..obs import schema

    bad = {v.contract for v in violations}
    recs = []
    for v in violations:
        recs.append(schema.contract_pin_record(
            run_id, v.contract, False, label=label, message=v.message,
            observed=v.observed, expected=v.expected))
    for contract in checked:
        if contract not in bad:
            recs.append(schema.contract_pin_record(
                run_id, contract, True, label=label))
    return recs


def check_compiled(compiled, *, label: str, pin: dict,
                   ) -> Tuple[List[ContractViolation], Any]:
    """Run every pin against one already-compiled program (no AOT hook
    needed — what the serve engine's per-bucket executables use)."""
    from ..obs import introspect

    hlo = compiled.as_text()
    cost = introspect.analyze_compiled(compiled, label=label)
    budget = int(pin.get("max_constant_bytes", DEFAULT_CONSTANT_BUDGET))
    violations = []
    violations += check_constant_budget(hlo, label, budget)
    if bool(pin.get("donation", True)):
        violations += check_donation(hlo, label, expect=True)
    if "collectives" in pin:
        violations += check_collective_census(cost.collectives, label,
                                              pin["collectives"])
    if "max_all_reduce_bytes" in pin:
        violations += check_allreduce_bytes(
            cost.collective_bytes, label,
            int(pin["max_all_reduce_bytes"]))
    return violations, cost


def check_serve_engine(pins: Optional[Dict[str, dict]] = None,
                       telemetry=None) -> List[ContractViolation]:
    """The serving half of the dynamic gate: build a small
    representative :class:`~spark_agd_tpu.serve.engine.ServeEngine`
    (logistic, two buckets, both ops) and pin EVERY per-bucket compiled
    program — donated output honored in the aliasing, zero collectives
    (serving is single-device SPMD-free by construction), and the
    embedded-constant budget (weights must ride as ARGUMENTS, or a hot
    swap would recompile).  Labels are per-op (``serve_logistic_
    predict`` …) — buckets share a pin because they share program
    structure."""
    import numpy as np

    from ..models.glm import LogisticRegressionModel
    from ..serve.engine import ServeEngine

    if pins is None:
        pins = load_pins()
    rng = np.random.default_rng(0)
    model = LogisticRegressionModel(
        rng.normal(size=16).astype(np.float32), 0.25)
    engine = ServeEngine(model, max_batch=16, buckets=(8, 16))

    out: List[ContractViolation] = []
    for (op, bucket), compiled in sorted(
            engine.compiled_programs().items()):
        label = engine.program_label(op)
        violations, cost = check_compiled(
            compiled, label=f"{label}/b{bucket}",
            pin=dict(pins.get(label, {})))
        out.extend(violations)
        if telemetry is not None:
            for rec in pin_records(telemetry.run_id,
                                   f"{label}/b{bucket}", violations,
                                   cost):
                telemetry.emit(rec)
    return out


def check_default_runners(pins: Optional[Dict[str, dict]] = None,
                          telemetry=None) -> List[ContractViolation]:
    """The gate body behind ``tools/graft_lint.py --contracts``: build
    the REAL public AGD and L-BFGS runners on a small synthetic problem
    (CPU-deterministic) and run every pin against their compiled
    programs.  When the host exposes at least two devices the meshed
    pair is pinned too — ``agd_mesh`` (replicated all-reduce update) and
    ``agd_sharded`` (``sharded_update=True``) over a 2-device data mesh,
    so a stray full-size all-reduce re-entering the sharded hot loop
    fails this gate on any CPU.  Emits ``contract_pin`` records on
    ``telemetry`` when given."""
    import jax
    import numpy as np

    from .. import api
    from ..ops.losses import LogisticGradient
    from ..ops.prox import SquaredL2Updater
    from ..parallel import mesh as mesh_lib

    rng = np.random.default_rng(0)
    X = rng.normal(size=(64, 8)).astype(np.float32)
    y = (rng.random(64) > 0.5).astype(np.float32)
    w0 = np.zeros(8, np.float32)
    data = (X, y)
    if pins is None:
        pins = load_pins()

    runners = [
        ("agd", api.make_runner(data, LogisticGradient(),
                                SquaredL2Updater(), reg_param=1e-3,
                                num_iterations=5, mesh=False)),
        ("lbfgs", api.make_lbfgs_runner(data, LogisticGradient(),
                                        SquaredL2Updater(),
                                        reg_param=1e-3,
                                        num_iterations=5,
                                        mesh=False)),
    ]
    if len(jax.devices()) >= 2:
        mesh2 = mesh_lib.make_mesh({mesh_lib.DATA_AXIS: 2},
                                   devices=jax.devices()[:2])
        runners.append(
            ("agd_mesh", api.make_runner(data, LogisticGradient(),
                                         SquaredL2Updater(),
                                         reg_param=1e-3,
                                         num_iterations=5,
                                         mesh=mesh2)))
        runners.append(
            ("agd_sharded", api.make_runner(data, LogisticGradient(),
                                            SquaredL2Updater(),
                                            reg_param=1e-3,
                                            num_iterations=5,
                                            mesh=mesh2,
                                            sharded_update=True)))

    out: List[ContractViolation] = []
    for label, fit in runners:
        violations, cost = check_runner(fit, w0, label=label, pins=pins)
        out.extend(violations)
        if telemetry is not None:
            checked = _DEFAULT_CONTRACTS
            if "max_all_reduce_bytes" in pins.get(label, {}):
                checked = checked + ("collective-bytes",)
            for rec in pin_records(telemetry.run_id, label, violations,
                                   cost, checked=checked):
                telemetry.emit(rec)
    return out
