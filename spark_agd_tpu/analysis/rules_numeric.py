"""Numeric-hygiene rules for traced code: np/jnp mixing and f64 literals.

- **np-jnp-mix** — a ``np.*`` array op inside a traced function either
  constant-folds at trace time (silently freezing a value that looks
  dynamic) or raises ``TracerArrayConversionError`` at the first real
  call.  Either way the author thought they wrote device code and
  didn't.  Trace-time *shape/dtype* arithmetic (``np.prod``,
  ``np.dtype``, dtype constructors) is legitimate and allowlisted.

- **f64-literal** — an explicit ``float64`` dtype inside traced code:
  under the default x64-disabled config it silently truncates to f32
  (a wrong-answer generator for the f64 parity oracles), and under x64
  it doubles HBM on the TPU where f64 is emulated.  Traced code derives
  dtypes from the carry (``core.agd``'s ``dt`` pattern); host-side
  oracles and ingest are out of scope.
"""

from __future__ import annotations

import ast
from typing import Iterable

from .framework import Finding, Module, Rule, dotted_name

_NP_ROOTS = ("np", "numpy")

# trace-time-legitimate numpy members: shape/dtype arithmetic and
# constants (attributes like np.pi/np.inf are not Calls and never flag)
_NP_OK = frozenset({
    "dtype", "finfo", "iinfo", "result_type", "promote_types",
    "can_cast", "prod", "ndim", "shape", "isscalar",
    "float32", "float16", "int8", "int16", "int32", "int64",
    "uint8", "uint16", "uint32", "uint64", "bool_",
    # f64 constructors are covered (better) by the f64-literal rule
    "float64", "double",
})

_F64_NAMES = frozenset({"np.float64", "numpy.float64", "jnp.float64",
                        "np.double", "numpy.double"})


def _np_member(node: ast.AST):
    """('np', member) when the expression is a numpy attribute chain."""
    name = dotted_name(node)
    if name is None:
        return None
    root, _, rest = name.partition(".")
    if root in _NP_ROOTS and rest:
        return rest.split(".")[-1]
    return None


class NpJnpMixRule(Rule):
    name = "np-jnp-mix"
    description = ("numpy array ops inside traced code constant-fold at "
                   "trace time or raise on tracers; use jnp")

    def check(self, mod: Module) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and mod.in_traced(node)):
                continue
            member = _np_member(node.func)
            if member is None or member in _NP_OK:
                continue
            yield mod.finding(
                self.name, node,
                f"np.{member}() inside a traced function runs on the "
                "host at trace time (constant-folds or raises on a "
                "tracer); use the jnp equivalent, or hoist genuine "
                "host-side staging out of the traced scope")


class F64LiteralRule(Rule):
    name = "f64-literal"
    description = ("explicit float64 dtypes in traced code truncate "
                   "silently under x64-off and double HBM under x64; "
                   "derive the dtype from the carry")

    def check(self, mod: Module) -> Iterable[Finding]:
        for node in ast.walk(mod.tree):
            if not mod.in_traced(node):
                continue
            if isinstance(node, ast.Attribute):
                if dotted_name(node) in _F64_NAMES and isinstance(
                        node.ctx, ast.Load):
                    # attribute used as dtype= value or called directly
                    yield mod.finding(
                        self.name, node,
                        "float64 literal in traced code — derive the "
                        "dtype from the carry (e.g. "
                        "jnp.result_type(*leaves)) instead of pinning "
                        "f64")
            elif isinstance(node, ast.keyword) and node.arg == "dtype" \
                    and isinstance(node.value, ast.Constant) \
                    and node.value.value in ("float64", "f64", "double"):
                yield mod.finding(
                    self.name, node.value,
                    "dtype='float64' string literal in traced code — "
                    "derive the dtype from the carry instead of "
                    "pinning f64")
