"""spark_agd_tpu — a TPU-native accelerated proximal gradient framework.

A ground-up re-design of the capabilities of ``staple/spark-agd`` (TFOCS-style
Accelerated Gradient Descent on Spark, reference mounted at
``/root/reference``) for TPU: XLA-compiled batched loss kernels instead of
per-example ``Gradient.compute``, a ``psum`` over the ICI mesh instead of
``RDD.treeAggregate``, on-chip weight updates instead of driver round-trips,
and the whole outer iteration — acceleration, backtracking line search,
restart — compiled into one XLA program via ``lax.while_loop``.

Layer map (mirrors SURVEY.md §1, re-drawn TPU-first):

====  =============================  =========================================
L5    public API                     ``AcceleratedGradientDescent`` class,
                                     ``run`` / ``run_minibatch_agd``
L4    optimizer core                 ``core.agd`` fused while-loop state
                                     machine
L3    math plugins                   ``ops.losses`` (Gradient), ``ops.prox``
                                     (Updater)
L2    distributed reduce             ``parallel`` — shard_map psum / pjit
                                     auto-sharding over a Mesh
L1    runtime                        XLA:TPU + host data staging (``data``)
L0    local math                     ``core.tvec`` pytree algebra inside the
                                     compiled program
====  =============================  =========================================

Beyond the reference's surface: batched regularization paths
(``sweep`` — K strengths in one compiled program), one-program K-fold
cross-validation (``cross_validate``), jitted evaluation metrics
(``models.evaluation``), model persistence, larger-than-HBM streaming
that composes with the mesh for dense AND sparse data, and fused
single-HBM-pass Pallas kernels.

Grid fits compose with EVERYTHING (round 3): lanes vmapped inside the
shard_map so sweeps/CV run on the full mesh (``parallel.grid``); the
GD oracle runs sharded with globally consistent sampling; K-lane
lock-step host AGD trains a whole path over a STREAM on one stream
read per trial (``streaming_sweep``), scores K candidates in one pass
(``make_streaming_eval_multi``), and survives kills via per-lane
checkpoints (``utils.checkpoint.run_agd_multi_checkpointed``).  See
``docs/DISTRIBUTED.md`` for the full composition matrix, each cell
named with its test.
"""

__version__ = "0.1.0"

from .ops.losses import (  # noqa: F401
    Gradient,
    LogisticGradient,
    LeastSquaresGradient,
    HingeGradient,
    SoftmaxGradient,
    CustomGradient,
)
from .api import (  # noqa: F401
    AcceleratedGradientDescent,
    LBFGS,
    run,
    run_lbfgs,
    make_lbfgs_runner,
    make_lbfgs_sweep_runner,
    run_minibatch_agd,
    run_minibatch_sgd,
    CVResult,
    cross_validate,
    make_cv_runner,
    make_sweep_runner,
    streaming_lbfgs_sweep,
    streaming_sweep,
    sweep,
    sweep_warm_state,
)
from .core.agd import AGDConfig, AGDResult  # noqa: F401
from .core.lbfgs import (  # noqa: F401
    LBFGSConfig,
    LBFGSResult,
    make_objective as make_lbfgs_objective,
    run_owlqn,
)
from .core.host_lbfgs import (  # noqa: F401
    HostLBFGSResult,
    HostLBFGSWarm,
    run_lbfgs_host,
    run_owlqn_host,
)
from .parallel.mesh import (  # noqa: F401
    ShardedBatch,
    make_mesh,
    replicate,
    shard_batch,
    shard_csr_batch,
)
from .ops.prox import (  # noqa: F401
    Prox,
    IdentityProx,
    L2Prox,
    MLlibSquaredL2Updater,
    L1Prox,
    ElasticNetProx,
    SimpleUpdater,
    SquaredL2Updater,
    L1Updater,
)
from .ops.sparse import CSRMatrix  # noqa: F401
from . import obs  # noqa: F401
from . import serve  # noqa: F401  (the serving plane: docs/SERVING.md)
from .obs import Telemetry  # noqa: F401
from .data.streaming import (  # noqa: F401
    StreamingDataset,
    make_streaming_eval_multi,
    make_streaming_smooth,
)
