"""Persistent XLA compilation cache (compile once, reuse across runs).

The fused AGD program's first compile costs 20–40 s on TPU (more over a
tunneled backend), and every fresh process pays it again — the reference
has no analogue (the JVM re-JITs per run), but a framework whose unit of
execution is one big compiled program should not.  Enabling the disk
cache makes every later process (a retried benchmark cycle, a
hyper-parameter sweep, a resumed job) deserialize the executable instead
of recompiling, which on the pooled single-chip bench environment
converts directly into measurement time (AVAILABILITY.md: chip claims
are scarce; recompiles burn them).

Thin by design: one call, idempotent, safe on every backend (backends
without executable serialization just log a JAX warning and skip).
"""

from __future__ import annotations

import os
from typing import Optional

DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "spark_agd_tpu", "xla")


def enable(path: Optional[str] = None, *,
           min_compile_time_secs: float = 1.0) -> str:
    """Turn on JAX's persistent compilation cache at ``path``.

    Call before the first compile (later calls still help later
    compiles).  ``min_compile_time_secs`` skips caching trivial programs
    (set 0 to cache everything, as tests do).  Returns the cache dir.
    """
    import jax

    path = path or os.environ.get("SPARK_AGD_COMPILE_CACHE", DEFAULT_DIR)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_time_secs)
    # The cache object initializes lazily at the FIRST compile and then
    # latches; if anything compiled before enable() (e.g. the
    # environment's sitecustomize touching the backend), the new dir
    # would silently never take effect.  Reset so it re-initializes.
    from jax.experimental.compilation_cache import compilation_cache
    compilation_cache.reset_cache()
    return path
