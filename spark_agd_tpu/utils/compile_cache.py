"""Persistent XLA compilation cache (compile once, reuse across runs).

The fused AGD program's first compile costs 20–40 s on TPU (more over a
tunneled backend), and every fresh process pays it again — the reference
has no analogue (the JVM re-JITs per run), but a framework whose unit of
execution is one big compiled program should not.  Enabling the disk
cache makes every later process (a retried benchmark cycle, a
hyper-parameter sweep, a resumed job) deserialize the executable instead
of recompiling, which on the pooled single-chip bench environment
converts directly into measurement time (AVAILABILITY.md: chip claims
are scarce; recompiles burn them).

Thin by design: one call, idempotent, safe on every backend (backends
without executable serialization just log a JAX warning and skip).
"""

from __future__ import annotations

import contextlib
import logging
import os
from typing import Optional

logger = logging.getLogger("spark_agd_tpu")

DEFAULT_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "spark_agd_tpu", "xla")

# census taken by enable(); observe_compile deltas against the most
# recent snapshot so consecutive observed compiles attribute correctly
_LAST_CENSUS: Optional[dict] = None
_LOGGED_ONCE = False


def stats(path: Optional[str] = None) -> dict:
    """Census of the cache dir: ``{"dir", "files", "bytes"}`` (recursive;
    zeros when the dir does not exist yet)."""
    path = path or os.environ.get("SPARK_AGD_COMPILE_CACHE", DEFAULT_DIR)
    files = size = 0
    for root, _, names in os.walk(path):
        for n in names:
            try:
                size += os.path.getsize(os.path.join(root, n))
                files += 1
            except OSError:  # racing eviction — census stays best-effort
                continue
    return {"dir": path, "files": files, "bytes": size}


def enable(path: Optional[str] = None, *,
           min_compile_time_secs: float = 1.0) -> str:
    """Turn on JAX's persistent compilation cache at ``path``.

    Call before the first compile (later calls still help later
    compiles).  ``min_compile_time_secs`` skips caching trivial programs
    (set 0 to cache everything, as tests do).  Returns the cache dir.

    Also snapshots the dir census (files, bytes) into the process
    metrics registry (gauges ``compile_cache.*``) so the cache's state
    is observable before the first compile; pair with
    :func:`observe_compile` to count hits/misses.
    """
    global _LAST_CENSUS
    import jax

    path = path or os.environ.get("SPARK_AGD_COMPILE_CACHE", DEFAULT_DIR)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_time_secs)
    # The cache object initializes lazily at the FIRST compile and then
    # latches; if anything compiled before enable() (e.g. the
    # environment's sitecustomize touching the backend), the new dir
    # would silently never take effect.  Reset so it re-initializes.
    from jax.experimental.compilation_cache import compilation_cache
    compilation_cache.reset_cache()
    _LAST_CENSUS = _record_census(stats(path))
    return path


def _record_census(census: dict, registry=None) -> dict:
    from ..obs.registry import default_registry

    reg = registry or default_registry()
    reg.gauge("compile_cache.files").set(census["files"])
    reg.gauge("compile_cache.bytes").set(census["bytes"])
    return census


@contextlib.contextmanager
def observe_compile(path: Optional[str] = None, registry=None):
    """Attribute ONE compile to the persistent cache by file census:
    wrap the call that triggers it (the first ``fit()``, an AOT
    ``.compile()``) and the dir is censused before/after — a new cache
    entry means the executable was built here (**miss**), no new entry
    with a populated cache means it was deserialized (**hit**).
    Counters ``compile_cache.hits`` / ``.misses`` and the dir gauges
    land in the metrics registry (default: the process registry), and
    the first observation logs the cache state once per process::

        compile_cache.enable(dir)
        with compile_cache.observe_compile():
            fit(w0)   # first call -> compile or cache load
    """
    global _LAST_CENSUS, _LOGGED_ONCE
    from ..obs.registry import default_registry

    reg = registry or default_registry()
    resolved = path or os.environ.get("SPARK_AGD_COMPILE_CACHE",
                                      DEFAULT_DIR)
    before = (_LAST_CENSUS
              if _LAST_CENSUS and _LAST_CENSUS["dir"] == resolved
              else stats(resolved))
    try:
        yield
    finally:
        after = stats(resolved)
        new_files = after["files"] - before["files"]
        if new_files > 0:
            reg.counter("compile_cache.misses").inc(new_files)
        else:
            reg.counter("compile_cache.hits").inc()
        _record_census(after, reg)
        _LAST_CENSUS = after
        if not _LOGGED_ONCE:
            _LOGGED_ONCE = True
            logger.info(
                "compile cache %s: %d file(s), %.1f MiB; first observed "
                "compile was a %s",
                after["dir"], after["files"], after["bytes"] / 2**20,
                "miss" if new_files > 0 else "hit")
