"""Checkpoint / resume — elastic restart for AGD runs.

The reference persists nothing (SURVEY §5 "Checkpoint / resume: none") and
delegates within-run fault tolerance to Spark task retry.  The TPU runtime
has no lineage recomputation, so the equivalent robustness story is the one
SURVEY §5 sketches: the *entire* optimizer state is two weight pytrees plus
three scalars (``core.agd.AGDWarmState``), so re-runnable outer segments +
tiny checkpoints give elastic restart almost for free.

Format: one ``.npz`` per checkpoint (atomic rename), holding the flattened
``x``/``z`` pytree leaves, the scalar carry, and the cumulative loss
history.  Loading needs a *template* pytree (normally ``w0``) to rebuild the
tree structure — the file stores leaves positionally, not a pickled treedef,
so checkpoints are plain data (no code execution on load).

``run_agd_checkpointed`` drives ``core.agd.run_agd`` (fused, default) or
``core.host_agd.run_agd_host`` (``driver="host"`` — required for
host-level streamed smooths) in segments
of ``segment_iters`` compiled iterations, checkpointing between segments and
resuming from ``path`` if a checkpoint exists.  Segment boundaries are
invisible to the math: the warm carry is exact (including the ``nIter > 1``
zero-step gate via ``prior_iters``), pinned by the parity tests in
``tests/test_checkpoint.py``.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import tempfile
import zipfile
import zlib
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import agd
from ..core.agd import AGDConfig, AGDWarmState

logger = logging.getLogger("spark_agd_tpu")


class CheckpointCorruptError(RuntimeError):
    """``path`` holds a truncated/garbage npz (kill mid-write on a
    non-atomic filesystem, torn volume, bad sector) — the typed wrapper
    every loader raises instead of surfacing a raw
    ``zipfile.BadZipFile`` / zlib error from deep inside numpy.
    Classified TRANSIENT-adjacent by recovery code: the
    ``AutoCheckpointer`` falls back to the previous ``.bak``
    generation; ``load_checkpoint`` does the same one-level fallback
    itself."""

    def __init__(self, path: str, cause: Optional[BaseException] = None):
        detail = f" ({type(cause).__name__}: {cause})" if cause else ""
        super().__init__(f"checkpoint at {path!r} is corrupt or "
                         f"truncated{detail}")
        self.path = path


def _flat(tree):
    return jax.tree_util.tree_leaves(tree)


# the npz entry holding the per-entry CRC32 map (JSON: name -> crc);
# written by atomic_savez, verified and stripped by read_npz_entries
CRC_ENTRY = "__crc32__"


def _entry_crc32(value: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(value).tobytes())


def read_npz_entries(path: str) -> Dict[str, np.ndarray]:
    """Materialize EVERY entry of an npz into host arrays, converting
    any parse failure — bad zip directory, truncated member, zlib
    garbage — into one typed :class:`CheckpointCorruptError`.  Forcing
    the full read up front is the point: ``np.load`` is lazy, so a
    truncated member would otherwise explode only at first access,
    midway through rebuilding a pytree.

    When the file carries a ``__crc32__`` entry (every npz written by
    :func:`atomic_savez` does), each listed entry's bytes are verified
    against its stored CRC32 — so a SILENT bit-flip (bad sector,
    bit-rot, a tool rewriting the archive) raises the same typed error
    as an unparseable file, instead of resuming from corrupt state.
    Files without the entry (pre-upgrade checkpoints) load unchecked."""
    try:
        with np.load(path) as data:
            entries = {k: np.asarray(data[k]) for k in data.files}
    except (zipfile.BadZipFile, EOFError, OSError, KeyError,
            ValueError) as e:
        raise CheckpointCorruptError(path, e) from e
    crc_entry = entries.pop(CRC_ENTRY, None)
    if crc_entry is not None:
        try:
            crcs = json.loads(str(crc_entry))
        except ValueError as e:
            raise CheckpointCorruptError(path, e) from e
        for name, expect in crcs.items():
            if name not in entries:
                raise CheckpointCorruptError(
                    path, KeyError(f"checksummed entry {name!r} missing"))
            if _entry_crc32(entries[name]) != int(expect):
                raise CheckpointCorruptError(
                    path, ValueError(
                        f"entry {name!r} fails its CRC32 (silent "
                        "bit-flip or partial rewrite)"))
    return entries


class _Entries:
    """Dict view over materialized npz entries whose missing-key error
    is the typed corruption error (a successfully-unzipped file missing
    required keys is a torn write, not a different format)."""

    def __init__(self, path: str, entries: Dict[str, np.ndarray]):
        self._path = path
        self._entries = entries

    def __contains__(self, key):
        return key in self._entries

    def __getitem__(self, key):
        try:
            return self._entries[key]
        except KeyError as e:
            raise CheckpointCorruptError(self._path, e) from e

    def prefixed(self, prefix: str) -> Dict[str, np.ndarray]:
        """Every entry under a namespace prefix — how rider payloads
        (the ``stream_*`` mid-epoch cursor) come back out of a file
        whose core keys predate them."""
        return {k: v for k, v in self._entries.items()
                if k.startswith(prefix)}


def _load_tree(data, treedef, n: int, name: str):
    """Rebuild one pytree from ``{name}_{i}`` npz entries — the ONE copy
    of the leaf-naming scheme all loaders share."""
    leaves = [jnp.asarray(data[f"{name}_{i}"]) for i in range(n)]
    return jax.tree_util.tree_unflatten(treedef, leaves)


def problem_fingerprint(w0: Any, config: AGDConfig) -> str:
    """A stable id of what a checkpoint continues: the weight pytree's
    structure/shapes/dtypes plus every config field except
    ``num_iterations`` (which legitimately differs between the killed run
    and its resume).  Guards against a stale file at a reused path silently
    hijacking a different problem.  The smooth/prox closures cannot be
    fingerprinted (they are code); changing those while keeping the same
    path is on the caller."""
    leaves, treedef = jax.tree_util.tree_flatten(w0)
    shapes = ";".join(
        f"{np.asarray(l).shape}:{np.asarray(l).dtype}" for l in leaves)
    cfg = dataclasses.asdict(config)
    cfg.pop("num_iterations")
    return f"{treedef}|{shapes}|{sorted(cfg.items())}"


def warm_payload(warm: AGDWarmState, loss_history=None, *,
                 converged: bool = False, aborted: bool = False,
                 fingerprint: Optional[str] = None,
                 extra: Optional[dict] = None) -> dict:
    """The npz payload of one ``AGDWarmState`` checkpoint — the ONE
    encoding :func:`save_checkpoint` and the multi-host shard writer
    (``resilience.distributed``) share, so a distributed shard is a
    superset of a single-host checkpoint and the loaders never fork.

    ``extra`` (optional): namespaced rider entries (the streaming
    layer's ``stream_*`` mid-epoch cursor) saved alongside the core
    keys; loaders that predate a rider ignore it (the entry set is
    open), and :func:`checkpoint_from_entries` hands riders back via
    ``LoadedCheckpoint.extras``.  Keys must not collide with the core
    payload."""
    payload = {}
    for name, tree in (("x", warm.x), ("z", warm.z)):
        for i, leaf in enumerate(_flat(tree)):
            payload[f"{name}_{i}"] = np.asarray(leaf)
    payload["theta"] = np.asarray(float(warm.theta))
    payload["big_l"] = np.asarray(float(warm.big_l))
    payload["bts"] = np.asarray(bool(warm.bts))
    payload["prior_iters"] = np.asarray(int(warm.prior_iters))
    payload["converged"] = np.asarray(bool(converged))
    payload["aborted"] = np.asarray(bool(aborted))
    if fingerprint is not None:
        payload["fingerprint"] = np.asarray(fingerprint)
    payload["loss_history"] = (np.zeros(0) if loss_history is None
                               else np.asarray(loss_history))
    if extra:
        for k, v in extra.items():
            if k in payload:
                raise ValueError(
                    f"extra checkpoint entry {k!r} collides with a "
                    "core payload key; namespace rider entries "
                    "(e.g. 'stream_*')")
            payload[k] = np.asarray(v)
    return payload


def save_checkpoint(path: str, warm: AGDWarmState, loss_history=None,
                    *, converged: bool = False, aborted: bool = False,
                    fingerprint: Optional[str] = None,
                    extra: Optional[dict] = None) -> None:
    """Atomically write the continuation carry (+ cumulative loss history).

    ``converged``/``aborted`` mark a *terminal* checkpoint: the run stopped
    by its own criteria, and resuming must be a no-op rather than extra
    iterations (or, for abort, a resume from non-finite weights).
    ``extra``: namespaced rider entries — see :func:`warm_payload`."""
    atomic_savez(path, warm_payload(
        warm, loss_history, converged=converged, aborted=aborted,
        fingerprint=fingerprint, extra=extra))


def atomic_savez(path: str, payload: dict):
    """Write an npz atomically (tempfile in the target dir + rename), so
    a kill mid-write can never leave a torn file.  Creates the directory
    if needed.  Shared by checkpoints and model persistence.

    Every write carries a ``__crc32__`` entry mapping each payload entry
    to the CRC32 of its bytes; ``read_npz_entries`` verifies it on load,
    so silent bit-flips are caught, not just unparseable files."""
    payload = dict(payload)
    payload[CRC_ENTRY] = np.asarray(json.dumps(
        {k: _entry_crc32(np.asarray(v)) for k, v in payload.items()}))
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, **payload)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


class LoadedCheckpoint(NamedTuple):
    warm: AGDWarmState
    loss_history: np.ndarray
    converged: bool
    aborted: bool
    fingerprint: Optional[str]
    # namespaced rider entries (``stream_*`` mid-epoch cursor) that rode
    # the file; empty for checkpoints written without extras
    extras: Dict[str, np.ndarray] = {}


def checkpoint_from_entries(path: str, data: "_Entries", template: Any,
                            expect_fingerprint: Optional[str] = None,
                            ) -> LoadedCheckpoint:
    """Rebuild one ``AGDWarmState`` checkpoint from already-read npz
    entries — the parsing half of :func:`load_checkpoint`, shared with
    the multi-host shard loader (``resilience.distributed``), whose
    shard files carry the same payload plus manifest bookkeeping."""
    treedef = jax.tree_util.tree_structure(template)
    n = treedef.num_leaves
    fp = str(data["fingerprint"]) if "fingerprint" in data else None
    if (expect_fingerprint is not None and fp is not None
            and fp != expect_fingerprint):
        raise ValueError(
            f"checkpoint at {path!r} belongs to a different problem "
            "(weight structure or config changed); delete it or use "
            "a different path")
    if "multi" in data:
        raise ValueError(
            f"checkpoint at {path!r} is a MULTI-lane checkpoint "
            "(run_agd_multi_checkpointed); load it with "
            "load_multi_checkpoint / resume it with the multi "
            "driver")
    if "lbfgs" in data:
        raise ValueError(
            f"checkpoint at {path!r} is an L-BFGS checkpoint "
            "(run_lbfgs_checkpointed); load it with "
            "load_lbfgs_checkpoint")
    tree = lambda name: _load_tree(data, treedef, n, name)

    warm = AGDWarmState(
        x=tree("x"), z=tree("z"),
        theta=float(data["theta"]), big_l=float(data["big_l"]),
        bts=bool(data["bts"]), prior_iters=int(data["prior_iters"]))
    hist = np.asarray(data["loss_history"])
    converged = bool(data["converged"]) if "converged" in data else False
    aborted = bool(data["aborted"]) if "aborted" in data else False
    extras = (data.prefixed("stream_") if hasattr(data, "prefixed")
              else {})
    return LoadedCheckpoint(warm, hist, converged, aborted, fp,
                            extras=extras)


def load_checkpoint(path: str, template: Any,
                    expect_fingerprint: Optional[str] = None, *,
                    fallback_to_bak: bool = True,
                    ) -> Optional[LoadedCheckpoint]:
    """Rebuild a checkpoint from ``path``; None if the file does not exist.
    ``template`` supplies the pytree structure (and therefore leaf order)
    of the weights — normally ``w0``.  If ``expect_fingerprint`` is given
    and the file carries a different one, raises ValueError rather than
    resuming the wrong problem.

    A truncated/garbage file raises :class:`CheckpointCorruptError` —
    unless ``fallback_to_bak`` (default) and a ``path + ".bak"``
    generation exists (the ``AutoCheckpointer`` retention chain), in
    which case the previous generation is loaded instead (logged).  The
    corrupt primary is left in place for post-mortems; the next save
    atomically replaces it."""
    if not os.path.exists(path):
        return None
    try:
        data = _Entries(path, read_npz_entries(path))
        return checkpoint_from_entries(path, data, template,
                                       expect_fingerprint)
    except CheckpointCorruptError:
        bak = path + ".bak"
        if fallback_to_bak and os.path.exists(bak):
            logger.warning(
                "checkpoint %r is corrupt; falling back to previous "
                "generation %r", path, bak)
            return load_checkpoint(bak, template, expect_fingerprint,
                                   fallback_to_bak=False)
        raise


# The iteration-zero carry is defined ONCE, in core.agd (all drivers expand
# it); re-exported here for checkpoint-facing code.
fresh_warm_state = AGDWarmState.initial


def warm_from_result(res, prior_iters: int) -> AGDWarmState:
    """Continuation carry out of an ``AGDResult`` / ``HostAGDResult``."""
    return AGDWarmState(
        x=res.weights, z=res.final_z, theta=float(res.final_theta),
        big_l=float(res.final_l), bts=bool(res.final_bts),
        prior_iters=int(prior_iters))


class CheckpointedResult(NamedTuple):
    weights: Any
    loss_history: np.ndarray
    num_iters: int  # total outer iterations across all runs of this path
    aborted_non_finite: bool
    resumed_from: int  # iterations already in the checkpoint at startup


def run_agd_checkpointed(
    smooth,
    prox,
    reg_value,
    w0: Any,
    config: AGDConfig,
    *,
    path: str,
    segment_iters: int = 10,
    smooth_loss=None,
    driver: str = "fused",
    staged=None,
    resilience=None,
) -> CheckpointedResult:
    """AGD with periodic checkpoints: run ``segment_iters`` outer
    iterations per launch, persist the carry after each.  Kill the
    process at any point; rerunning the same call continues from the
    last completed segment.

    ``driver="fused"`` (default) jits ``core.agd.run_agd`` once per
    segment shape — for device-resident smooths.  ``driver="host"``
    drives ``core.host_agd.run_agd_host`` — REQUIRED for host-level
    smooths (the streamed macro-batch fold, ``data.streaming``), whose
    Python loop cannot live inside a traced program.

    ``staged`` (fused driver only): the ``(build, data_args)`` pair
    from ``core.smooth.make_smooth_staged`` / ``parallel.dist_smooth.
    make_dist_smooth_staged``.  When given, each segment's jitted
    program takes the data as ARGUMENTS and ``smooth``/``smooth_loss``
    are ignored — a closure-captured smooth embeds the dataset as
    program constants and makes each segment's XLA compile scale with
    nnz (the r4 ``compile_s: 1842.74`` defect class).  Closure smooths
    remain supported for small problems and custom objectives.

    ``resilience`` (a ``resilience.RetryPolicy``, or ``True`` for the
    defaults): each segment additionally runs under the shared
    bounded-retry helper, so a TRANSIENT failure (device loss, runtime
    hiccup) re-executes that segment from its already-persisted carry
    instead of killing the driver.  For the full supervision set
    (numerics rollback, preemption flush, fault drills) use
    ``resilience.supervisor.run_agd_supervised`` /
    ``api.run(resilience=...)``."""
    if segment_iters <= 0:
        raise ValueError("segment_iters must be positive")
    if driver not in ("fused", "host"):
        raise ValueError(f"unknown driver {driver!r}: 'fused' | 'host'")
    if staged is not None and driver != "fused":
        raise ValueError(
            "staged=(build, data_args) applies to the fused driver "
            "only; the host driver never embeds data in a program")
    fp = problem_fingerprint(w0, config)
    loaded = load_checkpoint(path, w0, expect_fingerprint=fp)
    if loaded is not None:
        warm = loaded.warm
        hist = list(np.asarray(loaded.loss_history))
        if loaded.converged or loaded.aborted:
            # terminal checkpoint: the run already stopped by its own
            # criteria — rerunning must not execute further iterations
            return CheckpointedResult(
                weights=warm.x, loss_history=np.asarray(hist),
                num_iters=int(warm.prior_iters),
                aborted_non_finite=loaded.aborted,
                resumed_from=int(warm.prior_iters))
    else:
        warm = AGDWarmState.initial(w0, config)
        hist = []
    resumed_from = int(warm.prior_iters)

    # One jitted function per distinct segment length (at most two: the
    # full segment and the final remainder).
    seg_fns = {}

    def run_segment(warm_state, k):
        cfg_k = dataclasses.replace(config, num_iterations=k)
        if driver == "host":
            from ..core import host_agd

            return host_agd.run_agd_host(
                smooth, prox, reg_value, warm_state.x, cfg_k,
                smooth_loss=smooth_loss, warm=warm_state)
        if staged is not None:
            build, dargs = staged
            if k not in seg_fns:
                def _seg(ws, da, c=cfg_k):
                    sm, sl = build(*da)
                    return agd.run_agd(sm, prox, reg_value, ws.x, c,
                                       smooth_loss=sl, warm=ws)

                # graftlint: disable=donation -- ws is the segment-
                # retry anchor (resilience= paths rerun a failed
                # segment from the same warm state); donation would
                # invalidate it
                seg_fns[k] = jax.jit(_seg)
            return seg_fns[k](warm_state, dargs)
        if k not in seg_fns:
            # graftlint: disable=donation -- same segment-retry anchor
            seg_fns[k] = jax.jit(
                lambda ws, c=cfg_k: agd.run_agd(
                    smooth, prox, reg_value, ws.x, c,
                    smooth_loss=smooth_loss, warm=ws))
        return seg_fns[k](warm_state)

    if resilience is not None:
        from ..resilience import retry as retry_lib

        retry_policy = (retry_lib.RetryPolicy() if resilience is True
                        else resilience)
        plain_segment = run_segment

        def run_segment(warm_state, k):  # noqa: F811 — retry shell
            return retry_lib.call_with_retry(
                plain_segment, warm_state, k, policy=retry_policy,
                label="checkpointed_segment")

    total = config.num_iterations
    aborted = False
    while int(warm.prior_iters) < total:
        k = min(segment_iters, total - int(warm.prior_iters))
        res = run_segment(warm, k)
        done = int(res.num_iters)
        hist.extend(np.asarray(res.loss_history)[:done].tolist())
        warm = warm_from_result(res, int(warm.prior_iters) + done)
        aborted = bool(res.aborted_non_finite)
        save_checkpoint(path, warm, np.asarray(hist),
                        converged=bool(res.converged), aborted=aborted,
                        fingerprint=fp)
        if bool(res.converged) or aborted or done == 0:
            break

    return CheckpointedResult(
        weights=warm.x, loss_history=np.asarray(hist),
        num_iters=int(warm.prior_iters), aborted_non_finite=aborted,
        resumed_from=resumed_from)


# ---------------------------------------------------------------------------
# Multi-lane (streamed sweep) checkpointing: same format discipline — one
# atomic npz of plain arrays, a fingerprint, terminal semantics — for the
# K-lane lock-step host driver (core.host_agd.run_agd_host_multi).  The
# north-star composition closes here: a regularization path over a
# larger-than-HBM stream survives a mid-run kill.
# ---------------------------------------------------------------------------


def save_multi_checkpoint(path: str, warm, loss_history,
                          *, fingerprint: Optional[str] = None) -> None:
    """Atomically persist a ``core.host_agd.HostMultiWarm`` (+ the
    cumulative ``(iters, K)`` loss-history rows)."""
    payload = {}
    for name, tree in (("x", warm.x), ("z", warm.z)):
        for i, leaf in enumerate(_flat(tree)):
            payload[f"{name}_{i}"] = np.asarray(leaf)
    for field in ("theta", "big_l", "bts", "prior_iters", "converged",
                  "aborted", "num_backtracks", "num_restarts",
                  "last_loss"):
        payload[field] = np.asarray(getattr(warm, field))
    if fingerprint is not None:
        payload["fingerprint"] = np.asarray(fingerprint)
    payload["loss_history"] = np.asarray(loss_history)
    payload["multi"] = np.asarray(True)
    atomic_savez(path, payload)


def load_multi_checkpoint(path: str, template: Any,
                          expect_fingerprint: Optional[str] = None):
    """Rebuild a multi-lane checkpoint; ``template`` is the STACKED
    weight pytree (leaf order).  Returns ``(HostMultiWarm, hist)`` or
    None when the file does not exist."""
    from ..core import host_agd

    if not os.path.exists(path):
        return None
    treedef = jax.tree_util.tree_structure(template)
    n = treedef.num_leaves
    data = _Entries(path, read_npz_entries(path))
    fp = str(data["fingerprint"]) if "fingerprint" in data else None
    if (expect_fingerprint is not None and fp is not None
            and fp != expect_fingerprint):
        raise ValueError(
            f"checkpoint at {path!r} belongs to a different problem "
            "(weight structure or config changed); delete it or use "
            "a different path")
    if "multi" not in data:
        raise ValueError(
            f"checkpoint at {path!r} is a single-run checkpoint, "
            "not a multi-lane one")

    tree = lambda name: _load_tree(data, treedef, n, name)

    warm = host_agd.HostMultiWarm(
        x=tree("x"), z=tree("z"),
        theta=np.asarray(data["theta"]),
        big_l=np.asarray(data["big_l"]),
        bts=np.asarray(data["bts"]),
        prior_iters=np.asarray(data["prior_iters"]),
        converged=np.asarray(data["converged"]),
        aborted=np.asarray(data["aborted"]),
        num_backtracks=np.asarray(data["num_backtracks"]),
        num_restarts=np.asarray(data["num_restarts"]),
        last_loss=np.asarray(data["last_loss"]))
    hist = np.asarray(data["loss_history"])
    return warm, hist


class CheckpointedMultiResult(NamedTuple):
    weights: Any               # stacked (K, ...) pytree
    loss_history: np.ndarray   # cumulative (total_iters, K)
    num_iters: np.ndarray      # (K,) totals across all launches
    aborted_non_finite: np.ndarray  # (K,)
    converged: np.ndarray      # (K,)
    resumed_from: np.ndarray   # (K,) iterations already checkpointed


def run_agd_multi_checkpointed(
    smooth_multi,
    prox_multi,
    reg_value_multi,
    w0_stacked: Any,
    config: AGDConfig,
    *,
    path: str,
    segment_iters: int = 10,
    smooth_loss_multi=None,
) -> CheckpointedMultiResult:
    """The K-lane twin of :func:`run_agd_checkpointed` over the host
    multi driver: run ``segment_iters`` lock-step iterations per
    segment, checkpoint the full per-lane carry after each, resume
    exactly (converged lanes stay stopped) after any kill."""
    from ..core import host_agd

    if segment_iters <= 0:
        raise ValueError("segment_iters must be positive")
    fp = problem_fingerprint(w0_stacked, config)
    loaded = load_multi_checkpoint(path, w0_stacked,
                                   expect_fingerprint=fp)
    if loaded is not None:
        warm, hist = loaded
        hist = list(hist)
    else:
        warm, hist = None, []

    def _active_done(w):
        if w is None:
            return 0, True
        act = ~(w.converged | w.aborted)
        return (int(w.prior_iters[act].max()) if act.any()
                else int(config.num_iterations)), act.any()

    done, any_active = _active_done(warm)
    resumed_from = (np.zeros(_n_lanes(w0_stacked), np.int64)
                    if warm is None else warm.prior_iters.copy())
    while any_active and done < config.num_iterations:
        k = min(segment_iters, config.num_iterations - done)
        cfg_k = dataclasses.replace(config, num_iterations=k)
        res = host_agd.run_agd_host_multi(
            smooth_multi, prox_multi, reg_value_multi, w0_stacked,
            cfg_k, smooth_loss_multi=smooth_loss_multi, warm=warm)
        seg_rows = np.asarray(res.loss_history)
        hist.extend(seg_rows.tolist())
        warm = host_agd.multi_warm_state(
            res, prior_iters=(0 if warm is None else warm.prior_iters))
        save_multi_checkpoint(path, warm, np.asarray(hist),
                              fingerprint=fp)
        if seg_rows.shape[0] == 0:
            break
        done, any_active = _active_done(warm)

    if warm is None:  # zero-iteration request on a fresh path
        warm = host_agd.HostMultiWarm.initial(w0_stacked, config)
    return CheckpointedMultiResult(
        weights=warm.x,
        loss_history=(np.asarray(hist) if hist
                      else np.zeros((0, _n_lanes(w0_stacked)))),
        num_iters=warm.prior_iters,
        aborted_non_finite=warm.aborted, converged=warm.converged,
        resumed_from=np.asarray(resumed_from))


def _n_lanes(w0_stacked) -> int:
    return jax.tree_util.tree_leaves(w0_stacked)[0].shape[0]


# ---------------------------------------------------------------------------
# L-BFGS checkpointing: same format discipline (atomic npz, fingerprint,
# terminal semantics) for the quasi-Newton host driver.  The carry is
# larger than AGD's "2 vectors + 3 scalars": weights, gradient, and up to
# m curvature pairs — core.host_lbfgs.HostLBFGSWarm — but the same
# kill/resume contract holds: a resumed chain reproduces the
# uninterrupted run exactly (gradient and pairs carry over, nothing is
# re-evaluated at the junction).


def save_lbfgs_checkpoint(path: str, warm, loss_history=None, *,
                          converged: bool = False,
                          ls_failed: bool = False,
                          aborted: bool = False,
                          fingerprint: Optional[str] = None) -> None:
    """Atomic write of a ``core.host_lbfgs.HostLBFGSWarm`` (+ cumulative
    history).  ``converged``/``ls_failed``/``aborted`` mark a terminal
    checkpoint — resuming is a no-op."""
    payload = {"lbfgs": np.asarray(True)}
    for i, leaf in enumerate(_flat(warm.w)):
        payload[f"w_{i}"] = np.asarray(leaf)
    for i, leaf in enumerate(_flat(warm.g)):
        payload[f"g_{i}"] = np.asarray(leaf)
    payload["f"] = np.asarray(float(warm.f))
    payload["prior_iters"] = np.asarray(int(warm.prior_iters))
    payload["n_pairs"] = np.asarray(len(warm.pairs))
    payload["rho"] = np.asarray([p[2] for p in warm.pairs], np.float64)
    for k, (s, y, _) in enumerate(warm.pairs):
        for i, leaf in enumerate(_flat(s)):
            payload[f"p{k}s_{i}"] = np.asarray(leaf)
        for i, leaf in enumerate(_flat(y)):
            payload[f"p{k}y_{i}"] = np.asarray(leaf)
    payload["converged"] = np.asarray(bool(converged))
    payload["ls_failed"] = np.asarray(bool(ls_failed))
    payload["aborted"] = np.asarray(bool(aborted))
    if fingerprint is not None:
        payload["fingerprint"] = np.asarray(fingerprint)
    payload["loss_history"] = (np.zeros(0) if loss_history is None
                               else np.asarray(loss_history))
    atomic_savez(path, payload)


class LoadedLBFGSCheckpoint(NamedTuple):
    warm: Any  # core.host_lbfgs.HostLBFGSWarm
    loss_history: np.ndarray
    converged: bool
    ls_failed: bool
    aborted: bool
    fingerprint: Optional[str]


def load_lbfgs_checkpoint(path: str, template: Any,
                          expect_fingerprint: Optional[str] = None,
                          ) -> Optional[LoadedLBFGSCheckpoint]:
    """Rebuild an L-BFGS checkpoint; None if absent.  ``template``
    supplies the weight pytree structure (normally ``w0``)."""
    from ..core.host_lbfgs import HostLBFGSWarm

    if not os.path.exists(path):
        return None
    treedef = jax.tree_util.tree_structure(template)
    n = treedef.num_leaves
    data = _Entries(path, read_npz_entries(path))
    if "lbfgs" not in data:
        raise ValueError(
            f"checkpoint at {path!r} is not an L-BFGS checkpoint; "
            "load it with load_checkpoint / load_multi_checkpoint")
    fp = str(data["fingerprint"]) if "fingerprint" in data else None
    if (expect_fingerprint is not None and fp is not None
            and fp != expect_fingerprint):
        raise ValueError(
            f"checkpoint at {path!r} belongs to a different problem "
            "(weight structure or config changed); delete it or use "
            "a different path")

    tree = lambda name: _load_tree(data, treedef, n, name)

    rho = np.asarray(data["rho"])
    pairs = tuple(
        (tree(f"p{k}s"), tree(f"p{k}y"), float(rho[k]))
        for k in range(int(data["n_pairs"])))
    warm = HostLBFGSWarm(
        w=tree("w"), f=float(data["f"]), g=tree("g"), pairs=pairs,
        prior_iters=int(data["prior_iters"]))
    return LoadedLBFGSCheckpoint(
        warm, np.asarray(data["loss_history"]),
        bool(data["converged"]), bool(data["ls_failed"]),
        bool(data["aborted"]), fp)


class CheckpointedLBFGSResult(NamedTuple):
    weights: Any
    loss_history: np.ndarray
    num_iters: int  # TOTAL iterations across all segments
    converged: bool
    ls_failed: bool
    aborted_non_finite: bool
    resumed_from: int


def run_lbfgs_checkpointed(
    objective,
    w0: Any,
    config,
    path: str,
    *,
    segment_iters: int = 10,
    l1_reg: float = 0.0,
) -> CheckpointedLBFGSResult:
    """Host L-BFGS with periodic checkpoints: ``segment_iters``
    iterations per segment, carry persisted after each.  Kill the
    process anywhere; rerunning the same call continues from the last
    completed segment to the same answer as an uninterrupted run
    (``core.host_lbfgs``'s exact-resume contract).

    ``l1_reg > 0`` drives the OWL-QN host twin instead (``objective``
    is then the SMOOTH part; histories hold the full F = f + l1·‖w‖₁).
    ``l1_reg`` participates in the fingerprint, so a checkpoint written
    at one strength cannot silently resume another."""
    from ..core import host_lbfgs

    if segment_iters <= 0:
        raise ValueError("segment_iters must be positive")
    if l1_reg < 0:
        raise ValueError("l1_reg must be >= 0")
    # suffix only for the OWL-QN mode: an l1_reg=0 fingerprint stays
    # byte-identical to pre-upgrade checkpoints, so existing kill/
    # resume chains keep resuming; nonzero strengths still refuse to
    # cross-resume each other (or a smooth run)
    fp = problem_fingerprint(w0, config)
    if l1_reg > 0:
        fp += f"|l1={float(l1_reg)!r}"
    loaded = load_lbfgs_checkpoint(path, w0, expect_fingerprint=fp)
    if loaded is not None:
        warm = loaded.warm
        hist = list(np.asarray(loaded.loss_history))
        if loaded.converged or loaded.ls_failed or loaded.aborted:
            return CheckpointedLBFGSResult(
                weights=warm.w, loss_history=np.asarray(hist),
                num_iters=int(warm.prior_iters),
                converged=loaded.converged, ls_failed=loaded.ls_failed,
                aborted_non_finite=loaded.aborted,
                resumed_from=int(warm.prior_iters))
    else:
        warm = None
        hist = []
    resumed_from = int(warm.prior_iters) if warm is not None else 0

    total = config.num_iterations
    converged = ls_failed = aborted = False
    while True:
        prior = warm.prior_iters if warm is not None else 0
        if warm is not None and prior >= total:
            break
        # a fresh run enters at least once even when total == 0, so the
        # w0 evaluation happens and the return below has a carry
        cap = min(prior + segment_iters, total)
        cfg_k = dataclasses.replace(config, num_iterations=cap)
        if l1_reg > 0:
            res = host_lbfgs.run_owlqn_host(objective, w0, l1_reg,
                                            cfg_k, warm=warm)
        else:
            res = host_lbfgs.run_lbfgs_host(objective, w0, cfg_k,
                                            warm=warm)
        seg_hist = np.asarray(res.loss_history)
        hist.extend(seg_hist.tolist() if not hist
                    else seg_hist[1:].tolist())
        warm = host_lbfgs.HostLBFGSWarm.from_result(
            res, prior_iters=prior)
        converged = bool(res.converged)
        ls_failed = bool(res.ls_failed)
        aborted = bool(res.aborted_non_finite)
        save_lbfgs_checkpoint(path, warm, np.asarray(hist),
                              converged=converged, ls_failed=ls_failed,
                              aborted=aborted, fingerprint=fp)
        if converged or ls_failed or aborted or res.num_iters == 0:
            break

    return CheckpointedLBFGSResult(
        weights=warm.w, loss_history=np.asarray(hist),
        num_iters=int(warm.prior_iters), converged=converged,
        ls_failed=ls_failed, aborted_non_finite=aborted,
        resumed_from=resumed_from)
