"""Structured per-iteration observability.

The reference's entire logging surface is two calls: ``logWarning`` on a
non-finite loss (reference ``AcceleratedGradientDescent.scala:309-312``) and
``logInfo`` with the last 10 losses at completion (``:334-335``) — every
other per-iteration quantity (L, theta, step, restarts) is computed and
discarded.  SURVEY §5 flags that as the metrics gap; the fused loop already
returns those values as ``AGDResult`` diagnostic arrays, and this module
turns them into records and log lines.
"""

from __future__ import annotations

import json
import logging
from typing import Iterator, List, Optional

import numpy as np

from ..obs import schema

logger = logging.getLogger("spark_agd_tpu")


def iteration_records(result, *, run_id: Optional[str] = None,
                      algorithm: str = "agd") -> List[dict]:
    """One dict per executed iteration from an ``AGDResult``: iter (1-based,
    like the reference's nIter), loss, L, theta, step, restarted.

    With ``run_id`` set, each dict is a canonical ``obs.schema``
    iteration record (``schema_version``/``kind``/``run_id``/
    ``algorithm`` added) — the post-hoc twin of the live
    ``telemetry=`` stream, byte-compatible with its JSONL."""
    n = int(result.num_iters)
    hist = np.asarray(result.loss_history)[:n]
    ls = np.asarray(result.diag_l)[:n]
    thetas = np.asarray(result.diag_theta)[:n]
    steps = np.asarray(result.diag_step)[:n]
    restarted = np.asarray(result.diag_restarted)[:n]
    recs = [
        dict(iter=i + 1, loss=float(hist[i]), L=float(ls[i]),
             theta=float(thetas[i]), step=float(steps[i]),
             restarted=bool(restarted[i]))
        for i in range(n)
    ]
    if run_id is not None:
        recs = [schema.iteration_record(run_id, algorithm,
                                        r.pop("iter"), **r)
                for r in recs]
    return recs


def result_run_record(result, *, tool: str = "api.run",
                      algorithm: str = "agd",
                      run_id: Optional[str] = None, **extra) -> dict:
    """The canonical end-of-run ``run`` record for an ``AGDResult``."""
    n = int(result.num_iters)
    hist = np.asarray(result.loss_history)[:n]
    return schema.run_record(
        tool=tool, run_id=run_id, algorithm=algorithm, iters=n,
        final_loss=float(hist[-1]) if n else None,
        converged=bool(result.converged),
        error=("aborted: non-finite loss"
               if bool(result.aborted_non_finite) else None),
        **extra)


def write_result_jsonl(result, path: str, *, tool: str = "api.run",
                       algorithm: str = "agd",
                       run_id: Optional[str] = None) -> str:
    """Persist one completed run as canonical JSONL (the ``run`` record
    followed by its iteration records) — what ``tools/agd_report.py``
    consumes.  Returns the ``run_id``."""
    run_id = run_id or schema.new_run_id()
    with open(path, "a") as f:
        f.write(json.dumps(result_run_record(
            result, tool=tool, algorithm=algorithm,
            run_id=run_id)) + "\n")
        for rec in iteration_records(result, run_id=run_id,
                                     algorithm=algorithm):
            f.write(json.dumps(rec) + "\n")
    return run_id


def log_result(result, *, log: Optional[logging.Logger] = None,
               jsonl: bool = False) -> None:
    """Emit per-iteration lines plus the reference's completion/abort lines.

    ``jsonl=True`` formats each iteration as one JSON object per line (the
    machine-readable channel); default is a readable key=value line.
    """
    log = log or logger
    for rec in iteration_records(result):
        if jsonl:
            log.info(json.dumps(rec))
        else:
            log.info(
                "iter=%d loss=%.6g L=%.4g theta=%.4g step=%.4g%s",
                rec["iter"], rec["loss"], rec["L"], rec["theta"],
                rec["step"], " restart" if rec["restarted"] else "")
    if bool(result.aborted_non_finite):
        # the reference's logWarning on numerical failure (:309-312)
        log.warning("AcceleratedGradientDescent: loss is infinite or NaN; "
                    "aborted after %d iterations", int(result.num_iters))
    n = int(result.num_iters)
    hist = np.asarray(result.loss_history)[:n]
    # the reference's completion line: last 10 losses (:334-335)
    log.info("AcceleratedGradientDescent.run finished. Last 10 losses %s",
             ", ".join(f"{v:.6g}" for v in hist[-10:]))


def make_host_logger(*, log: Optional[logging.Logger] = None,
                     every: int = 1):
    """An ``on_iteration`` callback for ``core.host_agd.run_agd_host``:
    logs one structured line per ``every`` iterations as the run executes
    (the streaming/1B-row regime, where waiting for the end is not an
    option)."""
    log = log or logger

    def on_iteration(carry: dict):
        it = int(carry["prior_iters"])
        # a run's final callback (converged, aborted, or iteration-cap)
        # always logs — an operator tailing the stream must be able to
        # tell "finished" from "hung" regardless of `every`
        final = carry.get("stopped") or carry.get("last")
        if it % every and not final:
            return
        suffix = ""
        if carry.get("aborted"):
            suffix = " ABORTED-nonfinite"
        elif carry.get("stopped"):
            suffix = " converged"
        elif carry.get("last"):
            suffix = " done(iteration cap)"
        log.info("iter=%d loss=%.6g L=%.4g theta=%.4g%s",
                 it, float(carry["loss"]), float(carry["big_l"]),
                 float(carry["theta"]), suffix)

    return on_iteration
