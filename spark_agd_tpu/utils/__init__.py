"""Aux subsystems (SURVEY §5): checkpoint/resume, structured logging,
profiling.  The reference inherits all of this from Spark or omits it; here
each is a small first-class module."""

from .checkpoint import (  # noqa: F401
    CheckpointedLBFGSResult,
    CheckpointedResult,
    fresh_warm_state,
    load_checkpoint,
    load_lbfgs_checkpoint,
    run_agd_checkpointed,
    run_lbfgs_checkpointed,
    save_checkpoint,
    save_lbfgs_checkpoint,
    warm_from_result,
)
from .logging import (  # noqa: F401
    iteration_records,
    log_result,
    make_host_logger,
    result_run_record,
    write_result_jsonl,
)
from .profiling import TimedStats, annotate, timed, timed_stats, trace  # noqa: F401
