"""spark_agd_tpu.utils subpackage."""
