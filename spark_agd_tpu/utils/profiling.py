"""Tracing / profiling hooks (SURVEY §5: the reference has none in-tree and
leans on the Spark UI; the TPU equivalents are the JAX profiler for device
timelines and simple block-until-ready wall timing for iteration rates)."""

from __future__ import annotations

import contextlib
import time
from typing import Callable, List, NamedTuple, Optional, Tuple

import jax


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a JAX profiler trace (XLA ops, TPU timeline) viewable in
    TensorBoard / Perfetto.  Usage::

        with profiling.trace("/tmp/agd-trace"):
            api.run(...)
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (host-side annotation)."""
    return jax.profiler.TraceAnnotation(name)


class OneShotTrace:
    """Capture the FIRST wrapped region into a profiler trace, then
    become a no-op — ``jax.profiler.start_trace`` cannot nest and
    traces are large, so instrumented fits capture exactly one device
    timeline per telemetry object.  ``log_dir=None`` disables (every
    call is a no-op), letting call sites wrap unconditionally::

        capture = profiling.OneShotTrace(telemetry.profile_dir)
        with capture(), telemetry.span("execute"):
            exe(w, dargs)

    ``captured`` holds the log dir after the one capture (else None).
    """

    def __init__(self, log_dir: Optional[str]):
        self.log_dir = log_dir
        self.captured: Optional[str] = None
        self._armed = log_dir is not None

    @contextlib.contextmanager
    def __call__(self):
        if not self._armed:
            yield
            return
        self._armed = False
        with trace(self.log_dir):
            yield
        self.captured = self.log_dir


class TimedStats(NamedTuple):
    """Full repeat statistics from :func:`timed_stats` (seconds)."""

    min_s: float
    median_s: float
    max_s: float
    times: List[float]  # per-repeat, in execution order


def timed_stats(fn: Callable, *args, warmup: int = 1, repeats: int = 3,
                registry=None,
                name: Optional[str] = None) -> Tuple[TimedStats, object]:
    """Wall-clock a jitted callable honestly — ``warmup`` calls absorb
    compilation, then ``repeats`` block-until-ready timings — and return
    the FULL statistics ``(TimedStats(min, median, max, times),
    last_result)`` instead of :func:`timed`'s median-only view.

    ``registry`` (an ``obs.MetricsRegistry``; defaults to the process
    registry) records each repeat under the span ``name`` (default
    ``timed.<fn name>``) — one span event per repeat streams out live
    when the registry is attached to a ``Telemetry`` bus.
    """
    if registry is None:
        from ..obs.registry import default_registry

        registry = default_registry()
    span = registry.span(name or f"timed.{getattr(fn, '__name__', 'fn')}")
    out = None
    for _ in range(max(0, warmup)):
        out = jax.block_until_ready(fn(*args))
    times = []
    for _ in range(max(1, repeats)):
        with span:
            t0 = time.perf_counter()
            out = jax.block_until_ready(fn(*args))
            times.append(time.perf_counter() - t0)
    ordered = sorted(times)
    return TimedStats(min_s=ordered[0],
                      median_s=ordered[len(ordered) // 2],
                      max_s=ordered[-1], times=times), out


def timed(fn: Callable, *args, warmup: int = 1,
          repeats: int = 3) -> Tuple[float, object]:
    """Median-only wrapper over :func:`timed_stats` — the original
    surface, kept signature-compatible: ``(seconds, last_result)``."""
    stats, out = timed_stats(fn, *args, warmup=warmup, repeats=repeats)
    return stats.median_s, out
