"""Numerical-failure debugging: the sanitizer row of SURVEY §5.

The reference's only runtime guard is the NaN/Inf loss check that
aborts the loop (reference ``AcceleratedGradientDescent.scala:309-312``
— carried over as ``core.agd``'s abort flag).  That tells you THAT a
run went non-finite, not WHERE.  Two wrappers, one check set:

- ``checked_smooth(smooth)`` — EAGER wrapper: calls raise
  ``jax.errors.JaxRuntimeError`` naming the first non-finite quantity.
  For host-driven paths (``core.host_agd``, streamed smooths) and
  interactive debugging.  Not jittable: the error check must read a
  concrete value.
- ``checking_smooth(smooth)`` — embedded-check variant for COMPILED
  programs: the checks ride inside the traced computation, and the
  caller functionalizes the WHOLE program with ``checkify.checkify``
  (which handles ``lax.while_loop``), e.g.::

      sm_dbg = checking_smooth(sm)
      run = checkify.checkify(
          jax.jit(lambda w: agd.run_agd(sm_dbg, px, rv, w, cfg)))
      err, res = run(w0)
      err.throw()   # raises with the named failing leaf, or no-ops

The production path stays exactly as compiled — only the wrapped copy
is instrumented.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import checkify


def checking_smooth(smooth: Callable[[Any], Tuple[jax.Array, Any]],
                    name: str = "smooth") -> Callable:
    """``smooth`` with embedded ``checkify.check``s on the loss and every
    gradient leaf (named by pytree key path).  Use inside a program the
    caller wraps with ``checkify.checkify`` — see module docstring."""

    def inner(w):
        loss, grad = smooth(w)
        checkify.check(jnp.all(jnp.isfinite(loss)),
                       f"{name}: loss non-finite")
        for path, leaf in jax.tree_util.tree_flatten_with_path(grad)[0]:
            label = jax.tree_util.keystr(path) or "<root>"
            checkify.check(
                jnp.all(jnp.isfinite(leaf)),
                f"{name}: gradient leaf {label} non-finite")
        return loss, grad

    return inner


def report_numerics_failure(err, telemetry=None, *, source: str = "smooth",
                            **fields) -> None:
    """Raise a checkify ``Error`` the observable AND classifiable way:
    when it carries a failure and a telemetry bus is attached, a
    ``numerics_failure`` record (first failing leaf name parsed from
    the message, plus any locator ``fields`` — ``evaluation=``,
    ``iter=``) is emitted to the same JSONL stream as the metrics
    BEFORE the raise; the raise itself is a typed
    ``resilience.NumericsFailureError``, which the supervisor's
    failure classifier maps to NUMERIC — so a sanitizer hit enters the
    SAME rollback path (last-good warm state, step cut) as the fused
    loop's abort flag, instead of only existing as an event.  The
    ``checking_smooth``-in-compiled-program pattern calls this instead
    of ``err.throw()``::

        err, res = checkified_run(w0)
        debug.report_numerics_failure(err, telemetry)   # raises iff bad
    """
    msg = err.get()
    if msg is None:
        return
    if telemetry is not None:
        telemetry.numerics_failure(msg, source=source, **fields)
    from ..resilience.errors import NumericsFailureError

    raise NumericsFailureError(msg)


def checked_smooth(smooth: Callable[[Any], Tuple[jax.Array, Any]],
                   name: str = "smooth", *, telemetry=None) -> Callable:
    """Eager-raising wrapper around :func:`checking_smooth` — same
    signature as ``smooth``; raises on the first non-finite loss or
    gradient leaf.  For host-driven/streamed paths; for the fused
    compiled loop use :func:`checking_smooth` (module docstring).

    ``telemetry`` (an ``obs.Telemetry``): a failure additionally emits
    one ``numerics_failure`` record (failing leaf name, 1-based
    evaluation index) before raising — sanitizer hits land in the same
    JSONL as the run's metrics instead of only existing as a raise."""
    checked = checkify.checkify(checking_smooth(smooth, name))
    n_evals = itertools.count(1)

    def wrapped(w):
        k = next(n_evals)
        err, out = checked(w)
        report_numerics_failure(err, telemetry, source=name,
                                evaluation=k)
        return out

    return wrapped
