"""Macro-batch streaming: full-batch AGD semantics on larger-than-HBM data.

SURVEY §7 hard part 4: at the 1B-row north-star scale, the dataset cannot
live in device memory, but AGD is a *full-batch* method — every
``applySmooth`` must see every example.  The reference's treeAggregate
seqOp/combOp split (reference ``:196-204``) maps exactly onto streaming:
each macro-batch's jit-compiled kernel is the (vectorised) seqOp, and the
host-side accumulation of ``(Σloss, Σgrad, n)`` across macro-batches is the
combOp — associative sums, one division at the very end (reference ``:207``
semantics preserved bit-for-bit up to summation order).

The streamed smooth is a *host-level* callable (Python loop inside), so it
pairs with ``core.host_agd.run_agd_host`` — the driver-orchestrated twin of
the fused loop — rather than with ``lax.while_loop``.  Counts accumulate as
Python ints (no 2^31 wrap at any scale; see ``ops.losses._count``).
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import tvec
from ..ops.losses import Gradient
from ..ops.sparse import CSRMatrix
from ..parallel import mesh as mesh_lib


def iter_array_batches(X, y, batch_rows: int,
                       mask=None) -> Iterator[Tuple]:
    """Slice in-memory arrays into macro-batches (testing / memmap use —
    np.memmap slices lazily, so this also serves on-disk dense data)."""
    n = X.shape[0]
    for s in range(0, n, batch_rows):
        e = min(s + batch_rows, n)
        yield X[s:e], y[s:e], None if mask is None else mask[s:e]


def iter_csr_batches(indptr, indices, values, n_features: int, y,
                     batch_rows: int, mask=None,
                     with_csc: bool = True) -> Iterator[Tuple]:
    """Slice host CSR arrays into fixed-shape macro-batches.

    XLA compiles ONE kernel per shape, so every batch is padded to the
    same ``(batch_rows, nnz_pad)`` where ``nnz_pad`` is the largest
    per-batch entry count (computed up front from ``indptr``).  Padding
    follows the ops.sparse contract: inert 0.0 entries at the LAST
    row/col slot (ids stay nondecreasing), padded row slots masked 0.
    ``with_csc`` builds each batch's column-sorted twin on the host —
    the per-batch argsort overlaps device compute inside
    :func:`fold_stream`'s double buffering.
    """
    indptr = np.asarray(indptr)
    indices = np.asarray(indices, np.int32)
    values = np.asarray(values)
    y = np.asarray(y)
    n = len(indptr) - 1
    starts = np.arange(0, n, batch_rows)
    if not len(starts):  # empty input: yield nothing, like the dense twin
        return
    nnz_pad = max(1, int(np.max(
        indptr[np.minimum(starts + batch_rows, n)] - indptr[starts])))
    for s in starts.tolist():
        e = min(s + batch_rows, n)
        lo, hi = int(indptr[s]), int(indptr[e])
        k = hi - lo
        rid = np.full(nnz_pad, batch_rows - 1, np.int32)
        cid = np.full(nnz_pad, n_features - 1, np.int32)
        val = np.zeros(nnz_pad, values.dtype)
        rid[:k] = np.repeat(np.arange(e - s, dtype=np.int32),
                            np.diff(indptr[s:e + 1]))
        cid[:k] = indices[lo:hi]
        val[:k] = values[lo:hi]
        csc = {}
        if with_csc:
            order = np.argsort(cid[:k], kind="stable")
            crid = np.full(nnz_pad, batch_rows - 1, np.int32)
            ccid = np.full(nnz_pad, n_features - 1, np.int32)
            cval = np.zeros(nnz_pad, values.dtype)
            crid[:k] = rid[:k][order]
            ccid[:k] = cid[:k][order]
            cval[:k] = val[:k][order]
            csc = dict(csc_row_ids=crid, csc_col_ids=ccid,
                       csc_values=cval)
        Xb = CSRMatrix(rid, cid, val, (batch_rows, int(n_features)),
                       rows_sorted=True, **csc)
        yb = np.zeros(batch_rows, y.dtype)
        yb[:e - s] = y[s:e]
        mb = np.zeros(batch_rows, np.float32)
        mb[:e - s] = (np.ones(e - s, np.float32) if mask is None
                      else np.asarray(mask[s:e], np.float32))
        yield Xb, yb, mb


class StreamingDataset:
    """A re-iterable source of ``(X, y, mask)`` macro-batches.

    ``factory`` is a zero-arg callable returning a fresh iterator — AGD
    evaluates the smooth function 2-3 times per outer iteration, so one-shot
    generators are a footgun this interface rules out.
    """

    def __init__(self, factory: Callable[[], Iterable[Tuple]],
                 batch_rows: Optional[int] = None):
        self._factory = factory
        self.batch_rows = batch_rows

    @classmethod
    def from_arrays(cls, X, y, batch_rows: int, mask=None):
        return cls(lambda: iter_array_batches(X, y, batch_rows, mask),
                   batch_rows)

    @classmethod
    def from_csr(cls, indptr, indices, values, n_features: int, y,
                 batch_rows: int, mask=None, with_csc: bool = True):
        """Macro-batches over host CSR arrays (``data.libsvm.CSRData``'s
        fields) — the sparse twin of ``from_arrays``; see
        :func:`iter_csr_batches` for the fixed-shape padding contract."""
        return cls(lambda: iter_csr_batches(
            indptr, indices, values, n_features, y, batch_rows, mask,
            with_csc), batch_rows)

    def __iter__(self):
        return iter(self._factory())


def make_streaming_smooth(
    gradient: Gradient,
    dataset: StreamingDataset,
    *,
    mesh=None,
    pad_to: Optional[int] = None,
):
    """Build host-level ``(smooth, smooth_loss)`` that stream macro-batches.

    Each batch is (optionally) padded to ``pad_to`` rows so XLA compiles ONE
    kernel shape instead of one per ragged tail, then placed on ``mesh``
    (sharded over its data axis) or the default device.  Returns means, like
    every other smooth builder.
    """

    @jax.jit
    def batch_sums(w, X, y, mask):
        return gradient.batch_loss_and_grad(w, X, y, mask)

    # Loss-only twin: the gradient is a jit *output* in batch_sums, so XLA
    # cannot dead-code-eliminate it there — a separate kernel lets the
    # rmatvec (size-D work per macro-batch) vanish entirely.
    @jax.jit
    def batch_loss_sums(w, X, y, mask):
        ls, _, n = gradient.batch_loss_and_grad(w, X, y, mask)
        return ls, n

    def _place(X, y, mask):
        if isinstance(X, CSRMatrix):
            # iter_csr_batches already padded to fixed shape; just move
            # the leaves (csc twin included) onto the device
            if mesh is not None:
                raise NotImplementedError(
                    "mesh-sharded CSR streaming is not supported yet; "
                    "stream single-device or pre-shard with "
                    "parallel.mesh.shard_csr_batch")
            return (jax.tree_util.tree_map(jnp.asarray, X),
                    jnp.asarray(y), jnp.asarray(mask))
        X = np.asarray(X)
        y = np.asarray(y)
        n = X.shape[0]
        if pad_to is not None and n < pad_to:
            base = np.ones(n, np.float32) if mask is None else \
                np.asarray(mask, np.float32)
            X = np.concatenate(
                [X, np.zeros((pad_to - n,) + X.shape[1:], X.dtype)])
            y = np.concatenate([y, np.zeros(pad_to - n, y.dtype)])
            mask = np.concatenate([base, np.zeros(pad_to - n, np.float32)])
        if mesh is not None:
            return mesh_lib.shard_batch(mesh, X, y, mask)
        m = None if mask is None else jnp.asarray(mask)
        return jnp.asarray(X), jnp.asarray(y), m

    def smooth(w):
        (ls, gs), n = fold_stream(
            batch_sums,
            lambda a, b: [a[0] + b[0], tvec.add(a[1], b[1])],
            _place, dataset, w)
        nf = jnp.asarray(n, ls.dtype)
        return ls / nf, tvec.scale(1.0 / nf, gs)

    def smooth_loss(w):
        (ls,), n = fold_stream(
            batch_loss_sums, lambda a, b: [a[0] + b[0]], _place, dataset, w)
        return ls / jnp.asarray(n, ls.dtype)

    return smooth, smooth_loss


def fold_stream(kernel, combine, place, dataset, w):
    """Stream the dataset through ``kernel(w, X, y, mask) -> (sums…, n)``,
    combining device sums with ``combine`` and counts as host ints
    (immune to integer wrap at 1B rows).

    Transfer/compute overlap (VERDICT r1 weak #5): JAX dispatch is
    asynchronous, so the structure below keeps the device busy —

    - batch i's kernel is dispatched BEFORE batch i+1 is sliced/padded on
      the host and its ``device_put`` issued, so host prep and the H2D
      DMA run while the device computes batch i (one batch of lookahead =
      classic double buffering; peak device memory holds two batches);
    - the per-batch host sync the old loop had (``int(n)`` after every
      kernel) is gone — counts are drained ONCE after the stream, so no
      batch waits for its predecessor's scalar readback.
    """
    it = iter(dataset)
    first = next(it, None)
    if first is None:
        raise ValueError("streaming dataset yielded no batches")
    nxt = place(*first)
    acc = None
    ns = []
    while nxt is not None:
        *sums, n = kernel(w, *nxt)  # async dispatch on batch i
        ns.append(n)
        acc = sums if acc is None else combine(acc, sums)
        b = next(it, None)  # host prep of batch i+1 overlaps device work
        nxt = None if b is None else place(*b)
    return acc, sum(int(x) for x in ns)
