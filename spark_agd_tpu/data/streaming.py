"""Macro-batch streaming: full-batch AGD semantics on larger-than-HBM data.

SURVEY §7 hard part 4: at the 1B-row north-star scale, the dataset cannot
live in device memory, but AGD is a *full-batch* method — every
``applySmooth`` must see every example.  The reference's treeAggregate
seqOp/combOp split (reference ``:196-204``) maps exactly onto streaming:
each macro-batch's jit-compiled kernel is the (vectorised) seqOp, and the
host-side accumulation of ``(Σloss, Σgrad, n)`` across macro-batches is the
combOp — associative sums, one division at the very end (reference ``:207``
semantics preserved bit-for-bit up to summation order).

The streamed smooth is a *host-level* callable (Python loop inside), so it
pairs with ``core.host_agd.run_agd_host`` — the driver-orchestrated twin of
the fused loop — rather than with ``lax.while_loop``.  Counts accumulate as
Python ints (no 2^31 wrap at any scale; see ``ops.losses._count``).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import (Callable, Iterable, Iterator, NamedTuple, Optional,
                    Tuple)

import jax
import jax.numpy as jnp
import numpy as np

from ..core import tvec
from ..ops.losses import Gradient
from ..ops.sparse import CSRMatrix, RowShardedCSR
from ..parallel import dist_smooth, mesh as mesh_lib
from ..resilience import retry as retry_lib
from ..resilience.errors import StreamDataLoss

logger = logging.getLogger("spark_agd_tpu")


def iter_array_batches(X, y, batch_rows: int,
                       mask=None) -> Iterator[Tuple]:
    """Slice in-memory arrays into macro-batches (testing / memmap use —
    np.memmap slices lazily, so this also serves on-disk dense data)."""
    n = X.shape[0]
    for s in range(0, n, batch_rows):
        e = min(s + batch_rows, n)
        yield X[s:e], y[s:e], None if mask is None else mask[s:e]


def _max_batch_nnz(indptr, batch_rows: int) -> int:
    """Largest entry count of any ``batch_rows``-row slice — the one
    batching-boundary computation, shared by the padding loop and the
    ``from_libsvm_parts`` shape inference so they cannot disagree."""
    indptr = np.asarray(indptr)
    n = len(indptr) - 1
    starts = np.arange(0, n, batch_rows)
    if not len(starts):
        return 0
    return max(1, int(np.max(
        indptr[np.minimum(starts + batch_rows, n)] - indptr[starts])))


def iter_csr_batches(indptr, indices, values, n_features: int, y,
                     batch_rows: int, mask=None,
                     with_csc="lazy",
                     nnz_pad: Optional[int] = None) -> Iterator[Tuple]:
    """Slice host CSR arrays into fixed-shape macro-batches.

    XLA compiles ONE kernel per shape, so every batch is padded to the
    same ``(batch_rows, nnz_pad)`` — by default the largest per-batch
    entry count (computed up front from ``indptr``); pass ``nnz_pad``
    explicitly when batches from SEVERAL sources must share one compiled
    shape (``StreamingDataset.from_libsvm_parts``).  Padding follows the
    ops.sparse contract: inert 0.0 entries at the LAST row/col slot (ids
    stay nondecreasing), padded row slots masked 0.

    ``with_csc="lazy"`` (default) MARKS each batch as wanting the
    column-sorted twin (``CSRMatrix.want_csc``) and lets placement
    provide it the cheap way for each path: MESH streaming's
    ``shard_csr_batch`` builds per-shard twins itself (a global one
    would be argsort work thrown away), and single-device placement
    materializes the twin ON DEVICE (overlapped with compute by
    :func:`fold_stream`'s double buffering).  ``True`` builds each
    batch's twin eagerly on the host — useful to move the argsort off
    the device when host cores are idle.  ``False`` disables twins
    (gradient falls back to scatter-add).
    """
    indptr = np.asarray(indptr)
    indices = np.asarray(indices, np.int32)
    values = np.asarray(values)
    y = np.asarray(y)
    n = len(indptr) - 1
    starts = np.arange(0, n, batch_rows)
    if not len(starts):  # empty input: yield nothing, like the dense twin
        return
    max_batch_nnz = _max_batch_nnz(indptr, batch_rows)
    if nnz_pad is None:
        nnz_pad = max_batch_nnz
    elif max_batch_nnz > nnz_pad:
        raise ValueError(
            f"a macro-batch holds {max_batch_nnz} entries > nnz_pad="
            f"{nnz_pad}; raise nnz_pad (one compiled shape must fit "
            f"every batch — from_libsvm_parts callers: pass nnz_pad "
            f"sized for the densest part)")
    for s in starts.tolist():
        e = min(s + batch_rows, n)
        lo, hi = int(indptr[s]), int(indptr[e])
        k = hi - lo
        rid = np.full(nnz_pad, batch_rows - 1, np.int32)
        cid = np.full(nnz_pad, n_features - 1, np.int32)
        val = np.zeros(nnz_pad, values.dtype)
        rid[:k] = np.repeat(np.arange(e - s, dtype=np.int32),
                            np.diff(indptr[s:e + 1]))
        cid[:k] = indices[lo:hi]
        val[:k] = values[lo:hi]
        csc = {}
        if with_csc == "lazy":
            csc = dict(want_csc=True)
        elif with_csc:
            order = np.argsort(cid[:k], kind="stable")
            crid = np.full(nnz_pad, batch_rows - 1, np.int32)
            ccid = np.full(nnz_pad, n_features - 1, np.int32)
            cval = np.zeros(nnz_pad, values.dtype)
            crid[:k] = rid[:k][order]
            ccid[:k] = cid[:k][order]
            cval[:k] = val[:k][order]
            csc = dict(csc_row_ids=crid, csc_col_ids=ccid,
                       csc_values=cval)
        Xb = CSRMatrix(rid, cid, val, (batch_rows, int(n_features)),
                       rows_sorted=True, **csc)
        yb = np.zeros(batch_rows, y.dtype)
        yb[:e - s] = y[s:e]
        mb = np.zeros(batch_rows, np.float32)
        mb[:e - s] = (np.ones(e - s, np.float32) if mask is None
                      else np.asarray(mask[s:e], np.float32))
        yield Xb, yb, mb


@dataclasses.dataclass(frozen=True)
class QuarantinePolicy:
    """When may a streamed epoch continue after poisoned shards?

    A shard that still fails parse/validation after its retry budget is
    QUARANTINED: recorded as a typed ``shard_quarantine`` telemetry
    record, skipped for the rest of the process's life (sticky — the
    batch sequence must be identical on every subsequent pass or the
    mid-epoch cursor would replay different math), and the epoch
    continues degraded — the data-plane analogue of
    ``resilience.degrade``.  ``min_data_fraction`` is the honesty
    floor: once fewer than this fraction of shards is healthy the
    stream refuses with a typed
    :class:`~spark_agd_tpu.resilience.errors.StreamDataLoss` instead
    of silently fitting a sliver of the data."""

    min_data_fraction: float = 0.5

    def __post_init__(self):
        if not 0.0 <= self.min_data_fraction <= 1.0:
            raise ValueError("min_data_fraction must be in [0, 1]")


class StreamCursor(NamedTuple):
    """Mid-epoch resume point: which pass (since the last boundary
    checkpoint), which batch within it, plus the accumulator carry —
    everything needed to continue a streamed smooth evaluation from
    the last committed batch instead of restarting the epoch.

    ``pass_offset`` counts smooth/smooth-loss PASSES begun since the
    last boundary commit (a resumed process replays the boundary warm
    state deterministically, so its pass counter re-aligns);
    ``batch_index`` is the number of batches already folded into
    ``acc_leaves``; ``n`` is the host-side row count so far.  Leaves
    round-trip through npz as exact bytes, so a resumed pass is
    bit-identical to the uninterrupted one (pinned in tier-1)."""

    pass_offset: int
    batch_index: int
    n: int
    acc_leaves: Tuple[np.ndarray, ...]


# npz entry names of an encoded cursor — all under the ``stream_``
# namespace ``utils.checkpoint`` reserves for rider entries
_CUR_PASS = "stream_pass"
_CUR_BATCH = "stream_batch"
_CUR_N = "stream_n"
_CUR_LEN = "stream_acc_len"
_CUR_ACC = "stream_acc_"


def cursor_to_extra(cursor: StreamCursor) -> dict:
    """Encode a cursor as checkpoint rider entries (plain arrays)."""
    extra = {_CUR_PASS: np.asarray(int(cursor.pass_offset)),
             _CUR_BATCH: np.asarray(int(cursor.batch_index)),
             _CUR_N: np.asarray(int(cursor.n), np.int64),
             _CUR_LEN: np.asarray(len(cursor.acc_leaves))}
    for i, leaf in enumerate(cursor.acc_leaves):
        extra[f"{_CUR_ACC}{i}"] = np.asarray(leaf)
    return extra


def cursor_from_extras(extras) -> Optional[StreamCursor]:
    """Decode the cursor out of loaded checkpoint extras; None when the
    entries are absent or torn (a partial rider means the epoch restarts
    from the boundary — correct, just slower)."""
    if not extras or _CUR_PASS not in extras:
        return None
    try:
        k = int(extras[_CUR_LEN])
        leaves = tuple(np.asarray(extras[f"{_CUR_ACC}{i}"])
                       for i in range(k))
        return StreamCursor(int(extras[_CUR_PASS]),
                            int(extras[_CUR_BATCH]),
                            int(extras[_CUR_N]), leaves)
    except KeyError:
        return None


class StreamCheckpoint:
    """The mid-epoch commit protocol between :func:`fold_stream` and an
    ``AutoCheckpointer`` (or ``DistributedCheckpointer``): every
    ``every_batches`` folded batches the current :class:`StreamCursor`
    is force-saved as rider entries on the LAST BOUNDARY warm state
    (``AutoCheckpointer.update_stream``), so a preemption mid-pass
    resumes from the boundary and replays forward to the cursor —
    skipping the already-committed batches without re-running their
    kernels — instead of restarting the epoch.

    Wiring: constructing this sets ``checkpointer.stream_hook = self``;
    the checkpointer then reports boundary commits (which reset the
    pass counter and invalidate any pending cursor) and hands over
    loaded rider entries (:meth:`adopt`) whether ``load()`` ran before
    or after construction.  ``on_commit(count)`` (optional) fires after
    each durable commit — the stream drill's SIGKILL trigger."""

    def __init__(self, checkpointer, *, every_batches: int,
                 on_commit: Optional[Callable[[int], None]] = None):
        if every_batches < 1:
            raise ValueError("every_batches must be >= 1")
        self.checkpointer = checkpointer
        self.every_batches = int(every_batches)
        self.on_commit = on_commit
        self.commits = 0
        self._pass = 0  # passes begun since the last boundary commit
        self._pending: Optional[StreamCursor] = None
        checkpointer.stream_hook = self
        if getattr(checkpointer, "loaded_extras", None):
            self.adopt(checkpointer.loaded_extras)

    def begin_pass(self) -> Tuple[int, Optional[StreamCursor]]:
        """Start one streamed pass: returns ``(ordinal, cursor)`` where
        the cursor is non-None exactly when this pass is the one a
        loaded checkpoint interrupted (consumed once)."""
        ordinal = self._pass
        self._pass += 1
        cur = None
        if self._pending is not None \
                and self._pending.pass_offset == ordinal:
            cur = self._pending
            self._pending = None
        return ordinal, cur

    def maybe_commit(self, ordinal: int, batch_index: int, acc,
                     ns) -> bool:
        """Commit the cursor when the batch cadence is due.  ``acc`` is
        the live accumulator (any pytree; leaves are pulled to host
        arrays — this is the one sync point of a streamed pass), ``ns``
        the per-batch count list."""
        if batch_index % self.every_batches:
            return False
        leaves = tuple(np.asarray(x)
                       for x in jax.tree_util.tree_leaves(acc))
        cur = StreamCursor(int(ordinal), int(batch_index),
                           sum(int(x) for x in ns), leaves)
        if not self.checkpointer.update_stream(cursor_to_extra(cur)):
            return False  # no boundary carry yet to anchor the cursor
        self.commits += 1
        if self.on_commit is not None:
            self.on_commit(self.commits)
        return True

    # -- AutoCheckpointer hook interface ----------------------------------
    def on_boundary(self) -> None:
        """A boundary commit landed: the carry is exact again, so the
        pass counter resets and any not-yet-consumed cursor is stale.
        A boundary seen before any pass began (the supervisor seeding
        its checkpointer right after load) keeps the pending cursor —
        nothing has been replayed yet."""
        if self._pass > 0:
            self._pending = None
        self._pass = 0

    def adopt(self, extras) -> None:
        """Arm the pending cursor from loaded checkpoint extras."""
        cur = cursor_from_extras(extras)
        if cur is not None:
            self._pending = cur


class StreamingDataset:
    """A re-iterable source of ``(X, y, mask)`` macro-batches.

    ``factory`` is a zero-arg callable returning a fresh iterator — AGD
    evaluates the smooth function 2-3 times per outer iteration, so one-shot
    generators are a footgun this interface rules out.
    """

    def __init__(self, factory: Callable[[], Iterable[Tuple]],
                 batch_rows: Optional[int] = None):
        self._factory = factory
        self.batch_rows = batch_rows
        # path -> reason for shards the hardened reader has poisoned-out
        # (``from_libsvm_parts(quarantine=...)``); empty for in-memory
        # sources
        self.quarantined: dict = {}

    @classmethod
    def from_arrays(cls, X, y, batch_rows: int, mask=None):
        return cls(lambda: iter_array_batches(X, y, batch_rows, mask),
                   batch_rows)

    @classmethod
    def from_csr(cls, indptr, indices, values, n_features: int, y,
                 batch_rows: int, mask=None, with_csc="lazy",
                 nnz_pad: Optional[int] = None):
        """Macro-batches over host CSR arrays (``data.libsvm.CSRData``'s
        fields) — the sparse twin of ``from_arrays``; see
        :func:`iter_csr_batches` for the fixed-shape padding contract."""
        return cls(lambda: iter_csr_batches(
            indptr, indices, values, n_features, y, batch_rows, mask,
            with_csc, nnz_pad=nnz_pad), batch_rows)

    @classmethod
    def from_libsvm_parts(cls, paths, n_features: int, batch_rows: int,
                          with_csc="lazy",
                          nnz_pad: Optional[int] = None,
                          binarize_labels: bool = True,
                          retries=None, telemetry=None,
                          validate=False,
                          quarantine=None,
                          read_timeout: Optional[float] = None,
                          chaos=None):
        """Stream LIBSVM partition files (e.g. a Spark job's part-*
        output — the north star's ingest seam) as fixed-shape CSR
        macro-batches WITHOUT ever materializing the full dataset: one
        part is parsed (C++ parser, Python fallback) while the previous
        part's batches run, and each re-iteration re-reads from disk.

        All parts share one compiled kernel shape, so ``nnz_pad`` must
        bound every batch; by default it is sized from the first
        NON-EMPTY part (its max batch nnz, +25% headroom, lane-rounded;
        the part's parse is cached and consumed by the first iteration,
        not repeated).  A later, denser part then raises mid-stream with
        instructions — pass ``nnz_pad`` explicitly when part density
        varies.  ``n_features`` is required: parts must agree on the
        feature space (per-part inference would disagree on trailing
        sparse columns), and out-of-range indices fail at parse time
        rather than silently clamping inside the compiled gather.

        Fault hardening (the streamed smooth re-reads every part EVERY
        evaluation, multiplying exposure to flaky storage):

        - ``retries`` (a ``resilience.RetryPolicy``, default
          ``ingest.DEFAULT_READ_RETRIES``): each shard read runs under
          the shared retry engine — transient IO errors back off and
          re-read; each retry logs and (with ``telemetry``) emits a
          ``recovery`` record.
        - ``read_timeout`` (seconds per ATTEMPT): overlays
          ``attempt_timeout`` on the policy, so a reader that HANGS
          (NFS stall, wedged parser) raises a TRANSIENT
          ``AttemptTimeout`` instead of wedging the epoch.
        - ``validate`` (``False`` / ``"raise"`` / ``"drop"``): the
          ``ingest`` validation policy per shard — ``"raise"`` = typed
          ``DataValidationError`` (FATAL) on the first bad row,
          ``"drop"`` = discard invalid rows, log, and count them on the
          ``data.invalid_records`` telemetry counter.
        - ``quarantine`` (``True`` / :class:`QuarantinePolicy` /
          ``None`` = off): a shard STILL failing after its retry budget
          is quarantined (typed ``shard_quarantine`` record; sticky on
          ``dataset.quarantined``) and the epoch continues degraded —
          until fewer than ``min_data_fraction`` of shards survive, at
          which point the stream refuses with
          :class:`~spark_agd_tpu.resilience.errors.StreamDataLoss`.
        - ``chaos`` (a ``resilience.chaos.ChaosSchedule``): fault
          injection for the drill — ``before_shard`` fires inside the
          retried read, so ``slow_reader``/``hang_reader`` sleeps run
          under the watchdog and ``corrupt_shard`` garbles the file
          before the parse that discovers it.
        """
        from . import ingest
        from .. import native
        from . import libsvm

        paths = list(paths)
        if not paths:
            raise ValueError("from_libsvm_parts needs at least one path")
        if validate not in (False, "raise", "drop"):
            raise ValueError(
                f"validate must be False, 'raise', or 'drop'; "
                f"got {validate!r}")
        if quarantine is True:
            quarantine = QuarantinePolicy()
        policy = retries if retries is not None \
            else ingest.DEFAULT_READ_RETRIES
        if read_timeout is not None:
            policy = dataclasses.replace(
                policy, attempt_timeout=float(read_timeout))
        quarantined: dict = {}
        visit = [0]  # cumulative shard-visit index (chaos at_iter axis)

        def parse_part(path, visit_index=0, use_chaos=True):
            """ONE attempt at one shard: chaos hook (inside the retry
            loop, under the watchdog), parse, native-fallback
            telemetry, index-range check, validation policy."""
            if use_chaos and chaos is not None:
                chaos.before_shard(visit_index, path=path)
            d = libsvm.load_libsvm(path, n_features=n_features)
            if telemetry is not None:
                reason = native.pop_fallback_event("libsvm_parser.so")
                if reason:
                    telemetry.recovery(
                        action="native_fallback", reason=reason,
                        source="streaming")
            if len(d.indices) and int(d.indices.max()) >= n_features:
                raise ValueError(
                    f"{path}: feature index {int(d.indices.max())} >= "
                    f"n_features={n_features} — an undersized feature "
                    f"space would silently clamp/drop entries in the "
                    f"compiled gather/scatter")
            if validate:
                mask = libsvm.invalid_row_mask(d, n_features)
                n_bad = int(mask.sum())
                if n_bad and validate == "raise":
                    raise libsvm.DataValidationError(
                        path, libsvm.describe_invalid(d, mask))
                if n_bad:
                    logger.warning(
                        "%s: dropping %d invalid row(s) (non-finite "
                        "features/labels or out-of-range indices)",
                        path, n_bad)
                    if telemetry is not None:
                        telemetry.registry.counter(
                            "data.invalid_records").inc(n_bad)
                    d = libsvm.drop_rows(d, mask)
            y = d.binarized_labels() if binarize_labels else d.labels
            return d.indptr, d.indices, d.values, y.astype(np.float32)

        def load_part(path):
            """One shard under the full retry/quarantine contract;
            None = quarantined (skip), any raise = FATAL for the
            epoch."""
            vi = visit[0]
            visit[0] += 1
            attempts = [1]

            def on_retry(n_failures, exc, delay):
                attempts[0] = n_failures + 1
                logger.warning(
                    "stream shard read failed (%s: %s); retry %d/%d "
                    "in %.2fs", type(exc).__name__, exc, n_failures,
                    policy.max_attempts - 1, delay)

            try:
                return retry_lib.call_with_retry(
                    parse_part, path, visit_index=vi, policy=policy,
                    label="stream_shard", telemetry=telemetry,
                    on_retry=on_retry)
            except Exception as e:  # noqa: BLE001 — policy applied below
                if quarantine is None:
                    raise
                quarantined[path] = f"{type(e).__name__}: {e}"
                healthy = len(paths) - len(quarantined)
                frac = healthy / len(paths)
                logger.warning(
                    "quarantining shard %s after %d attempt(s): %s "
                    "(%d/%d shards healthy)", path, attempts[0],
                    quarantined[path], healthy, len(paths))
                if telemetry is not None:
                    telemetry.shard_quarantine(
                        shard=path, reason=quarantined[path],
                        attempts=attempts[0],
                        shard_index=paths.index(path),
                        healthy=healthy, total=len(paths),
                        data_fraction=frac, source="streaming")
                if frac < quarantine.min_data_fraction:
                    raise StreamDataLoss(
                        healthy, len(paths),
                        quarantine.min_data_fraction) from e
                return None

        first_cache = {}
        if nnz_pad is None:
            # shape inference runs OUTSIDE the chaos/quarantine path:
            # construction fails loudly on unreadable data rather than
            # silently sizing the kernel off a degraded subset
            for path in paths:  # first NON-EMPTY part sizes the shape
                arrays = retry_lib.call_with_retry(
                    parse_part, path, use_chaos=False, policy=policy,
                    label="stream_shard", telemetry=telemetry)
                m0 = _max_batch_nnz(arrays[0], batch_rows)
                if m0:
                    first_cache[path] = arrays
                    nnz_pad = -(-int(m0 * 1.25) // 128) * 128
                    break
            else:
                raise ValueError("all parts are empty")

        def factory():
            for path in paths:
                if path in quarantined:  # sticky: stable batch sequence
                    continue
                # the inference parse is reused exactly once (first pass)
                arrays = first_cache.pop(path, None)
                if arrays is None:
                    arrays = load_part(path)
                if arrays is None:
                    continue
                yield from iter_csr_batches(
                    *arrays[:3], n_features, arrays[3], batch_rows,
                    with_csc=with_csc, nnz_pad=nnz_pad)

        ds = cls(factory, batch_rows)
        ds.quarantined = quarantined
        return ds

    def __iter__(self):
        return iter(self._factory())


def _make_placer(mesh, pad_to, csr_nnz_per_shard):
    """The shared macro-batch placement closure: pad to one compiled
    shape, put on the device or shard over the mesh (dense via
    ``shard_batch``, CSR via nnz-budgeted ``shard_csr_batch``), and
    materialize a wanted-but-absent CSC twin on device (r2 ADVICE — a
    lazy twin must not silently fall back to scatter-add)."""
    budget = [csr_nnz_per_shard]  # resolved from the first batch
    warned_eager_twin = []  # warn once per smooth, not per batch

    def _place(X, y, mask):
        if isinstance(X, CSRMatrix):
            if mesh is not None:
                # row-shard this macro-batch like the in-memory sparse
                # mesh path; the fixed budget keeps one kernel shape
                if X.has_csc and not warned_eager_twin:
                    warned_eager_twin.append(True)
                    import warnings

                    warnings.warn(
                        "mesh CSR streaming with an EAGER per-batch CSC "
                        "twin: the sharder rebuilds per-shard twins and "
                        "discards the global one — build the dataset "
                        "with with_csc='lazy' to skip the wasted "
                        "per-batch argsort", stacklevel=2)
                if budget[0] is None:
                    n_shards = mesh.shape[mesh_lib.DATA_AXIS]
                    budget[0] = max(128, -(-int(X.nnz * 1.25 / n_shards)
                                           // 128) * 128)
                b = mesh_lib.shard_csr_batch(mesh, X, y, mask,
                                             nnz_per_shard=budget[0])
                return b.X, b.y, b.mask
            # iter_csr_batches already padded to fixed shape; move the
            # leaves onto the device and, when the batch WANTS a CSC
            # twin it doesn't carry (with_csc="lazy"), materialize it
            # there — an on-device argsort per batch, overlapped with
            # compute by fold_stream's double buffering; without this
            # the gradient would silently take the slow scatter-add
            # path (r2 ADVICE)
            Xd = jax.tree_util.tree_map(jnp.asarray, X)
            if Xd.want_csc and not Xd.has_csc:
                Xd = Xd.with_csc()
            return Xd, jnp.asarray(y), jnp.asarray(mask)
        X = np.asarray(X)
        y = np.asarray(y)
        n = X.shape[0]
        if pad_to is not None and n < pad_to:
            base = np.ones(n, np.float32) if mask is None else \
                np.asarray(mask, np.float32)
            X = np.concatenate(
                [X, np.zeros((pad_to - n,) + X.shape[1:], X.dtype)])
            y = np.concatenate([y, np.zeros(pad_to - n, y.dtype)])
            mask = np.concatenate([base, np.zeros(pad_to - n, np.float32)])
        if mesh is not None:
            return mesh_lib.shard_batch(mesh, X, y, mask)
        m = None if mask is None else jnp.asarray(mask)
        return jnp.asarray(X), jnp.asarray(y), m

    return _place


def make_streaming_smooth(
    gradient: Gradient,
    dataset: StreamingDataset,
    *,
    mesh=None,
    pad_to: Optional[int] = None,
    csr_nnz_per_shard: Optional[int] = None,
    prefetch: int = 0,
    stream_ckpt=None,
    telemetry=None,
):
    """Build host-level ``(smooth, smooth_loss)`` that stream macro-batches.

    ``prefetch`` (default 0 = off): background-thread ingest depth for
    the fold — see :func:`fold_stream`; batch k+1's host read/parse
    overlaps batch k's device compute.

    ``stream_ckpt`` (a :class:`StreamCheckpoint`): mid-epoch
    checkpointing — each smooth/smooth-loss pass registers with the
    hook and commits its cursor on the batch cadence, so a preemption
    mid-pass resumes from the last committed batch (see
    :func:`fold_stream`).  Host AGD interleaves ``smooth`` and
    ``smooth_loss`` calls deterministically, so the two share ONE pass
    counter — replay re-issues the identical pass sequence and the
    armed cursor lands in the right pass.

    ``telemetry``: one ``stream_epoch`` record per completed pass
    (batches, rows, wall/stall seconds, quarantine count) and one
    ``recovery(action="stream_resume")`` when a pass consumed a
    cursor.

    Each batch is (optionally) padded to ``pad_to`` rows so XLA compiles ONE
    kernel shape instead of one per ragged tail, then placed on ``mesh``
    (sharded over its data axis) or the default device.  Returns means, like
    every other smooth builder.

    Sparse + mesh (the north-star regime: more sparse rows than the pod's
    HBM): each CSR macro-batch is row-sharded over the mesh's data axis
    (nnz-balanced, ``parallel.mesh.shard_csr_batch``) and evaluated by the
    same shard_map+psum kernel as the in-memory sparse mesh path.  One
    compiled shape serves every batch: shards pad to a fixed
    ``csr_nnz_per_shard`` budget — default ``1.25 x batch_nnz / n_shards``
    lane-rounded, which covers the greedy balancer's worst case
    (mean + heaviest row) unless one row dominates the batch; a batch
    that cannot fit raises with the knob's name.  Build the dataset with
    ``with_csc="lazy"`` for this path: per-shard column-sorted twins are
    built by the sharder, so an eager global twin is per-batch argsort
    work thrown away.
    """

    @jax.jit
    def batch_sums(w, X, y, mask):
        if isinstance(X, RowShardedCSR):
            return _csr_mesh_sums(w, X, y, mask, with_grad=True)
        return gradient.batch_loss_and_grad(w, X, y, mask)

    # Loss-only twin: the gradient is a jit *output* in batch_sums, so XLA
    # cannot dead-code-eliminate it there — a separate kernel lets the
    # rmatvec (size-D work per macro-batch) vanish entirely.
    @jax.jit
    def batch_loss_sums(w, X, y, mask):
        if isinstance(X, RowShardedCSR):
            ls, n = _csr_mesh_sums(w, X, y, mask, with_grad=False)
            return ls, n
        ls, _, n = gradient.batch_loss_and_grad(w, X, y, mask)
        return ls, n

    def _csr_mesh_sums(w, X, y, mask, *, with_grad):
        # trace-time dispatch: the shard_map wrapper is built once per
        # compiled shape (dist_smooth.csr_shard_sums docstring)
        ev = dist_smooth.csr_shard_sums(
            gradient, X, y, mask, mesh, mesh_lib.DATA_AXIS,
            with_grad=with_grad)
        return ev(w, *dist_smooth.csr_shard_args(X, y, mask))

    _place = _make_placer(mesh, pad_to, csr_nnz_per_shard)
    pass_counter = [0]  # completed passes, for the stream_epoch record

    def _emit_pass(stats):
        pass_counter[0] += 1
        if telemetry is None or not stats:
            return
        resumed = stats.get("resumed_from_batch")
        if resumed is not None:
            telemetry.recovery(
                action="stream_resume", resumed_from_batch=int(resumed),
                source="streaming")
        pass_s = stats.get("pass_s", 0.0)
        stall_s = stats.get("stall_s", 0.0)
        extra = {}
        if resumed is not None:
            extra["resumed_from_batch"] = int(resumed)
        telemetry.stream_epoch(
            epoch=pass_counter[0], batches=int(stats.get("batches", 0)),
            rows=int(stats.get("rows", 0)), pass_s=float(pass_s),
            stall_s=float(stall_s),
            stall_fraction=float(stall_s / pass_s) if pass_s > 0 else 0.0,
            skipped_batches=int(stats.get("skipped_batches", 0)),
            quarantined=len(getattr(dataset, "quarantined", None) or {}),
            prefetch=int(prefetch), source="streaming", **extra)

    def smooth(w):
        treedef = jax.tree_util.tree_structure(w)

        def unflatten(leaves):
            # [Σloss] + grad leaves; reject a cursor whose leaf count
            # doesn't match this w's structure (stale rider)
            if len(leaves) != 1 + treedef.num_leaves:
                return None
            return [jnp.asarray(leaves[0]),
                    jax.tree_util.tree_unflatten(
                        treedef, [jnp.asarray(x) for x in leaves[1:]])]

        stats: dict = {}
        (ls, gs), n = fold_stream(
            batch_sums,
            lambda a, b: [a[0] + b[0], tvec.add(a[1], b[1])],
            _place, dataset, w, prefetch=prefetch,
            stream_ckpt=stream_ckpt, acc_unflatten=unflatten,
            stats=stats)
        _emit_pass(stats)
        nf = jnp.asarray(n, ls.dtype)
        return ls / nf, tvec.scale(1.0 / nf, gs)

    def smooth_loss(w):
        def unflatten(leaves):
            if len(leaves) != 1:
                return None
            return [jnp.asarray(leaves[0])]

        stats: dict = {}
        (ls,), n = fold_stream(
            batch_loss_sums, lambda a, b: [a[0] + b[0]], _place, dataset,
            w, prefetch=prefetch, stream_ckpt=stream_ckpt,
            acc_unflatten=unflatten, stats=stats)
        _emit_pass(stats)
        return ls / jnp.asarray(n, ls.dtype)

    return smooth, smooth_loss


def make_streaming_eval_multi(
    gradient: Gradient,
    dataset: StreamingDataset,
    *,
    mesh=None,
    pad_to: Optional[int] = None,
    csr_nnz_per_shard: Optional[int] = None,
    with_grad: bool = True,
):
    """Evaluate K weight vectors over ONE pass of the stream.

    ``eval_multi(W_stacked) -> (mean_losses, mean_grads)`` where
    ``W_stacked`` has a leading lane axis (``(K, D)`` array or a pytree
    of stacked leaves, e.g. a sweep result's ``res.weights``);
    ``mean_losses`` is ``(K,)`` and ``mean_grads`` keeps the lane axis.
    ``with_grad=False`` returns ``(K,)`` losses only — the gradient
    work (the size-D rmatvec per lane) vanishes from the compiled
    kernel, the right mode for validation scoring.

    This is the streaming member of the grid-fit family: the mesh sweep
    (``parallel.grid``) trains K lanes on in-HBM shards; this scores K
    candidates (a regularization path, CV refits) on data LARGER than
    HBM, reading the stream ONCE for all lanes instead of K times —
    per macro-batch the K margin products fuse into one
    ``(rows, D) @ (D, K)`` contraction, the same MXU batching the
    in-memory sweep gets.  Composes with ``mesh`` exactly like
    ``make_streaming_smooth`` (dense GSPMD / CSR shard_map+psum).
    """
    _place = _make_placer(mesh, pad_to, csr_nnz_per_shard)

    @jax.jit
    def batch_sums(W, X, y, mask):
        if isinstance(X, RowShardedCSR):
            ev = dist_smooth.csr_shard_sums(
                gradient, X, y, mask, mesh, mesh_lib.DATA_AXIS,
                with_grad=True, n_lanes=True)
            return ev(W, *dist_smooth.csr_shard_args(X, y, mask))
        ls, gs, n = jax.vmap(
            lambda wv: gradient.batch_loss_and_grad(wv, X, y, mask))(W)
        return ls, gs, n[0]  # count is mask-only: identical per lane

    @jax.jit
    def batch_loss_sums(W, X, y, mask):
        if isinstance(X, RowShardedCSR):
            ev = dist_smooth.csr_shard_sums(
                gradient, X, y, mask, mesh, mesh_lib.DATA_AXIS,
                with_grad=False, n_lanes=True)
            return ev(W, *dist_smooth.csr_shard_args(X, y, mask))
        ls, _, n = jax.vmap(
            lambda wv: gradient.batch_loss_and_grad(wv, X, y, mask))(W)
        return ls, n[0]

    def eval_multi(W):
        W = jax.tree_util.tree_map(jnp.asarray, W)
        if with_grad:
            (ls, gs), n = fold_stream(
                batch_sums,
                lambda a, b: [a[0] + b[0], tvec.add(a[1], b[1])],
                _place, dataset, W)
            nf = jnp.asarray(n, ls.dtype)
            return ls / nf, tvec.scale(1.0 / nf, gs)
        (ls,), n = fold_stream(
            batch_loss_sums, lambda a, b: [a[0] + b[0]], _place,
            dataset, W)
        return ls / jnp.asarray(n, ls.dtype)

    return eval_multi


class _Prefetcher:
    """Bounded background ingest: a daemon thread pulls raw batches off
    the iterator into a ``queue.Queue(maxsize=depth)`` so batch k+1's
    host-side read/parse/pad (the expensive part of ``next()`` for disk
    and LibSVM sources) overlaps batch k's device compute INSTEAD of
    serializing after it.  Placement (``device_put``) stays on the
    consuming thread — JAX dispatch ordering is per-thread, and the
    queue bound caps host memory at ``depth`` raw batches.  The sentinel
    marks exhaustion; a producer exception is re-raised at the consumer's
    next pull, not swallowed.

    Shutdown contract (:meth:`close`): every ``put`` is a bounded-wait
    loop on a stop event, so a consumer that ABANDONS the stream
    mid-pass (kernel raised, preemption unwinding) can always stop the
    pump even when the queue is full — the pump can never deadlock
    holding a batch, and ``close`` joins the thread (with timeout)
    instead of leaking it.  ``close`` never raises: it runs in the
    consumer's ``finally`` and must not mask the original exception —
    pump-side errors still surface through :meth:`__call__`."""

    _END = object()

    def __init__(self, it, depth: int):
        import queue
        import threading

        self._queue_mod = queue
        self._q = queue.Queue(maxsize=depth)
        self._err = None
        self._stop = threading.Event()

        def pump():
            try:
                for b in it:
                    while not self._stop.is_set():
                        try:
                            self._q.put(b, timeout=0.05)
                            break
                        except queue.Full:
                            continue
                    if self._stop.is_set():
                        return
            except BaseException as e:  # noqa: BLE001 — relayed, below
                self._err = e
            finally:
                # the sentinel must land even when the consumer stopped
                # reading — but a live consumer may still be draining a
                # full queue, so eviction (dropping a real batch to make
                # room) is legal ONLY after the stop flag is set
                while True:
                    try:
                        self._q.put(self._END, timeout=0.05)
                        break
                    except queue.Full:
                        if self._stop.is_set():
                            try:
                                self._q.get_nowait()
                            except queue.Empty:
                                pass

        self._thread = threading.Thread(
            target=pump, name="fold-stream-prefetch", daemon=True)
        self._thread.start()

    def __call__(self):
        b = self._q.get()
        if b is self._END:
            if self._err is not None:
                raise self._err
            return None
        return b

    def close(self, timeout: float = 5.0) -> bool:
        """Stop the pump and join its thread; True when the thread
        exited within ``timeout``.  Idempotent, never raises."""
        self._stop.set()
        # drain so a pump blocked mid-put sees the stop flag promptly
        while True:
            try:
                self._q.get_nowait()
            except self._queue_mod.Empty:
                break
        self._thread.join(timeout)
        return not self._thread.is_alive()


def fold_stream(kernel, combine, place, dataset, w, prefetch: int = 0, *,
                stream_ckpt=None, acc_unflatten=None, stats=None):
    """Stream the dataset through ``kernel(w, X, y, mask) -> (sums…, n)``,
    combining device sums with ``combine`` and counts as host ints
    (immune to integer wrap at 1B rows).

    Transfer/compute overlap (VERDICT r1 weak #5): JAX dispatch is
    asynchronous, so the structure below keeps the device busy —

    - batch i's kernel is dispatched BEFORE batch i+1 is sliced/padded on
      the host and its ``device_put`` issued, so host prep and the H2D
      DMA run while the device computes batch i (one batch of lookahead =
      classic double buffering; peak device memory holds two batches);
    - the per-batch host sync the old loop had (``int(n)`` after every
      kernel) is gone — counts are drained ONCE after the stream, so no
      batch waits for its predecessor's scalar readback.

    ``prefetch > 0`` adds a second stage of pipelining for sources whose
    ``next()`` does real host work (disk reads, LibSVM parse, CSC twin
    builds): a bounded background thread (:class:`_Prefetcher`) keeps up
    to ``prefetch`` RAW batches ready, so iteration k+1's ingest runs
    concurrently with iteration k's compute instead of inside the gap
    between dispatches.  ``0`` (default) is the exact single-threaded
    loop as before — nothing spawned, bit-identical behavior.  The
    prefetcher is closed (thread joined) on EVERY exit, including a
    kernel raise mid-pass — the original exception propagates.

    Mid-epoch resume (``stream_ckpt``, a :class:`StreamCheckpoint`):
    the fold registers each pass via ``begin_pass`` and commits a
    :class:`StreamCursor` every ``every_batches`` folded batches.  When
    a loaded checkpoint armed a cursor for THIS pass, the first
    ``batch_index`` batches are pulled and DISCARDED (no placement, no
    kernel) and the accumulator is re-seeded from the cursor's leaves
    via ``acc_unflatten(leaves) -> acc`` (return None to reject a
    structurally-incompatible cursor — the pass then replays in full,
    still bit-identical, just slower).

    ``stats`` (optional dict) is filled in place: ``batches``, ``rows``,
    ``pass_s``, ``stall_s`` (time blocked waiting on ingest — the
    prefetch-overlap numerator), ``skipped_batches`` and
    ``resumed_from_batch`` (cursor consumed this pass).
    """
    t_pass = time.perf_counter()
    stall = [0.0]
    it = iter(dataset)
    pf = None
    if prefetch > 0:
        pf = _Prefetcher(it, prefetch)
        raw_pull = pf
    else:
        def raw_pull():
            return next(it, None)

    def pull():
        t0 = time.perf_counter()
        b = raw_pull()
        stall[0] += time.perf_counter() - t0
        return b

    ordinal, resume = (stream_ckpt.begin_pass()
                       if stream_ckpt is not None else (0, None))
    acc = None
    ns = []
    skip = 0
    if resume is not None and acc_unflatten is not None:
        seeded = acc_unflatten(resume.acc_leaves)
        if seeded is not None:
            acc = seeded
            ns = [int(resume.n)]
            skip = int(resume.batch_index)
    batch_index = skip
    try:
        for _ in range(skip):  # already folded into the cursor's carry
            if pull() is None:
                break
        first = pull()
        if first is None and skip == 0:
            raise ValueError("streaming dataset yielded no batches")
        nxt = None if first is None else place(*first)
        while nxt is not None:
            *sums, n = kernel(w, *nxt)  # async dispatch on batch i
            ns.append(n)
            acc = sums if acc is None else combine(acc, sums)
            batch_index += 1
            if stream_ckpt is not None:
                stream_ckpt.maybe_commit(ordinal, batch_index, acc, ns)
            b = pull()  # host prep of batch i+1 overlaps device work
            nxt = None if b is None else place(*b)
    finally:
        if pf is not None:
            pf.close()
    total = sum(int(x) for x in ns)
    if stats is not None:
        stats["batches"] = batch_index
        stats["rows"] = total
        stats["pass_s"] = time.perf_counter() - t_pass
        stats["stall_s"] = stall[0]
        stats["skipped_batches"] = skip
        if skip:
            stats["resumed_from_batch"] = skip
    return acc, total
