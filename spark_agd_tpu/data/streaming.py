"""Macro-batch streaming: full-batch AGD semantics on larger-than-HBM data.

SURVEY §7 hard part 4: at the 1B-row north-star scale, the dataset cannot
live in device memory, but AGD is a *full-batch* method — every
``applySmooth`` must see every example.  The reference's treeAggregate
seqOp/combOp split (reference ``:196-204``) maps exactly onto streaming:
each macro-batch's jit-compiled kernel is the (vectorised) seqOp, and the
host-side accumulation of ``(Σloss, Σgrad, n)`` across macro-batches is the
combOp — associative sums, one division at the very end (reference ``:207``
semantics preserved bit-for-bit up to summation order).

The streamed smooth is a *host-level* callable (Python loop inside), so it
pairs with ``core.host_agd.run_agd_host`` — the driver-orchestrated twin of
the fused loop — rather than with ``lax.while_loop``.  Counts accumulate as
Python ints (no 2^31 wrap at any scale; see ``ops.losses._count``).
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core import tvec
from ..ops.losses import Gradient
from ..ops.sparse import CSRMatrix, RowShardedCSR
from ..parallel import dist_smooth, mesh as mesh_lib


def iter_array_batches(X, y, batch_rows: int,
                       mask=None) -> Iterator[Tuple]:
    """Slice in-memory arrays into macro-batches (testing / memmap use —
    np.memmap slices lazily, so this also serves on-disk dense data)."""
    n = X.shape[0]
    for s in range(0, n, batch_rows):
        e = min(s + batch_rows, n)
        yield X[s:e], y[s:e], None if mask is None else mask[s:e]


def _max_batch_nnz(indptr, batch_rows: int) -> int:
    """Largest entry count of any ``batch_rows``-row slice — the one
    batching-boundary computation, shared by the padding loop and the
    ``from_libsvm_parts`` shape inference so they cannot disagree."""
    indptr = np.asarray(indptr)
    n = len(indptr) - 1
    starts = np.arange(0, n, batch_rows)
    if not len(starts):
        return 0
    return max(1, int(np.max(
        indptr[np.minimum(starts + batch_rows, n)] - indptr[starts])))


def iter_csr_batches(indptr, indices, values, n_features: int, y,
                     batch_rows: int, mask=None,
                     with_csc="lazy",
                     nnz_pad: Optional[int] = None) -> Iterator[Tuple]:
    """Slice host CSR arrays into fixed-shape macro-batches.

    XLA compiles ONE kernel per shape, so every batch is padded to the
    same ``(batch_rows, nnz_pad)`` — by default the largest per-batch
    entry count (computed up front from ``indptr``); pass ``nnz_pad``
    explicitly when batches from SEVERAL sources must share one compiled
    shape (``StreamingDataset.from_libsvm_parts``).  Padding follows the
    ops.sparse contract: inert 0.0 entries at the LAST row/col slot (ids
    stay nondecreasing), padded row slots masked 0.

    ``with_csc="lazy"`` (default) MARKS each batch as wanting the
    column-sorted twin (``CSRMatrix.want_csc``) and lets placement
    provide it the cheap way for each path: MESH streaming's
    ``shard_csr_batch`` builds per-shard twins itself (a global one
    would be argsort work thrown away), and single-device placement
    materializes the twin ON DEVICE (overlapped with compute by
    :func:`fold_stream`'s double buffering).  ``True`` builds each
    batch's twin eagerly on the host — useful to move the argsort off
    the device when host cores are idle.  ``False`` disables twins
    (gradient falls back to scatter-add).
    """
    indptr = np.asarray(indptr)
    indices = np.asarray(indices, np.int32)
    values = np.asarray(values)
    y = np.asarray(y)
    n = len(indptr) - 1
    starts = np.arange(0, n, batch_rows)
    if not len(starts):  # empty input: yield nothing, like the dense twin
        return
    max_batch_nnz = _max_batch_nnz(indptr, batch_rows)
    if nnz_pad is None:
        nnz_pad = max_batch_nnz
    elif max_batch_nnz > nnz_pad:
        raise ValueError(
            f"a macro-batch holds {max_batch_nnz} entries > nnz_pad="
            f"{nnz_pad}; raise nnz_pad (one compiled shape must fit "
            f"every batch — from_libsvm_parts callers: pass nnz_pad "
            f"sized for the densest part)")
    for s in starts.tolist():
        e = min(s + batch_rows, n)
        lo, hi = int(indptr[s]), int(indptr[e])
        k = hi - lo
        rid = np.full(nnz_pad, batch_rows - 1, np.int32)
        cid = np.full(nnz_pad, n_features - 1, np.int32)
        val = np.zeros(nnz_pad, values.dtype)
        rid[:k] = np.repeat(np.arange(e - s, dtype=np.int32),
                            np.diff(indptr[s:e + 1]))
        cid[:k] = indices[lo:hi]
        val[:k] = values[lo:hi]
        csc = {}
        if with_csc == "lazy":
            csc = dict(want_csc=True)
        elif with_csc:
            order = np.argsort(cid[:k], kind="stable")
            crid = np.full(nnz_pad, batch_rows - 1, np.int32)
            ccid = np.full(nnz_pad, n_features - 1, np.int32)
            cval = np.zeros(nnz_pad, values.dtype)
            crid[:k] = rid[:k][order]
            ccid[:k] = cid[:k][order]
            cval[:k] = val[:k][order]
            csc = dict(csc_row_ids=crid, csc_col_ids=ccid,
                       csc_values=cval)
        Xb = CSRMatrix(rid, cid, val, (batch_rows, int(n_features)),
                       rows_sorted=True, **csc)
        yb = np.zeros(batch_rows, y.dtype)
        yb[:e - s] = y[s:e]
        mb = np.zeros(batch_rows, np.float32)
        mb[:e - s] = (np.ones(e - s, np.float32) if mask is None
                      else np.asarray(mask[s:e], np.float32))
        yield Xb, yb, mb


class StreamingDataset:
    """A re-iterable source of ``(X, y, mask)`` macro-batches.

    ``factory`` is a zero-arg callable returning a fresh iterator — AGD
    evaluates the smooth function 2-3 times per outer iteration, so one-shot
    generators are a footgun this interface rules out.
    """

    def __init__(self, factory: Callable[[], Iterable[Tuple]],
                 batch_rows: Optional[int] = None):
        self._factory = factory
        self.batch_rows = batch_rows

    @classmethod
    def from_arrays(cls, X, y, batch_rows: int, mask=None):
        return cls(lambda: iter_array_batches(X, y, batch_rows, mask),
                   batch_rows)

    @classmethod
    def from_csr(cls, indptr, indices, values, n_features: int, y,
                 batch_rows: int, mask=None, with_csc="lazy",
                 nnz_pad: Optional[int] = None):
        """Macro-batches over host CSR arrays (``data.libsvm.CSRData``'s
        fields) — the sparse twin of ``from_arrays``; see
        :func:`iter_csr_batches` for the fixed-shape padding contract."""
        return cls(lambda: iter_csr_batches(
            indptr, indices, values, n_features, y, batch_rows, mask,
            with_csc, nnz_pad=nnz_pad), batch_rows)

    @classmethod
    def from_libsvm_parts(cls, paths, n_features: int, batch_rows: int,
                          with_csc="lazy",
                          nnz_pad: Optional[int] = None,
                          binarize_labels: bool = True,
                          retries=None, telemetry=None):
        """Stream LIBSVM partition files (e.g. a Spark job's part-*
        output — the north star's ingest seam) as fixed-shape CSR
        macro-batches WITHOUT ever materializing the full dataset: one
        part is parsed (C++ parser, Python fallback) while the previous
        part's batches run, and each re-iteration re-reads from disk.

        All parts share one compiled kernel shape, so ``nnz_pad`` must
        bound every batch; by default it is sized from the first
        NON-EMPTY part (its max batch nnz, +25% headroom, lane-rounded;
        the part's parse is cached and consumed by the first iteration,
        not repeated).  A later, denser part then raises mid-stream with
        instructions — pass ``nnz_pad`` explicitly when part density
        varies.  ``n_features`` is required: parts must agree on the
        feature space (per-part inference would disagree on trailing
        sparse columns), and out-of-range indices fail at parse time
        rather than silently clamping inside the compiled gather.

        ``retries`` (a ``resilience.RetryPolicy``, default 3 attempts):
        each part's parse runs under the shared retrying helper, so a
        transient IO error mid-stream costs a backoff, not the whole
        fit — the streamed smooth re-reads every part EVERY evaluation,
        multiplying exposure to flaky storage.  Retries are logged and,
        when ``telemetry`` is given, land as ``recovery`` records.
        """
        from .libsvm import load_libsvm
        from .ingest import _retrying_loader

        paths = list(paths)
        if not paths:
            raise ValueError("from_libsvm_parts needs at least one path")
        load = _retrying_loader(load_libsvm, retries, telemetry)

        def part_arrays(path):
            d = load(path, n_features=n_features)
            if len(d.indices) and int(d.indices.max()) >= n_features:
                raise ValueError(
                    f"{path}: feature index {int(d.indices.max())} >= "
                    f"n_features={n_features} — an undersized feature "
                    f"space would silently clamp/drop entries in the "
                    f"compiled gather/scatter")
            y = d.binarized_labels() if binarize_labels else d.labels
            return d.indptr, d.indices, d.values, y.astype(np.float32)

        first_cache = {}
        if nnz_pad is None:
            for path in paths:  # first NON-EMPTY part sizes the shape
                arrays = part_arrays(path)
                m0 = _max_batch_nnz(arrays[0], batch_rows)
                if m0:
                    first_cache[path] = arrays
                    nnz_pad = -(-int(m0 * 1.25) // 128) * 128
                    break
            else:
                raise ValueError("all parts are empty")

        def factory():
            for path in paths:
                # the inference parse is reused exactly once (first pass)
                arrays = first_cache.pop(path, None) or part_arrays(path)
                yield from iter_csr_batches(
                    *arrays[:3], n_features, arrays[3], batch_rows,
                    with_csc=with_csc, nnz_pad=nnz_pad)

        return cls(factory, batch_rows)

    def __iter__(self):
        return iter(self._factory())


def _make_placer(mesh, pad_to, csr_nnz_per_shard):
    """The shared macro-batch placement closure: pad to one compiled
    shape, put on the device or shard over the mesh (dense via
    ``shard_batch``, CSR via nnz-budgeted ``shard_csr_batch``), and
    materialize a wanted-but-absent CSC twin on device (r2 ADVICE — a
    lazy twin must not silently fall back to scatter-add)."""
    budget = [csr_nnz_per_shard]  # resolved from the first batch
    warned_eager_twin = []  # warn once per smooth, not per batch

    def _place(X, y, mask):
        if isinstance(X, CSRMatrix):
            if mesh is not None:
                # row-shard this macro-batch like the in-memory sparse
                # mesh path; the fixed budget keeps one kernel shape
                if X.has_csc and not warned_eager_twin:
                    warned_eager_twin.append(True)
                    import warnings

                    warnings.warn(
                        "mesh CSR streaming with an EAGER per-batch CSC "
                        "twin: the sharder rebuilds per-shard twins and "
                        "discards the global one — build the dataset "
                        "with with_csc='lazy' to skip the wasted "
                        "per-batch argsort", stacklevel=2)
                if budget[0] is None:
                    n_shards = mesh.shape[mesh_lib.DATA_AXIS]
                    budget[0] = max(128, -(-int(X.nnz * 1.25 / n_shards)
                                           // 128) * 128)
                b = mesh_lib.shard_csr_batch(mesh, X, y, mask,
                                             nnz_per_shard=budget[0])
                return b.X, b.y, b.mask
            # iter_csr_batches already padded to fixed shape; move the
            # leaves onto the device and, when the batch WANTS a CSC
            # twin it doesn't carry (with_csc="lazy"), materialize it
            # there — an on-device argsort per batch, overlapped with
            # compute by fold_stream's double buffering; without this
            # the gradient would silently take the slow scatter-add
            # path (r2 ADVICE)
            Xd = jax.tree_util.tree_map(jnp.asarray, X)
            if Xd.want_csc and not Xd.has_csc:
                Xd = Xd.with_csc()
            return Xd, jnp.asarray(y), jnp.asarray(mask)
        X = np.asarray(X)
        y = np.asarray(y)
        n = X.shape[0]
        if pad_to is not None and n < pad_to:
            base = np.ones(n, np.float32) if mask is None else \
                np.asarray(mask, np.float32)
            X = np.concatenate(
                [X, np.zeros((pad_to - n,) + X.shape[1:], X.dtype)])
            y = np.concatenate([y, np.zeros(pad_to - n, y.dtype)])
            mask = np.concatenate([base, np.zeros(pad_to - n, np.float32)])
        if mesh is not None:
            return mesh_lib.shard_batch(mesh, X, y, mask)
        m = None if mask is None else jnp.asarray(mask)
        return jnp.asarray(X), jnp.asarray(y), m

    return _place


def make_streaming_smooth(
    gradient: Gradient,
    dataset: StreamingDataset,
    *,
    mesh=None,
    pad_to: Optional[int] = None,
    csr_nnz_per_shard: Optional[int] = None,
    prefetch: int = 0,
):
    """Build host-level ``(smooth, smooth_loss)`` that stream macro-batches.

    ``prefetch`` (default 0 = off): background-thread ingest depth for
    the fold — see :func:`fold_stream`; batch k+1's host read/parse
    overlaps batch k's device compute.

    Each batch is (optionally) padded to ``pad_to`` rows so XLA compiles ONE
    kernel shape instead of one per ragged tail, then placed on ``mesh``
    (sharded over its data axis) or the default device.  Returns means, like
    every other smooth builder.

    Sparse + mesh (the north-star regime: more sparse rows than the pod's
    HBM): each CSR macro-batch is row-sharded over the mesh's data axis
    (nnz-balanced, ``parallel.mesh.shard_csr_batch``) and evaluated by the
    same shard_map+psum kernel as the in-memory sparse mesh path.  One
    compiled shape serves every batch: shards pad to a fixed
    ``csr_nnz_per_shard`` budget — default ``1.25 x batch_nnz / n_shards``
    lane-rounded, which covers the greedy balancer's worst case
    (mean + heaviest row) unless one row dominates the batch; a batch
    that cannot fit raises with the knob's name.  Build the dataset with
    ``with_csc="lazy"`` for this path: per-shard column-sorted twins are
    built by the sharder, so an eager global twin is per-batch argsort
    work thrown away.
    """

    @jax.jit
    def batch_sums(w, X, y, mask):
        if isinstance(X, RowShardedCSR):
            return _csr_mesh_sums(w, X, y, mask, with_grad=True)
        return gradient.batch_loss_and_grad(w, X, y, mask)

    # Loss-only twin: the gradient is a jit *output* in batch_sums, so XLA
    # cannot dead-code-eliminate it there — a separate kernel lets the
    # rmatvec (size-D work per macro-batch) vanish entirely.
    @jax.jit
    def batch_loss_sums(w, X, y, mask):
        if isinstance(X, RowShardedCSR):
            ls, n = _csr_mesh_sums(w, X, y, mask, with_grad=False)
            return ls, n
        ls, _, n = gradient.batch_loss_and_grad(w, X, y, mask)
        return ls, n

    def _csr_mesh_sums(w, X, y, mask, *, with_grad):
        # trace-time dispatch: the shard_map wrapper is built once per
        # compiled shape (dist_smooth.csr_shard_sums docstring)
        ev = dist_smooth.csr_shard_sums(
            gradient, X, y, mask, mesh, mesh_lib.DATA_AXIS,
            with_grad=with_grad)
        return ev(w, *dist_smooth.csr_shard_args(X, y, mask))

    _place = _make_placer(mesh, pad_to, csr_nnz_per_shard)

    def smooth(w):
        (ls, gs), n = fold_stream(
            batch_sums,
            lambda a, b: [a[0] + b[0], tvec.add(a[1], b[1])],
            _place, dataset, w, prefetch=prefetch)
        nf = jnp.asarray(n, ls.dtype)
        return ls / nf, tvec.scale(1.0 / nf, gs)

    def smooth_loss(w):
        (ls,), n = fold_stream(
            batch_loss_sums, lambda a, b: [a[0] + b[0]], _place, dataset,
            w, prefetch=prefetch)
        return ls / jnp.asarray(n, ls.dtype)

    return smooth, smooth_loss


def make_streaming_eval_multi(
    gradient: Gradient,
    dataset: StreamingDataset,
    *,
    mesh=None,
    pad_to: Optional[int] = None,
    csr_nnz_per_shard: Optional[int] = None,
    with_grad: bool = True,
):
    """Evaluate K weight vectors over ONE pass of the stream.

    ``eval_multi(W_stacked) -> (mean_losses, mean_grads)`` where
    ``W_stacked`` has a leading lane axis (``(K, D)`` array or a pytree
    of stacked leaves, e.g. a sweep result's ``res.weights``);
    ``mean_losses`` is ``(K,)`` and ``mean_grads`` keeps the lane axis.
    ``with_grad=False`` returns ``(K,)`` losses only — the gradient
    work (the size-D rmatvec per lane) vanishes from the compiled
    kernel, the right mode for validation scoring.

    This is the streaming member of the grid-fit family: the mesh sweep
    (``parallel.grid``) trains K lanes on in-HBM shards; this scores K
    candidates (a regularization path, CV refits) on data LARGER than
    HBM, reading the stream ONCE for all lanes instead of K times —
    per macro-batch the K margin products fuse into one
    ``(rows, D) @ (D, K)`` contraction, the same MXU batching the
    in-memory sweep gets.  Composes with ``mesh`` exactly like
    ``make_streaming_smooth`` (dense GSPMD / CSR shard_map+psum).
    """
    _place = _make_placer(mesh, pad_to, csr_nnz_per_shard)

    @jax.jit
    def batch_sums(W, X, y, mask):
        if isinstance(X, RowShardedCSR):
            ev = dist_smooth.csr_shard_sums(
                gradient, X, y, mask, mesh, mesh_lib.DATA_AXIS,
                with_grad=True, n_lanes=True)
            return ev(W, *dist_smooth.csr_shard_args(X, y, mask))
        ls, gs, n = jax.vmap(
            lambda wv: gradient.batch_loss_and_grad(wv, X, y, mask))(W)
        return ls, gs, n[0]  # count is mask-only: identical per lane

    @jax.jit
    def batch_loss_sums(W, X, y, mask):
        if isinstance(X, RowShardedCSR):
            ev = dist_smooth.csr_shard_sums(
                gradient, X, y, mask, mesh, mesh_lib.DATA_AXIS,
                with_grad=False, n_lanes=True)
            return ev(W, *dist_smooth.csr_shard_args(X, y, mask))
        ls, _, n = jax.vmap(
            lambda wv: gradient.batch_loss_and_grad(wv, X, y, mask))(W)
        return ls, n[0]

    def eval_multi(W):
        W = jax.tree_util.tree_map(jnp.asarray, W)
        if with_grad:
            (ls, gs), n = fold_stream(
                batch_sums,
                lambda a, b: [a[0] + b[0], tvec.add(a[1], b[1])],
                _place, dataset, W)
            nf = jnp.asarray(n, ls.dtype)
            return ls / nf, tvec.scale(1.0 / nf, gs)
        (ls,), n = fold_stream(
            batch_loss_sums, lambda a, b: [a[0] + b[0]], _place,
            dataset, W)
        return ls / jnp.asarray(n, ls.dtype)

    return eval_multi


class _Prefetcher:
    """Bounded background ingest: a daemon thread pulls raw batches off
    the iterator into a ``queue.Queue(maxsize=depth)`` so batch k+1's
    host-side read/parse/pad (the expensive part of ``next()`` for disk
    and LibSVM sources) overlaps batch k's device compute INSTEAD of
    serializing after it.  Placement (``device_put``) stays on the
    consuming thread — JAX dispatch ordering is per-thread, and the
    queue bound caps host memory at ``depth`` raw batches.  The sentinel
    marks exhaustion; a producer exception is re-raised at the consumer's
    next pull, not swallowed."""

    _END = object()

    def __init__(self, it, depth: int):
        import queue
        import threading

        self._q = queue.Queue(maxsize=depth)
        self._err = None

        def pump():
            try:
                for b in it:
                    self._q.put(b)
            except BaseException as e:  # noqa: BLE001 — relayed, below
                self._err = e
            finally:
                self._q.put(self._END)

        self._thread = threading.Thread(
            target=pump, name="fold-stream-prefetch", daemon=True)
        self._thread.start()

    def __call__(self):
        b = self._q.get()
        if b is self._END:
            if self._err is not None:
                raise self._err
            return None
        return b


def fold_stream(kernel, combine, place, dataset, w, prefetch: int = 0):
    """Stream the dataset through ``kernel(w, X, y, mask) -> (sums…, n)``,
    combining device sums with ``combine`` and counts as host ints
    (immune to integer wrap at 1B rows).

    Transfer/compute overlap (VERDICT r1 weak #5): JAX dispatch is
    asynchronous, so the structure below keeps the device busy —

    - batch i's kernel is dispatched BEFORE batch i+1 is sliced/padded on
      the host and its ``device_put`` issued, so host prep and the H2D
      DMA run while the device computes batch i (one batch of lookahead =
      classic double buffering; peak device memory holds two batches);
    - the per-batch host sync the old loop had (``int(n)`` after every
      kernel) is gone — counts are drained ONCE after the stream, so no
      batch waits for its predecessor's scalar readback.

    ``prefetch > 0`` adds a second stage of pipelining for sources whose
    ``next()`` does real host work (disk reads, LibSVM parse, CSC twin
    builds): a bounded background thread (:class:`_Prefetcher`) keeps up
    to ``prefetch`` RAW batches ready, so iteration k+1's ingest runs
    concurrently with iteration k's compute instead of inside the gap
    between dispatches.  ``0`` (default) is the exact single-threaded
    loop as before — nothing spawned, bit-identical behavior.
    """
    it = iter(dataset)
    if prefetch > 0:
        pull = _Prefetcher(it, prefetch)
    else:
        def pull():
            return next(it, None)
    first = pull()
    if first is None:
        raise ValueError("streaming dataset yielded no batches")
    nxt = place(*first)
    acc = None
    ns = []
    while nxt is not None:
        *sums, n = kernel(w, *nxt)  # async dispatch on batch i
        ns.append(n)
        acc = sums if acc is None else combine(acc, sums)
        b = pull()  # host prep of batch i+1 overlaps device work
        nxt = None if b is None else place(*b)
    return acc, sum(int(x) for x in ns)
