"""On-device synthetic data generation (no host↔device bulk transfer).

Why this module exists: the benchmark/check harnesses originally built
datasets with host NumPy and staged them via one big ``device_put``.  On
the tunneled single-chip environment the host↔device link is the least
reliable component (observed: multi-GiB transfers hang indefinitely while
on-device RNG generates 1 GiB in seconds and compiles go through fine).
Generating the data *on the device that will consume it* removes the bulk
transfer entirely — only scalars and PRNG keys cross the link — and is
also the right TPU-native design: HBM is filled at HBM bandwidth by the
chip's own PRNG instead of at tunnel bandwidth by the host.

Cross-backend determinism: JAX's threefry PRNG produces identical random
BITS for the same key on every backend.  Derived *floats* can differ by
an ulp across backends (transcendental lowering), so any value that
gates a discrete outcome (a label threshold) must be computed from raw
bits/uniforms with exact arithmetic only.  ``class_logistic`` follows
that rule — labels come from ``bernoulli(0.5)`` (exact compare against
0.5), features from elementwise ops — so a CPU "host twin" of a TPU
dataset has bit-identical labels and ulp-identical features.  That is
what lets ``bench.py`` run its float64 host oracle on the same logical
dataset without ever transferring it.

Reference mapping: these generators replace the role of MLlib's
``GradientDescentSuite.generateGDInput`` (reference
``AcceleratedGradientDescentSuite.scala:46``) at benchmark scale — the
synthetic fixture data the suite trains on, here produced where the
FLOPs are.
"""

from __future__ import annotations

import math
from typing import Tuple

import jax
import jax.numpy as jnp


def ensure_cpu_backend() -> None:
    """Make sure the host CPU platform is registered alongside the
    accelerator.

    Driver environments pin ``JAX_PLATFORMS=axon`` (or ``tpu``), which
    *unregisters* the CPU backend — but the host twin (`host_gen`) and
    degraded fallbacks need it.  Appending ``,cpu`` before the first
    backend touch restores it; a no-op when unset (all platforms) or
    already listed.
    """
    cur = jax.config.jax_platforms
    if cur and "cpu" not in [p.strip() for p in cur.split(",")]:
        jax.config.update("jax_platforms", cur + ",cpu")


def cpu_device():
    ensure_cpu_backend()
    return jax.local_devices(backend="cpu")[0]


def class_logistic(key, n: int, d: int,
                   sep: float = 1.0) -> Tuple[jax.Array, jax.Array]:
    """Two-class Gaussian mixture whose Bayes posterior IS a logistic
    model: y ~ Bernoulli(1/2), x | y ~ N(±mu, I) with ``‖mu‖ ≈ sep``.

    Elementwise-only (no matmuls/reductions) so a host twin is
    bit-identical in labels and ulp-identical in features — see module
    docstring.  Returns ``(X f32[n,d], y f32[n])`` with y in {0, 1}.
    """
    kx, ky, km = jax.random.split(key, 3)
    y = jax.random.bernoulli(ky, 0.5, (n,))
    mu = (sep / math.sqrt(d)) * jax.random.normal(km, (d,), jnp.float32)
    signs = jnp.where(y, 1.0, -1.0).astype(jnp.float32)
    X = jax.random.normal(kx, (n, d), jnp.float32) \
        + signs[:, None] * mu[None, :]
    return X, y.astype(jnp.float32)


def device_gen(fn, *args, device=None):
    """Run generator ``fn(*args)`` jitted on ``device`` (default: the
    default backend's first device).  Fresh jit per call site keeps the
    compile caches of different target devices independent."""
    if device is None:
        return jax.jit(fn)(*args)
    with jax.default_device(device):
        return jax.jit(fn)(*args)


def host_gen(fn, *args):
    """Run generator ``fn`` on the host CPU backend and return the
    results as host-committed arrays (cheap ``np.asarray`` views)."""
    return device_gen(fn, *args, device=cpu_device())


# ---------------------------------------------------------------------------
# Benchmark-geometry generators (device-side twins of benchmarks.datasets)
# ---------------------------------------------------------------------------

def linreg_params(key, d: int):
    """The planted linreg weight — ONE definition shared by the
    monolithic generator and the blockwise twin, so the model cannot
    silently diverge between the two paths (r5 review)."""
    return jax.random.normal(key, (d,), jnp.float32) / math.sqrt(d)


def _linreg_body(kx, ke, w, rows: int, d: int, noise: float):
    X = jax.random.normal(kx, (rows, d), jnp.float32)
    return X, X @ w + noise * jax.random.normal(ke, (rows,), jnp.float32)


def planted_dense_linreg(key, n: int, d: int,
                         noise: float = 0.1) -> Tuple[jax.Array, jax.Array]:
    """Dense least-squares with a planted weight vector.  (Key split
    order is frozen — committed trajectories were measured on exactly
    these bits.)"""
    kx, kw, ke = jax.random.split(key, 3)
    return _linreg_body(kx, ke, linreg_params(kw, d), n, d, noise)


def linreg_block(key, w, rows: int, d: int, noise: float = 0.1):
    """One row block of the SAME planted linreg model (weights from
    :func:`linreg_params`), for bounded-transient blockwise generation
    (``benchmarks.datasets``).  Bits differ from the monolithic path —
    the block layout is part of the stream."""
    kx, ke = jax.random.split(key)
    return _linreg_body(kx, ke, w, rows, d, noise)


def softmax_params(key, d: int, k: int):
    """The planted softmax weight matrix — shared like linreg_params."""
    return jax.random.normal(key, (d, k), jnp.float32) / math.sqrt(d)


def _softmax_body(kx, kg, W, rows: int, d: int, k: int):
    X = jax.random.normal(kx, (rows, d), jnp.float32)
    logits = X @ W + jax.random.gumbel(kg, (rows, k), jnp.float32)
    return X, jnp.argmax(logits, axis=1).astype(jnp.int32)


def planted_softmax(key, n: int, d: int,
                    k: int) -> Tuple[jax.Array, jax.Array]:
    """Dense multiclass data: labels drawn from the planted softmax model
    via the Gumbel-max trick (exactly a categorical sample)."""
    kx, kw, kg = jax.random.split(key, 3)
    return _softmax_body(kx, kg, softmax_params(kw, d, k), n, d, k)


def softmax_block(key, W, rows: int, d: int, k: int):
    """One row block of the SAME planted softmax model (see
    :func:`linreg_block`)."""
    kx, kg = jax.random.split(key)
    return _softmax_body(kx, kg, W, rows, d, k)


def planted_mlp(key, n: int, d: int, h: int,
                gain: float = 4.0) -> Tuple[jax.Array, jax.Array]:
    """Binary labels from a planted two-layer tanh MLP (signal a linear
    model cannot fully capture — BASELINE config 5's stand-in)."""
    kx, k1, k2, ku = jax.random.split(key, 4)
    X = jax.random.normal(kx, (n, d), jnp.float32)
    W1 = jax.random.normal(k1, (d, h), jnp.float32) / math.sqrt(d)
    W2 = jax.random.normal(k2, (h,), jnp.float32) / math.sqrt(h)
    margins = jnp.tanh(X @ W1) @ W2
    p = jax.nn.sigmoid(gain * margins)
    y = (jax.random.uniform(ku, (n,)) < p).astype(jnp.int32)
    return X, y


def planted_sparse_parts(key, n_rows: int, n_features: int,
                         nnz_per_row: int):
    """Device-side COO parts for a planted sparse logistic problem.

    Returns ``(row_ids, col_ids, values, y)`` — row-sorted by
    construction (ids repeat in blocks of ``nnz_per_row``).  The margin
    uses a segment-sum, not a scatter, so generation itself is
    TPU-friendly.  The caller wraps the parts in ``CSRMatrix`` (and can
    request a device-built CSC twin — `ops.sparse.CSRMatrix.with_csc`
    sorts with ``jnp.argsort`` when the entries live on device).
    """
    kc, kv, kw, ku = jax.random.split(key, 4)
    nnz = n_rows * nnz_per_row
    col_ids = jax.random.randint(kc, (nnz,), 0, n_features, jnp.int32)
    row_ids = jnp.repeat(jnp.arange(n_rows, dtype=jnp.int32), nnz_per_row)
    values = jax.random.normal(kv, (nnz,), jnp.float32)
    # planted weights scaled so each row's margin has unit variance
    w = jax.random.normal(kw, (n_features,), jnp.float32) \
        / math.sqrt(nnz_per_row)
    margins = jax.ops.segment_sum(values * jnp.take(w, col_ids),
                                  row_ids, num_segments=n_rows,
                                  indices_are_sorted=True)
    p = jax.nn.sigmoid(margins)
    y = (jax.random.uniform(ku, (n_rows,)) < p).astype(jnp.float32)
    return row_ids, col_ids, values, y


def planted_sparse_parts_varied(key, n_rows: int, n_features: int,
                                nnz_mean: int, sigma: float = 0.5,
                                max_factor: int = 3):
    """:func:`planted_sparse_parts` with a LONG-TAILED per-row nonzero
    count instead of a constant one — the documented-distribution twin
    BASELINE's real datasets need (rcv1.binary's ~74 nnz/row is a mean
    over a skewed histogram, not a constant).

    Per-row counts are log-normal (``mu = ln(nnz_mean) - sigma²/2`` so
    the mean lands on ``nnz_mean``), clipped to
    ``[1, max_factor·nnz_mean]``.  The COO shape stays STATIC at
    ``n_rows·max_factor·nnz_mean`` — entries past each row's count keep
    their random ``col_ids`` but get value 0, so the shape is
    TPU-compile-friendly while every margin, gradient, and nnz
    *histogram* reflects the drawn counts (an explicit zero contributes
    nothing to any segment sum).  This is an approximation of the real
    histograms, labeled as such in the provenance fields — the real
    files are not fetchable from this environment (BASELINE.md:21-25).
    """
    kc, kv, kw, ku, kn = jax.random.split(key, 5)
    nnz_max = max_factor * nnz_mean
    mu = math.log(nnz_mean) - 0.5 * sigma * sigma
    counts = jnp.clip(jnp.round(jnp.exp(
        mu + sigma * jax.random.normal(kn, (n_rows,)))), 1, nnz_max
    ).astype(jnp.int32)
    nnz = n_rows * nnz_max
    col_ids = jax.random.randint(kc, (nnz,), 0, n_features, jnp.int32)
    row_ids = jnp.repeat(jnp.arange(n_rows, dtype=jnp.int32), nnz_max)
    live = (jnp.arange(nnz, dtype=jnp.int32) % nnz_max) \
        < jnp.repeat(counts, nnz_max)
    values = jnp.where(live, jax.random.normal(kv, (nnz,), jnp.float32),
                       0.0)
    w = jax.random.normal(kw, (n_features,), jnp.float32) \
        / math.sqrt(nnz_mean)
    margins = jax.ops.segment_sum(values * jnp.take(w, col_ids),
                                  row_ids, num_segments=n_rows,
                                  indices_are_sorted=True)
    p = jax.nn.sigmoid(margins)
    y = (jax.random.uniform(ku, (n_rows,)) < p).astype(jnp.float32)
    return row_ids, col_ids, values, y
