"""Partitioned-file ingest — the Spark-seam adapter (SURVEY §7 step 5).

The north star keeps Spark "only as the ingest layer": upstream, a Spark
job (or any writer) materializes the dataset as K partition files; here
each HOST of the SPMD job reads only its own subset of those partitions
and the shards are assembled into one global mesh-sharded array — the
structural equivalent of "Spark shards RDD[LabeledPoint] onto the mesh"
with no JVM in the serving path (VERDICT r1 item 9).

Contract (every host runs the same code — jax.distributed SPMD):

- ``paths`` is the SAME full partition list on every host (sorted
  internally, so any consistent enumeration works);
- host p reads partitions ``paths[p::process_count]`` (round-robin, so a
  size-skewed tail spreads instead of landing on the last host);
- per-host row counts and the inferred feature width are equalized with
  one ``process_allgather``; hosts pad their local block to the common
  height with mask-0 rows (the kernels' padding contract keeps all sums
  exact — ``ops.losses._as_mask``);
- ``jax.make_array_from_process_local_data`` assembles the global
  (N_padded, D) array, row-sharded over the mesh ``data`` axis.

Single-process (tests, one chip) degenerates to: read everything, shard
like ``mesh.shard_batch`` — same return type, no branching in callers.

Two assembly layouts:

- :func:`from_partitioned_files` — densified rows (the MXU path for
  moderate D);
- :func:`from_partitioned_files_csr` — SPARSE end to end: each host
  lays out its local rows over its own device shards
  (``mesh.csr_shard_layout``, nnz-balanced) with globally-agreed
  shard dimensions (two allgather-max reductions), and the per-host
  blocks assemble into one ``RowShardedCSR`` without ever densifying —
  the url_combined regime (D≈3.2M) where a dense row is 12.8 MB and
  densifying is impossible.  This is the reference's sparse-Vector
  ingest capability (``AcceleratedGradientDescent.scala:196-204``
  accepts sparse MLlib vectors) at mesh scale.
"""

from __future__ import annotations

import logging
from typing import Callable, Optional, Sequence

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import mesh as mesh_lib
from ..resilience import retry as retry_lib
from . import libsvm

logger = logging.getLogger("spark_agd_tpu")

# transient IO mid-ingest costs a short backoff, not the whole SPMD
# job; bounded so a genuinely-dead source still fails fast (the
# supervisor above classifies OSError TRANSIENT and retries the
# larger unit)
DEFAULT_READ_RETRIES = retry_lib.RetryPolicy(
    max_attempts=3, backoff_base=0.05, backoff_max=2.0, jitter=0.1)


def _retrying_loader(loader: Callable, retries, telemetry) -> Callable:
    """``loader`` under the shared retrying helper (``resilience.
    retry``): transient IO errors back off and re-read; each retry is
    logged and — when a ``telemetry`` is attached — emitted as a
    ``recovery`` record into the run's JSONL.  Shared by the ingest
    assemblers and ``data.streaming.StreamingDataset.
    from_libsvm_parts``."""
    policy = retries if retries is not None else DEFAULT_READ_RETRIES

    def on_retry(n_failures, exc, delay):
        logger.warning(
            "ingest read failed (%s: %s); retry %d/%d in %.2fs",
            type(exc).__name__, exc, n_failures,
            policy.max_attempts - 1, delay)

    return retry_lib.retrying(policy, label="ingest_read",
                              telemetry=telemetry,
                              on_retry=on_retry)(loader)


def _validated_parts(paths_used, parts, d, validate, telemetry):
    """Apply the ``validate=`` policy to freshly-read partitions:
    ``False`` = trust the writer (the historical behavior), ``"raise"``
    = typed :class:`~spark_agd_tpu.data.libsvm.DataValidationError` on
    the first bad partition (classified FATAL by the resilience layer —
    re-reading garbage yields garbage), ``"drop"`` = discard invalid
    rows, log, and count them on the ``data.invalid_records`` telemetry
    counter — bounded data loss instead of silently training on NaNs."""
    if not validate:
        return parts
    if validate not in ("raise", "drop"):
        raise ValueError(
            f"validate must be False, 'raise', or 'drop'; "
            f"got {validate!r}")
    out = []
    for path, part in zip(paths_used, parts):
        mask = libsvm.invalid_row_mask(part, d)
        n_bad = int(mask.sum())
        if not n_bad:
            out.append(part)
            continue
        if validate == "raise":
            raise libsvm.DataValidationError(
                path, libsvm.describe_invalid(part, mask))
        logger.warning(
            "%s: dropping %d invalid row(s) (non-finite features/"
            "labels or out-of-range indices)", path, n_bad)
        if telemetry is not None:
            telemetry.registry.counter("data.invalid_records").inc(n_bad)
        out.append(libsvm.drop_rows(part, mask))
    return out


def _allgather_max(value: int) -> int:
    """Max of a per-host int across the SPMD job (identity when
    single-process)."""
    if jax.process_count() == 1:
        return int(value)
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        np.asarray([value], np.int64))
    return int(np.max(gathered))


def _allgather_sum(value: int) -> int:
    """Sum of a per-host int across the SPMD job."""
    if jax.process_count() == 1:
        return int(value)
    from jax.experimental import multihost_utils

    gathered = multihost_utils.process_allgather(
        np.asarray([value], np.int64))
    return int(np.sum(gathered))


def local_partitions(paths: Sequence[str]) -> list:
    """The partition files THIS host reads: round-robin over the sorted
    list (Spark's even-ish task assignment, minus locality)."""
    paths = sorted(paths)
    return paths[jax.process_index()::jax.process_count()]


def from_partitioned_files(
    paths: Sequence[str],
    mesh=None,
    *,
    n_features: Optional[int] = None,
    dtype=np.float32,
    binarize_labels: bool = True,
    loader: Optional[Callable[..., "libsvm.CSRData"]] = None,
    axis: str = mesh_lib.DATA_AXIS,
    retries: Optional[retry_lib.RetryPolicy] = None,
    telemetry=None,
    validate=False,
    assignment: Optional[Sequence[str]] = None,
    pad_to_rows: Optional[int] = None,
) -> mesh_lib.ShardedBatch:
    """Load one LIBSVM partition set into a mesh-sharded batch.

    ``loader(path, n_features=...) -> CSRData`` defaults to the LIBSVM
    reader (native C++ parser when built); swap it for a parquet/npz
    reader with the same return shape.  ``n_features`` pins the global
    width; when omitted it is inferred as the max across ALL hosts'
    partitions (one allgather).  Labels are mapped to {0,1} unless
    ``binarize_labels=False`` (multinomial class ids).

    Every partition read runs under the shared retrying helper
    (``retries``, default 3 attempts with backoff): one flaky NFS read
    must not abort a whole-pod SPMD ingest.  Retries are logged and,
    when ``telemetry`` (an ``obs.Telemetry``) is given, emitted as
    ``recovery`` records.

    ``validate`` (default off): ``"raise"`` rejects non-finite
    features/labels and out-of-range indices with a typed
    :class:`~spark_agd_tpu.data.libsvm.DataValidationError`; ``"drop"``
    discards the offending ROWS, logging and counting them on the
    ``data.invalid_records`` telemetry counter — either way the model
    never silently trains on garbage.

    ``assignment`` (optional): an EXPLICIT partition list for THIS host
    instead of the round-robin rule — the straggler scheduler's
    weighted re-split (``resilience.scheduler``) re-ingests through
    this seat (an empty list is legal: the host contributes only
    mask-0 padding rows but keeps its replicated carry and its place
    in every collective).  ``pad_to_rows`` (optional, multi-process
    assembly only) PINS the per-host block height instead of the
    allgather-max: every assignment up to that many rows produces the
    SAME global array shape, so a generation-boundary rebalance swaps
    data arguments without re-tracing a single program.

    Returns a :class:`~spark_agd_tpu.parallel.mesh.ShardedBatch` whose
    mask excludes inter-host padding rows; feed it straight to
    ``api.run`` / ``dist_smooth.make_dist_smooth``.
    """
    if not paths:
        raise ValueError("no partition files")
    loader = _retrying_loader(loader or libsvm.load_libsvm, retries,
                              telemetry)
    mesh = mesh if mesh is not None else mesh_lib.make_mesh(
        {axis: len(jax.devices())})

    mine = (sorted(str(p) for p in assignment)
            if assignment is not None else local_partitions(paths))
    parts = [loader(p, n_features=n_features) for p in mine]
    d = n_features or _allgather_max(
        max((part.n_features for part in parts), default=0))
    if d == 0:
        raise ValueError("could not infer n_features (all partitions "
                         "empty on this host and none given)")
    parts = _validated_parts(mine, parts, d, validate, telemetry)

    ys, Xs = [], []
    for part in parts:
        ys.append(part.binarized_labels() if binarize_labels
                  else np.asarray(part.labels))
        Xs.append(part.to_dense(d, dtype=dtype))
    n_local = int(sum(len(y) for y in ys))
    X_local = (np.concatenate(Xs) if Xs
               else np.zeros((0, d), dtype))
    y_local = (np.concatenate(ys).astype(np.float32) if ys
               else np.zeros((0,), np.float32))

    if jax.process_count() == 1:
        return mesh_lib.shard_batch(mesh, X_local, y_local, axis=axis)

    # Equalize per-host block heights (allgather max), rounding up so the
    # global row count splits evenly over the data axis; padding rows are
    # mask-0 and exact no-ops in every kernel sum.  The even split is only
    # guaranteed when the axis divides across processes evenly — the
    # standard SPMD layout; reject anything else loudly.
    n_dev_axis = mesh.shape[axis]
    if n_dev_axis % jax.process_count():
        raise ValueError(
            f"mesh axis {axis!r} has {n_dev_axis} devices, not divisible "
            f"by {jax.process_count()} processes; per-host shard assembly "
            f"needs an even device-per-process split")
    per_host_quantum = n_dev_axis // jax.process_count()
    if pad_to_rows is not None:
        rows_host = int(pad_to_rows)
        if rows_host < n_local:
            raise ValueError(
                f"pad_to_rows={rows_host} < this host's {n_local} "
                "rows; the pinned block height must fit every "
                "assignment")
        if rows_host % per_host_quantum:
            raise ValueError(
                f"pad_to_rows={rows_host} must be a multiple of the "
                f"per-host device quantum {per_host_quantum}")
    else:
        rows_host = _allgather_max(n_local)
        rows_host = -(-rows_host // per_host_quantum) * per_host_quantum
    pad = rows_host - n_local
    mask_local = np.concatenate(
        [np.ones(n_local, np.float32), np.zeros(pad, np.float32)])
    X_local = np.concatenate(
        [X_local, np.zeros((pad, d), X_local.dtype)])
    y_local = np.concatenate([y_local, np.zeros(pad, np.float32)])

    n_global = rows_host * jax.process_count()
    row_spec = NamedSharding(mesh, P(axis))
    Xg = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(axis, None)), X_local, (n_global, d))
    yg = jax.make_array_from_process_local_data(
        row_spec, y_local, (n_global,))
    mg = jax.make_array_from_process_local_data(
        row_spec, mask_local, (n_global,))
    return mesh_lib.ShardedBatch(Xg, yg, mg)


def from_partitioned_files_csr(
    paths: Sequence[str],
    mesh=None,
    *,
    n_features: Optional[int] = None,
    binarize_labels: bool = True,
    with_csc: bool = True,
    balance: bool = True,
    loader: Optional[Callable[..., "libsvm.CSRData"]] = None,
    axis: str = mesh_lib.DATA_AXIS,
    retries: Optional[retry_lib.RetryPolicy] = None,
    telemetry=None,
    validate=False,
) -> mesh_lib.ShardedBatch:
    """Load a LIBSVM partition set into a mesh-sharded SPARSE batch —
    no densification at any point (r2 VERDICT item 3).

    Same host/partition contract as :func:`from_partitioned_files`; the
    result's ``X`` is a :class:`~spark_agd_tpu.ops.sparse.RowShardedCSR`
    (per-device local CSR slices, nnz-balanced within each host), so it
    feeds the same shard_map+psum kernels as ``mesh.shard_csr_batch``
    output.  Cross-host agreement costs two allgather-max reductions
    (rows-per-shard, padded nnz-per-shard); a host with no partitions
    contributes all-padding shards (mask 0 — exact no-ops in every sum).

    ``with_csc=True`` (default) builds each shard's column-sorted twin
    so the gradient uses sorted segment-sums.  ``n_features`` pins the
    global width (url_combined: 3,231,961); inferred by allgather-max
    when omitted.  ``retries``/``telemetry``/``validate``: per-partition
    reads run under the shared retrying helper and the same validation
    policy as :func:`from_partitioned_files` (``"drop"`` removes
    invalid rows BEFORE the nnz-balanced layout, so a poisoned
    partition costs rows, not the ingest).
    """
    if not paths:
        raise ValueError("no partition files")
    loader = _retrying_loader(loader or libsvm.load_libsvm, retries,
                              telemetry)
    mesh = mesh if mesh is not None else mesh_lib.make_mesh(
        {axis: len(jax.devices())})
    n_dev_axis = mesh.shape[axis]
    if n_dev_axis % jax.process_count():
        raise ValueError(
            f"mesh axis {axis!r} has {n_dev_axis} devices, not divisible "
            f"by {jax.process_count()} processes; per-host shard assembly "
            f"needs an even device-per-process split")
    local_shards = n_dev_axis // jax.process_count()

    parts = [loader(p, n_features=n_features)
             for p in local_partitions(paths)]
    d = n_features or _allgather_max(
        max((part.n_features for part in parts), default=0))
    if d == 0:
        raise ValueError("could not infer n_features (all partitions "
                         "empty on this host and none given)")
    parts = _validated_parts(local_partitions(paths), parts, d,
                             validate, telemetry)
    for p, part in zip(local_partitions(paths), parts):
        if len(part.indices) and int(part.indices.max()) >= d:
            raise ValueError(
                f"{p}: feature index {int(part.indices.max())} >= "
                f"n_features={d}")

    # concatenate this host's partitions into one local CSR triple
    row_ids, col_ids, values, ys = [], [], [], []
    row_base = 0
    for part in parts:
        counts = np.diff(part.indptr)
        row_ids.append(row_base + np.repeat(
            np.arange(len(counts), dtype=np.int64), counts))
        col_ids.append(np.asarray(part.indices, np.int64))
        values.append(np.asarray(part.values, np.float32))
        ys.append(part.binarized_labels() if binarize_labels
                  else np.asarray(part.labels))
        row_base += len(counts)
    n_local = row_base
    cat = (lambda xs, dt: np.concatenate(xs).astype(dt) if xs
           else np.zeros(0, dt))
    lay = mesh_lib.csr_shard_layout(
        cat(row_ids, np.int64), cat(col_ids, np.int64),
        cat(values, np.float32), cat(ys, np.float32), None,
        n_local, d, local_shards, balance=balance, with_csc=with_csc,
        reduce_max=_allgather_max)

    n_rows_global = _allgather_sum(n_local)
    if jax.process_count() == 1:
        return mesh_lib.place_csr_layout(lay, mesh, axis,
                                          n_rows_global, d)

    spec = NamedSharding(mesh, P(axis))
    nnz_g = n_dev_axis * lay["nnz_shard"]
    rows_g = n_dev_axis * lay["rps"]

    def g(a, n):
        return jax.make_array_from_process_local_data(
            spec, np.ascontiguousarray(a.reshape(-1)), (n,))

    csc = {}
    if with_csc:
        csc = dict(csc_row_ids=g(lay["Rc"], nnz_g),
                   csc_col_ids=g(lay["Cc"], nnz_g),
                   csc_values=g(lay["Vc"], nnz_g))
    from ..ops.sparse import RowShardedCSR

    Xs = RowShardedCSR(
        row_ids=g(lay["R"], nnz_g), col_ids=g(lay["C"], nnz_g),
        values=g(lay["V"], nnz_g), shape=(n_rows_global, d),
        rows_per_shard=lay["rps"], n_shards=n_dev_axis,
        rows_sorted=True, **csc)
    return mesh_lib.ShardedBatch(Xs, g(lay["Y"], rows_g),
                                 g(lay["M"], rows_g))
