"""LIBSVM reader — ingest for the sparse benchmark configs.

The reference reads data through Spark's text RDDs (``MLUtils.loadLibSVMFile``
in typical spark-agd usage); BASELINE configs 1 and 3 (rcv1.binary,
url_combined) are LIBSVM files.  This reader uses the native C++ parser
(``native/libsvm_parser.cpp``) when available and a pure-Python tokenizer
otherwise — same output either way: a CSR triple plus labels.

Sparse-on-TPU strategy (SURVEY §7 hard part 3): the MXU wants dense tiles,
so the default materialisation is row-dense (``to_dense``) for datasets
whose D fits HBM (rcv1: ~47k features is fine at bf16/f32 for moderate
batches); truly huge-D data stays CSR and flows through the segment-sum
kernel in ``ops.sparse`` or streams via ``data.streaming``.
"""

from __future__ import annotations

import io
from typing import NamedTuple, Optional

import numpy as np

from .. import native


class DataValidationError(ValueError):
    """The dataset itself is bad — a non-finite feature/label or an
    out-of-range feature index.  ``ValueError`` parent on purpose: the
    resilience classifier (``resilience.errors.classify_failure``) maps
    it FATAL — re-reading garbage yields the same garbage, so retry/
    backoff would only delay the failure.  Raised by ``validate="raise"``
    ingest; ``validate="drop"`` discards the offending rows instead and
    counts them (``data.invalid_records``)."""

    def __init__(self, where: str, problems):
        problems = list(problems)
        shown = "; ".join(problems[:5])
        more = f" (+{len(problems) - 5} more)" if len(problems) > 5 else ""
        super().__init__(f"{where}: invalid data: {shown}{more}")
        self.where = where
        self.problems = problems


class CSRData(NamedTuple):
    """Labels + CSR features; the LabeledPoint collection analogue."""

    labels: np.ndarray  # (n,) float64
    indptr: np.ndarray  # (n+1,) int64
    indices: np.ndarray  # (nnz,) int32, 0-based
    values: np.ndarray  # (nnz,) float32
    n_features: int

    @property
    def n_rows(self) -> int:
        return len(self.labels)

    def to_dense(self, n_features: Optional[int] = None,
                 dtype=np.float32) -> np.ndarray:
        d = n_features or self.n_features
        X = np.zeros((self.n_rows, d), dtype=dtype)
        for i in range(self.n_rows):
            s, e = self.indptr[i], self.indptr[i + 1]
            X[i, self.indices[s:e]] = self.values[s:e]
        return X

    def binarized_labels(self) -> np.ndarray:
        """Map {-1,+1} or {0,1} labels to {0,1} (the kernels' convention;
        MLlib requires the same)."""
        y = np.asarray(self.labels)
        return (y > 0).astype(np.float64)


def invalid_row_mask(data: CSRData,
                     n_features: Optional[int] = None) -> np.ndarray:
    """Boolean (n_rows,) mask of rows that must not reach training: a
    non-finite label, a non-finite feature value, or a feature index
    outside ``[0, n_features)``.  Index checks need a width — pass
    ``n_features`` or rely on ``data.n_features``."""
    d = int(n_features or data.n_features)
    bad = ~np.isfinite(np.asarray(data.labels, np.float64))
    values = np.asarray(data.values)
    indices = np.asarray(data.indices)
    bad_nnz = ~np.isfinite(values)
    if d > 0:
        bad_nnz |= (indices < 0) | (indices >= d)
    if bad_nnz.any():
        counts = np.diff(np.asarray(data.indptr))
        rows = np.repeat(np.arange(len(counts)), counts)
        bad |= np.isin(np.arange(len(counts)), rows[bad_nnz])
    return bad


def describe_invalid(data: CSRData, mask: np.ndarray) -> list:
    """Human-readable problems for the masked rows (first few; the
    DataValidationError payload)."""
    problems = []
    for i in np.nonzero(mask)[0][:8]:
        s, e = int(data.indptr[i]), int(data.indptr[i + 1])
        label = data.labels[i]
        if not np.isfinite(label):
            problems.append(f"row {i}: non-finite label {label!r}")
            continue
        vals = np.asarray(data.values[s:e])
        idxs = np.asarray(data.indices[s:e])
        nf = np.nonzero(~np.isfinite(vals))[0]
        if len(nf):
            problems.append(
                f"row {i}: non-finite value at feature "
                f"{int(idxs[nf[0]])}")
            continue
        oob = np.nonzero((idxs < 0) | (idxs >= data.n_features))[0]
        if len(oob):
            problems.append(
                f"row {i}: feature index {int(idxs[oob[0]])} outside "
                f"[0, {data.n_features})")
        else:
            problems.append(f"row {i}: invalid")
    return problems


def drop_rows(data: CSRData, mask: np.ndarray) -> CSRData:
    """``data`` without the masked rows (CSR re-packed; width kept)."""
    keep = ~np.asarray(mask, bool)
    counts = np.diff(np.asarray(data.indptr))
    nnz_keep = np.repeat(keep, counts)
    return CSRData(
        labels=np.asarray(data.labels)[keep],
        indptr=np.concatenate([[0], np.cumsum(counts[keep])]).astype(
            np.int64),
        indices=np.asarray(data.indices)[nnz_keep],
        values=np.asarray(data.values)[nnz_keep],
        n_features=data.n_features)


def validate_csr(data: CSRData, *, n_features: Optional[int] = None,
                 where: str = "data") -> None:
    """Raise :class:`DataValidationError` when any row is invalid."""
    mask = invalid_row_mask(data, n_features)
    if mask.any():
        raise DataValidationError(where, describe_invalid(data, mask))


def load_libsvm(path: str, n_features: Optional[int] = None,
                force_python: bool = False,
                validate: bool = False) -> CSRData:
    """Parse a LIBSVM file.  ``n_features`` overrides the inferred feature
    count (pass it when a test split lacks the train split's tail
    features).  ``validate=True`` additionally rejects non-finite
    features/labels and out-of-range indices with a typed
    :class:`DataValidationError` — LIBSVM text happily encodes ``nan``
    and the parser happily reads it, so an unvalidated bad file would
    otherwise train to garbage silently."""
    parsed = None if force_python else native.parse_libsvm_native(path)
    if parsed is None:
        parsed = _parse_python(path)
    labels, indptr, indices, values, inferred = parsed
    data = CSRData(labels, indptr, indices, values,
                   int(n_features or inferred))
    if validate:
        validate_csr(data, where=path)
    return data


def _parse_python(path: str):
    """Pure-Python fallback tokenizer (slow but dependency-free)."""
    labels, indptr, indices, values = [], [0], [], []
    max_idx = -1
    with io.open(path, "r", encoding="utf-8") as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            for tok in parts[1:]:
                idx_s, val_s = tok.split(":", 1)
                idx = int(idx_s) - 1
                if idx < 0:
                    raise ValueError(f"bad 1-based index in {tok!r}")
                max_idx = max(max_idx, idx)
                indices.append(idx)
                values.append(float(val_s))
            indptr.append(len(indices))
    return (np.asarray(labels, np.float64),
            np.asarray(indptr, np.int64),
            np.asarray(indices, np.int32),
            np.asarray(values, np.float32),
            max_idx + 1)


def save_libsvm(path: str, X, y) -> None:
    """Write dense (X, y) as LIBSVM text (test/bench fixture helper)."""
    X = np.asarray(X)
    y = np.asarray(y)
    with io.open(path, "w", encoding="utf-8") as f:
        for i in range(X.shape[0]):
            row = X[i]
            nz = np.nonzero(row)[0]
            toks = " ".join(f"{j + 1}:{row[j]:.9g}" for j in nz)
            f.write(f"{y[i]:.9g} {toks}\n")
