"""Model evaluation metrics — the ``mllib.evaluation`` surface, TPU-native.

The reference trains models through MLlib's ``GeneralizedLinearAlgorithm``
and its users evaluate them with ``org.apache.spark.mllib.evaluation``
(``BinaryClassificationMetrics`` / ``RegressionMetrics`` /
``MulticlassMetrics``).  That package is external to the reference repo
(same status as the Gradient/Updater contract, SURVEY §2.2) but part of
what a migrating user expects to find.  These are the batched
equivalents: every metric is a pure jittable ``jnp`` reduction — AUC is
the rank-based Mann-Whitney statistic (one on-device sort, average ranks
for ties; no threshold sweep), the confusion matrix is one segment-sum —
so evaluation runs on the same device (and at the same scale) as
training, instead of Spark's per-threshold RDD passes.

All functions take an optional ``mask`` (1.0 = valid) so padded batches
(``shard_batch`` / streaming) evaluate exactly like unpadded data.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _masked(v, mask):
    if mask is None:
        return v, v.shape[0]
    m = jnp.asarray(mask, v.dtype)
    return v * m, jnp.sum(m)


def _avg_ranks(scores, tie_break=None):
    """1-based ranks with ties sharing their group's AVERAGE rank (the
    Mann-Whitney convention) — one sort + two segment passes, O(N log N)
    on device.  ``tie_break`` (optional secondary key) both orders
    equal-score rows and SPLITS their tie group — ``roc_auc`` uses it to
    keep masked sink rows strictly below equal-valued valid rows."""
    n = scores.shape[0]
    if tie_break is None:
        order = jnp.argsort(scores, stable=True)
    else:
        order = jnp.lexsort((tie_break, scores))
    s_sorted = scores[order]
    # group ids: increment where the sorted value changes
    change = s_sorted[1:] != s_sorted[:-1]
    if tie_break is not None:
        t_sorted = tie_break[order]
        change = change | (t_sorted[1:] != t_sorted[:-1])
    new_group = jnp.concatenate(
        [jnp.ones((1,), jnp.int32), change.astype(jnp.int32)])
    gid = jnp.cumsum(new_group) - 1
    pos = jnp.arange(1, n + 1, dtype=jnp.float32)  # 1-based sorted rank
    gsum = jax.ops.segment_sum(pos, gid, num_segments=n,
                               indices_are_sorted=True)
    gcnt = jax.ops.segment_sum(jnp.ones_like(pos), gid, num_segments=n,
                               indices_are_sorted=True)
    avg = gsum / jnp.maximum(gcnt, 1.0)
    ranks_sorted = avg[gid]
    return jnp.zeros(n, jnp.float32).at[order].set(ranks_sorted)


def roc_auc(scores, labels, mask: Optional[jax.Array] = None):
    """Area under the ROC curve via the rank statistic:
    ``AUC = (Σ ranks(positives) − P(P+1)/2) / (P·N)``.

    Exactly the threshold-sweep trapezoid with average-rank tie handling
    (what ``BinaryClassificationMetrics.areaUnderROC`` converges to with
    per-score thresholds), in one device sort instead of an RDD pass per
    threshold.  Masked rows are excluded by pushing them below every
    valid score.  Returns NaN when either class is empty.
    """
    scores = jnp.asarray(scores, jnp.float32)
    y = jnp.asarray(labels, jnp.float32)
    if mask is not None:
        m = (jnp.asarray(mask, jnp.float32) > 0).astype(jnp.float32)
        # sink masked rows to -inf; the mask as tie-break key keeps them
        # STRICTLY below any valid row (even a valid -inf, and immune to
        # the f32 `min - 1 == min` collision at |min| >= 2^24)
        scores = jnp.where(m > 0, scores, -jnp.inf)
        y = y * m
        valid = m
        ranks = _avg_ranks(scores, tie_break=m)
    else:
        valid = jnp.ones_like(y)
        ranks = _avg_ranks(scores)
    n_pos = jnp.sum(y)
    n_val = jnp.sum(valid)
    n_neg = n_val - n_pos
    # masked rows occupy the LOWEST ranks (the sink): every valid row's
    # rank counts the masked block, so subtract it from positives' ranks
    n_masked = jnp.asarray(scores.shape[0], jnp.float32) - n_val
    rank_sum_pos = jnp.sum(ranks * y) - n_masked * n_pos
    auc = (rank_sum_pos - n_pos * (n_pos + 1.0) / 2.0) \
        / jnp.maximum(n_pos * n_neg, 1.0)
    return jnp.where((n_pos > 0) & (n_neg > 0), auc, jnp.nan)


def log_loss(probs, labels, mask: Optional[jax.Array] = None,
             eps: float = 1e-7):
    """Mean binary cross-entropy of predicted probabilities."""
    p = jnp.clip(jnp.asarray(probs, jnp.float32), eps, 1.0 - eps)
    y = jnp.asarray(labels, jnp.float32)
    ll = -(y * jnp.log(p) + (1.0 - y) * jnp.log1p(-p))
    ll, n = _masked(ll, mask)
    return jnp.sum(ll) / jnp.maximum(n, 1)


def binary_metrics(scores, labels, mask: Optional[jax.Array] = None,
                   threshold: float = 0.5) -> dict:
    """``BinaryClassificationMetrics``-style summary at one threshold
    plus threshold-free AUC.  ``scores`` are probabilities or margins
    (AUC is rank-based, so either works; the thresholded metrics assume
    ``scores > threshold`` predicts class 1)."""
    scores = jnp.asarray(scores, jnp.float32)
    y = jnp.asarray(labels, jnp.float32)
    pred = (scores > threshold).astype(jnp.float32)
    tp, _ = _masked(pred * y, mask)
    fp, _ = _masked(pred * (1.0 - y), mask)
    fn, _ = _masked((1.0 - pred) * y, mask)
    correct, n = _masked((pred == y).astype(jnp.float32), mask)
    tp, fp, fn = jnp.sum(tp), jnp.sum(fp), jnp.sum(fn)
    precision = tp / jnp.maximum(tp + fp, 1.0)
    recall = tp / jnp.maximum(tp + fn, 1.0)
    f1 = 2.0 * precision * recall / jnp.maximum(precision + recall,
                                                jnp.float32(1e-30))
    return {
        "accuracy": jnp.sum(correct) / jnp.maximum(n, 1),
        "precision": precision,
        "recall": recall,
        "f1": f1,
        "auc_roc": roc_auc(scores, y, mask),
    }


def regression_metrics(predictions, targets,
                       mask: Optional[jax.Array] = None) -> dict:
    """``RegressionMetrics`` equivalents: mse/rmse/mae/r2 and the
    explained-variance score ``1 − Var(t−p)/Var(t)`` (population
    variances; r2 uses the residual SUM of squares, so the two differ
    exactly when the residuals have nonzero mean)."""
    p = jnp.asarray(predictions, jnp.float32)
    t = jnp.asarray(targets, jnp.float32)
    err = p - t
    se, n = _masked(err * err, mask)
    ae, _ = _masked(jnp.abs(err), mask)
    n = jnp.maximum(n, 1)
    err_m, _ = _masked(err, mask)
    err_mean = jnp.sum(err_m) / n
    ve, _ = _masked((err - err_mean) ** 2, mask)
    tm, _ = _masked(t, mask)
    t_mean = jnp.sum(tm) / n
    tv, _ = _masked((t - t_mean) ** 2, mask)
    mse = jnp.sum(se) / n
    var_t = jnp.maximum(jnp.sum(tv) / n, jnp.float32(1e-30))
    return {
        "mse": mse,
        "rmse": jnp.sqrt(mse),
        "mae": jnp.sum(ae) / n,
        "r2": 1.0 - mse / var_t,
        "explained_variance": 1.0 - (jnp.sum(ve) / n) / var_t,
    }


def confusion_matrix(predictions, labels, num_classes: int,
                     mask: Optional[jax.Array] = None):
    """(K, K) counts[true, pred] via one segment-sum."""
    p = jnp.asarray(predictions, jnp.int32)
    y = jnp.asarray(labels, jnp.int32)
    idx = y * num_classes + p
    w = (jnp.ones(p.shape[0], jnp.float32) if mask is None
         else jnp.asarray(mask, jnp.float32))
    flat = jax.ops.segment_sum(w, idx,
                               num_segments=num_classes * num_classes)
    return flat.reshape(num_classes, num_classes)


def cv_validation_scores(cv, X, y, *, score_fn, predict_fn=None,
                         base_mask=None):
    """Score every (fold, strength) lane of an ``api.cross_validate``
    result with an arbitrary metric — e.g. select by held-out AUC
    instead of loss — in ONE vmapped program.

    ``score_fn(scores, labels, mask) -> SCALAR`` (e.g. :func:`roc_auc`,
    :func:`log_loss`, or a closure extracting one entry from the
    dict-returning metrics); ``predict_fn(w) -> scores`` maps one lane's
    weights to scores (default: the linear margin ``X @ w``).  Rows the
    CV excluded stay excluded: ``base_mask`` defaults to the mask the
    ``CVResult`` ran under (``cv.base_mask``).  Returns ``(per_lane
    (F, R), mean_per_strength (R,))`` — ``nanmean`` over folds.  Select
    with ``nanargmax``/``nanargmin`` per the metric's direction and
    check the winner is finite (a strength can be NaN in every fold,
    e.g. single-class validation sets under AUC; plain argmax would
    pick it).
    """
    from ..ops import sparse

    F, R = cv.val_loss.shape
    y = jnp.asarray(y)
    if base_mask is None:
        base_mask = getattr(cv, "base_mask", None)
    base = (jnp.ones(y.shape[0], jnp.float32) if base_mask is None
            else jnp.asarray(base_mask, jnp.float32))
    W = cv.train_result.weights
    flat_w = jax.tree_util.tree_map(
        lambda a: a.reshape((F * R,) + a.shape[2:]), W)
    fold_lane = jnp.repeat(jnp.arange(F, dtype=jnp.int32), R)

    # labels/masks/fold ids ride as jit arguments (lane-invariant), not
    # closure constants — constant-embedded data scales compile time
    # with the dataset (core.smooth.make_smooth_staged).  The default
    # linear-margin path threads X through the same argument tuple (r5
    # advisor: the old default closed over X, embedding the feature
    # matrix as a program constant — the exact defect class the staged
    # split removed everywhere else); a custom predict_fn still closes
    # over whatever it needs by API contract.
    if predict_fn is None:
        def one(w, fold_k, da):
            Xa, ya, basea, fids = da
            val_mask = basea * (fids == fold_k)
            return score_fn(sparse.matvec(Xa, w), ya, val_mask)

        dargs = (X, y, base, cv.fold_ids)
    else:
        def one(w, fold_k, da):
            ya, basea, fids = da
            val_mask = basea * (fids == fold_k)
            return score_fn(predict_fn(w), ya, val_mask)

        dargs = (y, base, cv.fold_ids)
    # graftlint: disable=donation -- w here is a read-only stacked
    # batch of candidate weights (vmap lanes) scored once, not a
    # mutated optimizer carry; nothing is aliased in place
    per_lane = jax.jit(jax.vmap(one, in_axes=(0, 0, None)))(
        flat_w, fold_lane, dargs).reshape(F, R)
    return per_lane, jnp.nanmean(per_lane, axis=0)


def multiclass_metrics(predictions, labels, num_classes: int,
                       mask: Optional[jax.Array] = None) -> dict:
    """``MulticlassMetrics`` equivalents from one confusion matrix:
    accuracy, per-class precision/recall/f1, macro averages."""
    cm = confusion_matrix(predictions, labels, num_classes, mask)
    total = jnp.maximum(jnp.sum(cm), 1.0)
    diag = jnp.diagonal(cm)
    col = jnp.sum(cm, axis=0)  # predicted-as-k counts
    row = jnp.sum(cm, axis=1)  # true-k counts
    precision = diag / jnp.maximum(col, 1.0)
    recall = diag / jnp.maximum(row, 1.0)
    f1 = 2.0 * precision * recall / jnp.maximum(precision + recall,
                                                jnp.float32(1e-30))
    return {
        "accuracy": jnp.sum(diag) / total,
        "confusion": cm,
        "precision_per_class": precision,
        "recall_per_class": recall,
        "f1_per_class": f1,
        "macro_precision": jnp.mean(precision),
        "macro_recall": jnp.mean(recall),
        "macro_f1": jnp.mean(f1),
    }
