"""Two-layer MLP trained with AGD via a custom Gradient (BASELINE config 5).

The reference's extension story for non-GLM models is "subclass MLlib's
``Gradient``" — the stretch config names "a custom Gradient for a two-layer
MLP".  Here the same seam is ``ops.losses.CustomGradient``: any batch loss
over a parameter *pytree*, differentiated by ``jax.grad``, dropped into the
unchanged AGD core (which is pytree-polymorphic through ``core.tvec``).
This module provides that custom gradient plus the trainer/model wrappers,
so config 5 is a first-class citizen rather than a recipe.

Non-convex caveat carried over honestly: AGD's convergence theory is convex;
on an MLP it is a heuristic (momentum + adaptive-L line search + O'Donoghue–
Candes restart, which is exactly what makes accelerated methods usable
non-convexly).  The default activation is ``tanh`` — smooth, so the
backtracking curvature estimates (reference ``:272-279`` semantics) stay
meaningful; ``relu`` is accepted for parity with common practice.

TP disposition (SURVEY §2.3): the hidden dimension is the ``model``-axis
sharding target — pass a mesh with a ``model`` axis and ``dist_mode='auto'``
and XLA shards ``W1 (D,H)``/``W2 (H,K)`` column/row-wise.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import api
from ..ops.losses import CustomGradient
from ..ops.prox import IdentityProx, L2Prox, Prox
from ..ops.sparse import matvec

_ACTIVATIONS = {
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "gelu": jax.nn.gelu,
}


def init_mlp_params(n_features: int, hidden_units: int, num_classes: int,
                    seed: int = 0, dtype=jnp.float32):
    """Glorot-scaled random init as a flat dict pytree.

    AGD cannot start an MLP at zeros (symmetric saddle — every hidden unit
    identical, gradient symmetric forever), so unlike the GLM trainers the
    default init is random and seeded.
    """
    rng = np.random.default_rng(seed)
    s1 = np.sqrt(2.0 / (n_features + hidden_units))
    s2 = np.sqrt(2.0 / (hidden_units + num_classes))
    return {
        "W1": jnp.asarray(
            rng.normal(0.0, s1, (n_features, hidden_units)), dtype),
        "b1": jnp.zeros((hidden_units,), dtype),
        "W2": jnp.asarray(
            rng.normal(0.0, s2, (hidden_units, num_classes)), dtype),
        "b2": jnp.zeros((num_classes,), dtype),
    }


def mlp_forward(params, X, activation: Callable = jnp.tanh):
    """Logits ``(N, K)``: two MXU matmuls with a fused elementwise between.
    First layer goes through the polymorphic ``matvec`` so CSR feature
    matrices (Criteo-style sparse rows) feed the same model."""
    h = activation(matvec(X, params["W1"]) + params["b1"])
    return h @ params["W2"] + params["b2"]


def make_mlp_loss_sum(activation: Callable = jnp.tanh):
    """Batch softmax cross-entropy *sum* (the kernel contract — sums, not
    means, so streaming/sharding accumulate associatively).  Signature
    matches ``CustomGradient(supports_mask=True)``: mask zeroes padded
    rows out of the loss and, through ``jax.grad``, out of the gradient."""

    def loss_sum(params, X, y, mask=None):
        logits = mlp_forward(params, X, activation)
        logz = jax.scipy.special.logsumexp(logits, axis=-1)
        picked = jnp.take_along_axis(
            logits, y.astype(jnp.int32)[:, None], axis=-1)[:, 0]
        per = logz - picked
        if mask is not None:
            per = per * mask.astype(per.dtype)
        return jnp.sum(per)

    return loss_sum


def mlp_gradient(activation="tanh") -> CustomGradient:
    """The config-5 deliverable: a drop-in ``Gradient`` for the AGD core."""
    act = _ACTIVATIONS[activation] if isinstance(activation, str) \
        else activation
    return CustomGradient(make_mlp_loss_sum(act), supports_mask=True)


class MLPModel:
    def __init__(self, params, activation: Callable = jnp.tanh):
        self.params = params
        self.activation = activation

    def logits(self, X):
        return mlp_forward(self.params, X, self.activation)

    def predict_proba(self, X):
        return jax.nn.softmax(self.logits(X), axis=-1)

    def predict(self, X):
        return jnp.argmax(self.logits(X), axis=-1)

    def __repr__(self):
        d, h = self.params["W1"].shape
        k = self.params["W2"].shape[1]
        return f"MLPModel(d={d}, hidden={h}, k={k})"

    # -- persistence (same npz discipline as the GLM models) --------------
    def save(self, path: str):
        from .glm import save_model

        save_model(self, path)

    def _to_payload(self) -> dict:
        name = next((n for n, f in _ACTIVATIONS.items()
                     if f is self.activation), None)
        if name is None:
            raise ValueError(
                "cannot persist a custom activation callable; use one "
                f"of the registered names {sorted(_ACTIVATIONS)}")
        payload = {"class": np.asarray("MLPModel"),
                   "activation": np.asarray(name)}
        payload.update({f"param_{k}": np.asarray(v)
                        for k, v in self.params.items()})
        return payload

    @classmethod
    def _from_npz(cls, z):
        name = str(z["activation"])
        act = _ACTIVATIONS.get(name)
        if act is None:
            raise ValueError(
                f"unknown activation {name!r} in saved MLP; known: "
                f"{sorted(_ACTIVATIONS)}")
        params = {k[len("param_"):]: jnp.asarray(z[k])
                  for k in z.files if k.startswith("param_")}
        return cls(params, act)


class MLPClassifierWithAGD:
    """Trainer mirroring the GLM trainers' shape: a public ``.optimizer``
    configured via the nine fluent setters, ``train(X, y) -> MLPModel``."""

    def __init__(self, hidden_units: int, num_classes: int = 2,
                 reg_param: float = 0.0, updater: Optional[Prox] = None,
                 activation: str = "tanh", seed: int = 0, mesh=None):
        self.hidden_units = int(hidden_units)
        self.num_classes = int(num_classes)
        self.seed = int(seed)
        self._act = (_ACTIVATIONS[activation]
                     if isinstance(activation, str) else activation)
        if updater is None:
            # a requested penalty must select a penalizing prox — IdentityProx
            # would silently ignore reg_param
            updater = L2Prox() if reg_param else IdentityProx()
        self.optimizer = api.AcceleratedGradientDescent(
            mlp_gradient(self._act), updater)
        self.optimizer.set_reg_param(reg_param)
        if mesh is not None:
            self.optimizer.set_mesh(mesh)
            if "model" in getattr(mesh, "shape", {}):
                self.optimizer.set_dist_mode("auto")

    def train(self, X, y, initial_params=None) -> MLPModel:
        if initial_params is None:
            initial_params = init_mlp_params(
                X.shape[1], self.hidden_units, self.num_classes, self.seed)
        params = self.optimizer.optimize((X, y), initial_params)
        return MLPModel(params, self._act)


from .glm import _MODEL_CLASSES  # noqa: E402  (registration, no cycle)

_MODEL_CLASSES["MLPModel"] = MLPModel
