"""Model layer: the ``GeneralizedLinearAlgorithm``-style callers the
reference's optimizer was built to plug into (see ``glm.py``), the
two-layer-MLP custom gradient of BASELINE config 5 (``mlp.py``), and the
``mllib.evaluation`` metric equivalents (``evaluation.py``)."""

from .evaluation import (  # noqa: F401
    binary_metrics,
    cv_validation_scores,
    confusion_matrix,
    log_loss,
    multiclass_metrics,
    regression_metrics,
    roc_auc,
)
from .glm import (  # noqa: F401
    GLMModel,
    load_model,
    save_model,
    GeneralizedLinearAlgorithm,
    LinearRegressionModel,
    LinearRegressionWithAGD,
    LogisticRegressionModel,
    LogisticRegressionWithAGD,
    LogisticRegressionWithLBFGS,
    SVMModel,
    SVMWithAGD,
    SoftmaxRegressionModel,
    SoftmaxRegressionWithAGD,
    SoftmaxRegressionWithLBFGS,
)
from .mlp import (  # noqa: F401
    MLPClassifierWithAGD,
    MLPModel,
    init_mlp_params,
    make_mlp_loss_sum,
    mlp_forward,
    mlp_gradient,
)
