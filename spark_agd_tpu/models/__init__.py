"""spark_agd_tpu.models subpackage."""
