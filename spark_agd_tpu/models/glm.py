"""Model layer — the ``GeneralizedLinearAlgorithm``-style callers.

The reference's optimizer implements the MLlib ``Optimizer`` trait exactly so
it can be dropped into MLlib's ``GeneralizedLinearAlgorithm`` subclasses
(``LogisticRegressionWithSGD`` & co.) in place of ``GradientDescent`` /
``LBFGS`` (reference ``AcceleratedGradientDescent.scala:41-42`` and the
class doc at ``:31-39``).  The reference repo itself ships no model layer —
it relies on MLlib's.  This module re-provides that surrounding layer
TPU-native, so a user of the reference who trained models through
``GeneralizedLinearAlgorithm`` finds the same workflow here:

- a trainer object holding a configurable ``.optimizer`` (the exact MLlib
  pattern: ``lr.optimizer.setNumIterations(...)``),
- ``train(X, y)`` → a typed model with ``predict``,
- optional intercept handling (MLlib prepends a bias term; the reference's
  own test does this manually at Suite:47-49 — ``add_intercept=True``
  automates it).

Weights stay on device end-to-end; ``predict`` is a jitted batched matmul
(MXU), not a per-row loop.  For the wide softmax weight matrix, pass a
``mesh`` with a ``model`` axis to shard classes (tensor parallelism —
SURVEY §2.3 disposition).
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import api
from ..ops.losses import (
    Gradient,
    HingeGradient,
    LeastSquaresGradient,
    LogisticGradient,
    SoftmaxGradient,
)
from ..ops.prox import IdentityProx, L1Prox, L2Prox, Prox
from ..ops.sparse import CSRMatrix, matvec


def _add_intercept(X):
    """Prepend the all-ones column (reference Suite:47-49 convention: the
    intercept is weight 0)."""
    if isinstance(X, CSRMatrix):
        n, d = X.shape
        # intercept entries: one per row at column 0; shift existing cols +1
        row_ids = jnp.concatenate(
            [jnp.arange(n, dtype=X.row_ids.dtype), X.row_ids])
        col_ids = jnp.concatenate(
            [jnp.zeros(n, X.col_ids.dtype), X.col_ids + 1])
        values = jnp.concatenate(
            [jnp.ones(n, X.values.dtype), X.values])
        csc = {}
        if X.has_csc:
            # prepending the all-col-0 intercept block keeps column order
            csc = dict(
                csc_row_ids=jnp.concatenate(
                    [jnp.arange(n, dtype=X.csc_row_ids.dtype),
                     X.csc_row_ids]),
                csc_col_ids=jnp.concatenate(
                    [jnp.zeros(n, X.csc_col_ids.dtype), X.csc_col_ids + 1]),
                csc_values=jnp.concatenate(
                    [jnp.ones(n, X.csc_values.dtype), X.csc_values]))
        # the interleave puts all intercept entries first: row ids are no
        # longer nondecreasing, so the forward copy drops its sorted claim
        return CSRMatrix(row_ids, col_ids, values, (n, d + 1),
                         want_csc=X.want_csc, **csc)
    X = jnp.asarray(X)
    return jnp.concatenate(
        [jnp.ones((X.shape[0], 1), X.dtype), X], axis=1)


class GLMModel:
    """Trained linear model: ``margin(x) = w·x + intercept``.

    The MLlib ``GeneralizedLinearModel`` analogue; weights live on device.
    """

    def __init__(self, weights, intercept: float = 0.0):
        self.weights = jnp.asarray(weights)
        self.intercept = float(intercept)

    def margin(self, X):
        return matvec(X, self.weights) + self.intercept

    def predict(self, X):
        raise NotImplementedError

    def predict_stream(self, dataset):
        """Iterate predictions over a ``data.streaming.StreamingDataset``
        — scoring's twin of the streamed training path, for data that
        never fits in memory.  Yields one NumPy array per macro-batch,
        padding rows (mask 0) already dropped; concatenate for the full
        vector or consume lazily."""
        import numpy as np

        for X, _, mask in dataset:
            pred = np.asarray(self.predict(X))
            if mask is not None:
                pred = pred[np.asarray(mask) > 0]
            yield pred

    def __repr__(self):
        return (f"{type(self).__name__}(d={self.weights.shape[0]}, "
                f"intercept={self.intercept:.4g})")

    # -- persistence (MLlib models are Saveable; reference-era workflow) --
    def save(self, path: str):
        """Atomic npz snapshot (class name + arrays + scalars); reload
        with :func:`load_model`."""
        save_model(self, path)

    @classmethod
    def _from_arrays(cls, weights, intercept, threshold):
        """Restore hook for :func:`load_model`; classes whose ctor shape
        differs (no threshold / vector intercept) override this."""
        return cls(weights, float(intercept), threshold=threshold)

    @classmethod
    def _from_npz(cls, z):
        return _decode_glm_npz(cls, z)

    def _to_payload(self) -> dict:
        return _glm_payload(self)


class LogisticRegressionModel(GLMModel):
    """Binary logistic model.  ``threshold`` semantics follow MLlib's
    ``clearThreshold`` convention: with a threshold, ``predict`` returns
    {0,1}; with ``threshold=None`` it returns raw probabilities."""

    def __init__(self, weights, intercept: float = 0.0,
                 threshold: Optional[float] = 0.5):
        super().__init__(weights, intercept)
        self.threshold = threshold

    def clear_threshold(self):
        self.threshold = None
        return self

    def predict_proba(self, X):
        return jax.nn.sigmoid(self.margin(X))

    def predict(self, X):
        p = self.predict_proba(X)
        if self.threshold is None:
            return p
        return (p > self.threshold).astype(jnp.float32)


class SVMModel(GLMModel):
    """Linear SVM: class = [margin > threshold] (default 0, as MLlib)."""

    def __init__(self, weights, intercept: float = 0.0,
                 threshold: Optional[float] = 0.0):
        super().__init__(weights, intercept)
        self.threshold = threshold

    def clear_threshold(self):
        self.threshold = None
        return self

    def predict(self, X):
        m = self.margin(X)
        if self.threshold is None:
            return m
        return (m > self.threshold).astype(jnp.float32)


class LinearRegressionModel(GLMModel):
    def predict(self, X):
        return self.margin(X)

    @classmethod
    def _from_arrays(cls, weights, intercept, threshold):
        del threshold  # regression has none
        return cls(weights, float(intercept))


class SoftmaxRegressionModel:
    """Multinomial model with weight matrix ``(D, K)`` (BASELINE config 4).

    Beyond spark-mllib 1.3's binary-only menu (SURVEY §2.2).  ``intercept``
    is a ``(K,)`` vector when the trainer added one, else zeros.
    """

    def __init__(self, weights, intercept=None):
        self.weights = jnp.asarray(weights)
        k = self.weights.shape[1]
        self.intercept = (jnp.zeros((k,), self.weights.dtype)
                          if intercept is None else jnp.asarray(intercept))

    @property
    def num_classes(self) -> int:
        return int(self.weights.shape[1])

    def logits(self, X):
        return matvec(X, self.weights) + self.intercept

    def predict_proba(self, X):
        return jax.nn.softmax(self.logits(X), axis=-1)

    def predict(self, X):
        return jnp.argmax(self.logits(X), axis=-1)

    def __repr__(self):
        d, k = self.weights.shape
        return f"SoftmaxRegressionModel(d={d}, k={k})"

    def save(self, path: str):
        save_model(self, path)

    @classmethod
    def _from_arrays(cls, weights, intercept, threshold):
        del threshold  # softmax predicts by argmax
        return cls(weights, intercept)

    @classmethod
    def _from_npz(cls, z):
        return _decode_glm_npz(cls, z)

    def _to_payload(self) -> dict:
        return _glm_payload(self)


def _decode_glm_npz(cls, z):
    thr = float(z["threshold"])
    return cls._from_arrays(z["weights"], z["intercept"],
                            None if np.isnan(thr) else thr)


def _glm_payload(model) -> dict:
    """The GLM-shaped npz payload (class name, weights, intercept,
    NaN-encoded optional threshold)."""
    payload = {"class": np.asarray(type(model).__name__),
               "weights": np.asarray(model.weights),
               "intercept": np.asarray(model.intercept)}
    thr = getattr(model, "threshold", None)
    payload["threshold"] = np.asarray(
        np.nan if thr is None else float(thr))
    return payload


def save_model(model, path: str):
    """Persist any registered model as one npz (atomic write via
    ``utils.checkpoint.atomic_savez``).  Dispatches through the model's
    ``_to_payload`` so save and :func:`load_model` stay symmetric for
    every class — including ones (the MLP) whose payload is not the
    GLM weights/intercept shape."""
    from ..utils.checkpoint import atomic_savez

    atomic_savez(path, model._to_payload())


_MODEL_CLASSES = {}


def load_model(path: str):
    """Reload a model saved by ``model.save``.  Each registered class
    owns its restore (``_from_npz``), so a class with a different
    payload shape (the MLP's parameter pytree, regression without a
    threshold) cannot silently fall into another's decode."""
    with np.load(path) as z:
        cls_name = str(z["class"])
        cls = _MODEL_CLASSES.get(cls_name)
        if cls is None:
            raise ValueError(
                f"unknown model class {cls_name!r} in {path}; known: "
                f"{sorted(_MODEL_CLASSES)}")
        return cls._from_npz(z)


class GeneralizedLinearAlgorithm:
    """Base trainer: holds a public ``.optimizer`` the user configures with
    the nine fluent setters — the exact MLlib workflow
    (``algo.optimizer.setNumIterations(20).setRegParam(0.1)``), with AGD
    in the optimizer seat the reference was built to occupy."""

    def __init__(self, gradient: Gradient, updater: Prox, *,
                 add_intercept: bool = False, mesh=None,
                 optimizer=None):
        """``optimizer``: the object in the optimizer seat — default a
        fresh ``AcceleratedGradientDescent(gradient, updater)``; pass an
        ``api.LBFGS`` (or anything with the Optimizer trait's
        ``optimize``) to swap, the exact interchange MLlib's
        ``GeneralizedLinearAlgorithm`` was built for.  When supplied,
        ``gradient``/``updater`` are NOT injected into it — the seat
        carries its own."""
        self.optimizer = (api.AcceleratedGradientDescent(gradient,
                                                         updater)
                          if optimizer is None else optimizer)
        if mesh is not None:
            self.optimizer.set_mesh(mesh)
        self.add_intercept = bool(add_intercept)

    def _create_model(self, weights, intercept) -> Any:
        raise NotImplementedError

    def _zero_weights(self, X):
        d = X.shape[1] + (1 if self.add_intercept else 0)
        return np.zeros(d, np.float32)

    def _split_intercept(self, w):
        if self.add_intercept:
            return w[1:], float(w[0])
        return w, 0.0

    def _prepare_fit(self, X, initial_weights):
        """Shared fit preamble: the (possibly intercept-augmented) design
        matrix and starting weights.  ``initial_weights`` is in
        *augmented* space when ``add_intercept`` (intercept first,
        matching the reference's manual column at Suite:47-49)."""
        data_X = _add_intercept(X) if self.add_intercept else X
        w0 = (self._zero_weights(X) if initial_weights is None
              else initial_weights)
        return data_X, w0

    def train(self, X, y, initial_weights=None):
        """Fit and return the typed model (see ``_prepare_fit`` for the
        ``initial_weights`` convention)."""
        data_X, w0 = self._prepare_fit(X, initial_weights)
        weights = self.optimizer.optimize((data_X, y), w0)
        return self._create_model(*self._split_intercept(weights))

    def _require_grid_optimizer(self, op_name: str):
        """Batched grid fits need the matching method on the optimizer
        seat (AGD has ``sweep`` + ``cross_validate``; LBFGS has
        ``sweep``) — a seat without it gets a named error instead of an
        AttributeError."""
        if not hasattr(self.optimizer, op_name):
            raise ValueError(
                f"{op_name} requires an optimizer seat providing it "
                f"(AcceleratedGradientDescent: sweep + cross_validate; "
                f"LBFGS: sweep only); "
                f"{type(self.optimizer).__name__} does not")

    def train_path(self, X, y, reg_params, initial_weights=None):
        """Fit the regularization path: K typed models from ONE compiled
        program (``api.sweep`` — the dataset stays in HBM once, the K
        margin products batch onto the MXU).  The trainer's configured
        ``reg_param`` is ignored; ``reg_params`` supplies the grid.

        Returns ``(models, result)``: the per-strength models in
        ``reg_params`` order plus the batched ``AGDResult`` (loss
        histories, iteration counts, diagnostics per lane).
        """
        self._require_grid_optimizer("sweep")
        data_X, w0 = self._prepare_fit(X, initial_weights)
        # config forwarding (and the IdentityProx / mesh guards) live on
        # the optimizer object, next to optimize()'s
        res = self.optimizer.sweep((data_X, y), reg_params, w0)
        w_all = jnp.asarray(res.weights)
        models = [
            self._create_model(*self._split_intercept(w_all[k]))
            for k in range(w_all.shape[0])
        ]
        return models, res

    def cross_validate(self, X, y, reg_params, n_folds: int = 5,
                       seed: int = 0, refit: bool = True):
        """K-fold CV over ``reg_params`` in one compiled program
        (``api.cross_validate``), then (``refit=True``) one final fit of
        the winning strength on ALL rows.  Returns ``(model, cv)`` —
        ``model`` is None when ``refit=False``."""
        self._require_grid_optimizer("cross_validate")
        reg_params = list(reg_params)  # consumed more than once below
        data_X, w0 = self._prepare_fit(X, None)
        cv = self.optimizer.cross_validate((data_X, y), reg_params, w0,
                                           n_folds=n_folds, seed=seed)
        model = None
        if refit:
            best_score = float(cv.mean_val_loss[int(cv.best_index)])
            if not np.isfinite(best_score):
                raise ValueError(
                    "cross-validation produced no finite validation "
                    "score (every fold/strength was empty or aborted); "
                    "refusing to refit an arbitrary strength")
            best = float(reg_params[int(cv.best_index)])
            old = self.optimizer._reg_param
            try:
                self.optimizer.set_reg_param(best)
                model = self.train(X, y)
            finally:
                self.optimizer.set_reg_param(old)
        return model, cv


class LogisticRegressionWithAGD(GeneralizedLinearAlgorithm):
    """BASELINE config 1: LogisticGradient + SquaredL2Updater-style prox."""

    def __init__(self, reg_param: float = 0.0, updater: Prox = None,
                 add_intercept: bool = True, mesh=None):
        super().__init__(
            LogisticGradient(),
            updater if updater is not None else L2Prox(),
            add_intercept=add_intercept, mesh=mesh)
        self.optimizer.set_reg_param(reg_param)

    def _create_model(self, weights, intercept):
        return LogisticRegressionModel(weights, intercept)


class LogisticRegressionWithLBFGS(GeneralizedLinearAlgorithm):
    """MLlib's ``LogisticRegressionWithLBFGS`` analogue: the same typed
    model and trainer workflow, with the quasi-Newton member in the
    optimizer seat (``api.LBFGS``) — the interchange the reference's
    ``Optimizer`` trait exists to allow.  L1 / elastic-net penalties
    dispatch to OWL-QN for single fits; ``train_path`` works from this
    seat too (``api.LBFGS.sweep``, smooth penalties only);
    ``cross_validate`` remains AGD-only and raises a named error."""

    def __init__(self, reg_param: float = 0.0,
                 num_corrections: int = 10, updater: Prox = None,
                 add_intercept: bool = True, mesh=None):
        updater = updater if updater is not None else L2Prox()
        gradient = LogisticGradient()
        super().__init__(
            gradient, updater,
            add_intercept=add_intercept, mesh=mesh,
            optimizer=api.LBFGS(gradient, updater))
        self.optimizer.set_reg_param(reg_param)
        self.optimizer.set_num_corrections(num_corrections)

    def _create_model(self, weights, intercept):
        return LogisticRegressionModel(weights, intercept)


class LinearRegressionWithAGD(GeneralizedLinearAlgorithm):
    """BASELINE config 2: LeastSquaresGradient.  Unregularized by default;
    a nonzero ``reg_param`` with no explicit updater selects the L2 prox
    (ridge) — never a silent no-op."""

    def __init__(self, reg_param: float = 0.0, updater: Prox = None,
                 add_intercept: bool = True, mesh=None):
        if updater is None:
            updater = L2Prox() if reg_param else IdentityProx()
        super().__init__(
            LeastSquaresGradient(), updater,
            add_intercept=add_intercept, mesh=mesh)
        self.optimizer.set_reg_param(reg_param)

    def _create_model(self, weights, intercept):
        return LinearRegressionModel(weights, intercept)


class SVMWithAGD(GeneralizedLinearAlgorithm):
    """BASELINE config 3: HingeGradient + L1Updater (sparse-model SVM).

    Note AGD's theory wants a smooth loss; hinge is subdifferentiable only —
    same caveat the reference inherits by accepting any MLlib ``Gradient``.
    Backtracking still terminates (``l_exact`` caps L growth at the MLlib
    semantics' expense); restarts keep it monotone enough in practice.
    """

    def __init__(self, reg_param: float = 0.0, updater: Prox = None,
                 add_intercept: bool = True, mesh=None):
        super().__init__(
            HingeGradient(),
            updater if updater is not None else L1Prox(),
            add_intercept=add_intercept, mesh=mesh)
        self.optimizer.set_reg_param(reg_param)

    def _create_model(self, weights, intercept):
        return SVMModel(weights, intercept)


class SoftmaxRegressionWithAGD(GeneralizedLinearAlgorithm):
    """BASELINE config 4 (MNIST-8M shape): multinomial softmax, weight
    matrix ``(D, K)``.  With a ``mesh`` carrying a ``model`` axis and
    ``dist_mode='auto'`` the class dimension is tensor-parallel."""

    def __init__(self, num_classes: int, reg_param: float = 0.0,
                 updater: Prox = None, add_intercept: bool = True,
                 mesh=None, optimizer=None):
        super().__init__(
            SoftmaxGradient(num_classes),
            updater if updater is not None else L2Prox(),
            add_intercept=add_intercept, mesh=mesh,
            optimizer=optimizer)
        self.num_classes = int(num_classes)
        self.optimizer.set_reg_param(reg_param)
        # model-axis tensor parallelism applies to WHATEVER sits in the
        # optimizer seat (AGD and LBFGS both expose set_dist_mode)
        if mesh is not None and "model" in getattr(mesh, "shape", {}):
            self.optimizer.set_dist_mode("auto")

    def _zero_weights(self, X):
        d = X.shape[1] + (1 if self.add_intercept else 0)
        return np.zeros((d, self.num_classes), np.float32)

    def _split_intercept(self, w):
        if self.add_intercept:
            return w[1:, :], w[0, :]
        return w, None

    def _create_model(self, weights, intercept):
        return SoftmaxRegressionModel(weights, intercept)


class SoftmaxRegressionWithLBFGS(SoftmaxRegressionWithAGD):
    """Multinomial classification with the quasi-Newton member in the
    optimizer seat — MLlib 1.3's ``LogisticRegressionWithLBFGS.
    setNumClasses(K)`` surface (its LBFGS path is the one MLlib
    recommends for multinomial).  The (D, K) weight matrix is just a
    pytree leaf to the fused L-BFGS loop."""

    def __init__(self, num_classes: int, reg_param: float = 0.0,
                 num_corrections: int = 10, updater: Prox = None,
                 add_intercept: bool = True, mesh=None):
        updater = updater if updater is not None else L2Prox()
        super().__init__(
            num_classes, reg_param=reg_param, updater=updater,
            add_intercept=add_intercept, mesh=mesh,
            optimizer=api.LBFGS(SoftmaxGradient(num_classes), updater))
        self.optimizer.set_num_corrections(num_corrections)


_MODEL_CLASSES.update({
    "LogisticRegressionModel": LogisticRegressionModel,
    "SVMModel": SVMModel,
    "LinearRegressionModel": LinearRegressionModel,
    "SoftmaxRegressionModel": SoftmaxRegressionModel,
})
