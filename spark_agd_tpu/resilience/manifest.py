"""Checksummed manifests — the COMMIT RECORD of a multi-host checkpoint.

The reference never faces this problem: Spark checkpoints nothing, and a
lost executor's partitions recompute from lineage.  Our SPMD port has N
processes each writing a shard file, and "the checkpoint exists" is only
true once ALL of them landed — a generation with a missing, torn, or
stale shard must be invisible to every loader.  The orbax-style answer
implemented here:

- every generation ``g`` consists of N shard files
  (``shard-g00000007.h000.npz`` …) plus ONE ``manifest-g00000007.json``;
- shard files are written first (atomic tempfile+rename per host); the
  manifest is written by the primary host ONLY AFTER an all-host
  barrier, so its existence proves every shard landed;
- the manifest carries the generation id, the saving topology
  (``process_count``, ``mesh_shape``), the problem fingerprint, and one
  ``{path, process, crc32, size}`` entry per shard — CRC32 of the FILE
  bytes, so a loader can verify a generation without parsing any npz;
- ``manifest.json`` is an atomically-replaced copy of the newest
  committed manifest (the "HEAD pointer"); per-generation manifests
  stay on disk as the fallback chain — the multi-host extension of the
  single-host ``.bak`` retention.

Loaders (``resilience.distributed``) walk generations newest → oldest
and REFUSE any generation whose manifest is unreadable, whose shard set
is incomplete, whose CRCs/sizes mismatch, or whose shards disagree on
the embedded generation id — falling back one generation instead of
resuming from a torn write.

Deliberately jax-free (stdlib + numpy): manifest reading/writing is
plain file IO a monitor process can do without a backend.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import tempfile
import time
import zlib
from typing import Dict, List, Optional

MANIFEST_FORMAT = "spark_agd_tpu.dist_checkpoint"
MANIFEST_VERSION = 1

# the HEAD pointer: an atomically-replaced copy of the newest committed
# per-generation manifest
HEAD_NAME = "manifest.json"

_MANIFEST_RE = re.compile(r"^manifest-g(\d{8})\.json$")


def shard_name(generation: int, process: int) -> str:
    """The shard file name convention: generation-stamped so a torn
    write of generation g+1 can never collide with (or shadow) a
    committed generation-g file."""
    return f"shard-g{generation:08d}.h{process:03d}.npz"


def manifest_name(generation: int) -> str:
    return f"manifest-g{generation:08d}.json"


def crc32_file(path: str, chunk: int = 1 << 20) -> int:
    """CRC32 of the file's bytes (streamed; shard files can be large)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                return crc
            crc = zlib.crc32(block, crc)


@dataclasses.dataclass(frozen=True)
class ShardEntry:
    """One host's shard in a committed generation."""

    path: str      # file name relative to the checkpoint directory
    process: int   # the saving host's process index
    crc32: int     # CRC32 of the file bytes
    size: int      # file size in bytes


@dataclasses.dataclass(frozen=True)
class Manifest:
    """One committed generation — see module docstring."""

    generation: int
    process_count: int
    shards: List[ShardEntry]
    mesh_shape: Optional[Dict[str, int]] = None
    fingerprint: Optional[str] = None
    converged: bool = False
    aborted: bool = False
    prior_iters: int = 0
    timestamp_unix: float = 0.0

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["format"] = MANIFEST_FORMAT
        d["manifest_version"] = MANIFEST_VERSION
        return json.dumps(d, indent=1, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "Manifest":
        d = json.loads(text)
        if d.get("format") != MANIFEST_FORMAT:
            raise ValueError(
                f"not a {MANIFEST_FORMAT} manifest "
                f"(format={d.get('format')!r})")
        if d.get("manifest_version") != MANIFEST_VERSION:
            raise ValueError(
                f"manifest version {d.get('manifest_version')!r} "
                f"unsupported (this code reads {MANIFEST_VERSION})")
        shards = [ShardEntry(**s) for s in d["shards"]]
        return cls(
            generation=int(d["generation"]),
            process_count=int(d["process_count"]),
            shards=shards,
            mesh_shape=d.get("mesh_shape"),
            fingerprint=d.get("fingerprint"),
            converged=bool(d.get("converged", False)),
            aborted=bool(d.get("aborted", False)),
            prior_iters=int(d.get("prior_iters", 0)),
            timestamp_unix=float(d.get("timestamp_unix", 0.0)))

    def shard_path(self, directory: str, process: int) -> str:
        for s in self.shards:
            if s.process == process:
                return os.path.join(directory, s.path)
        raise KeyError(f"manifest g{self.generation} has no shard for "
                       f"process {process}")


def _atomic_write_text(path: str, text: str) -> None:
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".json.tmp")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def write_manifest(directory: str, manifest: Manifest) -> str:
    """Commit one generation: write its per-generation manifest, then
    atomically repoint ``manifest.json`` at it.  The per-generation
    write is the commit point; a kill between the two writes leaves a
    stale HEAD, which loaders tolerate (they scan per-generation
    manifests when HEAD is older or unreadable)."""
    if manifest.timestamp_unix == 0.0:
        manifest = dataclasses.replace(
            manifest, timestamp_unix=round(time.time(), 3))
    text = manifest.to_json()
    path = os.path.join(directory, manifest_name(manifest.generation))
    _atomic_write_text(path, text)
    _atomic_write_text(os.path.join(directory, HEAD_NAME), text)
    return path


def repoint_head(directory: str, manifest: Manifest) -> str:
    """Atomically repoint ``manifest.json`` at an ALREADY-COMMITTED
    generation without writing a new one — the rollback half of the
    commit protocol (``serve.registry.repoint``).  The per-generation
    manifest chain is untouched; only the HEAD pointer moves, so a
    restart loads the repointed generation while every newer committed
    generation stays on disk as evidence."""
    head = os.path.join(directory, HEAD_NAME)
    _atomic_write_text(head, manifest.to_json())
    return head


def committed_generations(directory: str) -> List[int]:
    """Generation ids with a per-generation manifest on disk, newest
    first.  (A committed manifest may still fail verification — torn
    shards — which is what the loader's fallback walk is for.)"""
    if not os.path.isdir(directory):
        return []
    gens = []
    for name in os.listdir(directory):
        m = _MANIFEST_RE.match(name)
        if m:
            gens.append(int(m.group(1)))
    return sorted(gens, reverse=True)


def load_manifest(directory: str,
                  generation: Optional[int] = None) -> Optional[Manifest]:
    """Parse one manifest — the HEAD copy when ``generation`` is None
    (falling back to the newest per-generation file when HEAD is absent
    or unreadable).  Returns None when the directory holds no manifest
    at all; raises ``ValueError`` on a present-but-garbage file only
    when it was explicitly requested by generation."""
    if generation is not None:
        path = os.path.join(directory, manifest_name(generation))
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return Manifest.from_json(f.read())
    head = os.path.join(directory, HEAD_NAME)
    if os.path.exists(head):
        try:
            with open(head) as f:
                return Manifest.from_json(f.read())
        except (ValueError, OSError):
            pass  # torn HEAD rewrite: fall through to the scan
    gens = committed_generations(directory)
    if not gens:
        return None
    return load_manifest(directory, gens[0])


def verify_manifest(manifest: Manifest, directory: str) -> List[str]:
    """File-level verification of one committed generation: every shard
    present, with the manifest's exact size and CRC32.  Returns the
    problem list (``[]`` = the generation is loadable); the npz-level
    checks (embedded generation id, per-entry CRCs) happen in the
    loader, which must parse the shards anyway."""
    problems = []
    if len(manifest.shards) != manifest.process_count:
        problems.append(
            f"manifest g{manifest.generation} lists "
            f"{len(manifest.shards)} shards for process_count="
            f"{manifest.process_count}")
    seen = set()
    for s in manifest.shards:
        if s.process in seen:
            problems.append(f"duplicate shard for process {s.process}")
        seen.add(s.process)
        path = os.path.join(directory, s.path)
        if not os.path.exists(path):
            problems.append(f"shard {s.path} missing")
            continue
        size = os.path.getsize(path)
        if size != s.size:
            problems.append(
                f"shard {s.path}: size {size} != manifest {s.size} "
                "(torn write)")
            continue
        crc = crc32_file(path)
        if crc != s.crc32:
            problems.append(
                f"shard {s.path}: CRC32 {crc:#010x} != manifest "
                f"{s.crc32:#010x} (corrupt or stale file)")
    return problems


def gc_generations(directory: str, keep: int) -> List[str]:
    """Delete shard+manifest files of all but the ``keep`` newest
    committed generations (primary-host housekeeping after a commit).
    Returns the removed file names.  Uncommitted shard files (a torn
    write's orphans from a DEAD generation — older than the newest
    committed one) are removed too; orphans NEWER than the newest
    commit are left alone (they may be a commit in flight)."""
    gens = committed_generations(directory)
    if not gens:
        return []
    keep_set = set(gens[:max(1, keep)])
    newest = gens[0]
    removed = []
    for name in sorted(os.listdir(directory)):
        m = _MANIFEST_RE.match(name)
        g = None
        if m:
            g = int(m.group(1))
        else:
            s = re.match(r"^shard-g(\d{8})\.h\d{3}\.npz$", name)
            if s:
                g = int(s.group(1))
        if g is None or g in keep_set or g > newest:
            continue
        os.unlink(os.path.join(directory, name))
        removed.append(name)
    return removed
