"""Failure taxonomy + the ONE classifier every recovery path consults.

The reference inherits Spark's implicit taxonomy: a lost executor is
retried by the scheduler, a deterministic exception fails the job, and a
non-finite loss silently terminates the loop (reference
``AcceleratedGradientDescent.scala:309-312``).  Here the taxonomy is
explicit and shared — the supervisor (``resilience.supervisor``), the
retrying IO helper (``resilience.retry``), the sanitizer
(``utils.debug.report_numerics_failure``), and the fault-injection
harness (``resilience.faults``) all speak these kinds:

- ``TRANSIENT`` — worth retrying: simulated/real device loss, runtime/
  IO errors, attempt timeouts, and a lost peer host (``HostLost`` —
  retryable, but possibly on a CHANGED topology via the distributed
  checkpoint's elastic resume).  The supervisor retries with
  exponential backoff; the same attempt is expected to succeed.
- ``NUMERIC`` — the math went non-finite: retrying the identical
  attempt would fail identically.  The supervisor rolls back to the
  last-good ``AGDWarmState`` with a step-size cut instead.
- ``PREEMPTED`` — the host was told to go away (SIGTERM/SIGINT).  The
  auto-checkpointer has already flushed; the supervisor re-raises so
  the process can exit and a NEW process resumes from the checkpoint.
- ``FATAL`` — a programming/config error (ValueError, TypeError, …) or
  a lost quorum (``QuorumLost`` — retrying cannot resurrect hosts):
  retrying is noise; raise immediately with the attempt ledger.

Deliberately stdlib-only (no jax import): ``utils.debug`` and the data
layer import this leaf without dragging in the supervisor.
"""

from __future__ import annotations

from typing import List, Optional

TRANSIENT = "transient"
NUMERIC = "numeric"
PREEMPTED = "preempted"
FATAL = "fatal"

FAILURE_KINDS = (TRANSIENT, NUMERIC, PREEMPTED, FATAL)


class SimulatedDeviceLoss(RuntimeError):
    """A fault-injected stand-in for the runtime losing a device
    mid-run (TPU preemption sibling: the XLA ``DATA_LOSS`` /
    ``UNAVAILABLE`` RuntimeErrors).  Classified TRANSIENT."""


class HostLost(RuntimeError):
    """A PEER process of the SPMD job died or stopped heartbeating
    (``resilience.distributed.HostMonitor``) — the multi-host sibling of
    device loss.  Classified TRANSIENT: the work is retryable, but
    unlike a plain transient the retry may have to happen on a CHANGED
    topology (the dead host is gone), which is exactly what
    ``DistributedCheckpointer.load_for_topology`` resumes onto.  Spark's
    equivalent is a lost executor: the scheduler reruns its partitions
    elsewhere rather than failing the job."""

    def __init__(self, process_index: int, detail: str = "",
                 stale_for_s: Optional[float] = None):
        extra = f" ({detail})" if detail else ""
        if stale_for_s is not None:
            extra += f"; no heartbeat for {stale_for_s:.1f}s"
        super().__init__(
            f"host {process_index} lost{extra}; resume on the surviving "
            "topology via DistributedCheckpointer.load_for_topology")
        self.process_index = int(process_index)
        self.stale_for_s = stale_for_s


class QuorumLost(RuntimeError):
    """Too many peers are gone for a DEGRADED continuation
    (``resilience.degrade.DegradePolicy`` refused): the surviving
    process count is below quorum.  Classified FATAL — unlike a single
    ``HostLost``, retrying cannot resurrect the missing hosts; the run
    needs a full elastic restart on restored capacity (or an operator
    decision), and a supervisor must give up typed rather than back
    off forever."""

    def __init__(self, reason: str):
        super().__init__(
            f"quorum lost: {reason}; degraded continuation refused — "
            "restart elastically on restored capacity")


class StreamDataLoss(RuntimeError):
    """Too many shards of a streamed dataset are quarantined for the
    epoch to be statistically honest (``data.streaming.
    QuarantinePolicy`` refused): the surviving data fraction is below
    the policy's floor.  The data-plane sibling of :class:`QuorumLost`
    and classified FATAL for the same reason — retrying cannot
    un-poison the shards, and silently fitting on a sliver of the data
    would be worse than stopping."""

    def __init__(self, healthy: int, total: int, min_fraction: float):
        frac = healthy / total if total else 0.0
        super().__init__(
            f"stream data loss: {healthy}/{total} shards healthy "
            f"({frac:.3f} < minimum data fraction {min_fraction:g}); "
            "refusing to continue the degraded epoch — restore or "
            "replace the quarantined shards")
        self.healthy = int(healthy)
        self.total = int(total)
        self.min_fraction = float(min_fraction)


class ServeOverloaded(RuntimeError):
    """The serving plane's typed backpressure rejection
    (``serve.queue.MicroBatchQueue``): the micro-batching queue is at
    capacity and admitting the request would let latency grow without
    bound.  Classified TRANSIENT — the overload clears as the queue
    drains, so the client-side remedy is the same backoff-and-retry the
    supervisor applies to a lost device; the SERVER never retries (it
    sheds, which is the point)."""

    def __init__(self, queued_rows: int, limit_rows: int,
                 detail: str = ""):
        extra = f" ({detail})" if detail else ""
        super().__init__(
            f"serving queue overloaded: {queued_rows} rows queued "
            f"against a limit of {limit_rows}{extra}; back off and "
            "retry")
        # kept as attributes so the fleet transport can re-raise the
        # rejection typed on the client side with the numbers intact
        self.queued_rows = int(queued_rows)
        self.limit_rows = int(limit_rows)
        self.detail = detail
        self.queued_rows = int(queued_rows)
        self.limit_rows = int(limit_rows)


class NumericsFailureError(FloatingPointError):
    """The smooth evaluation (or the in-loop loss stream) went
    non-finite — raised by ``utils.debug.report_numerics_failure`` so a
    sanitizer hit enters the SAME rollback path as the fused loop's
    abort flag.  ``FloatingPointError`` parent: classified NUMERIC by
    type, not by message-matching."""


class Preempted(Exception):
    """Raised (from the ``AutoCheckpointer`` signal handler) after the
    preemption flush lands: the process must stop, and a rerun of the
    same call resumes from the flushed checkpoint."""

    def __init__(self, signum: Optional[int] = None):
        super().__init__(
            f"preempted (signal {signum}); final checkpoint flushed"
            if signum is not None else "preempted")
        self.signum = signum


class AttemptTimeout(TimeoutError):
    """The per-attempt wall-clock watchdog fired.  Classified
    TRANSIENT (a hung collective / stuck host looks exactly like a
    lost device from the driver's seat)."""

    def __init__(self, label: str, seconds: float):
        super().__init__(f"{label}: attempt exceeded {seconds:g}s "
                         "wall-clock watchdog")
        self.seconds = seconds


class SupervisorGivingUp(RuntimeError):
    """The policy's budget is exhausted (retries or rollbacks) or the
    failure was FATAL.  Carries the full attempt ledger so the
    post-mortem does not depend on scraping logs."""

    def __init__(self, message: str, ledger: Optional[List[dict]] = None):
        super().__init__(message)
        self.ledger = list(ledger or [])


# message fragments that mark a RuntimeError as the runtime losing its
# backend rather than a code bug (XLA status codes surface as text)
_TRANSIENT_RUNTIME_MARKERS = (
    "data_loss", "unavailable", "deadline_exceeded", "resource_exhausted",
    "device", "socket closed", "connection reset", "aborted",
)
_NUMERIC_MARKERS = ("non-finite", "nan", " inf")


def classify_failure(exc: BaseException) -> str:
    """Map one exception to a failure kind (module constants).

    Typed exceptions classify by type; bare ``RuntimeError`` (how both
    jaxlib's ``XlaRuntimeError`` and checkify's ``JaxRuntimeError``
    reach Python) falls back to message inspection — non-finite text
    means NUMERIC, device/status markers (or no marker at all) mean
    TRANSIENT, matching the issue contract "transient RuntimeError /
    device loss → retry".
    """
    if isinstance(exc, Preempted):
        return PREEMPTED
    if isinstance(exc, (NumericsFailureError, FloatingPointError,
                        ZeroDivisionError)):
        return NUMERIC
    if isinstance(exc, (QuorumLost, StreamDataLoss)):
        # unlike HostLost: retrying cannot bring a QUORUM (or the
        # quarantined shards) back — must be checked before the
        # transient isinstance row (RuntimeError)
        return FATAL
    if isinstance(exc, (SimulatedDeviceLoss, HostLost, ServeOverloaded,
                        TimeoutError, OSError, ConnectionError,
                        BrokenPipeError)):
        return TRANSIENT
    if isinstance(exc, (ValueError, TypeError, KeyError, AttributeError,
                        AssertionError, NotImplementedError)):
        return FATAL
    if isinstance(exc, RuntimeError):
        msg = str(exc).lower()
        if any(m in msg for m in _NUMERIC_MARKERS):
            return NUMERIC
        return TRANSIENT
    return FATAL
