"""``spark_agd_tpu.resilience`` — the supervision layer.

The reference inherits fault tolerance from Spark (task re-execution,
lineage recomputation, driver restart); the JAX/TPU runtime has none,
so this package rebuilds the recovery discipline around the one fact
that makes it cheap here: the complete optimizer state is two weight
pytrees plus three scalars.  Four modules (guide:
``docs/ROBUSTNESS.md``):

- ``errors`` — the failure taxonomy (TRANSIENT / NUMERIC / PREEMPTED /
  FATAL) and the ONE classifier every recovery path consults;
- ``retry`` — bounded retries, exponential backoff with deterministic
  jitter, per-attempt watchdog; shared by the supervisor and the data
  layer's flaky-IO wrappers;
- ``autockpt`` — cadence-based auto-checkpointing, a ``.bak``
  retention chain, corruption-tolerant resume, SIGTERM/SIGINT
  preemption flush;
- ``supervisor`` — the fault-aware driver: segmented AGD fits with
  classified failure handling (transient → retry; non-finite numerics
  → rollback to the last-good ``AGDWarmState`` with a step-size cut;
  preemption → flush and unwind; fatal → raise with the attempt
  ledger), plus the generic ``supervised_call`` for any other runner;
- ``faults`` — the deterministic fault-injection harness that proves
  all of the above (``tools/fault_drill.py`` runs the scripted
  kill-and-resume drill);
- ``manifest`` + ``distributed`` — the MULTI-HOST half (PR 4):
  barrier-committed generation checkpoints with checksummed manifests
  (``DistributedCheckpointer``), heartbeat files + ``HostLost``
  detection (``HeartbeatWriter``/``HostMonitor``), and elastic resume
  onto a changed topology (``load_for_topology``); drilled by
  ``tools/dist_fault_drill.py`` (SIGKILL one of two real processes,
  resume on one);
- ``chaos`` — seeded multi-fault campaigns (``ChaosSchedule``
  generalizing ``FaultScript`` to fault SEQUENCES, ``ChaosCampaign.
  generate(seed)`` for whole deterministic scenarios) and the campaign
  executor behind ``tools/chaos_drill.py``'s randomized soak;
- ``journal`` — the crash-safe recovery journal: an append-only,
  CRC-per-record, torn-tail-tolerant WAL of every recovery decision
  (attach ``JournalSink`` to the run's telemetry), replayable for
  post-mortems and exactly-once segment accounting across resumes;
- ``degrade`` — quorum-based graceful degradation: on a lost peer,
  ``DegradePolicy`` decides whether the survivors may keep training on
  the surviving data partitions (``load_degraded`` /
  ``DegradedCheckpointer``; below quorum → typed ``QuorumLost``)
  instead of a mandatory full restart;
- ``scheduler`` — straggler-aware scheduling: ``SkewTracker`` folds
  allgather-synced per-host boundary timings into a hysteresis-gated
  skew estimate, ``StragglerScheduler`` rebalances the partition
  assignment toward fast hosts at generation checkpoint boundaries
  (committed through the manifest protocol), and the speculation
  helpers re-execute a straggling segment from the last committed
  generation (deterministic math: first-result-wins is bit-safe);
  drilled by ``tools/straggler_drill.py``.

Every retry, rollback, preemption flush, and checkpoint fallback lands
as an ``attempt`` / ``recovery`` record in the canonical ``obs.schema``
JSONL, so resilience events live in the same stream as the metrics.
``api.run(..., resilience=ResiliencePolicy(...))`` is the one-argument
entry point.
"""

from .errors import (  # noqa: F401
    FATAL,
    FAILURE_KINDS,
    NUMERIC,
    PREEMPTED,
    TRANSIENT,
    AttemptTimeout,
    HostLost,
    NumericsFailureError,
    Preempted,
    QuorumLost,
    ServeOverloaded,
    SimulatedDeviceLoss,
    StreamDataLoss,
    SupervisorGivingUp,
    classify_failure,
)
from .retry import (  # noqa: F401
    BackoffSchedule,
    RetryPolicy,
    call_with_retry,
    retrying,
)
from .autockpt import AutoCheckpointer, generation_paths  # noqa: F401
from .supervisor import (  # noqa: F401
    ResiliencePolicy,
    SupervisedResult,
    run_agd_supervised,
    supervised_call,
)
from . import faults  # noqa: F401
from .faults import FaultScript  # noqa: F401
from . import manifest  # noqa: F401
from .distributed import (  # noqa: F401
    DistributedCheckpointer,
    HeartbeatWriter,
    HostMonitor,
    LoadedDistCheckpoint,
    load_for_topology,
)
from . import chaos  # noqa: F401
from .chaos import (  # noqa: F401
    ChaosCampaign,
    ChaosSchedule,
    ScheduledFault,
    run_campaign,
)
from . import journal  # noqa: F401
from .journal import Journal, JournalSink  # noqa: F401
from . import degrade  # noqa: F401
from .degrade import (  # noqa: F401
    DegradePolicy,
    DegradedCheckpointer,
    load_degraded,
)
from . import scheduler  # noqa: F401
from .scheduler import (  # noqa: F401
    RebalanceDecision,
    ReschedulePolicy,
    SkewTracker,
    StragglerScheduler,
    assign_weighted,
    resolve_speculation,
    run_speculative_segment,
    speculation_due,
)
