"""Chaos campaigns: seeded, composable MULTI-fault schedules and the
campaign executor that proves recovery against fault *sequences*.

``resilience.faults.FaultScript`` arms one fault per kind and fires it
once — enough to prove each recovery path in isolation, nothing like a
real pod's day: a straggler, then a preemption, then a torn write, all
against one run.  This module generalizes the harness:

- :class:`ScheduledFault` — one scripted fault: a kind, the iteration
  it arms at, the process it targets (``None`` = every process, which
  is MANDATORY for numeric faults in SPMD runs: a poison on one host
  would break collective lockstep), and a kind-specific payload.
- :class:`ChaosSchedule` — an ordered SEQUENCE of one-shot faults
  behind the exact supervisor interface ``FaultScript`` established
  (``before_segment`` / ``take_poison`` / ``fired`` / ``exhausted``),
  so it drops into ``run_agd_supervised(faults=...)`` unchanged.  Each
  fired fault is also emitted as a ``chaos`` telemetry record (and so
  lands in the recovery journal when a ``JournalSink`` is attached).
- :class:`ChaosCampaign` — a whole scenario, fully deterministic from
  one seed: the in-run faults per process plus the FILE faults
  (checkpoint truncation/scrambling) the driver applies at relaunch
  boundaries.  ``ChaosCampaign.generate(seed, ...)`` draws a
  normalized random campaign (file faults are always paired with an
  earlier preemption so a relaunch exists to apply them at; numeric
  faults are capped so the run can still re-converge).
- :func:`run_campaign` — the single-process campaign executor the soak
  driver (``tools/chaos_drill.py``) and the tier-1 tests share: run
  the supervised fit under the schedule, relaunch on preemption
  (applying due file faults to the checkpoint chain first), and
  classify the terminal outcome — ``converged`` (baseline-matching),
  ``gave_up`` (typed ``SupervisorGivingUp``), or the failure modes the
  drill treats as bugs (``mismatch``, ``stalled``).

Fault kinds (:data:`FAULT_KINDS`):

``nan``            poison the next segment (NUMERIC → rollback)
``device_loss``    raise ``SimulatedDeviceLoss`` (TRANSIENT → retry)
``slow_host``      sleep ``payload`` seconds at the boundary (a
                   straggler; peers just wait at the collective)
``sigterm``        self-deliver SIGTERM (preemption flush → relaunch)
``sigkill``        self-deliver SIGKILL (dead host; two-process drills)
``fatal``          raise :class:`InjectedFatalError` (FATAL → typed
                   ``SupervisorGivingUp`` — the give-up leg)
``truncate_ckpt``  byte-truncate the newest checkpoint (driver-applied
                   at the next relaunch; ``.bak``/generation fallback)
``scramble_ckpt``  overwrite checkpoint bytes in place (same seat)
``slow_replica``   sleep ``payload`` seconds before serving a request
                   (a degraded serving replica; fired by
                   ``before_request``, one-shot or persistent)
``kill_replica``   self-deliver SIGKILL at a request boundary (a dead
                   serving replica; the fleet drill's eviction leg)
``slow_reader``    sleep ``payload`` seconds before a shard read (a
                   degraded data source; fired by ``before_shard``)
``corrupt_shard``  overwrite the shard FILE's leading bytes with
                   garbage before the read — the poisoned-shard leg
                   the quarantine path must absorb typed
``hang_reader``    sleep ``payload`` seconds before a shard read, with
                   the payload sized ABOVE the reader's watchdog so
                   the attempt times out (``AttemptTimeout`` →
                   TRANSIENT → retry finds the fault popped)

The replica kinds drive the SERVE fleet (``serve.fleet`` replicas call
``before_request(request_index)`` per admitted request) with the same
deterministic seeded interface the training drills use; ``at_iter``
for them means the request index, not the optimizer iteration.  The
reader kinds drive the STREAMING data plane the same way
(``data.streaming.from_libsvm_parts`` calls ``before_shard(visit,
path=...)`` inside each retried shard load; ``at_iter`` = the
cumulative shard visit index across passes).

Everything is deterministic: iterations, targets, payloads, and the
corruption bytes all derive from the campaign seed.
"""

from __future__ import annotations

import dataclasses
import os
import signal as signal_lib
import time
from typing import Callable, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from . import faults as faults_lib
from .autockpt import AutoCheckpointer, generation_paths
from .errors import Preempted, SimulatedDeviceLoss, SupervisorGivingUp

IN_RUN_KINDS = ("nan", "device_loss", "slow_host", "sigterm", "sigkill",
                "fatal")
FILE_KINDS = ("truncate_ckpt", "scramble_ckpt")
# replica-scoped serve-fleet faults, fired per admitted request via
# ChaosSchedule.before_request (``at_iter`` = request index); appended
# AFTER the existing kinds so FAULT_KINDS.index-based sort keys (and
# every seeded campaign that derives from them) are unchanged
REPLICA_KINDS = ("slow_replica", "kill_replica")
# reader-scoped streaming faults, fired per shard visit via
# ChaosSchedule.before_shard (``at_iter`` = shard visit index); same
# append-only contract — AFTER every existing kind
READER_KINDS = ("slow_reader", "corrupt_shard", "hang_reader")
FAULT_KINDS = IN_RUN_KINDS + FILE_KINDS + REPLICA_KINDS + READER_KINDS

# the kinds persist=True is meaningful for: a degraded host/replica
# that stays degraded (kills and poisons are one-shot by nature)
_PERSISTABLE_KINDS = ("slow_host", "slow_replica")


class InjectedFatalError(ValueError):
    """A scripted configuration-class error (classified FATAL): the
    chaos pool's give-up leg — the supervisor must answer with a typed
    ``SupervisorGivingUp``, never a retry loop or a bare traceback."""


@dataclasses.dataclass(frozen=True)
class ScheduledFault:
    """One scripted fault of a campaign — see the module docstring.

    ``persist=True`` (``slow_host`` only) turns the one-shot boundary
    sleep into a PERSISTENT per-segment delay: the fault fires at
    EVERY boundary at or past ``at_iter``, sleeping ``payload *
    decay**n`` seconds on its n-th firing — a genuinely degraded host
    (``decay=1``: steady degradation; ``decay<1``: a host that slowly
    recovers, e.g. a transient noisy neighbor).  Persistent faults are
    exactly what the straggler scheduler (``resilience.scheduler``)
    exists to detect and rebalance away from."""

    kind: str
    at_iter: int
    process: Optional[int] = None  # None = every process
    payload: float = 0.0           # slow_host: seconds; truncate_ckpt:
    #                                keep fraction; scramble_ckpt: bytes
    persist: bool = False          # slow_host only: fire every boundary
    decay: float = 1.0             # persistent per-firing multiplier

    def __post_init__(self):
        if self.kind not in FAULT_KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r}; "
                             f"one of {FAULT_KINDS}")
        if self.at_iter < 0:
            raise ValueError("at_iter must be >= 0")
        if self.persist and self.kind not in _PERSISTABLE_KINDS:
            raise ValueError(
                f"persist=True is a {'/'.join(_PERSISTABLE_KINDS)} "
                f"modifier; a persistent {self.kind!r} has no meaning "
                "(kills and poisons are one-shot by nature)")
        if not 0.0 < self.decay <= 1.0:
            raise ValueError("decay must be in (0, 1]")


class ChaosSchedule:
    """A sequence of one-shot in-run faults behind the ``FaultScript``
    supervisor interface.  Faults fire in ``at_iter`` order at the
    first segment boundary at or past their iteration; one
    interrupting fault fires per boundary visit (the supervisor comes
    back after handling it, and the next due fault fires then).
    ``telemetry`` (optional): one ``chaos`` record per fired fault —
    flushed BEFORE a sigkill is delivered, so the kill itself is on
    record in the journal.

    PERSISTENT ``slow_host`` faults (``ScheduledFault(persist=True)``)
    fire at every boundary at or past their iteration, never exhaust,
    and never interrupt.  ``slow_scale`` (optional callable → float)
    scales every slow-host sleep at fire time — the straggler drill
    wires it to the host's CURRENT data share, so a rebalance that
    moves partitions off the degraded host genuinely shrinks its
    delay.  A bound heartbeat (``bind_heartbeat`` — the supervisor
    binds its own writer) is beaten ``phase="slow"`` at the start of
    every injected sleep and again every ``beat_interval_s`` during
    it, so a sleep longer than a monitor's staleness window reads as
    SLOW, not LOST (``distributed.HostMonitor.verdicts``)."""

    def __init__(self, faults: Sequence[ScheduledFault], *,
                 telemetry=None, seed: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 slow_scale: Optional[Callable[[], float]] = None,
                 beat_interval_s: float = 0.25):
        for f in faults:
            if f.kind in FILE_KINDS:
                raise ValueError(
                    f"{f.kind!r} is a FILE fault — applied by the "
                    "campaign driver at relaunch boundaries, not by "
                    "the in-run schedule (ChaosCampaign.file_faults)")
        ordered = sorted(faults, key=lambda f: (f.at_iter,
                                                FAULT_KINDS.index(f.kind)))
        self._poison = [f for f in ordered if f.kind == "nan"]
        self._persistent = [f for f in ordered
                            if f.kind == "slow_host" and f.persist]
        self._persist_fired = [0] * len(self._persistent)
        # replica-scoped faults fire at REQUEST boundaries
        # (before_request), never at segment boundaries — keeping them
        # out of _pending keeps before_segment's interrupt loop exact
        self._replica_persistent = [f for f in ordered
                                    if f.kind == "slow_replica"
                                    and f.persist]
        self._replica_fired = [0] * len(self._replica_persistent)
        self._replica_pending = [f for f in ordered
                                 if f.kind in REPLICA_KINDS
                                 and not f.persist]
        # reader-scoped faults fire at SHARD visits (before_shard),
        # never at segment boundaries
        self._reader_pending = [f for f in ordered
                                if f.kind in READER_KINDS]
        self._pending = [f for f in ordered
                         if f.kind != "nan" and not f.persist
                         and f.kind not in REPLICA_KINDS
                         and f.kind not in READER_KINDS]
        self._telemetry = telemetry
        self._seed = seed
        self._sleep = sleep
        self._slow_scale = slow_scale
        self._beat_interval_s = float(beat_interval_s)
        self._heartbeat = None
        self.fired: List[Tuple[str, int]] = []  # (kind, boundary iter)

    def bind_heartbeat(self, heartbeat) -> None:
        """Attach the host's ``HeartbeatWriter`` (the supervisor does)
        so injected sleeps keep beating — see the class docstring."""
        self._heartbeat = heartbeat

    def _emit(self, fault: ScheduledFault, global_iter: int,
              payload: Optional[float] = None) -> None:
        self.fired.append((fault.kind, global_iter))
        if self._telemetry is not None:
            fields = {"at_iter": int(fault.at_iter),
                      "fired_iter": int(global_iter)}
            if fault.process is not None:
                fields["process"] = int(fault.process)
            eff = fault.payload if payload is None else payload
            if eff:
                fields["payload"] = float(eff)
            if self._seed is not None:
                fields["seed"] = int(self._seed)
            self._telemetry.chaos(fault=fault.kind, **fields)

    def _slow_sleep(self, seconds: float, global_iter: int) -> None:
        """One injected straggler sleep.  With a bound heartbeat the
        sleep is chunked into sub-intervals with a ``phase="slow"``
        beat before each, so the host's liveness file never goes stale
        mid-sleep; without one the sleep is a single call (the
        historical behavior tests pin)."""
        if self._heartbeat is None:
            self._sleep(seconds)
            return
        remaining = float(seconds)
        while remaining > 0:
            try:
                self._heartbeat.beat(iter=global_iter, phase="slow")
            except OSError:
                pass  # a dying filesystem must not mask the drill
            chunk = min(remaining, self._beat_interval_s)
            self._sleep(chunk)
            remaining -= chunk

    # -- the supervisor hooks (FaultScript interface) ---------------------
    def before_segment(self, global_iter: int) -> None:
        for i, f in enumerate(self._persistent):
            if f.at_iter > global_iter:
                continue
            eff = float(f.payload) * (float(f.decay)
                                      ** self._persist_fired[i])
            if self._slow_scale is not None:
                eff *= max(0.0, float(self._slow_scale()))
            self._persist_fired[i] += 1
            if eff > 1e-9:
                # a fully-rebalanced-away (or fully-decayed) persistent
                # straggler goes quiet: no sleep, no record
                self._emit(f, global_iter, payload=eff)
                self._slow_sleep(eff, global_iter)
        while self._pending and self._pending[0].at_iter <= global_iter:
            f = self._pending.pop(0)
            self._emit(f, global_iter)
            if f.kind == "slow_host":
                scale = (max(0.0, float(self._slow_scale()))
                         if self._slow_scale is not None else 1.0)
                self._slow_sleep((float(f.payload) or 0.25) * scale,
                                 global_iter)
                continue  # a straggler interrupts nothing
            if f.kind == "sigkill":
                if self._telemetry is not None:
                    self._telemetry.flush()  # the kill must be on record
                os.kill(os.getpid(), signal_lib.SIGKILL)
            if f.kind == "sigterm":
                signal_lib.raise_signal(signal_lib.SIGTERM)
                time.sleep(0)  # let the Python-level handler run
                return
            if f.kind == "device_loss":
                raise SimulatedDeviceLoss(
                    f"injected device loss at iteration {global_iter}")
            if f.kind == "fatal":
                raise InjectedFatalError(
                    f"injected fatal config error at iteration "
                    f"{global_iter}")

    def before_request(self, request_index: int) -> None:
        """The serve-fleet mirror of :meth:`before_segment`: a replica
        calls this once per admitted request (``at_iter`` for replica
        kinds = request index).  Persistent ``slow_replica`` faults
        sleep at every request at or past their index (with the same
        ``phase="slow"`` heartbeat sub-beats, so a slowed replica reads
        SLOW and never LOST); one-shot ``slow_replica`` sleeps once;
        ``kill_replica`` flushes telemetry and self-delivers SIGKILL —
        a dead replica, mid-soak, with the kill on record."""
        for i, f in enumerate(self._replica_persistent):
            if f.at_iter > request_index:
                continue
            eff = float(f.payload) * (float(f.decay)
                                      ** self._replica_fired[i])
            if self._slow_scale is not None:
                eff *= max(0.0, float(self._slow_scale()))
            self._replica_fired[i] += 1
            if eff > 1e-9:
                self._emit(f, request_index, payload=eff)
                self._slow_sleep(eff, request_index)
        while self._replica_pending \
                and self._replica_pending[0].at_iter <= request_index:
            f = self._replica_pending.pop(0)
            self._emit(f, request_index)
            if f.kind == "slow_replica":
                self._slow_sleep(float(f.payload) or 0.25,
                                 request_index)
                continue
            if f.kind == "kill_replica":
                if self._telemetry is not None:
                    self._telemetry.flush()  # the kill must be on record
                os.kill(os.getpid(), signal_lib.SIGKILL)

    def before_shard(self, visit_index: int,
                     path: Optional[str] = None) -> None:
        """The streaming data plane's mirror of :meth:`before_request`:
        the shard loader calls this once per shard visit, INSIDE the
        retried attempt, so a fault that raises (or corrupts) is
        absorbed by the same retry/quarantine machinery a real flaky
        source would exercise.  ``visit_index`` counts shard visits
        cumulatively across passes; ``path`` is the shard file a
        ``corrupt_shard`` fault overwrites (the fault still fires — on
        record — when the caller cannot name a file).

        ``slow_reader`` and ``hang_reader`` both just sleep their
        payload: the difference is the contract with the caller's
        watchdog — a slow reader's payload is sized BELOW the attempt
        timeout (degraded throughput, same result), a hung reader's
        ABOVE it (the watchdog fires ``AttemptTimeout``, the retry
        comes back, and the popped fault lets the attempt succeed)."""
        while self._reader_pending \
                and self._reader_pending[0].at_iter <= visit_index:
            f = self._reader_pending.pop(0)
            self._emit(f, visit_index)
            if f.kind in ("slow_reader", "hang_reader"):
                self._slow_sleep(float(f.payload) or 0.25, visit_index)
                continue
            # corrupt_shard: stomp the file's leading bytes with text no
            # LIBSVM parser (native or Python) can read — the epoch must
            # quarantine the shard typed, not crash or silently skip
            if path is not None:
                size = os.path.getsize(path)
                garbage = b"\x00<chaos:corrupt_shard>\x00 not : libsvm\n"
                with open(path, "r+b") as fh:
                    fh.write(garbage[:max(1, size)])

    def take_poison(self, global_iter: int) -> bool:
        if self._poison and self._poison[0].at_iter <= global_iter:
            f = self._poison.pop(0)
            self._emit(f, global_iter)
            return True
        return False

    @property
    def exhausted(self) -> bool:
        """True once every ONE-SHOT fault has fired.  Persistent
        slow-host/slow-replica faults are deliberately excluded: they
        re-fire at every boundary by design, so counting them would
        make a degraded-host campaign read as eternally unfinished."""
        return (not self._pending and not self._poison
                and not self._replica_pending
                and not self._reader_pending)


@dataclasses.dataclass(frozen=True)
class ChaosCampaign:
    """One whole chaos scenario — a seed, its fault set, and the run
    shape it was drawn for.  Pure data: :meth:`schedule_for` builds the
    per-process in-run schedule, :meth:`file_faults` lists the
    driver-applied corruption faults."""

    seed: int
    faults: Tuple[ScheduledFault, ...]
    iters: int
    process_count: int = 1

    @classmethod
    def generate(cls, seed: int, *, iters: int = 48,
                 process_count: int = 1, max_faults: int = 4,
                 p_fatal: float = 0.15) -> "ChaosCampaign":
        """Draw one normalized random campaign, deterministic in
        ``seed``.  Normalization rules (so every campaign is a FAIR
        drill, not a guaranteed wedge): faults arm in the first ~70% of
        the budget (a late rollback must still have room to
        re-converge); at most two ``nan`` faults; file faults only ride
        along with an earlier ``sigterm`` (the relaunch they are
        applied at); in multi-process campaigns numeric/transient
        faults target every process (collective lockstep) while
        kill-class faults pick one victim; with probability ``p_fatal``
        the last fault becomes ``fatal`` — the typed give-up leg.
        About half the drawn ``slow_host`` faults come out PERSISTENT
        (``persist=True`` with a sub-1 decay, so the total injected
        delay stays bounded) — the genuinely-degraded-host scenario
        the straggler scheduler rebalances away from."""
        rng = np.random.default_rng(int(seed))
        pool = ["nan", "device_loss", "slow_host", "sigterm",
                "truncate_ckpt", "scramble_ckpt"]
        n = int(rng.integers(1, max(2, max_faults + 1)))
        hi = max(3, int(iters * 0.7))
        iters_at = sorted(rng.choice(
            np.arange(2, hi), size=min(n, hi - 2), replace=False))
        kinds = [str(pool[int(rng.integers(0, len(pool)))])
                 for _ in iters_at]
        # cap numeric faults at two (each costs a rollback's worth of
        # re-convergence headroom)
        while kinds.count("nan") > 2:
            kinds[kinds.index("nan")] = "device_loss"
        # file faults need a relaunch to be applied at: ensure a
        # sigterm precedes the first one
        file_idx = [i for i, k in enumerate(kinds) if k in FILE_KINDS]
        if file_idx and "sigterm" not in kinds[:file_idx[0]]:
            if file_idx[0] == 0:
                kinds[0] = "sigterm"
                file_idx = [i for i, k in enumerate(kinds)
                            if k in FILE_KINDS]
            else:
                kinds[file_idx[0] - 1] = "sigterm"
        if float(rng.random()) < p_fatal:
            kinds[-1] = "fatal"
        victim = int(rng.integers(0, process_count))
        out = []
        for k, at in zip(kinds, iters_at):
            payload = 0.0
            process: Optional[int] = None
            persist = False
            decay = 1.0
            if k == "slow_host":
                payload = float(rng.uniform(0.02, 0.08))
                if process_count > 1:
                    process = int(rng.integers(0, process_count))
                if float(rng.random()) < 0.5:
                    # the degraded-host variant: per-segment delay with
                    # a sub-1 decay so the total stays bounded (geometric
                    # sum <= payload / (1 - decay))
                    persist = True
                    payload = float(rng.uniform(0.01, 0.04))
                    decay = float(rng.uniform(0.5, 0.85))
            elif k == "truncate_ckpt":
                payload = float(rng.uniform(0.2, 0.7))
            elif k == "scramble_ckpt":
                payload = float(rng.integers(16, 128))
            elif k in ("sigterm", "sigkill", "fatal") \
                    and process_count > 1:
                process = victim
            out.append(ScheduledFault(kind=k, at_iter=int(at),
                                      process=process, payload=payload,
                                      persist=persist, decay=decay))
        return cls(seed=int(seed), faults=tuple(out), iters=int(iters),
                   process_count=int(process_count))

    @classmethod
    def generate_fleet(cls, seed: int, *, requests: int = 64,
                       replica_count: int = 3, max_faults: int = 2,
                       p_kill: float = 0.5) -> "ChaosCampaign":
        """Draw one normalized replica-scoped fleet campaign,
        deterministic in ``seed`` — the serve-fleet twin of
        :meth:`generate` (a SEPARATE draw path, so the training
        campaign pool and its seeded histories stay byte-identical).
        Normalization: faults arm in the first ~70% of the request
        budget; every fault targets ONE replica (``process`` = replica
        index) and no replica is hit twice — at least one replica
        always stays healthy so the router has a survivor to route to;
        with probability ``p_kill`` a fault is ``kill_replica``,
        otherwise a persistent ``slow_replica`` with a sub-1 decay."""
        if replica_count < 2:
            raise ValueError("a fleet campaign needs >= 2 replicas "
                             "(one fault victim plus one survivor)")
        rng = np.random.default_rng(int(seed))
        n = int(rng.integers(1, max(2, max_faults + 1)))
        n = min(n, replica_count - 1)  # one survivor, always
        hi = max(3, int(requests * 0.7))
        req_at = sorted(rng.choice(
            np.arange(1, hi), size=min(n, hi - 1), replace=False))
        victims = rng.choice(np.arange(replica_count),
                             size=len(req_at), replace=False)
        out = []
        for at, victim in zip(req_at, victims):
            if float(rng.random()) < p_kill:
                out.append(ScheduledFault(
                    kind="kill_replica", at_iter=int(at),
                    process=int(victim)))
            else:
                out.append(ScheduledFault(
                    kind="slow_replica", at_iter=int(at),
                    process=int(victim),
                    payload=float(rng.uniform(0.05, 0.2)),
                    persist=True,
                    decay=float(rng.uniform(0.85, 1.0))))
        return cls(seed=int(seed), faults=tuple(out),
                   iters=int(requests),
                   process_count=int(replica_count))

    @property
    def expects_giveup(self) -> bool:
        return any(f.kind == "fatal" for f in self.faults)

    def schedule_for(self, process: int = 0, *, telemetry=None,
                     sleep: Callable[[float], None] = time.sleep,
                     ) -> ChaosSchedule:
        mine = [f for f in self.faults if f.kind in IN_RUN_KINDS
                and (f.process is None or f.process == int(process))]
        return ChaosSchedule(mine, telemetry=telemetry, seed=self.seed,
                             sleep=sleep)

    def schedule_for_replica(self, replica: int, *, telemetry=None,
                             sleep: Callable[[float], None] = time.sleep,
                             ) -> ChaosSchedule:
        """The per-replica in-run schedule of a fleet campaign: the
        REPLICA_KINDS faults targeting ``replica`` (a ``process`` of
        None means every replica), behind the same ChaosSchedule
        interface — the replica drives it via ``before_request``."""
        mine = [f for f in self.faults if f.kind in REPLICA_KINDS
                and (f.process is None or f.process == int(replica))]
        return ChaosSchedule(mine, telemetry=telemetry, seed=self.seed,
                             sleep=sleep)

    def file_faults(self) -> Tuple[ScheduledFault, ...]:
        return tuple(f for f in self.faults if f.kind in FILE_KINDS)

    def describe(self) -> str:
        return (f"seed={self.seed} "
                + " ".join(f"{f.kind}"
                           + ("~persist" if f.persist else "")
                           + f"@{f.at_iter}"
                           + (f"/p{f.process}" if f.process is not None
                              else "")
                           for f in self.faults))


class CampaignResult(NamedTuple):
    outcome: str              # converged | gave_up | mismatch | stalled
    final_loss: Optional[float]
    diff: Optional[float]     # |final - baseline| (converged/mismatch)
    relaunches: int
    fired: List[Tuple[str, int]]   # every in-run fault that fired
    file_applied: List[str]        # file faults applied at relaunches
    giveup_message: Optional[str]  # SupervisorGivingUp text
    num_iters: int = 0        # iterations that COUNT at exit — the
    #                           journal's exactly-once census must match


def _apply_file_fault(fault: ScheduledFault, ckpt_path: str, keep: int,
                      seed: int, telemetry=None) -> Optional[str]:
    """Corrupt the newest EXISTING generation of the checkpoint chain
    per the fault's kind/payload; returns what was done (None when no
    checkpoint file exists yet to corrupt)."""
    target = next((p for p in generation_paths(ckpt_path, keep)
                   if os.path.exists(p)), None)
    if target is None:
        return None
    if fault.kind == "truncate_ckpt":
        kept = faults_lib.truncate_file(
            target, keep_fraction=float(fault.payload) or 0.4)
        what = f"truncate_ckpt:{os.path.basename(target)}:{kept}B"
    else:
        n = int(fault.payload) or 64
        faults_lib.scramble_file(target, seed=seed ^ fault.at_iter,
                                 n_bytes=n)
        what = f"scramble_ckpt:{os.path.basename(target)}:{n}B"
    if telemetry is not None:
        telemetry.chaos(fault=fault.kind, at_iter=int(fault.at_iter),
                        outcome=what, seed=int(seed))
    return what


def run_campaign(
    campaign: ChaosCampaign,
    *,
    staged,
    prox,
    reg_value,
    w0,
    config,
    policy,
    workdir: str,
    baseline_loss: float,
    telemetry=None,
    seg_cache: Optional[dict] = None,
    tol: float = 1e-6,
    keep: int = 4,
) -> CampaignResult:
    """Execute one SINGLE-process campaign to its terminal outcome —
    see the module docstring.  The relaunch loop is bounded by the
    fault count (every in-run fault is one-shot), so a campaign can
    never spin: exceeding the bound is reported as ``stalled``, which
    the drill counts as a failure (it would have been a hang)."""
    from .supervisor import run_agd_supervised

    ckpt_path = os.path.join(workdir, "chaos_ckpt.npz")
    schedule = campaign.schedule_for(0, telemetry=telemetry)
    file_queue = list(campaign.file_faults())
    file_applied: List[str] = []
    relaunches = 0
    max_relaunches = len(campaign.faults) + 2
    while True:
        ck = AutoCheckpointer(ckpt_path,
                              every_iters=policy.segment_iters,
                              keep=keep, telemetry=telemetry)
        try:
            res = run_agd_supervised(
                prox=prox, reg_value=reg_value, w0=w0, config=config,
                policy=policy, staged=staged, telemetry=telemetry,
                checkpointer=ck, faults=schedule,
                seg_cache=seg_cache, stream_iterations=False)
        except Preempted:
            relaunches += 1
            if relaunches > max_relaunches:
                return CampaignResult("stalled", None, None, relaunches,
                                      schedule.fired, file_applied, None)
            if file_queue:
                what = _apply_file_fault(
                    file_queue.pop(0), ckpt_path, keep, campaign.seed,
                    telemetry=telemetry)
                if what is not None:
                    file_applied.append(what)
            continue
        except SupervisorGivingUp as e:
            return CampaignResult("gave_up", None, None, relaunches,
                                  schedule.fired, file_applied, str(e))
        final = float(res.loss_history[-1])
        diff = abs(final - float(baseline_loss))
        outcome = "converged" if diff <= tol else "mismatch"
        return CampaignResult(outcome, final, diff, relaunches,
                              schedule.fired, file_applied, None,
                              num_iters=int(res.num_iters))
