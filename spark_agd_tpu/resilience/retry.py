"""Bounded retry with exponential backoff + deterministic jitter.

The ONE retry engine: the supervisor's per-segment attempt loop, the
data layer's flaky-IO wrappers (``data.ingest`` / ``data.streaming``),
and ad-hoc callers (``retrying(...)`` as a decorator) all run through
:func:`call_with_retry`, so backoff arithmetic, failure classification,
and the ``recovery`` record emitted per retry exist exactly once.

Jitter is DETERMINISTIC (seeded ``random.Random``): the fault-injection
drill asserts byte-stable trajectories, and a seeded schedule still
decorrelates thundering-herd restarts across hosts (seed defaults to a
per-process value).
"""

from __future__ import annotations

import dataclasses
import random
import threading
import time
from typing import Callable, Optional, Tuple

from . import errors


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """How hard to try: bounded attempts, exponential backoff, a
    wall-clock watchdog per attempt.

    ``max_attempts`` counts TOTAL tries (1 = no retry).  The sleep
    before retry ``i`` (1-based failure count) is
    ``min(backoff_max, backoff_base * backoff_factor**(i-1))``
    scaled by ``1 ± jitter`` (seeded).  ``attempt_timeout`` (seconds,
    None = off) runs the attempt under a watchdog thread and raises
    :class:`~spark_agd_tpu.resilience.errors.AttemptTimeout`
    (TRANSIENT) when it fires — NOTE the timed-out attempt's thread
    cannot be killed and is left to finish in the background; the
    watchdog bounds the *driver's* wait, not the work."""

    max_attempts: int = 3
    backoff_base: float = 0.05
    backoff_factor: float = 2.0
    backoff_max: float = 5.0
    jitter: float = 0.1
    seed: Optional[int] = None
    attempt_timeout: Optional[float] = None

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if self.backoff_base < 0 or self.backoff_max < 0:
            raise ValueError("backoff must be >= 0")
        if not 0.0 <= self.jitter <= 1.0:
            raise ValueError("jitter must be in [0, 1]")

    def backoff_schedule(self) -> "BackoffSchedule":
        return BackoffSchedule(self)


class BackoffSchedule:
    """Stateful sleep-length generator for ONE retry loop (the rng must
    not be shared across loops or the drill's schedule would depend on
    unrelated callers)."""

    def __init__(self, policy: RetryPolicy):
        self._p = policy
        seed = policy.seed
        if seed is None:
            seed = (id(self) ^ int(time.time() * 1e3)) & 0x7FFFFFFF
        self._rng = random.Random(seed)

    def next_delay(self, failure_index: int) -> float:
        """Sleep before retrying after the ``failure_index``-th (1-based)
        consecutive failure."""
        p = self._p
        base = min(p.backoff_max,
                   p.backoff_base * p.backoff_factor ** (failure_index - 1))
        if p.jitter:
            base *= 1.0 + p.jitter * self._rng.uniform(-1.0, 1.0)
        return max(0.0, base)


def run_with_watchdog(fn: Callable, args: tuple, kwargs: dict,
                      timeout: Optional[float], label: str):
    """Run ``fn(*args, **kwargs)``; raise ``AttemptTimeout`` if it is
    still running after ``timeout`` seconds (None = run inline)."""
    if timeout is None:
        return fn(*args, **kwargs)
    box: list = []

    def target():
        try:
            box.append(("ok", fn(*args, **kwargs)))
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            box.append(("err", e))

    t = threading.Thread(target=target, name=f"attempt:{label}",
                         daemon=True)
    t.start()
    t.join(timeout)
    if t.is_alive():
        raise errors.AttemptTimeout(label, timeout)
    status, payload = box[0]
    if status == "err":
        raise payload
    return payload


def call_with_retry(
    fn: Callable,
    *args,
    policy: Optional[RetryPolicy] = None,
    label: str = "call",
    retry_kinds: Tuple[str, ...] = (errors.TRANSIENT,),
    classify: Callable[[BaseException], str] = errors.classify_failure,
    telemetry=None,
    on_retry: Optional[Callable] = None,
    sleep: Callable[[float], None] = time.sleep,
    **kwargs,
):
    """``fn(*args, **kwargs)`` under ``policy``; retries failures whose
    classified kind is in ``retry_kinds``, re-raises everything else
    (and the last failure once attempts are exhausted).

    Each retry emits one ``recovery`` record (``action="retry"``) when a
    ``telemetry`` is attached, and calls ``on_retry(attempt, exc,
    delay)`` when given — the data layer passes a logger hook here so
    ingest retries are visible even without telemetry.
    """
    policy = policy or RetryPolicy()
    schedule = policy.backoff_schedule()
    failures = 0
    while True:
        try:
            return run_with_watchdog(fn, args, kwargs,
                                     policy.attempt_timeout, label)
        except Exception as e:  # noqa: BLE001 — classified below
            kind = classify(e)
            failures += 1
            if kind not in retry_kinds or failures >= policy.max_attempts:
                raise
            delay = schedule.next_delay(failures)
            if telemetry is not None:
                telemetry.recovery(
                    action="retry", reason=f"{type(e).__name__}: {e}",
                    failure_kind=kind, attempt=failures, backoff_s=delay,
                    source=label)
            if on_retry is not None:
                on_retry(failures, e, delay)
            if delay:
                sleep(delay)


def retrying(policy: Optional[RetryPolicy] = None, *,
             label: Optional[str] = None, telemetry=None,
             on_retry: Optional[Callable] = None,
             retry_kinds: Tuple[str, ...] = (errors.TRANSIENT,),
             **policy_kwargs):
    """Decorator / wrapper factory over :func:`call_with_retry` — the
    "small ``retrying(max_attempts, backoff, timeout)`` helper" the
    data layer wraps file opens in::

        loader = retrying(max_attempts=3, backoff_base=0.05)(open_part)
        part = loader(path)

    Keyword shorthands (``max_attempts=``, ``backoff_base=``,
    ``attempt_timeout=``, …) build the :class:`RetryPolicy` when one is
    not passed explicitly.
    """
    if policy is None:
        policy = RetryPolicy(**policy_kwargs)
    elif policy_kwargs:
        policy = dataclasses.replace(policy, **policy_kwargs)

    def wrap(fn: Callable) -> Callable:
        name = label or getattr(fn, "__name__", "call")

        def wrapped(*args, **kwargs):
            return call_with_retry(
                fn, *args, policy=policy, label=name,
                retry_kinds=retry_kinds, telemetry=telemetry,
                on_retry=on_retry, **kwargs)

        wrapped.__name__ = f"retrying_{name}"
        wrapped.__wrapped__ = fn
        return wrapped

    return wrap
