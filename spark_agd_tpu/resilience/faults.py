"""Deterministic, seedable fault injection — the harness that PROVES the
recovery machinery works.

Spark papers get their fault-tolerance evidence by killing executors;
this module is the single-process equivalent: every fault is scripted
(fires at an exact iteration / call count), one-shot (fires once, then
disarms, so the retried attempt succeeds — a permanently-poisoned
smooth would look FATAL, not TRANSIENT), and seeded where randomness is
involved.  Used by ``tests/test_resilience.py`` and the
``tools/fault_drill.py`` kill-and-resume drill.

Fault kinds:

- :func:`poison_smooth` — a smooth whose loss (and gradient) evaluate
  non-finite: drives the NUMERIC → rollback path.
- :class:`FaultScript` — iteration-scripted faults the supervisor
  consults at segment boundaries: simulated device loss
  (``device_loss_at_iter``), NaN poisoning of the next segment
  (``nan_at_iter``), a self-delivered SIGTERM (``sigterm_at_iter``)
  that exercises the preemption flush, and a self-delivered SIGKILL
  (``sigkill_at_iter``) — uncatchable, no flush — that plays the DEAD
  HOST in the multi-host drill (``tools/dist_fault_drill.py``).
- :func:`truncate_file` / :func:`scramble_file` — corrupt a checkpoint
  on disk: drives the ``.bak``-generation fallback.
- :func:`flaky` — a callable that fails its first N calls with an IO
  error (optionally sleeping first): drives the ingest retry path.

MULTI-fault sequences (a straggler, then a preemption, then a torn
write — against one run) are ``resilience.chaos``'s job:
``ChaosSchedule`` generalizes :class:`FaultScript` behind the same
supervisor hooks, ``ChaosCampaign.generate(seed)`` draws whole
deterministic scenarios, and ``tools/chaos_drill.py`` soaks the
recovery machinery against dozens of them.

Injection granularity note: the fused AGD loop is ONE compiled program,
so in-loop faults cannot fire at an arbitrary iteration of a running
segment; ``FaultScript`` fires at the first segment BOUNDARY at or
after the scripted iteration.  Pick ``segment_iters`` so the scripted
iterations are boundaries when exactness matters (the drill does).
"""

from __future__ import annotations

import os
import signal as signal_lib
import time
from typing import Callable, Optional

import numpy as np

from .errors import SimulatedDeviceLoss  # noqa: F401  (re-export)


def poison_smooth(smooth: Callable, mode: str = "nan") -> Callable:
    """A smooth returning non-finite loss AND gradient (trace-compatible
    — the poison is a multiplicative constant, so it works inside the
    fused jitted loop and on host drivers alike)."""
    if mode == "nan":
        bad = float("nan")
    elif mode == "inf":
        bad = float("inf")
    else:
        raise ValueError(f"unknown poison mode {mode!r}: 'nan' | 'inf'")

    def poisoned(w):
        loss, grad = smooth(w)
        import jax

        return loss * bad, jax.tree_util.tree_map(lambda g: g * bad,
                                                  grad)

    return poisoned


class FaultScript:
    """Iteration-scripted one-shot faults, consulted by the supervisor.

    Each ``*_at_iter`` arms one fault that fires at the first segment
    boundary whose global iteration count is >= the scripted value,
    then disarms.  ``fired`` records what fired and where, so a drill
    can assert the script actually executed.
    """

    def __init__(self, *, device_loss_at_iter: Optional[int] = None,
                 nan_at_iter: Optional[int] = None,
                 sigterm_at_iter: Optional[int] = None,
                 sigkill_at_iter: Optional[int] = None,
                 signum: int = signal_lib.SIGTERM):
        self._device_loss_at = device_loss_at_iter
        self._nan_at = nan_at_iter
        self._sigterm_at = sigterm_at_iter
        self._sigkill_at = sigkill_at_iter
        self._signum = signum
        self.fired: list = []  # (fault_name, global_iter) in fire order

    def _take(self, attr: str, global_iter: int) -> bool:
        at = getattr(self, attr)
        if at is not None and global_iter >= at:
            setattr(self, attr, None)  # one-shot
            return True
        return False

    # -- hooks the supervisor calls ---------------------------------------
    def before_segment(self, global_iter: int) -> None:
        """May raise / signal.  Called before each segment launches with
        the iterations completed so far."""
        if self._take("_sigkill_at", global_iter):
            # the HOST-DEATH fault: SIGKILL cannot be caught, so there
            # is no preemption flush, no unwind, no goodbye — exactly
            # the artifact a dead peer leaves behind (stale heartbeat,
            # possibly an uncommitted shard).  fired is appended first
            # only for the (untestable) case the kill fails.
            self.fired.append(("sigkill", global_iter))
            os.kill(os.getpid(), signal_lib.SIGKILL)
        if self._take("_sigterm_at", global_iter):
            self.fired.append(("sigterm", global_iter))
            signal_lib.raise_signal(self._signum)
            # the Python-level handler runs at the next bytecode
            # boundary; give it one (the AutoCheckpointer handler
            # raises Preempted from here)
            time.sleep(0)
        if self._take("_device_loss_at", global_iter):
            self.fired.append(("device_loss", global_iter))
            raise SimulatedDeviceLoss(
                f"injected device loss at iteration {global_iter}")

    def take_poison(self, global_iter: int) -> bool:
        """True exactly once, for the segment that should evaluate
        non-finite."""
        if self._take("_nan_at", global_iter):
            self.fired.append(("nan", global_iter))
            return True
        return False

    @property
    def exhausted(self) -> bool:
        return (self._device_loss_at is None and self._nan_at is None
                and self._sigterm_at is None
                and self._sigkill_at is None)


def truncate_file(path: str, keep_fraction: float = 0.5,
                  keep_bytes: Optional[int] = None) -> int:
    """Byte-truncate ``path`` in place (the classic kill-mid-write /
    torn-volume artifact a checkpoint loader must survive).  Returns
    the new size."""
    size = os.path.getsize(path)
    keep = (int(keep_bytes) if keep_bytes is not None
            else int(size * keep_fraction))
    keep = max(0, min(size - 1, keep))  # strictly smaller: truncation
    with open(path, "r+b") as f:
        f.truncate(keep)
    return keep


def scramble_file(path: str, seed: int = 0,
                  n_bytes: Optional[int] = None,
                  offset: int = 0) -> None:
    """Overwrite bytes of ``path`` with seeded garbage — corruption
    that keeps the original length (a bad sector, not a truncation).
    ``offset`` places the bad sector (default 0: the head, which kills
    npz/zip directories outright; a mid-file offset is the journal
    bit-flip case — everything before it must still replay)."""
    rng = np.random.default_rng(seed)
    size = os.path.getsize(path)
    offset = max(0, min(int(offset), size))
    n = (size - offset) if n_bytes is None else min(n_bytes,
                                                    size - offset)
    with open(path, "r+b") as f:
        f.seek(offset)
        f.write(rng.integers(0, 256, n, dtype=np.uint8).tobytes())


def flaky(fn: Callable, fail_times: int, *,
          exc: Callable[[str], Exception] = OSError,
          delay_s: float = 0.0,
          sleep: Callable[[float], None] = time.sleep) -> Callable:
    """``fn`` that raises ``exc`` on its first ``fail_times`` calls
    (after ``delay_s`` — a slow-then-dead read), then behaves normally.
    Deterministic: the failure count is the only state.  The standard
    stand-in for a flaky ingest source in tests and drills."""
    state = {"calls": 0}

    def wrapped(*args, **kwargs):
        state["calls"] += 1
        if state["calls"] <= fail_times:
            if delay_s:
                sleep(delay_s)
            raise exc(f"injected IO failure "
                      f"{state['calls']}/{fail_times} in "
                      f"{getattr(fn, '__name__', 'call')}")
        return fn(*args, **kwargs)

    wrapped.calls = lambda: state["calls"]
    return wrapped
