"""The fault-aware driver: supervised AGD fits with retry, rollback,
and auto-checkpointing.

The reference gets this layer for free from Spark — a failed task is
re-executed from lineage, a lost executor's partitions recompute, and
the driver survives by rerunning the job.  The JAX runtime offers none
of that, so the supervisor rebuilds it at the one place the math makes
cheap: the AGD carry is two weight pytrees plus three scalars
(``core.agd.AGDWarmState``), so "re-run from last-good state" costs a
tiny host copy, not a lineage graph.

The execution model is SEGMENTED: ``policy.segment_iters`` compiled
iterations per attempt (one jitted program per distinct segment
length, exactly like ``utils.checkpoint.run_agd_checkpointed``).  Each
segment runs under the retry engine (``resilience.retry``) with the
shared failure taxonomy (``resilience.errors``):

- TRANSIENT (device loss, runtime/IO errors, watchdog timeouts) —
  retry the SAME segment from the same warm state, after exponential
  backoff + jitter, at most ``max_attempts`` tries per segment;
- NUMERIC (a non-finite loss — the fused loop's abort flag, or a
  ``NumericsFailureError`` out of ``utils.debug``'s sanitizer) —
  ROLL BACK: restore the last-good warm state with its Lipschitz
  estimate multiplied by ``rollback_l_factor`` (the proximal step is
  ``1/L``, so this is the step-size cut), at most ``max_rollbacks``
  times; the poisoned segment's iterations and history are discarded;
- PREEMPTED — the auto-checkpointer's handler already flushed;
  re-raise so the process exits and the NEXT process resumes;
- FATAL — raise :class:`SupervisorGivingUp` immediately, attempt
  ledger attached.

Every attempt lands as an ``attempt`` record and every recovery action
as a ``recovery`` record in the canonical ``obs.schema`` JSONL, next to
the run's metrics.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Any, Callable, List, NamedTuple, Optional

import jax
import numpy as np

from ..core import agd
from ..core.agd import AGDConfig, AGDWarmState
from ..obs import flight as flight_lib
from ..utils import checkpoint as ckpt
from . import errors, faults as faults_lib, retry as retry_lib


@dataclasses.dataclass(frozen=True)
class ResiliencePolicy(retry_lib.RetryPolicy):
    """The supervisor's knob set: the retry engine's fields
    (``max_attempts``, ``backoff_*``, ``jitter``, ``seed``,
    ``attempt_timeout``) plus the rollback and segmentation policy.

    ``segment_iters=None`` runs the whole remaining budget as one
    attempt (cheapest; rollback then restarts from the initial point or
    the last checkpoint).  Smaller segments bound how much work one
    fault can destroy — and set the granularity of auto-checkpoints,
    fault injection, and preemption points.

    ``max_wall_seconds`` (None = unbounded) is the run's total
    wall-clock budget: once exceeded, the supervisor STOPS retrying —
    a DEADLINE-tagged entry lands in the ledger and
    :class:`~spark_agd_tpu.resilience.errors.SupervisorGivingUp` is
    raised — instead of backing off forever against a fault that is
    never going to clear.  Checked at segment boundaries (a compiled
    segment cannot be interrupted mid-flight; bound single-attempt
    time with ``attempt_timeout``).
    """

    max_rollbacks: int = 3
    rollback_l_factor: float = 4.0
    segment_iters: Optional[int] = None
    max_wall_seconds: Optional[float] = None

    def __post_init__(self):
        super().__post_init__()
        if self.max_rollbacks < 0:
            raise ValueError("max_rollbacks must be >= 0")
        if self.rollback_l_factor <= 1.0:
            raise ValueError(
                "rollback_l_factor must be > 1 (a rollback must CUT "
                "the step, or the retried segment fails identically)")
        if self.segment_iters is not None and self.segment_iters < 1:
            raise ValueError("segment_iters must be >= 1")
        if self.max_wall_seconds is not None and self.max_wall_seconds <= 0:
            raise ValueError("max_wall_seconds must be > 0")


class SupervisedResult(NamedTuple):
    weights: Any
    loss_history: np.ndarray
    num_iters: int            # executed iterations that COUNT (rolled-
    #                           back segments' work is discarded)
    converged: bool
    aborted_non_finite: bool  # True only when rollbacks were exhausted
    #                           and the policy said to return, not raise
    retries: int              # transient re-attempts across the run
    rollbacks: int            # numeric rollbacks across the run
    resumed_from: int         # iterations already checkpointed at start
    attempts: List[dict]      # the full ledger, one dict per attempt


def _rollback(warm: AGDWarmState, factor: float) -> AGDWarmState:
    """Last-good carry with the step cut: the proximal step is ``1/L``,
    so multiplying the Lipschitz estimate by ``factor`` shrinks the
    next step by the same ratio.  ``bts=True`` re-arms backtracking so
    the cut estimate can still grow back if it proves conservative."""
    return warm._replace(big_l=float(warm.big_l) * float(factor),
                         bts=True)


def run_agd_supervised(
    smooth: Optional[Callable] = None,
    prox: Callable = None,
    reg_value: Callable = None,
    w0: Any = None,
    config: AGDConfig = None,
    *,
    policy: Optional[ResiliencePolicy] = None,
    telemetry=None,
    checkpointer=None,
    staged=None,
    driver: str = "fused",
    smooth_loss: Optional[Callable] = None,
    faults: Optional["faults_lib.FaultScript"] = None,
    place_w: Optional[Callable] = None,
    heartbeat=None,
    monitor=None,
    scheduler=None,
    seg_cache: Optional[dict] = None,
    stream_iterations: bool = True,
    sleep: Callable[[float], None] = time.sleep,
    clock: Callable[[], float] = time.monotonic,
) -> SupervisedResult:
    """Run one AGD fit to completion under the supervision policy.

    ``staged=(build, data_args)`` (from ``core.smooth.
    make_smooth_staged`` / the dist twin) passes the data THROUGH jit
    as arguments — mandatory at scale (a closure smooth embeds the
    dataset as program constants); ``smooth``/``smooth_loss`` closures
    remain supported for small problems.  ``place_w`` (optional) maps
    the initial weights onto devices (mesh replication) before the
    first segment.

    ``driver="host"`` runs each segment through ``core.host_agd.
    run_agd_host`` instead of the fused ``lax.while_loop`` — REQUIRED
    when ``smooth`` is itself a host-level loop (``data.streaming.
    make_streaming_smooth``): a streamed smooth cannot trace into jit.
    The whole supervision envelope — retries, rollbacks,
    checkpointing, chaos poison, watchdog — is unchanged; only the
    segment executor differs.  ``staged`` is fused-only (the host
    driver never embeds data in a program) and per-iteration telemetry
    streaming does not apply.

    ``checkpointer`` (an :class:`~spark_agd_tpu.resilience.autockpt.
    AutoCheckpointer`): resume happens from its surviving generation
    (corruption-tolerant), each completed segment is offered for a
    cadence save, signal handlers are installed for the duration of
    the run, and terminal states are force-flushed.

    ``faults`` (a :class:`~spark_agd_tpu.resilience.faults.FaultScript`
    or :class:`~spark_agd_tpu.resilience.chaos.ChaosSchedule` — any
    object with the same ``before_segment``/``take_poison`` hooks):
    consulted at segment boundaries — test/drill only.

    ``seg_cache`` (a dict, default private): the jitted-segment cache,
    keyed ``(segment length, poisoned)``.  Pass ONE dict across
    repeated calls that share the same ``staged``/``smooth``/``prox``/
    ``config`` (and the same telemetry-streaming state) to skip
    re-tracing — the chaos soak driver runs dozens of supervised fits
    of one problem and pays compilation once.  Never share it across
    different problems or different in-loop callbacks.

    ``stream_iterations=False`` skips the in-loop per-iteration
    telemetry callback (the host round-trip per iteration) while
    keeping every attempt/recovery/heartbeat record — the right mode
    for drills, and REQUIRED when ``seg_cache`` is shared across runs
    with different ``Telemetry`` objects (the callback would be baked
    into the cached program).

    ``heartbeat`` (a :class:`~spark_agd_tpu.resilience.distributed.
    HeartbeatWriter`): beaten at every segment boundary and once at
    exit, so a peer/babysitter can detect this host's death within one
    segment.  ``monitor`` (a :class:`~spark_agd_tpu.resilience.
    distributed.HostMonitor`): checked before each segment; a stale
    peer raises ``HostLost``, which classifies TRANSIENT — retried with
    backoff here, and resumable onto a changed topology by a relaunch
    (``DistributedCheckpointer.load_for_topology``).

    ``scheduler`` (a :class:`~spark_agd_tpu.resilience.scheduler.
    StragglerScheduler`): the straggler feedback loop.  Each successful
    segment's HOST-LOCAL boundary seconds (where chaos ``slow_host``
    sleeps and real per-host work land — in lockstep SPMD the coupled
    segment timings tie) feed ``scheduler.after_segment``; a returned
    :class:`~spark_agd_tpu.resilience.scheduler.RebalanceDecision` is
    applied AT THE GENERATION BOUNDARY: the staged data args are
    swapped for the rebuilt assignment and the checkpointer
    force-commits a generation carrying the new partition list, so a
    crash mid-rebalance resumes consistently from either side of the
    commit.  ``scheduler=None`` leaves this path untouched
    (bit-identical to a plain supervised run — pinned by tests).
    """
    if w0 is None or config is None:
        raise ValueError("w0 and config are required")
    if staged is None and smooth is None:
        raise ValueError("pass smooth=... or staged=(build, data_args)")
    if driver not in ("fused", "host"):
        raise ValueError(
            f"driver must be 'fused' or 'host'; got {driver!r}")
    if driver == "host":
        if staged is not None:
            raise ValueError(
                "staged=(build, data_args) applies to the fused driver "
                "only; the host driver never embeds data in a program")
        if smooth is None:
            raise ValueError("driver='host' needs smooth=...")
    if scheduler is not None and getattr(scheduler, "rebuild", None) \
            is not None and staged is None:
        raise ValueError(
            "scheduler rebalancing swaps the staged data arguments: "
            "pass staged=(build, data_args), not a closure smooth")
    policy = policy or ResiliencePolicy()
    w0 = jax.tree_util.tree_map(np.asarray, w0)
    if place_w is not None:
        w0 = place_w(w0)

    tel_cb = (None if telemetry is None or not stream_iterations
              else telemetry.iteration_callback("agd"))

    # one jitted program per (segment length, poisoned); the poisoned
    # variant only ever traces in drills/tests
    seg_fns = {} if seg_cache is None else seg_cache

    def run_segment(warm: AGDWarmState, k: int, poisoned: bool):
        cfg_k = dataclasses.replace(config, num_iterations=k)
        key = (k, poisoned)
        if driver == "host":
            # host-orchestrated segment: a Python loop calling the
            # (possibly streamed) smooth per iteration — nothing to
            # jit or cache, and poison wraps the callable directly
            from ..core import host_agd

            sm = faults_lib.poison_smooth(smooth) if poisoned else smooth
            return host_agd.run_agd_host(
                sm, prox, reg_value, warm.x, cfg_k,
                smooth_loss=smooth_loss, warm=warm)
        if staged is not None:
            build, dargs = staged
            if getattr(build, "make_agd_run", None) is not None:
                # sharded-update build (parallel.sharded_update): the
                # whole segment loop is one shard_map program speaking
                # full trees at entry/exit, so the warm carry, rollback,
                # and checkpointing below work unchanged.  Rebalance
                # still swaps only ``dargs``.
                if key not in seg_fns:
                    # graftlint: disable=donation -- ws is the rollback
                    # anchor: reused to retry after a failed segment, so
                    # donating it would hand numerics rollback a deleted
                    # buffer
                    seg_fns[key] = jax.jit(build.make_agd_run(
                        prox, reg_value, cfg_k, telemetry_cb=tel_cb,
                        poison=poisoned, warm_entry=True))
                res = seg_fns[key](warm, dargs)
                jax.block_until_ready(res.num_iters)
                return res
            if key not in seg_fns:
                def _seg(ws, da, c=cfg_k, poison=poisoned):
                    sm, sl = build(*da)
                    if poison:
                        sm = faults_lib.poison_smooth(sm)
                    return agd.run_agd(sm, prox, reg_value, ws.x, c,
                                       smooth_loss=sl, warm=ws,
                                       telemetry_cb=tel_cb)

                # graftlint: disable=donation -- ws is the rollback
                # anchor: reused to retry after a failed segment, so
                # donating it would hand numerics rollback a deleted
                # buffer
                seg_fns[key] = jax.jit(_seg)
            res = seg_fns[key](warm, dargs)
        else:
            if key not in seg_fns:
                sm = (faults_lib.poison_smooth(smooth) if poisoned
                      else smooth)
                # graftlint: disable=donation -- same rollback anchor
                seg_fns[key] = jax.jit(
                    lambda ws, c=cfg_k, s=sm: agd.run_agd(
                        s, prox, reg_value, ws.x, c,
                        smooth_loss=smooth_loss, warm=ws,
                        telemetry_cb=tel_cb))
            res = seg_fns[key](warm)
        jax.block_until_ready(res.num_iters)
        return res

    # the causal trace (obs.trace): one ``supervised_run`` span per
    # call — parented to whatever context is active (a drill's cross-
    # process root rides in through trace.activate/from_env) — opened
    # BEFORE resume so the generation-zero/post-resume checkpoint
    # commit is part of the tree, with one child ``segment`` span PER
    # ATTEMPT.  A retried or rolled-back segment opens a fresh span
    # re-parented to the run root (never to the failed attempt), so
    # the tree reads as siblings with the same start_iter.  All
    # host-side: the compiled program is untouched (pinned
    # HLO-identical by tests/test_trace.py).
    run_span = (telemetry.trace_span("supervised_run", algorithm="agd")
                if telemetry is not None else None)
    try:
        with run_span if run_span is not None \
                else contextlib.nullcontext():
            # -- resume ----------------------------------------------------------
            hist: list = []
            warm = None
            if checkpointer is not None:
                loaded = checkpointer.load(w0)
                if loaded is not None:
                    if loaded.converged or loaded.aborted:
                        # terminal checkpoint: rerunning must not add iterations
                        return SupervisedResult(
                            weights=loaded.warm.x,
                            loss_history=np.asarray(loaded.loss_history),
                            num_iters=int(loaded.warm.prior_iters),
                            converged=loaded.converged,
                            aborted_non_finite=loaded.aborted,
                            retries=0, rollbacks=0,
                            resumed_from=int(loaded.warm.prior_iters),
                            attempts=[])
                    warm = loaded.warm
                    hist = list(np.asarray(loaded.loss_history))
            if warm is None:
                warm = AGDWarmState.initial(w0, config)
            resumed_from = int(warm.prior_iters)
            if run_span is not None:
                run_span.note(resumed_from=resumed_from)
            if checkpointer is not None:
                checkpointer.install_signal_handlers()
                checkpointer.update(warm, hist)  # generation zero / post-resume

            if faults is not None and heartbeat is not None \
                    and hasattr(faults, "bind_heartbeat"):
                # injected slow_host sleeps beat the heartbeat in
                # sub-intervals (chaos.ChaosSchedule), so a monitor
                # classifies the host SLOW rather than LOST
                faults.bind_heartbeat(heartbeat)

            schedule = policy.backoff_schedule()
            ledger: List[dict] = []
            attempt_no = 0
            seg_failures = 0   # consecutive transient failures of THIS segment
            retries = rollbacks = 0
            converged = aborted = False
            total = int(config.num_iterations)
            t_run0 = clock()

            def record_attempt(outcome: str, start_iter: int, iters: int,
                               seconds: float, error: Optional[str] = None,
                               failure_kind: Optional[str] = None):
                entry = {"attempt": attempt_no, "outcome": outcome,
                         "start_iter": start_iter, "iters": iters,
                         "seconds": round(seconds, 6), "error": error,
                         "failure_kind": failure_kind, "algorithm": "agd"}
                ledger.append(entry)
                if telemetry is not None:
                    telemetry.attempt(**entry)

            def recovery(action: str, **fields):
                if telemetry is not None:
                    telemetry.recovery(action=action, **fields)

            def numeric_rollback(start: int, reason: str):
                nonlocal warm, rollbacks
                if rollbacks >= policy.max_rollbacks:
                    raise errors.SupervisorGivingUp(
                        f"non-finite numerics persisted through "
                        f"{policy.max_rollbacks} rollbacks (last: {reason})",
                        ledger)
                rollbacks += 1
                warm = _rollback(warm, policy.rollback_l_factor)
                recovery("rollback", reason=reason, failure_kind=errors.NUMERIC,
                         from_iter=start, to_iter=int(warm.prior_iters),
                         big_l=float(warm.big_l), source="supervisor")

            try:
                while int(warm.prior_iters) < total:
                    start = int(warm.prior_iters)
                    k = min(policy.segment_iters or total, total - start)
                    if policy.max_wall_seconds is not None:
                        elapsed = clock() - t_run0
                        if elapsed > policy.max_wall_seconds:
                            attempt_no += 1
                            record_attempt(
                                "deadline", start, 0, elapsed,
                                error=(f"wall-clock budget "
                                       f"{policy.max_wall_seconds:g}s "
                                       "exceeded"),
                                failure_kind="deadline")
                            raise errors.SupervisorGivingUp(
                                f"DEADLINE: wall-clock budget "
                                f"{policy.max_wall_seconds:g}s exhausted "
                                f"after {elapsed:.3f}s at iteration "
                                f"{start} ({retries} retries, "
                                f"{rollbacks} rollbacks so far); not "
                                "retrying further", ledger)
                    seg_span = (telemetry.trace_span(
                        "segment", start_iter=start, iters=k)
                        if telemetry is not None else None)
                    with seg_span if seg_span is not None \
                            else contextlib.nullcontext():
                        # the boundary hooks are HOST-LOCAL work (no
                        # collective), so they get their own child
                        # span: in lockstep SPMD a straggler's delay
                        # is absorbed into every PEER's next
                        # collective — coupled segment spans tie — and
                        # this span is where per-host skew stays
                        # attributable (obs.timeline, the drills'
                        # straggler checks).  Only opened when hooks
                        # exist, so plain runs pay no extra records.
                        boundary_span = (telemetry.trace_span(
                            "boundary", start_iter=start)
                            if telemetry is not None
                            and (heartbeat is not None
                                 or faults is not None
                                 or monitor is not None) else None)
                        hook_exc: Optional[BaseException] = None
                        t_bnd = time.perf_counter()
                        with boundary_span if boundary_span is not None \
                                else contextlib.nullcontext():
                            if heartbeat is not None:
                                heartbeat.beat(iter=start,
                                               phase="segment")
                            if faults is not None or monitor is not None:
                                try:
                                    if faults is not None:
                                        faults.before_segment(start)
                                    if monitor is not None:
                                        monitor.check()
                                except Exception as e:  # noqa: BLE001 — classified below
                                    hook_exc = e
                                    if boundary_span is not None:
                                        boundary_span.note(
                                            status="error",
                                            error=(f"{type(e).__name__}"
                                                   f": {e}"))
                        boundary_dt = time.perf_counter() - t_bnd
                        if hook_exc is not None:
                            e = hook_exc
                            attempt_no += 1
                            kind = errors.classify_failure(e)
                            record_attempt(
                                "failed", start, 0, 0.0,
                                error=f"{type(e).__name__}: {e}",
                                failure_kind=kind)
                            if seg_span is not None:
                                seg_span.note(
                                    status="error",
                                    outcome="failed",
                                    attempt=attempt_no,
                                    error=f"{type(e).__name__}: {e}")
                            if kind == errors.FATAL:
                                # a fatal boundary fault (chaos-
                                # injected config error, QuorumLost)
                                # must give up TYPED, exactly like a
                                # fatal segment failure — never a
                                # bare traceback with the ledger lost
                                raise errors.SupervisorGivingUp(
                                    f"fatal failure at iteration "
                                    f"{start}: {type(e).__name__}: "
                                    f"{e}", ledger) from e
                            if kind != errors.TRANSIENT:
                                raise e
                            seg_failures += 1
                            retries += 1
                            if seg_failures >= policy.max_attempts:
                                raise errors.SupervisorGivingUp(
                                    f"segment at iteration {start} "
                                    f"failed {seg_failures} times "
                                    f"(last: {e})", ledger) from e
                            delay = schedule.next_delay(seg_failures)
                            recovery("retry", reason=str(e),
                                     failure_kind=kind,
                                     attempt=seg_failures,
                                     backoff_s=delay,
                                     from_iter=start,
                                     source="supervisor")
                            if delay:
                                sleep(delay)
                            continue
                        poisoned = (faults is not None
                                    and faults.take_poison(start))

                        attempt_no += 1
                        t0 = time.perf_counter()
                        try:
                            res = retry_lib.run_with_watchdog(
                                run_segment, (warm, k, poisoned), {},
                                policy.attempt_timeout, f"agd@{start}")
                        except errors.Preempted:
                            raise
                        except Exception as e:  # noqa: BLE001 — classified below
                            dt = time.perf_counter() - t0
                            kind = errors.classify_failure(e)
                            record_attempt(
                                "failed", start, 0, dt,
                                error=f"{type(e).__name__}: {e}",
                                failure_kind=kind)
                            if seg_span is not None:
                                seg_span.note(
                                    status="error", outcome="failed",
                                    attempt=attempt_no,
                                    failure_kind=kind,
                                    error=f"{type(e).__name__}: {e}")
                            if kind == errors.NUMERIC:
                                numeric_rollback(
                                    start, f"{type(e).__name__}: {e}")
                                seg_failures = 0
                                continue
                            if kind == errors.TRANSIENT:
                                seg_failures += 1
                                retries += 1
                                if seg_failures >= policy.max_attempts:
                                    raise errors.SupervisorGivingUp(
                                        f"segment at iteration {start} "
                                        f"failed {seg_failures} times "
                                        f"(last: {e})", ledger) from e
                                delay = schedule.next_delay(seg_failures)
                                recovery("retry", reason=str(e),
                                         failure_kind=kind,
                                         attempt=seg_failures,
                                         backoff_s=delay,
                                         from_iter=start,
                                         source="supervisor")
                                if delay:
                                    sleep(delay)
                                continue
                            raise errors.SupervisorGivingUp(
                                f"fatal failure at iteration {start}: "
                                f"{type(e).__name__}: {e}", ledger) from e
                        dt = time.perf_counter() - t0

                        if bool(res.aborted_non_finite):
                            record_attempt("aborted_non_finite", start,
                                           int(res.num_iters), dt,
                                           failure_kind=errors.NUMERIC)
                            if seg_span is not None:
                                seg_span.note(
                                    status="error",
                                    outcome="aborted_non_finite",
                                    attempt=attempt_no)
                            numeric_rollback(
                                start, "non-finite loss in segment")
                            seg_failures = 0
                            continue

                        done = int(res.num_iters)
                        record_attempt("ok", start, done, dt)
                        if seg_span is not None:
                            seg_span.note(outcome="ok",
                                          attempt=attempt_no,
                                          iters=done)
                        # graftlint: disable=host-sync -- ONE device
                        # read per SEGMENT boundary (the batching the
                        # rule recommends), not a per-iteration sync
                        hist.extend(
                            np.asarray(res.loss_history)[:done].tolist())
                        warm = ckpt.warm_from_result(res, start + done)
                        converged = bool(res.converged)
                        seg_failures = 0
                        if checkpointer is not None:
                            checkpointer.update(warm, hist,
                                                converged=converged)
                        if scheduler is not None and not converged \
                                and done > 0:
                            decision = scheduler.after_segment(
                                start_iter=start, iters=done,
                                boundary_s=boundary_dt, segment_s=dt)
                            if decision is not None:
                                # generation-boundary rebalance: swap
                                # the staged data for the rebuilt
                                # assignment, then commit a generation
                                # that CARRIES it — a crash on either
                                # side of the commit resumes from a
                                # self-consistent assignment
                                new_staged = scheduler.apply(
                                    decision, checkpointer=checkpointer)
                                if new_staged is not None:
                                    staged = new_staged
                                    if getattr(scheduler, "retrace",
                                               False):
                                        seg_fns.clear()
                                if checkpointer is not None:
                                    checkpointer.update(
                                        warm, hist, converged=converged,
                                        force=True)
                        if converged or done == 0:
                            break
            finally:
                if checkpointer is not None:
                    # terminal/abandon flush: whatever the exit path,
                    # the last completed state is on disk before
                    # handlers come off
                    checkpointer.update(warm, hist, converged=converged,
                                        aborted=aborted, force=True)
                    checkpointer.uninstall_signal_handlers()
                if heartbeat is not None:
                    try:
                        heartbeat.beat(iter=int(warm.prior_iters),
                                       phase="exit")
                    except OSError:  # a dying filesystem must not mask
                        pass         # the real exit path
    except errors.SupervisorGivingUp:
        # the give-up ships with its last-seconds timeline: dump the
        # run's flight ring (no-op without a recorder/destination)
        flight_lib.dump_on_failure(telemetry, "supervisor_giving_up")
        raise

    return SupervisedResult(
        weights=warm.x, loss_history=np.asarray(hist),
        num_iters=int(warm.prior_iters), converged=converged,
        aborted_non_finite=aborted, retries=retries,
        rollbacks=rollbacks, resumed_from=resumed_from,
        attempts=ledger)


def supervised_call(fn: Callable, *args, policy=None, telemetry=None,
                    label: str = "fit", **kwargs):
    """Wrap ANY runner's ``fit`` (L-BFGS, sweeps, custom drivers) in the
    bounded-retry half of the supervision policy — the generic member
    for result types that carry no ``AGDWarmState`` to roll back to.
    Transient failures retry with backoff (each emitting a ``recovery``
    record); NUMERIC/FATAL raise immediately; the final failure raises
    :class:`SupervisorGivingUp` with the ledger."""
    policy = policy or ResiliencePolicy()
    ledger: List[dict] = []
    attempt = [0]

    def attempted(*a, **kw):
        attempt[0] += 1
        t0 = time.perf_counter()
        try:
            out = fn(*a, **kw)
        except Exception as e:
            entry = {"attempt": attempt[0], "outcome": "failed",
                     "seconds": round(time.perf_counter() - t0, 6),
                     "error": f"{type(e).__name__}: {e}",
                     "failure_kind": errors.classify_failure(e)}
            ledger.append(entry)
            if telemetry is not None:
                telemetry.attempt(**entry)
            raise
        entry = {"attempt": attempt[0], "outcome": "ok",
                 "seconds": round(time.perf_counter() - t0, 6)}
        ledger.append(entry)
        if telemetry is not None:
            telemetry.attempt(**entry)
        return out

    try:
        return retry_lib.call_with_retry(
            attempted, *args, policy=policy, label=label,
            telemetry=telemetry, **kwargs)
    except Exception as e:
        if isinstance(e, (errors.Preempted, errors.SupervisorGivingUp)):
            raise
        raise errors.SupervisorGivingUp(
            f"{label}: {type(e).__name__}: {e}", ledger) from e
