"""The crash-safe recovery journal — an append-only, CRC-per-record WAL
of every recovery DECISION a run makes.

The attempt ledger (``SupervisorGivingUp.ledger``) dies with the
process, and the JSONL telemetry stream is line-buffered prose — after
a SIGKILL its tail is whatever the stdio buffer happened to flush.
Post-mortems and exactly-once accounting need something stronger: a log
that (a) survives any kill at any byte, (b) detects its own torn tail
instead of replaying garbage, and (c) continues across resumes so one
file tells the whole multi-attempt story.  This module is that log:

- **Framing**: an 8-byte file header (``AGDWAL01``), then per record an
  8-byte frame ``<II`` (payload length, payload CRC32) followed by the
  payload — one canonical JSON object (``sort_keys=True``) carrying a
  monotonically increasing ``seq``.  Every append is flushed (and
  optionally fsynced) immediately.
- **Torn-tail tolerance**: :func:`replay` walks records until the first
  incomplete frame, short payload, CRC mismatch, or unparseable JSON —
  everything before that point is returned intact, everything after is
  the torn tail (a kill mid-append, a scrambled sector).  Opening a
  :class:`Journal` at an existing path replays, TRUNCATES the torn tail
  in place (the repair), and continues ``seq`` from the last committed
  record — so exactly-once accounting holds across any number of
  resumes.
- **Wiring**: :class:`JournalSink` is an ``obs.sinks.Sink`` — attach it
  to the run's ``Telemetry`` next to the JSONL sink and every decision
  record (``attempt`` / ``recovery`` / ``chaos`` / ``degraded`` /
  ``journal_replay``) the supervisor, the checkpointers
  (``AutoCheckpointer`` / ``DistributedCheckpointer``), the host
  monitor, and the chaos harness emit lands in the journal in emission
  order.  Replaying the journal reconstructs the exact decision
  sequence bit-identically (the drill asserts payload-byte equality).

``segment_accounting`` derives the exactly-once iteration census from a
replayed record list: each segment is counted once by its ``start_iter``
with the LAST occurrence winning — a segment re-run after a rollback or
a checkpoint fallback supersedes, never double-counts.

Deliberately stdlib-only (no jax, no numpy at import): a monitor
process can replay a journal without a backend.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

from ..obs.sinks import Sink, _jsonable

MAGIC = b"AGDWAL01"
_FRAME = struct.Struct("<II")  # (payload length, payload CRC32)
FRAME_SIZE = _FRAME.size  # bytes of frame header before each payload

# a frame claiming more than this is torn/garbage, not a real record
MAX_RECORD_BYTES = 1 << 26

# the record kinds that are DECISIONS (what JournalSink keeps by
# default) — the high-rate streams (iteration/span/metrics/heartbeat)
# stay in the JSONL where volume is cheap
DECISION_KINDS = ("attempt", "recovery", "chaos", "degraded",
                  "journal_replay")


class JournalReplay(NamedTuple):
    """What :func:`replay` recovered from one journal file."""

    records: List[dict]     # every committed record, in append order
    payloads: List[bytes]   # the exact payload bytes (bit-identity)
    valid_bytes: int        # offset of the first torn byte (= file size
    #                         when the journal is clean)
    torn_bytes: int         # bytes dropped past valid_bytes
    reason: Optional[str]   # why replay stopped early; None when clean

    @property
    def last_seq(self) -> int:
        """Highest committed ``seq`` (-1 for an empty journal)."""
        if not self.records:
            return -1
        return max(int(r.get("seq", -1)) for r in self.records)


def _encode(record: dict) -> bytes:
    payload = json.dumps(record, sort_keys=True,
                         default=_jsonable).encode("utf-8")
    return _FRAME.pack(len(payload), zlib.crc32(payload)) + payload


def encode_record(record: dict) -> bytes:
    """One record's frame (header + canonical-JSON payload) — the
    framing shared with the flight recorder (``obs.flight``), which
    writes the same frames under its own magic."""
    return _encode(record)


def replay(path: str, *, magic: bytes = MAGIC) -> JournalReplay:
    """Recover every committed record from ``path`` — see the module
    docstring for the stop conditions.  A missing file replays empty
    and clean; a file whose header is damaged replays empty with the
    reason (nothing after an unidentifiable header can be trusted).
    ``magic`` selects the file family: the journal's own header by
    default, ``obs.flight.MAGIC`` when replaying a flight-recorder
    dump (same frames, different producer)."""
    if not os.path.exists(path):
        return JournalReplay([], [], 0, 0, None)
    with open(path, "rb") as f:
        blob = f.read()
    if len(blob) < len(magic):
        return JournalReplay([], [], 0, len(blob),
                             "torn header" if blob else None)
    if blob[:len(magic)] != magic:
        return JournalReplay([], [], 0, len(blob),
                             "bad magic (not a journal, or its header "
                             "was overwritten)")
    records: List[dict] = []
    payloads: List[bytes] = []
    off = len(magic)
    reason = None
    while off < len(blob):
        if off + _FRAME.size > len(blob):
            reason = f"torn frame at byte {off}"
            break
        length, crc = _FRAME.unpack_from(blob, off)
        if length > MAX_RECORD_BYTES:
            reason = (f"frame at byte {off} claims {length} bytes "
                      "(corrupt length)")
            break
        start = off + _FRAME.size
        payload = blob[start:start + length]
        if len(payload) < length:
            reason = f"torn payload at byte {off}"
            break
        if zlib.crc32(payload) != crc:
            reason = (f"CRC mismatch at record {len(records)} "
                      f"(byte {off})")
            break
        try:
            rec = json.loads(payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            reason = (f"unparseable payload at record {len(records)} "
                      f"(byte {off}): {e}")
            break
        if not isinstance(rec, dict):
            reason = (f"non-object payload at record {len(records)} "
                      f"(byte {off})")
            break
        records.append(rec)
        payloads.append(payload)
        off = start + length
    return JournalReplay(records, payloads, off, len(blob) - off, reason)


class Journal:
    """One run's decision WAL — see the module docstring.

    Opening an existing path replays it, truncates any torn tail in
    place, and continues ``seq`` from the last committed record
    (``repair=False`` opens for inspection without touching the bytes —
    appends to a torn journal are then unreachable on replay, so only
    repaired journals should be written to).  The replay summary is kept
    on :attr:`replay_summary` — emit it through
    ``Telemetry.journal_replay(**journal.replay_summary)`` (or pass
    ``telemetry=`` here) so the resume decision is itself on record.

    ``fsync=True`` fsyncs every append — required when the writer may
    be SIGKILLed (the chaos drill's children); the default flush-only
    append survives any Python-level death.

    :attr:`written` mirrors the exact payload bytes appended by THIS
    object, so a driver can assert disk replay is bit-identical to what
    the live run decided.
    """

    def __init__(self, path: str, *, fsync: bool = False,
                 repair: bool = True, telemetry=None):
        self.path = path
        self.fsync = bool(fsync)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        rep = replay(path)
        self.recovered: List[dict] = rep.records
        repaired = False
        if rep.torn_bytes and repair:
            with open(path, "r+b") as f:
                f.truncate(rep.valid_bytes)
            repaired = True
        self._next_seq = rep.last_seq + 1
        self.replay_summary = {
            "records": len(rep.records), "path": path,
            "torn_bytes": int(rep.torn_bytes),
            "last_seq": int(rep.last_seq), "repaired": repaired,
            "reason": rep.reason,
        }
        self.written: List[bytes] = []
        new = not os.path.exists(path) or os.path.getsize(path) == 0
        self._f = open(path, "ab")
        if new:
            self._f.write(MAGIC)
            self._f.flush()
        if telemetry is not None:
            telemetry.journal_replay(**self.replay_summary)

    @property
    def next_seq(self) -> int:
        return self._next_seq

    def append(self, record: dict) -> dict:
        """Append one record (a COPY, stamped with the next ``seq``),
        flush, and return the stamped copy."""
        rec = dict(record)
        rec["seq"] = self._next_seq
        frame = _encode(rec)
        self._f.write(frame)
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
        self._next_seq += 1
        self.written.append(frame[_FRAME.size:])
        return rec

    def flush(self) -> None:
        if not self._f.closed:
            self._f.flush()

    def close(self) -> None:
        if not self._f.closed:
            self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        self.close()
        return False


class JournalSink(Sink):
    """Telemetry sink writing every decision record through a
    :class:`Journal` — the one-point wiring that makes the supervisor,
    both checkpointers, the host monitor, and the chaos harness journal
    their decisions without any of them knowing the journal exists.
    ``kinds=None`` journals everything (including the per-iteration
    stream — only sensible for tiny runs)."""

    def __init__(self, journal: Journal,
                 kinds: Optional[Sequence[str]] = DECISION_KINDS):
        self.journal = journal
        self.kinds = None if kinds is None else frozenset(kinds)

    def emit(self, record: dict) -> None:
        if self.kinds is not None and record.get("kind") not in self.kinds:
            return
        self.journal.append(record)

    def flush(self) -> None:
        self.journal.flush()

    def close(self) -> None:
        self.journal.close()


def decision_sequence(records: Sequence[dict]) -> List[Tuple]:
    """The compact, order-preserving decision tuple list of a record
    stream — the thing two replays (or a replay and a live mirror) are
    compared on.  Non-decision kinds are skipped."""
    out: List[Tuple] = []
    for r in records:
        kind = r.get("kind")
        if kind == "attempt":
            out.append(("attempt", r.get("outcome"), r.get("start_iter"),
                        r.get("iters")))
        elif kind == "recovery":
            out.append(("recovery", r.get("action"), r.get("from_iter"),
                        r.get("to_iter"), r.get("generation")))
        elif kind == "chaos":
            out.append(("chaos", r.get("fault"), r.get("at_iter"),
                        r.get("process")))
        elif kind == "degraded":
            out.append(("degraded", r.get("surviving"),
                        r.get("saved_process_count"), r.get("to_iter")))
        elif kind == "journal_replay":
            out.append(("journal_replay", r.get("records"),
                        r.get("torn_bytes")))
    return out


def segment_accounting(records: Sequence[dict]) -> Dict[int, int]:
    """Exactly-once iteration census over a replayed record stream:
    ``{start_iter: iters}`` from the ``attempt`` records with outcome
    ``ok``, LAST occurrence winning — a segment re-executed after a
    rollback, retry, or checkpoint fallback supersedes its earlier
    entry instead of double-counting.  ``sum(values())`` is the number
    of iterations that COUNT across every resume in the journal."""
    out: Dict[int, int] = {}
    for r in records:
        if r.get("kind") != "attempt" or r.get("outcome") != "ok":
            continue
        start = r.get("start_iter")
        if start is None:
            continue
        out[int(start)] = int(r.get("iters", 0))
    return out
