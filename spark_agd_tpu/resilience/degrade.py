"""Quorum-based graceful degradation: keep training on the survivors
instead of restarting the world.

Spark's answer to a lost executor is recompute-and-continue; PR 4's
answer was a full elastic restart from the last committed generation —
correct, but it re-assembles the DEAD host's data partitions, which is
only possible when the storage outlives the host.  This module adds the
middle path real pods use: when a peer dies (``HostLost``) and a
**quorum** of processes survives, the run continues DEGRADED — the
surviving processes resume from the last committed generation using
only the SURVIVING shards, drop the dead hosts' data partitions, and
keep training on what remains.

The math: the distributed smooth is the reference's ``treeAggregate``
contract — ``(Σloss, Σgrad, n)`` summed over partitions, divided by the
valid-row count AFTER the reduction (``parallel.dist_smooth``).
Dropping partitions therefore *re-weights automatically*: the gradient
becomes the exact mean over the surviving rows — a smaller-sample
estimate of the same objective, not a biased sum.  The trajectory is
the one an uninterrupted run over the surviving partitions would have
taken from the same iterate (the chaos drill pins this to 1e-6 in f64).

Pieces:

- :class:`DegradePolicy` — the quorum knob: ``min_quorum`` (fraction of
  the saving topology that must survive) and ``min_processes``.
  :meth:`DegradePolicy.decide` returns a :class:`QuorumDecision`;
  below quorum the answer is :class:`~spark_agd_tpu.resilience.errors.
  QuorumLost` (classified FATAL — retrying cannot resurrect hosts;
  a full elastic restart or operator action is required).
- :func:`load_degraded` — the surviving-shards loader: newest committed
  generation whose SURVIVING shards verify (manifest size/CRC32 +
  per-entry npz CRCs), warm state from the lowest surviving process's
  shard (the commit barrier proved all replicas byte-equal), the
  surviving hosts' partition lists re-split round-robin among the
  survivors, row-sharded extras re-split likewise.  Emits one
  ``degraded`` record and a ``degraded_continue`` recovery action.
- :class:`DegradedCheckpointer` — drops into the supervisor's
  ``checkpointer=`` seat for the degraded continuation: ``load`` is
  :func:`load_degraded`; saves proceed as a normal (smaller-topology)
  barrier commit, so the degraded run's own generations chain on.

Telemetry: every degraded continuation carries the ``degraded`` flag
in its records (`kind="degraded"` entry + the recovery action), so a
post-mortem can tell a degraded tail from a full-strength run.

Quorum matrix (``min_quorum=0.5``, ``min_processes=1``):

======  =========  ========
saved   surviving  decision
======  =========  ========
2       1          degrade (0.50 >= 0.50)
4       2          degrade
4       1          refuse (QuorumLost)
8       3          refuse
======  =========  ========
"""

from __future__ import annotations

import dataclasses
import logging
import os
from typing import Any, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from ..obs import flight as flight_lib
from ..utils import checkpoint as ckpt
from . import manifest as manifest_lib
from .distributed import (
    ROWSTATE_PREFIX,
    DistributedCheckpointer,
    LoadedDistCheckpoint,
    _check_embedded_generation,
    _shard_partitions,
    _shard_row_state,
    reshard_partitions,
)
from .errors import QuorumLost

logger = logging.getLogger("spark_agd_tpu")


class QuorumDecision(NamedTuple):
    """One quorum evaluation — kept whole so the decision itself can be
    journaled/asserted, not just its boolean."""

    allowed: bool
    surviving: int
    saved: int
    quorum: float          # surviving / saved
    required: float        # the policy's min_quorum
    reason: str


@dataclasses.dataclass(frozen=True)
class DegradePolicy:
    """When may a run continue without its dead peers?

    ``min_quorum``: the fraction of the SAVING topology that must
    survive (0 < q <= 1); ``min_processes``: an absolute floor (a
    999-host job at q=0.001 still needs at least this many).  The
    default (0.5, 1) is the classic majority-or-half rule: a 2-host
    job degrades to 1, a 4-host job to 2, below that the sample loss
    is judged too far from the objective to keep training silently.
    """

    min_quorum: float = 0.5
    min_processes: int = 1

    def __post_init__(self):
        if not 0.0 < self.min_quorum <= 1.0:
            raise ValueError("min_quorum must be in (0, 1]")
        if self.min_processes < 1:
            raise ValueError("min_processes must be >= 1")

    def decide(self, saved_process_count: int,
               surviving: int) -> QuorumDecision:
        saved = int(saved_process_count)
        alive = int(surviving)
        if not 0 <= alive <= saved:
            raise ValueError(
                f"surviving={alive} out of range for saved topology "
                f"of {saved}")
        quorum = alive / saved if saved else 0.0
        ok = quorum >= self.min_quorum and alive >= self.min_processes
        reason = (f"{alive}/{saved} processes survive "
                  f"(quorum {quorum:.2f} "
                  f"{'>=' if ok else '<'} {self.min_quorum:.2f}"
                  + ("" if alive >= self.min_processes else
                     f"; floor {self.min_processes} unmet") + ")")
        return QuorumDecision(ok, alive, saved, quorum,
                              self.min_quorum, reason)


def _verify_surviving(m: "manifest_lib.Manifest", directory: str,
                      surviving: Sequence[int]) -> List[str]:
    """The surviving-shard subset of ``manifest.verify_manifest``: the
    dead hosts' shards are ALLOWED to be missing or torn (their host
    may have died mid-write) — only the shards the degraded resume will
    actually read must verify."""
    problems = []
    by_process = {s.process: s for s in m.shards}
    for p in surviving:
        s = by_process.get(int(p))
        if s is None:
            problems.append(f"manifest g{m.generation} has no shard "
                            f"for surviving process {p}")
            continue
        path = os.path.join(directory, s.path)
        if not os.path.exists(path):
            problems.append(f"surviving shard {s.path} missing")
            continue
        size = os.path.getsize(path)
        if size != s.size:
            problems.append(f"surviving shard {s.path}: size {size} != "
                            f"manifest {s.size} (torn write)")
            continue
        crc = manifest_lib.crc32_file(path)
        if crc != s.crc32:
            problems.append(
                f"surviving shard {s.path}: CRC32 {crc:#010x} != "
                f"manifest {s.crc32:#010x}")
    return problems


class DegradedResume(NamedTuple):
    """:func:`load_degraded`'s result: the loaded checkpoint (shaped
    exactly like an elastic ``LoadedDistCheckpoint`` — the supervisor
    reads the same first five fields), the quorum decision that allowed
    it, and the data partitions that were dropped with the dead."""

    loaded: LoadedDistCheckpoint
    decision: QuorumDecision
    dropped_partitions: Tuple[str, ...]


def load_degraded(
    directory: str,
    template: Any,
    *,
    surviving: Sequence[int],
    policy: Optional[DegradePolicy] = None,
    process_index: Optional[int] = None,
    fingerprint: Optional[str] = None,
    telemetry=None,
) -> Optional[DegradedResume]:
    """Load the newest committed generation for a DEGRADED continuation
    on ``surviving`` (sorted original process indices) — see the module
    docstring.  ``process_index`` is the caller's ORIGINAL index (must
    be in ``surviving``); its new rank is its position there.  Raises
    :class:`QuorumLost` when the policy refuses; returns None when no
    generation survives verification (each refusal recorded)."""
    from ..parallel import multihost as mh

    policy = policy or DegradePolicy()
    survivors = sorted(int(p) for p in surviving)
    if not survivors:
        raise ValueError("surviving must name at least one process")
    if process_index is None:
        process_index = survivors[0]
    if int(process_index) not in survivors:
        raise ValueError(f"process_index {process_index} is not in "
                         f"surviving={survivors}")
    rank = mh.rank_among(survivors, int(process_index))
    n_surv = len(survivors)

    gens = manifest_lib.committed_generations(directory)
    for gen in gens:
        try:
            m = manifest_lib.load_manifest(directory, gen)
        except (ValueError, OSError) as e:
            _fallback(telemetry, directory, gen,
                      f"manifest unreadable: {e}")
            continue
        decision = policy.decide(m.process_count, n_surv)
        if not decision.allowed:
            # quorum is a property of the topology, not of this
            # generation: no older generation can fix it.  The refusal
            # ships with its last-seconds timeline (obs.flight).
            flight_lib.dump_on_failure(telemetry, "quorum_lost")
            raise QuorumLost(decision.reason)
        problems = _verify_surviving(m, directory, survivors)
        if problems:
            _fallback(telemetry, directory, gen, "; ".join(problems))
            continue
        try:
            return _load_surviving(directory, m, template, survivors,
                                   rank, n_surv, decision, fingerprint,
                                   telemetry)
        except ckpt.CheckpointCorruptError as e:
            _fallback(telemetry, directory, gen, str(e))
            continue
    if gens:
        logger.warning(
            "degraded resume: every committed generation under %r "
            "failed surviving-shard verification", directory)
    return None


def _fallback(telemetry, directory: str, generation: int,
              reason: str) -> None:
    logger.warning("degraded resume refusing generation %d under %r: %s",
                   generation, directory, reason)
    if telemetry is not None:
        telemetry.recovery(action="checkpoint_fallback", path=directory,
                           generation=generation, reason=reason,
                           source="degrade")


def _load_surviving(directory, m, template, survivors, rank, n_surv,
                    decision, fingerprint, telemetry):
    from ..parallel import multihost as mh

    per_host = []
    for p in survivors:
        path = m.shard_path(directory, p)
        entries = ckpt.read_npz_entries(path)
        _check_embedded_generation(path, entries, m.generation)
        per_host.append((p, path, entries))
    _, path0, e0 = per_host[0]
    # the warm carry is replicated (the commit barrier verified all
    # replicas byte-equal BEFORE this generation existed) — any
    # surviving copy is canonical; take the lowest survivor's
    lc = ckpt.checkpoint_from_entries(
        path0, ckpt._Entries(path0, e0), template, fingerprint)

    saved_parts = [p for _, _, e in per_host
                   if (p := _shard_partitions(e)) is not None]
    partitions = (reshard_partitions(saved_parts, rank, n_surv)
                  if saved_parts else None)
    surviving_union = sorted({p for host in saved_parts for p in host})
    # what died with the dead hosts: everything the manifest's topology
    # saved minus what the survivors still hold — recoverable only from
    # the dead shards, which a degraded resume deliberately forgoes
    dead = sorted(set(range(m.process_count)) - set(survivors))
    dropped: Tuple[str, ...] = ()
    if saved_parts:
        all_parts = set(surviving_union)
        for p in dead:
            try:
                path = m.shard_path(directory, p)
                if os.path.exists(path):
                    entries = ckpt.read_npz_entries(path)
                    lost = _shard_partitions(entries)
                    if lost is not None:
                        all_parts |= set(lost)
            except (ckpt.CheckpointCorruptError, KeyError, OSError):
                pass  # a dead host's shard owes us nothing
        dropped = tuple(sorted(all_parts - set(surviving_union)))

    names = sorted({k for _, _, e in per_host
                    for k in e if k.startswith(ROWSTATE_PREFIX)})
    row_state = {}
    for k in names:
        whole = np.concatenate(
            [e[k] for _, _, e in per_host if k in e], axis=0)
        row_state[k[len(ROWSTATE_PREFIX):]] = whole[
            mh.local_rows_slice(whole.shape[0], rank, n_surv)]

    if telemetry is not None:
        telemetry.degraded(
            surviving=n_surv, saved_process_count=m.process_count,
            lost=dead, quorum=round(decision.quorum, 4),
            min_quorum=decision.required, generation=m.generation,
            to_iter=int(lc.warm.prior_iters), process=rank,
            dropped_partitions=len(dropped), source="degrade")
        telemetry.recovery(
            action="degraded_continue", path=directory,
            generation=m.generation,
            saved_process_count=m.process_count, process_count=n_surv,
            process=rank, to_iter=int(lc.warm.prior_iters),
            reason=decision.reason, source="degrade")
    logger.warning(
        "DEGRADED resume: generation %d saved by %d processes, "
        "continuing on %d survivor(s) (%s); %d data partition(s) "
        "dropped with the dead hosts",
        m.generation, m.process_count, n_surv, decision.reason,
        len(dropped))
    loaded = LoadedDistCheckpoint(
        *lc[:5], generation=m.generation,
        saved_process_count=m.process_count, elastic=True,
        partitions=partitions, row_state=row_state, extras=lc.extras)
    return DegradedResume(loaded, decision, dropped)


class DegradedCheckpointer(DistributedCheckpointer):
    """The degraded continuation's checkpointer: ``load`` reads only
    the surviving shards (:func:`load_degraded`, quorum-gated), and
    saves chain on as normal barrier commits of the SURVIVING topology
    (``process_count = len(surviving)``, this process's rank among the
    survivors) — so the degraded run's own generations are first-class
    and a later full restart resumes from them elastically."""

    def __init__(self, directory: str, *, surviving: Sequence[int],
                 original_process_index: Optional[int] = None,
                 degrade_policy: Optional[DegradePolicy] = None,
                 **kwargs):
        from ..parallel import multihost as mh

        self.surviving = sorted(int(p) for p in surviving)
        if original_process_index is None:
            original_process_index = self.surviving[0]
        self.original_process_index = int(original_process_index)
        self.degrade_policy = degrade_policy or DegradePolicy()
        rank = mh.rank_among(self.surviving, self.original_process_index)
        super().__init__(directory, process_index=rank,
                         process_count=len(self.surviving), **kwargs)
        self.last_decision: Optional[QuorumDecision] = None
        self.dropped_partitions: Tuple[str, ...] = ()
        self._loaded_once: Optional[LoadedDistCheckpoint] = None

    def load(self, template: Any) -> Optional[LoadedDistCheckpoint]:
        # memoized: the degraded-resume DECISION is made once — the
        # driver loads first (it needs the surviving partitions to
        # build the degraded problem), then the supervisor's own load
        # call reuses the result instead of re-reading shards and
        # re-emitting the decision records
        if self._loaded_once is not None:
            return self._loaded_once
        resumed = load_degraded(
            self.directory, template, surviving=self.surviving,
            policy=self.degrade_policy,
            process_index=self.original_process_index,
            fingerprint=self.fingerprint, telemetry=self.telemetry)
        if resumed is None:
            return None
        self.last_decision = resumed.decision
        self.dropped_partitions = resumed.dropped_partitions
        loaded = resumed.loaded
        self._next_generation = loaded.generation + 1
        self._last_saved_iters = int(loaded.warm.prior_iters)
        self._last_saved_t = self._clock()
        if loaded.partitions is not None and self.partitions is None:
            self.partitions = list(loaded.partitions)
        self._loaded_once = loaded
        return loaded
