"""Straggler-aware scheduling: live skew detection, generation-boundary
partition rebalancing, and speculative segment execution.

PAPERS.md arXiv 1612.01437 identifies stragglers and partition skew as
the dominant cost of distributed ML on Spark; DeepSpark (1602.08191)
shows relaxed/overlapped execution is the cure.  Before this module the
repo could *detect* a slow host (``obs.timeline`` per-host step times),
*simulate* one (``resilience.chaos`` ``slow_host`` faults), and *act*
on a changed topology (the elastic re-split of ``resilience.
distributed.load_for_topology``) — but nothing closed the loop, so a
persistent 5× straggler made every lockstep collective straggler-bound
for the whole run.  This module is the loop, in three pieces:

**Detection** (:class:`SkewTracker`).  In lockstep SPMD a straggler's
delay is absorbed into every peer's next collective — the coupled
``segment`` spans tie — so the attributable signal is the HOST-LOCAL
work at segment boundaries (where the chaos ``slow_host`` sleeps land,
and where real per-host work like ingest and beats happens).  Each
host folds its own boundary seconds into the tracker; every
``sync_every`` segments the per-host sums cross one small allgather
(the same int64-limb exchange the distributed checkpoint commits
through), so EVERY host holds the IDENTICAL per-host cost estimate —
the precondition for a deterministic fleet-wide decision.  An EWMA
smooths one-off blips; a persistent straggler is one whose skew
(max cost over the interpolating median) stays above the policy
threshold for ``trigger_segments`` CONSECUTIVE syncs with the same
host on top — the hysteresis that distinguishes a degraded host from
a noisy one.  Heartbeat files are the second signal: a host beating
``phase="slow"`` (the chaos sub-interval beats) or falling behind on
mtime corroborates the timing estimate without being able to fake it.

**Rebalancing** (:func:`assign_weighted` + :class:`StragglerScheduler`).
At a generation checkpoint boundary, when the straggler is persistent,
every host deterministically recomputes the partition assignment from
the sorted union weighted by measured speed (largest-remainder counts
with a min-shard floor, then greedy makespan improvement, never worse
than uniform), swaps its staged data arguments via the caller's
``rebuild`` hook, and the supervisor force-commits a generation whose
shards carry the NEW assignment through the existing barrier-committed
manifest protocol — a crash mid-rebalance resumes cleanly from either
the old or the new assignment, both self-consistent.  With static
padded shapes (``data.ingest.from_partitioned_files(pad_to_rows=...)``)
the swap re-traces NOTHING: the compiled segment program reads the new
data as arguments.

**Speculation** (:func:`run_speculative_segment` /
:func:`resolve_speculation`).  Spark's backup-task idea, scoped to the
decision-only segment tail: when the slowest host's segment exceeds
``speculative_multiple`` × the fleet median (:func:`speculation_due`),
a backup re-executes that segment from the last committed generation.
The AGD carry is REPLICATED and the math deterministic, so re-running
the same program from the same committed warm state is bit-identical —
first-result-wins is bit-safe (pinned by tests; a cross-topology
backup agrees to f64 reduction-order noise instead, which is what the
drill's 1-process babysitter measures).  Every speculation lands as a
``speculative_exec`` recovery record with its won/lost outcome.

Every decision is on record: ``skew_estimate`` records each sync,
``rebalance`` records (plus the ``rebalance`` recovery action) on each
applied decision.  ``tools/straggler_drill.py`` proves the headline on
CPU: a real 2-process gloo run with a scripted persistent 5× straggler
converges to the no-fault solution within ~1.5× of its wall clock
instead of ~5×, and ``obs.perfgate.gate_rebalance`` gates the
post-rebalance straggler score below the pre-rebalance value.

Scheduling off is free: without a ``scheduler=`` the supervisor path
is untouched (bit-identical results, no new traces — pinned by
``tests/test_scheduler.py``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import (Any, Callable, Dict, List, NamedTuple, Optional,
                    Sequence, Tuple)

import numpy as np

from .distributed import (_HEARTBEAT_RE, _default_exchange,
                          _process_defaults)

# costs below this floor are indistinguishable from host noise (a
# sub-millisecond boundary is "idle", not "fast") — without it the
# skew ratio of two idle hosts is garbage
DEFAULT_FLOOR_S = 1e-3


def _median(vals: Sequence[float]) -> float:
    """Interpolating median (same convention as ``obs.timeline``: with
    two hosts, one slow, a nearest-rank median would land entirely on
    one of them and hide the skew)."""
    s = sorted(vals)
    n = len(s)
    mid = n // 2
    return s[mid] if n % 2 else (s[mid - 1] + s[mid]) / 2.0


@dataclasses.dataclass(frozen=True)
class ReschedulePolicy:
    """The feedback loop's knob set.

    ``skew_threshold``: skew (max per-host cost / median) at or above
    which a sync counts toward the trigger; ``trigger_segments``: how
    many CONSECUTIVE over-threshold syncs naming the same straggler
    arm a rebalance (the hysteresis — one blip never triggers);
    ``sync_every``: segments between allgather syncs; ``min_shard``:
    the fewest partitions any host may be assigned (0 lets a degraded
    host run data-free while still holding its replicated carry);
    ``max_rebalances``: lifetime cap; ``rebalance=False`` runs the
    tracker observe-only (skew records, no decisions);
    ``speculative_multiple``: how many fleet-median segment times the
    slowest host may take before :func:`speculation_due` says a backup
    execution is warranted; ``ewma_alpha``/``floor_s``: the tracker's
    smoothing and noise floor.
    """

    skew_threshold: float = 1.5
    trigger_segments: int = 3
    sync_every: int = 1
    min_shard: int = 1
    max_rebalances: int = 4
    rebalance: bool = True
    speculative_multiple: float = 3.0
    ewma_alpha: float = 0.5
    floor_s: float = DEFAULT_FLOOR_S

    def __post_init__(self):
        if self.skew_threshold < 1.0:
            raise ValueError("skew_threshold must be >= 1 (skew of 1 "
                             "means perfectly balanced)")
        if self.trigger_segments < 1:
            raise ValueError("trigger_segments must be >= 1")
        if self.sync_every < 1:
            raise ValueError("sync_every must be >= 1")
        if self.min_shard < 0:
            raise ValueError("min_shard must be >= 0")
        if self.max_rebalances < 0:
            raise ValueError("max_rebalances must be >= 0")
        if self.speculative_multiple <= 1.0:
            raise ValueError("speculative_multiple must be > 1")
        if not 0.0 < self.ewma_alpha <= 1.0:
            raise ValueError("ewma_alpha must be in (0, 1]")
        if self.floor_s <= 0:
            raise ValueError("floor_s must be > 0")


class SkewSnapshot(NamedTuple):
    """One sync's view of the fleet — what :meth:`SkewTracker.fold`
    returns and what a ``skew_estimate`` record serializes."""

    skew: float
    straggler: Optional[int]     # argmax-cost host (None when balanced)
    consecutive: int             # over-threshold syncs naming it in a row
    persistent: bool             # consecutive >= trigger_segments
    speeds: Dict[int, float]     # host -> relative speed (1.0 typical)
    costs: Dict[int, float]      # host -> floored EWMA seconds/segment


class SkewTracker:
    """Online per-host speed estimate with hysteresis — see the module
    docstring.  Feed it per-host boundary seconds (:meth:`observe` /
    :meth:`fold`); read the skew, the straggler, and the relative
    speeds the weighted re-split consumes.  Heartbeat files are the
    second signal (:meth:`observe_heartbeats`)."""

    def __init__(self, *, alpha: float = 0.5,
                 floor_s: float = DEFAULT_FLOOR_S,
                 skew_threshold: float = 1.5,
                 trigger_segments: int = 3):
        self.alpha = float(alpha)
        self.floor_s = float(floor_s)
        self.skew_threshold = float(skew_threshold)
        self.trigger_segments = int(trigger_segments)
        self._ewma: Dict[int, float] = {}
        self._straggler: Optional[int] = None
        self.consecutive = 0
        self.hb_ages: Dict[int, float] = {}
        self.hb_slow: List[int] = []

    # -- the primary signal: host-local boundary seconds ------------------
    def observe(self, process: int, seconds: float) -> None:
        p = int(process)
        s = max(0.0, float(seconds))
        prev = self._ewma.get(p)
        self._ewma[p] = s if prev is None else (
            self.alpha * s + (1.0 - self.alpha) * prev)

    def costs(self) -> Dict[int, float]:
        """Floored EWMA seconds of host-local work per segment."""
        return {p: max(e, self.floor_s)
                for p, e in sorted(self._ewma.items())}

    def skew(self) -> Optional[float]:
        costs = self.costs()
        if not costs:
            return None
        return max(costs.values()) / _median(list(costs.values()))

    def straggler(self) -> Optional[int]:
        costs = self.costs()
        if not costs:
            return None
        worst = max(costs.values())
        if worst <= self.floor_s:
            return None  # everyone is idle-fast: no straggler
        return min(p for p, c in costs.items() if c == worst)

    def speeds(self) -> Dict[int, float]:
        """Relative per-host speed: the typical (median-cost) host is
        1.0, a 5×-slower host ~0.2 — the weights the re-split uses."""
        costs = self.costs()
        if not costs:
            return {}
        med = _median(list(costs.values()))
        return {p: med / c for p, c in costs.items()}

    def fold(self, costs: Dict[int, float]) -> SkewSnapshot:
        """One sync: fold every host's per-segment seconds, update the
        hysteresis counter, and return the snapshot.  The counter
        advances only while the SAME host stays on top of an
        over-threshold skew; any below-threshold sync (or a change of
        straggler) resets it — a blip cannot accumulate into a
        trigger."""
        for p, s in costs.items():
            self.observe(p, s)
        skew = self.skew() or 1.0
        straggler = self.straggler()
        if skew >= self.skew_threshold and straggler is not None:
            if straggler == self._straggler:
                self.consecutive += 1
            else:
                self._straggler = straggler
                self.consecutive = 1
        else:
            self._straggler = None
            self.consecutive = 0
        return SkewSnapshot(
            skew=skew, straggler=self._straggler,
            consecutive=self.consecutive,
            persistent=self.consecutive >= self.trigger_segments,
            speeds=self.speeds(), costs=self.costs())

    # -- the second signal: heartbeat files -------------------------------
    def observe_heartbeats(self, directory: str, *,
                           clock: Callable[[], float] = time.time
                           ) -> Dict[int, dict]:
        """Read the heartbeat files of ``directory`` (the
        ``resilience.distributed.HeartbeatWriter`` convention): per
        host, the file's age (mtime — a host that stopped rewriting is
        falling behind even if its content lies) and the last recorded
        phase.  Hosts whose latest beat is ``phase="slow"`` (the chaos
        sub-interval beats during an injected sleep) land in
        :attr:`hb_slow` — corroboration for the timing estimate."""
        out: Dict[int, dict] = {}
        slow: List[int] = []
        if os.path.isdir(directory):
            now = clock()
            for name in sorted(os.listdir(directory)):
                m = _HEARTBEAT_RE.match(name)
                if not m:
                    continue
                path = os.path.join(directory, name)
                try:
                    age = max(0.0, now - os.path.getmtime(path))
                    with open(path) as f:
                        rec = json.load(f)
                except (ValueError, OSError):
                    continue  # mid-rewrite: skip this poll
                p = int(m.group(1))
                out[p] = {"age_s": age, "phase": rec.get("phase")}
                if rec.get("phase") == "slow":
                    slow.append(p)
        self.hb_ages = {p: v["age_s"] for p, v in out.items()}
        self.hb_slow = slow
        return out


# ---------------------------------------------------------------------------
# Weighted partition re-split
# ---------------------------------------------------------------------------


def modeled_makespan(counts: Sequence[int],
                     speeds: Sequence[float]) -> float:
    """The makespan the speed model predicts for an assignment: the
    slowest host's (partitions / speed).  The quantity the weighted
    split minimizes and the property tests compare against uniform."""
    return max(c / max(float(s), 1e-9)
               for c, s in zip(counts, speeds)) if counts else 0.0


def uniform_counts(n_parts: int, n_hosts: int) -> List[int]:
    """The round-robin baseline: ``union[p::n]`` block sizes."""
    return [len(range(p, n_parts, n_hosts)) for p in range(n_hosts)]


def weighted_counts(n_parts: int, speeds: Sequence[float], *,
                    min_shard: int = 1) -> List[int]:
    """Integer per-host partition counts ∝ measured speed: a
    largest-remainder split over min-shard floors, then greedy moves
    from the modeled-slowest host to the host that can absorb one more
    cheapest, and a final never-worse-than-uniform guard.  Fully
    deterministic (ties break on host index)."""
    n_hosts = len(speeds)
    if n_hosts == 0:
        raise ValueError("speeds must name at least one host")
    if n_parts < 0:
        raise ValueError("n_parts must be >= 0")
    v = [max(float(s), 1e-9) for s in speeds]
    floor = min(int(min_shard), n_parts // n_hosts)
    total_v = sum(v)
    spare = n_parts - floor * n_hosts
    ideal = [spare * s / total_v for s in v]
    counts = [floor + int(i) for i in ideal]
    remainders = sorted(range(n_hosts),
                        key=lambda p: (-(ideal[p] - int(ideal[p])), p))
    for p in remainders[:spare - sum(int(i) for i in ideal)]:
        counts[p] += 1

    # greedy improvement: move one partition off the modeled-slowest
    # host while it strictly reduces the makespan (bounded by n_parts)
    for _ in range(n_parts):
        donor = max(range(n_hosts), key=lambda p: (counts[p] / v[p], p))
        if counts[donor] <= floor:
            break
        recv = min(range(n_hosts),
                   key=lambda p: ((counts[p] + 1) / v[p], p))
        if recv == donor:
            break
        trial = list(counts)
        trial[donor] -= 1
        trial[recv] += 1
        if modeled_makespan(trial, v) < modeled_makespan(counts, v):
            counts = trial
        else:
            break

    uniform = uniform_counts(n_parts, n_hosts)
    if modeled_makespan(counts, v) > modeled_makespan(uniform, v):
        counts = uniform  # the guard: weighted is NEVER worse
    return counts


def assign_weighted(union: Sequence[str], speeds: Sequence[float], *,
                    min_shard: int = 1) -> Tuple[Tuple[str, ...], ...]:
    """Per-host partition assignment: the sorted union cut into
    contiguous blocks sized by :func:`weighted_counts`.  Covers every
    partition exactly once; deterministic in its inputs — every SPMD
    host computing this from the same allgathered speeds derives the
    same table."""
    union = sorted(str(p) for p in union)
    counts = weighted_counts(len(union), speeds, min_shard=min_shard)
    out: List[Tuple[str, ...]] = []
    at = 0
    for c in counts:
        out.append(tuple(union[at:at + c]))
        at += c
    return tuple(out)


class RebalanceDecision(NamedTuple):
    """One committed-through-the-manifest rebalance decision — pure
    data so it can be journaled and asserted whole."""

    at_iter: int
    assignments: Tuple[Tuple[str, ...], ...]  # per host, full table
    mine: Tuple[str, ...]                     # this host's new row
    speeds: Dict[int, float]
    skew: float
    straggler: Optional[int]
    before: Tuple[int, ...]                   # per-host counts
    after: Tuple[int, ...]

    @property
    def moved(self) -> int:
        return sum(abs(a - b)
                   for a, b in zip(self.after, self.before)) // 2


# ---------------------------------------------------------------------------
# The scheduler the supervisor drives
# ---------------------------------------------------------------------------


class StragglerScheduler:
    """The feedback loop behind ``run_agd_supervised(scheduler=...)``.

    The supervisor calls :meth:`after_segment` at every successful
    segment boundary with the host-local boundary seconds; every
    ``policy.sync_every`` segments the per-host sums cross the
    ``exchange`` allgather (default: the distributed checkpoint's
    int64-limb barrier; identity on a single process), the
    :class:`SkewTracker` folds them, one ``skew_estimate`` record is
    emitted, and — when the straggler is persistent under the policy's
    hysteresis — a :class:`RebalanceDecision` is returned for the
    supervisor to :meth:`apply` at the generation boundary.

    ``rebuild(decision) -> staged`` is the caller's data hook: re-ingest
    this host's new partition list (``decision.mine``) and return the
    new ``(build, data_args)`` staged pair.  With fixed padded shapes
    (``ingest.from_partitioned_files(pad_to_rows=...)``) the swap
    reuses the compiled segment program unchanged; set
    ``retrace=True`` when the rebuild changes array shapes so the
    supervisor drops its jitted-segment cache.

    The sync is a COLLECTIVE: like the distributed checkpoint's commit
    barrier, every host must reach the same successful boundaries in
    lockstep, which SPMD guarantees for the fault-free path the
    scheduler optimizes.  The exchange refuses a mixed-iteration sync
    (hosts out of lockstep) the same way the commit refuses mixed
    generations.
    """

    def __init__(self, partitions: Sequence[str], *,
                 policy: Optional[ReschedulePolicy] = None,
                 rebuild: Optional[Callable[[RebalanceDecision], Any]] = None,
                 telemetry=None,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 exchange: Optional[Callable] = None,
                 heartbeat_dir: Optional[str] = None,
                 retrace: bool = False):
        self.union: Tuple[str, ...] = tuple(
            sorted(str(p) for p in partitions))
        if not self.union:
            raise ValueError("partitions must name at least one file")
        self.policy = policy or ReschedulePolicy()
        self.rebuild = rebuild
        self.telemetry = telemetry
        self.process_index, self.process_count = _process_defaults(
            process_index, process_count)
        self._exchange = exchange or _default_exchange
        self.heartbeat_dir = heartbeat_dir
        self.retrace = bool(retrace)
        self.tracker = SkewTracker(
            alpha=self.policy.ewma_alpha, floor_s=self.policy.floor_s,
            skew_threshold=self.policy.skew_threshold,
            trigger_segments=self.policy.trigger_segments)
        # initial table = the round-robin ingest.local_partitions rule
        self.assignments: Tuple[Tuple[str, ...], ...] = tuple(
            tuple(self.union[p::self.process_count])
            for p in range(self.process_count))
        self.rebalances = 0
        self.last_snapshot: Optional[SkewSnapshot] = None
        self._segments = 0
        self._window_us = 0
        self._window_segments = 0

    @property
    def assignment(self) -> Tuple[str, ...]:
        """This host's current partition list."""
        return self.assignments[self.process_index]

    # -- the supervisor hook ----------------------------------------------
    def after_segment(self, *, start_iter: int, iters: int,
                      boundary_s: float,
                      segment_s: Optional[float] = None
                      ) -> Optional[RebalanceDecision]:
        """Fold one successful segment's host-local boundary seconds;
        on a sync boundary, exchange, estimate, emit, and possibly
        decide.  Returns the decision to apply, or None."""
        self._segments += 1
        self._window_us += max(0, int(float(boundary_s) * 1e6))
        self._window_segments += 1
        if self._segments % self.policy.sync_every:
            return None
        done_iter = int(start_iter) + int(iters)
        row = np.asarray(
            [done_iter, self._window_us, self._window_segments],
            np.int64)
        gathered = np.asarray(self._exchange(row), np.int64).reshape(
            self.process_count, row.size)
        self._window_us = 0
        self._window_segments = 0
        iters_seen = gathered[:, 0]
        if not (iters_seen == iters_seen[0]).all():
            raise RuntimeError(
                "scheduler sync out of lockstep: hosts report "
                f"iterations {sorted(set(int(i) for i in iters_seen))} "
                "at the same boundary — refusing a skew estimate that "
                "mixes different segments")
        costs = {p: (float(gathered[p, 1]) / 1e6
                     / max(1, int(gathered[p, 2])))
                 for p in range(self.process_count)}
        snap = self.tracker.fold(costs)
        self.last_snapshot = snap
        if self.heartbeat_dir is not None:
            self.tracker.observe_heartbeats(self.heartbeat_dir)
        if self.telemetry is not None:
            fields = {
                "speeds": {str(p): round(v, 4)
                           for p, v in snap.speeds.items()},
                "consecutive": int(snap.consecutive),
                "persistent": bool(snap.persistent),
                "iter": done_iter,
                "window_segments": int(self.policy.sync_every),
                "threshold": float(self.policy.skew_threshold),
                "process": self.process_index,
                "source": "scheduler",
            }
            if snap.straggler is not None:
                fields["straggler"] = int(snap.straggler)
            if self.tracker.hb_slow:
                fields["hb_slow"] = list(self.tracker.hb_slow)
            self.telemetry.skew_estimate(skew=round(snap.skew, 4),
                                         **fields)

        if not (self.policy.rebalance and snap.persistent
                and self.rebalances < self.policy.max_rebalances):
            return None
        speeds_list = [snap.speeds.get(p, 1.0)
                       for p in range(self.process_count)]
        table = assign_weighted(self.union, speeds_list,
                                min_shard=self.policy.min_shard)
        if table == self.assignments:
            # nothing to move: re-arm the hysteresis instead of
            # re-deciding the same assignment every sync
            self.tracker.consecutive = 0
            return None
        return RebalanceDecision(
            at_iter=done_iter, assignments=table,
            mine=table[self.process_index], speeds=snap.speeds,
            skew=snap.skew, straggler=snap.straggler,
            before=tuple(len(a) for a in self.assignments),
            after=tuple(len(a) for a in table))

    def apply(self, decision: RebalanceDecision, *,
              checkpointer=None) -> Any:
        """Adopt the decision: update the assignment table, point the
        checkpointer's next generation at the NEW partition list (the
        manifest-commit that makes the rebalance durable is the
        supervisor's forced save right after), emit the ``rebalance``
        record + recovery action, and return the caller's rebuilt
        staged data (None without a ``rebuild`` hook)."""
        self.rebalances += 1
        self.tracker.consecutive = 0
        self.assignments = decision.assignments
        if checkpointer is not None and hasattr(checkpointer,
                                                "partitions"):
            checkpointer.partitions = list(decision.mine)
        if self.telemetry is not None:
            fields = {
                "speeds": {str(p): round(v, 4)
                           for p, v in decision.speeds.items()},
                "skew": round(float(decision.skew), 4),
                "before": {str(p): int(c)
                           for p, c in enumerate(decision.before)},
                "after": {str(p): int(c)
                          for p, c in enumerate(decision.after)},
                "moved": int(decision.moved),
                "process": self.process_index,
                "source": "scheduler",
            }
            if decision.straggler is not None:
                fields["straggler"] = int(decision.straggler)
            gen = getattr(checkpointer, "_next_generation", None)
            if gen is not None:
                fields["generation"] = int(gen)
            self.telemetry.rebalance(at_iter=int(decision.at_iter),
                                     **fields)
            self.telemetry.recovery(
                action="rebalance", from_iter=int(decision.at_iter),
                reason=(f"persistent straggler h{decision.straggler} "
                        f"(skew {decision.skew:.2f}); moved "
                        f"{decision.moved} partition(s)"),
                process=self.process_index, source="scheduler")
        if self.rebuild is not None:
            return self.rebuild(decision)
        return None


# ---------------------------------------------------------------------------
# Speculative segment execution
# ---------------------------------------------------------------------------


def speculation_due(elapsed_s: float, median_segment_s: float,
                    multiple: float = 3.0) -> bool:
    """Spark's speculation rule at segment granularity: the slowest
    host's in-flight segment has taken ``multiple`` × the fleet-median
    segment time — a backup execution is warranted.  False while the
    median is unknown (never speculate on the first segment)."""
    return (median_segment_s > 0.0
            and float(elapsed_s) >= float(multiple)
            * float(median_segment_s))


class SpeculationResult(NamedTuple):
    """One backup execution: the segment result, the re-derived warm
    carry, and its timing — kept whole for :func:`resolve_speculation`."""

    result: Any
    warm: Any
    seconds: float
    from_iter: int
    iters: int


def run_speculative_segment(run_segment: Callable[[Any, int], Any],
                            warm: Any, k: int, *,
                            from_iter: Optional[int] = None,
                            clock: Callable[[], float] = time.perf_counter
                            ) -> SpeculationResult:
    """Execute the backup: ``run_segment(warm, k)`` from the COMMITTED
    warm carry (never a live one — the committed generation is the
    only state both the primary and the straggler provably share).
    Deterministic math means a same-program backup reproduces the
    straggler's pending result bit-for-bit."""
    from ..utils import checkpoint as ckpt

    start = int(from_iter if from_iter is not None
                else warm.prior_iters)
    t0 = clock()
    res = run_segment(warm, int(k))
    seconds = clock() - t0
    new_warm = ckpt.warm_from_result(res, start + int(res.num_iters))
    return SpeculationResult(result=res, warm=new_warm,
                             seconds=seconds, from_iter=start,
                             iters=int(res.num_iters))


def warm_max_diff(a: Any, b: Any) -> float:
    """Max absolute elementwise difference across two warm carries'
    payload arrays (loss histories excluded — they may be rank-0-only,
    exactly like the commit barrier's replica-divergence CRC)."""
    from ..utils import checkpoint as ckpt

    pa, pb = ckpt.warm_payload(a), ckpt.warm_payload(b)
    worst = 0.0
    for name in sorted(set(pa) & set(pb)):
        if name == "loss_history":
            continue
        worst = max(worst, float(np.max(np.abs(
            np.asarray(pa[name], np.float64)
            - np.asarray(pb[name], np.float64)), initial=0.0)))
    return worst


def resolve_speculation(spec: SpeculationResult, committed_warm: Any, *,
                        fleet_seconds: Optional[float] = None,
                        tol: float = 0.0,
                        straggler: Optional[int] = None,
                        telemetry=None) -> dict:
    """First-result-wins accounting: compare the backup's warm carry
    against the (eventually) committed one — ``tol=0.0`` demands
    bit-identity (the same-program guarantee); a cross-topology backup
    passes a small f64 tolerance instead — and emit the
    ``speculative_exec`` recovery record.  ``won`` means the backup
    finished before the fleet's own result for the segment
    (``fleet_seconds``, when known) — either way the results MATCH, so
    taking whichever lands first is safe."""
    diff = warm_max_diff(spec.warm, committed_warm)
    matched = bool(diff <= tol) if tol > 0 else bool(diff == 0.0)
    won = bool(fleet_seconds is not None
               and spec.seconds < float(fleet_seconds))
    out = {"outcome": "won" if won else "lost", "matched": matched,
           "from_iter": int(spec.from_iter), "iters": int(spec.iters),
           "seconds": round(float(spec.seconds), 6),
           "max_diff": float(diff)}
    if fleet_seconds is not None:
        out["fleet_seconds"] = round(float(fleet_seconds), 6)
    if telemetry is not None:
        fields = dict(out)
        if straggler is not None:
            fields["straggler"] = int(straggler)
        telemetry.recovery(action="speculative_exec",
                           source="scheduler", **fields)
    return out
